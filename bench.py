"""Headline benchmark, run by the driver on real TPU hardware.

Primary metric — BASELINE config 1: ``range(1e9).groupBy(id % 100)
.count()``. The apples-to-apples reference row is the GROUPED hash
aggregate with whole-stage codegen + vectorized hashmap:
**84.3 M rows/s** (`sql/core/benchmarks/AggregateBenchmark-results.txt:43`,
"codegen = T hashmap = T", Xeon Platinum 8171M). Round 1 compared against
the no-grouping row (1812.5 M rows/s) — the wrong comparator for a
grouped query, per VERDICT.md.

Also runs the TPC-H SF1 north-star queries (Q1/Q3/Q5/Q6) with result
parity against the independent pandas golden implementations, reporting
per-query wall-clock in the ``extra`` field (the
`TPCDSQueryBenchmark.scala:54` pattern; the reference commits no TPC-H
numbers, so these rows are tracked round-over-round rather than against a
committed baseline).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

import json
import os
import time

N = 1_000_000_000
# AggregateBenchmark-results.txt:43 — "codegen = T hashmap = T" single-key
# grouped aggregate: the row matching this benchmark's shape
SPARK_GROUPED_AGG_ROWS_PER_SEC = 84.3e6

TPCH_SF = float(os.environ.get("BENCH_TPCH_SF", "1"))
TPCH_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "data", "tpch", f"sf{TPCH_SF:g}")


def bench_grouped_agg(spark):
    import numpy as np
    from spark_tpu.functions import col

    df = spark.range(N).group_by((col("id") % 100).alias("k")).count()
    qe = df._qe()

    def run_sync():
        b, _, _ = qe.execute_batch()
        # a host pull is the only reliable sync point on tunneled runtimes
        # where block_until_ready returns before execution completes
        np.asarray(b.columns["count"].data)
        return b

    batch = run_sync()  # warmup: compile + first run
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        batch = run_sync()
        times.append(time.perf_counter() - t0)

    # correctness gate: every group must count N/100
    pdf = batch.to_arrow().to_pydict()
    assert sorted(pdf["k"]) == list(range(100)), pdf["k"][:5]
    assert all(c == N // 100 for c in pdf["count"]), pdf["count"][:5]
    return N / min(times)


def bench_tpch(spark):
    """Generate (cached) SF data, run Q1/Q6/Q3/Q5 timed, check parity."""
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    write_parquet(TPCH_PATH, TPCH_SF)
    Q.register_tables(spark, TPCH_PATH)
    extra = {}
    for name in ("q1", "q6", "q3", "q5"):
        df_fn = Q.QUERIES[name]
        got = df_fn(spark).to_pandas()  # warmup (compile + ingest)
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            got = df_fn(spark).to_pandas()
            times.append(time.perf_counter() - t0)
        extra[f"tpch_{name}_sf{TPCH_SF:g}_ms"] = round(min(times) * 1e3, 1)
        # result parity vs the independent pandas implementation
        for c in got.columns:
            if len(got) and got[c].dtype == object and \
                    got[c].iloc[0].__class__.__name__ == "Decimal":
                got[c] = got[c].astype(float)
        want = G.GOLDEN[name](TPCH_PATH)
        if name == "q5":
            got = got.sort_values("n_name").reset_index(drop=True)
            want = want.sort_values("n_name").reset_index(drop=True)
        G.compare(got.reset_index(drop=True), want,
                  float_rtol=1e-6, float_atol=1e-4)
        extra[f"tpch_{name}_parity"] = True
    return extra


def main():
    from spark_tpu import SparkTpuSession

    spark = SparkTpuSession.builder().get_or_create()
    rows_per_sec = bench_grouped_agg(spark)

    extra = {}
    try:
        extra = bench_tpch(spark)
    except Exception as e:  # keep the headline metric on TPC-H failure
        extra = {"tpch_error": f"{type(e).__name__}: {e}"[:300]}

    print(json.dumps({
        "metric": "grouped_agg_rows_per_sec",
        "value": round(rows_per_sec / 1e6, 1),
        "unit": "M rows/s",
        "vs_baseline": round(rows_per_sec / SPARK_GROUPED_AGG_ROWS_PER_SEC,
                             3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()

"""Headline benchmark, run by the driver on real TPU hardware.

Primary metric — the EXACT reference shape of `AggregateBenchmark.scala:69-75`
("aggregate with linear keys"): ``range(20<<22).selectExpr("(id & 65535)
as k").groupBy(k).sum()`` — 83.9M rows, 65,536 groups, a SUM per group.
The apples-to-apples comparator is its best row, **84.3 M rows/s**
(codegen=T vectorized hashmap=T, `AggregateBenchmark-results.txt:41`,
Xeon Platinum 8171M). Round 2 benchmarked a 100-group count against that
row — a far easier shape — per VERDICT weak #3; the 100-group
BASELINE-config-1 metric is kept as a secondary row.

Also benchmarked: global stddev over `range(100<<20)` vs the reference's
91.4 M rows/s (`AggregateBenchmark-results.txt:18-24` "stat functions"),
and the TPC-H north-star queries (Q1/Q6/Q3/Q5) with result parity
against the independent pandas goldens, per-query wall-clock in `extra`
(the `TPCDSQueryBenchmark.scala:54` pattern).

Output is timeout-proof (round-5 ran into the driver's rc:124 with zero
parseable output): every section prints its OWN complete JSON line the
moment it finishes (flushed), each section runs under a SIGALRM
deadline, AND the aggregate summary line {"metric", "value", "unit",
"vs_baseline", "extra"} is rewritten (with "partial": true) after every
section — a killed or hung run leaves both per-section lines and a
parseable partial summary. Consumers take the LAST summary-shaped line;
the final rewrite drops the partial marker.

Round-5 post-mortem (rc:124, parsed:null): per-section budgets of 900s
x 5 sections + 1650s of SF10 never fit the driver's outer `timeout`, so
the kill arrived with nothing parseable emitted. The matrix now fits a
TOTAL budget (`BENCH_TOTAL_BUDGET_S`, default 2400s) enforced on top of
tighter per-section deadlines (`BENCH_SECTION_BUDGET_S`, default 420s):
each section gets min(section budget, remaining total), sections past
the total are SKIPPED with their own JSON line, and the SF10 sweep is
opt-in (`BENCH_RUN_SF10=1`) instead of default — the default matrix
completes inside the budget with a final (non-partial) summary.
"""

import contextlib
import json
import os
import signal
import tempfile
import time

import numpy as np

# AggregateBenchmark.scala:69 "aggregate with linear keys"
N_KEYS = 20 << 22            # 83,886,080 rows
KEYS_BASELINE = 84.3e6       # M rows/s, vectorized hashmap row
# AggregateBenchmark.scala:57 "stat functions" / stddev
N_STDDEV = 100 << 20         # 104,857,600 rows
STDDEV_BASELINE = 91.4e6
# BASELINE config 1 (kept as a secondary metric)
N_100G = 1_000_000_000

TPCH_SF = float(os.environ.get("BENCH_TPCH_SF", "1"))
TPCH_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "data", "tpch", f"sf{TPCH_SF:g}")
TPCDS_SF = float(os.environ.get("BENCH_TPCDS_SF", "1"))
TPCDS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "tpcds", f"sf{TPCDS_SF:g}")


class SectionTimeout(BaseException):
    """BaseException, NOT Exception: section bodies (stddev fallbacks,
    kernel_pick per-mode loop) catch broad Exception for infra
    failures, and the deadline must punch through those handlers."""


@contextlib.contextmanager
def _section_deadline(seconds: float):
    """SIGALRM-backed per-section bound. A section that blows its budget
    raises SectionTimeout at the next Python bytecode (a single hung C
    call can still stall past it, but the per-section JSON lines already
    printed survive any outer `timeout` kill)."""
    if seconds <= 0 or not hasattr(signal, "SIGALRM"):
        yield
        return

    def handler(signum, frame):
        raise SectionTimeout()

    old = signal.signal(signal.SIGALRM, handler)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def _emit(section: str, status: str, t0: float, data: dict) -> None:
    print(json.dumps({"section": section, "status": status,
                      "elapsed_s": round(time.perf_counter() - t0, 1),
                      "data": data}), flush=True)


#: the bench session whose flight recorder section failures dump
#: bundles from (set by _arm_flight_recorder in main)
_FLIGHTREC_SESSION = None


def _arm_flight_recorder(spark) -> None:
    """Arm the always-on flight recorder for every section: ring
    recording on, bundles under the bench output dir, and the session
    registered so _run_section can dump on a timeout/error."""
    global _FLIGHTREC_SESSION
    spark.conf.set("spark_tpu.sql.flightRecorder.enabled", "true")
    spark.conf.set("spark_tpu.sql.flightRecorder.dir",
                   os.path.join(tempfile.gettempdir(),
                                "spark-tpu-bench-flightrec"))
    _FLIGHTREC_SESSION = spark


def _section_bundle(name: str, detail: str):
    """Dump a flight-recorder bundle for a failed/timed-out section;
    returns its path (None when unarmed or the dump failed)."""
    if _FLIGHTREC_SESSION is None:
        return None
    from spark_tpu.observability.flight_recorder import FlightRecorder
    rec = FlightRecorder.of(_FLIGHTREC_SESSION)
    if rec is None:
        return None
    return rec.dump(f"bench_{name}", extra={"section": name,
                                            "detail": detail})


def _run_section(name: str, fn, budget_s: float) -> dict:
    """Run one bench section under its own deadline and emit its JSON
    line immediately; always returns a dict (possibly {'error': ...}).
    A timeout or error additionally dumps a flight-recorder bundle and
    carries its path in the JSON line ('bundle'): the post-mortem for
    a wedged section starts from the bundle, not from rerunning it."""
    t0 = time.perf_counter()
    data = None
    try:
        with _section_deadline(budget_s):
            data = fn()
        _emit(name, "ok", t0, data)
        return data
    except SectionTimeout:
        if data is not None:
            # the alarm fired in the window between fn() returning and
            # the deadline context disarming it: the section DID finish
            _emit(name, "ok", t0, data)
            return data
        detail = f"section timeout after {budget_s:g}s"
        data = {f"{name}_error": detail,
                "bundle": _section_bundle(name, detail)}
        _emit(name, "timeout", t0, data)
        return data
    except Exception as e:  # noqa: BLE001
        detail = f"{type(e).__name__}: {e}"[:300]
        data = {f"{name}_error": detail,
                "bundle": _section_bundle(name, detail)}
        _emit(name, "error", t0, data)
        return data


def _time3(run_sync):
    run_sync()  # warmup: compile + first run
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_sync()
        times.append(time.perf_counter() - t0)
    return min(times)


def _warm_best2(run_once):
    """Warmup + best-of-2 for the TPC query sections: `run_once`
    returns (qe, result); returns (qe, result, best_seconds). ONE
    definition so the tpch and tpcds sections cannot drift on the
    warmup protocol."""
    run_once()  # warmup: compile + first ingest
    best = None
    qe = got = None
    for _ in range(2):
        t0 = time.perf_counter()
        qe, got = run_once()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return qe, got, best


def _prediction_sidecars(qe, extra: dict, key: str) -> None:
    """Analyzer/planner self-grading sidecars (mean |error| of the
    plan-time size predictions vs this run's observed metrics) under
    `<key>_pred_err_pct` / `<key>_pred_under` — shared by the tpch and
    tpcds sections so the grading semantics cannot drift."""
    from spark_tpu.history import grade_predictions
    graded = grade_predictions(qe.plan_predictions or [],
                               qe.last_metrics)
    errs = [abs(g["err_pct"]) for g in graded
            if g.get("err_pct") is not None]
    if errs:
        extra[f"{key}_pred_err_pct"] = round(sum(errs) / len(errs), 1)
        extra[f"{key}_pred_under"] = sum(
            1 for g in graded if g["grade"] == "under")


def bench_linear_keys(spark):
    """(id & 65535) keys, sum per group — the reference's headline shape.
    pmod(id, 65536) == id & 65535 for the non-negative range ids, and its
    statically non-negative range keeps the kernel's limb count minimal
    (the same property `& 65535` gives the reference's codegen)."""
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    df = (spark.range(N_KEYS)
          .select(F.pmod(col("id"), 65536).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("sum(k)")))
    qe = df._qe()

    def run_sync():
        b, _, _ = qe.execute_batch()
        # a host pull is the only reliable sync point on tunneled
        # runtimes; device_get's batched path avoids the slow
        # per-array RPC np.asarray takes (~150ms, measured)
        import jax
        jax.device_get(b.columns["sum(k)"].data)
        return b

    best = _time3(run_sync)
    b, _, _ = qe.execute_batch()
    pdf = b.to_arrow().to_pydict()
    assert sorted(pdf["k"]) == list(range(65536)), pdf["k"][:5]
    per_key = N_KEYS // 65536
    assert pdf["sum(k)"][pdf["k"].index(7)] == 7 * per_key
    return N_KEYS / best


def bench_stddev(spark):
    """Falls back kernelMode=scatter, then unstreamed, on compile
    failure (round-4: a remote tpu_compile_helper 500 left the metric
    unmeasured with no retry)."""
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    def attempt():
        df = spark.range(N_STDDEV).agg(F.stddev(col("id")).alias("sd"))
        qe = df._qe()

        def run_sync():
            b, _, _ = qe.execute_batch()
            import jax
            return float(jax.device_get(b.columns["sd"].data)[0])

        best = _time3(run_sync)
        sd = run_sync()
        want = np.sqrt((N_STDDEV**2 - 1) / 12.0)  # stddev of 0..N-1
        assert abs(sd - want) / want < 1e-6, (sd, want)
        return N_STDDEV / best

    kern_key = "spark_tpu.sql.aggregate.kernelMode"
    chunk_key = "spark_tpu.sql.execution.streamingChunkRows"
    fallbacks = [{}, {kern_key: "scatter"},
                 {kern_key: "scatter", chunk_key: N_STDDEV * 2}]
    last = None
    for fb in fallbacks:
        old = {k: spark.conf.get(k) for k in fb}
        try:
            for k, v in fb.items():
                spark.conf.set(k, v)
            return attempt()
        except AssertionError:
            raise
        except Exception as e:  # compile/runtime infra failure: retry
            last = e
        finally:
            for k, v in old.items():
                spark.conf.set(k, v)
    raise last


def bench_100_groups(spark):
    from spark_tpu.functions import col

    df = spark.range(N_100G).group_by((col("id") % 100).alias("k")).count()
    qe = df._qe()

    def run_sync():
        b, _, _ = qe.execute_batch()
        import jax
        jax.device_get(b.columns["count"].data)
        return b

    best = _time3(run_sync)
    b, _, _ = qe.execute_batch()
    pdf = b.to_arrow().to_pydict()
    assert sorted(pdf["k"]) == list(range(100)), pdf["k"][:5]
    assert all(c == N_100G // 100 for c in pdf["count"]), pdf["count"][:5]
    return N_100G / best


def bench_kernel_pick(spark):
    """Measure the 65k-group headline shape under each aggregate kernel
    (factorized MXU matmul vs XLA scatter) ON HARDWARE and report both —
    the winner is chosen by measurement, not fixed at trace time
    (round-4 VERDICT weak #1)."""
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    kern_key = "spark_tpu.sql.aggregate.kernelMode"
    out = {}
    for mode in ("matmul", "scatter"):
        try:
            spark.conf.set(kern_key, mode)
            df = (spark.range(N_KEYS)
                  .select(F.pmod(col("id"), 65536).alias("k"))
                  .group_by(col("k")).agg(F.sum(col("k")).alias("s")))
            qe = df._qe()

            def run_sync():
                b, _, _ = qe.execute_batch()
                import jax
                jax.device_get(b.columns["s"].data)

            out[f"kern_{mode}_rows_per_sec_M"] = round(
                N_KEYS / _time3(run_sync) / 1e6, 1)
        except Exception as e:
            out[f"kern_{mode}_error"] = f"{type(e).__name__}: {e}"[:160]
        finally:
            spark.conf.set(kern_key, "auto")
    return out


def bench_join_microbench(spark):
    """Hash vs sort join-kernel microbench: inner-join probe rows/s at
    1M and 16M probe rows against a 64k-row build side (duplicate keys
    included so the many-to-many expansion runs). The result feeds the
    kernel-choice heuristics (join.hashMinProbeRows /
    hashProbeBuildRatio) with measured crossover data per platform."""
    import numpy as np
    import pandas as pd

    from spark_tpu import functions as F
    from spark_tpu.functions import col

    mode_key = "spark_tpu.sql.join.kernelMode"
    old_mode = spark.conf.get(mode_key)
    build_n = 1 << 16
    # BENCH_JOIN_PROBE_ROWS: comma list of probe sizes (preflight
    # smokes shrink it; the default pair is the BENCH trajectory shape)
    probe_sizes = [int(v) for v in os.environ.get(
        "BENCH_JOIN_PROBE_ROWS", f"{1 << 20},{1 << 24}").split(",")]
    rs = np.random.RandomState(42)
    dim = pd.DataFrame({
        # ~1/16 duplicated build keys: exercises expansion without
        # blowing the out_cap past the probe capacity
        "k2": np.concatenate([
            np.arange(build_n - (build_n >> 4), dtype=np.int64),
            rs.randint(0, build_n >> 4, build_n >> 4)]),
        "w": np.arange(build_n, dtype=np.int64)})
    spark.register_table("jmb_dim", dim)
    out = {}
    try:
        for probe_n in probe_sizes:
            label = f"{probe_n >> 20}m" if probe_n >= 1 << 20 \
                else f"{probe_n >> 10}k"
            fact = pd.DataFrame({
                "k": rs.randint(0, build_n, probe_n).astype(np.int64),
                "v": np.arange(probe_n, dtype=np.int64)})
            spark.register_table("jmb_fact", fact)
            for mode in ("sort", "hash"):
                spark.conf.set(mode_key, mode)
                # aggregate the join output so timing measures the
                # kernel, not a multi-million-row host transfer
                df = (spark.table("jmb_fact")
                      .join(spark.table("jmb_dim"), left_on=col("k"),
                            right_on=col("k2"))
                      .agg(F.sum(col("v") + col("w")).alias("s")))
                qe = df._qe()

                def run_sync():
                    b, _, _ = qe.execute_batch()
                    import jax
                    jax.device_get(b.columns["s"].data)
                    return b

                best = _time3(run_sync)
                out[f"join_{label}_{mode}_rows_per_sec_M"] = round(
                    probe_n / best / 1e6, 1)
            srt = out[f"join_{label}_sort_rows_per_sec_M"]
            hsh = out[f"join_{label}_hash_rows_per_sec_M"]
            if srt:
                out[f"join_{label}_hash_speedup"] = round(hsh / srt, 3)
    finally:
        spark.conf.set(mode_key, old_mode)
    return out


#: compile-cache child: one fresh process running Q1+Q3 with the
#: persistent AOT compile cache pointed at argv[2] — prints compile
#: span ms (eager AOT under the cache, so the span is the true
#: trace+compile or deserialize cost), first-run e2e ms, disk
#: hit/miss counters and a result digest. Run twice by
#: bench_compile_cache: cold (empty dir) then warm (same dir).
_CC_CHILD = r'''
import hashlib, json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from spark_tpu import SparkTpuSession
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q

path, cc_dir = sys.argv[1], sys.argv[2]
spark = SparkTpuSession.builder().get_or_create()
spark.conf.set("spark_tpu.sql.compileCache.enabled", True)
spark.conf.set("spark_tpu.sql.compileCache.dir", cc_dir)
Q.register_tables(spark, path)
out = {}
for name in ("q1", "q3"):
    t0 = time.perf_counter()
    qe = Q.QUERIES[name](spark)._qe()
    got = qe.collect().to_pandas()
    e2e = (time.perf_counter() - t0) * 1e3
    # compile spans ONLY: the deserialize sub-span is nested inside
    # its compile span's interval, so summing both would double count
    compile_ms = sum(s.dur_ms for s in qe.spans.spans
                     if s.name == "compile")
    digest = hashlib.md5(G.normalize_decimals(got)
                         .to_csv(index=False).encode()).hexdigest()
    out[name] = {"e2e_ms": round(e2e, 1),
                 "compile_ms": round(compile_ms, 1), "md5": digest}
m = spark.metrics
out["disk_hits"] = int(m.counter("compile_cache_disk_hits").value)
out["disk_misses"] = int(m.counter("compile_cache_disk_misses").value)
out["deser_ms"] = round(float(m.counter("compile_cache_deser_ms").value), 1)
print("CCBENCH " + json.dumps(out), flush=True)
'''


def bench_compile_cache(spark):
    """Cold-vs-warm-PROCESS compile cost for the persistent AOT
    compile cache (execution/compile_cache.py): TPC-H Q1+Q3 each run
    in a FRESH subprocess against one shared cache dir — the first
    child pays trace + XLA compile and serializes, the second must
    open warm (compile_cache_disk_hits >= 1) with byte-identical
    results, paying deserialization only. The children are pinned to
    CPU: the TPU runtime is single-client and this parent holds the
    chip, so CPU XLA compile time is the measured proxy (the
    mechanism is backend-agnostic; disk hits + parity are asserted
    either way, and compile_cache_backend labels the rows)."""
    import subprocess
    import sys
    import tempfile

    from spark_tpu.tpch.datagen import write_parquet

    base = tempfile.mkdtemp(prefix="bench_cc_")
    sf_path = os.path.join(base, "sf")
    write_parquet(sf_path, 0.01)
    cc_dir = os.path.join(base, "cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run_child():
        proc = subprocess.run(
            [sys.executable, "-c", _CC_CHILD, sf_path, cc_dir],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in proc.stdout.splitlines():
            if line.startswith("CCBENCH "):
                return json.loads(line[len("CCBENCH "):])
        raise RuntimeError(
            f"compile-cache child rc={proc.returncode}: "
            f"{proc.stderr[-400:]}")

    cold = run_child()
    warm = run_child()
    assert warm["disk_hits"] >= 1, (cold, warm)
    out = {"compile_cache_backend": "cpu",
           "compile_cache_warm_disk_hits": warm["disk_hits"],
           "compile_cache_warm_disk_misses": warm["disk_misses"],
           "compile_cache_warm_deser_ms": warm["deser_ms"]}
    for q in ("q1", "q3"):
        assert cold[q]["md5"] == warm[q]["md5"], (q, cold, warm)
        out[f"tpch_{q}_compile_cold_ms"] = cold[q]["compile_ms"]
        out[f"tpch_{q}_compile_warm_ms"] = warm[q]["compile_ms"]
        out[f"tpch_{q}_e2e_cold_ms"] = cold[q]["e2e_ms"]
        out[f"tpch_{q}_e2e_warm_ms"] = warm[q]["e2e_ms"]
    return out


def bench_tpch(spark, sf: float, path: str, queries=("q1", "q6", "q3",
                                                     "q5"),
               float_atol: float = 1e-4, deadline: float = None):
    """Generate (cached) SF data, run the queries timed, check parity.
    `deadline` (perf_counter value): remaining queries are skipped once
    passed, so a slow scale factor can never starve the whole bench."""
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    write_parquet(path, sf)
    Q.register_tables(spark, path)
    extra = {}
    # XLA cost/HBM sidecars (flops, bytes accessed, peak HBM demand per
    # query) ride along with the wall-clock rows, so BENCH rounds form a
    # real perf trajectory: time deltas become attributable to compute
    # vs movement vs memory pressure. Capture pays one extra analysis
    # compile per stage key (memoized session-wide), on the warmup run.
    cost_key = "spark_tpu.sql.observability.xlaCost"
    old_cost_mode = spark.conf.get(cost_key)
    spark.conf.set(cost_key, "on")
    try:
        return _bench_tpch_queries(spark, sf, queries, float_atol,
                                   deadline, path, extra)
    finally:
        spark.conf.set(cost_key, old_cost_mode)


def _bench_tpch_queries(spark, sf, queries, float_atol, deadline, path,
                        extra):
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q

    for name in queries:
        if deadline is not None and time.perf_counter() > deadline:
            extra[f"tpch_{name}_sf{sf:g}_skipped"] = "time budget"
            continue
        df_fn = Q.QUERIES[name]

        def run_once():
            qe = df_fn(spark)._qe()
            b, _, _ = qe.execute_batch()
            return qe, b.to_arrow().to_pandas()

        # partial-progress recovery sidecar: chunks replayed by the
        # per-chunk retry across this query's runs. MUST stay 0 on a
        # clean run — nonzero means the TPU runtime flaked mid-stream
        # (and the stream resumed instead of restarting)
        rec0 = spark.metrics.counter("rec_chunks_replayed").value
        # elastic-mesh sidecar baselines: gang restarts applied and
        # rows the straggler rebalancer shifted — both MUST stay 0 on
        # a clean single-host round; nonzero means the mesh healed
        # (or rebalanced) mid-bench instead of degrading
        mr0 = spark.metrics.counter("mesh_restart_attempts").value
        rb0 = spark.metrics.counter("rebalance_rows").value
        # ingest-pipeline sidecar baselines (registry counters)
        stall0 = spark.metrics.counter("ingest_stall_ms").value
        overlap0 = spark.metrics.counter("ingest_overlap_ms").value
        # keep the FIRST (warmup) run's qe: its compile/deserialize
        # spans carry the compile cost this query paid in this
        # process (the compile-cache trajectory sidecar; ~0 once the
        # session's stage cache is warm from an earlier section)
        first_qe = []

        def run_once_capturing():
            r = run_once()
            if not first_qe:
                first_qe.append(r[0])
            return r

        qe, got, best = _warm_best2(run_once_capturing)
        extra[f"tpch_{name}_sf{sf:g}_ms"] = round(best * 1e3, 1)
        # compile spans only — the deserialize sub-span is nested
        # inside its compile span, so including it would double count
        extra[f"tpch_{name}_sf{sf:g}_compile_ms"] = round(sum(
            s.dur_ms for s in first_qe[0].spans.spans
            if s.name == "compile"), 1)
        # ingest vs compute split of the last run (VERDICT r3 next-1d):
        # with the device-table cache warm, ingest should be ~0
        for phase in ("ingest", "execution", "streaming"):
            if phase in qe.phase_times:
                extra[f"tpch_{name}_{phase}_ms"] = round(
                    qe.phase_times[phase] * 1e3, 1)
        # XLA cost/HBM accounting sidecar (observability/xla_cost.py):
        # total flops + bytes accessed across the query's compiled
        # stages, and the worst single-stage peak HBM demand
        costs = [c for c in qe.stage_costs.values()
                 if c.get("flops") is not None
                 or c.get("peak_hbm_bytes") is not None]
        if costs:
            extra[f"tpch_{name}_sf{sf:g}_flops"] = int(
                sum(c.get("flops") or 0 for c in costs))
            extra[f"tpch_{name}_sf{sf:g}_xla_bytes"] = int(
                sum(c.get("bytes_accessed") or 0 for c in costs))
            extra[f"tpch_{name}_sf{sf:g}_peak_hbm_bytes"] = int(max(
                c.get("peak_hbm_bytes") or 0 for c in costs))
        extra[f"tpch_{name}_sf{sf:g}_rec_chunks_replayed"] = int(
            spark.metrics.counter("rec_chunks_replayed").value - rec0)
        extra[f"tpch_{name}_sf{sf:g}_mesh_restarts"] = int(
            spark.metrics.counter("mesh_restart_attempts").value - mr0)
        extra[f"tpch_{name}_sf{sf:g}_rebalanced_rows"] = int(
            spark.metrics.counter("rebalance_rows").value - rb0)
        # hash-join kernel sidecar: per-join table build/probe program
        # cost (0.0 when every join took the sort path — expected on
        # small probes under kernelMode=auto)
        extra[f"tpch_{name}_sf{sf:g}_join_build_ms"] = round(sum(
            v for k, v in qe.last_metrics.items()
            if k.startswith("join_build_ms_")), 3)
        slots = [v for k, v in qe.last_metrics.items()
                 if k.startswith("join_table_slots_")]
        if slots:
            extra[f"tpch_{name}_sf{sf:g}_join_table_slots"] = int(
                max(slots))
        # ingest pipeline sidecar: decode time hidden behind compute
        # vs consumer stalls, across this query's warmup+timed runs
        extra[f"tpch_{name}_sf{sf:g}_ingest_overlap_ms"] = round(
            spark.metrics.counter("ingest_overlap_ms").value
            - overlap0, 3)
        extra[f"tpch_{name}_sf{sf:g}_ingest_stall_ms"] = round(
            spark.metrics.counter("ingest_stall_ms").value - stall0, 3)
        # analyzer self-grading sidecar: the BENCH trajectory shows
        # whether the estimators feeding AQE seeds and runtime-filter
        # sizing are getting tighter or drifting
        _prediction_sidecars(qe, extra, f"tpch_{name}_sf{sf:g}")
        # static-analyzer sidecar: findings per query (the BENCH
        # trajectory must show analyzer noise staying at zero on the
        # TPC-H suite; a nonzero count is either a real hazard at this
        # scale factor or an analyzer regression — both reportable)
        extra[f"tpch_{name}_sf{sf:g}_analysis_findings"] = int(
            len(qe.analysis_findings or []))
        # runtime-filter observability: fraction of probe rows the
        # injected Bloom/min-max filters pruned before the exchanges
        tested = sum(v for k, v in qe.last_metrics.items()
                     if k.startswith("rtf_tested_"))
        pruned = sum(v for k, v in qe.last_metrics.items()
                     if k.startswith("rtf_pruned_"))
        if tested:
            extra[f"tpch_{name}_sf{sf:g}_rtf_pruned_ratio"] = round(
                pruned / tested, 4)
        # result parity vs the independent pandas implementation
        got = G.normalize_decimals(got)
        want = G.GOLDEN[name](path)
        if name == "q5":
            got = got.sort_values("n_name").reset_index(drop=True)
            want = want.sort_values("n_name").reset_index(drop=True)
        G.compare(got.reset_index(drop=True), want,
                  float_rtol=1e-6, float_atol=float_atol)
        extra[f"tpch_{name}_parity"] = True
    _tpch_udf_sidecars(spark, sf, deadline, extra)
    return extra


def _tpch_udf_sidecars(spark, sf, deadline, extra) -> None:
    """Python-UDF lane sidecars over real TPC-H data: a revenue UDF
    over the (pruned) lineitem scan in both lanes, so the BENCH
    trajectory prices the worker pool's IPC overhead against the
    in-process lane at scale — plus the worker lane's batch count and
    its prediction grading (udf_batches/udf_rows hit/over/under)."""
    if deadline is not None and time.perf_counter() > deadline:
        extra[f"tpch_udf_sf{sf:g}_skipped"] = "time budget"
        return
    from spark_tpu.functions import col, pandas_udf, to_date
    from spark_tpu.history import grade_predictions

    @pandas_udf(returnType="double")
    def disc_price(ep, d):
        # the decimal columns arrive as object-dtype Decimal series
        return ep.astype("float64") * (1.0 - d.astype("float64"))

    mode_key = "spark_tpu.sql.udf.mode"
    batch_key = "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"

    def run(mode):
        spark.conf.set(mode_key, mode)
        qe = (spark.table("lineitem")
              .filter(col("l_shipdate") <= to_date("1998-09-02"))
              .select(disc_price(col("l_extendedprice"),
                                 col("l_discount")).alias("p")))._qe()
        t0 = time.perf_counter()
        b, _, _ = qe.execute_batch()
        dt = time.perf_counter() - t0
        return qe, b.to_arrow().to_pandas(), dt

    old_batch = spark.conf.get(batch_key)
    try:
        qe_in, got_in, t_in = run("inprocess")
        rows = len(got_in)
        extra[f"tpch_udf_sf{sf:g}_inprocess_ms"] = round(t_in * 1e3, 1)
        qe_w, got_w, t_w = run("worker")
        extra[f"tpch_udf_sf{sf:g}_worker_ms"] = round(t_w * 1e3, 1)
        if rows:
            extra[f"tpch_udf_sf{sf:g}_rows_per_sec_M"] = round(
                rows / t_w / 1e6, 2)
        assert got_w.equals(got_in), "udf worker-lane parity broke"
        u = qe_w.udf_summary or {}
        extra[f"tpch_udf_sf{sf:g}_worker_batches"] = int(
            u.get("batches", 0))
        extra[f"tpch_udf_sf{sf:g}_worker_restarts"] = int(
            u.get("worker_restarts", 0))
        # grade the analyzer's batch/row prediction against this run
        graded = grade_predictions(
            qe_w.plan_predictions or [],
            {"udf_batches": u.get("batches"), "udf_rows": u.get("rows")})
        errs = [abs(g["err_pct"]) for g in graded
                if g["kind"].startswith("udf")
                and g.get("err_pct") is not None]
        if errs:
            extra[f"tpch_udf_sf{sf:g}_pred_err_pct"] = round(
                sum(errs) / len(errs), 1)
    finally:
        spark.conf.set(mode_key, "inprocess")
        spark.conf.set(batch_key, old_batch)


def bench_tpcds(spark, sf: float, path: str,
                queries=("q3", "q19", "q68"), float_atol: float = 1e-3,
                deadline: float = None):
    """TPC-DS tranche section: generate (cached) SF data, run the
    representative snowflake queries timed with result parity against
    the independent pandas goldens, and emit the `tpcds_*_ms` rows the
    perf gate tracks plus the prediction-error and join-reorder
    sidecars — the reference's committed perf baselines are TPC-DS
    (`TPCDSQueryBenchmark.scala:54`), so the BENCH trajectory now has
    the same spine."""
    from spark_tpu.tpcds import SQL_QUERIES, register_tables
    from spark_tpu.tpcds import golden as G
    from spark_tpu.tpcds.datagen import write_parquet

    write_parquet(path, sf)
    register_tables(spark, path)
    extra = {}
    for name in queries:
        if deadline is not None and time.perf_counter() > deadline:
            extra[f"tpcds_{name}_sf{sf:g}_skipped"] = "time budget"
            continue

        def run_once():
            qe = spark.sql(SQL_QUERIES[name])._qe()
            b, _, _ = qe.execute_batch()
            return qe, b.to_arrow().to_pandas()

        qe, got, best = _warm_best2(run_once)
        extra[f"tpcds_{name}_sf{sf:g}_ms"] = round(best * 1e3, 1)
        for phase in ("ingest", "execution", "streaming"):
            if phase in qe.phase_times:
                extra[f"tpcds_{name}_{phase}_ms"] = round(
                    qe.phase_times[phase] * 1e3, 1)
        # self-grading sidecars (incl. basis cbo-reorder predictions),
        # plus whether the reorder pass changed this query's join
        # SEQUENCE (kind "order" — an orientation-only flip must not
        # read as a reorder, same discipline as tests/preflight)
        _prediction_sidecars(qe, extra, f"tpcds_{name}_sf{sf:g}")
        extra[f"tpcds_{name}_sf{sf:g}_reordered"] = int(any(
            d.get("kind") == "order"
            for d in (qe.reorder_decisions or [])))
        extra[f"tpcds_{name}_sf{sf:g}_analysis_findings"] = int(
            len(qe.analysis_findings or []))
        # result parity vs the independent pandas implementation
        got = G.normalize_decimals(got)
        want = G.GOLDEN[name](path)
        G.compare(got[list(want.columns)].reset_index(drop=True), want,
                  float_rtol=1e-6, float_atol=float_atol)
        extra[f"tpcds_{name}_parity"] = True
    return extra


def obs_conf_on(base_dir: str) -> dict:
    """EVERY observability output's conf, pointed at base_dir — the
    ONE definition of 'all sinks on' shared by this bench section and
    the preflight stage-5 overhead gate (a new observability key added
    here is automatically measured by both)."""
    return {"spark_tpu.sql.eventLog.dir": base_dir + "/ev",
            "spark_tpu.sql.trace.dir": base_dir + "/tr",
            "spark_tpu.sql.metrics.sink": "jsonl,prometheus",
            "spark_tpu.sql.metrics.dir": base_dir + "/m",
            "spark_tpu.sql.observability.xlaCost": "on",
            "spark_tpu.sql.observability.shardSpans": "on",
            "spark_tpu.sql.status.enabled": "true",
            "spark_tpu.sql.flightRecorder.enabled": "true",
            "spark_tpu.sql.flightRecorder.dir": base_dir + "/fr",
            "spark_tpu.sql.planChangeValidation": "full"}


OBS_CONF_OFF = {"spark_tpu.sql.eventLog.dir": "",
                "spark_tpu.sql.trace.dir": "",
                "spark_tpu.sql.metrics.sink": "",
                "spark_tpu.sql.observability.xlaCost": "off",
                "spark_tpu.sql.observability.shardSpans": "off",
                "spark_tpu.sql.status.enabled": "false",
                "spark_tpu.sql.flightRecorder.enabled": "false",
                "spark_tpu.sql.planChangeValidation": "off"}


def measure_obs_overhead(spark, run, base_dir: str, best_of: int = 3
                         ) -> dict:
    """Warm best-of-N wall-clock of `run` with all observability ON
    (obs_conf_on) vs OFF (OBS_CONF_OFF); restores the caller's conf.
    Used by the bench `obs_overhead` section and the preflight gate."""
    on_conf = obs_conf_on(base_dir)
    saved = {k: spark.conf.get(k) for k in on_conf}

    def best(fn):
        fn()  # warm: compile + cache fill
        times = []
        for _ in range(best_of):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return min(times)

    try:
        for k, v in OBS_CONF_OFF.items():
            spark.conf.set(k, v)
        off_s = best(run)
        for k, v in on_conf.items():
            spark.conf.set(k, v)
        on_s = best(run)
    finally:
        for k, v in saved.items():
            spark.conf.set(k, v)
    return {"obs_overhead_ms": round((on_s - off_s) * 1e3, 1),
            "obs_overhead_pct": round((on_s - off_s) / off_s * 100, 1)
            if off_s > 0 else None,
            "obs_off_ms": round(off_s * 1e3, 1),
            "obs_on_ms": round(on_s * 1e3, 1)}


def bench_streaming(spark):
    """Durable-streaming section: a file-source stateful stream where
    ~6% of a 4096-group domain changes per trigger — the shape the
    incremental state store (execution/state_store.py) exists for.
    Sidecars: `streaming_rows_per_s` (micro-batch throughput incl.
    per-trigger delta persistence), `streaming_state_delta_bytes`
    (steady-state delta size) vs `streaming_state_snapshot_bytes`
    (the full-state write it replaces — the ratio is the incremental
    win), and `streaming_restore_ms` (fresh-query recovery =
    newest snapshot + <= snapshotEveryDeltas delta replays)."""
    import tempfile

    import pandas as pd

    from spark_tpu import functions as F
    from spark_tpu.functions import col

    base = tempfile.mkdtemp(prefix="bench_stream_")
    src_dir = os.path.join(base, "src")
    os.makedirs(src_dir)
    ck = os.path.join(base, "ck")
    domain = 4096
    batch_rows = 1 << 16
    n_batches = 12
    schema = pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                           "v": pd.Series([], dtype=np.int64)})
    records = []

    class _Cap:
        def on_streaming_batch(self, event):
            records.append(event.record)

    cap = _Cap()
    spark.add_listener(cap)
    try:
        def build():
            src = spark.file_stream(src_dir, schema_df=schema)
            return (src.to_df()
                    .group_by(F.pmod(col("k"), domain).alias("g"))
                    .agg(F.sum(col("v")).alias("s"),
                         F.count().alias("c"))
                    .write_stream(ck))

        q = build()
        rng = np.random.RandomState(11)
        total_rows = 0
        t0 = time.perf_counter()
        for i in range(n_batches):
            if i == 0:
                k = np.arange(batch_rows, dtype=np.int64)  # all groups
            else:
                # ~6% of groups churn per trigger
                hot = rng.choice(domain, domain // 16, replace=False)
                k = hot[rng.randint(0, len(hot), batch_rows)] \
                    .astype(np.int64)
            pd.DataFrame({"k": k, "v": np.ones(batch_rows, np.int64)}) \
                .to_parquet(os.path.join(src_dir, f"b{i:04d}.parquet"))
            q.process_available()
            total_rows += batch_rows
        elapsed = time.perf_counter() - t0
        # fresh-query recovery wall-clock (snapshot + delta replays)
        r0 = spark.metrics.counter("streaming_restore_ms").value
        q2 = build()
        restore_ms = spark.metrics.counter(
            "streaming_restore_ms").value - r0
        replayed = q2._store.last_restore_replayed
    finally:
        spark.remove_listener(cap)
    snaps = [r["state_bytes"] for r in records
             if r["kind"] == "snapshot"]
    deltas = [r["state_bytes"] for r in records if r["kind"] == "delta"]
    out = {"streaming_rows_per_s": round(total_rows / elapsed, 1),
           "streaming_batches": len(records),
           "streaming_restore_ms": round(restore_ms, 1),
           "streaming_restore_replayed_deltas": int(replayed)}
    if snaps and deltas:
        out["streaming_state_snapshot_bytes"] = int(max(snaps))
        out["streaming_state_delta_bytes"] = int(
            sum(deltas) / len(deltas))
        out["streaming_delta_ratio"] = round(
            out["streaming_state_delta_bytes"] / max(snaps), 4)
    return out


def bench_streaming_network(spark):
    """Unattended-streaming section: the socket network source
    (io/network_source.py) driven by an in-process FrameProducer.
    Sidecars: `streaming_net_rows_per_s_f<N>` (end-to-end micro-batch
    throughput — wire transfer + durable frame persistence + stateful
    fold — at two frame sizes: small frames bound replay cost, large
    frames amortize the round-trip), `streaming_net_reconnect_ms`
    (wall-clock from a mid-stream connection kill to the next batch
    committed over a fresh handshake) with the observed
    `streaming_reconnects` delta, and the host-spill tier:
    `streaming_spilled_ms` vs `streaming_resident_ms` for the SAME
    event-time stream (the spill tax), `streaming_spill_bytes` and the
    `streaming_spill_parity` byte-identical check."""
    import tempfile

    import pandas as pd

    from spark_tpu import functions as F
    from spark_tpu.functions import col
    from spark_tpu.io.network_source import FrameProducer
    from spark_tpu.streaming import (SPILL_BYTES_KEY, SPILL_PARTS_KEY,
                                     MemoryStream)

    base = tempfile.mkdtemp(prefix="bench_stream_net_")
    schema = pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                           "v": pd.Series([], dtype=np.int64)})
    rng = np.random.RandomState(13)
    out = {}

    # -- throughput at two frame sizes
    n_frames = 8
    for rows in (4096, 65536):
        prod = FrameProducer()
        port = prod.start()
        try:
            src = spark.network_stream("127.0.0.1", port, schema)
            q = (src.to_df()
                 .group_by(F.pmod(col("k"), 1024).alias("g"))
                 .agg(F.sum(col("v")).alias("s"))
                 .write_stream(os.path.join(base, f"ck_{rows}")))
            frames = [pd.DataFrame(
                {"k": rng.randint(0, 1 << 20, rows).astype(np.int64),
                 "v": np.ones(rows, np.int64)})
                for _ in range(n_frames)]
            prod.send(frames[0])
            q.process_available()  # warmup: compile + first handshake
            t0 = time.perf_counter()
            for d in frames[1:]:
                prod.send(d)
            q.process_available()
            dt = time.perf_counter() - t0
            out[f"streaming_net_rows_per_s_f{rows}"] = round(
                rows * (n_frames - 1) / dt, 1)
            src.close()
        finally:
            prod.close()

    # -- reconnect recovery latency (kill mid-stream, fresh handshake)
    prod = FrameProducer()
    port = prod.start()
    try:
        rc0 = spark.metrics.counter("streaming_reconnects").value
        src = spark.network_stream("127.0.0.1", port, schema)
        q = (src.to_df().filter(col("v") >= 0)
             .write_stream(os.path.join(base, "ck_rc"),
                           output_mode="append"))
        d = pd.DataFrame({"k": np.arange(4096, dtype=np.int64),
                          "v": np.ones(4096, np.int64)})
        prod.send(d)
        q.process_available()
        prod.kill_connection()
        prod.send(d)
        t0 = time.perf_counter()
        q.process_available()
        out["streaming_net_reconnect_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        out["streaming_reconnects"] = int(
            spark.metrics.counter("streaming_reconnects").value - rc0)
        src.close()
    finally:
        prod.close()

    # -- host-spill tier: spilled vs resident timing + output parity
    def event_rounds():
        ts0 = pd.Timestamp("2024-01-01")
        return [pd.DataFrame(
            {"ts": ts0 + pd.to_timedelta(
                rng.randint(0, 1280, 4096), unit="s"),
             "v": np.ones(4096)}) for _ in range(4)]

    rng = np.random.RandomState(13)
    rounds_r = event_rounds()
    rng = np.random.RandomState(13)
    rounds_s = event_rounds()  # identical data for both runs

    def run_event(tag, rounds):
        src = MemoryStream(spark, pd.DataFrame(
            {"ts": [pd.Timestamp("2024-01-01")], "v": [0.0]}))
        q = (src.to_df().with_watermark("ts", "10 seconds")
             .group_by(F.window(col("ts"), "10 seconds").alias("w"))
             .agg(F.sum(col("v")).alias("s"))
             .write_stream(os.path.join(base, f"ck_{tag}")))
        src.add_data(rounds[0])
        q.process_available()  # warmup batch
        t0 = time.perf_counter()
        for d in rounds[1:]:
            src.add_data(d)
            q.process_available()
        return q, time.perf_counter() - t0

    q_r, dt_r = run_event("resident", rounds_r)
    old_spill = spark.conf.get(SPILL_BYTES_KEY)
    old_parts = spark.conf.get(SPILL_PARTS_KEY)
    sp0 = spark.metrics.counter("streaming_spill_bytes").value
    try:
        spark.conf.set(SPILL_BYTES_KEY, 1)
        spark.conf.set(SPILL_PARTS_KEY, 16)
        q_s, dt_s = run_event("spilled", rounds_s)
    finally:
        spark.conf.set(SPILL_BYTES_KEY, old_spill or 0)
        spark.conf.set(SPILL_PARTS_KEY, old_parts or 16)
    out["streaming_resident_ms"] = round(dt_r * 1e3, 1)
    out["streaming_spilled_ms"] = round(dt_s * 1e3, 1)
    out["streaming_spill_bytes"] = int(
        spark.metrics.counter("streaming_spill_bytes").value - sp0)
    a = q_r.latest().sort_values("w").reset_index(drop=True)
    b = q_s.latest().sort_values("w").reset_index(drop=True)
    out["streaming_spill_parity"] = bool(a.equals(b))
    return out


def bench_obs_overhead(spark):
    """Observability tax on the wall-clock (satellite of the flight
    -recorder PR): TPC-H Q1 at a small SF, warm, best-of-3, with ALL
    sinks + xlaCost + per-shard spans ON vs everything OFF. The
    `obs_overhead_ms` / `obs_overhead_pct` sidecars make the tax
    visible across BENCH rounds; preflight stage 5 gates it at 10%."""
    import tempfile

    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    base = tempfile.mkdtemp(prefix="bench_obs_")
    write_parquet(base + "/sf", 0.01)
    Q.register_tables(spark, base + "/sf")
    return measure_obs_overhead(
        spark, lambda: Q.QUERIES["q1"](spark)._qe().collect(), base)


def bench_udf(spark):
    """Python-UDF lane section: rows/s for one vectorized pandas_udf
    over a synthetic frame, in-process vs the Arrow-batched worker
    pool, the worker lane at TWO `udf.arrow.maxRecordsPerBatch` sizes
    (the batch size is the lane's one tuning knob: small batches bound
    replay cost, large batches amortize the IPC round-trip). Sidecars:
    `udf_inprocess_rows_per_sec_M`, `udf_worker_rows_per_sec_M_b<N>`
    per batch size, plus the observed batch/restart counters from the
    worker runs."""
    import pandas as pd

    from spark_tpu.functions import col, pandas_udf

    mode_key = "spark_tpu.sql.udf.mode"
    batch_key = "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"
    n = 1 << 20
    batch_sizes = (16384, 131072)

    @pandas_udf(returnType="double")
    def fused(x, y):
        return x * 1.0001 + y.fillna(0.0) * 0.5

    df = (spark.range(n)
          .select(fused(col("id"), col("id")).alias("v")))

    def run_once():
        qe = df._qe()
        t0 = time.perf_counter()
        b, _, _ = qe.execute_batch()
        dt = time.perf_counter() - t0
        return qe, b, dt

    def best2():
        run_once()  # warmup: compile + (worker mode) pool spawn
        qe = best = None
        for _ in range(2):
            qe, _, dt = run_once()
            best = dt if best is None else min(best, dt)
        return qe, best

    out = {"udf_rows": n}
    old_mode = spark.conf.get(mode_key)
    old_batch = spark.conf.get(batch_key)
    try:
        spark.conf.set(mode_key, "inprocess")
        _, best = best2()
        out["udf_inprocess_rows_per_sec_M"] = round(n / best / 1e6, 2)
        spark.conf.set(mode_key, "worker")
        for bs in batch_sizes:
            spark.conf.set(batch_key, bs)
            qe, best = best2()
            out[f"udf_worker_rows_per_sec_M_b{bs}"] = round(
                n / best / 1e6, 2)
            summ = getattr(qe, "udf_summary", None) or {}
            out[f"udf_worker_batches_b{bs}"] = summ.get("batches")
            restarts = summ.get("worker_restarts")
            if restarts:
                out[f"udf_worker_restarts_b{bs}"] = restarts
    finally:
        spark.conf.set(mode_key, old_mode or "inprocess")
        if old_batch is not None:
            spark.conf.set(batch_key, old_batch)
    return out


def main():
    from spark_tpu import SparkTpuSession

    spark = SparkTpuSession.builder().get_or_create()
    _arm_flight_recorder(spark)
    budget = float(os.environ.get("BENCH_SECTION_BUDGET_S", "420"))
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "2400"))
    t_run0 = time.perf_counter()

    def remaining() -> float:
        return total_budget - (time.perf_counter() - t_run0)

    def run_budgeted(name: str, fn, want_s: float) -> dict:
        """_run_section under the TOTAL budget: a section whose slice
        has shrunk below 30s is skipped (with its own JSON line) so the
        run always reaches the final summary rewrite inside the
        driver's outer timeout."""
        left = remaining()
        if left < 30:
            data = {f"{name}_skipped": f"total budget "
                                       f"({total_budget:g}s) exhausted"}
            _emit(name, "skipped", time.perf_counter(), data)
            return data
        return _run_section(name, fn, min(want_s, left))

    # The aggregate summary is REWRITTEN (one flushed JSON line, marked
    # "partial": true) after EVERY section, so a global `timeout` kill
    # mid-run still leaves a parseable summary of each finished section
    # (BENCH_r05's rc:124 / parsed:null failure mode). The consumer
    # takes the LAST summary-shaped line; the final rewrite drops the
    # partial marker and is byte-identical in shape to the legacy line.
    summary = {"metric": "linear_keys_agg_rows_per_sec", "value": None,
               "unit": "M rows/s", "vs_baseline": None, "extra": {}}
    extra = summary["extra"]

    def emit_summary(final=False):
        out = summary if final else dict(summary, partial=True)
        print(json.dumps(out), flush=True)

    keys = run_budgeted(
        "linear_keys",
        lambda: {"keys_rows_per_sec_M":
                 round(bench_linear_keys(spark) / 1e6, 1)},
        budget)
    keys_rps = keys.get("keys_rows_per_sec_M")
    summary["value"] = keys_rps
    summary["vs_baseline"] = (round(keys_rps * 1e6 / KEYS_BASELINE, 3)
                              if keys_rps is not None else None)
    if keys_rps is None:
        extra.update(keys)  # surface the headline failure in the summary
    emit_summary()

    def stddev_section():
        rps = bench_stddev(spark)
        return {"stddev_rows_per_sec_M": round(rps / 1e6, 1),
                "stddev_vs_baseline": round(rps / STDDEV_BASELINE, 3)}

    extra.update(run_budgeted("stddev", stddev_section, budget))
    emit_summary()
    extra.update(run_budgeted(
        "grouped100",
        lambda: {"grouped100_rows_per_sec_M":
                 round(bench_100_groups(spark) / 1e6, 1)},
        budget))
    emit_summary()
    extra.update(run_budgeted(
        "kernel_pick", lambda: bench_kernel_pick(spark), budget))
    emit_summary()
    extra.update(run_budgeted(
        "join_microbench", lambda: bench_join_microbench(spark),
        budget))
    emit_summary()
    extra.update(run_budgeted(
        "obs_overhead", lambda: bench_obs_overhead(spark),
        min(budget, 240)))
    emit_summary()
    # durable streaming: micro-batch throughput + incremental
    # state-store delta-vs-snapshot bytes + fresh-query restore cost
    extra.update(run_budgeted(
        "streaming", lambda: bench_streaming(spark),
        min(budget, 240)))
    emit_summary()
    # unattended streaming: network-source throughput at two frame
    # sizes, reconnect recovery latency, spilled-vs-resident state
    extra.update(run_budgeted(
        "streaming_network", lambda: bench_streaming_network(spark),
        min(budget, 240)))
    emit_summary()
    # Python-UDF lane: in-process vs Arrow worker pool rows/s at two
    # batch sizes (the lane's tuning knob)
    extra.update(run_budgeted(
        "udf", lambda: bench_udf(spark), min(budget, 240)))
    emit_summary()
    # persistent compile cache: cold vs warm PROCESS compile cost via
    # two fresh subprocesses sharing one cache dir
    extra.update(run_budgeted(
        "compile_cache", lambda: bench_compile_cache(spark),
        min(budget, 300)))
    emit_summary()
    # the TPC-H trajectory is the headline consumer of BENCH rounds:
    # give it whatever remains of the total budget (at least its
    # section slice) so earlier overruns can't starve it entirely
    tpch_budget = max(budget, min(2 * budget, remaining() - 30))
    extra.update(run_budgeted(
        f"tpch_sf{TPCH_SF:g}",
        lambda: bench_tpch(
            spark, TPCH_SF, TPCH_PATH,
            deadline=time.perf_counter()
            + min(tpch_budget, max(remaining(), 1)) * 0.9),
        tpch_budget))
    emit_summary()
    # TPC-DS tranche: the reference's own committed-baseline suite (3
    # representative snowflake queries under the same budget machinery)
    extra.update(run_budgeted(
        f"tpcds_sf{TPCDS_SF:g}",
        lambda: bench_tpcds(
            spark, TPCDS_SF, TPCDS_PATH,
            deadline=time.perf_counter()
            + min(budget, max(remaining(), 1)) * 0.9),
        budget))
    emit_summary()

    # SF10: the north-star scale on one chip (VERDICT r4 #2). The
    # device-table cache budget rises so the pruned lineitem goes
    # RESIDENT (~3.6GB in 16GB HBM): warm runs then skip host ingest.
    # Opt-in (BENCH_RUN_SF10=1): the default matrix must fit the total
    # budget, and r05 proved the SF10 sweep alone can blow it.
    if os.environ.get("BENCH_RUN_SF10") \
            and not os.environ.get("BENCH_SKIP_SF10"):
        sf10_path = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "data", "tpch", "sf10")
        sf10_budget = float(os.environ.get("BENCH_SF10_BUDGET_S", "1500"))

        def sf10_section():
            spark.conf.set("spark_tpu.sql.io.deviceCacheBytes", 12 << 30)
            try:
                return bench_tpch(
                    spark, 10, sf10_path, float_atol=1e-3,
                    deadline=time.perf_counter() + sf10_budget)
            finally:
                spark.conf.set("spark_tpu.sql.io.deviceCacheBytes",
                               6 << 30)

        extra.update(run_budgeted("tpch_sf10", sf10_section,
                                  sf10_budget * 1.1))

    emit_summary(final=True)


if __name__ == "__main__":
    main()

"""Headline benchmark, run by the driver on real TPU hardware.

Config 1 from BASELINE.json: ``range(1e9).groupBy(id % 100).count()`` —
the same fused range->hash-aggregate loop as the reference's
`AggregateBenchmark-results.txt` "w/ keys" rows. The committed reference
number for single-key hash aggregation with whole-stage codegen is
1812.5 M rows/s (no grouping; `AggregateBenchmark-results.txt:9-11`,
Xeon Platinum 8171M) — vs_baseline is our rows/s over that.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

N = 1_000_000_000
SPARK_BASELINE_ROWS_PER_SEC = 1812.5e6  # AggregateBenchmark codegen ON


def main():
    from spark_tpu import SparkTpuSession
    from spark_tpu.functions import col

    spark = SparkTpuSession.builder().get_or_create()
    df = spark.range(N).group_by((col("id") % 100).alias("k")).count()
    qe = df._qe()

    import numpy as np

    def run_sync():
        b, _, _ = qe.execute_batch()
        # a host pull is the only reliable sync point on tunneled runtimes
        # where block_until_ready returns before execution completes
        np.asarray(b.columns["count"].data)
        return b

    # warmup: compile + first run
    batch = run_sync()

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        batch = run_sync()
        times.append(time.perf_counter() - t0)
    best = min(times)

    # correctness gate: every group must count N/100
    pdf = batch.to_arrow().to_pydict()
    assert sorted(pdf["k"]) == list(range(100)), pdf["k"][:5]
    assert all(c == N // 100 for c in pdf["count"]), pdf["count"][:5]

    rows_per_sec = N / best
    print(json.dumps({
        "metric": "hash_aggregate_range_1e9_groupby_100",
        "value": round(rows_per_sec / 1e6, 1),
        "unit": "M rows/s",
        "vs_baseline": round(rows_per_sec / SPARK_BASELINE_ROWS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()

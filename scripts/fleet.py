#!/usr/bin/env python
"""Run a crash-only SqlService serving fleet on this host.

Thin launcher over spark_tpu/service/fleet.py: a supervisor process
that owns the public port and routes to N SqlService worker
subprocesses (session-affine consistent hashing, read failover,
RetryPolicy restart ladder with flap-breaker quarantine). SIGTERM or
SIGINT drains: new work sheds with 503 FLEET_DRAINING, in-flight
queries finish under spark_tpu.service.fleet.drainTimeoutMs, workers
exit 0, the supervisor follows.

Usage:
    scripts/fleet.py --workers 4 --port 8080 \
        --conf spark_tpu.sql.compileCache.dir=/var/cache/sptpu \
        --init myapp.serving:init_session

Workers share the compile-cache dir, so a respawned worker opens hot
(warm-start manifest replay instead of XLA recompiles).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_tpu.service.fleet import _supervisor_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(_supervisor_main(sys.argv[1:]))

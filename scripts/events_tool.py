#!/usr/bin/env python
"""Event-log JSONL tooling: schema validation + tail pretty-printer.

The event log is the engine's replay/debug surface (history.py,
GET /queries, BENCH trajectory analysis); a malformed line silently
breaks every consumer downstream. This tool makes the schema contract
enforceable in CI:

    scripts/events_tool.py validate <file-or-dir> [...]
        Validate every app-*.jsonl line against the versioned schema.
        Knows every published schema_version (1..7): v3 added the
        per-shard `shards` records, `plan_tree` and `predictions`;
        v4 added the per-micro-batch `streaming` record; v5 added the
        per-query `udf` record (worker-lane batch/row totals); v6
        added the per-tick `trigger` record (supervised streaming
        trigger loop); v7 added the per-(batch, rule) `rule_trace`
        optimizer records — purely additive, so old logs must (and do)
        validate under their own version's rules. Exits nonzero listing file:line: problem for
        every violation.

    scripts/events_tool.py tail <file-or-dir> [-n N]
        Pretty-print the last N events (default 10): query id, status,
        wall seconds, top spans, fault/straggler notes.

    scripts/events_tool.py stats <file-or-dir> [...]
        Summarize the logs: per-record-type counts (query executions
        by status, streaming batches, trigger ticks, shard/span
        carriers), a schema-version histogram, and the time span
        covered (first/last ts, wall duration).

Wired into scripts/preflight.sh after the observability smoke, so a
schema regression (a field rename, a non-serializable value degrading
to repr) fails the gate instead of landing in a BENCH round.
"""

from __future__ import annotations

import glob
import json
import os
import sys

KNOWN_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7)

#: per-micro-batch streaming record contract (schema v4):
#: field -> allowed types
_STREAMING_FIELDS = {
    "batch_id": (int,),
    "start": (int,),
    "end": (int,),
    "rows_in": (int,),
    "rows_out": (int,),
    "kind": (str,),
    "state_bytes": (int, type(None)),
    "quarantined": (int,),
    "sink_parts": (int,),
    "source": (str,),
}

_STREAMING_KINDS = ("stateless", "delta", "snapshot")

#: per-query Python-UDF record contract (schema v5): field -> allowed
#: types (one record per execution that evaluated UDFs, summed across
#: UDF nodes; mirrors the udf_* metric counters)
_UDF_FIELDS = {
    "mode": (str,),
    "batches": (int,),
    "rows": (int,),
    "exec_ms": (int, float),
    "worker_restarts": (int,),
    "max_records_per_batch": (int,),
}

_UDF_MODES = ("inprocess", "worker")

#: per-tick trigger record contract (schema v6): field -> allowed
#: types (one record per supervised trigger-loop tick that ran
#: batches, plus the parking tick of a FAILED query)
_TRIGGER_FIELDS = {
    "tick": (int,),
    "skew_ms": (int, float),
    "batches_run": (int,),
    "restarts": (int,),
    "source": (str,),
    "reconnects": (int,),
}

#: per-(batch, rule) optimizer-trace record contract (schema v7):
#: field -> allowed types; `diff` (first effective before/after tree
#: diff) rides only when spark_tpu.sql.planChangeLog is on
_RULE_TRACE_FIELDS = {
    "batch": (str,),
    "rule": (str,),
    "invocations": (int,),
    "effective": (int,),
    "ms": (int, float),
}


#: per-shard record contract (schema v3): field -> allowed types
#: (shard None marks host-side ingest records)
_SHARD_FIELDS = {
    "shard": (int, type(None)),
    "host": (int,),
    "phase": (str,),
    "chunk": (int, type(None)),
    "rows": (int, type(None)),
    "bytes": (int, type(None)),
    "source": (str,),
}

_SHARD_PHASES = ("ingest", "compute", "transfer")


def _problem(out, path, lineno, msg):
    out.append(f"{path}:{lineno}: {msg}")


def validate_event(e: dict, path: str, lineno: int, out: list) -> None:
    """One event-log record against its own schema_version's rules."""
    ver = e.get("schema_version")
    if ver not in KNOWN_SCHEMA_VERSIONS:
        _problem(out, path, lineno,
                 f"unknown schema_version {ver!r} "
                 f"(known: {KNOWN_SCHEMA_VERSIONS})")
        return
    for key, types in (("ts", (int, float)), ("status", (str,)),
                       ("plan", (str,)), ("query_id", (int,))):
        if not isinstance(e.get(key), types):
            _problem(out, path, lineno,
                     f"field {key!r} missing or not {types}")
    # "cancelled"/"deadline_exceeded": lifecycle-control stops
    # (execution/lifecycle.py), written by the executor's query-end
    # event next to ok/error
    if e.get("status") not in ("ok", "error", "cancelled",
                               "deadline_exceeded"):
        _problem(out, path, lineno, f"bad status {e.get('status')!r}")
    phases = e.get("phase_times_s")
    if phases is not None and (
            not isinstance(phases, dict)
            or any(not isinstance(v, (int, float))
                   for v in phases.values())):
        _problem(out, path, lineno, "phase_times_s must map to numbers")
    metrics = e.get("metrics")
    if metrics is not None and not isinstance(metrics, dict):
        _problem(out, path, lineno, "metrics must be a dict")
    for s in e.get("spans") or []:
        if not isinstance(s, dict) or not isinstance(s.get("name"), str) \
                or not isinstance(s.get("t0_ms"), (int, float)) \
                or not isinstance(s.get("dur_ms"), (int, float)):
            _problem(out, path, lineno, f"malformed span record: {s!r}")
            break
    for st in e.get("stages") or []:
        if not isinstance(st, dict) or "key_hash" not in st:
            _problem(out, path, lineno,
                     f"malformed stage-cost record: {st!r}")
            break
    if ver < 3:
        for v3_field in ("shards", "predictions", "plan_tree",
                         "reorder"):
            if v3_field in e:
                _problem(out, path, lineno,
                         f"schema v{ver} record carries v3 field "
                         f"{v3_field!r}")
    if ver < 4 and "streaming" in e:
        _problem(out, path, lineno,
                 f"schema v{ver} record carries v4 field 'streaming'")
    if ver < 5 and "udf" in e:
        _problem(out, path, lineno,
                 f"schema v{ver} record carries v5 field 'udf'")
    if ver < 6 and "trigger" in e:
        _problem(out, path, lineno,
                 f"schema v{ver} record carries v6 field 'trigger'")
    if ver < 7 and "rule_trace" in e:
        _problem(out, path, lineno,
                 f"schema v{ver} record carries v7 field 'rule_trace'")
    if ver < 3:
        return
    reorder = e.get("reorder")
    if reorder is not None and (
            not isinstance(reorder, dict)
            or not isinstance(reorder.get("regions"), list)
            or any(not isinstance(d, dict)
                   or not isinstance(d.get("relations"), list)
                   or not isinstance(d.get("order"), list)
                   for d in reorder["regions"])):
        _problem(out, path, lineno,
                 f"malformed reorder record: {reorder!r}")
    for rec in e.get("shards") or []:
        bad = None
        if not isinstance(rec, dict):
            bad = "not a dict"
        else:
            for field, types in _SHARD_FIELDS.items():
                if not isinstance(rec.get(field), types):
                    bad = f"field {field!r} not {types}"
                    break
            if bad is None and rec.get("phase") not in _SHARD_PHASES:
                bad = f"phase {rec.get('phase')!r} not in {_SHARD_PHASES}"
            if bad is None and rec.get("shard") is None \
                    and rec.get("phase") != "ingest":
                bad = "shard-less record must be phase 'ingest'"
        if bad is not None:
            _problem(out, path, lineno,
                     f"malformed shard record ({bad}): {rec!r}")
            break
    for p in e.get("predictions") or []:
        if not isinstance(p, dict) or not isinstance(p.get("kind"), str) \
                or not isinstance(p.get("predicted"), (int, float)):
            _problem(out, path, lineno,
                     f"malformed prediction record: {p!r}")
            break
    if ver >= 4:
        s = e.get("streaming")
        if s is not None:
            bad = None
            if not isinstance(s, dict):
                bad = "not a dict"
            else:
                for field, types in _STREAMING_FIELDS.items():
                    if not isinstance(s.get(field), types):
                        bad = f"field {field!r} not {types}"
                        break
                if bad is None and s.get("kind") not in _STREAMING_KINDS:
                    bad = (f"kind {s.get('kind')!r} not in "
                           f"{_STREAMING_KINDS}")
            if bad is not None:
                _problem(out, path, lineno,
                         f"malformed streaming record ({bad}): {s!r}")
    if ver >= 5:
        u = e.get("udf")
        if u is not None:
            bad = None
            if not isinstance(u, dict):
                bad = "not a dict"
            else:
                for field, types in _UDF_FIELDS.items():
                    if not isinstance(u.get(field), types):
                        bad = f"field {field!r} not {types}"
                        break
                if bad is None and u.get("mode") not in _UDF_MODES:
                    bad = f"mode {u.get('mode')!r} not in {_UDF_MODES}"
            if bad is not None:
                _problem(out, path, lineno,
                         f"malformed udf record ({bad}): {u!r}")
    if ver >= 6:
        t = e.get("trigger")
        if t is not None:
            bad = None
            if not isinstance(t, dict):
                bad = "not a dict"
            else:
                for field, types in _TRIGGER_FIELDS.items():
                    if not isinstance(t.get(field), types):
                        bad = f"field {field!r} not {types}"
                        break
            if bad is not None:
                _problem(out, path, lineno,
                         f"malformed trigger record ({bad}): {t!r}")
    if ver >= 7:
        rt = e.get("rule_trace")
        if rt is not None and not isinstance(rt, list):
            _problem(out, path, lineno,
                     f"rule_trace must be a list: {rt!r}")
        else:
            for rec in rt or []:
                bad = None
                if not isinstance(rec, dict):
                    bad = "not a dict"
                else:
                    for field, types in _RULE_TRACE_FIELDS.items():
                        if not isinstance(rec.get(field), types):
                            bad = f"field {field!r} not {types}"
                            break
                    if bad is None and rec["effective"] > \
                            rec["invocations"]:
                        bad = "effective exceeds invocations"
                    if bad is None and "diff" in rec \
                            and not isinstance(rec["diff"], str):
                        bad = "field 'diff' not a string"
                if bad is not None:
                    _problem(out, path, lineno,
                             f"malformed rule_trace record ({bad}): "
                             f"{rec!r}")
                    break


def _log_files(targets):
    files = []
    for t in targets:
        if os.path.isdir(t):
            files.extend(sorted(glob.glob(os.path.join(t, "app-*.jsonl"))))
        else:
            files.append(t)
    return files


def validate(targets) -> list:
    """All violations across the targets as 'path:line: msg' strings."""
    out: list = []
    files = _log_files(targets)
    if not files:
        out.append(f"no event-log files found under {targets}")
        return out
    for path in files:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError as ex:
                    _problem(out, path, lineno, f"unparseable JSON: {ex}")
                    continue
                if not isinstance(e, dict):
                    _problem(out, path, lineno, "line is not an object")
                    continue
                validate_event(e, path, lineno, out)
    return out


def tail(targets, n: int = 10) -> list:
    """The last n events across the targets, pretty-printed lines."""
    events = []
    for path in _log_files(targets):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                events.append((e.get("ts") or 0, os.path.basename(path), e))
    events.sort(key=lambda t: t[0])
    lines = []
    for _, name, e in events[-n:]:
        phases = e.get("phase_times_s") or {}
        total = sum(v for v in phases.values()
                    if isinstance(v, (int, float)))
        spans = sorted(e.get("spans") or [],
                       key=lambda s: -(s.get("dur_ms") or 0))[:3]
        bits = [f"{name} q{e.get('query_id')} {e.get('status')}"
                f" {total:.3f}s v{e.get('schema_version')}"]
        if spans:
            bits.append("spans: " + ", ".join(
                f"{s['name']}={s['dur_ms']:.0f}ms" for s in spans))
        shards = e.get("shards") or []
        if shards:
            ns = {r.get("shard") for r in shards
                  if r.get("shard") is not None}
            bits.append(f"shards: {len(ns)} x "
                        f"{len(shards) // max(len(ns), 1)} recs")
        fs = e.get("fault_summary") or {}
        acts = {k: v for k, v in fs.items()
                if isinstance(v, int) and k != "events_dropped"}
        if acts:
            bits.append(f"faults: {acts}")
        lines.append("  ".join(bits))
    return lines


def stats(targets) -> list:
    """Aggregate log statistics as printable lines: record-type
    counts, schema-version histogram, covered time span."""
    n_lines = 0
    statuses: dict = {}
    versions: dict = {}
    kinds = {"streaming": 0, "trigger": 0, "with_shards": 0,
             "with_spans": 0, "with_faults": 0}
    ts_min = ts_max = None
    files = _log_files(targets)
    for path in files:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(e, dict):
                    continue
                n_lines += 1
                ver = e.get("schema_version")
                versions[ver] = versions.get(ver, 0) + 1
                ts = e.get("ts")
                if isinstance(ts, (int, float)):
                    ts_min = ts if ts_min is None else min(ts_min, ts)
                    ts_max = ts if ts_max is None else max(ts_max, ts)
                if "streaming" in e:
                    kinds["streaming"] += 1
                elif "trigger" in e:
                    kinds["trigger"] += 1
                else:
                    st = e.get("status")
                    statuses[st] = statuses.get(st, 0) + 1
                if e.get("shards"):
                    kinds["with_shards"] += 1
                if e.get("spans"):
                    kinds["with_spans"] += 1
                if e.get("fault_summary"):
                    kinds["with_faults"] += 1
    lines = [f"files: {len(files)}  records: {n_lines}"]
    execs = sum(statuses.values())
    lines.append("executions: " + (
        f"{execs} (" + ", ".join(
            f"{s}={n}" for s, n in sorted(statuses.items(),
                                          key=lambda kv: -kv[1]))
        + ")" if execs else "0"))
    lines.append(f"streaming batches: {kinds['streaming']}  "
                 f"trigger ticks: {kinds['trigger']}")
    lines.append(f"carrying shards/spans/faults: "
                 f"{kinds['with_shards']}/{kinds['with_spans']}"
                 f"/{kinds['with_faults']}")
    lines.append("schema versions: " + (", ".join(
        f"v{v}={n}" for v, n in sorted(
            versions.items(), key=lambda kv: (kv[0] is None, kv[0])))
        or "none"))
    if ts_min is not None:
        import datetime

        def iso(t):
            return datetime.datetime.fromtimestamp(t).isoformat(
                timespec="seconds")
        lines.append(f"time span: {iso(ts_min)} .. {iso(ts_max)} "
                     f"({ts_max - ts_min:.1f}s)")
    return lines


def main(argv) -> int:
    if not argv or argv[0] not in ("validate", "tail", "stats"):
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]
    n = 10
    if "-n" in rest:
        i = rest.index("-n")
        n = int(rest[i + 1])
        rest = rest[:i] + rest[i + 2:]
    if not rest:
        print(f"events_tool {cmd}: need at least one file or directory",
              file=sys.stderr)
        return 2
    if cmd == "validate":
        problems = validate(rest)
        if problems:
            print(f"events_tool validate: FAILED "
                  f"({len(problems)} problem(s))")
            for p in problems:
                print("  " + p)
            return 1
        nfiles = len(_log_files(rest))
        print(f"events_tool validate: ok ({nfiles} file(s))")
        return 0
    if cmd == "stats":
        for line in stats(rest):
            print(line)
        return 0
    for line in tail(rest, n):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""Unified source-lint runner (spark_tpu/analysis/lints framework).

The generalization of scripts/metrics_lint.py: one registry of AST
passes over the repository — metric prefixes, conf-key registration,
fault-site wiring, tracer-leak shapes — run together from preflight
stage 6 and tests/test_analysis.py.

Usage:
    scripts/lint.py --all            # every registered pass
    scripts/lint.py --list           # show the pass catalog
    scripts/lint.py conf-key ...     # named subset
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(names=None):
    """All violations as 'path:line: [pass] message' strings (empty =
    clean tree)."""
    sys.path.insert(0, REPO)
    from spark_tpu.analysis.lints import run_passes
    return [v.render() for v in run_passes(names)]


def main(argv) -> int:
    sys.path.insert(0, REPO)
    from spark_tpu.analysis.lints import LINT_PASSES
    from spark_tpu.analysis.lints import passes as _passes  # noqa: F401
    args = [a for a in argv if a not in ("--all",)]
    if "--list" in args:
        for name in sorted(LINT_PASSES):
            print(f"{name:14s} {LINT_PASSES[name].doc}")
        return 0
    names = args or None
    problems = run(names)
    label = ",".join(names) if names else "all passes"
    if problems:
        print(f"lint ({label}): FAILED")
        for p in problems:
            print("  " + p)
        return 1
    print(f"lint ({label}): ok ({len(LINT_PASSES) if not names else len(names)} passes, 0 violations)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

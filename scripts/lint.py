#!/usr/bin/env python
"""Unified source-lint runner (spark_tpu/analysis/lints framework).

The generalization of scripts/metrics_lint.py: one registry of AST
passes over the repository — metric prefixes, conf-key registration,
fault-site wiring, tracer-leak shapes, the concurrency analyzer's
guarded-by and lock-order passes — run together from preflight and
tests/test_analysis.py.

Usage:
    scripts/lint.py --all            # every registered pass
    scripts/lint.py --list           # show the pass catalog
    scripts/lint.py --json [...]     # machine-readable findings
    scripts/lint.py conf-key ...     # named subset

--json emits one JSON object on stdout:
    {"ok": bool, "passes": [...],
     "violations": [{"pass", "code", "severity", "path", "line",
                     "message"}, ...],
     "notes": ["waiver: ...", ...]}
CI/preflight gates on exit status (nonzero iff any error-severity
violation) or on the `violations` array directly; `notes` carries the
reviewer-visible guarded-by waiver list and lock-order graph summary.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(names=None, collect_notes=None):
    """All violations as 'path:line: [pass] message' strings (empty =
    clean tree)."""
    sys.path.insert(0, REPO)
    from spark_tpu.analysis.lints import run_passes
    return [v.render()
            for v in run_passes(names, collect_notes=collect_notes)]


def main(argv) -> int:
    sys.path.insert(0, REPO)
    from spark_tpu.analysis.lints import LINT_PASSES, run_passes
    from spark_tpu.analysis.lints import passes as _passes  # noqa: F401
    as_json = "--json" in argv
    args = [a for a in argv if a not in ("--all", "--json")]
    if "--list" in args:
        from spark_tpu.analysis.concurrency import (  # noqa: F401
            lint_passes as _cpasses)
        for name in sorted(LINT_PASSES):
            print(f"{name:14s} {LINT_PASSES[name].doc}")
        return 0
    names = args or None
    notes: list = []
    violations = run_passes(names, collect_notes=notes)
    errors = [v for v in violations if v.severity == "error"]
    if as_json:
        print(json.dumps({
            "ok": not errors,
            "passes": names or sorted(LINT_PASSES),
            "violations": [v.to_dict() for v in violations],
            "notes": notes,
        }, indent=2))
        return 1 if errors else 0
    label = ",".join(names) if names else "all passes"
    if errors:
        print(f"lint ({label}): FAILED")
        for v in violations:
            print("  " + v.render())
        return 1
    if violations:
        # warn/info only: surfaced, never failing — the verdict must
        # agree with the exit status (and with --json's `ok` field)
        print(f"lint ({label}): ok with {len(violations)} warning(s)")
        for v in violations:
            print("  " + v.render())
        return 0
    n = len(names) if names else len(LINT_PASSES)
    print(f"lint ({label}): ok ({n} passes, 0 violations)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""Static lint: every `ctx.add_metric(...)` name must use a registered
prefix (observability.metrics.METRIC_PREFIXES).

A traced metric with an unregistered name would flow into the event log
but silently miss every history summary column — this lint (plus the
trace-time check in ExecContext.add_metric) makes that a CI failure
instead. Runs from preflight.sh and tests/test_observability.py.

Rules checked per call site:
  - first argument is a string literal  -> full name must match
  - first argument is an f-string       -> the LEADING literal part is
    the prefix; it must be non-empty and match (a metric name that
    starts with an interpolation can't be attributed to a registry
    prefix at all)
  - anything else (variable, call)      -> flagged: the name can't be
    statically attributed
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "spark_tpu")


def _prefix_of(node: ast.expr):
    """(kind, literal-or-None) for an add_metric name argument."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "literal", node.value
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str) \
                and node.values[0].value:
            return "fstring", node.values[0].value
        return "fstring", None
    return "dynamic", None


def lint_file(path: str, prefixes) -> List[Tuple[int, str]]:
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    problems: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_metric"
                and node.args):
            continue
        kind, text = _prefix_of(node.args[0])
        if text is None:
            problems.append((node.lineno,
                             f"metric name not statically attributable "
                             f"({kind} argument)"))
        elif not text.startswith(tuple(prefixes)):
            problems.append((node.lineno,
                             f"unregistered metric prefix: {text!r}"))
    return problems


def run(root: str = PACKAGE) -> List[str]:
    """All violations as 'path:line: message' strings (empty = clean)."""
    sys.path.insert(0, REPO)
    from spark_tpu.observability.metrics import METRIC_PREFIXES
    out: List[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            for lineno, msg in lint_file(path, METRIC_PREFIXES):
                rel = os.path.relpath(path, REPO)
                out.append(f"{rel}:{lineno}: {msg}")
    return out


def main() -> int:
    problems = run()
    if problems:
        print("metrics_lint: FAILED")
        for p in problems:
            print("  " + p)
        return 1
    print("metrics_lint: ok (every add_metric name is registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Static lint: every `ctx.add_metric(...)` name must use a registered
prefix (observability.metrics.METRIC_PREFIXES).

Kept as a thin compatibility wrapper: the pass now lives in the unified
lint framework (`spark_tpu/analysis/lints`, pass name `metric-prefix`)
and runs with every other pass via `scripts/lint.py --all` (preflight
stage 6). `run()` keeps its original contract — a list of
'path:line: message' strings, empty on a clean tree — for
tests/test_observability.py and any external caller.
"""

from __future__ import annotations

import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(root: str = None) -> List[str]:
    """All metric-prefix violations (empty = clean). `root` is ignored
    (the framework walks the repository); kept for signature compat."""
    sys.path.insert(0, REPO)
    from spark_tpu.analysis.lints import run_passes
    return [f"{v.path}:{v.line}: {v.message}"
            for v in run_passes(["metric-prefix"])]


def main() -> int:
    problems = run()
    if problems:
        print("metrics_lint: FAILED")
        for p in problems:
            print("  " + p)
        return 1
    print("metrics_lint: ok (every add_metric name is registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

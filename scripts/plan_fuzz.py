#!/usr/bin/env python
"""Differential optimizer fuzz campaign CLI.

Runs `spark_tpu.testing.plan_fuzz` seeds: each seed generates random
tables + a random query, executes it optimizer-off vs optimizer-on
(under planChangeValidation=full) and per-rule-ablated, and asserts
byte-identical results, zero integrity findings, and stable stage
keys across repeated planning.

Usage:
    python scripts/plan_fuzz.py --seeds 500
    python scripts/plan_fuzz.py --seeds 64 --ablate one
    python scripts/plan_fuzz.py --start 1000 --seeds 100 --stop-on-fail

Exits nonzero if any seed fails; failing seeds replay exactly with
`run_seed(session, <seed>)`.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=None,
                    help="number of seeds (default: conf "
                         "spark_tpu.sql.fuzz.seeds)")
    ap.add_argument("--start", type=int, default=0,
                    help="first seed (default 0)")
    ap.add_argument("--ablate", default="effective",
                    choices=("none", "one", "effective", "all"),
                    help="rule-ablation mode (default: effective — "
                         "ablate each rule that fired)")
    ap.add_argument("--max-rows", type=int, default=None,
                    help="max rows per generated table (default: conf "
                         "spark_tpu.sql.fuzz.maxRows)")
    ap.add_argument("--stop-on-fail", action="store_true",
                    help="abort the campaign at the first failing seed")
    args = ap.parse_args(argv)

    from spark_tpu.session import SparkTpuSession
    from spark_tpu.testing import plan_fuzz

    session = SparkTpuSession.builder().get_or_create()
    n = args.seeds if args.seeds is not None else \
        int(session.conf.get(plan_fuzz.SEEDS_KEY))
    seeds = range(args.start, args.start + n)

    t0 = time.time()
    done = [0]

    def progress(seed, ok):
        done[0] += 1
        if done[0] % 50 == 0:
            print(f"  ... {done[0]}/{n} seeds "
                  f"({time.time() - t0:.1f}s)", flush=True)

    res = plan_fuzz.run_campaign(session, seeds, ablate=args.ablate,
                                 max_rows=args.max_rows,
                                 stop_on_fail=args.stop_on_fail,
                                 progress=progress)
    dt = time.time() - t0
    print(f"plan-fuzz: {len(res['ok'])}/{n} seeds clean in {dt:.1f}s "
          f"(seeds {args.start}..{args.start + n - 1}, "
          f"ablate={args.ablate})")
    if res["effective_counts"]:
        print("effective-rule coverage:")
        for rule, cnt in sorted(res["effective_counts"].items(),
                                key=lambda kv: -kv[1]):
            print(f"  {rule}: {cnt}")
    if res["failures"]:
        print(f"\n{len(res['failures'])} FAILING seed(s):",
              file=sys.stderr)
        for seed, err in res["failures"]:
            print(f"  seed {seed}: {err}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Perf-regression gate: bench smoke vs the last good BENCH round.

The round-5 failure mode was a perf trajectory going dark (BENCH_r05:
rc 124, parsed null) with nothing in CI noticing. This gate runs the
TPC-H smoke (Q1 + Q3, small scale factor, current backend) and fails
preflight when `tpch_*_ms` regresses more than the threshold against
the recorded baseline:

- The baseline lives in PERF_BASELINE.json, keyed by platform+scale
  (CPU preflight numbers must never be compared against TPU BENCH
  rounds). A missing entry self-calibrates: on a TPU backend at the
  BENCH scale factor it seeds from the newest BENCH_*.json that
  actually parsed tpch metrics (the "last good" round); otherwise from
  the current measurement — then passes with a note.
- Regression = current > baseline * (1 + threshold) AND current >
  baseline + abs_floor_ms (small queries jitter; a 25% blowup of 80ms
  is noise, of 800ms is a regression).

Usage:
    scripts/perf_gate.py [--update]        # --update re-calibrates
Env:
    PERF_GATE_SF (default 0.01), PERF_GATE_THRESHOLD_PCT (default 25),
    PERF_GATE_FLOOR_MS (default 200), PERF_GATE_QUERIES (q1,q3),
    PERF_GATE_COMPILE=1 (opt-in: also measure + gate the
    compile-cache cold/warm-process rows, tpch_q*_compile_*_ms)
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "PERF_BASELINE.json")
sys.path.insert(0, REPO)


#: baseline metric families merged independently from BENCH rounds:
#: name -> key regex. `compile` carries the compile-cache section's
#: cold/warm-process compile rows (bench_compile_cache), so warm
#: compile-time regressions enter the gated baseline like wall-clock.
FAMILIES = (
    ("tpch", r"tpch_q\d+_sf[\d.]+_ms$"),
    ("tpcds", r"tpcds_q\d+_sf[\d.]+_ms$"),
    ("compile", r"tpch_q\d+_compile_(?:cold|warm)_ms$"),
)


def last_good_bench() -> tuple:
    """(name, {metric: ms}) merged PER FAMILY from the newest
    BENCH_*.json rounds: tpch_*_ms from the newest round that carries
    any, tpcds_*_ms likewise, tpch_*_compile_*_ms likewise — a round
    whose tpch section timed out but whose tpcds section parsed must
    not shadow an older round's good tpch numbers (and vice versa).
    `name` is the newest contributing round; (None, {}) when the
    trajectory is dark."""
    rounds = []
    for name in os.listdir(REPO):
        m = re.match(r"BENCH_r(\d+)\.json$", name)
        if m:
            rounds.append((int(m.group(1)), name))
    merged: dict = {}
    newest = None
    seen_families = set()
    for _, name in sorted(rounds, reverse=True):
        try:
            doc = json.load(open(os.path.join(REPO, name)))
        except (OSError, ValueError):
            continue
        extra = ((doc.get("parsed") or {}).get("extra")) or {}
        for fam, rx in FAMILIES:
            if fam in seen_families:
                continue
            ms = {k: float(v) for k, v in extra.items()
                  if re.match(rx, k)}
            if ms:
                seen_families.add(fam)
                merged.update(ms)
                if newest is None:
                    newest = name
        if len(seen_families) == len(FAMILIES):
            break
    return newest, merged


def _time3(run_once) -> float:
    run_once()  # warmup: compile + ingest
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_once()
        times.append(time.perf_counter() - t0)
    return round(min(times) * 1e3, 1)


def measure(sf: float, queries, tpcds_queries=()) -> dict:
    """Warm min-of-3 wall-clock per query at `sf` on the current
    backend — the same shapes bench.py's tpch/tpcds sections time.
    `queries` are TPC-H DataFrame names (tpch_<q>_ms keys);
    `tpcds_queries` are TPC-DS SQL names (tpcds_<q>_ms keys)."""
    import tempfile

    from spark_tpu import SparkTpuSession
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    path = os.path.join(tempfile.gettempdir(),
                        f"perf_gate_tpch_sf{sf:g}")
    write_parquet(path, sf)  # cached across runs (datagen skips fresh)
    spark = SparkTpuSession.builder().get_or_create()
    Q.register_tables(spark, path)
    out = {}
    for name in queries:
        df_fn = Q.QUERIES[name]

        def run_once():
            qe = df_fn(spark)._qe()
            b, _, _ = qe.execute_batch()
            return b.to_arrow()

        out[f"tpch_{name}_ms"] = _time3(run_once)
    if tpcds_queries:
        from spark_tpu.tpcds import SQL_QUERIES, register_tables
        from spark_tpu.tpcds.datagen import write_parquet as ds_write
        ds_path = os.path.join(tempfile.gettempdir(),
                               f"perf_gate_tpcds_sf{sf:g}")
        ds_write(ds_path, sf)
        register_tables(spark, ds_path)
        for name in tpcds_queries:
            sql = SQL_QUERIES[name]

            def run_once_ds():
                qe = spark.sql(sql)._qe()
                b, _, _ = qe.execute_batch()
                return b.to_arrow()

            out[f"tpcds_{name}_ms"] = _time3(run_once_ds)
    return out


def platform_key(sf: float) -> str:
    """Backend + scale + a coarse machine fingerprint (arch, core
    count). Wall-clock baselines only gate between comparable hosts:
    the same numbers on a machine of a different shape would fail
    preflight on hardware variance, not regressions — a key mismatch
    self-recalibrates instead."""
    import platform

    import jax
    return (f"{jax.default_backend()}-sf{sf:g}"
            f"-{platform.machine()}-c{os.cpu_count()}")


def _default_sf(bench_ms: dict) -> float:
    """Without an explicit PERF_GATE_SF: 0.01 on CPU (preflight smoke),
    but on a TPU backend gate at the largest scale factor the last good
    BENCH round actually measured — baseline ms only seed from BENCH
    when the scale factors match, so gating at a different sf would
    leave the documented seed path dead and self-calibrate against a
    possibly-regressed current measurement."""
    import jax
    if jax.default_backend() != "tpu" or not bench_ms:
        return 0.01
    sfs = [float(m.group(1)) for m in
           (re.match(r"tpc(?:h|ds)_q\d+_sf([\d.]+)_ms$", k)
            for k in bench_ms) if m]
    return max(sfs) if sfs else 0.01


def main(argv) -> int:
    threshold = float(os.environ.get("PERF_GATE_THRESHOLD_PCT", "25"))
    floor_ms = float(os.environ.get("PERF_GATE_FLOOR_MS", "200"))
    queries = [q.strip() for q in os.environ.get(
        "PERF_GATE_QUERIES", "q1,q3").split(",") if q.strip()]
    tpcds_queries = [q.strip() for q in os.environ.get(
        "PERF_GATE_TPCDS_QUERIES", "q3,q19").split(",") if q.strip()]
    update = "--update" in argv

    bench_name, bench_ms = last_good_bench()
    sf_env = os.environ.get("PERF_GATE_SF")
    sf = float(sf_env) if sf_env else _default_sf(bench_ms)
    current = measure(sf, queries, tpcds_queries)
    if os.environ.get("PERF_GATE_COMPILE"):
        # opt-in (two fresh subprocesses, ~1min): the compile-cache
        # cold/warm-process rows join the gated set — a warm-compile
        # regression (deserialization suddenly recompiling) fails
        # preflight like a wall-clock regression would
        import bench
        cc = bench.bench_compile_cache(None)
        current.update({k: float(v) for k, v in cc.items()
                        if re.match(FAMILIES[2][1], k)})
    key = platform_key(sf)

    baselines = {}
    if os.path.exists(BASELINE_PATH):
        try:
            baselines = json.load(open(BASELINE_PATH))
        except ValueError:
            baselines = {}
    entry = baselines.get(key)

    if entry is None or update:
        # calibrate: prefer the last good BENCH round when its numbers
        # are same-platform/same-scale (the TPU driver path), else the
        # current measurement (the CPU preflight path)
        seeded = {}
        if key.startswith("tpu"):  # key is platform_key(sf), computed once
            for fam, names in (("tpch", queries),
                               ("tpcds", tpcds_queries)):
                for name in names:
                    bkey = f"{fam}_{name}_sf{sf:g}_ms"
                    if bkey in bench_ms:
                        seeded[f"{fam}_{name}_ms"] = bench_ms[bkey]
            # compile-cache rows are sf-less (bench emits them from a
            # fixed-size subprocess pair): seed the ones we measure
            for k, v in bench_ms.items():
                if re.match(FAMILIES[2][1], k) and k in current:
                    seeded[k] = v
        source = bench_name if seeded else "self"
        # per-family merge: bench-seeded keys win, the current
        # measurement fills every family the bench round didn't carry
        # (a partial seed must not leave the other family ungated)
        entry = dict(current, **seeded)
        entry.update(calibrated_against=source,
                     calibrated_ts=round(time.time(), 1))
        baselines[key] = entry
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps({"perf_gate": "calibrated", "platform": key,
                          "source": source, "current": current}))
        return 0

    # metrics measured for the first time on an existing baseline (the
    # tpcds family landing on a platform calibrated pre-tranche):
    # self-calibrate JUST the missing keys so the next run gates them
    missing = {k: v for k, v in current.items() if k not in entry}
    if missing:
        entry.update(missing)
        baselines[key] = entry
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps({"perf_gate": "extended", "platform": key,
                          "new_metrics": missing}))

    failures = []
    for metric, now in sorted(current.items()):
        base = entry.get(metric)
        if base is None:
            continue
        if now > base * (1 + threshold / 100) and now > base + floor_ms:
            failures.append(f"{metric}: {now:.1f}ms vs baseline "
                            f"{base:.1f}ms (>{threshold:g}% + "
                            f"{floor_ms:g}ms floor)")
    verdict = {"perf_gate": "fail" if failures else "ok",
               "platform": key, "current": current,
               "baseline": {k: v for k, v in entry.items()
                            if k.startswith(("tpch_", "tpcds_"))},
               "last_good_bench": bench_name}
    if failures:
        verdict["regressions"] = failures
    print(json.dumps(verdict))
    if failures:
        print("perf gate FAILED (recalibrate with scripts/perf_gate.py "
              "--update if the regression is intended):",
              file=sys.stderr)
        for f_ in failures:
            print("  " + f_, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

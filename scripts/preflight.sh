#!/usr/bin/env bash
# Preflight gate: run before committing/snapshotting so the round-5
# class of "snapshot committed with a broken mesh path" cannot recur.
# Any stage failing exits this script NONZERO (set -e + explicit rc
# checks), enforcing the ROADMAP pre-snapshot gate.
#
# Eighteen stages, all mandatory:
#   1. full tier-1 pytest suite (virtual 8-device CPU mesh via conftest)
#   2. dryrun_multichip(8): jit + run the distributed collectives path
#      end-to-end with single-chip parity checks
#   3. bench smoke + perf-regression gate: the headline aggregate
#      shape at a reduced size (bench entrypoint known-runnable before
#      the driver spends a TPU slot), then scripts/perf_gate.py runs
#      the TPC-H Q1/Q3 smoke and FAILS on >25% tpch_*_ms regression
#      against the recorded platform baseline (PERF_BASELINE.json,
#      seeded from the last good BENCH_*.json on TPU)
#   4. chaos smoke: one injected OOM + one injected transient against
#      TPC-H Q1 with golden parity — the failure-recovery ladder
#      (executor taxonomy + fault injection) must survive end-to-end —
#      plus one mid-stream `stream_chunk` fault against chunked Q1
#      asserting a `chunk_retry` recovery action (partial-progress
#      recovery replays ONE chunk, never restarts the stream) with
#      golden parity
#   5. observability + analysis smoke: TPC-H Q1/Q3 with eventLog +
#      trace + Prometheus sinks on AND the pre-compile static analyzer
#      explicitly enabled (enabled=true, non-strict); golden parity
#      must hold, the event line (spans + XLA cost fields), the Chrome
#      trace JSON and the metrics exposition file must all exist and
#      parse, and the analyzer must report ZERO findings on the TPC-H
#      plans — observability and analysis must never be the thing that
#      breaks (or noises up) a query. Then the observability-overhead
#      gate (Q1 warm, everything ON vs OFF, must stay ≤10% /
#      `obs_overhead_ms`), and scripts/events_tool.py validates the
#      written event log against the versioned schema
#   6. source lint: every registered pass of the unified lint framework
#      (scripts/lint.py --all — metric prefixes, conf-key
#      registration, fault-site wiring, tracer-leak shapes; absorbs
#      the former metrics-lint stage)
#   7. service smoke: start the SQL service (spark_tpu/service/) on an
#      ephemeral port, POST TPC-H Q1 over HTTP, assert golden parity
#      of the JSON result, that GET /metrics parses as Prometheus
#      text exposition, that the live history API serves the query
#      (GET /queries listing + /queries/<id>/timeline with spans and
#      stage peak-HBM + /queries/<id>/plan), and a clean shutdown
#   8. join-kernel + ingest parity smoke: TPC-H Q3+Q5 byte-identical
#      across join.kernelMode hash vs sort (the hash path PROVEN to
#      have run via join_table_slots_*) and ingest.prefetch on vs off,
#      plus a reduced-size join_microbench section run
#   9. TPC-DS smoke: SF0.01 datagen + two tranche queries (q3 + the
#      6-way q19) at pandas golden parity, and the cost-based join
#      reorder proven live — cbo.joinReorder on/off byte-identical
#      with the reorder decisions actually changing q19's join order
#  10. elastic mesh smoke: a fatal mesh fault injected mid-stream on an
#      8-device virtual mesh must GANG-RESTART (mesh_restart==1, no
#      single-device fallback), resume from the last checkpoint with
#      at most checkpoint.everyChunks chunks replayed, and hit TPC-H
#      Q1 golden parity
#  11. streaming durability smoke: a file-source stateful streaming
#      query crashed at the stream_state_commit seam, the query object
#      discarded, and a FRESH StreamingQuery over the same checkpoint
#      must recover to output byte-identical to an uninterrupted run
#      (incremental state store: delta restore), with the
#      streaming_batches metric and per-batch event records sane
#  12. concurrency smoke: the guarded-by + lock-order passes in --json
#      form must report zero violations (machine-readable gate), and a
#      concurrent service run (2 sessions x 2 queries, prefetch on)
#      under the runtime lockwatch must show an observed lock
#      acquisition order consistent with the static registry ranking,
#      golden parity per query, and no prefetch daemon outliving its
#      query
#  13. compile-cache smoke: cold TPC-H Q1 in-process with the
#      persistent AOT compile cache on, then Q1 in a FRESH subprocess
#      over the same cache dir asserting compile_cache_disk_hits >= 1
#      with ZERO disk misses (no backend recompiles of cached shapes)
#      and byte-identical results, plus a corrupted-entry run proving
#      the compile_cache_corrupt fallback never fails the query
#  14. cancellation smoke: start a chunked TPC-H Q3 via the service,
#      DELETE it mid-stream, and assert the structured QUERY_CANCELLED
#      record, no leaked prefetch daemon (assert_no_thread_leak), the
#      arbiter lease pool drained to idle, and an immediate identical
#      re-run at golden parity — the query-lifecycle hard guarantee
#      (execution/lifecycle.py) end to end over HTTP
#  15. python-UDF worker smoke: the out-of-process Arrow lane
#      (spark_tpu.sql.udf.mode=worker) must match the in-process lane
#      byte-for-byte across scalar + pandas UDFs, an injected
#      udf_batch:fatal SIGKILL mid-batch must replay EXACTLY one
#      batch (rec_chunks_replayed delta 1) at parity, and after pool
#      shutdown ZERO worker children may survive
#  16. unattended-streaming smoke: a socket FrameProducer feeds a
#      stateful network-source query under the supervised trigger loop
#      (start(trigger_ms=50)); the producer connection is killed
#      mid-stream and the consumer must reconnect exactly once
#      (streaming_reconnects delta 1) with zero loss/duplication; an
#      injected trigger_tick:fatal must park the query in structured
#      FAILED status; a FRESH query over the same checkpoint must
#      recover byte-identical to an uninterrupted twin; and after a
#      clean stop ZERO spark-tpu-stream-trigger threads may survive
#  17. status-store + flight-recorder smoke: GET /status on a live
#      service must parse with latency p50/p95/p99 present after one
#      query, /status/timeseries must carry heartbeat-sampled series,
#      and a query failed by an injected `stage_run:fatal` must leave
#      a flight-recorder bundle whose spans + conf snapshot + thread
#      stacks all parse — the crash-time diagnostics must exist
#      exactly when a query dies. (The ≤10% observability-overhead
#      gate in stage 5 already measures with the status store and
#      flight recorder ON: bench.py's obs_conf_on includes both.)
#  18. plan-integrity smoke: the rule-registry lint (RL100, part of
#      stage 6's scripts/lint.py --all) green, a 64-seed differential
#      fuzz campaign (scripts/plan_fuzz.py: optimizer-on vs -off under
#      planChangeValidation=full plus one rule ablation per seed, all
#      byte-identical with stable stage keys), and TPC-H Q3 under
#      validation=full at golden parity with the schema-v7 rule_trace
#      record present in the event log. (The stage-5 overhead gate
#      already measures validation=full in obs_conf_on, so the
#      verifier itself is held to the ≤10% budget.)
#
# Usage: scripts/preflight.sh [--fast]
#   --fast skips the full pytest suite (stages 2-19 still run) for
#   quick inner-loop checks; CI and end-of-round runs must use the
#   default.

set -euo pipefail
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

echo "== preflight: $(date -u +%FT%TZ) =="

if [ "$FAST" -eq 0 ]; then
    echo "-- stage 1/19: tier-1 test suite --"
    rm -f /tmp/_preflight_t1.log
    set +e  # keep control on pytest failure so the diagnostic prints
    # budget sized for the grown suite (773 tests, ~15min on one CPU
    # mesh) — the old 870s cap was tripping on wall clock, not failures
    timeout -k 10 1500 env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee /tmp/_preflight_t1.log
    rc=${PIPESTATUS[0]}
    set -e
    if [ "$rc" -ne 0 ]; then
        echo "preflight FAILED: tier-1 suite rc=$rc" >&2
        exit "$rc"
    fi
else
    echo "-- stage 1/19: SKIPPED (--fast) --"
fi

echo "-- stage 2/19: dryrun_multichip(8) --"
env JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
"

echo "-- stage 3/19: bench smoke --"
# Reduced-size smoke of the bench entrypoint: section harness, JSON
# emission and the aggregate hot path must run end-to-end on CPU.
env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import bench
from spark_tpu import SparkTpuSession
from spark_tpu import functions as F
from spark_tpu.functions import col

spark = SparkTpuSession.builder().get_or_create()


def smoke():
    df = (spark.range(1 << 16)
          .select(F.pmod(col("id"), 256).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))
    pdf = df.to_pandas()
    assert len(pdf) == 256, len(pdf)
    return {"groups": int(len(pdf))}


out = bench._run_section("bench_smoke", smoke, 300)
assert out.get("groups") == 256, out
print(json.dumps({"preflight_bench_smoke": "ok"}))
EOF

# perf-regression gate: TPC-H Q1/Q3 smoke vs the recorded platform
# baseline; >25% tpch_*_ms regression fails the preflight (recalibrate
# deliberate changes with scripts/perf_gate.py --update)
env JAX_PLATFORMS=cpu python scripts/perf_gate.py

echo "-- stage 4/19: chaos smoke --"
# One injected RESOURCE_EXHAUSTED (rung 1: device-cache evict + retry)
# and one injected transient UNAVAILABLE (backoff retry), then Q1 must
# still hit golden parity with both recoveries visible in fault_summary.
env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import tempfile
import warnings

from spark_tpu import SparkTpuSession
from spark_tpu.testing import faults
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
spark.conf.set("spark_tpu.execution.backoffMs", 1)
path = tempfile.mkdtemp(prefix="preflight_tpch_") + "/sf"
write_parquet(path, 0.001)
Q.register_tables(spark, path)

with warnings.catch_warnings():
    warnings.simplefilter("ignore")  # the retry warnings are the point
    with faults.inject(
            spark.conf,
            "stage_run:resource_exhausted:1,stage_run:unavailable:2"):
        qe = Q.QUERIES["q1"](spark)._qe()
        got = G.normalize_decimals(qe.collect().to_pandas())
assert qe.fault_summary.get("oom_cache_evict", 0) >= 1, qe.fault_summary
assert qe.fault_summary.get("transient_retry", 0) >= 1, qe.fault_summary
G.compare(got.reset_index(drop=True), G.GOLDEN["q1"](path))

# mid-stream fault: partial-progress recovery (execution/recovery.py)
# must replay ONE chunk (chunk_retry) — never surface to the
# whole-query loop and restart the stream (no transient_retry)
spark.conf.set("spark_tpu.sql.execution.streamingChunkRows", 1024)
spark.conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)
spark._stage_cache.clear()
with warnings.catch_warnings():
    warnings.simplefilter("ignore")
    with faults.inject(spark.conf, "stream_chunk:unavailable:2") as fp:
        qe2 = Q.QUERIES["q1"](spark)._qe()
        got2 = G.normalize_decimals(qe2.collect().to_pandas())
assert fp.fired_log, "stream_chunk never fired — smoke is vacuous"
assert qe2.fault_summary.get("chunk_retry", 0) == 1, qe2.fault_summary
assert "transient_retry" not in qe2.fault_summary, qe2.fault_summary
G.compare(got2.reset_index(drop=True), G.GOLDEN["q1"](path))
print(json.dumps({"preflight_chaos_smoke": "ok",
                  "fault_summary": {k: v for k, v in
                                    qe.fault_summary.items()},
                  "stream_fault_summary": {k: v for k, v in
                                           qe2.fault_summary.items()}}))
EOF

echo "-- stage 5/19: observability + analysis smoke --"
env JAX_PLATFORMS=cpu python - <<'EOF2'
import json
import os
import tempfile

from spark_tpu import SparkTpuSession
from spark_tpu.observability.metrics import parse_prometheus
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
base = tempfile.mkdtemp(prefix="preflight_obs_")
spark.conf.set("spark_tpu.sql.eventLog.dir", base + "/events")
spark.conf.set("spark_tpu.sql.trace.dir", base + "/traces")
spark.conf.set("spark_tpu.sql.metrics.sink", "jsonl,prometheus")
spark.conf.set("spark_tpu.sql.metrics.dir", base + "/metrics")
# pre-compile static analyzer explicitly on (non-strict): Q1/Q3 golden
# parity must hold end to end and the analyzer must stay at zero
# findings on the TPC-H plans (noise gate)
spark.conf.set("spark_tpu.sql.analysis.enabled", "true")
spark.conf.set("spark_tpu.sql.analysis.strict", "false")

path = base + "/sf"
write_parquet(path, 0.001)
Q.register_tables(spark, path)
qe = Q.QUERIES["q1"](spark)._qe()
got = G.normalize_decimals(qe.collect().to_pandas())
G.compare(got.reset_index(drop=True), G.GOLDEN["q1"](path))
assert qe.analysis_findings == [], qe.analysis_findings

qe3 = Q.QUERIES["q3"](spark)._qe()
got3 = G.normalize_decimals(qe3.collect().to_pandas())
G.compare(got3.reset_index(drop=True), G.GOLDEN["q3"](path))
assert qe3.analysis_findings == [], qe3.analysis_findings

# (a) event line with spans + XLA cost fields
from spark_tpu import history
events = history.read_event_log(base + "/events")
assert len(events) >= 1, events
stages = history.compile_summary(events)
assert len(stages) >= 1 and stages["flops"].notna().any(), stages
assert len(history.stage_summary(events)) >= 3
assert len(history.hbm_summary(events)) >= 1

# (b) Chrome trace parses and has complete events
traces = [f for f in os.listdir(base + "/traces")
          if f.endswith(".trace.json")]
assert traces, os.listdir(base + "/traces")
t = json.load(open(os.path.join(base + "/traces", traces[-1])))
assert t["traceEvents"] and any(e.get("ph") == "X"
                                for e in t["traceEvents"])

# (c) Prometheus exposition scrape-parses
prom = parse_prometheus(base + "/metrics/metrics.prom")
assert prom.get("spark_tpu_queries_total", 0) >= 1, prom

# (d) observability-overhead gate: Q1 warm best-of-5 with every sink
# + xlaCost + shard spans ON vs everything OFF must stay within 10%
# (a tiny absolute floor absorbs scheduler jitter on CI boxes). The
# ON/OFF conf sets and the timed runner are bench.py's — ONE
# definition, so this gate and the BENCH obs_overhead sidecar can
# never measure different things. Measured at SF0.01, not the smoke's
# SF0.001: the per-query fixed cost (event line + trace file + prom
# rewrite, ~3ms) would read as ~30% of a 10ms query — the gate must
# measure the RATIO at a query size where the ratio is meaningful.
import bench

path10 = base + "/sf10x"
write_parquet(path10, 0.01)
Q.register_tables(spark, path10)
obs = bench.measure_obs_overhead(
    spark, lambda: Q.QUERIES["q1"](spark)._qe().collect(),
    base + "/ovh", best_of=5)
assert obs["obs_overhead_pct"] <= 10.0 \
    or obs["obs_overhead_ms"] <= 25.0, (
    f"observability overhead exceeds the 10% gate: {obs}")

with open("/tmp/_preflight_obs_dir", "w") as f:
    f.write(base + "/events")
print(json.dumps({"preflight_observability_smoke": "ok",
                  "stages": int(len(stages)),
                  "trace_events": len(t["traceEvents"]),
                  "obs_overhead_ms": obs["obs_overhead_ms"],
                  "obs_overhead_pct": obs["obs_overhead_pct"]}))
EOF2

# event-log schema validation (scripts/events_tool.py): every line the
# smoke above wrote must parse against the versioned schema
env JAX_PLATFORMS=cpu python scripts/events_tool.py validate \
    "$(cat /tmp/_preflight_obs_dir)"

echo "-- stage 6/19: source lint (scripts/lint.py --all) --"
env JAX_PLATFORMS=cpu python scripts/lint.py --all

echo "-- stage 7/19: SQL service smoke --"
# Start the concurrent SQL service on an ephemeral port, POST TPC-H Q1
# over HTTP, check golden parity of the JSON rows, scrape-parse
# GET /metrics, then shut down cleanly.
env JAX_PLATFORMS=cpu python - <<'EOF3'
import json
import tempfile
import urllib.request

import pandas as pd

from spark_tpu import Conf
from spark_tpu.observability.metrics import parse_prometheus_text
from spark_tpu.service.server import SqlService
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

path = tempfile.mkdtemp(prefix="preflight_service_") + "/sf"
write_parquet(path, 0.001)

conf = Conf()
conf.set("spark_tpu.service.port", 0)
# stage-cost capture on, so /queries/<id>/timeline can serve peak-HBM
conf.set("spark_tpu.sql.observability.xlaCost", "on")
svc = SqlService(conf,
                 init_session=lambda s: Q.register_tables(s, path)).start()
try:
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/sql",
        data=json.dumps({"sql": SQLQ.Q1}).encode(),
        headers={"Content-Type": "application/json"})
    resp = json.load(urllib.request.urlopen(req, timeout=300))
    assert resp["status"] == "ok", resp
    got = pd.DataFrame(resp["rows"], columns=resp["columns"])
    want = G.GOLDEN["q1"](path)
    G.compare(G.normalize_decimals(got)[list(want.columns)]
              .reset_index(drop=True), want.reset_index(drop=True))
    # structured status record fed by the listener bus
    rec = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}/queries/{resp['query_id']}",
        timeout=30))
    assert rec["status"] == "ok" and rec["engine_query_id"] >= 1, rec
    # live Prometheus exposition parses
    prom = parse_prometheus_text(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}/metrics", timeout=30)
        .read().decode())
    assert prom.get("spark_tpu_service_completed", 0) >= 1, prom
    assert prom.get("spark_tpu_queries_total", 0) >= 1, prom
    # live query history API: listing + timeline + plan (the flight
    # recorder over HTTP — no JSONL scraping)
    listing = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}/queries", timeout=30))
    assert listing["total"] >= 1 and any(
        q["id"] == resp["query_id"] and q["status"] == "ok"
        for q in listing["queries"]), listing
    tl = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}/queries/{resp['query_id']}"
        f"/timeline", timeout=30))
    assert tl["spans"] and any(
        s.get("name") == "dispatch" for s in tl["spans"]), tl["spans"]
    assert any(s.get("peak_hbm_bytes") for s in tl["stages"]), tl
    assert isinstance(tl["shards"], list), tl  # [] single-chip, never absent
    pl = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}/queries/{resp['query_id']}/plan",
        timeout=30))
    assert pl["physical"] and pl["sql"], pl
finally:
    svc.stop()
print(json.dumps({"preflight_service_smoke": "ok",
                  "rows": int(resp["row_count"])}))
EOF3

echo "-- stage 8/19: join-kernel + ingest parity smoke --"
# Q3+Q5 byte-identical across join.kernelMode hash/sort and
# ingest.prefetch on/off; the hash path must actually have run (a
# join_table_slots_* metric) so the parity check can't go vacuous.
env JAX_PLATFORMS=cpu BENCH_JOIN_PROBE_ROWS=262144 python - <<'EOF4'
import json
import tempfile

import pandas as pd

import bench
from spark_tpu import SparkTpuSession
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
path = tempfile.mkdtemp(prefix="preflight_hj_") + "/sf"
write_parquet(path, 0.002)
Q.register_tables(spark, path)
spark.conf.set("spark_tpu.sql.execution.streamingChunkRows", 4096)

MODE = "spark_tpu.sql.join.kernelMode"
PREFETCH = "spark_tpu.sql.ingest.prefetch"
hash_proven = 0
for qname in ("q3", "q5"):
    outs = {}
    for mode, prefetch in (("sort", True), ("hash", True),
                           ("sort", False), ("hash", False)):
        spark.conf.set(MODE, mode)
        spark.conf.set(PREFETCH, prefetch)
        qe = Q.QUERIES[qname](spark)._qe()
        outs[(mode, prefetch)] = qe.collect().to_pandas()
        if mode == "hash":
            hash_proven += any(k.startswith("join_table_slots_")
                               for k in qe.last_metrics)
    base = outs[("sort", True)]
    # normalize a COPY: normalize_decimals casts in place, and `base`
    # must stay byte-identical for the cross-config comparisons below
    got_n = G.normalize_decimals(base.copy()).reset_index(drop=True)
    want = G.GOLDEN[qname](path)
    if qname == "q5":  # revenue ties: compare in n_name order
        got_n = got_n.sort_values("n_name").reset_index(drop=True)
        want = want.sort_values("n_name").reset_index(drop=True)
    G.compare(got_n, want)
    for key, got in outs.items():
        try:
            pd.testing.assert_frame_equal(base, got)
        except AssertionError as e:
            raise AssertionError(
                f"{qname} diverged at (kernelMode, prefetch)={key}") from e
assert hash_proven == 4, f"hash kernel ran {hash_proven}/4 configs"
mb = bench.bench_join_microbench(spark)
assert any(k.endswith("_hash_rows_per_sec_M") for k in mb), mb
print(json.dumps({"preflight_join_kernel_smoke": "ok",
                  "microbench": mb}))
EOF4

echo "-- stage 9/19: TPC-DS + join-reorder smoke --"
# SF0.01 datagen, q3 + q19 golden parity, and the cost-based join
# reorder proven live: on/off byte-identical with q19's join order
# demonstrably changed (decision log + differing physical plans).
env JAX_PLATFORMS=cpu python - <<'EOF5'
import json
import tempfile

import pandas as pd

from spark_tpu import SparkTpuSession
from spark_tpu.tpcds import SQL_QUERIES, register_tables
from spark_tpu.tpcds import golden as G
from spark_tpu.tpcds.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
path = tempfile.mkdtemp(prefix="preflight_tpcds_") + "/sf"
write_parquet(path, 0.01)
register_tables(spark, path)

CBO = "spark_tpu.sql.cbo.joinReorder"
reordered = 0
for qname in ("q3", "q19"):
    spark.conf.set(CBO, True)
    qe_on = spark.sql(SQL_QUERIES[qname])._qe()
    on = qe_on.collect().to_pandas()
    spark.conf.set(CBO, False)
    qe_off = spark.sql(SQL_QUERIES[qname])._qe()
    off = qe_off.collect().to_pandas()
    spark.conf.set(CBO, True)
    pd.testing.assert_frame_equal(on, off)
    if any(d.get("kind") == "order"
           for d in (qe_on.reorder_decisions or [])):
        reordered += 1
        assert qe_on.executed_plan.describe() != \
            qe_off.executed_plan.describe(), qname
    want = G.GOLDEN[qname](path)
    got = G.normalize_decimals(on.copy())[list(want.columns)]
    G.compare(got.reset_index(drop=True), want, float_atol=1e-4)
assert reordered >= 1, "join reorder never changed an order (vacuous)"
print(json.dumps({"preflight_tpcds_smoke": "ok",
                  "reordered_queries": reordered}))
EOF5

echo "-- stage 10/19: elastic mesh smoke --"
# A host lost mid-stream (fatal at the 2nd mesh snapshot point) must
# gang-restart the mesh — NOT degrade to single-device — resume from
# the chunk-2 checkpoint with a bounded replay, and hit golden parity.
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" python - <<'EOF6'
import json
import tempfile
import warnings

import jax

jax.config.update("jax_platforms", "cpu")

from spark_tpu import SparkTpuSession
from spark_tpu.testing import faults
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
path = tempfile.mkdtemp(prefix="preflight_elastic_") + "/sf"
write_parquet(path, 0.001)
Q.register_tables(spark, path)
conf = spark.conf
conf.set("spark_tpu.execution.backoffMs", 1)
conf.set("spark_tpu.sql.execution.streamingChunkRows", 1024)
conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)
conf.set("spark_tpu.sql.mesh.size", 8)
conf.set("spark_tpu.execution.checkpoint.everyChunks", 2)

rec0 = spark.metrics.counter("rec_chunks_replayed").value
with warnings.catch_warnings():
    warnings.simplefilter("ignore")  # the restart warnings are the point
    with faults.inject(conf, "mesh_checkpoint:fatal:2") as fp:
        qe = Q.QUERIES["q1"](spark)._qe()
        got = G.normalize_decimals(qe.collect().to_pandas())
assert fp.fired_log, "mesh_checkpoint seam never fired — smoke is vacuous"
assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
assert "mesh_fallback" not in qe.fault_summary, qe.fault_summary
assert qe.fault_summary.get("checkpoint_restore") == 1, qe.fault_summary
replayed = spark.metrics.counter("rec_chunks_replayed").value - rec0
assert replayed <= 2, f"replayed {replayed} chunks > everyChunks=2"
conf.set("spark_tpu.sql.mesh.size", 0)
G.compare(got.reset_index(drop=True), G.GOLDEN["q1"](path))
print(json.dumps({"preflight_elastic_smoke": "ok",
                  "replayed_chunks": int(replayed),
                  "fault_summary": dict(qe.fault_summary)}))
EOF6

echo "-- stage 11/19: streaming durability smoke --"
# File source -> stateful query -> crash at the state-commit seam ->
# query object discarded -> fresh query over the same checkpoint must
# recover exactly-once (output byte-identical to an uninterrupted run)
# with the streaming_* metrics and v4 event records sane.
env JAX_PLATFORMS=cpu python - <<'EOF7'
import json
import os
import tempfile

import numpy as np
import pandas as pd

from spark_tpu import SparkTpuSession, history
from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.testing import faults

spark = SparkTpuSession.builder().get_or_create()
base = tempfile.mkdtemp(prefix="preflight_stream_")
spark.conf.set("spark_tpu.sql.eventLog.dir", base + "/events")
schema = pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                       "v": pd.Series([], dtype=np.int64)})


def setup(tag):
    src_dir = os.path.join(base, f"src_{tag}")
    os.makedirs(src_dir, exist_ok=True)

    def feed(i):
        # batch 0 touches every group (snapshot); later batches touch
        # 16 of 64 (deltas — the incremental-store steady state)
        n = 256 if i == 0 else 16
        pd.DataFrame({"k": np.arange(n, dtype=np.int64),
                      "v": np.full(n, i + 1, dtype=np.int64)}) \
            .to_parquet(os.path.join(src_dir, f"b{i:03d}.parquet"))

    def build():
        src = spark.file_stream(src_dir, schema_df=schema)
        return (src.to_df()
                .group_by(F.pmod(col("k"), 64).alias("g"))
                .agg(F.sum(col("v")).alias("s"), F.count().alias("c"))
                .write_stream(os.path.join(base, f"ck_{tag}")))

    return feed, build


# uninterrupted twin
feed_u, build_u = setup("clean")
qu = build_u()
for i in range(3):
    feed_u(i)
    qu.process_available()
want = qu.latest().sort_values("g").reset_index(drop=True)

# crashed run: batch 0 commits, batch 1 dies AT the state commit
b0 = spark.metrics.counter("streaming_batches").value
feed_c, build_c = setup("crash")
q = build_c()
feed_c(0)
q.process_available()
feed_c(1)
crashed = False
with faults.inject(spark.conf, "stream_state_commit:fatal:1") as fp:
    try:
        q.process_available()
    except faults.FaultInjected:
        crashed = True
assert crashed and fp.fired_log, "stream_state_commit never fired — smoke is vacuous"
del q  # the hard crash: only the checkpoint dir survives
feed_c(2)
q2 = build_c()
q2.process_available()
got = q2.latest().sort_values("g").reset_index(drop=True)
pd.testing.assert_frame_equal(got, want)
batches = spark.metrics.counter("streaming_batches").value - b0
assert batches == 3, batches  # batch 0 + replayed batch 1 + batch 2
events = history.read_event_log(base + "/events")
ss = history.streaming_summary(events)
# 3 clean-run + 3 crash-run committed batches, snapshot at each v0
assert len(ss) == 6 and set(ss["kind"]) == {"snapshot", "delta"}, ss
spark.conf.set("spark_tpu.sql.eventLog.dir", "")
with open("/tmp/_preflight_stream_dir", "w") as f:
    f.write(base + "/events")
print(json.dumps({"preflight_streaming_smoke": "ok",
                  "batches": int(batches),
                  "kinds": ss["kind"].tolist()}))
EOF7

# the streaming event lines validate against the versioned schema
env JAX_PLATFORMS=cpu python scripts/events_tool.py validate \
    "$(cat /tmp/_preflight_stream_dir)"

echo "-- stage 12/19: concurrency smoke --"
# (a) the concurrency passes gate machine-readably at zero violations
env JAX_PLATFORMS=cpu python - <<'EOF8'
import json
import subprocess
import sys

out = subprocess.run(
    [sys.executable, "scripts/lint.py", "--json", "guarded-by",
     "lock-order"], capture_output=True, text=True)
payload = json.loads(out.stdout)
assert out.returncode == 0 and payload["ok"], payload
assert payload["violations"] == [], payload["violations"]
assert any(n.startswith("waiver:") for n in payload["notes"])
print(json.dumps({"preflight_concurrency_lint": "ok",
                  "waivers": sum(n.startswith("waiver:")
                                 for n in payload["notes"])}))
EOF8

# (b) lockwatch smoke: concurrent service queries with prefetch on —
# observed lock order must be consistent with the static registry
# ranking, golden parity per query, no leaked prefetch daemons
env JAX_PLATFORMS=cpu python - <<'EOF9'
import json
import tempfile
import threading

from spark_tpu import Conf
from spark_tpu.service.arbiter import install_arbiter
from spark_tpu.service.server import SqlService
from spark_tpu.testing.lockwatch import LockWatch
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

path = tempfile.mkdtemp(prefix="preflight_lockwatch_") + "/sf"
write_parquet(path, 0.001)
conf = Conf()
conf.set("spark_tpu.service.port", 0)
conf.set("spark_tpu.service.hbmBudget", 1 << 30)
conf.set("spark_tpu.sql.execution.streamingChunkRows", 2048)
conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)
svc = SqlService(conf,
                 init_session=lambda s: Q.register_tables(s, path))
watch = LockWatch()
try:
    for name in ("a", "b"):  # warm the pool, then watch it
        svc.submit(SQLQ.Q1, session=name)
    watch.install_service(svc)
    results, errors = [], []

    def run(name):
        try:
            for _ in range(2):
                results.append(svc.submit(SQLQ.Q1, session=name)[1])
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    ts = [threading.Thread(target=run, args=(n,)) for n in ("a", "b")]
    [t.start() for t in ts]
    [t.join(300) for t in ts]
    # a wedged worker (the deadlock class this stage exists to catch)
    # must FAIL here, not pass vacuously and hang interpreter exit
    assert not any(t.is_alive() for t in ts), "query thread wedged"
    assert not errors, errors
    assert len(results) == 4, f"expected 4 results, got {len(results)}"
    want = G.GOLDEN["q1"](path).reset_index(drop=True)
    for table in results:
        got = G.normalize_decimals(table.to_pandas())[list(want.columns)]
        G.compare(got.reset_index(drop=True), want)
    edges = watch.edges()
    assert edges, "no lock nesting observed — smoke is vacuous"
    watch.assert_order_consistent()
    watch.assert_no_thread_leak()
finally:
    watch.uninstall()
    svc.stop()
    install_arbiter(None)
print(json.dumps({"preflight_lockwatch_smoke": "ok",
                  "observed_edges": len(edges)}))
EOF9

echo "-- stage 13/19: compile-cache smoke --"
# Cold Q1 in-process fills the persistent AOT compile cache; a FRESH
# subprocess over the same dir must open warm (disk_hits >= 1, ZERO
# disk misses = no backend recompiles of cached shapes) with
# byte-identical results; a corrupted entry must fall back to a fresh
# compile (compile_cache_corrupt) and still hit parity.
env JAX_PLATFORMS=cpu python - <<'EOF11'
import json
import os
import subprocess
import sys
import tempfile
import warnings

from spark_tpu import SparkTpuSession
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

base = tempfile.mkdtemp(prefix="preflight_cc_")
path = base + "/sf"
cc_dir = base + "/cache"
write_parquet(path, 0.001)

spark = SparkTpuSession.builder().get_or_create()
spark.conf.set("spark_tpu.sql.compileCache.enabled", True)
spark.conf.set("spark_tpu.sql.compileCache.dir", cc_dir)
Q.register_tables(spark, path)

# (a) cold in-process run: entries + manifest land on disk
qe = Q.QUERIES["q1"](spark)._qe()
cold = G.normalize_decimals(qe.collect().to_pandas())
G.compare(cold.reset_index(drop=True), G.GOLDEN["q1"](path))
entries = [f for f in os.listdir(cc_dir) if f.startswith("cc-")]
assert entries, "cold run stored no compile-cache entries"
cold_csv = cold.to_csv(index=False)

# (b) warm FRESH-PROCESS run: deserialization only, byte parity
CHILD = r'''
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
from spark_tpu import SparkTpuSession
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
path, cc_dir = sys.argv[1], sys.argv[2]
spark = SparkTpuSession.builder().get_or_create()
spark.conf.set("spark_tpu.sql.compileCache.enabled", True)
spark.conf.set("spark_tpu.sql.compileCache.dir", cc_dir)
Q.register_tables(spark, path)
got = G.normalize_decimals(
    Q.QUERIES["q1"](spark)._qe().collect().to_pandas())
m = spark.metrics
print("CCSMOKE " + json.dumps({
    "csv": got.to_csv(index=False),
    "disk_hits": int(m.counter("compile_cache_disk_hits").value),
    "disk_misses": int(m.counter("compile_cache_disk_misses").value),
    "corrupt": int(m.counter("compile_cache_corrupt").value),
}), flush=True)
'''


def run_child():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", CHILD, path, cc_dir],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    for line in proc.stdout.splitlines():
        if line.startswith("CCSMOKE "):
            return json.loads(line[len("CCSMOKE "):])
    raise AssertionError(
        f"child rc={proc.returncode}: {proc.stderr[-800:]}")


warm = run_child()
assert warm["disk_hits"] >= 1, warm
assert warm["disk_misses"] == 0, \
    f"warm process recompiled a cached shape: {warm}"
assert warm["csv"] == cold_csv, "warm-process result diverged"

# (c) corrupted entry: fresh subprocess must log+count+recompile,
# never fail, and still hit byte parity
victim = os.path.join(cc_dir, sorted(
    f for f in os.listdir(cc_dir) if f.startswith("cc-"))[0])
with open(victim, "wb") as f:
    f.write(b"torn")
fixed = run_child()
assert fixed["corrupt"] >= 1, fixed
assert fixed["csv"] == cold_csv, "corrupt-fallback result diverged"
assert os.path.getsize(victim) > 4, "bad entry was not overwritten"

print(json.dumps({"preflight_compile_cache_smoke": "ok",
                  "entries": len(entries),
                  "warm_disk_hits": warm["disk_hits"],
                  "corrupt_recovered": fixed["corrupt"]}))
EOF11

echo "-- stage 14/19: query-lifecycle cancellation smoke --"
# Start a chunked Q3 via the service, DELETE it mid-stream, assert the
# structured error + no thread leak + arbiter drained + an immediate
# clean re-run at golden parity (the cancellation hard guarantee).
env JAX_PLATFORMS=cpu python - <<'EOF12'
import json
import tempfile
import time
import urllib.error
import urllib.request

import pandas as pd

from spark_tpu import Conf
from spark_tpu.service.server import SqlService
from spark_tpu.testing.lockwatch import LockWatch
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

path = tempfile.mkdtemp(prefix="preflight_lifecycle_") + "/sf"
write_parquet(path, 0.002)

conf = Conf()
conf.set("spark_tpu.service.port", 0)
conf.set("spark_tpu.service.hbmBudget", 1 << 30)
svc = SqlService(conf,
                 init_session=lambda s: Q.register_tables(s, path)).start()


def post(body, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/sql",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


try:
    # async chunked Q3 held mid-stream by an interruptible slow fault
    # (a >=10s uncancelled floor), then DELETE while running
    status, body = post({
        "sql": SQLQ.Q3, "mode": "async",
        "conf": {"spark_tpu.sql.execution.streamingChunkRows": 512,
                 "spark_tpu.sql.memory.deviceBudget": 1,
                 "spark_tpu.faults.inject": "stream_chunk:slow:2:10000"}})
    assert status == 202, (status, body)
    rid = body["query_id"]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        rec = svc.query_snapshot(rid)
        if rec.get("status") == "running":
            break
        time.sleep(0.01)
    time.sleep(0.3)  # into the chunk loop / slow sleep
    t0 = time.perf_counter()
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/queries/{rid}", method="DELETE")
    resp = json.load(urllib.request.urlopen(req, timeout=30))
    assert resp["status"] == "cancel_requested", resp
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        rec = svc.query_snapshot(rid)
        if rec.get("status") not in ("submitted", "running"):
            break
        time.sleep(0.02)
    latency_s = time.perf_counter() - t0
    assert rec["status"] == "cancelled", rec
    assert rec["error"]["error"] == "QUERY_CANCELLED", rec
    assert latency_s < 5.0, f"cancel took {latency_s:.2f}s"
    # hard guarantee: no daemon outlives the query, leases drained
    LockWatch().assert_no_thread_leak(timeout_s=10.0)
    arb = svc.arbiter.stats()
    assert arb["leased_bytes"] == 0 and arb["owners"] == 0, arb
    # immediate identical re-run (chaos disarmed): golden parity
    status, again = post({
        "sql": SQLQ.Q3,
        "conf": {"spark_tpu.faults.inject": "",
                 "spark_tpu.sql.memory.deviceBudget": 0}})
    assert status == 200, (status, again)
    got = pd.DataFrame(again["rows"], columns=again["columns"])
    want = G.GOLDEN["q3"](path)
    G.compare(G.normalize_decimals(got)[list(want.columns)]
              .reset_index(drop=True), want.reset_index(drop=True))
    assert svc.metrics.counter("query_cancelled").value >= 1
finally:
    svc.stop()
print(json.dumps({"preflight_cancellation_smoke": "ok",
                  "cancel_latency_s": round(latency_s, 3)}))
EOF12

echo "-- stage 15/19: python-UDF worker pool smoke --"
# Worker-lane parity with in-process, an injected SIGKILL mid-batch
# replaying exactly one batch, and the zero-leaked-children contract.
env JAX_PLATFORMS=cpu python - <<'EOF13'
import json

import numpy as np
import pandas as pd

from spark_tpu import SparkTpuSession
from spark_tpu.functions import col, pandas_udf, udf
from spark_tpu.testing import faults

s = SparkTpuSession.builder().get_or_create()
s.conf.set("spark_tpu.sql.udf.arrow.maxRecordsPerBatch", 64)
pdf = pd.DataFrame({
    "x": np.where(np.arange(256) % 7 == 0, np.nan,
                  np.arange(256, dtype="float64")),
    "s": [None if i % 5 == 0 else f"v{i}" for i in range(256)]})
s.register_table("udf_pf", pdf)

plus = udf(lambda v: None if v is None else v + 1.5, "double")
shout = udf(lambda v: None if v is None else v.upper(), "string")


@pandas_udf(returnType="double")
def scaled(v: pd.Series) -> pd.Series:
    return v * 3.0


def run():
    return s.table("udf_pf").select(
        plus(col("x")).alias("a"), shout(col("s")).alias("b"),
        scaled(col("x")).alias("c")).to_pandas()


s.conf.set("spark_tpu.sql.udf.mode", "inprocess")
want = run()
s.conf.set("spark_tpu.sql.udf.mode", "worker")
got = run()
pd.testing.assert_frame_equal(got, want)

# SIGKILL mid-batch: exactly ONE batch replays, results identical
replayed0 = s.metrics.counter("rec_chunks_replayed").value
restarts0 = s.metrics.counter("udf_worker_restarts").value
with faults.inject(s.conf, "udf_batch:fatal:2") as plan:
    chaos = run()
    assert plan.fired_log == [("udf_batch", 2, "fatal")], plan.fired_log
pd.testing.assert_frame_equal(chaos, want)
replayed = s.metrics.counter("rec_chunks_replayed").value - replayed0
assert replayed == 1, f"expected exactly 1 replayed batch, got {replayed}"
assert s.metrics.counter("udf_worker_restarts").value - restarts0 == 1

# zero leaked children after shutdown
s._udf_pool.shutdown()
leaked = [p.pid for p in s._udf_pool.child_procs() if p.poll() is None]
assert not leaked, f"leaked udf workers: {leaked}"
print(json.dumps({
    "preflight_udf_worker_smoke": "ok",
    "udf_batches": int(s.metrics.counter("udf_batches").value),
    "udf_rows": int(s.metrics.counter("udf_rows").value),
    "replayed_batches": int(replayed),
    "workers_spawned": len(s._udf_pool.child_procs())}))
EOF13

echo "-- stage 16/19: unattended streaming smoke --"
# Socket producer under the supervised trigger loop: a mid-stream
# connection kill must reconnect exactly once with zero loss, an
# injected trigger_tick fatal must park the query in structured FAILED,
# a fresh query over the same checkpoint must land byte-identical to an
# uninterrupted twin, and no trigger thread may outlive its query.
env JAX_PLATFORMS=cpu python - <<'EOF14'
import json
import os
import tempfile
import time

import numpy as np
import pandas as pd

from spark_tpu import SparkTpuSession
from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.io.network_source import FrameProducer
from spark_tpu.testing import faults
from spark_tpu.testing.lockwatch import LockWatch

spark = SparkTpuSession.builder().get_or_create()
base = tempfile.mkdtemp(prefix="preflight_unattended_")

SCHEMA = pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                       "v": pd.Series([], dtype=np.int64)})


def round_df(i):
    return pd.DataFrame({"k": np.arange(6, dtype=np.int64) + i,
                         "v": np.arange(6, dtype=np.int64) * (i + 1)})


def build(producer, ck):
    src = spark.network_stream("127.0.0.1", producer.port, SCHEMA)
    plan = (src.to_df()
            .group_by(F.pmod(col("k"), 5).alias("g"))
            .agg(F.sum(col("v")).alias("s"), F.count().alias("c")))
    return plan.write_stream(ck, output_mode="complete")


def wait_commit(q, want, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while q._committed_batch < want and time.monotonic() < deadline:
        time.sleep(0.01)
    assert q._committed_batch >= want, (q._committed_batch, q.state())


prod = FrameProducer()
prod.start()
ck = os.path.join(base, "ck")

rc0 = spark.metrics.counter("streaming_reconnects").value
q = build(prod, ck)
q.start(trigger_ms=50)
prod.send(round_df(0))
wait_commit(q, 0)
committed0 = q._committed_batch

# mid-stream socket kill: the reconnect ladder re-establishes via the
# durable-offset handshake; the next round commits with zero loss
prod.kill_connection()
prod.send(round_df(1))
wait_commit(q, committed0 + 1)
q.stop()
assert q.status == "STOPPED", q.state()
rec = spark.metrics.counter("streaming_reconnects").value - rc0
assert rec == 1, f"expected exactly 1 reconnect, got {rec}"

# injected fatal at the trigger seam parks the loop in FAILED
with faults.inject(spark.conf, "trigger_tick:fatal:1") as plan:
    q.start(trigger_ms=50)
    deadline = time.monotonic() + 30.0
    while q.status == "RUNNING" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert plan.fired_log, "trigger_tick fault never fired"
assert q.status == "FAILED", q.state()
assert "FaultInjected" in (q.exception() or ""), q.exception()

# hard crash (query object GONE), then a FRESH query over the same
# checkpoint must recover byte-identical to an uninterrupted twin
q.stream.close()
del q
prod.send(round_df(2))
q2 = build(prod, ck)
q2.process_available()
got = q2.latest().sort_values("g").reset_index(drop=True)

twin = FrameProducer()
twin.start()
q3 = build(twin, os.path.join(base, "ck_twin"))
for i in range(3):
    twin.send(round_df(i))
q3.process_available()
want = q3.latest().sort_values("g").reset_index(drop=True)
pd.testing.assert_frame_equal(got, want)

q2.stream.close()
q3.stream.close()
LockWatch().assert_no_thread_leak("spark-tpu-stream-trigger")
prod.close()
twin.close()
print(json.dumps({
    "preflight_unattended_streaming_smoke": "ok",
    "reconnects": int(rec),
    "committed_batches": int(q2._committed_batch + 1),
    "groups": int(len(got))}))
EOF14

echo "-- stage 17/19: status store + flight recorder smoke --"
# Live /status must parse with latency percentiles after one query,
# /status/timeseries must carry heartbeat-sampled series, and an
# injected stage_run fatal must leave a flight-recorder bundle whose
# spans + conf + thread stacks parse.
env JAX_PLATFORMS=cpu python - <<'EOF15'
import glob
import json
import os
import tempfile
import time
import urllib.error
import urllib.request

from spark_tpu import Conf
from spark_tpu.service.server import SqlService
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

base = tempfile.mkdtemp(prefix="preflight_status_")
path = base + "/sf"
fr_dir = base + "/flightrec"
write_parquet(path, 0.001)

conf = Conf()
conf.set("spark_tpu.service.port", 0)
conf.set("spark_tpu.sql.status.heartbeatMs", 50)
conf.set("spark_tpu.sql.flightRecorder.dir", fr_dir)
svc = SqlService(conf,
                 init_session=lambda s: Q.register_tables(s, path)).start()


def post(body, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/sql",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(route):
    return json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{svc.port}{route}", timeout=30))


try:
    status, body = post({"sql": SQLQ.Q1})
    assert status == 200 and body["status"] == "ok", (status, body)

    # (a) /status parses; latency percentiles present after the query
    st = get("/status")
    assert st["enabled"] is True, st
    e2e = st["latency"]["e2e_ms"]
    assert e2e["count"] >= 1, e2e
    for pk in ("p50", "p95", "p99"):
        assert isinstance(e2e[pk], (int, float)), (pk, e2e)
    assert st["statuses"].get("ok", 0) >= 1, st["statuses"]
    assert "admission" in st["providers"], st["providers"]
    assert "arbiter" in st["providers"], st["providers"]

    # (b) heartbeat-sampled time series accumulate in bounded rings
    deadline = time.monotonic() + 15
    ts = get("/status/timeseries")
    while time.monotonic() < deadline and ts["heartbeats"] < 3:
        time.sleep(0.05)
        ts = get("/status/timeseries")
    assert ts["heartbeats"] >= 3, ts["heartbeats"]
    assert ts["series"], "no time series sampled"
    for pts in ts["series"].values():
        assert len(pts) <= ts["ring_capacity"], (len(pts), ts)

    # (c) injected fatal fails the query AND leaves a parseable bundle
    status, body = post({
        "sql": SQLQ.Q1,
        "conf": {"spark_tpu.faults.inject": "stage_run:fatal:1"}})
    assert status != 200 and body.get("error"), (status, body)
    bundles = glob.glob(os.path.join(fr_dir, "bundle-*"))
    assert len(bundles) == 1, bundles
    b = bundles[0]
    manifest = json.load(open(os.path.join(b, "MANIFEST.json")))
    assert manifest["reason"] == "fatal", manifest
    assert "FaultInjected" in manifest["error"], manifest
    spans = json.load(open(os.path.join(b, "spans.json")))
    assert any(spans["spans"].values()), spans
    conf_snap = json.load(open(os.path.join(b, "conf.json")))
    assert "spark_tpu.faults.inject" in conf_snap["explicitly_set"], \
        conf_snap["explicitly_set"]
    threads = open(os.path.join(b, "threads.txt")).read()
    assert "MainThread" in threads or "Thread-" in threads, threads[:200]
    rings = [json.loads(line)
             for line in open(os.path.join(b, "rings.jsonl"))]
    assert {"query", "stage"} <= {r["subsystem"] for r in rings}, rings

    # the failed query is visible in /status too
    st2 = get("/status")
    assert st2["statuses"].get("error", 0) >= 1, st2["statuses"]
finally:
    svc.stop()
print(json.dumps({"preflight_status_smoke": "ok",
                  "heartbeats": int(ts["heartbeats"]),
                  "series": len(ts["series"]),
                  "bundle": os.path.basename(b)}))
EOF15

echo "-- stage 18/19: plan-integrity smoke --"
# (a) 64-seed differential fuzz: optimizer-on vs -off (full validation)
# plus one rule ablation per seed — byte parity, zero integrity
# findings, stable stage keys (the RL100 rule-registry lint already
# gated green inside stage 6's scripts/lint.py --all)
env JAX_PLATFORMS=cpu python scripts/plan_fuzz.py --seeds 64 --ablate one

# (b) TPC-H Q3 under planChangeValidation=full: golden parity must
# hold with the verifier on, and the executed query's event-log line
# must carry the schema-v7 rule_trace record with >=1 effective rule
env JAX_PLATFORMS=cpu python - <<'EOF16'
import json
import tempfile

from spark_tpu import SparkTpuSession, history
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

spark = SparkTpuSession.builder().get_or_create()
base = tempfile.mkdtemp(prefix="preflight_plan_integrity_")
spark.conf.set("spark_tpu.sql.eventLog.dir", base + "/events")
spark.conf.set("spark_tpu.sql.planChangeValidation", "full")
path = base + "/sf"
write_parquet(path, 0.001)
Q.register_tables(spark, path)
qe = Q.QUERIES["q3"](spark)._qe()
got = G.normalize_decimals(qe.collect().to_pandas())
G.compare(got.reset_index(drop=True), G.GOLDEN["q3"](path))
assert qe.rule_trace, "no rule_trace recorded under validation=full"
effective = sum(r["effective"] for r in qe.rule_trace)
assert effective >= 1, qe.rule_trace
spark.conf.set("spark_tpu.sql.eventLog.dir", "")
events = history.read_event_log(base + "/events")
traces = [t for t in events.get("rule_trace", []) if isinstance(t, list)]
assert traces and traces[-1], "event log carries no rule_trace record"
rr = history.rule_report(events)
assert len(rr) >= 1 and (rr["effective"] >= 1).any(), rr
with open("/tmp/_preflight_pi_dir", "w") as f:
    f.write(base + "/events")
print(json.dumps({"preflight_plan_integrity_smoke": "ok",
                  "effective_rules": int(effective),
                  "trace_records": len(qe.rule_trace)}))
EOF16

# the v7 rule_trace lines validate against the versioned schema
env JAX_PLATFORMS=cpu python scripts/events_tool.py validate \
    "$(cat /tmp/_preflight_pi_dir)"

echo "-- stage 19/19: serving-fleet smoke --"
# Crash-only fleet loop end-to-end: 2 supervised worker subprocesses
# behind the session-affinity router, Q1 golden parity through the
# router AND direct at the owning worker (same bytes), kill -9 the
# home worker mid-query (a slow-stage fault holds it on device) and
# require the idempotent-read failover answer — 200 with
# X-Fleet-Failover and parity, or the structured 503 WORKER_LOST —
# then the fleet back at 2 ready with the respawned worker serving
# Q1 from the SHARED persistent compile cache (disk hit, no
# recompile), and a SIGTERM-path drain that exits clean with zero
# orphaned worker processes. warmStart stays off here so the
# respawn's cache heat is visible on the disk-hit counter (the
# warm-start replay path is stage 13's surface).
env JAX_PLATFORMS=cpu python - <<'EOF17'
import json
import os
import signal
import tempfile
import time
import urllib.error
import urllib.request

import pandas as pd

from spark_tpu import Conf
from spark_tpu.observability.metrics import parse_prometheus_text
from spark_tpu.service.fleet import FleetSupervisor
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

base = tempfile.mkdtemp(prefix="preflight_fleet_")
path = base + "/sf"
write_parquet(path, 0.001)
os.makedirs(base + "/init")
with open(base + "/init/preflight_fleet_init.py", "w") as f:
    f.write("import spark_tpu.tpch.queries as Q\n"
            f"PATH = {path!r}\n"
            "def init(session):\n"
            "    Q.register_tables(session, PATH)\n")
os.environ["PYTHONPATH"] = base + "/init" + (
    os.pathsep + os.environ["PYTHONPATH"]
    if os.environ.get("PYTHONPATH") else "")

conf = (Conf()
        .set("spark_tpu.service.port", 0)
        .set("spark_tpu.service.fleet.workers", 2)
        .set("spark_tpu.service.fleet.healthIntervalMs", 100)
        .set("spark_tpu.service.fleet.restartBackoffMs", 100)
        .set("spark_tpu.service.fleet.init",
             "preflight_fleet_init:init")
        .set("spark_tpu.service.fleet.dir", base + "/fleet")
        .set("spark_tpu.sql.warehouse.dir", base + "/wh")
        .set("spark_tpu.sql.compileCache.enabled", True)
        .set("spark_tpu.sql.compileCache.dir", base + "/cc")
        .set("spark_tpu.sql.compileCache.warmStart", False))
sup = FleetSupervisor(conf).start()
assert sup.wait_ready(300), sup.fleet_health()


def post(port, sql, session, extra=None, timeout=300):
    body = {"sql": sql, "session": session}
    body.update(extra or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def check_parity(resp):
    got = pd.DataFrame(resp["rows"], columns=resp["columns"])
    want = G.GOLDEN["q1"](path)
    G.compare(G.normalize_decimals(got)[list(want.columns)]
              .reset_index(drop=True), want.reset_index(drop=True))


worker_pids = sup.worker_pids()
home = sup._route("pf")[0]
home_snap = sup._workers[home].snapshot()

# routed vs direct: same golden bytes through both doors
st, hdrs, resp = post(sup.port, SQLQ.Q1, "pf")
assert st == 200 and resp["status"] == "ok", resp
assert int(hdrs["X-Fleet-Worker"]) == home, hdrs
check_parity(resp)
st, _, direct = post(home_snap["port"], SQLQ.Q1, "pf")
assert st == 200, direct
assert direct["rows"] == resp["rows"], "router vs direct divergence"

# kill -9 the home worker mid-query: the sync read either fails over
# (200 + X-Fleet-Failover + parity) or sheds the structured 503
import threading
out = []
t = threading.Thread(target=lambda: out.append(post(
    sup.port, SQLQ.Q1, "pf",
    {"conf": {"spark_tpu.faults.inject": "stage_run:slow:1:2500"}})),
    daemon=True)
t.start()
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    listing = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{sup.port}/queries", timeout=30).read())
    if any(q.get("status") == "running"
           for q in listing.get("queries", [])):
        break
    time.sleep(0.05)
os.kill(home_snap["pid"], signal.SIGKILL)
t.join(300)
st, hdrs, resp = out[0]
if st == 200:
    assert hdrs.get("X-Fleet-Failover") == "1", hdrs
    check_parity(resp)
    failover = "parity"
else:
    assert st == 503 and resp["error"] in (
        "WORKER_LOST", "FLEET_UNAVAILABLE"), resp
    failover = resp["error"]

# crash-only recovery: back at 2 ready, and the RESPAWNED worker
# serves Q1 hot from the shared persistent cache (disk hit)
assert sup.wait_ready(300), sup.fleet_health()
respawn = sup._workers[home].snapshot()
assert respawn["generation"] >= 2, respawn
st, _, resp = post(respawn["port"], SQLQ.Q1, "pf2")
assert st == 200, resp
check_parity(resp)
prom = parse_prometheus_text(urllib.request.urlopen(
    f"http://127.0.0.1:{respawn['port']}/metrics",
    timeout=30).read().decode())
assert prom.get("spark_tpu_compile_cache_disk_hits", 0) >= 1, \
    "respawned worker recompiled instead of loading the shared cache"

# SIGTERM-path drain: clean exit, zero orphans
assert sup.shutdown(), "fleet drain was not clean"
worker_pids += [respawn["pid"]]
for pid in worker_pids:
    for _ in range(200):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"orphaned worker pid {pid}")
print(json.dumps({"preflight_fleet_smoke": "ok",
                  "failover": failover,
                  "respawned_generation": respawn["generation"],
                  "disk_hits": int(
                      prom["spark_tpu_compile_cache_disk_hits"])}))
EOF17

echo "== preflight PASSED =="

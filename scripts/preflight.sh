#!/usr/bin/env bash
# Preflight gate: run before committing/snapshotting so the round-5
# class of "snapshot committed with a broken mesh path" cannot recur.
#
# Three stages, all mandatory:
#   1. full tier-1 pytest suite (virtual 8-device CPU mesh via conftest)
#   2. dryrun_multichip(8): jit + run the distributed collectives path
#      end-to-end with single-chip parity checks
#   3. bench smoke: the headline aggregate shape at a reduced size, so
#      the bench entrypoint itself (imports, section harness, JSON
#      emission) is known-runnable before the driver spends a TPU slot
#
# Usage: scripts/preflight.sh [--fast]
#   --fast skips the full pytest suite (stages 2+3 only) for quick
#   inner-loop checks; CI and end-of-round runs must use the default.

set -euo pipefail
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

echo "== preflight: $(date -u +%FT%TZ) =="

if [ "$FAST" -eq 0 ]; then
    echo "-- stage 1/3: tier-1 test suite --"
    rm -f /tmp/_preflight_t1.log
    set +e  # keep control on pytest failure so the diagnostic prints
    timeout -k 10 870 env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee /tmp/_preflight_t1.log
    rc=${PIPESTATUS[0]}
    set -e
    if [ "$rc" -ne 0 ]; then
        echo "preflight FAILED: tier-1 suite rc=$rc" >&2
        exit "$rc"
    fi
else
    echo "-- stage 1/3: SKIPPED (--fast) --"
fi

echo "-- stage 2/3: dryrun_multichip(8) --"
env JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
"

echo "-- stage 3/3: bench smoke --"
# Reduced-size smoke of the bench entrypoint: section harness, JSON
# emission and the aggregate hot path must run end-to-end on CPU.
env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import bench
from spark_tpu import SparkTpuSession
from spark_tpu import functions as F
from spark_tpu.functions import col

spark = SparkTpuSession.builder().get_or_create()


def smoke():
    df = (spark.range(1 << 16)
          .select(F.pmod(col("id"), 256).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))
    pdf = df.to_pandas()
    assert len(pdf) == 256, len(pdf)
    return {"groups": int(len(pdf))}


out = bench._run_section("bench_smoke", smoke, 300)
assert out.get("groups") == 256, out
print(json.dumps({"preflight_bench_smoke": "ok"}))
EOF

echo "== preflight PASSED =="

"""Function-library breadth (round-4 task: expression registry + ~100
functions): per-function parity vs pandas/numpy, SQL registry dispatch,
extended aggregates, and mesh parity. Reference:
mathExpressions.scala / datetimeExpressions.scala /
stringExpressions.scala / regexpExpressions.scala /
FunctionRegistry.scala."""

import datetime as DT

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col


@pytest.fixture(scope="module")
def tdf(session):
    pdf = pd.DataFrame({
        "x": np.array([-3.7, 0.0, 2.5, 9.0]),
        "n": np.array([1, 2, 3, 4], dtype=np.int64),
        "s": ["Hello World", "foo", "Bar42", "  pad  "]})
    return session.create_dataframe(pdf, "fn_t"), pdf


def test_math_functions(tdf):
    df, pdf = tdf
    out = df.select(
        F.abs(col("x")).alias("a"), F.sqrt(col("x")).alias("sq"),
        F.round(col("x"), 1).alias("r"), F.ceil(col("x")).alias("c"),
        F.floor(col("x")).alias("f"), F.pow(col("n"), 2).alias("p"),
        F.greatest(col("x"), col("n")).alias("g"),
        F.least(col("x"), col("n")).alias("l"),
        F.signum(col("x")).alias("sg"),
        F.factorial(col("n")).alias("fact"),
        F.log(col("x")).alias("ln"),
        F.exp(col("n")).alias("e"),
        F.atan2(col("x"), col("n")).alias("at"),
        F.shiftleft(col("n"), 2).alias("sl"),
        F.bit_count(col("n")).alias("bc"),
    ).to_pandas()
    assert out["a"].tolist() == [3.7, 0.0, 2.5, 9.0]
    assert np.isnan(out["sq"][0]) and abs(out["sq"][3] - 3.0) < 1e-12
    assert out["r"].tolist() == [-3.7, 0.0, 2.5, 9.0]
    assert out["c"].tolist() == [-3, 0, 3, 9]
    assert out["f"].tolist() == [-4, 0, 2, 9]
    assert out["p"].tolist() == [1.0, 4.0, 9.0, 16.0]
    assert out["g"].tolist() == [1.0, 2.0, 3.0, 9.0]
    assert out["l"].tolist() == [-3.7, 0.0, 2.5, 4.0]
    assert out["sg"].tolist() == [-1.0, 0.0, 1.0, 1.0]
    assert out["fact"].tolist() == [1, 2, 6, 24]
    # ln of non-positive is NULL (reference Logarithm semantics)
    assert pd.isna(out["ln"][0]) and pd.isna(out["ln"][1])
    assert np.allclose(out["e"], np.exp(pdf["n"]))
    assert np.allclose(out["at"], np.arctan2(pdf["x"], pdf["n"]))
    assert out["sl"].tolist() == [4, 8, 12, 16]
    assert out["bc"].tolist() == [1, 1, 2, 1]


def test_round_half_up_and_decimals(session):
    import pyarrow as pa
    import decimal
    t = pa.table({"d": pa.array([decimal.Decimal("2.345"),
                                 decimal.Decimal("-2.345")],
                                type=pa.decimal128(10, 3))})
    out = (session.create_dataframe(t)
           .select(F.round(col("d"), 2).alias("r")).to_pandas())
    assert [str(v) for v in out["r"]] == ["2.35", "-2.35"]  # HALF_UP


def test_string_functions(tdf):
    df, pdf = tdf
    out = df.select(
        F.ltrim(col("s")).alias("lt"), F.rtrim(col("s")).alias("rt"),
        F.reverse(col("s")).alias("rv"), F.initcap(col("s")).alias("ic"),
        F.instr(col("s"), "o").alias("i"),
        F.rlike(col("s"), r"\d+").alias("rl"),
        F.regexp_replace(col("s"), r"\d+", "#").alias("rr"),
        F.regexp_extract(col("s"), r"([A-Za-z]+)(\d+)", 2).alias("re"),
        F.lpad(col("s"), 5, "*").alias("lp"),
        F.rpad(col("s"), 5, "*").alias("rp"),
        F.replace(col("s"), "o", "0").alias("rep"),
        F.translate(col("s"), "lo", "LO").alias("tr"),
        F.repeat(col("s"), 2).alias("rep2"),
        F.contains(col("s"), "42").alias("ct"),
        F.startswith(col("s"), "Hel").alias("sw"),
        F.endswith(col("s"), "42").alias("ew"),
        F.ascii(col("s")).alias("asc"),
    ).to_pandas()
    assert out["lt"][3] == "pad  " and out["rt"][3] == "  pad"
    assert out["rv"][1] == "oof"
    assert out["ic"][0] == "Hello World"
    assert out["i"].tolist() == [5, 2, 0, 0]
    assert out["rl"].tolist() == [False, False, True, False]
    assert out["rr"][2] == "Bar#"
    assert out["re"][2] == "42" and out["re"][1] == ""
    assert out["lp"][1] == "**foo" and out["rp"][1] == "foo**"
    assert out["rep"][1] == "f00"
    assert out["tr"][0] == "HeLLO WOrLd"
    assert out["rep2"][1] == "foofoo"
    assert out["ct"].tolist() == [False, False, True, False]
    assert out["sw"].tolist() == [True, False, False, False]
    assert out["ew"].tolist() == [False, False, True, False]
    assert out["asc"].tolist() == [ord("H"), ord("f"), ord("B"), ord(" ")]


def test_datetime_functions(session):
    dd = session.create_dataframe(pd.DataFrame(
        {"d": pd.to_datetime(
            ["2024-01-31", "2024-02-29", "2023-12-15"]).date}))
    out = dd.select(
        F.quarter(col("d")).alias("q"),
        F.dayofweek(col("d")).alias("dw"),
        F.weekday(col("d")).alias("wd"),
        F.dayofyear(col("d")).alias("dy"),
        F.weekofyear(col("d")).alias("wy"),
        F.last_day(col("d")).alias("ld"),
        F.add_months(col("d"), 1).alias("am"),
        F.trunc(col("d"), "month").alias("tm"),
        F.trunc(col("d"), "year").alias("ty"),
        F.next_day(col("d"), "MON").alias("nd"),
        F.months_between(col("d"), col("d")).alias("mb"),
    ).to_pandas()
    assert out["q"].tolist() == [1, 1, 4]
    assert out["dw"].tolist() == [4, 5, 6]  # Wed, Thu, Fri (1=Sunday)
    assert out["wd"].tolist() == [2, 3, 4]  # 0=Monday
    assert out["dy"].tolist() == [31, 60, 349]
    assert out["wy"].tolist() == [5, 9, 50]
    assert out["ld"].tolist() == [DT.date(2024, 1, 31),
                                  DT.date(2024, 2, 29),
                                  DT.date(2023, 12, 31)]
    assert out["am"].tolist() == [DT.date(2024, 2, 29),
                                  DT.date(2024, 3, 29),
                                  DT.date(2024, 1, 15)]
    assert out["tm"].tolist() == [DT.date(2024, 1, 1),
                                  DT.date(2024, 2, 1),
                                  DT.date(2023, 12, 1)]
    assert out["ty"].tolist() == [DT.date(2024, 1, 1),
                                  DT.date(2024, 1, 1),
                                  DT.date(2023, 1, 1)]
    assert out["nd"].tolist() == [DT.date(2024, 2, 5),
                                  DT.date(2024, 3, 4),
                                  DT.date(2023, 12, 18)]
    assert out["mb"].tolist() == [0.0, 0.0, 0.0]


def test_null_conditional(session):
    pdf = pd.DataFrame({"a": pd.array([1, None, 3], dtype="Int64"),
                        "b": np.array([9, 8, 3], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = df.select(
        F.nvl(col("a"), col("b")).alias("nv"),
        F.nvl2(col("a"), col("b"), F.lit(-1)).alias("nv2"),
        F.nullif(col("a"), col("b")).alias("nf"),
        F.coalesce(col("a"), col("b")).alias("co"),
    ).to_pandas()
    assert out["nv"].tolist() == [1, 8, 3]
    assert out["nv2"].tolist() == [9, -1, 3]
    assert out["nf"][0] == 1 and pd.isna(out["nf"][1]) and \
        pd.isna(out["nf"][2])  # a==b on the last row -> NULL
    assert out["co"].tolist() == [1, 8, 3]


def test_extended_aggregates(session):
    rs = np.random.RandomState(3)
    pdf = pd.DataFrame({
        "g": rs.randint(0, 4, 200).astype(np.int64),
        "x": rs.randn(200), "y": rs.randn(200),
        "i": rs.randint(0, 50, 200).astype(np.int64),
        "b": rs.randint(0, 2, 200).astype(bool),
        "s": rs.choice(["aa", "bb", "cc"], 200)})
    session.register_table("fn_agg", pdf)
    out = (session.table("fn_agg").group_by(col("g")).agg(
        F.corr(col("x"), col("y")).alias("c"),
        F.covar_samp(col("x"), col("y")).alias("cs"),
        F.covar_pop(col("x"), col("y")).alias("cp"),
        F.skewness(col("x")).alias("sk"),
        F.kurtosis(col("x")).alias("ku"),
        F.first(col("i")).alias("fi"), F.last(col("i")).alias("la"),
        F.first(col("x")).alias("fx"),
        F.first(col("s")).alias("fs"),
        F.bool_and(col("b")).alias("ba"), F.bool_or(col("b")).alias("bo"),
        F.count_if(col("x") > 0).alias("ci"),
    ).to_pandas().sort_values("g").reset_index(drop=True))

    def per_group(d):
        xc = d["x"] - d["x"].mean()
        m2 = (xc ** 2).mean()
        return pd.Series({
            "c": d["x"].corr(d["y"]), "cs": d["x"].cov(d["y"]),
            "cp": d["x"].cov(d["y"]) * (len(d) - 1) / len(d),
            "sk": (xc ** 3).mean() / m2 ** 1.5,
            "ku": (xc ** 4).mean() / m2 ** 2 - 3,
            "fi": d["i"].iloc[0], "la": d["i"].iloc[-1],
            "fx": d["x"].iloc[0], "fs": d["s"].iloc[0],
            "ba": d["b"].all(), "bo": d["b"].any(),
            "ci": int((d["x"] > 0).sum())})

    want = (pdf.groupby("g").apply(per_group, include_groups=False)
            .reset_index())
    for c in ("c", "cs", "cp", "sk", "ku", "fx"):
        assert np.allclose(out[c], want[c], rtol=1e-9), c
    for c in ("fi", "la", "fs", "ba", "bo", "ci"):
        assert out[c].tolist() == want[c].tolist(), c


def test_distinct_sum_avg(session):
    session.register_table("fn_dt", pd.DataFrame(
        {"g": np.array([1, 1, 1, 2, 2], dtype=np.int64),
         "v": np.array([10, 10, 20, 5, 6], dtype=np.int64)}))
    o = session.sql(
        "SELECT g, sum(DISTINCT v) AS s, avg(DISTINCT v) AS a "
        "FROM fn_dt GROUP BY g ORDER BY g").to_pandas()
    assert o["s"].tolist() == [30, 11]
    assert o["a"].tolist() == [15.0, 5.5]
    o2 = (session.table("fn_dt").group_by(col("g"))
          .agg(F.sum_distinct(col("v")).alias("s"))
          .to_pandas().sort_values("g").reset_index(drop=True))
    assert o2["s"].tolist() == [30, 11]


def test_sql_registry_dispatch(session):
    o = session.sql(
        "SELECT abs(-3) AS a, round(2.567, 2) AS r, greatest(1, 7, 3) "
        "AS g, nullif(4, 4) AS n, pow(2, 10) AS p, least(5, 2, 9) AS l,"
        " mod(7, 3) AS m, if(1 > 2, 'x', 'y') AS i").to_pandas()
    assert o["a"][0] == 3 and abs(o["r"][0] - 2.57) < 1e-9
    assert o["g"][0] == 7 and pd.isna(o["n"][0]) and o["p"][0] == 1024.0
    assert o["l"][0] == 2 and o["m"][0] == 1 and o["i"][0] == "y"
    # arity errors are loud
    from spark_tpu.expr import AnalysisError
    with pytest.raises(Exception):
        session.sql("SELECT abs(1, 2) FROM fn_dt")


def test_sql_string_datetime_registry(session):
    session.register_table("fn_s", pd.DataFrame(
        {"s": ["a1", "b22", "c"],
         "d": pd.to_datetime(["2024-03-15", "2024-06-01",
                              "2024-12-31"]).date}))
    o = session.sql(
        "SELECT regexp_extract(s, '([a-z])(\\d+)', 2) AS digits, "
        "lpad(s, 4, '0') AS lp, quarter(d) AS q, trunc(d, 'year') AS ty "
        "FROM fn_s").to_pandas()
    assert o["digits"].tolist() == ["1", "22", ""]
    assert o["lp"].tolist() == ["00a1", "0b22", "000c"]
    assert o["q"].tolist() == [1, 2, 4]
    assert o["ty"].tolist() == [DT.date(2024, 1, 1)] * 3


def test_new_aggs_mesh_parity(session):
    mesh_key = "spark_tpu.sql.mesh.size"
    session.register_table("fn_m", pd.DataFrame(
        {"g": np.arange(100, dtype=np.int64) % 5,
         "v": np.arange(100, dtype=np.int64),
         "f": np.arange(100, dtype=np.float64) * 1.5}))
    build = lambda: (session.table("fn_m").group_by(col("g")).agg(
        F.corr(col("v"), col("f")).alias("c"),
        F.covar_pop(col("v"), col("f")).alias("cv"),
        F.bool_or(col("f") > 100).alias("bo"),
        F.count_if(col("v") % 2 == 0).alias("ci")))
    want = build().to_pandas().sort_values("g").reset_index(drop=True)
    try:
        session.conf.set(mesh_key, 8)
        got = build().to_pandas().sort_values("g").reset_index(drop=True)
    finally:
        session.conf.set(mesh_key, 0)
    assert np.allclose(got["c"].fillna(-9), want["c"].fillna(-9))
    assert np.allclose(got["cv"], want["cv"])
    assert got["bo"].tolist() == want["bo"].tolist()
    assert got["ci"].tolist() == want["ci"].tolist()


def test_advisor_round4_fn_semantics(session):
    """Round-4 ADVICE: bit_count sign-extends to 64 bits (Long.bitCount),
    NaN orders as the largest double in greatest/least, and make_date
    NULLs invalid calendar dates instead of rolling them over."""
    pdf = pd.DataFrame({
        "i": np.array([-1, 1, -2], dtype=np.int32),
        # sqrt of a negative makes a true device NaN (pandas-NaN would
        # ingest as NULL, which greatest/least legitimately skip)
        "f": np.array([-1.0, 1.0, -1.0]),
        "g": np.array([4.0, -1.0, -1.0]),
        "y": np.array([2023, 2023, 2024], dtype=np.int32),
        "m": np.array([2, 2, 2], dtype=np.int32),
        "d": np.array([30, 28, 29], dtype=np.int32)})
    df = session.create_dataframe(pdf, "adv4_fns")
    out = df.select(
        F.bit_count(col("i")).alias("bc"),
        F.greatest(F.sqrt(col("f")), F.sqrt(col("g"))).alias("gr"),
        F.least(F.sqrt(col("f")), F.sqrt(col("g"))).alias("le"),
        F.make_date(col("y"), col("m"), col("d")).alias("md"),
    ).to_pandas()
    # -1 as int sign-extends to 64 set bits; -2 to 63
    assert out["bc"].tolist() == [64, 1, 63]
    # NaN is the largest double: greatest prefers it, least avoids it
    assert np.isnan(out["gr"][0]) and np.isnan(out["gr"][1]) \
        and np.isnan(out["gr"][2])
    assert out["le"][0] == 2.0 and out["le"][1] == 1.0 \
        and np.isnan(out["le"][2])
    # 2023-02-30 is invalid -> NULL; 2023-02-28 and 2024-02-29 are real
    assert pd.isna(out["md"][0])
    assert str(out["md"][1])[:10] == "2023-02-28"
    assert str(out["md"][2])[:10] == "2024-02-29"

"""General join semantics vs pandas ground truth: many-to-many expansion,
outer joins (left/right/full), residual conditions, capacity-overflow
retry, semi/anti with residuals. Models the reference's
`OuterJoinSuite`/`InnerJoinSuite` conf-matrix style."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit


def _tables(session):
    left = pd.DataFrame({
        "k": np.array([1, 2, 2, 3, 5], dtype=np.int64),
        "lv": np.array([10, 20, 21, 30, 50], dtype=np.int64)})
    right = pd.DataFrame({
        "k": np.array([2, 2, 3, 4], dtype=np.int64),
        "rv": np.array([200, 201, 300, 400], dtype=np.int64)})
    return (session.create_dataframe(left, "l"),
            session.create_dataframe(right, "r"), left, right)


def _expect(left, right, how):
    m = left.merge(right, on="k", how=how)
    return m.sort_values(["lv", "rv"], na_position="first") \
        .reset_index(drop=True)


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_join_matrix_vs_pandas(session, how):
    ldf, rdf, left, right = _tables(session)
    out = (ldf.join(rdf, on="k", how=how).to_pandas()
           .sort_values(["lv", "rv"], na_position="first")
           .reset_index(drop=True))
    exp = _expect(left, right, "outer" if how == "outer" else how)
    assert len(out) == len(exp), (how, out, exp)
    for c in ("lv", "rv"):
        got = out[c].fillna(-1).astype(np.int64).tolist()
        want = exp[c].fillna(-1).astype(np.int64).tolist()
        assert got == want, (how, c, out, exp)


def test_full_outer_keys_coalesced(session):
    ldf, rdf, _, _ = _tables(session)
    out = ldf.join(rdf, on="k", how="outer").to_pandas()
    assert "k_r" not in out.columns
    # k=4 exists only on the right; coalesce must surface it
    assert 4 in set(out["k"])
    assert 5 in set(out["k"])


def test_join_overflow_retry(session):
    # expansion 10x the probe capacity: forces the executor's
    # capacity-retry loop (out_cap seeds at probe capacity)
    n_left, n_right_dup = 64, 40
    left = session.create_dataframe(pd.DataFrame({
        "k": np.zeros(n_left, dtype=np.int64),
        "lv": np.arange(n_left, dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.zeros(n_right_dup, dtype=np.int64),
        "rv": np.arange(n_right_dup, dtype=np.int64)}))
    out = left.join(right, on="k").to_pandas()
    assert len(out) == n_left * n_right_dup


def test_join_residual_condition_inner(session):
    ldf, rdf, left, right = _tables(session)
    out = (ldf.join(rdf, on="k", condition=col("rv") > lit(200))
           .to_pandas().sort_values(["lv", "rv"]).reset_index(drop=True))
    exp = left.merge(right, on="k")
    exp = exp[exp["rv"] > 200].sort_values(["lv", "rv"]).reset_index(drop=True)
    assert list(out["rv"]) == list(exp["rv"])


def test_join_residual_condition_left_outer(session):
    # ON-clause residual: probe rows with no surviving match are kept,
    # null-extended (reference outer-join ON semantics)
    ldf, rdf, left, right = _tables(session)
    out = (ldf.join(rdf, on="k", how="left", condition=col("rv") > lit(200))
           .to_pandas())
    assert len(out[out["lv"] == 20]) == 1  # only rv=201 passes
    assert out[out["lv"] == 20]["rv"].iloc[0] == 201
    # k=5 unmatched and k=2/rv<=200-only rows are null-extended, all kept
    assert sorted(out["lv"]) == [10, 20, 21, 30, 50]


def test_semi_anti_with_duplicates(session):
    ldf, rdf, _, _ = _tables(session)
    semi = ldf.join(rdf, on="k", how="left_semi").to_pandas()
    anti = ldf.join(rdf, on="k", how="left_anti").to_pandas()
    assert sorted(semi["lv"]) == [20, 21, 30]
    assert sorted(anti["lv"]) == [10, 50]


def test_anti_join_keeps_null_keys(session):
    left = session.create_dataframe(pd.DataFrame({
        "k": pd.array([1, None, 3], dtype="Int64"),
        "lv": np.array([1, 2, 3], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([1], dtype=np.int64)}))
    anti = left.join(right, on="k", how="left_anti").to_pandas()
    # NULL keys never match -> kept by anti join (reference LeftAnti)
    assert sorted(anti["lv"]) == [2, 3]


def test_semi_with_residual(session):
    ldf, rdf, _, _ = _tables(session)
    semi = (ldf.join(rdf, on="k", how="left_semi",
                     condition=col("rv") >= lit(300)).to_pandas())
    assert sorted(semi["lv"]) == [30]


def test_cross_join(session):
    a = session.create_dataframe(pd.DataFrame(
        {"x": np.array([1, 2, 3], dtype=np.int64)}))
    b = session.create_dataframe(pd.DataFrame(
        {"y": np.array([10, 20], dtype=np.int64)}))
    out = a.cross_join(b).to_pandas()
    assert len(out) == 6
    assert sorted(zip(out["x"], out["y"])) == [
        (1, 10), (1, 20), (2, 10), (2, 20), (3, 10), (3, 20)]


def test_semi_residual_uses_same_rename_convention(session):
    # both sides have `v`: the residual sees the right copy as `v_r` for
    # EVERY join type, semi/anti included
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2], dtype=np.int64),
        "v": np.array([5, 5], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2], dtype=np.int64),
        "v": np.array([10, 1], dtype=np.int64)}))
    inner = left.join(right, on="k",
                      condition=col("v_r") > col("v")).to_pandas()
    semi = left.join(right, on="k", how="left_semi",
                     condition=col("v_r") > col("v")).to_pandas()
    assert sorted(inner["k"]) == [1]
    assert sorted(semi["k"]) == [1]


def test_streamed_substr_groupby_multichunk(session):
    # derived string keys must NOT stream (per-chunk dictionaries are
    # incompatible); verify the fallback is correct across chunks
    prev = session.conf.get("spark_tpu.sql.execution.streamingChunkRows")
    session.conf.set("spark_tpu.sql.execution.streamingChunkRows", 64)
    try:
        strs = [f"aa{i}" for i in range(100)] + \
               [f"bb{i}" for i in range(100)] + \
               [f"cc{i}" for i in range(100)]
        df = session.create_dataframe(pd.DataFrame(
            {"s": strs, "v": np.ones(300, dtype=np.int64)}))
        out = (df.group_by(col("s").substr(1, 2).alias("p"))
               .agg(F.count().alias("c"))
               .to_pandas().sort_values("p").reset_index(drop=True))
        assert list(out["p"]) == ["aa", "bb", "cc"]
        assert list(out["c"]) == [100, 100, 100]
    finally:
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows", prev)


def test_string_key_outer_join(session):
    left = session.create_dataframe(pd.DataFrame({
        "s": ["a", "b", "c"], "lv": np.array([1, 2, 3], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "s": ["c", "d"], "rv": np.array([30, 40], dtype=np.int64)}))
    out = (left.join(right, on="s", how="outer").to_pandas()
           .sort_values("s").reset_index(drop=True))
    assert list(out["s"]) == ["a", "b", "c", "d"]
    assert out["rv"].fillna(-1).tolist() == [-1, -1, 30, 40]

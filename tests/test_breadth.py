"""Expression/aggregate breadth: stddev/variance, count distinct,
distinct(), date parts, string functions, null-safe equality — each
parity-checked against pandas/numpy (the reference's QueryTest.checkAnswer
discipline)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit


@pytest.fixture(scope="module")
def data(session):
    rs = np.random.RandomState(42)
    pdf = pd.DataFrame({
        "k": rs.randint(0, 7, 500).astype(np.int64),
        "v": rs.normal(100.0, 15.0, 500),
        "i": rs.randint(0, 40, 500).astype(np.int64),
        "d": (np.datetime64("1995-01-01") +
              rs.randint(0, 2000, 500).astype("timedelta64[D]")),
        "s": [f"Item_{i % 5} " for i in range(500)],
    })
    session.register_table("breadth", pdf)
    return session, pdf


def test_stddev_variance_global(data):
    session, pdf = data
    got = (session.table("breadth")
           .agg(F.stddev(col("v")).alias("sd"),
                F.stddev_pop(col("v")).alias("sdp"),
                F.variance(col("v")).alias("var"),
                F.var_pop(col("v")).alias("varp"))
           .to_pandas())
    assert np.isclose(got["sd"][0], pdf["v"].std(ddof=1), rtol=1e-9)
    assert np.isclose(got["sdp"][0], pdf["v"].std(ddof=0), rtol=1e-9)
    assert np.isclose(got["var"][0], pdf["v"].var(ddof=1), rtol=1e-9)
    assert np.isclose(got["varp"][0], pdf["v"].var(ddof=0), rtol=1e-9)


def test_stddev_grouped(data):
    session, pdf = data
    got = (session.table("breadth").group_by(col("k"))
           .agg(F.stddev(col("v")).alias("sd"))
           .sort(col("k")).to_pandas())
    want = pdf.groupby("k")["v"].std(ddof=1).sort_index()
    assert np.allclose(got["sd"], want.values, rtol=1e-9)


def test_count_distinct_global(data):
    session, pdf = data
    got = (session.table("breadth")
           .agg(F.count_distinct(col("i")).alias("cd")).to_pandas())
    assert got["cd"][0] == pdf["i"].nunique()


def test_count_distinct_grouped(data):
    session, pdf = data
    got = (session.table("breadth").group_by(col("k"))
           .agg(F.count_distinct(col("i")).alias("cd"))
           .sort(col("k")).to_pandas())
    want = pdf.groupby("k")["i"].nunique().sort_index()
    assert got["cd"].tolist() == want.tolist()


def test_distinct(data):
    session, pdf = data
    got = (session.table("breadth").select(col("k"), col("i"))
           .distinct().to_pandas())
    want = pdf[["k", "i"]].drop_duplicates()
    assert len(got) == len(want)
    assert (sorted(map(tuple, got.values.tolist()))
            == sorted(map(tuple, want.values.tolist())))


def test_date_parts(data):
    session, pdf = data
    got = (session.table("breadth")
           .select(F.year(col("d")).alias("y"),
                   F.month(col("d")).alias("m"),
                   F.day(col("d")).alias("dd"))
           .to_pandas())
    dts = pd.to_datetime(pdf["d"])
    assert got["y"].tolist() == dts.dt.year.tolist()
    assert got["m"].tolist() == dts.dt.month.tolist()
    assert got["dd"].tolist() == dts.dt.day.tolist()


def test_date_add_sub(data):
    session, pdf = data
    got = (session.table("breadth")
           .select(F.date_add(col("d"), 31).alias("p"),
                   F.date_sub(col("d"), 7).alias("q"))
           .to_pandas())
    dts = pd.to_datetime(pdf["d"])
    assert pd.to_datetime(got["p"]).tolist() == \
        (dts + pd.Timedelta(days=31)).tolist()
    assert pd.to_datetime(got["q"]).tolist() == \
        (dts - pd.Timedelta(days=7)).tolist()


def test_string_functions(data):
    session, pdf = data
    got = (session.table("breadth")
           .select(F.upper(col("s")).alias("u"),
                   F.lower(col("s")).alias("l"),
                   F.trim(col("s")).alias("t"),
                   F.length(col("s")).alias("n"),
                   F.concat(lit("<"), col("s"), lit(">")).alias("c"))
           .to_pandas())
    assert got["u"].tolist() == pdf["s"].str.upper().tolist()
    assert got["l"].tolist() == pdf["s"].str.lower().tolist()
    assert got["t"].tolist() == pdf["s"].str.strip().tolist()
    assert got["n"].tolist() == pdf["s"].str.len().tolist()
    assert got["c"].tolist() == ("<" + pdf["s"] + ">").tolist()


def test_null_safe_equality(session):
    pdf = pd.DataFrame({"a": [1.0, None, 3.0, None],
                        "b": [1.0, None, 4.0, 5.0]})
    session.register_table("nse", pdf)
    got = (session.table("nse")
           .select(F.eq_null_safe(col("a"), col("b")).alias("e"))
           .to_pandas())
    assert got["e"].tolist() == [True, True, False, False]


def test_sql_count_distinct_and_stddev(data):
    session, pdf = data
    got = session.sql(
        "SELECT k, count(DISTINCT i) AS cd, stddev(v) AS sd "
        "FROM breadth GROUP BY k ORDER BY k"
    )
    # mixing distinct + plain aggregates is unsupported: expect a clean
    # error, not wrong results
    from spark_tpu.expr import AnalysisError
    with pytest.raises(AnalysisError):
        got.to_pandas()
    got = session.sql(
        "SELECT k, count(DISTINCT i) AS cd FROM breadth "
        "GROUP BY k ORDER BY k").to_pandas()
    want = pdf.groupby("k")["i"].nunique().sort_index()
    assert got["cd"].tolist() == want.tolist()
    got2 = session.sql(
        "SELECT stddev(v) AS sd FROM breadth").to_pandas()
    assert np.isclose(got2["sd"][0], pdf["v"].std(ddof=1), rtol=1e-9)


def test_sql_select_distinct(data):
    session, pdf = data
    got = session.sql("SELECT DISTINCT k FROM breadth ORDER BY k") \
        .to_pandas()
    assert got["k"].tolist() == sorted(pdf["k"].unique().tolist())


def test_drop_duplicates_subset(data):
    session, pdf = data
    got = (session.table("breadth").drop_duplicates(["k"])
           .to_pandas())
    assert sorted(got["k"].tolist()) == sorted(pdf["k"].unique().tolist())
    # kept rows are real rows of the input
    merged = got.merge(pdf, on=list(got.columns), how="left", indicator=True)
    assert (merged["_merge"] == "both").all()

"""End-to-end slice tests: the minimum viable query paths
(SURVEY.md section 7 step 4: range -> group-by -> count)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit


def test_range_collect(session):
    df = session.range(10)
    out = df.collect()
    assert out.column("id").to_pylist() == list(range(10))


def test_range_groupby_count(session):
    # BASELINE config 1 in miniature
    df = session.range(1000).group_by((col("id") % 10).alias("k")).count()
    pdf = df.to_pandas().sort_values("k").reset_index(drop=True)
    assert list(pdf["k"]) == list(range(10))
    assert all(pdf["count"] == 100)


def test_filter_project(session):
    df = (session.range(100)
          .filter(col("id") >= 90)
          .select((col("id") * 2).alias("x")))
    out = df.collect().column("x").to_pylist()
    assert out == [2 * i for i in range(90, 100)]


def test_global_aggregate(session):
    df = session.range(101).agg(
        F.sum(col("id")).alias("s"),
        F.count().alias("c"),
        F.min(col("id")).alias("mn"),
        F.max(col("id")).alias("mx"),
        F.avg(col("id")).alias("a"))
    row = df.to_pandas().iloc[0]
    assert row["s"] == 5050
    assert row["c"] == 101
    assert row["mn"] == 0
    assert row["mx"] == 100
    assert abs(row["a"] - 50.0) < 1e-9


def test_groupby_sum_multi_key_sort_path(session):
    pdf = pd.DataFrame({
        "a": np.array([1, 1, 2, 2, 2, 3], dtype=np.int64) * 1_000_000_007,
        "b": np.array([0, 0, 0, 1, 1, 1], dtype=np.int64),
        "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    })
    df = session.create_dataframe(pdf)
    out = (df.group_by(col("a"), col("b"))
           .agg(F.sum(col("v")).alias("s"), F.count().alias("c"))
           .to_pandas().sort_values(["a", "b"]).reset_index(drop=True))
    expected = (pdf.groupby(["a", "b"], as_index=False)
                .agg(s=("v", "sum"), c=("v", "count"))
                .sort_values(["a", "b"]).reset_index(drop=True))
    assert len(out) == len(expected)
    assert np.allclose(out["s"], expected["s"])
    assert list(out["c"]) == list(expected["c"])


def test_join_inner(session):
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2, 3, 4, 5], dtype=np.int64),
        "lv": np.array([10, 20, 30, 40, 50], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([2, 4, 6], dtype=np.int64),
        "rv": np.array([200, 400, 600], dtype=np.int64)}))
    out = (left.join(right, on="k")
           .to_pandas().sort_values("k").reset_index(drop=True))
    assert list(out["k"]) == [2, 4]
    assert list(out["lv"]) == [20, 40]
    assert list(out["rv"]) == [200, 400]


def test_join_left(session):
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2, 3], dtype=np.int64),
        "lv": np.array([10, 20, 30], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([2], dtype=np.int64),
        "rv": np.array([200], dtype=np.int64)}))
    out = (left.join(right, on="k", how="left")
           .to_pandas().sort_values("k").reset_index(drop=True))
    assert list(out["k"]) == [1, 2, 3]
    assert out["rv"].isna().tolist() == [True, False, True]
    assert out.loc[1, "rv"] == 200


def test_join_semi_anti(session):
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2, 3, 4], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([2, 4], dtype=np.int64)}))
    semi = left.join(right, on="k", how="left_semi").to_pandas()
    anti = left.join(right, on="k", how="left_anti").to_pandas()
    assert sorted(semi["k"]) == [2, 4]
    assert sorted(anti["k"]) == [1, 3]


def test_join_many_to_many(session):
    # duplicate build keys expand (round 1 aborted at runtime)
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2, 2], dtype=np.int64),
        "lv": np.array([10, 20, 21], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([2, 2, 3], dtype=np.int64),
        "rv": np.array([1, 2, 3], dtype=np.int64)}))
    out = (left.join(right, on="k")
           .to_pandas().sort_values(["lv", "rv"]).reset_index(drop=True))
    # 2 left rows with k=2 x 2 right rows with k=2 = 4 rows
    assert list(out["lv"]) == [20, 20, 21, 21]
    assert list(out["rv"]) == [1, 2, 1, 2]


def test_sort_limit(session):
    df = session.range(100).sort(col("id").desc()).limit(3)
    assert df.collect().column("id").to_pylist() == [99, 98, 97]


def test_sort_multi_key_with_strings(session):
    pdf = pd.DataFrame({
        "s": ["banana", "apple", "cherry", "apple"],
        "v": np.array([1, 2, 3, 4], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = df.sort(col("s").asc(), col("v").desc()).to_pandas()
    assert list(out["s"]) == ["apple", "apple", "banana", "cherry"]
    assert list(out["v"]) == [4, 2, 1, 3]


def test_string_filter_and_groupby(session):
    pdf = pd.DataFrame({
        "s": ["x", "y", "x", "z", "y", "x"],
        "v": np.array([1, 2, 3, 4, 5, 6], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = (df.filter(col("s") != lit("z"))
           .group_by(col("s")).agg(F.sum(col("v")).alias("s_v"))
           .to_pandas().sort_values("s").reset_index(drop=True))
    assert list(out["s"]) == ["x", "y"]
    assert list(out["s_v"]) == [10, 7]


def test_nulls_propagate(session):
    pdf = pd.DataFrame({
        "a": pd.array([1, None, 3, None], dtype="Int64"),
        "b": np.array([10.0, 20.0, 30.0, 40.0])})
    df = session.create_dataframe(pdf)
    out = df.agg(F.sum(col("a")).alias("s"), F.count(col("a")).alias("c"),
                 F.count().alias("star")).to_pandas().iloc[0]
    assert out["s"] == 4
    assert out["c"] == 2
    assert out["star"] == 4
    # filter on nullable: NULL comparisons drop rows
    flt = df.filter(col("a") > 0).to_pandas()
    assert sorted(flt["b"]) == [10.0, 30.0]


def test_union(session):
    a = session.range(3)
    b = session.range(3)
    assert a.union(b).count() == 6


def test_decimal_sum_exact(session):
    import pyarrow as pa
    import decimal
    vals = [decimal.Decimal("123456.78"), decimal.Decimal("0.01"),
            decimal.Decimal("99999999.99")]
    table = pa.table({"d": pa.array(vals, type=pa.decimal128(18, 2))})
    df = session.create_dataframe(table)
    out = df.agg(F.sum(col("d")).alias("s")).collect()
    assert out.column("s")[0].as_py() == decimal.Decimal("100123456.78")


def test_case_when(session):
    df = session.range(6).select(
        F.when(col("id") < 2, lit(0)).when(col("id") < 4, lit(1))
        .otherwise(lit(2)).alias("bucket"))
    assert df.collect().column("bucket").to_pylist() == [0, 0, 1, 1, 2, 2]


def test_mod_strength_reduction_exact(session):
    # the TPU mod fast path must match the reference's truncated-division
    # `%` (sign of the dividend), including values near the int64 boundary
    import pyarrow as pa
    vals = [0, 1, 99, 100, 101, -1, -100, -101, 2**31 - 1, -2**31,
            2**52, 2**52 + 12345, 2**62, -2**62, 2**63 - 1, -2**63,
            987654321987654321, -987654321987654321]

    def trunc_mod(v, m):
        r = abs(v) % m
        return r if v >= 0 else -r

    for m in (1, 2, 7, 100, 1 << 20, (1 << 26) - 1):
        df = session.create_dataframe(
            pa.table({"x": pa.array(vals, type=pa.int64())}))
        from spark_tpu.functions import col, lit
        out = df.select((col("x") % lit(m)).alias("r")).collect()
        got = out.column("r").to_pylist()
        expect = [trunc_mod(v, m) for v in vals]
        assert got == expect, (m, got, expect)


def test_pmod(session):
    import pyarrow as pa
    from spark_tpu.functions import pmod
    vals = [-7, -1, 0, 1, 7, -2**62, 2**62]
    df = session.create_dataframe(
        pa.table({"x": pa.array(vals, type=pa.int64())}))
    out = df.select(pmod(col("x"), lit(3)).alias("r")).collect()
    assert out.column("r").to_pylist() == [v % 3 for v in vals]


def test_streamed_join_aggregate(session):
    """Chunked scans stream THROUGH joins: build sides materialize once,
    probe chunks join + fold into carried tables (the over-HBM path for
    join+aggregate queries; SURVEY section 7 step 8)."""
    import numpy as np
    import pandas as pd
    import spark_tpu.execution.streaming_agg as SA
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    rs = np.random.RandomState(12)
    fact = pd.DataFrame({
        "fk": rs.randint(0, 50, 6000).astype(np.int64),
        "v": rs.randint(0, 1000, 6000).astype(np.int64)})
    dim = pd.DataFrame({"fk": np.arange(50, dtype=np.int64),
                        "g": (np.arange(50, dtype=np.int64) % 7)})
    session.register_table("sj_fact", fact)
    session.register_table("sj_dim", dim)

    engaged = []
    orig = SA.stream_scan_aggregate

    def spy(agg, chain, leaf, conf, cache=None, recovery=None):
        out = orig(agg, chain, leaf, conf, cache, recovery)
        engaged.append((out is not None,
                        sum(1 for op in chain
                            if hasattr(op, "left_keys"))))
        return out

    SA.stream_scan_aggregate = spy
    prev = session.conf.get("spark_tpu.sql.execution.streamingChunkRows")
    session.conf.set("spark_tpu.sql.execution.streamingChunkRows", 1024)
    # the device-table cache would keep this (tiny) scan resident and
    # skip streaming entirely; disable it to exercise the chunked path
    prev_cache = session.conf.get("spark_tpu.sql.io.deviceCacheBytes")
    session.conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)
    try:
        got = (session.table("sj_fact")
               .join(session.table("sj_dim"), on="fk")
               .group_by(F.pmod(col("g"), 7).alias("gg"))
               .agg(F.sum(col("v")).alias("s"), F.count().alias("c"))
               .to_pandas().sort_values("gg").reset_index(drop=True))
    finally:
        SA.stream_scan_aggregate = orig
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows", prev)
        session.conf.set("spark_tpu.sql.io.deviceCacheBytes", prev_cache)

    m = fact.merge(dim, on="fk")
    want = (m.assign(gg=m["g"] % 7).groupby("gg")
            .agg(s=("v", "sum"), c=("v", "size")).reset_index())
    assert got["s"].tolist() == want["s"].tolist()
    assert got["c"].tolist() == want["c"].tolist()
    assert any(ok and njoins > 0 for ok, njoins in engaged), engaged


def test_streamed_join_many_to_many_overflow(session):
    """Per-chunk join expansion overflowing the chunk capacity must
    retry with a bigger capacity, not drop pairs."""
    import numpy as np
    import pandas as pd
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    fact = pd.DataFrame({"fk": np.zeros(3000, dtype=np.int64),
                         "v": np.ones(3000, dtype=np.int64)})
    dim = pd.DataFrame({"fk": np.zeros(4, dtype=np.int64),
                        "g": np.arange(4, dtype=np.int64)})
    session.register_table("sjo_fact", fact)
    session.register_table("sjo_dim", dim)
    prev = session.conf.get("spark_tpu.sql.execution.streamingChunkRows")
    session.conf.set("spark_tpu.sql.execution.streamingChunkRows", 512)
    try:
        got = (session.table("sjo_fact")
               .join(session.table("sjo_dim"), on="fk")
               .group_by(F.pmod(col("g"), 4).alias("gg"))
               .agg(F.count().alias("c"))
               .to_pandas().sort_values("gg").reset_index(drop=True))
    finally:
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows", prev)
    # every fact row matches all 4 dim rows: 3000 per group
    assert got["c"].tolist() == [3000] * 4


def test_groupby_null_keys_direct_path(session):
    """NULL group keys form their own group on the dense-domain path
    (the dedicated null slot; SQL null-grouping semantics)."""
    import numpy as np
    import pandas as pd
    from spark_tpu import functions as F
    from spark_tpu.functions import col

    pdf = pd.DataFrame({"k": pd.array([1, 2, None, 1, None, 2, 1],
                                      dtype="Int8"),
                        "v": np.arange(7, dtype=np.int64)})
    session.register_table("nullkeys", pdf)
    got = (session.table("nullkeys").group_by(col("k"))
           .agg(F.sum(col("v")).alias("s"), F.count().alias("c"))
           .to_pandas())
    got = got.sort_values("k", na_position="last").reset_index(drop=True)
    want = (pdf.groupby("k", dropna=False)["v"]
            .agg(["sum", "size"]).reset_index()
            .sort_values("k", na_position="last").reset_index(drop=True))
    assert got["s"].tolist() == want["sum"].tolist()
    assert got["c"].tolist() == want["size"].tolist()
    assert got["k"].isna().sum() == 1

"""spark_tpu.ml: Pipeline/Estimator/Transformer + linear & logistic
regression, KMeans, scaler, evaluators (reference: ml/Pipeline.scala:1
and friends), with closed-form numpy parity checks."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.ml import (BinaryClassificationEvaluator, KMeans,
                          LinearRegression, LinearRegressionModel,
                          LogisticRegression, Pipeline,
                          RegressionEvaluator, StandardScaler,
                          VectorAssembler)


@pytest.fixture
def reg_df(session):
    rs = np.random.RandomState(7)
    n = 400
    x1 = rs.randn(n)
    x2 = rs.randn(n) * 2.0
    y = 3.0 * x1 - 1.5 * x2 + 0.75 + rs.randn(n) * 0.01
    pdf = pd.DataFrame({"x1": x1, "x2": x2, "label": y})
    session.register_table("ml_reg", pdf)
    return session.table("ml_reg"), pdf


def test_linear_regression_parity_with_lstsq(reg_df):
    df, pdf = reg_df
    assembled = VectorAssembler(["x1", "x2"], "features").transform(df)
    model = LinearRegression().fit(assembled)
    A = np.column_stack([pdf[["x1", "x2"]].to_numpy(),
                         np.ones(len(pdf))])
    want, *_ = np.linalg.lstsq(A, pdf["label"].to_numpy(), rcond=None)
    assert np.allclose(model.coefficients, want[:2], atol=1e-8)
    assert np.isclose(model.intercept, want[2], atol=1e-8)
    scored = model.transform(assembled)
    rmse = RegressionEvaluator().evaluate(scored)
    assert rmse < 0.02
    r2 = RegressionEvaluator(metricName="r2").evaluate(scored)
    assert r2 > 0.999


def test_pipeline_fit_transform(reg_df):
    df, _ = reg_df
    pipe = Pipeline([
        VectorAssembler(["x1", "x2"], "raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LinearRegression(),
    ])
    model = pipe.fit(df)
    out = model.transform(df)
    rmse = RegressionEvaluator().evaluate(out)
    assert rmse < 0.02


def test_model_save_load(reg_df, tmp_path):
    df, _ = reg_df
    assembled = VectorAssembler(["x1", "x2"], "features").transform(df)
    model = LinearRegression().fit(assembled)
    p = str(tmp_path / "lr.npz")
    model.save(p)
    loaded = LinearRegressionModel.load(p)
    assert np.allclose(loaded.coefficients, model.coefficients)
    assert loaded.intercept == model.intercept


def test_logistic_regression_separates(session):
    rs = np.random.RandomState(11)
    n = 600
    x = rs.randn(n, 2)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    pdf = pd.DataFrame({"a": x[:, 0], "b": x[:, 1], "label": y})
    session.register_table("ml_clf", pdf)
    df = VectorAssembler(["a", "b"], "features").transform(
        session.table("ml_clf"))
    model = LogisticRegression(maxIter=300, stepSize=2.0).fit(df)
    scored = model.transform(df)
    out = scored.to_pandas()
    acc = (out["prediction"] == out["label"]).mean()
    assert acc > 0.97
    auc = BinaryClassificationEvaluator().evaluate(scored)
    assert auc > 0.99


def test_kmeans_recovers_blobs(session):
    rs = np.random.RandomState(5)
    c1 = rs.randn(100, 2) * 0.2 + np.array([5.0, 5.0])
    c2 = rs.randn(100, 2) * 0.2 + np.array([-5.0, 5.0])
    c3 = rs.randn(100, 2) * 0.2 + np.array([0.0, -5.0])
    X = np.vstack([c1, c2, c3])
    pdf = pd.DataFrame({"a": X[:, 0], "b": X[:, 1],
                        "blob": np.repeat([0, 1, 2], 100)})
    session.register_table("ml_km", pdf)
    df = VectorAssembler(["a", "b"], "features").transform(
        session.table("ml_km"))
    model = KMeans(k=3, maxIter=25, seed=3).fit(df)
    out = model.transform(df).to_pandas()
    # every true blob maps to exactly one predicted cluster
    for b in range(3):
        preds = out[out["blob"] == b]["prediction"]
        assert preds.nunique() == 1
    assert out["prediction"].nunique() == 3
    centers = np.sort(np.round(model.cluster_centers), axis=0)
    assert centers.shape == (3, 2)


def test_params_set_and_errors():
    lr = LinearRegression()
    lr2 = lr.set(regParam=0.5)
    assert lr2.regParam == 0.5 and lr.regParam == 0.0
    with pytest.raises(ValueError):
        lr.set(nope=1)

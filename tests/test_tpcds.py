"""TPC-DS tranche-1: golden parity, join-kernel matrix, cost-based
join-reorder on/off identity, and plan-stability snapshots.

The TPC-DS analog of test_tpch/test_sql's parity suites plus the
reference's `TPCDSQueryTestSuite.scala:54` plan-golden discipline:
committed physical-plan snapshots under tests/tpcds_plans/ guard
against silent plan churn (regenerate intentionally with
SPARK_TPU_REGEN_TPCDS_PLANS=1 after a deliberate planner change)."""

import os

import pandas as pd
import pytest

from spark_tpu.tpcds import QUERIES, SQL_QUERIES, register_tables
from spark_tpu.tpcds import golden as G
from spark_tpu.tpcds.datagen import write_parquet

SF = 0.01
CBO_KEY = "spark_tpu.sql.cbo.joinReorder"
KERNEL_KEY = "spark_tpu.sql.join.kernelMode"
PLAN_DIR = os.path.join(os.path.dirname(__file__), "tpcds_plans")

#: queries whose reorder decisions must change the join SEQUENCE at
#: this scale (the acceptance gate: >= 3 multi-join queries reordered;
#: kind "order", not a mere probe/build orientation flip). 10 of the
#: 21 tranche queries re-sequence at SF0.01; these three keep the
#: tier-1 wall-clock down (q61 re-sequences too but costs ~23s alone)
REORDER_CHANGED = ("q19", "q73", "q79")
#: kernel-matrix pair: multi-join queries with large-enough probes
KERNEL_MATRIX = ("q19", "q68")
#: plan-stability snapshot subset
PLAN_SNAPSHOT = ("q3", "q19", "q55", "q73", "q96")


@pytest.fixture(scope="session")
def tpcds_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpcds") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpcds_session(session, tpcds_path):
    register_tables(session, tpcds_path)
    return session


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    return G.normalize_decimals(df.copy())


def _check(got: pd.DataFrame, qname: str, path: str) -> None:
    want = G.GOLDEN[qname](path)
    got = _norm(got)[list(want.columns)].reset_index(drop=True)
    G.compare(got, want, float_atol=1e-4)


@pytest.fixture()
def _no_runtime_filters(tpcds_session):
    """The parity sweeps run with runtime filters OFF: rf injection
    compiles a creation-chain stage per eligible join, which is ~55%
    of the snowflake queries' tier-1 wall-clock, and rf is
    results-identical on/off by design. rf-on TPC-DS coverage lives in
    the kernel-matrix / reorder / event-log tests and preflight stage
    9, which all keep the default."""
    key = "spark_tpu.sql.runtimeFilter.enabled"
    tpcds_session.conf.set(key, False)
    yield
    tpcds_session.conf.set(key, True)


@pytest.mark.parametrize("qname", sorted(SQL_QUERIES))
def test_tpcds_sql_parity(tpcds_session, tpcds_path,
                          _no_runtime_filters, qname):
    got = tpcds_session.sql(SQL_QUERIES[qname]).to_pandas()
    _check(got, qname, tpcds_path)


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpcds_dataframe_parity(tpcds_session, tpcds_path,
                                _no_runtime_filters, qname):
    got = QUERIES[qname](tpcds_session).to_pandas()
    _check(got, qname, tpcds_path)


@pytest.mark.parametrize("qname", KERNEL_MATRIX)
def test_tpcds_join_kernel_matrix(tpcds_session, tpcds_path, qname):
    """Both join kernels must produce byte-identical results on the
    snowflake queries, with the hash path PROVEN to have run (its
    join_table_slots metric) so the parity check can't go vacuous."""
    outs = {}
    hash_ran = False
    for mode in ("sort", "hash"):
        tpcds_session.conf.set(KERNEL_KEY, mode)
        try:
            qe = tpcds_session.sql(SQL_QUERIES[qname])._qe()
            outs[mode] = qe.collect().to_pandas()
        finally:
            tpcds_session.conf.set(KERNEL_KEY, "auto")
        if mode == "hash":
            hash_ran = any(k.startswith("join_table_slots_")
                           for k in qe.last_metrics)
    assert hash_ran, "hash kernel never ran — forced mode was ignored"
    pd.testing.assert_frame_equal(outs["sort"], outs["hash"])
    _check(outs["hash"], qname, tpcds_path)


@pytest.mark.parametrize("qname", REORDER_CHANGED)
def test_tpcds_reorder_on_off_identical(tpcds_session, tpcds_path,
                                        qname):
    """cbo.joinReorder on vs off: byte-identical results; off restores
    the frontend order (no decisions logged at all)."""
    tpcds_session.conf.set(CBO_KEY, True)
    qe_on = tpcds_session.sql(SQL_QUERIES[qname])._qe()
    on = qe_on.collect().to_pandas()
    assert qe_on.reorder_decisions is not None
    tpcds_session.conf.set(CBO_KEY, False)
    try:
        qe_off = tpcds_session.sql(SQL_QUERIES[qname])._qe()
        off = qe_off.collect().to_pandas()
        assert qe_off.reorder_decisions == []  # rule disabled: no log
    finally:
        tpcds_session.conf.set(CBO_KEY, True)
    pd.testing.assert_frame_equal(on, off)
    # a genuine SEQUENCE change (kind "order"), not just a probe/build
    # orientation flip
    changed = [d for d in qe_on.reorder_decisions
               if d["kind"] == "order"]
    assert changed, qe_on.reorder_decisions
    # the physical trees genuinely differ (the order change is not
    # just a log entry)
    assert qe_on.executed_plan.describe() != \
        qe_off.executed_plan.describe()
    # every decision carries the per-join estimates the explain /
    # history surfaces show
    assert all(len(d["est_rows"]) == len(d["order"]) - 1
               for d in qe_on.reorder_decisions)


def test_tpcds_reorder_explain_annotation(tpcds_session):
    qe = tpcds_session.sql(SQL_QUERIES["q19"])._qe()
    text = qe.explain()
    assert "== Join Reorder ==" in text
    assert "reorder: yes" in text
    assert "->" in text  # chosen order arrow
    # an unreordered single-join query reads "reorder: no"
    qe2 = tpcds_session.sql(
        "select count(*) as c from store_sales, date_dim "
        "where ss_sold_date_sk = d_date_sk")._qe()
    assert "reorder: no" in qe2.explain()


def test_tpcds_reorder_event_log_and_grading(tpcds_session, tmp_path):
    """Reorder decisions land in the event log (`reorder` record) and
    the cbo-reorder join estimates are graded by prediction_report."""
    from spark_tpu import history
    tpcds_session.conf.set("spark_tpu.sql.eventLog.dir", str(tmp_path))
    try:
        qe = tpcds_session.sql(SQL_QUERIES["q19"])._qe()
        qe.collect()
    finally:
        tpcds_session.conf.set("spark_tpu.sql.eventLog.dir", "")
    events = history.read_event_log(str(tmp_path))
    assert len(events) == 1
    reorder = events.iloc[0]["reorder"]
    assert reorder["enabled"] and reorder["changed"], reorder
    assert any(d["changed"] for d in reorder["regions"])
    graded = history.grade_predictions(qe.plan_predictions,
                                       qe.last_metrics)
    cbo = [g for g in graded if g["basis"] == "cbo-reorder"]
    assert cbo, graded
    report = history.prediction_report(events)
    assert (report["basis"] == "cbo-reorder").any(), report


def test_parquet_footer_stats(tpcds_path):
    """ParquetSource.column_stats: per-column min/max + null counts
    merged across row groups, cached, no row data touched."""
    from spark_tpu.io.sources import ParquetSource
    src = ParquetSource(os.path.join(tpcds_path, "store_sales.parquet"))
    stats = src.column_stats()
    q = stats["ss_quantity"]
    assert q["min"] == 1 and q["max"] == 100
    assert stats["ss_promo_sk"]["null_count"] > 0
    assert stats["ss_sold_date_sk"]["min"] >= 2450000
    assert src.column_stats() is stats  # cached


def test_reorder_selectivity_uses_footer_stats(tpcds_path):
    """Range selectivities interpolate against footer min/max instead
    of the flat default."""
    from spark_tpu.io.sources import ParquetSource
    from spark_tpu.plan.join_reorder import (SEL_RANGE,
                                             estimate_selectivity)
    from spark_tpu.functions import col, lit
    src = ParquetSource(os.path.join(tpcds_path, "store_sales.parquet"))
    stats = src.column_stats()
    low = estimate_selectivity((col("ss_quantity") <= lit(10)), stats)
    high = estimate_selectivity((col("ss_quantity") <= lit(90)), stats)
    assert low < SEL_RANGE < high
    # no stats for the column -> the flat default
    assert estimate_selectivity((col("nope") <= lit(10)), stats) \
        == SEL_RANGE


@pytest.mark.parametrize("qname", PLAN_SNAPSHOT)
def test_tpcds_plan_stability(tpcds_session, qname):
    """Plan fingerprints are stable across planner runs AND match the
    committed snapshot (the TPCDSQueryTestSuite plan-golden analog).
    Regenerate with SPARK_TPU_REGEN_TPCDS_PLANS=1 after an intended
    planner change."""
    a = tpcds_session.sql(SQL_QUERIES[qname])._qe().executed_plan \
        .describe()
    b = tpcds_session.sql(SQL_QUERIES[qname])._qe().executed_plan \
        .describe()
    assert a == b, f"{qname}: plan fingerprint unstable across runs"
    path = os.path.join(PLAN_DIR, f"{qname}.plan.txt")
    if os.environ.get("SPARK_TPU_REGEN_TPCDS_PLANS"):
        os.makedirs(PLAN_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(a + "\n")
    assert os.path.exists(path), \
        f"missing plan golden {path}; regenerate with " \
        f"SPARK_TPU_REGEN_TPCDS_PLANS=1"
    want = open(path).read().rstrip("\n")
    assert a == want, \
        f"{qname}: physical plan drifted from the committed golden " \
        f"(SPARK_TPU_REGEN_TPCDS_PLANS=1 to accept)"

"""Regression tests for the round-1 correctness findings (VERDICT.md
"What's weak" + ADVICE.md): dictionary-transform group-by, cross-dictionary
string joins/unions, multi-key packing, truncated %, decimal division,
USING-join column dedup, signed dense-domain group keys."""

import decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit


def test_substring_groupby_merges_colliding_codes(session):
    # round-1 bug: substr() rewrote the dictionary but left codes distinct,
    # so "aa1"/"aa2"/"aa3" grouped as three separate "aa" groups
    pdf = pd.DataFrame({"s": ["aa1", "aa2", "bb1", "aa3"],
                        "v": np.array([1, 2, 3, 4], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = (df.group_by(col("s").substr(1, 2).alias("p"))
           .agg(F.sum(col("v")).alias("sv"))
           .to_pandas().sort_values("p").reset_index(drop=True))
    assert list(out["p"]) == ["aa", "bb"]
    assert list(out["sv"]) == [7, 3]


def test_string_join_different_dictionaries(session):
    # left and right encode strings independently: code equality is
    # meaningless without unification (ADVICE high-severity)
    left = session.create_dataframe(pd.DataFrame({
        "k": ["apple", "banana", "cherry"],
        "lv": np.array([1, 2, 3], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": ["cherry", "apple"],  # reversed insertion order -> codes differ
        "rv": np.array([30, 10], dtype=np.int64)}))
    out = (left.join(right, on="k")
           .to_pandas().sort_values("k").reset_index(drop=True))
    assert list(out["k"]) == ["apple", "cherry"]
    assert list(out["lv"]) == [1, 3]
    assert list(out["rv"]) == [10, 30]


def test_union_string_dictionaries(session):
    a = session.create_dataframe(pd.DataFrame({"s": ["x", "y"]}))
    b = session.create_dataframe(pd.DataFrame({"s": ["z", "x"]}))
    out = sorted(a.union(b).collect().column("s").to_pylist())
    assert out == ["x", "x", "y", "z"]


def test_multi_key_join_wide_keys(session):
    # two int64 keys cannot pack into 64 bits: the hashed path must
    # re-verify true equality (round-1: silent 32-bit truncation collided)
    k1 = np.array([1 << 40, (1 << 40) + 1, 5], dtype=np.int64)
    k2 = np.array([7, 7, 8], dtype=np.int64)
    left = session.create_dataframe(pd.DataFrame(
        {"a": k1, "b": k2, "lv": np.array([1, 2, 3], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame(
        {"a": k1[:2], "b": k2[:2], "rv": np.array([10, 20], dtype=np.int64)}))
    out = (left.join(right, on=["a", "b"])
           .to_pandas().sort_values("lv").reset_index(drop=True))
    assert list(out["lv"]) == [1, 2]
    assert list(out["rv"]) == [10, 20]


def test_multi_key_join_colliding_low_words(session):
    # round-1 bug: keys masked to low 32 bits -> (2^33+5, x) joined (5, x)
    left = session.create_dataframe(pd.DataFrame({
        "a": np.array([(1 << 33) + 5], dtype=np.int64),
        "b": np.array([1], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "a": np.array([5], dtype=np.int64),
        "b": np.array([1], dtype=np.int64),
        "rv": np.array([99], dtype=np.int64)}))
    out = left.join(right, on=["a", "b"]).to_pandas()
    assert len(out) == 0


def test_division_by_zero_is_null(session):
    pdf = pd.DataFrame({"a": np.array([10.0, 20.0]),
                        "b": np.array([2.0, 0.0])})
    df = session.create_dataframe(pdf)
    out = df.select((col("a") / col("b")).alias("q")).to_pandas()
    assert out["q"][0] == 5.0
    assert pd.isna(out["q"][1])
    # integer % 0 is NULL too
    pdf2 = pd.DataFrame({"a": np.array([10, 20], dtype=np.int64),
                         "b": np.array([3, 0], dtype=np.int64)})
    out2 = (session.create_dataframe(pdf2)
            .select((col("a") % col("b")).alias("m")).to_pandas())
    assert out2["m"][0] == 1
    assert pd.isna(out2["m"][1])


def test_decimal_division_returns_decimal(session):
    t = pa.table({
        "a": pa.array([decimal.Decimal("10.00"), decimal.Decimal("1.00")],
                      type=pa.decimal128(10, 2)),
        "b": pa.array([decimal.Decimal("4.00"), decimal.Decimal("3.00")],
                      type=pa.decimal128(10, 2))})
    df = session.create_dataframe(t)
    qt = df.select((col("a") / col("b")).alias("q"))
    import spark_tpu.types as T
    assert isinstance(qt.schema.fields[0].dtype, T.DecimalType)
    out = qt.collect().column("q").to_pylist()
    assert out[0] == decimal.Decimal("2.5")
    assert abs(float(out[1]) - 1 / 3) < 1e-6


def test_using_join_drops_right_key(session):
    left = session.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2], dtype=np.int64),
        "lv": np.array([1, 2], dtype=np.int64)}))
    right = session.create_dataframe(pd.DataFrame({
        "k": np.array([2], dtype=np.int64),
        "rv": np.array([20], dtype=np.int64)}))
    out = left.join(right, on="k")
    assert out.columns == ["k", "lv", "rv"]  # no k_r leak


def test_groupby_negative_mod_keys(session):
    # truncated % yields negative keys; dense-domain path must not merge
    # them into slot 0
    pdf = pd.DataFrame({"x": np.array([-7, -4, -1, 1, 4, 7], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = (df.group_by((col("x") % lit(3)).alias("k"))
           .agg(F.count().alias("c"))
           .to_pandas().sort_values("k").reset_index(drop=True))
    assert list(out["k"]) == [-1, 1]
    assert list(out["c"]) == [3, 3]


def test_groupby_negative_bytes(session):
    t = pa.table({"b": pa.array([-128, -1, 0, 127, -1], type=pa.int8()),
                  "v": pa.array([1, 2, 3, 4, 5], type=pa.int64())})
    df = session.create_dataframe(t)
    out = (df.group_by(col("b")).agg(F.sum(col("v")).alias("s"))
           .to_pandas().sort_values("b").reset_index(drop=True))
    assert list(out["b"]) == [-128, -1, 0, 127]
    assert list(out["s"]) == [1, 7, 3, 4]


def test_final_merge_kernel_full_width():
    """Round-3 ADVICE high: the MXU kernel bounded per-row limbs by
    AccSpec.width in ALL modes, but final-mode contributions are partial
    accumulators (counts in the thousands with width=8) — counts came
    back mod 256. merge=True must force full 64-bit limbs."""
    import jax.numpy as jnp
    from spark_tpu.execution import aggregate as K
    from spark_tpu.expr import Vec
    from spark_tpu.expr_agg import AccSpec
    import spark_tpu.types as T

    n = 160  # > the kernel's small-input gate when matmul is forced
    keys = Vec(jnp.arange(n, dtype=jnp.int64) % 4, T.LONG, None, None)
    specs = [[AccSpec("count", np.dtype(np.int64), "sum", width=8)],
             [AccSpec("sum", np.dtype(np.int64), "sum", width=16)]]
    # partial counts of 1000 (> 2^8) and partial sums of 1<<40 (> 2^16)
    contribs = [[jnp.full((n,), 1000, jnp.int64)],
                [jnp.full((n,), 1 << 40, jnp.int64)]]
    domains = [(4, 0)]
    spans = [4]
    _, _, accs, _ = K.direct_aggregate(
        [keys], domains, spans, contribs, specs, None,
        kernel_mode="matmul", merge=True)
    assert np.asarray(accs[0][0]).tolist() == [1000 * 40] * 4
    assert np.asarray(accs[1][0]).tolist() == [(1 << 40) * 40] * 4


def test_two_phase_mesh_agg_forced_matmul(session):
    """End-to-end: a distributed two-phase aggregate with the Pallas
    kernel forced (interpret mode on CPU) must match the single-chip
    scatter result — >256 rows per group per shard so a width-bounded
    merge would truncate."""
    mesh_key = "spark_tpu.sql.mesh.size"
    kern_key = "spark_tpu.sql.aggregate.kernelMode"
    n = 40_000  # 5 groups -> 8000 rows/group, ~1000/group/shard
    build = lambda: (session.range(n)
                     .group_by((col("id") % 5).alias("k"))
                     .agg(F.count().alias("c"), F.sum(col("id")).alias("s")))
    want = build().to_pandas().sort_values("k").reset_index(drop=True)
    try:
        session.conf.set(mesh_key, 8)
        session.conf.set(kern_key, "matmul")
        got = build().to_pandas().sort_values("k").reset_index(drop=True)
    finally:
        session.conf.set(mesh_key, 0)
        session.conf.set(kern_key, "auto")
    assert got["c"].tolist() == want["c"].tolist() == [8000] * 5
    assert got["s"].tolist() == want["s"].tolist()


def test_prune_columns_preserves_join_renames(session):
    """Plan-level: pruning must not change join output names — the
    colliding left column that forced an `_r` suffix stays alive
    (code-review: chained `x`/`x_r` collisions included)."""
    import pandas as pd
    from spark_tpu.functions import col
    from spark_tpu.plan.logical import Join, Project, Scan
    from spark_tpu.plan.optimizer import PruneColumns

    left = pd.DataFrame({"k": [1, 2], "x": [10, 20], "x_r": [5, 6]})
    right = pd.DataFrame({"k": [1, 2], "x": [7, 8]})
    df = (session.create_dataframe(left, "pl")
          .join(session.create_dataframe(right, "pr"),
                left_on=col("k"), right_on=col("k")))
    # right `x` collides twice -> x_r_r
    assert "x_r_r" in df.plan.schema().names
    pruned = PruneColumns().apply(
        Project(df.plan, [col("x_r_r")]))
    # output name still resolves after pruning
    assert pruned.schema().names == ["x_r_r"]
    got = (session.create_dataframe(left, "pl2")
           .join(session.create_dataframe(right, "pr2"),
                 left_on=col("k"), right_on=col("k"))
           .select(col("x_r_r")).to_pandas())
    assert got["x_r_r"].tolist() == [7, 8]


def test_first_merge_does_not_fabricate_values():
    """Round-4 ADVICE high: First packs (pos<<33|isnull<<32|word) per
    32-bit word under independent min reduces; when two merged updates
    tie on in-chunk position, the two word accumulators of a 64-bit
    value could each pick a DIFFERENT row — e.g. merging (2<<32)|1 and
    (1<<32)|5 at the same position returned (1<<32)|1, a value present
    in no input row. Globally unique row bases must make one genuine
    row win all words."""
    import jax.numpy as jnp
    from spark_tpu.columnar import Batch, Column
    from spark_tpu.expr import ColumnRef
    from spark_tpu.expr_agg import First
    import spark_tpu.types as T

    v1, v2 = (2 << 32) | 1, (1 << 32) | 5
    f = First(ColumnRef("x"))

    def one_row(v):
        return Batch({"x": Column(jnp.asarray([v], jnp.int64), T.LONG)},
                     jnp.asarray([True]))

    schema = one_row(v1).schema()
    u1 = f.update(one_row(v1), None, row_base=0)
    u2 = f.update(one_row(v2), None, row_base=1)  # a later chunk
    merged = [np.minimum(np.asarray(a), np.asarray(b))
              for a, b in zip(u1[:-1], u2[:-1])]
    merged.append(np.asarray(u1[-1]) + np.asarray(u2[-1]))
    val, valid = f.finalize(merged, schema)
    assert bool(valid[0])
    assert int(val[0]) == v1  # the smaller global position, verbatim


def test_first_mesh_merge_picks_genuine_rows(session):
    """End-to-end on the 8-device mesh: the partial/final split merges
    per-shard First accumulators whose in-shard positions all restart at
    0 — without globally unique row bases the final min-merge combined
    shard 0's low word with shard 1's high word, returning 4294967297
    ((1<<32)|1), a value present in no input row."""
    mesh_key = "spark_tpu.sql.mesh.size"
    v1, v2 = (2 << 32) | 1, (1 << 32) | 5
    n = 4096
    x = np.full(n, v2, np.int64)
    x[:512] = v1  # shard 0 holds the v1 rows; shards 1..7 hold v2
    pdf = pd.DataFrame({"k": np.zeros(n, np.int64), "x": x})
    session.register_table("first_mesh", pdf)
    try:
        session.conf.set(mesh_key, 8)
        out = (session.table("first_mesh").group_by(col("k"))
               .agg(F.first(col("x")).alias("f"),
                    F.last(col("x")).alias("l"))
               .to_pandas())
    finally:
        session.conf.set(mesh_key, 0)
    assert int(out["f"][0]) in (v1, v2)
    assert int(out["l"][0]) in (v1, v2)

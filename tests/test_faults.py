"""Chaos suite: deterministic fault injection (spark_tpu/testing/faults.py)
against the executor's failure taxonomy and degradation ladder
(spark_tpu/execution/failures.py).

Every injected fault class — RESOURCE_EXHAUSTED, UNAVAILABLE, stage
timeout, mesh failure — must be recovered or cleanly degraded with
TPC-H Q1/Q3 result parity against the independent pandas goldens, and
the recovery path must be visible in the fault_summary metrics."""

import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.execution.failures import (FailureClass, RetryPolicy,
                                          StageOOMError, StageTimeoutError,
                                          classify, is_mesh_failure)
from spark_tpu.testing import faults
from spark_tpu.testing.faults import FaultInjected, FaultPlan
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
MESH_KEY = "spark_tpu.sql.mesh.size"
BACKOFF_KEY = "spark_tpu.execution.backoffMs"
RETRIES_KEY = "spark_tpu.execution.maxRetries"
TIMEOUT_KEY = "spark_tpu.execution.stageTimeoutMs"


@pytest.fixture(scope="session")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_faults") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


@pytest.fixture(autouse=True)
def fast_backoff(tpch_session):
    """Millisecond backoffs + a disarmed plan around every test."""
    tpch_session.conf.set(BACKOFF_KEY, 1)
    faults.reset()
    yield
    faults.reset()


def _cold(session):
    """Drop compiled stages + device-resident tables so trace-time
    injection sites (shuffle/join_build/mesh) deterministically fire on
    a fresh compile, and scan_load actually ingests."""
    from spark_tpu.io.device_cache import CACHE
    session._stage_cache.clear()
    session._aqe_caps.clear()
    CACHE.clear()


def _run_query(session, qname):
    """Execute through a QueryExecution (so fault_summary is
    inspectable) and return (normalized pandas, qe)."""
    df = Q.QUERIES[qname](session)
    qe = df._qe()
    table = qe.collect()
    got = G.normalize_decimals(table.to_pandas()).reset_index(drop=True)
    return got, qe


def _check_golden(got, tpch_path, qname):
    G.compare(got, G.GOLDEN[qname](tpch_path))


# -- spec parsing / plan mechanics -------------------------------------------

def test_spec_parse_and_fire_once():
    with faults.scoped_site("s"):
        plan = FaultPlan("s:unavailable:2,s:fatal:3")
        plan.fire("s")  # hit 1: below nth
        with pytest.raises(FaultInjected, match="UNAVAILABLE"):
            plan.fire("s")  # hit 2
        with pytest.raises(FaultInjected, match="INTERNAL"):
            plan.fire("s")  # hit 3: second rule
        plan.fire("s")  # hit 4: both rules spent
        assert plan.fired_log == [("s", 2, "unavailable"),
                                  ("s", 3, "fatal")]
        assert plan.hits["s"] == 4


def test_spec_sites_independent():
    with faults.scoped_site("a"), faults.scoped_site("b"):
        plan = FaultPlan("a:deadline:1")
        plan.fire("b")  # other sites never interfere
        with pytest.raises(FaultInjected, match="DEADLINE_EXCEEDED"):
            plan.fire("a")


@pytest.mark.parametrize("bad", ["scan_load:resource_exhausted",
                                 "scan_load:nope:1",
                                 "scan_load:slow:0", "justasite"])
def test_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        FaultPlan(bad)


def test_spec_rejects_unknown_site():
    """The PR-4 satellite bug: a typo'd site (`stage_rnu`) used to parse
    fine and then silently never fire — the chaos test tested nothing.
    Parse-time validation against the wired-seam registry makes the
    typo loud."""
    typo = "stage_rnu"  # f-strings below keep the deliberate typo
    # invisible to the fault-site lint pass (static literals only)
    with pytest.raises(ValueError, match="unknown fault site 'stage_rnu'"):
        FaultPlan(f"{typo}:fatal:1")
    # conf-driven arming goes through the same parser
    from spark_tpu.config import Conf
    conf = Conf()
    conf.set(faults.INJECT_KEY, f"shuffle:unavailable:1,{typo}:fatal:1")
    faults.reset()
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.arm(conf)
    faults.reset()
    # scoped_site opens an ad-hoc seam for test-planted fire() points,
    # and closes it again: a leaked registration would re-open the
    # silent-no-fire hole for the rest of the process
    with faults.scoped_site("my_test_seam"):
        plan = FaultPlan("my_test_seam:fatal:1")
        with pytest.raises(FaultInjected, match="INTERNAL"):
            plan.fire("my_test_seam")
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan("my_test_seam:fatal:1")  # registration is gone


def test_inject_context_restores(tpch_session):
    conf = tpch_session.conf
    with faults.inject(conf, "scan_load:fatal:1") as plan:
        assert faults.active() is plan
        assert conf.get(faults.INJECT_KEY) == "scan_load:fatal:1"
    assert faults.active() is None
    assert conf.get(faults.INJECT_KEY) == ""


def test_classify_taxonomy():
    assert classify(RuntimeError("RESOURCE_EXHAUSTED: oom")) \
        is FailureClass.OOM
    assert classify(RuntimeError("UNAVAILABLE: conn")) \
        is FailureClass.TRANSIENT
    assert classify(RuntimeError("INTERNAL: remote_compile 500")) \
        is FailureClass.TRANSIENT
    assert classify(StageTimeoutError("slow")) is FailureClass.TIMEOUT
    assert classify(ValueError("bad plan")) is FailureClass.FATAL
    assert classify(MemoryError()) is FailureClass.OOM
    assert is_mesh_failure(RuntimeError("shard_map lowering failed"))
    assert not is_mesh_failure(RuntimeError("UNAVAILABLE: conn"))


def test_retry_policy_backoff_exponential_jittered():
    slept = []
    p = RetryPolicy(3, 100.0, sleep=lambda s: slept.append(s * 1e3))
    d0, d1, d2 = (p.attempt_retry() for _ in range(3))
    assert p.attempt_retry() is None  # budget spent
    assert 50 <= d0 <= 100 and 100 <= d1 <= 200 and 200 <= d2 <= 400
    # approx, not ==: the sleep callback sees seconds (ms / 1e3) and
    # re-scales, which round-trips with an ULP of error for ~1 in 4
    # jitter draws — exact equality made this test flaky
    assert slept == [pytest.approx(d) for d in (d0, d1, d2)]
    assert p.total_sleep_ms == pytest.approx(d0 + d1 + d2)


# -- recovery with TPC-H golden parity per fault class -----------------------

#: (site rules, fault_summary action asserted)
_SCENARIOS = [
    ("stage_run:unavailable:1", "transient_retry"),
    ("scan_load:unavailable:1", "transient_retry"),
    ("stage_run:resource_exhausted:1", "oom_cache_evict"),
    ("stage_run:resource_exhausted:1,stage_run:resource_exhausted:2",
     "oom_spill_reroute"),
]


@pytest.mark.parametrize("qname", ["q1", "q3"])
@pytest.mark.parametrize("spec,action", _SCENARIOS)
def test_recovery_parity(tpch_session, tpch_path, qname, spec, action):
    _cold(tpch_session)
    with faults.inject(tpch_session.conf, spec) as plan:
        got, qe = _run_query(tpch_session, qname)
        assert plan.fired_log, "fault never fired — scenario is vacuous"
    assert qe.fault_summary.get(action, 0) >= 1, qe.fault_summary
    _check_golden(got, tpch_path, qname)


def test_join_build_fault_recovers_q3(tpch_session, tpch_path):
    _cold(tpch_session)
    with faults.inject(tpch_session.conf,
                       "join_build:unavailable:1") as plan:
        got, qe = _run_query(tpch_session, "q3")
        assert plan.fired_log, "join_build site never fired"
    assert qe.fault_summary.get("transient_retry", 0) >= 1
    _check_golden(got, tpch_path, "q3")


def test_stage_timeout_retry_parity(tpch_session, tpch_path):
    """An injected slow stage blows stageTimeoutMs once; the retry (the
    compiled entry is kept — only the flake was slow) succeeds."""
    conf = tpch_session.conf
    _run_query(tpch_session, "q1")  # warm compile: the deadline bounds
    conf.set(TIMEOUT_KEY, 2000)     # run+sync, not cold XLA compiles
    try:
        with faults.inject(conf, "stage_run:slow:1:4000") as plan:
            got, qe = _run_query(tpch_session, "q1")
            assert plan.fired_log == [("stage_run", 1, "slow")]
    finally:
        conf.set(TIMEOUT_KEY, 0)
    assert qe.fault_summary.get("stage_timeout", 0) >= 1, qe.fault_summary
    _check_golden(got, tpch_path, "q1")


@pytest.mark.parametrize("qname", ["q1", "q3"])
def test_mesh_failure_falls_back_single_device(tpch_session, tpch_path,
                                               qname):
    """A fault in the mesh/shard_map path re-plans single-device: the
    degraded run must still hit golden parity and flag mesh_fallback.
    Gang restart (the elastic rung that would now win first — see
    tests/test_elastic.py) is disabled to pin the fallback rung."""
    _cold(tpch_session)
    tpch_session.conf.set("spark_tpu.execution.meshRestart.enabled",
                          False)
    tpch_session.conf.set(MESH_KEY, 8)
    try:
        with faults.inject(tpch_session.conf, "mesh:fatal:1") as plan:
            got, qe = _run_query(tpch_session, qname)
            assert plan.fired_log == [("mesh", 1, "fatal")]
    finally:
        tpch_session.conf.set(MESH_KEY, 0)
    assert qe.fault_summary.get("mesh_fallback", 0) == 1, qe.fault_summary
    assert qe.last_metrics.get("mesh_fallback") == 1
    _check_golden(got, tpch_path, qname)


def test_mesh_misconfiguration_surfaces(tpch_session):
    """get_mesh's 'mesh.size=N but only M devices visible' diagnostic is
    a pre-dispatch setup error, not a collective failure: it must
    surface with its remediation hint, not silently degrade the run to
    single-device via the mesh fallback."""
    conf = tpch_session.conf
    conf.set(MESH_KEY, 64)  # more than the 8 virtual CPU devices
    try:
        with pytest.raises(RuntimeError, match="devices visible"):
            tpch_session.range(100).agg(
                F.sum(col("id")).alias("s")).collect()
    finally:
        conf.set(MESH_KEY, 0)


def test_mesh_fallback_disabled_surfaces(tpch_session):
    """With BOTH elastic rungs off (no restart, no degrade), a fatal
    mesh failure surfaces unchanged. meshFallback.enabled=false alone
    no longer disables gang restarts — each rung has its own conf."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(MESH_KEY, 8)
    conf.set("spark_tpu.execution.meshRestart.enabled", False)
    conf.set("spark_tpu.execution.meshFallback.enabled", False)
    try:
        with faults.inject(conf, "mesh:fatal:1"):
            with pytest.raises(FaultInjected, match="INTERNAL"):
                _run_query(tpch_session, "q1")
    finally:
        conf.set(MESH_KEY, 0)
        conf.set("spark_tpu.execution.meshFallback.enabled", True)


def test_shuffle_fault_retries_under_mesh(tpch_session, tpch_path):
    """A trace-time fault inside the collective exchange retries with a
    fresh compile (the stage entry is dropped, so the site re-fires its
    next hit and passes)."""
    _cold(tpch_session)
    tpch_session.conf.set(MESH_KEY, 8)
    try:
        with faults.inject(tpch_session.conf,
                           "shuffle:unavailable:1") as plan:
            got, qe = _run_query(tpch_session, "q1")
            assert plan.fired_log, "no exchange lowered — vacuous"
    finally:
        tpch_session.conf.set(MESH_KEY, 0)
    assert qe.fault_summary.get("transient_retry", 0) >= 1
    _check_golden(got, tpch_path, "q1")


# -- budget exhaustion / ladder bottom ---------------------------------------

def test_transient_budget_exhausted_surfaces(tpch_session):
    conf = tpch_session.conf
    conf.set(RETRIES_KEY, 1)
    try:
        with faults.inject(conf, "stage_run:unavailable:1,"
                                 "stage_run:unavailable:2"):
            with pytest.raises(FaultInjected, match="UNAVAILABLE"):
                tpch_session.range(1000).agg(
                    F.sum(col("id")).alias("s")).collect()
    finally:
        conf.set(RETRIES_KEY, 3)


def test_oom_ladder_exhausted_diagnostic(tpch_session):
    """Three OOMs burn every rung; the terminal error names the stage
    and its capacity stats (issue acceptance: a diagnostic, not a bare
    XLA error)."""
    spec = ",".join(f"stage_run:resource_exhausted:{n}" for n in (1, 2, 3))
    with faults.inject(tpch_session.conf, spec):
        with pytest.raises(StageOOMError) as ei:
            tpch_session.range(1000).agg(
                F.sum(col("id")).alias("s")).collect()
    msg = str(ei.value)
    assert "degradation ladder" in msg
    assert "stage:" in msg and "capacity stats" in msg


def test_legacy_max_task_failures_still_honored(tpch_session):
    """spark_tpu.sql.execution.maxTaskFailures, when explicitly set,
    overrides the new maxRetries key (deprecated alias)."""
    conf = tpch_session.conf
    conf.set("spark_tpu.sql.execution.maxTaskFailures", 0)
    try:
        with faults.inject(conf, "stage_run:unavailable:1"):
            with pytest.raises(FaultInjected, match="UNAVAILABLE"):
                tpch_session.range(100).agg(
                    F.sum(col("id")).alias("s")).collect()
    finally:
        conf.unset("spark_tpu.sql.execution.maxTaskFailures")


# -- observability ------------------------------------------------------------

def test_fault_summary_reaches_history(tpch_session, tmp_path):
    from spark_tpu import history
    log_dir = str(tmp_path / "events")
    conf = tpch_session.conf
    conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    try:
        with faults.inject(conf, "stage_run:unavailable:1,"
                                 "stage_run:resource_exhausted:2"):
            df = tpch_session.range(10000).group_by(
                (col("id") % 7).alias("k")).agg(
                F.sum(col("id")).alias("s"))
            out = df.to_pandas().sort_values("k").reset_index(drop=True)
    finally:
        conf.set("spark_tpu.sql.eventLog.dir", "")
    assert out["s"].sum() == sum(range(10000))
    events = history.read_event_log(log_dir)
    summary = history.fault_summary(events)
    assert len(summary) >= 1, events.columns
    row = summary.iloc[-1]
    assert row["transient_retry"] >= 1
    assert row["oom_cache_evict"] >= 1
    assert row["retry_backoff_ms"] > 0
    assert any(ev.get("action") == "transient_retry"
               for ev in row["events"])


def test_fault_free_run_logs_no_summary(tpch_session, tmp_path):
    from spark_tpu import history
    log_dir = str(tmp_path / "events_clean")
    conf = tpch_session.conf
    conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    try:
        tpch_session.range(100).agg(F.sum(col("id")).alias("s")).collect()
    finally:
        conf.set("spark_tpu.sql.eventLog.dir", "")
    events = history.read_event_log(log_dir)
    assert len(events) >= 1
    assert history.fault_summary(events).empty
"""Static-analysis suite: the pre-compile plan/jaxpr analyzer
(spark_tpu/analysis/) and the unified source-lint framework
(spark_tpu/analysis/lints + scripts/lint.py).

Analyzer contract under test: each finding category fires on a
seeded-violation plan, strict mode raises BEFORE any compile, TPC-H
Q1/Q3 goldens are byte-identical with the analyzer on, and the real
TPC-H plans produce ZERO findings (the noise gate). Framework contract:
every lint pass catches a synthetic violation and passes on the real
tree."""

import decimal
import os

import pyarrow as pa
import pytest

import jax

from spark_tpu import functions as F
from spark_tpu import types as T
from spark_tpu.analysis import (AnalysisFindingError, FINDING_CODES,
                                Finding, analyze_plan)
from spark_tpu.functions import col, udf
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.001
ENABLED_KEY = "spark_tpu.sql.analysis.enabled"
STRICT_KEY = "spark_tpu.sql.analysis.strict"
JAXPR_KEY = "spark_tpu.sql.analysis.jaxpr"
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
MESH_KEY = "spark_tpu.sql.mesh.size"


@pytest.fixture(scope="session")
def tpch_session(session, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_analysis") / "sf")
    write_parquet(path, SF)
    Q.register_tables(session, path)
    session._tpch_analysis_path = path
    return session


def _codes(findings):
    return [f.code for f in (findings or [])]


# -- finding registry ---------------------------------------------------------

def test_finding_codes_closed_registry():
    with pytest.raises(ValueError, match="unknown finding code"):
        Finding("MADE_UP", "nope")
    f = Finding("SUM_I64_OVERFLOW", "msg", op="x")
    assert f.category == "dtype-overflow" and f.severity == "error"
    d = f.to_dict()
    assert d["code"] == "SUM_I64_OVERFLOW" and d["severity"] == "error"
    # every registered code carries (category, severity, doc)
    for code, (cat, sev, doc) in FINDING_CODES.items():
        assert sev in ("error", "warn", "info"), code
        assert doc, code


# -- dtype-overflow -----------------------------------------------------------

def _overflow_plan(session):
    """int32 sum over a lazily-planned 2^33-row range: 33 rows-bits +
    31 value-bits > 63 accumulator bits. Never executed — Range is
    synthesized in-trace, so planning/analysis touch no data."""
    return (session.range(1 << 33)
            .select(col("id").cast(T.INT).alias("v"))
            .agg(F.sum(col("v")).alias("s")))


def test_overflow_finding_int32_plan(session):
    qe = _overflow_plan(session)._qe()
    findings = analyze_plan(qe.executed_plan, session.conf, 1)
    hits = [f for f in findings if f.code == "SUM_I64_OVERFLOW"]
    assert hits, findings
    assert hits[0].severity == "error"
    assert hits[0].detail["required_bits"] > hits[0].detail["acc_bits"]


def test_overflow_finding_decimal_executes(session):
    """decimal(18,0) values near the type max: ~60 value bits (by
    dtype AND by the source's actual min/max stats), 16 rows ->
    64 > 63. Execution still succeeds (non-strict): the finding is
    advisory and lands on the QueryExecution. Values must GENUINELY
    overflow since the footer/in-memory stats tightening: tiny values
    in a wide decimal no longer flag (that false positive is exactly
    what the stats bound removes — see the suppression test below)."""
    vals = [decimal.Decimal(9 * 10**17 + i) for i in range(16)]
    table = pa.table({"d": pa.array(vals, type=pa.decimal128(18, 0))})
    session.register_table("ana_dec", table)
    qe = session.table("ana_dec").agg(F.sum(col("d")).alias("s"))._qe()
    out = qe.collect()
    assert out.num_rows == 1
    assert "SUM_I64_OVERFLOW" in _codes(qe.analysis_findings)


def test_overflow_suppressed_by_column_stats(session):
    """Small actual values in a wide decimal: the dtype alone says 60
    bits (finding), the source min/max says 4 bits (no finding). The
    stats bound wins — and turning stats off restores the dtype-only
    verdict, so the suppression is attributable."""
    vals = [decimal.Decimal(i) for i in range(16)]
    table = pa.table({"d": pa.array(vals, type=pa.decimal128(18, 0))})
    session.register_table("ana_dec_small", table)
    qe = session.table("ana_dec_small") \
        .agg(F.sum(col("d")).alias("s"))._qe()
    qe.collect()
    assert "SUM_I64_OVERFLOW" not in _codes(qe.analysis_findings)
    session.conf.set("spark_tpu.sql.stats.parquetFooter", False)
    try:
        qe2 = session.table("ana_dec_small") \
            .agg(F.sum(col("d")).alias("s"))._qe()
        qe2.collect()
        assert "SUM_I64_OVERFLOW" in _codes(qe2.analysis_findings)
    finally:
        session.conf.set("spark_tpu.sql.stats.parquetFooter", True)


def test_no_overflow_on_bounded_sum(session):
    # pmod bounds the value statically: 16 rows x 2^8 stays tiny
    qe = (session.range(16)
          .select(F.pmod(col("id"), 256).alias("k"))
          .agg(F.sum(col("k")).alias("s")))._qe()
    qe.collect()
    assert _codes(qe.analysis_findings) == []


def test_strict_raises_before_compile(session):
    session.conf.set(STRICT_KEY, "true")
    session._stage_cache.clear()
    with pytest.raises(AnalysisFindingError) as ei:
        _overflow_plan(session)._qe().execute_batch()
    assert "SUM_I64_OVERFLOW" in [f.code for f in ei.value.findings]
    # pre-compile: nothing was jitted, no device work happened
    assert session._stage_cache == {}


def test_strict_ignores_warn_findings(session):
    session.conf.set(STRICT_KEY, "true")
    session.conf.set(CHUNK_KEY, 1 << 10)
    df = (session.range(1 << 12)
          .select(F.pmod(col("id"), 64).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))
    qe = df._qe()
    out = qe.collect()  # STREAMING_HOST_SYNC is warn-severity: no raise
    assert out.num_rows == 64
    assert "STREAMING_HOST_SYNC" in _codes(qe.analysis_findings)


# -- host-sync ----------------------------------------------------------------

def test_streaming_host_sync_finding(session):
    session.conf.set(CHUNK_KEY, 1 << 10)
    qe = (session.range(1 << 12)
          .select(F.pmod(col("id"), 64).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))._qe()
    qe.collect()
    hits = [f for f in qe.analysis_findings
            if f.code == "STREAMING_HOST_SYNC"]
    assert hits and hits[0].detail["chunks"] >= 4
    assert hits[0].severity == "warn"


def test_spill_host_sync_finding_external_path(session, tmp_path):
    """deviceBudget reroutes collect() through the out-of-core external
    path, which never reaches execute_batch — the analyzer must still
    run (and find the spill) there."""
    import pandas as pd
    pd.DataFrame({"v": range(4096)}).to_parquet(tmp_path / "t.parquet")
    df = session.read_parquet(str(tmp_path / "t.parquet"))
    session.conf.set("spark_tpu.sql.memory.deviceBudget", 1024)
    try:
        qe = df._qe()
        out = qe.collect()
        assert out.num_rows == 4096
        assert "SPILL_HOST_SYNC" in _codes(qe.analysis_findings)
        assert "external" in qe.phase_times  # really took the path
    finally:
        session.conf.set("spark_tpu.sql.memory.deviceBudget", 0)


def test_no_duplicate_findings_on_dag_shared_scans(tpch_session):
    """A runtime filter's creation chain shares its scan leaf with the
    join build side (the tree is a DAG): each shared node must be
    analyzed once, not once per path — duplicates would inflate the
    bench sidecar and the event log."""
    session = tpch_session
    session.conf.set("spark_tpu.sql.memory.deviceBudget", 1024)
    try:
        qe = Q.QUERIES["q3"](session)._qe()
        findings = analyze_plan(qe.executed_plan, session.conf, 1)
        spills = [f for f in findings if f.code == "SPILL_HOST_SYNC"]
        ops = [f.op for f in spills]
        assert spills and len(ops) == len(set(ops)), ops
    finally:
        session.conf.set("spark_tpu.sql.memory.deviceBudget", 0)


def test_udf_host_roundtrip_finding(session):
    import pandas as pd
    session.register_table("ana_udf", pd.DataFrame({"v": [1.0, 2.0]}))
    plus = udf(lambda v: v + 1.0, "double")
    qe = session.table("ana_udf").select(plus(col("v")).alias("w"))._qe()
    qe.collect()
    assert "UDF_HOST_ROUNDTRIP" in _codes(qe.analysis_findings)


# -- recompile ----------------------------------------------------------------

def test_recompile_clean_on_real_plans(tpch_session):
    """The shipped planner buckets every capacity it bakes into stage
    keys — the analyzer (which flags exactly what a raw row count used
    to cause) must be silent on real TPC-H plans."""
    for qname in ("q1", "q3"):
        qe = Q.QUERIES[qname](tpch_session)._qe()
        findings = analyze_plan(qe.executed_plan, tpch_session.conf, 1)
        assert [f for f in findings if f.category == "recompile"] == []


def test_recompile_finding_seeded(session):
    import spark_tpu.plan.physical as P
    qe = (session.range(1 << 12)
          .select(F.pmod(col("id"), 64).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))._qe()
    root = qe.executed_plan

    def seed(n):
        if isinstance(n, P.HashAggregateExec):
            n.est_groups = 1000  # raw row count, the pre-PR-4 shape
        for c in n.children:
            seed(c)

    seed(root)
    findings = analyze_plan(root, session.conf, 1)
    hits = [f for f in findings if f.code == "UNBUCKETED_CAPACITY"]
    assert hits and hits[0].detail == {
        "kind": "aggregate.est_groups", "value": 1000, "bucketed": 1024}


# -- mesh ---------------------------------------------------------------------

def test_mesh_replication_finding(tpch_session):
    import pandas as pd
    session = tpch_session
    session.conf.set(MESH_KEY, 8)
    try:
        left = session.create_dataframe(
            pd.DataFrame({"k": list(range(2000)),
                          "v": list(range(2000))}), "ana_l")
        right = session.create_dataframe(
            pd.DataFrame({"k": list(range(10)),
                          "n": list(range(10))}), "ana_r")
        qe = left.join(right, on="k", how="inner")._qe()
        findings = analyze_plan(qe.executed_plan, session.conf, 8)
        hits = [f for f in findings
                if f.code == "MESH_FULL_REPLICATION"]
        assert hits, findings  # broadcast build side under the mesh
        assert hits[0].detail["mesh_n"] == 8
    finally:
        session.conf.set(MESH_KEY, 0)


def test_mesh_jaxpr_all_gather_finding(session):
    import pandas as pd
    session.conf.set(MESH_KEY, 8)
    session.conf.set(JAXPR_KEY, "on")
    try:
        left = session.create_dataframe(
            pd.DataFrame({"k": list(range(160)),
                          "v": list(range(160))}), "ana_jl")
        right = session.create_dataframe(
            pd.DataFrame({"k": list(range(8)),
                          "n": list(range(8))}), "ana_jr")
        qe = left.join(right, on="k", how="inner")._qe()
        out = qe.collect()
        assert out.num_rows == 8
        codes = _codes(qe.analysis_findings)
        assert "JAXPR_ALL_GATHER" in codes, codes
    finally:
        session.conf.set(MESH_KEY, 0)


# -- x64 ----------------------------------------------------------------------

def test_x64_truncation_finding(session):
    qe = session.range(128).agg(F.sum(col("id")).alias("s"))._qe()
    root = qe.executed_plan
    jax.config.update("jax_enable_x64", False)
    try:
        findings = analyze_plan(root, session.conf, 1)
        hits = [f for f in findings if f.code == "X64_TRUNCATION"]
        assert hits and hits[0].severity == "error"
    finally:
        jax.config.update("jax_enable_x64", True)
    # x64 back on: same plan, no finding
    assert "X64_TRUNCATION" not in _codes(
        analyze_plan(root, session.conf, 1))


# -- surfacing: bus, event log, explain --------------------------------------

def test_analysis_event_on_bus(session):
    from spark_tpu.observability import QueryListener

    class Collect(QueryListener):
        def __init__(self):
            self.events = []

        def on_analysis(self, event):
            self.events.append(event)

    listener = Collect()
    session.add_listener(listener)
    session.conf.set(CHUNK_KEY, 1 << 10)
    try:
        df = (session.range(1 << 12)
              .select(F.pmod(col("id"), 64).alias("k"))
              .group_by(col("k")).agg(F.sum(col("k")).alias("s")))
        df._qe().collect()
    finally:
        session.remove_listener(listener)
    assert listener.events, "on_analysis never posted"
    codes = [f["code"] for f in listener.events[-1].findings]
    assert "STREAMING_HOST_SYNC" in codes


def test_analysis_findings_in_event_log(session, tmp_path):
    import json
    session.conf.set("spark_tpu.sql.eventLog.dir", str(tmp_path))
    session.conf.set(CHUNK_KEY, 1 << 10)
    qe = (session.range(1 << 12)
          .select(F.pmod(col("id"), 64).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))._qe()
    qe.collect()
    lines = []
    for name in os.listdir(tmp_path):
        with open(tmp_path / name) as f:
            lines += [json.loads(l) for l in f if l.strip()]
    logged = [l for l in lines if l.get("analysis_findings")]
    assert logged, lines
    rec = logged[-1]["analysis_findings"][0]
    assert set(rec) >= {"code", "category", "severity", "message"}


def test_explain_analysis_section(session):
    qe = _overflow_plan(session)._qe()
    text = qe.explain(analysis=True)
    assert "== Static Analysis ==" in text
    assert "SUM_I64_OVERFLOW" in text
    clean = session.range(8)._qe().explain(analysis=True)
    assert "no findings" in clean


def test_analysis_disabled_leaves_none_and_explain_still_works(session):
    session.conf.set(ENABLED_KEY, "false")
    session.conf.set(CHUNK_KEY, 1 << 10)
    qe = (session.range(1 << 12)
          .select(F.pmod(col("id"), 64).alias("k"))
          .group_by(col("k")).agg(F.sum(col("k")).alias("s")))._qe()
    qe.collect()
    # None = "never analyzed", distinct from [] = "analyzed clean"
    assert qe.analysis_findings is None
    # explain(analysis=True) is an explicit request: the on-demand walk
    # still runs and reports the hazard the disabled execution skipped
    assert "STREAMING_HOST_SYNC" in qe.explain(analysis=True)


# -- golden parity (acceptance) ----------------------------------------------

@pytest.mark.parametrize("qname", ["q1", "q3"])
def test_tpch_golden_parity_analysis_on(tpch_session, qname):
    """Byte-identical results with the analyzer on (non-strict), zero
    findings on the real plans, golden parity vs the independent pandas
    implementation."""
    session = tpch_session
    session.conf.set(ENABLED_KEY, "false")
    t_off = Q.QUERIES[qname](session)._qe().collect()
    session.conf.set(ENABLED_KEY, "true")
    session.conf.set(JAXPR_KEY, "on")
    qe = Q.QUERIES[qname](session)._qe()
    t_on = qe.collect()
    assert t_on.equals(t_off)  # byte-identical Arrow tables
    assert qe.analysis_findings == [], qe.analysis_findings
    got = G.normalize_decimals(t_on.to_pandas()).reset_index(drop=True)
    G.compare(got, G.GOLDEN[qname](session._tpch_analysis_path))


# -- lint framework -----------------------------------------------------------

def test_lint_all_clean_on_real_tree():
    from spark_tpu.analysis.lints import run_passes
    assert [v.render() for v in run_passes()] == []


def test_lint_cli_run_helper():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_cli", os.path.join(root, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.run() == []
    with pytest.raises(ValueError, match="unknown lint pass"):
        mod.run(["not-a-pass"])


def _tmp_repo(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


def test_metric_prefix_pass_synthetic(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    repo = _tmp_repo(tmp_path, {
        "spark_tpu/bad.py":
            "ctx.add_metric('made_up_name', 1)\n"
            "ctx.add_metric(f'{x}_dynamic', 1)\n"
            "ctx.add_metric('rows_fine', 1)\n"})
    out = run_passes(["metric-prefix"], repo=repo)
    msgs = [v.message for v in out]
    assert len(out) == 2, out
    assert any("made_up_name" in m for m in msgs)
    assert any("not statically attributable" in m for m in msgs)


def test_conf_key_pass_synthetic(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    repo = _tmp_repo(tmp_path, {
        "spark_tpu/bad.py":
            "x = conf.get('spark_tpu.sql.not.registered')\n"
            "BAD_KEY = 'spark_tpu.also.not.registered'\n"
            "ok = conf.get('spark_tpu.sql.shuffle.partitions')\n"})
    out = run_passes(["conf-key"], repo=repo)
    assert len(out) == 2, out
    assert {v.line for v in out} == {1, 2}
    assert all("unregistered conf key" in v.message for v in out)


def test_fault_site_pass_synthetic(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    repo = _tmp_repo(tmp_path, {
        "spark_tpu/engine.py":
            "faults.fire('scan_load')\n"
            "faults.fire('bogus_seam')\n",
        "tests/test_x.py":
            "spec = 'stage_rnu:fatal:1'\n"})
    out = run_passes(["fault-site"], repo=repo)
    msgs = [v.render() for v in out]
    assert any("bogus_seam" in m for m in msgs), msgs
    assert any("stage_rnu" in m for m in msgs), msgs
    # sites declared in KNOWN_SITES but unwired in this (synthetic)
    # tree are reported against the faults module
    unwired = [v for v in out
               if v.path == "spark_tpu/testing/faults.py"]
    assert unwired and all("no faults.fire" in v.message
                           for v in unwired)


def test_fault_site_pass_register_site_escape(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    repo = _tmp_repo(tmp_path, {
        "tests/test_x.py":
            "faults.register_site('my_seam')\n"
            "plan.fire('my_seam')\n"
            "spec = 'my_seam:fatal:1'\n"})
    out = [v for v in run_passes(["fault-site"], repo=repo)
           if "my_seam" in v.message]
    assert out == []


def test_tracer_leak_pass_synthetic(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    repo = _tmp_repo(tmp_path, {
        "spark_tpu/execution/bad.py":
            "k = hash(col.data)\n"
            "ok = hash('literal')\n"
            "b = bool(jnp.any(x))\n"
            "fine = bool(flag_value)\n",
        "spark_tpu/other.py":
            "h = hash(x)  # out of scope: not execution/ or parallel/\n"})
    out = run_passes(["tracer-leak"], repo=repo)
    assert {v.line for v in out} == {1, 3}, out

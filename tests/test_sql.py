"""SQL frontend tests: parser units + TPC-H SQL-vs-DataFrame parity.

Model: the reference's golden-file SQL suites
(`SQLQueryTestSuite.scala:124`) — here each SQL text must produce the
same result as the hand-built DataFrame program for the same query."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.sql.lexer import ParseError
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet
from spark_tpu.tpch.sql_queries import SQL_QUERIES

SF = 0.002


@pytest.fixture(scope="session")
def sql_session(session, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_sql") / "sf_small")
    write_parquet(path, SF)
    Q.register_tables(session, path)
    session._tpch_path = path
    return session


@pytest.fixture(scope="session")
def tiny(session):
    df = pd.DataFrame({
        "k": [1, 2, 1, 3, 2, 1],
        "v": [10.0, 20.0, 30.0, 40.0, 50.0, 60.0],
        "s": ["a", "b", "a", "c", "b", "a"],
    })
    session.register_table("tiny", df)
    other = pd.DataFrame({"k": [1, 2, 4], "w": [100, 200, 400]})
    session.register_table("other", other)
    return session


def test_select_project_filter(tiny):
    got = tiny.sql(
        "SELECT k, v * 2 AS dv FROM tiny WHERE v > 15 ORDER BY dv"
    ).to_pandas()
    assert got["dv"].tolist() == [40.0, 60.0, 80.0, 100.0, 120.0]
    assert got.columns.tolist() == ["k", "dv"]


def test_select_star(tiny):
    got = tiny.sql("SELECT * FROM tiny ORDER BY v LIMIT 2").to_pandas()
    assert got["v"].tolist() == [10.0, 20.0]
    assert got.columns.tolist() == ["k", "v", "s"]


def test_group_by_having_order(tiny):
    got = tiny.sql("""
        SELECT k, sum(v) AS sv, count(*) AS c
        FROM tiny GROUP BY k HAVING count(*) > 1 ORDER BY sv DESC
    """).to_pandas()
    assert got["k"].tolist() == [1, 2]
    assert got["sv"].tolist() == [100.0, 70.0]
    assert got["c"].tolist() == [3, 2]


def test_agg_inside_arithmetic(tiny):
    got = tiny.sql(
        "SELECT sum(v) / count(v) AS mean, max(v) - min(v) AS spread "
        "FROM tiny"
    ).to_pandas()
    assert got["mean"].tolist() == [35.0]
    assert got["spread"].tolist() == [50.0]


def test_group_by_position_and_alias(tiny):
    by_pos = tiny.sql(
        "SELECT k, count(*) AS c FROM tiny GROUP BY 1 ORDER BY 1"
    ).to_pandas()
    by_alias = tiny.sql(
        "SELECT k AS kk, count(*) AS c FROM tiny GROUP BY kk ORDER BY kk"
    ).to_pandas()
    assert by_pos["c"].tolist() == by_alias["c"].tolist() == [3, 2, 1]


def test_explicit_join_on(tiny):
    got = tiny.sql("""
        SELECT t.k, t.v, o.w FROM tiny t JOIN other o ON t.k = o.k
        ORDER BY v
    """).to_pandas()
    assert got["w"].tolist() == [100, 200, 100, 200, 100]


def test_left_join_null_extension(tiny):
    got = tiny.sql("""
        SELECT tiny.k, w FROM tiny LEFT JOIN other ON tiny.k = other.k
        ORDER BY tiny.k, w
    """).to_pandas()
    k3 = got[got["k"] == 3]
    assert len(k3) == 1 and np.isnan(k3["w"].iloc[0])


def test_implicit_comma_join(tiny):
    got = tiny.sql("""
        SELECT s, sum(w) AS sw FROM tiny, other
        WHERE tiny.k = other.k GROUP BY s ORDER BY s
    """).to_pandas()
    assert got["s"].tolist() == ["a", "b"]
    assert got["sw"].tolist() == [300, 400]


def test_case_when_in_like_between(tiny):
    got = tiny.sql("""
        SELECT k,
               CASE WHEN v >= 30 THEN 1 ELSE 0 END AS big
        FROM tiny WHERE k IN (1, 2) AND s LIKE 'a%' AND v BETWEEN 5 AND 35
        ORDER BY v
    """).to_pandas()
    assert got["big"].tolist() == [0, 1]


def test_union_all(tiny):
    got = tiny.sql(
        "SELECT k FROM tiny WHERE k = 1 UNION ALL SELECT k FROM other"
    ).to_pandas()
    assert sorted(got["k"].tolist()) == [1, 1, 1, 1, 2, 4]


def test_subquery_in_from(tiny):
    got = tiny.sql("""
        SELECT kk, c FROM (
            SELECT k AS kk, count(*) AS c FROM tiny GROUP BY k
        ) sub WHERE c > 1 ORDER BY kk
    """).to_pandas()
    assert got["kk"].tolist() == [1, 2]


def test_parse_errors():
    from spark_tpu.sql.parser import Parser
    for bad in ("SELECT", "SELECT FROM t", "SELECT a FROM t WHERE",
                "SELECT a FROM t GROUP", "SELECT min(DISTINCT a) FROM t"):
        with pytest.raises((ParseError, Exception)):
            Parser(bad).parse_statement()


def test_date_interval_folding():
    from spark_tpu.sql.parser import Parser
    from spark_tpu import types as T
    sel = Parser(
        "SELECT 1 AS one FROM t WHERE d <= date '1998-12-01' - interval "
        "'90' day").parse_statement()
    cond = sel.where
    lit = cond.children[1]
    days = (np.datetime64("1998-09-02", "D")
            - np.datetime64("1970-01-01", "D")).astype(int)
    assert lit.value == days and isinstance(lit._dtype, T.DateType)


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for c in out.columns:
        if len(out) and out[c].dtype == object and \
                out[c].iloc[0].__class__.__name__ == "Decimal":
            out[c] = out[c].astype(float)
    return out


@pytest.mark.parametrize("qname", ["q1", "q3", "q5", "q6"])
def test_tpch_sql_parity(sql_session, qname):
    got = _norm(sql_session.sql(SQL_QUERIES[qname]).to_pandas())
    want = G.GOLDEN[qname](sql_session._tpch_path)
    got = got[want.columns.tolist()]  # official text column order differs
    if qname == "q5":
        got = got.sort_values("n_name").reset_index(drop=True)
        want = want.sort_values("n_name").reset_index(drop=True)
    G.compare(got.reset_index(drop=True), want)


def test_case_with_qualified_refs(tiny):
    """Code-review: Scope.rewrite left CaseWhen.branches stale."""
    got = tiny.sql("""
        SELECT CASE WHEN tiny.v > 25 THEN tiny.k ELSE 0 END AS kk
        FROM tiny ORDER BY v
    """).to_pandas()
    assert got["kk"].tolist() == [0, 0, 1, 3, 2, 1]


def test_case_with_join_refs(tiny):
    got = tiny.sql("""
        SELECT CASE WHEN o.w > 150 THEN 1 ELSE 0 END AS big
        FROM tiny t, other o WHERE t.k = o.k ORDER BY t.v
    """).to_pandas()
    assert got["big"].tolist() == [0, 1, 0, 1, 0]


def test_union_order_limit_binds_to_whole(tiny):
    """Code-review: trailing ORDER BY/LIMIT bound to the right arm only."""
    got = tiny.sql("""
        SELECT k FROM tiny WHERE k >= 2
        UNION ALL SELECT k FROM other
        ORDER BY k DESC LIMIT 3
    """).to_pandas()
    assert got["k"].tolist() == [4, 3, 2]


def test_order_by_ordinal_with_hidden_key(session):
    """Code-review: ordinals resolved against the child schema in the
    hidden-sort path."""
    import pandas as pd
    session.register_table("ord3", pd.DataFrame(
        {"a": [2, 1, 3], "b": [7, 8, 9], "c": [0, 0, 1]}))
    got = session.sql(
        "SELECT b, a FROM ord3 ORDER BY c, 2").to_pandas()
    assert got["a"].tolist() == [1, 2, 3]
    assert got["b"].tolist() == [8, 7, 9]


def test_ambiguous_unqualified_select_raises(tiny):
    from spark_tpu.expr import AnalysisError
    with pytest.raises(AnalysisError, match="ambiguous"):
        tiny.sql("SELECT k FROM tiny t, other o WHERE t.k = o.k") \
            .to_pandas()


def test_having_without_aggregates_raises(tiny):
    from spark_tpu.expr import AnalysisError
    with pytest.raises(AnalysisError, match="HAVING"):
        tiny.sql("SELECT k FROM tiny HAVING k > 1").to_pandas()


def test_right_semi_join_rejected(tiny):
    with pytest.raises(ParseError, match="RIGHT SEMI"):
        tiny.sql("SELECT * FROM tiny RIGHT SEMI JOIN other ON tiny.k = other.k")


def test_decimal_float_compare_large_values(session):
    import decimal
    import pyarrow as pa
    tbl = pa.table({"d": pa.array([decimal.Decimal(6 * 10**17),
                                   decimal.Decimal(4 * 10**17)],
                                  type=pa.decimal128(19, 0))})
    session.register_table("bigdec", tbl)
    from spark_tpu.functions import col, lit
    got = (session.table("bigdec").filter(col("d") > lit(5e17))
           .to_pandas())
    assert len(got) == 1


@pytest.mark.parametrize("qname", ["q4", "q7", "q8", "q9", "q10", "q11",
                                   "q12", "q13", "q14", "q16", "q17",
                                   "q18", "q19", "q22", "q15", "q2",
                                   "q20", "q21"])
def test_tpch_sql_extended(sql_session, qname):
    got = _norm(sql_session.sql(SQL_QUERIES[qname]).to_pandas())
    want = G.GOLDEN[qname](sql_session._tpch_path)
    got = got[want.columns.tolist()]
    G.compare(got.reset_index(drop=True), want)


def test_uncorrelated_scalar_subquery(tiny):
    got = tiny.sql("""
        SELECT k, v FROM tiny WHERE v > (SELECT avg(v) FROM tiny)
        ORDER BY v
    """).to_pandas()
    assert got["v"].tolist() == [40.0, 50.0, 60.0]


def test_in_subquery(tiny):
    got = tiny.sql("""
        SELECT v FROM tiny WHERE k IN (SELECT k FROM other WHERE w < 300)
        ORDER BY v
    """).to_pandas()
    assert got["v"].tolist() == [10.0, 20.0, 30.0, 50.0, 60.0]
    got = tiny.sql("""
        SELECT v FROM tiny WHERE k NOT IN (SELECT k FROM other)
        ORDER BY v
    """).to_pandas()
    assert got["v"].tolist() == [40.0]


@pytest.fixture(scope="session")
def bounds(session):
    session.register_table("bounds", pd.DataFrame({
        "bk": [1, 2, 3], "lo": [15, 100, 35], "hi": [100, 10, 45]}))
    session.register_table("t2", pd.DataFrame({
        "k": [1, 2, 3, 4], "v": [10.0, 30.0, 50.0, 99.0]}))
    return session


def test_two_correlated_scalar_subqueries(bounds):
    """Code-review: generated names collided across conjuncts."""
    got = bounds.sql("""
        SELECT v FROM t2
        WHERE v < (SELECT min(hi) FROM bounds WHERE bk = k)
          AND v > (SELECT max(lo) FROM bounds WHERE bk = k) - 10
        ORDER BY v
    """).to_pandas()
    # k=1: 5 < v < 100 -> 10 in; k=2: v<10 & v>90 -> none; k=3: 25<v<45 -> none (50 out)
    assert got["v"].tolist() == [10.0]


def test_correlated_scalar_left_join_semantics(bounds):
    """Code-review: inner join dropped rows with no matching group even
    when an OR-disjunct made the predicate true."""
    got = bounds.sql("""
        SELECT v FROM t2
        WHERE v = 99 OR v > (SELECT min(lo) FROM bounds WHERE bk = k)
        ORDER BY v
    """).to_pandas()
    # k=3: 50 > 35 in; k=4 has no group but v=99 disjunct holds
    assert got["v"].tolist() == [50.0, 99.0]


def test_qualified_correlation(bounds):
    got = bounds.sql("""
        SELECT v FROM t2
        WHERE v > (SELECT min(bounds.lo) FROM bounds
                   WHERE bounds.bk = t2.k)
        ORDER BY v
    """).to_pandas()
    assert got["v"].tolist() == [50.0]


def test_exists_with_qualified_local_conjunct(bounds):
    got = bounds.sql("""
        SELECT v FROM t2 t
        WHERE EXISTS (SELECT * FROM bounds b
                      WHERE b.bk = t.k AND b.lo < 50)
        ORDER BY v
    """).to_pandas()
    assert got["v"].tolist() == [10.0, 50.0]


def test_scalar_subquery_multi_column_raises(bounds):
    with pytest.raises(RuntimeError, match="one column"):
        bounds.sql(
            "SELECT v FROM t2 WHERE v > (SELECT lo, hi FROM bounds)"
        ).to_pandas()


def test_exists_with_aggregate_raises(bounds):
    from spark_tpu.expr import AnalysisError
    with pytest.raises(AnalysisError, match="aggregates inside"):
        bounds.sql("""
            SELECT v FROM t2
            WHERE EXISTS (SELECT count(*) FROM bounds WHERE bk = k)
        """).to_pandas()






















def test_cte_with_union_body(tiny):
    got = tiny.sql("""
        WITH u AS (
            SELECT k FROM tiny WHERE k = 1
            UNION ALL
            SELECT k FROM other
        )
        SELECT k, count(*) AS c FROM u GROUP BY k ORDER BY k
    """).to_pandas()
    assert got["k"].tolist() == [1, 2, 4]
    assert got["c"].tolist() == [4, 1, 1]


def test_cte_multiple_references_share_materialization(tiny):
    got = tiny.sql("""
        WITH agg AS (
            SELECT k, sum(v) AS sv FROM tiny GROUP BY k
        )
        SELECT k, sv FROM agg
        WHERE sv = (SELECT max(sv) FROM agg)
    """).to_pandas()
    assert got["k"].tolist() == [1]
    assert got["sv"].tolist() == [100.0]




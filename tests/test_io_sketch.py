"""CSV/JSON sources and the device sketch kernels (reference:
csv/json FileFormats + common/sketch)."""

import json

import numpy as np
import pandas as pd
import pytest

from spark_tpu.functions import col
from spark_tpu import functions as F


def test_read_csv(session, tmp_path):
    p = tmp_path / "t.csv"
    pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "x"],
                  "c": [1.5, 2.5, 3.5]}).to_csv(p, index=False)
    got = (session.read_csv(str(p)).filter(col("a") >= 2)
           .to_pandas())
    assert got["a"].tolist() == [2, 3]
    assert got["b"].tolist() == ["y", "x"]


def test_read_csv_delimiter(session, tmp_path):
    p = tmp_path / "t2.csv"
    p.write_text("a|b\n1|x\n2|y\n")
    got = session.read_csv(str(p), sep="|").to_pandas()
    assert got["a"].tolist() == [1, 2]


def test_read_json(session, tmp_path):
    p = tmp_path / "t.jsonl"
    with open(p, "w") as f:
        for i in range(5):
            f.write(json.dumps({"k": i, "s": f"v{i % 2}"}) + "\n")
    got = (session.read_json(str(p))
           .group_by(col("s")).agg(F.count().alias("c"))
           .sort(col("s")).to_pandas())
    assert got["c"].tolist() == [3, 2]


def test_bloom_filter(session):
    import jax.numpy as jnp
    pdf = pd.DataFrame({"k": np.arange(0, 2000, 2).astype(np.int64)})
    session.register_table("bf_t", pdf)
    bf = session.table("bf_t").stat.bloom_filter("k", 1000, fpp=0.01)
    probe = jnp.arange(2000, dtype=jnp.int64)
    got = np.asarray(bf.might_contain(probe))
    # no false negatives
    assert got[::2].all()
    # false positive rate near target
    assert got[1::2].mean() < 0.05


def test_count_min_sketch(session):
    import jax.numpy as jnp
    vals = np.repeat(np.arange(50, dtype=np.int64), np.arange(1, 51))
    session.register_table("cms_t", pd.DataFrame({"k": vals}))
    cms = session.table("cms_t").stat.count_min_sketch("k", eps=0.001)
    est = np.asarray(cms.estimate(jnp.arange(50, dtype=jnp.int64)))
    true = np.arange(1, 51)
    # CMS never underestimates; slack bounded by eps * total
    assert (est >= true).all()
    assert (est <= true + 0.001 * vals.size + 1).all()

"""Durable streaming: crash-point chaos matrix + incremental state
store + file source/sink exactly-once proofs.

The matrix kills the micro-batch loop at EVERY persistence seam
(`stream_source_list` / `stream_offset_write` / `stream_state_commit`
/ `stream_sink_emit`), discards the query object (the hard-crash
simulation: in-memory state is gone, only the checkpoint dir
survives), builds a fresh StreamingQuery over the same checkpoint and
proves the recovered sink output is byte-identical to an
uninterrupted run — no lost rows, no duplicated rows — for stateless,
stateful-complete and event-time/watermark queries on both the memory
and the file source."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.config import Conf
from spark_tpu.execution.state_store import StateStore
from spark_tpu.functions import col
from spark_tpu.streaming import (FileStreamSink, FileStreamSource,
                                 MemoryStream, _MetadataLog, read_sink)
from spark_tpu.testing import faults

SEAMS = ("stream_source_list", "stream_offset_write",
         "stream_state_commit", "stream_sink_emit")

SHAPES = ("stateless", "stateful", "event_time")


# -- harness ----------------------------------------------------------------


def _schema_df(shape):
    if shape == "event_time":
        return pd.DataFrame({"ts": [pd.Timestamp("2024-01-01")],
                             "v": [0.0]})
    return pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                         "v": pd.Series([], dtype=np.int64)})


def _round_df(shape, i):
    """Feed round i. Event-time rounds carry monotonically increasing
    timestamps so late-data drop never depends on batch BOUNDARIES
    (a crash before the offset write legally merges two rounds into
    one batch; the comparison needs watermark-independent data)."""
    if shape == "event_time":
        base = pd.Timestamp("2024-01-01") + pd.Timedelta(seconds=30 * i)
        return pd.DataFrame(
            {"ts": [base, base + pd.Timedelta(seconds=4)],
             "v": [float(i + 1), float(2 * i + 1)]})
    return pd.DataFrame(
        {"k": np.arange(6, dtype=np.int64) + i,
         "v": np.arange(6, dtype=np.int64) * (i + 1)})


def _plan(shape, src):
    df = src.to_df()
    if shape == "stateless":
        return df.filter(col("v") >= 0), "append"
    if shape == "stateful":
        return (df.group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s"),
                     F.count().alias("c")), "complete")
    return (df.with_watermark("ts", "10 seconds")
            .group_by(F.window(col("ts"), "10 seconds").alias("w"))
            .agg(F.sum(col("v")).alias("s"),
                 F.count().alias("c")), "complete")


class _Feeder:
    """One (shape, source) stream fixture: feeds rounds, builds
    (fresh) queries over ONE persistent checkpoint + source + sink."""

    def __init__(self, session, shape, source, base, tag):
        self.session = session
        self.shape = shape
        self.source = source
        self.src_dir = os.path.join(base, f"src_{tag}")
        self.ck = os.path.join(base, f"ck_{tag}")
        self.sink = os.path.join(base, f"sink_{tag}")
        os.makedirs(self.src_dir, exist_ok=True)
        self._mem = (MemoryStream(session, _schema_df(shape))
                     if source == "memory" else None)
        self._n = 0

    def feed(self):
        df = _round_df(self.shape, self._n)
        self._n += 1
        if self._mem is not None:
            self._mem.add_data(df)
        else:
            df.to_parquet(os.path.join(self.src_dir,
                                       f"r{self._n:03d}.parquet"))

    def query(self):
        src = self._mem if self._mem is not None else FileStreamSource(
            self.session, self.src_dir,
            schema_df=_schema_df(self.shape))
        plan_df, mode = _plan(self.shape, src)
        return plan_df.write_stream(self.ck, output_mode=mode,
                                    sink_path=self.sink)


def _norm(shape, pdf):
    if pdf is None or not len(pdf):
        return pdf
    key = {"stateful": "g", "event_time": "w"}.get(shape)
    if key is not None and key in pdf.columns:
        return pdf.sort_values(key).reset_index(drop=True)
    return pdf.reset_index(drop=True)


# -- the crash matrix -------------------------------------------------------


@pytest.mark.parametrize("source", ["memory", "file"])
@pytest.mark.parametrize("shape", SHAPES)
def test_crash_matrix(session, tmp_path, shape, source):
    base = str(tmp_path)
    # uninterrupted baseline: 3 feed rounds, one query start to finish
    fb = _Feeder(session, shape, source, base, "base")
    qb = fb.query()
    for _ in range(3):
        fb.feed()
        qb.process_available()
    want_concat = pd.concat(qb.results(), ignore_index=True)
    want_final = _norm(shape, qb.latest())
    want_sink = _norm(shape, read_sink(fb.sink))

    for seam in SEAMS:
        f = _Feeder(session, shape, source, base, seam)
        q = f.query()
        f.feed()
        q.process_available()  # batch 0 commits clean
        f.feed()
        fired = False
        with faults.inject(session.conf, f"{seam}:fatal:1") as fp:
            try:
                q.process_available()  # crash mid-batch-1
            except faults.FaultInjected:
                fired = True
        # stateless queries have no state commit; every other
        # (seam, shape) must actually crash or the cell is vacuous
        expect_fire = not (seam == "stream_state_commit"
                           and shape == "stateless")
        assert fired == expect_fire, (shape, source, seam,
                                      fp.fired_log)
        survivors = dict(q._sink_results)
        del q  # the hard crash: the query object is GONE
        f.feed()
        q2 = f.query()  # fresh query over the same checkpoint
        q2.process_available()
        combined = dict(survivors)
        combined.update(q2._sink_results)
        cell = f"{shape}/{source}/{seam}"
        try:
            if shape == "stateless":
                got = pd.concat([combined[k] for k in sorted(combined)],
                                ignore_index=True)
                pd.testing.assert_frame_equal(got, want_concat)
            else:
                got_final = _norm(shape, combined[max(combined)])
                pd.testing.assert_frame_equal(got_final, want_final)
            # the file sink saw the same crash: manifested rows must
            # be byte-identical to the uninterrupted run's
            got_sink = _norm(shape, read_sink(f.sink))
            pd.testing.assert_frame_equal(
                got_sink.sort_values(list(got_sink.columns))
                .reset_index(drop=True),
                want_sink.sort_values(list(want_sink.columns))
                .reset_index(drop=True))
        except AssertionError as e:
            raise AssertionError(f"crash-matrix cell {cell}: {e}") from e


def test_same_object_retry_after_commit_crash(session, tmp_path):
    """Replay-duplication regression (in-process flavor): a crash
    between sink emit and commit-log write, retried on the SAME query
    object, must REPLACE the batch's sink entry — the memory sink is
    keyed by batch id, the file sink by its manifest — never append a
    duplicate."""
    ck, sink = str(tmp_path / "ck"), str(tmp_path / "sink")
    src = MemoryStream(session, _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(ck, output_mode="append", sink_path=sink))
    src.add_data(_round_df("stateless", 0))
    q.process_available()
    src.add_data(_round_df("stateless", 1))

    def boom(batch_id, payload):
        raise RuntimeError("simulated commit-log write crash")

    q.commit_log.add = boom  # instance shadow
    with pytest.raises(RuntimeError, match="commit-log write crash"):
        q.process_available()
    del q.commit_log.add  # heal
    q.process_available()  # same-object retry replays batch 1
    assert sorted(q._sink_results) == [0, 1]
    want = pd.concat([_round_df("stateless", 0),
                      _round_df("stateless", 1)], ignore_index=True)
    got = pd.concat(q.results(), ignore_index=True)
    pd.testing.assert_frame_equal(got, want)
    # file sink: the replayed batch overwrote its own part — the
    # manifested row multiset equals the uninterrupted run's
    got_sink = read_sink(sink).sort_values(["k", "v"]) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got_sink, want.sort_values(["k", "v"]).reset_index(drop=True))


def test_stateful_crash_between_offset_and_commit_restart(session,
                                                          tmp_path):
    """The satellite's cross-process flavor: offset written, commit
    missing, STATEFUL batch — the restart must re-run the logged range
    against the committed state version, landing on the same totals as
    an uninterrupted run (no double-fold)."""
    ck = str(tmp_path / "ck")
    src = MemoryStream(session, _schema_df("stateful"))

    def build():
        return (src.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s"))
                .write_stream(ck))

    q = build()
    src.add_data(_round_df("stateful", 0))
    q.process_available()
    src.add_data(_round_df("stateful", 1))
    with faults.inject(session.conf, "stream_sink_emit:fatal:1"):
        with pytest.raises(faults.FaultInjected):
            q.process_available()  # state v1 written, commit missing
    del q
    q2 = build()
    q2.process_available()
    # uninterrupted twin
    src2 = MemoryStream(session, _schema_df("stateful"))
    q3 = (src2.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
          .agg(F.sum(col("v")).alias("s"))
          .write_stream(str(tmp_path / "ck2")))
    src2.add_data(_round_df("stateful", 0))
    src2.add_data(_round_df("stateful", 1))
    q3.process_available()
    pd.testing.assert_frame_equal(
        q2.latest().sort_values("g").reset_index(drop=True),
        q3.latest().sort_values("g").reset_index(drop=True))


# -- incremental state store (unit) -----------------------------------------


def _rand_tables(rng, n=64):
    return {"cnt": rng.randint(0, 5, n).astype(np.int64),
            "acc_0_0": rng.randint(-100, 100, n).astype(np.int64),
            "acc_1_0": rng.rand(n)}


def test_state_store_delta_snapshot_restore(tmp_path):
    conf = Conf().set(
        "spark_tpu.streaming.stateStore.snapshotEveryDeltas", 10)
    store = StateStore(str(tmp_path / "st"), conf)
    rng = np.random.RandomState(7)
    state = _rand_tables(rng)
    prev = None
    per_version = {}
    for v in range(13):
        if v:
            # mutate a few groups only (the delta shape)
            idx = rng.choice(64, 5, replace=False)
            state = {k: a.copy() for k, a in state.items()}
            state["acc_0_0"][idx] += 1
            state["cnt"][idx] += 1
        info = store.commit_tables(v, state, prev)
        per_version[v] = {k: a.copy() for k, a in state.items()}
        prev = state
        want_kind = "snapshot" if v in (0, 10) else "delta"
        assert info["kind"] == want_kind, (v, info)
        if want_kind == "delta":
            assert info["changed"] <= 5 + 5  # cnt+acc share groups
    # restore from snapshot + deltas byte-identical to the full state
    for v in (0, 3, 9, 10, 12):
        got = store.load_tables(v)
        for k, want in per_version[v].items():
            np.testing.assert_array_equal(got[k], want, err_msg=f"v{v}/{k}")
    assert store.last_restore_replayed <= 10
    got12 = store.load_tables(12)
    assert store.last_restore_replayed == 2  # snapshot 10 + 2 deltas


def test_state_store_nan_slots_not_flagged_changed(tmp_path):
    conf = Conf()
    store = StateStore(str(tmp_path / "st"), conf)
    a = {"cnt": np.array([1, 0, 2], np.int64),
         "acc_0_0": np.array([1.0, np.nan, 3.0])}
    store.commit_tables(0, a, None)
    b = {"cnt": np.array([2, 0, 2], np.int64),
         "acc_0_0": np.array([5.0, np.nan, 3.0])}
    info = store.commit_tables(1, b, a)
    assert info["kind"] == "delta" and info["changed"] == 1, info
    got = store.load_tables(1)
    np.testing.assert_array_equal(got["cnt"], b["cnt"])
    assert np.isnan(got["acc_0_0"][1]) and got["acc_0_0"][0] == 5.0


def test_state_store_prune_never_breaks_restore(tmp_path):
    """Compaction safety: pruning at every commit never deletes a file
    the last committed version's restore chain needs."""
    conf = Conf().set(
        "spark_tpu.streaming.stateStore.snapshotEveryDeltas", 4)
    store = StateStore(str(tmp_path / "st"), conf)
    rng = np.random.RandomState(3)
    state = _rand_tables(rng, 16)
    prev = None
    for v in range(23):
        if v:
            state = {k: a.copy() for k, a in state.items()}
            state["cnt"][rng.randint(0, 16)] += 1
        store.commit_tables(v, state, prev)
        prev = state
        store.prune(v, retain=2)
        got = store.load_tables(v)  # restore after every compaction
        for k in state:
            np.testing.assert_array_equal(got[k], state[k])
        assert store.last_restore_replayed < 4
    # compaction actually retired files (not a no-op)
    assert min(store.snapshot_versions()) >= 16
    assert min(store.delta_versions()) > min(store.snapshot_versions())


def test_state_store_frame_delta_tombstones(tmp_path):
    conf = Conf().set(
        "spark_tpu.streaming.stateStore.snapshotEveryDeltas", 10)
    store = StateStore(str(tmp_path / "st"), conf)
    s0 = pd.DataFrame({"w": [0, 10, 20], "acc": [1.0, 2.0, 3.0]})
    store.commit_frame(0, s0, None, ["w"])
    # v1: update w=10, evict w=0, add w=30
    s1 = pd.DataFrame({"w": [10, 20, 30], "acc": [5.0, 3.0, 7.0]})
    info = store.commit_frame(1, s1, s0, ["w"])
    assert info["kind"] == "delta" and info["changed"] == 2, info
    got = store.load_frame(1).sort_values("w").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, s1.sort_values("w").reset_index(drop=True))
    # v2: no change at all -> empty delta
    info2 = store.commit_frame(2, s1, s1, ["w"])
    assert info2["kind"] == "delta" and info2["changed"] == 0
    got2 = store.load_frame(2).sort_values("w").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got2, s1.sort_values("w").reset_index(drop=True))


def test_incremental_delta_ratio_and_bounded_restore(session, tmp_path):
    """The incremental-checkpointing acceptance: with ~6% of groups
    changing per trigger, steady-state delta bytes stay <= 25% of the
    snapshot bytes, and a fresh query's restore replays at most
    snapshotEveryDeltas deltas."""
    ck = str(tmp_path / "ck")
    records = []

    class _Cap:
        def on_streaming_batch(self, event):
            records.append(event.record)

    cap = _Cap()
    session.add_listener(cap)
    try:
        src = MemoryStream(session, _schema_df("stateful"))
        q = (src.to_df()
             .group_by(F.pmod(col("k"), 1024).alias("g"))
             .agg(F.sum(col("v")).alias("s"))
             .write_stream(ck))
        # batch 0 touches EVERY group; batches 1..24 touch 64 (~6%)
        src.add_data(pd.DataFrame(
            {"k": np.arange(1024, dtype=np.int64),
             "v": np.ones(1024, dtype=np.int64)}))
        q.process_available()
        for i in range(1, 25):
            src.add_data(pd.DataFrame(
                {"k": np.arange(64, dtype=np.int64),
                 "v": np.full(64, i, dtype=np.int64)}))
            q.process_available()
    finally:
        session.remove_listener(cap)
    assert len(records) == 25
    assert records[0]["kind"] == "snapshot"
    snap_bytes = records[0]["state_bytes"]
    deltas = [r for r in records[1:] if r["kind"] == "delta"]
    snaps = [r for r in records if r["kind"] == "snapshot"]
    assert [r["batch_id"] for r in snaps] == [0, 10, 20]
    assert deltas, records
    steady = max(r["state_bytes"] for r in deltas)
    assert steady <= 0.25 * snap_bytes, (steady, snap_bytes)
    # fresh query restore: newest snapshot (20) + at most
    # snapshotEveryDeltas deltas
    q2 = (src.to_df()
          .group_by(F.pmod(col("k"), 1024).alias("g"))
          .agg(F.sum(col("v")).alias("s"))
          .write_stream(ck))
    assert q2._store.last_restore_replayed == 24 - 20
    assert q2._store.last_restore_replayed <= 10
    # the restored state is live: one more batch lands on exact totals
    src.add_data(pd.DataFrame({"k": np.array([0], dtype=np.int64),
                               "v": np.array([1000], dtype=np.int64)}))
    q2.process_available()
    out = q2.latest().set_index("g")["s"]
    assert out.loc[0] == 1 + sum(range(1, 25)) + 1000
    assert out.loc[100] == 1  # untouched group carried intact


# -- metadata-log durability ------------------------------------------------


def test_metadata_log_latest_skips_torn_and_empty(tmp_path, session):
    m = session.metrics
    c0 = m.counter("streaming_log_corrupt").value
    log = _MetadataLog(str(tmp_path / "log"), metrics=m)
    log.add(0, {"start": 0, "end": 1})
    log.add(1, {"start": 1, "end": 2})
    # torn newest entry: truncated mid-JSON
    with open(os.path.join(log.path, "2"), "w") as f:
        f.write('{"start": 2, "e')
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        i, payload = log.latest()
    assert (i, payload) == (1, {"start": 1, "end": 2})
    # empty newest entry (crash before any byte flushed)
    open(os.path.join(log.path, "3"), "w").close()
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        i, payload = log.latest()
    assert i == 1
    assert m.counter("streaming_log_corrupt").value >= c0 + 3
    # no tmp litter from the fsync'd add path
    assert not [f for f in os.listdir(log.path) if f.endswith(".tmp")]


def test_metadata_log_all_corrupt_returns_none(tmp_path, session):
    log = _MetadataLog(str(tmp_path / "log"), metrics=session.metrics)
    open(os.path.join(log.path, "0"), "w").close()
    with pytest.warns(UserWarning):
        assert log.latest() == (None, None)


def test_recovery_survives_torn_commit_entry(session, tmp_path):
    """A torn newest COMMIT entry falls back one version: the restart
    re-runs the batch it covered (idempotent) instead of crashing the
    whole recovery."""
    ck = str(tmp_path / "ck")
    src = MemoryStream(session, _schema_df("stateful"))

    def build():
        return (src.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s")).write_stream(ck))

    q = build()
    for i in range(2):
        src.add_data(_round_df("stateful", i))
        q.process_available()
    want = q.latest().sort_values("g").reset_index(drop=True)
    # tear the newest commit entry
    with open(os.path.join(ck, "commits", "1"), "w") as f:
        f.write('{"ok": tru')
    del q
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        q2 = build()
    assert q2._committed_batch == 0  # fell back one version
    q2.process_available()  # replays batch 1 from its logged range
    pd.testing.assert_frame_equal(
        q2.latest().sort_values("g").reset_index(drop=True), want)


def test_recovery_survives_torn_offset_entry_with_intact_commit(
        session, tmp_path):
    """Asymmetric corruption: the newest OFFSET entry torn while its
    COMMIT entry survived. Falling back one offset entry used to
    re-plan (and double-fold) the committed batch's range; the commit
    entry's `end` watermark now floors the next planned range."""
    ck = str(tmp_path / "ck")
    src = MemoryStream(session, _schema_df("stateful"))

    def build():
        return (src.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s")).write_stream(ck))

    q = build()
    for i in range(2):
        src.add_data(_round_df("stateful", i))
        q.process_available()
    del q
    # tear the newest offset entry; its commit survives
    with open(os.path.join(ck, "offsets", "1"), "w") as f:
        f.write('{"start": 1, "e')
    src.add_data(_round_df("stateful", 2))
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        q2 = build()
        q2.process_available()
    # uninterrupted twin proves no range was folded twice
    src3 = MemoryStream(session, _schema_df("stateful"))
    q3 = (src3.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
          .agg(F.sum(col("v")).alias("s"))
          .write_stream(str(tmp_path / "ck3")))
    for i in range(3):
        src3.add_data(_round_df("stateful", i))
    q3.process_available()
    pd.testing.assert_frame_equal(
        q2.latest().sort_values("g").reset_index(drop=True),
        q3.latest().sort_values("g").reset_index(drop=True))


def test_file_source_heals_torn_seen_log_tail(session, tmp_path):
    """A torn seen-file-log tail below a PLANNED offset range must not
    silently drop the lost files' rows: re-discovery appends them back
    at their original indices (deterministic (mtime, name) order) and
    the replayed batch covers the full planned range."""
    src_dir = str(tmp_path / "src")
    ck = str(tmp_path / "ck")
    os.makedirs(src_dir)
    for i in range(3):
        _round_df("stateless", i).to_parquet(
            os.path.join(src_dir, f"r{i}.parquet"))

    def build():
        s = FileStreamSource(session, src_dir,
                             schema_df=_schema_df("stateless"))
        return (s.to_df().filter(col("v") >= 0)
                .write_stream(ck, output_mode="append"))

    q = build()
    q.process_available()  # batch 0 covers files [0, 3)
    assert len(q.results()) == 1 and len(q.results()[0]) == 18
    del q
    # simulate the torn tail: the newest seen-log entry is corrupt, so
    # a planned-but-uncommitted batch range exceeds the reloaded log
    with open(os.path.join(ck, "sources", "0", "2"), "w") as f:
        f.write('{"name": "r2.par')
    os.remove(os.path.join(ck, "commits", "0"))  # batch 0 uncommitted
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        q2 = build()
    q2.process_available()  # replays [0, 3) — healed, nothing lost
    got = pd.concat(q2.results(), ignore_index=True)
    want = pd.concat([_round_df("stateless", i) for i in range(3)],
                     ignore_index=True)
    pd.testing.assert_frame_equal(
        got.sort_values(["k", "v"]).reset_index(drop=True),
        want.sort_values(["k", "v"]).reset_index(drop=True))
    assert len(q2.stream._seen) == 3
    assert [e["name"] for e in q2.stream._seen] == \
        ["r0.parquet", "r1.parquet", "r2.parquet"]


def test_file_source_vanished_planned_file_fails_loudly(session,
                                                        tmp_path):
    """Files covered by a planned batch that are GONE from the
    directory (not just a torn log entry) cannot be replayed
    exactly-once — the batch must raise, not silently skip them."""
    src_dir = str(tmp_path / "src")
    ck = str(tmp_path / "ck")
    os.makedirs(src_dir)
    for i in range(2):
        _round_df("stateless", i).to_parquet(
            os.path.join(src_dir, f"r{i}.parquet"))

    def build():
        s = FileStreamSource(session, src_dir,
                             schema_df=_schema_df("stateless"))
        return s, (s.to_df().filter(col("v") >= 0)
                   .write_stream(ck, output_mode="append"))

    _, q = build()
    q.process_available()
    del q
    # lose the seen-log tail AND the file itself
    with open(os.path.join(ck, "sources", "0", "1"), "w") as f:
        f.write("")
    os.remove(os.path.join(ck, "commits", "0"))
    os.remove(os.path.join(src_dir, "r1.parquet"))
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        _, q2 = build()
    with pytest.raises(RuntimeError, match="planned batch vanished"):
        q2.process_available()


# -- file source: quarantine ------------------------------------------------


def _write_corrupt(path):
    with open(path, "wb") as f:
        f.write(b"these bytes are not a parquet file")


def test_file_source_quarantines_corrupt_file(session, tmp_path):
    src_dir = str(tmp_path / "src")
    os.makedirs(src_dir)
    _round_df("stateless", 0).to_parquet(
        os.path.join(src_dir, "a.parquet"))
    _write_corrupt(os.path.join(src_dir, "b.parquet"))
    _round_df("stateless", 1).to_parquet(
        os.path.join(src_dir, "c.parquet"))
    q0 = session.metrics.counter("streaming_files_quarantined").value
    src = FileStreamSource(session, src_dir,
                           schema_df=_schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    with pytest.warns(UserWarning, match="quarantined"):
        q.process_available()
    got = pd.concat(q.results(), ignore_index=True)
    want = pd.concat([_round_df("stateless", 0),
                      _round_df("stateless", 1)], ignore_index=True)
    pd.testing.assert_frame_equal(
        got.sort_values(["k", "v"]).reset_index(drop=True),
        want.sort_values(["k", "v"]).reset_index(drop=True))
    assert session.metrics.counter(
        "streaming_files_quarantined").value == q0 + 1
    quar = src.quarantined()
    assert len(quar) == 1 and quar[0]["name"] == "b.parquet"
    # the quarantine is IN the seen log: a fresh query over the same
    # checkpoint skips the file without re-decoding (and without
    # re-counting)
    src2 = FileStreamSource(session, src_dir,
                            schema_df=_schema_df("stateless"))
    q2 = (src2.to_df().filter(col("v") >= 0)
          .write_stream(str(tmp_path / "ck"), output_mode="append"))
    q2.process_available()  # drained: nothing new
    assert len(src2.quarantined()) == 1
    assert session.metrics.counter(
        "streaming_files_quarantined").value == q0 + 1


def test_file_source_strict_mode_fails_batch(session, tmp_path):
    src_dir = str(tmp_path / "src")
    os.makedirs(src_dir)
    _write_corrupt(os.path.join(src_dir, "bad.parquet"))
    session.conf.set("spark_tpu.streaming.source.file.strict", True)
    src = FileStreamSource(session, src_dir,
                           schema_df=_schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    with pytest.raises(RuntimeError, match="strict"):
        q.process_available()


def test_file_source_schema_mismatch_quarantines(session, tmp_path):
    src_dir = str(tmp_path / "src")
    os.makedirs(src_dir)
    pd.DataFrame({"other": [1.5]}).to_parquet(
        os.path.join(src_dir, "wrong.parquet"))
    _round_df("stateless", 0).to_parquet(
        os.path.join(src_dir, "right.parquet"))
    src = FileStreamSource(session, src_dir,
                           schema_df=_schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    with pytest.warns(UserWarning, match="quarantined"):
        q.process_available()
    got = pd.concat(q.results(), ignore_index=True)
    assert len(got) == len(_round_df("stateless", 0))


def test_file_source_ignores_metadata_and_tmp_names(session, tmp_path):
    src_dir = str(tmp_path / "src")
    os.makedirs(os.path.join(src_dir, "_metadata"))
    _round_df("stateless", 0).to_parquet(
        os.path.join(src_dir, "data.parquet"))
    _round_df("stateless", 1).to_parquet(
        os.path.join(src_dir, "inflight.parquet.tmp"))
    with open(os.path.join(src_dir, "_SUCCESS"), "w"):
        pass
    src = FileStreamSource(session, src_dir,
                           schema_df=_schema_df("stateless"))
    assert src.latest_offset() == 1
    assert src._seen[0]["name"] == "data.parquet"


# -- file sink: manifest atomicity ------------------------------------------


def test_file_sink_reader_ignores_unmanifested_parts(session, tmp_path):
    sink = str(tmp_path / "sink")
    src = MemoryStream(session, _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append",
                       sink_path=sink))
    for i in range(2):
        src.add_data(_round_df("stateless", i))
        q.process_available()
    want = read_sink(sink)
    # an orphaned part (its batch never manifested) is invisible
    pd.DataFrame({"k": [999], "v": [999]}).to_parquet(
        os.path.join(sink, "part-09999.parquet"))
    pd.testing.assert_frame_equal(read_sink(sink), want)
    assert 999 not in read_sink(sink)["k"].values
    # a torn manifest entry is skipped with a warning, not fatal
    with open(os.path.join(sink, "_metadata", "7"), "w") as f:
        f.write('{"parts": ["part-0')
    with pytest.warns(UserWarning, match="corrupt metadata log"):
        pd.testing.assert_frame_equal(read_sink(sink), want)


def test_file_sink_complete_mode_reads_latest_batch(session, tmp_path):
    sink = str(tmp_path / "sink")
    src = MemoryStream(session, _schema_df("stateful"))
    q = (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .write_stream(str(tmp_path / "ck"), sink_path=sink))
    for i in range(3):
        src.add_data(_round_df("stateful", i))
        q.process_available()
    got = read_sink(sink).sort_values("g").reset_index(drop=True)
    want = q.latest().sort_values("g").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want)


def test_file_sink_complete_mode_prunes_superseded_parts(session,
                                                         tmp_path):
    """Complete mode rewrites the full result every batch: parts
    outside the retention window are dead and must be GC'd (a
    long-running stream must not fill the disk), while append-mode
    parts are the data and stay."""
    sink = str(tmp_path / "sink")
    src = MemoryStream(session, _schema_df("stateful"))
    q = (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .write_stream(str(tmp_path / "ck"), sink_path=sink))
    for i in range(6):
        src.add_data(_round_df("stateful", i))
        q.process_available()
    parts = [f for f in os.listdir(sink) if f.endswith(".parquet")]
    # retainBatches=2: only batches >= committed-2 survive
    assert sorted(parts) == ["part-00003.parquet", "part-00004.parquet",
                             "part-00005.parquet"], parts
    got = read_sink(sink).sort_values("g").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, q.latest().sort_values("g").reset_index(drop=True))
    # append mode: nothing pruned
    sink2 = str(tmp_path / "sink2")
    src2 = MemoryStream(session, _schema_df("stateless"))
    q2 = (src2.to_df().filter(col("v") >= 0)
          .write_stream(str(tmp_path / "ck2"), output_mode="append",
                        sink_path=sink2))
    for i in range(6):
        src2.add_data(_round_df("stateless", i))
        q2.process_available()
    parts2 = [f for f in os.listdir(sink2) if f.endswith(".parquet")]
    assert len(parts2) == 6, parts2


def test_file_sink_replay_overwrites_own_parts(session, tmp_path):
    sink = str(tmp_path / "sink")
    fs = FileStreamSink(session, sink, "append")
    fs.emit(0, pd.DataFrame({"k": [1], "v": [10]}))
    fs.emit(1, pd.DataFrame({"k": [2], "v": [20]}))
    # replay of batch 1 (crash between emit and commit): overwrite
    fs.emit(1, pd.DataFrame({"k": [2], "v": [20]}))
    got = read_sink(sink).sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, pd.DataFrame({"k": [1, 2], "v": [10, 20]}))


# -- observability: streaming record + summary + validator ------------------


def test_streaming_event_log_record_and_summary(session, tmp_path):
    from spark_tpu import history
    ev_dir = str(tmp_path / "events")
    session.conf.set("spark_tpu.sql.eventLog.dir", ev_dir)
    src = MemoryStream(session, _schema_df("stateful"))
    q = (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .write_stream(str(tmp_path / "ck"),
                       sink_path=str(tmp_path / "sink")))
    src.add_data(_round_df("stateful", 0))
    q.process_available()
    # second batch touches ONE group: a genuine (small) delta
    src.add_data(pd.DataFrame({"k": np.array([0], dtype=np.int64),
                               "v": np.array([7], dtype=np.int64)}))
    q.process_available()
    session.conf.set("spark_tpu.sql.eventLog.dir", "")
    events = history.read_event_log(ev_dir)
    ss = history.streaming_summary(events)
    assert len(ss) == 2, ss
    assert ss["kind"].tolist() == ["snapshot", "delta"]
    assert (ss["state_bytes"] > 0).all()
    assert ss["batch_id"].tolist() == [0, 1]
    assert (ss["sink_parts"] == 1).all()
    assert (ss["quarantined"] == 0).all()
    assert (ss["source"] == "memory").all()
    # the versioned-schema validator accepts the v4 lines
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "events_tool", os.path.join(root, "scripts", "events_tool.py"))
    et = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(et)
    assert et.validate([ev_dir]) == []
    # and rejects a pre-v4 line smuggling a streaming record
    bad = {"schema_version": 3, "query_id": 1, "ts": 1.0,
           "status": "ok", "plan": "x", "streaming": {"batch_id": 0}}
    bad_path = os.path.join(ev_dir, "app-bad.jsonl")
    with open(bad_path, "w") as f:
        f.write(json.dumps(bad) + "\n")
    problems = et.validate([bad_path])
    assert any("v4 field 'streaming'" in p for p in problems), problems


def test_streaming_metrics_counters(session, tmp_path):
    m = session.metrics
    b0 = m.counter("streaming_batches").value
    r0 = m.counter("streaming_rows").value
    d0 = m.counter("streaming_state_delta_bytes").value
    s0 = m.counter("streaming_state_snapshot_bytes").value
    src = MemoryStream(session, _schema_df("stateful"))
    ck = str(tmp_path / "ck")

    def build():
        return (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s")).write_stream(ck))

    q = build()
    src.add_data(_round_df("stateful", 0))
    q.process_available()
    for i in range(2):
        # partial churn: one group per batch -> deltas, not snapshots
        src.add_data(pd.DataFrame(
            {"k": np.array([i], dtype=np.int64),
             "v": np.array([6], dtype=np.int64)}))
        q.process_available()
    assert m.counter("streaming_batches").value == b0 + 3
    assert m.counter("streaming_rows").value == r0 + 8
    assert m.counter("streaming_state_snapshot_bytes").value > s0
    assert m.counter("streaming_state_delta_bytes").value > d0
    # restore wall-clock ticks on a fresh query over the checkpoint
    t0 = m.counter("streaming_restore_ms").value
    build()
    assert m.counter("streaming_restore_ms").value > t0

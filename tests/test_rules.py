"""Rule-level optimizer tests: plan == plan after one rule application
(the reference's PlanTest.scala:37 comparePlans pattern), one per rule
in default_optimizer — plus the decimal-division precision guard and a
mocked multi-host bring-up."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit


@pytest.fixture
def scan(session):
    pdf = pd.DataFrame({"a": np.arange(10, dtype=np.int64),
                        "b": np.arange(10, dtype=np.float64),
                        "c": np.arange(10, dtype=np.int64)})
    session.register_table("rule_t", pdf)
    from spark_tpu.plan import logical as L
    return L.Scan(session.catalog["rule_t"])


def _plans_equal(a, b) -> bool:
    return a.tree_string() == b.tree_string()


def test_combine_filters(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import CombineFilters
    p = L.Filter(L.Filter(scan, col("a") > 1), col("b") < 5)
    out = CombineFilters().apply(p)
    want = L.Filter(scan, (col("a") > 1) & (col("b") < 5))
    assert _plans_equal(out, want), out.tree_string()


def test_push_filter_through_project(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import PushFilterThroughProject
    from spark_tpu.expr import Alias
    proj = L.Project(scan, [Alias(col("a"), "x"), col("b")])
    p = L.Filter(proj, col("x") > 3)
    out = PushFilterThroughProject().apply(p)
    # the filter lands below the projection, rewritten to base columns
    want = L.Project(L.Filter(scan, col("a") > 3),
                     [Alias(col("a"), "x"), col("b")])
    assert _plans_equal(out, want), out.tree_string()


def test_push_filter_into_scan(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import PushFilterIntoScan
    p = L.Filter(scan, col("a") > 3)
    out = PushFilterIntoScan().apply(p)

    def find_scan(n):
        if isinstance(n, L.Scan):
            return n
        return find_scan(n.children[0])

    s = find_scan(out)
    assert s.pushed_filters, "expected the predicate pushed to the scan"
    assert "a" in repr(s.pushed_filters[0])


def test_prune_columns(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import PruneColumns
    p = L.Project(scan, [col("a")])
    out = PruneColumns().apply(p)

    def find_scan(n):
        if isinstance(n, L.Scan):
            return n
        return find_scan(n.children[0])

    s = find_scan(out)
    assert s.required_columns is not None
    assert set(s.required_columns) == {"a"}


def test_constant_folding(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import ConstantFolding
    from spark_tpu.expr import Alias, Literal
    p = L.Project(scan, [Alias(lit(1) + lit(2), "x")])
    out = ConstantFolding().apply(p)
    e = out.exprs[0]
    assert isinstance(e, Alias) and isinstance(e.child, Literal)
    assert e.child.value == 3


def test_collapse_project_into_aggregate(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import CollapseProjectIntoAggregate
    from spark_tpu.expr import Alias
    from spark_tpu.expr_agg import AggExpr, Sum
    proj = L.Project(scan, [Alias(col("a") % 3, "k"), col("b")])
    agg = L.Aggregate(proj, [col("k")],
                      [AggExpr(Sum(col("b")), "s")])
    out = CollapseProjectIntoAggregate().apply(agg)
    assert isinstance(out, L.Aggregate)
    assert isinstance(out.child, L.Scan), out.tree_string()
    assert "%" in repr(out.group_exprs[0])


def test_rewrite_distinct_aggregates(scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import RewriteDistinctAggregates
    from spark_tpu.expr_agg import AggExpr, SumDistinct
    agg = L.Aggregate(scan, [],
                      [AggExpr(SumDistinct(col("a")), "sd")])
    out = RewriteDistinctAggregates().apply(agg)
    # the rewrite produces a nested aggregation (dedupe then sum)
    assert out.tree_string() != agg.tree_string()
    aggs = []

    def walk(n):
        if isinstance(n, L.Aggregate):
            aggs.append(n)
        for c in n.children:
            walk(c)

    walk(out)
    assert len(aggs) == 2, out.tree_string()


def test_rewrite_group_key_aggregates(session, scan):
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import RewriteGroupKeyAggregates
    from spark_tpu.expr_agg import AggExpr, Max
    # max(k) over group key k is the key itself
    agg = L.Aggregate(scan, [col("a")], [AggExpr(Max(col("a")), "m")])
    out = RewriteGroupKeyAggregates().apply(agg)
    assert out.tree_string() != agg.tree_string()


def test_fixed_point_is_stable(session, scan):
    """The optimizer must reach a fixed point: optimizing an optimized
    plan changes nothing (catches rules that flip-flop)."""
    from spark_tpu.plan import logical as L
    from spark_tpu.plan.optimizer import default_optimizer
    p = L.Filter(
        L.Project(scan, [col("a"), (col("b") * 2).alias("b2")]),
        col("a") > 2)
    once = default_optimizer().execute(p)
    twice = default_optimizer().execute(once)
    assert _plans_equal(once, twice)


def test_decimal_division_precision_guard(session):
    """Round-2..4 VERDICT weak: decimal division computed in f64 must
    NULL (not silently round) when intermediates leave the 2^53
    mantissa."""
    import decimal
    ok = decimal.Decimal("1234.56")
    huge = decimal.Decimal("99999999999999.99")  # ~1e16 unscaled > 2^53
    pdf = pd.DataFrame({"x": [ok, huge], "y": [decimal.Decimal("2.00")] * 2})
    session.register_table("dec_div_t", pdf)
    out = (session.table("dec_div_t")
           .select((col("x") / col("y")).alias("q")).to_pandas())
    assert float(out["q"][0]) == pytest.approx(617.28)
    assert pd.isna(out["q"][1]), "expected NULL past the 2^53 bound"


def test_init_distributed_mocked(session, monkeypatch):
    """Multi-host bring-up calls jax.distributed.initialize with the
    configured coordinator/rank exactly once (mocked — round-4 VERDICT
    weak #7: this path had zero coverage)."""
    import jax
    from spark_tpu.parallel import mesh as M

    calls = []

    class FakeDistributed:
        global_state = None

        @staticmethod
        def initialize(coordinator_address=None, num_processes=None,
                       process_id=None):
            calls.append((coordinator_address, num_processes, process_id))

    monkeypatch.setattr(jax, "distributed", FakeDistributed)
    old = {k: session.conf.get(k) for k in
           ("spark_tpu.sql.cluster.coordinator",
            "spark_tpu.sql.cluster.numProcesses",
            "spark_tpu.sql.cluster.processId")}
    try:
        session.conf.set("spark_tpu.sql.cluster.coordinator",
                         "host0:8476")
        session.conf.set("spark_tpu.sql.cluster.numProcesses", 2)
        session.conf.set("spark_tpu.sql.cluster.processId", 1)
        n = M.init_distributed(session.conf)
        assert calls == [("host0:8476", 2, 1)]
        assert n == len(jax.devices())
    finally:
        for k, v in old.items():
            session.conf.set(k, v)

"""Out-of-HBM execution: the deviceBudget-gated spill paths.

Covers (VERDICT r4 #1, reference `UnsafeExternalSorter.java:1`,
`ExternalAppendOnlyMap.scala:55`):
- general-key aggregate spill (partial-mode chunks -> host Arrow ->
  FINAL re-reduce), incl. through probe-side joins (the TPC-H Q3 shape);
- external collect: plain chain, LIMIT, ORDER BY+LIMIT (chunked
  tournament top-n), and pure ORDER BY with host merge;
- TPC-H Q3/Q5 parity under a budget small enough to force streaming.

Every test pins a tiny deviceBudget + chunk size so the out-of-core
machinery runs on CI-size data, then checks parity against the same
query executed whole-input (budget 0).
"""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col

BUDGET_KEY = "spark_tpu.sql.memory.deviceBudget"
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
CACHE_KEY = "spark_tpu.sql.io.deviceCacheBytes"


@pytest.fixture
def tiny_budget(session):
    old = {k: session.conf.get(k) for k in (BUDGET_KEY, CHUNK_KEY,
                                            CACHE_KEY)}
    yield session
    for k, v in old.items():
        session.conf.set(k, v)


def _force_spill(session, chunk_rows=1000):
    session.conf.set(BUDGET_KEY, 1)  # 1 byte: everything is out-of-core
    session.conf.set(CHUNK_KEY, chunk_rows)
    session.conf.set(CACHE_KEY, 0)


def _unforce(session):
    session.conf.set(BUDGET_KEY, 0)


def _mk(session, n=5237, name="spill_t", seed=7):
    rs = np.random.RandomState(seed)
    pdf = pd.DataFrame({
        "k": rs.randint(0, 10_000_000, n).astype(np.int64),
        "g": rs.randint(0, 7, n).astype(np.int64),
        "v": rs.randn(n),
        "s": rs.choice(["aa", "bb", "cc", "dd"], n)})
    session.register_table(name, pdf)
    return pdf


def test_aggregate_spill_unbounded_keys(tiny_budget):
    """Group keys with no static domain (the Q3 l_orderkey shape) take
    the partial-spill path and must match the whole-input result."""
    session = tiny_budget
    _mk(session, name="spill_agg")
    q = lambda: (session.table("spill_agg").group_by(col("k"))
                 .agg(F.sum(col("v")).alias("sv"),
                      F.count().alias("c"))
                 .to_pandas().sort_values("k").reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    qe_probe = (session.table("spill_agg").group_by(col("k"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.count().alias("c"))._qe())
    got_tbl = qe_probe.collect().to_pandas()
    assert qe_probe.spilled_partial_rows is not None, \
        "expected the partial-spill path to engage"
    got = got_tbl.sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == want["k"].tolist()
    assert got["c"].tolist() == want["c"].tolist()
    assert np.allclose(got["sv"], want["sv"])


def test_aggregate_spill_string_keys(tiny_budget):
    """Derived/dictionary group keys round-trip through host Arrow (no
    shared-encoding requirement on the spill path)."""
    session = tiny_budget
    _mk(session, name="spill_agg_s")
    q = lambda: (session.table("spill_agg_s")
                 .group_by(col("s"), (col("k") % 1000).alias("kb"))
                 .agg(F.sum(col("v")).alias("sv"))
                 .to_pandas().sort_values(["s", "kb"])
                 .reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    got = q()
    assert got["s"].tolist() == want["s"].tolist()
    assert got["kb"].tolist() == want["kb"].tolist()
    assert np.allclose(got["sv"], want["sv"])


def test_aggregate_spill_through_join(tiny_budget):
    """The Q3 shape: probe-side join chain under the aggregate; build
    side resident, probe streamed, partials spilled."""
    session = tiny_budget
    pdf = _mk(session, name="spill_fact")
    dim = pd.DataFrame({"g": np.arange(7, dtype=np.int64),
                        "w": np.arange(7, dtype=np.float64) * 2.0})
    session.register_table("spill_dim", dim)
    q = lambda: (session.table("spill_fact")
                 .join(session.table("spill_dim"),
                       left_on=col("g"), right_on=col("g"))
                 .group_by(col("k"))
                 .agg(F.sum(col("v") * col("w")).alias("sv"))
                 .to_pandas().sort_values("k").reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    got = q()
    assert got["k"].tolist() == want["k"].tolist()
    assert np.allclose(got["sv"], want["sv"])


def test_external_collect_plain_chain(tiny_budget):
    session = tiny_budget
    _mk(session, name="ext_plain")
    q = lambda: (session.table("ext_plain")
                 .filter(col("v") > 0.5)
                 .select(col("k"), (col("v") * 2).alias("v2"))
                 .to_pandas().sort_values("k").reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    got = q()
    assert got["k"].tolist() == want["k"].tolist()
    assert np.allclose(got["v2"], want["v2"])


def test_external_collect_order_by_limit(tiny_budget):
    """Chunked tournament top-n: per-chunk device sort+limit, one final
    small device sort over the spilled winners."""
    session = tiny_budget
    _mk(session, name="ext_topn")
    q = lambda: (session.table("ext_topn")
                 .sort(col("v").desc(), col("k"))
                 .limit(17).to_pandas().reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    got = q()
    assert got["k"].tolist() == want["k"].tolist()
    assert np.allclose(got["v"], want["v"])


def test_external_collect_order_by_host_merge(tiny_budget):
    """Pure ORDER BY: spilled runs merge on host honoring direction."""
    session = tiny_budget
    _mk(session, name="ext_sort")
    q = lambda: (session.table("ext_sort")
                 .sort(col("v").desc())
                 .to_pandas().reset_index(drop=True))
    _unforce(session)
    want = q()
    _force_spill(session)
    got = q()
    assert np.allclose(got["v"], want["v"])
    assert got["k"].head(50).tolist() == want["k"].head(50).tolist()


def test_external_collect_plain_limit(tiny_budget):
    """Plain LIMIT stops streaming once enough rows spilled; rows must
    come from the input (order unspecified, like the reference)."""
    session = tiny_budget
    pdf = _mk(session, name="ext_lim")
    _force_spill(session)
    got = session.table("ext_lim").limit(123).to_pandas()
    assert len(got) == 123
    assert set(got["k"]).issubset(set(pdf["k"]))


def test_tpch_q3_q5_parity_under_budget(session, tmp_path):
    """TPC-H Q3 (unbounded l_orderkey keys -> partial spill) and Q5
    (dictionary keys -> direct stream) with the scans forced
    out-of-core; parity vs the independent pandas goldens."""
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    path = str(tmp_path / "tpch_budget")
    write_parquet(path, 0.01)
    Q.register_tables(session, path)
    old = {k: session.conf.get(k) for k in (BUDGET_KEY, CHUNK_KEY,
                                            CACHE_KEY)}
    try:
        session.conf.set(BUDGET_KEY, 1 << 16)
        session.conf.set(CHUNK_KEY, 10_000)
        session.conf.set(CACHE_KEY, 0)
        for qname in ("q3", "q5"):
            got = Q.QUERIES[qname](session).to_pandas()
            for c in got.columns:
                if len(got) and got[c].dtype == object and \
                        got[c].iloc[0].__class__.__name__ == "Decimal":
                    got[c] = got[c].astype(float)
            want = G.GOLDEN[qname](path)
            if qname == "q5":
                got = got.sort_values("n_name").reset_index(drop=True)
                want = want.sort_values("n_name").reset_index(drop=True)
            G.compare(got.reset_index(drop=True), want,
                      float_rtol=1e-6, float_atol=1e-4)
    finally:
        for k, v in old.items():
            session.conf.set(k, v)

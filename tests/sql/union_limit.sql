SELECT k, v FROM golden_t WHERE k = 0
UNION ALL
SELECT k, v FROM golden_t WHERE k = 1
ORDER BY k, v LIMIT 7

SELECT k, v * 2 AS v2, s FROM golden_t WHERE v > 10 AND k <> 2 ORDER BY k, v2

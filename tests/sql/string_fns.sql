SELECT upper(s) AS u, length(s) AS l, v
FROM golden_t WHERE v < 8 ORDER BY u, l, v

SELECT k, count(DISTINCT s) AS ds
FROM golden_t GROUP BY k ORDER BY k

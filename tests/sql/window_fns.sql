SELECT k, v, row_number() OVER (PARTITION BY k ORDER BY v) AS rn,
       sum(v) OVER (PARTITION BY k ORDER BY v
                    ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS rsum
FROM golden_t ORDER BY k, v

SELECT d.name, count(*) AS c, sum(g.v) AS sv
FROM golden_t g JOIN golden_dim d ON g.k = d.k
GROUP BY d.name ORDER BY d.name

SELECT k, avg(v) AS av FROM golden_t GROUP BY k
HAVING count(*) > (SELECT min(k) + 2 FROM golden_dim) ORDER BY k

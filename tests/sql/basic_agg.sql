SELECT k, count(*) AS c, sum(v) AS s, min(v) AS mn, max(v) AS mx
FROM golden_t GROUP BY k ORDER BY k

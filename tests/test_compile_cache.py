"""Persistent cross-process AOT compile cache (execution/compile_cache.py).

Covers the ISSUE-14 acceptance surface: cross-process reuse proven
with a real subprocess (disk-hit counter + byte parity vs the cold
run), environment-fingerprint invalidation (an altered version string
misses cleanly, never crashes), maxBytes LRU eviction, corrupt-entry
chaos parity through the `compile_cache_load` seam, concurrent pooled
writers racing one key under lockwatch, and the warm-start surfaces
(`session.warmup()` / `SqlService.start()`).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_tpu.execution import compile_cache as CC
from spark_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _enable(session, base: str) -> str:
    cc_dir = os.path.join(base, "cc")
    session.conf.set(CC.ENABLED_KEY, True)
    session.conf.set(CC.DIR_KEY, cc_dir)
    # the fixture session's IN-MEMORY stage cache persists across
    # tests: clear it so this test's "cold" run actually consults
    # (and fills) its own fresh on-disk cache dir
    session._stage_cache.clear()
    return cc_dir


def _counter(session, name: str) -> float:
    return session.metrics.counter(name).value


def _query(session, domain: int = 64):
    from spark_tpu import functions as F
    from spark_tpu.functions import col
    return (session.range(1 << 12)
            .select(F.pmod(col("id"), domain).alias("k"))
            .group_by(col("k")).agg(F.sum(col("k")).alias("s"))
            .order_by(col("k")))


def _entry_files(cc_dir: str):
    if not os.path.isdir(cc_dir):
        return []
    return sorted(f for f in os.listdir(cc_dir)
                  if f.startswith("cc-") and f.endswith(".pkl"))


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_get_cache_disabled_by_default(session):
    from spark_tpu.config import Conf
    assert CC.get_cache(Conf()) is None
    c = Conf()
    c.set(CC.ENABLED_KEY, True)
    c.set(CC.DIR_KEY, "")
    assert CC.get_cache(c) is None  # no directory = no cache


def test_env_fingerprint_fields():
    fp = CC.env_fingerprint()
    for field in ("spark_tpu", "jax", "jaxlib", "backend",
                  "device_kind", "n_devices"):
        assert field in fp, fp
    assert "mesh_devices" not in fp

    class _Dev:
        def __init__(self, i):
            self.id = i

    class _Mesh:
        devices = np.array([_Dev(0), _Dev(1)])

    fpm = CC.env_fingerprint(_Mesh())
    assert fpm["mesh_shape"] == (2,) and fpm["mesh_devices"] == (0, 1)
    # a different gang over the same base environment keys differently
    assert CC.entry_hash("k", fp, ((), ())) \
        != CC.entry_hash("k", fpm, ((), ()))


def test_call_signature_distinguishes_dictionaries():
    """Column pytree aux embeds host dictionaries: two batches equal in
    shape but differing in dictionary CONTENT must sign differently —
    a deserialized Compiled whose out_tree carries trace-time
    dictionaries would silently decode wrong strings otherwise (the
    exact reason dispatch requires treedef equality, like jit)."""
    import pyarrow as pa

    from spark_tpu.columnar import Batch
    t1 = pa.table({"s": pa.array(["a", "b", "a"]).dictionary_encode()})
    t2 = pa.table({"s": pa.array(["a", "Z", "a"]).dictionary_encode()})
    b1, b2 = Batch.from_arrow(t1), Batch.from_arrow(t2)
    sig1, sig2 = CC.call_signature(([b1],)), CC.call_signature(([b2],))
    assert sig1[1] == sig2[1]          # same leaf shapes/dtypes
    assert sig1[0] != sig2[0]          # different treedef aux
    same = CC.call_signature(
        ([Batch.from_arrow(pa.table(
            {"s": pa.array(["a", "b", "a"]).dictionary_encode()}))],))
    assert same == sig1


def test_cached_stage_fn_requires_builder_for_novel_sig():
    fn = CC.CachedStageFn()
    with pytest.raises(RuntimeError, match="no jit builder"):
        fn(np.zeros(4))
    fn.bind_builder(lambda: (lambda *a: "jitted"))
    assert fn(np.zeros(4)) == "jitted"


def test_manifest_compaction_keeps_newest_chronological(tmp_path,
                                                        monkeypatch):
    """Compaction must keep the NEWEST records and leave the file in
    chronological order: readers reverse the file, so a newest-first
    rewrite would invert every later read and make the next compaction
    keep the stalest half."""
    monkeypatch.setattr(CC, "_MANIFEST_MAX_LINES", 6)
    monkeypatch.setattr(CC, "_MANIFEST_MAX_BYTES", 200)
    cc = CC.CompileCache(str(tmp_path), 0)
    for i in range(10):
        cc._note_seen(f"key-{i}", f"cc-{i}.pkl")
    names = [r["file"] for r in cc._read_manifest()]
    assert names[0] == "cc-9.pkl", names          # newest first
    assert "cc-0.pkl" not in names                # oldest compacted away
    cc._note_seen("key-x", "cc-x.pkl")            # appends stay newest
    assert cc._read_manifest()[0]["file"] == "cc-x.pkl"


def test_concurrent_eviction_is_miss_not_corruption(tmp_path,
                                                    monkeypatch):
    """A file vanishing between the exists() check and open() (another
    process's LRU eviction) is a plain disk miss — it must not warn or
    light the compile_cache_corrupt signal."""
    import warnings as w

    from spark_tpu.observability import MetricsRegistry
    cc = CC.CompileCache(str(tmp_path / "e"), 0)
    m = MetricsRegistry()
    monkeypatch.setattr(CC.os.path, "exists", lambda p: True)
    with w.catch_warnings():
        w.simplefilter("error")
        out = cc.load("k", None, (np.zeros(2),), metrics=m)
    assert out is None
    assert m.counter("compile_cache_disk_misses").value == 1
    assert m.counter("compile_cache_corrupt").value == 0


def test_lru_eviction_unit(tmp_path):
    cc = CC.CompileCache(str(tmp_path), max_bytes=3000)
    for i, name in enumerate(["cc-old.pkl", "cc-mid.pkl", "cc-new.pkl"]):
        p = os.path.join(str(tmp_path), name)
        with open(p, "wb") as f:
            f.write(b"x" * 1500)
        os.utime(p, (time.time() - 100 + i, time.time() - 100 + i))
    removed = cc.evict()
    assert removed == 1
    assert _entry_files(str(tmp_path)) == ["cc-mid.pkl", "cc-new.pkl"]


# ---------------------------------------------------------------------------
# in-process disk round trip
# ---------------------------------------------------------------------------


def test_disk_roundtrip_in_process(session, tmp_path):
    cc_dir = _enable(session, str(tmp_path))
    h0 = _counter(session, "compile_cache_disk_hits")
    w0 = _counter(session, "compile_cache_write_bytes")
    cold = _query(session).to_pandas()
    assert _entry_files(cc_dir), "no entry written on the cold miss"
    assert _counter(session, "compile_cache_write_bytes") > w0
    assert os.path.exists(os.path.join(cc_dir, "manifest.jsonl"))
    # a fresh-process miss is modeled by clearing the in-memory cache
    session._stage_cache.clear()
    qe = _query(session)._qe()
    warm = qe.collect().to_pandas()
    assert _counter(session, "compile_cache_disk_hits") >= h0 + 1
    assert _counter(session, "compile_cache_deser_ms") > 0
    pd.testing.assert_frame_equal(cold, warm)
    # the deserialize sub-span rode under the compile phase
    names = [s.name for s in qe.spans.spans]
    assert "deserialize" in names and "compile" in names, names
    disk_attr = [s.attrs.get("disk_hit") for s in qe.spans.spans
                 if s.name == "compile"]
    assert True in disk_attr, qe.spans.spans


def test_fingerprint_invalidation(session, tmp_path, monkeypatch):
    """An altered toolchain version string (the jaxlib-upgrade model)
    must MISS cleanly — recompile, not crash, and never load the
    stale executable."""
    _enable(session, str(tmp_path))
    cold = _query(session, domain=32).to_pandas()
    real = CC.env_fingerprint
    monkeypatch.setattr(
        CC, "env_fingerprint",
        lambda mesh=None: dict(real(mesh), jax="9.9.9-test"))
    session._stage_cache.clear()
    h0 = _counter(session, "compile_cache_disk_hits")
    m0 = _counter(session, "compile_cache_disk_misses")
    warm = _query(session, domain=32).to_pandas()
    assert _counter(session, "compile_cache_disk_hits") == h0
    assert _counter(session, "compile_cache_disk_misses") >= m0 + 1
    pd.testing.assert_frame_equal(cold, warm)


def test_maxbytes_lru_eviction_integration(session, tmp_path):
    """maxBytes=1: each store immediately evicts every OTHER entry
    (the just-written one is never its own victim), so re-running the
    first query is a disk miss that re-stores it."""
    cc_dir = _enable(session, str(tmp_path))
    session.conf.set(CC.MAX_BYTES_KEY, 1)
    _query(session, domain=16).to_pandas()
    assert len(_entry_files(cc_dir)) == 1
    first = _entry_files(cc_dir)[0]
    _query(session, domain=48).to_pandas()  # different plan, new entry
    assert _entry_files(cc_dir) != [first]
    assert len(_entry_files(cc_dir)) == 1
    session._stage_cache.clear()
    m0 = _counter(session, "compile_cache_disk_misses")
    _query(session, domain=16).to_pandas()
    assert _counter(session, "compile_cache_disk_misses") >= m0 + 1


def test_mesh_stage_roundtrip(session, tmp_path):
    """shard_map-wrapped mesh executables serialize/deserialize too,
    and their entries carry the gang fingerprint (shape + device ids)
    so a re-numbered or drained pool misses instead of loading a
    program compiled over other devices."""
    import pickle
    cc_dir = _enable(session, str(tmp_path))
    session.conf.set("spark_tpu.sql.mesh.size", 8)
    cold = _query(session, domain=24).to_pandas()
    assert _entry_files(cc_dir)
    session._stage_cache.clear()
    h0 = _counter(session, "compile_cache_disk_hits")
    warm = _query(session, domain=24).to_pandas()
    assert _counter(session, "compile_cache_disk_hits") >= h0 + 1
    pd.testing.assert_frame_equal(cold, warm)
    entries = []
    for f in _entry_files(cc_dir):
        with open(os.path.join(cc_dir, f), "rb") as fh:
            entries.append(pickle.load(fh))
    mesh_fps = [e["fingerprint"] for e in entries
                if "mesh_devices" in e.get("fingerprint", {})]
    assert mesh_fps and mesh_fps[0]["mesh_shape"] == (8,), entries


# ---------------------------------------------------------------------------
# corruption: chaos seam + torn files
# ---------------------------------------------------------------------------


def test_corrupt_entry_falls_back_and_overwrites(session, tmp_path):
    cc_dir = _enable(session, str(tmp_path))
    cold = _query(session).to_pandas()
    entry = os.path.join(cc_dir, _entry_files(cc_dir)[0])
    good_size = os.path.getsize(entry)
    with open(entry, "wb") as f:
        f.write(b"torn-write-garbage")
    session._stage_cache.clear()
    c0 = _counter(session, "compile_cache_corrupt")
    with pytest.warns(UserWarning, match="failed to load"):
        warm = _query(session).to_pandas()
    pd.testing.assert_frame_equal(cold, warm)
    assert _counter(session, "compile_cache_corrupt") >= c0 + 1
    # the bad entry was overwritten by the fresh compile...
    assert os.path.getsize(entry) == good_size
    # ...and serves the next process-miss again
    session._stage_cache.clear()
    h0 = _counter(session, "compile_cache_disk_hits")
    _query(session).to_pandas()
    assert _counter(session, "compile_cache_disk_hits") >= h0 + 1


def test_compile_cache_load_fault_seam(session, tmp_path):
    """The registered chaos seam: an injected fault during entry load
    counts as corrupt, falls back to a fresh compile and NEVER fails
    the query (golden parity)."""
    cc_dir = _enable(session, str(tmp_path))
    cold = _query(session).to_pandas()
    assert _entry_files(cc_dir), "cold run stored nothing — vacuous"
    session._stage_cache.clear()
    c0 = _counter(session, "compile_cache_corrupt")
    with faults.inject(session.conf, "compile_cache_load:fatal:1") as fp:
        with pytest.warns(UserWarning, match="failed to load"):
            warm = _query(session).to_pandas()
    assert fp.fired_log, "compile_cache_load never fired — vacuous"
    assert _counter(session, "compile_cache_corrupt") >= c0 + 1
    pd.testing.assert_frame_equal(cold, warm)


def test_second_signature_fills_wrapper_from_disk(session, tmp_path):
    """One stage key, two call signatures (same plan over two tables
    whose dictionary CONTENT differs): the 'never jit a known shape
    twice' contract holds per SIGNATURE — a warm key meeting a novel
    signature consults the disk (and persists a fresh compile), and
    warm_start installs every signature onto one wrapper."""
    from spark_tpu import functions as F
    from spark_tpu.functions import col
    cc_dir = _enable(session, str(tmp_path))
    d1 = pd.DataFrame({"s": ["a", "b", "a", "c"], "v": [1, 2, 3, 4]})
    d2 = pd.DataFrame({"s": ["x", "y", "x", "z"], "v": [1, 2, 3, 4]})

    def q():
        return (session.table("cc_sig").group_by(col("s"))
                .agg(F.sum(col("v")).alias("t"))
                .order_by(col("s"))).to_pandas()

    session.register_table("cc_sig", d1)
    r1 = q()                              # sig S1: AOT + store
    session.register_table("cc_sig", d2)
    w0 = _counter(session, "compile_cache_write_bytes")
    q()                                   # warm KEY, novel sig S2:
    assert _counter(session, "compile_cache_write_bytes") > w0, \
        "second signature's compile was not persisted"
    assert len(_entry_files(cc_dir)) >= 2
    # a fresh process touching S2 first, then S1: the S1 executable
    # must come off DISK, not a jit fallback
    session._stage_cache.clear()
    q()                                   # S2 from disk
    session.register_table("cc_sig", d1)
    h0 = _counter(session, "compile_cache_disk_hits")
    w1 = _counter(session, "compile_cache_write_bytes")
    r3 = q()                              # warm key, S1 from disk
    assert _counter(session, "compile_cache_disk_hits") >= h0 + 1
    assert _counter(session, "compile_cache_write_bytes") == w1
    pd.testing.assert_frame_equal(r1, r3)
    # warm_start stacks both signatures onto ONE wrapper
    cc = CC.get_cache(session.conf)
    fresh = {}
    assert cc.warm_start(fresh) >= 2
    assert any(len(v._compiled) >= 2 for v in fresh.values()
               if isinstance(v, CC.CachedStageFn)), \
        "warm start installed only one signature per stage key"


def test_trace_time_chaos_rules_bypass_disk_cache(session, tmp_path):
    """`join_build`/`shuffle` seams fire at TRACE time, once per
    (re)compile. A disk hit deserializes with zero trace, so while a
    rule on those sites is armed the disk cache must be bypassed —
    otherwise the rule's hit silently never arrives and the chaos test
    goes vacuous (and a transient-retry eviction stops re-tracing)."""
    from spark_tpu import functions as F
    from spark_tpu.functions import col
    _enable(session, str(tmp_path))
    dim = session.create_dataframe(pd.DataFrame(
        {"k2": np.arange(8, dtype=np.int64),
         "w": np.arange(8, dtype=np.int64)}), "cc_dim")

    def q():
        return (session.range(64)
                .select(F.pmod(col("id"), 8).alias("k"))
                .join(dim, left_on=col("k"), right_on=col("k2"))
                .agg(F.sum(col("w")).alias("s"))).to_pandas()

    clean = q()  # stores the stage's executable on disk
    session._stage_cache.clear()
    session.conf.set("spark_tpu.execution.backoffMs", 1)
    import warnings as w
    with w.catch_warnings():
        w.simplefilter("ignore")  # the retry warning is the point
        with faults.inject(session.conf,
                           "join_build:unavailable:1") as fp:
            got = q()
    assert fp.fired_log, \
        "trace-time seam never fired — a disk hit swallowed the trace"
    pd.testing.assert_frame_equal(clean, got)


# ---------------------------------------------------------------------------
# cross-process reuse (the acceptance criterion)
# ---------------------------------------------------------------------------

_CHILD = r'''
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
from spark_tpu import SparkTpuSession
from spark_tpu import functions as F
from spark_tpu.functions import col

path, cc_dir = sys.argv[1], sys.argv[2]
spark = SparkTpuSession.builder().get_or_create()
spark.conf.set("spark_tpu.sql.compileCache.enabled", True)
spark.conf.set("spark_tpu.sql.compileCache.dir", cc_dir)
df = (spark.read_parquet(path, "t").filter(col("v") > 10)
      .group_by(col("k")).agg(F.sum(col("v")).alias("s"),
                              F.count().alias("c"))
      .order_by(col("k")))
out = df.to_pandas()
m = spark.metrics
print("CHILD " + json.dumps({
    "csv": out.to_csv(index=False),
    "disk_hits": int(m.counter("compile_cache_disk_hits").value),
    "disk_misses": int(m.counter("compile_cache_disk_misses").value),
}), flush=True)
'''


def test_cross_process_reuse(tmp_path):
    """Two REAL processes over one cache dir: the second must open
    warm (disk hits >= 1, zero disk misses = zero backend recompiles
    of cached shapes) with byte-identical results."""
    rs = np.random.RandomState(7)
    data = pd.DataFrame({
        "k": rs.randint(0, 32, 4096).astype(np.int64),
        "v": rs.randint(0, 1000, 4096).astype(np.int64)})
    src = str(tmp_path / "t.parquet")
    data.to_parquet(src)
    cc_dir = str(tmp_path / "cc")

    def run_child():
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD, src, cc_dir],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=REPO)
        for line in proc.stdout.splitlines():
            if line.startswith("CHILD "):
                return json.loads(line[len("CHILD "):])
        raise AssertionError(
            f"child rc={proc.returncode}: {proc.stderr[-800:]}")

    cold = run_child()
    assert cold["disk_hits"] == 0 and cold["disk_misses"] >= 1, cold
    warm = run_child()
    assert warm["disk_hits"] >= 1, warm
    assert warm["disk_misses"] == 0, \
        f"warm process recompiled a cached shape: {warm}"
    assert warm["csv"] == cold["csv"]  # byte parity vs the cold run


# ---------------------------------------------------------------------------
# warm start
# ---------------------------------------------------------------------------


def test_session_warmup(session, tmp_path):
    from spark_tpu.config import Conf
    from spark_tpu.session import SparkTpuSession
    cc_dir = _enable(session, str(tmp_path))
    cold = _query(session).to_pandas()
    assert _entry_files(cc_dir)
    conf = Conf()
    conf.set(CC.ENABLED_KEY, True)
    conf.set(CC.DIR_KEY, cc_dir)
    s2 = SparkTpuSession(conf, register_active=False)
    n = s2.warmup()
    assert n >= 1 and len(s2._stage_cache) >= 1
    assert s2.metrics.counter("compile_cache_warm_entries").value == n
    # the warmed entry serves as an in-memory hit: no compiles at all
    got = _query(s2).to_pandas()
    assert s2.metrics.counter("compile_cache_hits").value >= 1
    assert s2.metrics.counter("compile_cache_disk_misses").value == 0
    pd.testing.assert_frame_equal(cold, got)
    # disabled cache: warmup is a 0 no-op
    from spark_tpu.config import Conf as _C
    s3 = SparkTpuSession(_C(), register_active=False)
    assert s3.warmup() == 0


def test_service_warm_start(tmp_path):
    """SqlService.start() replays the manifest into the sessions-shared
    stage cache (compileCache.warmStart), so a restarted serving
    process answers its first query without compiling."""
    from spark_tpu.config import Conf
    from spark_tpu.service.arbiter import install_arbiter
    from spark_tpu.service.server import SqlService

    data = pd.DataFrame({"k": np.arange(64, dtype=np.int64) % 8,
                         "v": np.arange(64, dtype=np.int64)})
    sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"
    cc_dir = str(tmp_path / "cc")
    conf = Conf()
    conf.set(CC.ENABLED_KEY, True)
    conf.set(CC.DIR_KEY, cc_dir)
    conf.set("spark_tpu.service.port", 0)

    def init(s):
        s.register_table("t", data)

    svc = SqlService(conf, init_session=init)
    try:
        rec, cold = svc.submit(sql)
        assert rec["status"] == "ok"
    finally:
        svc.stop()
        install_arbiter(None)
    assert _entry_files(cc_dir)

    svc2 = SqlService(conf, init_session=init).start()
    try:
        # warm start replays on a background thread AFTER the socket
        # binds (a full manifest must never delay /healthz): join it
        # before asserting
        assert svc2._warm_thread is not None
        svc2._warm_thread.join(timeout=120)
        assert len(svc2.arbiter.stage_cache) >= 1, \
            "warm start installed nothing"
        assert svc2.metrics.gauge("service_warm_stages").value >= 1
        rec2, warm = svc2.submit(sql)
        assert rec2["status"] == "ok"
        assert svc2.metrics.counter("compile_cache_hits").value >= 1
        assert svc2.metrics.counter(
            "compile_cache_disk_misses").value == 0
    finally:
        svc2.stop()
        install_arbiter(None)
    assert warm.to_pandas().equals(cold.to_pandas())


# ---------------------------------------------------------------------------
# concurrent writers (two pooled sessions racing one key) + lockwatch
# ---------------------------------------------------------------------------


def test_concurrent_writers_under_lockwatch(tmp_path):
    from spark_tpu.config import Conf
    from spark_tpu.service.arbiter import install_arbiter
    from spark_tpu.service.server import SqlService
    from spark_tpu.testing.lockwatch import LockWatch

    data = pd.DataFrame({"k": np.arange(256, dtype=np.int64) % 16,
                         "v": np.arange(256, dtype=np.int64)})
    sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"
    conf = Conf()
    cc_dir = str(tmp_path / "cc")
    conf.set(CC.ENABLED_KEY, True)
    conf.set(CC.DIR_KEY, cc_dir)
    svc = SqlService(conf,
                     init_session=lambda s: s.register_table("t", data))
    watch = LockWatch()
    try:
        # warm the pool so both session entries exist to be watched
        for name in ("a", "b"):
            svc.pool.get_or_create(name)
        watch.install_service(svc)
        cc = CC.get_cache(conf)
        watch.watch_attr(cc, "_lock", "execution.compile_cache")
        results, errors = [], []

        def run(name):
            try:
                for _ in range(2):
                    results.append(svc.submit(sql, session=name)[1])
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        ts = [threading.Thread(target=run, args=(n,))
              for n in ("a", "b")]
        [t.start() for t in ts]
        [t.join(300) for t in ts]
        assert not any(t.is_alive() for t in ts), "query thread wedged"
        assert not errors, errors
        assert len(results) == 4
        base = results[0].to_pandas()
        for table in results[1:]:
            pd.testing.assert_frame_equal(base, table.to_pandas())
        watch.assert_order_consistent()
    finally:
        watch.uninstall()
        svc.stop()
        install_arbiter(None)
    # the racing writers published a loadable entry
    assert _entry_files(cc_dir)
    fresh = {}
    assert cc.warm_start(fresh) >= 1

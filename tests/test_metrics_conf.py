"""Config wiring + per-operator metrics (round-2 'dead configuration'
findings made load-bearing)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col


def test_case_sensitive_resolution(session):
    session.register_table("cs_t", pd.DataFrame({"Mixed": [1, 2, 3]}))
    # default: case-insensitive fallback resolves 'mixed'
    got = session.table("cs_t").select(col("mixed")).to_pandas()
    assert got.iloc[:, 0].tolist() == [1, 2, 3]
    from spark_tpu.expr import AnalysisError
    session.conf.set("spark_tpu.sql.caseSensitive", True)
    try:
        with pytest.raises(AnalysisError):
            session.table("cs_t").select(col("mixed")).to_pandas()
    finally:
        session.conf.set("spark_tpu.sql.caseSensitive", False)


def test_agg_overflow_retry(session):
    """est_groups sized below the true distinct count must re-jit bigger,
    not drop groups."""
    rs = np.random.RandomState(5)
    pdf = pd.DataFrame({
        "k": (rs.permutation(3000) * 1_000_003).astype(np.int64),
        "v": np.ones(3000, dtype=np.int64)})
    session.register_table("ovf_t", pdf)
    session.conf.set("spark_tpu.sql.aggregate.estimatedGroups", 64)
    try:
        got = (session.table("ovf_t").group_by(col("k"))
               .agg(F.count().alias("c")).to_pandas())
    finally:
        session.conf.unset("spark_tpu.sql.aggregate.estimatedGroups")
    assert len(got) == 3000
    assert got["c"].sum() == 3000


def test_adaptive_disabled_raises(session):
    rs = np.random.RandomState(6)
    pdf = pd.DataFrame({
        "k": (rs.permutation(2000) * 7_000_003).astype(np.int64)})
    session.register_table("noadapt_t", pdf)
    session.conf.set("spark_tpu.sql.aggregate.estimatedGroups", 32)
    session.conf.set("spark_tpu.sql.adaptive.enabled", False)
    try:
        with pytest.raises(RuntimeError, match="adaptive"):
            (session.table("noadapt_t").group_by(col("k"))
             .agg(F.count().alias("c")).to_pandas())
    finally:
        session.conf.set("spark_tpu.sql.adaptive.enabled", True)
        session.conf.unset("spark_tpu.sql.aggregate.estimatedGroups")


def test_runtime_explain_rows(session):
    session.register_table("rt_t", pd.DataFrame(
        {"x": np.arange(100, dtype=np.int64)}))
    df = session.table("rt_t").filter(col("x") < 10)
    qe = df._qe()
    qe.execute_batch()
    text = qe.explain(runtime=True)
    assert "rows out: 10" in text, text
    assert "FilterExec" in text


def test_per_op_metrics_disable(session):
    session.conf.set("spark_tpu.sql.metrics.enabled", False)
    try:
        df = session.range(50).filter(col("id") > 40)
        qe = df._qe()
        qe.execute_batch()
        assert not any(k.startswith("rows_") for k in qe.last_metrics)
    finally:
        session.conf.set("spark_tpu.sql.metrics.enabled", True)


def test_event_log_and_history(session, tmp_path):
    log_dir = str(tmp_path / "events")
    session.conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    try:
        session.range(100).filter(col("id") > 50).to_pandas()
        session.range(10).to_pandas()
    finally:
        session.conf.set("spark_tpu.sql.eventLog.dir", "")
    from spark_tpu.history import read_event_log
    df = read_event_log(log_dir)
    assert len(df) == 2
    assert "phase_execution_s" in df.columns
    assert df["plan"].str.contains("RangeExec").all()


def test_checkpoint_truncates_lineage(session, tmp_path):
    df = session.range(50).filter(col("id") % 2 == 0)
    ck = df.checkpoint()
    from spark_tpu.plan.logical import Scan
    assert isinstance(ck.plan, Scan)
    assert ck.to_pandas()["id"].tolist() == list(range(0, 50, 2))
    # reliable variant writes parquet
    session.conf.set("spark_tpu.sql.checkpoint.dir", str(tmp_path / "ck"))
    try:
        ck2 = session.range(10).checkpoint()
    finally:
        session.conf.set("spark_tpu.sql.checkpoint.dir", "")
    assert ck2.to_pandas()["id"].tolist() == list(range(10))


def test_checkpoint_fingerprints_unique(session):
    """Code-review: shared '__checkpoint__' names cross-matched in the
    fingerprint-keyed data cache."""
    a = session.range(10).checkpoint()
    b = session.range(20).checkpoint()
    a.cache()
    assert len(a.to_pandas()) == 10
    assert len(b.to_pandas()) == 20
    a.unpersist()


def test_event_log_failure_does_not_break_query(session, tmp_path):
    bad = tmp_path / "afile"
    bad.write_text("x")
    session.conf.set("spark_tpu.sql.eventLog.dir", str(bad))
    try:
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = session.range(5).to_pandas()
        assert len(out) == 5
    finally:
        session.conf.set("spark_tpu.sql.eventLog.dir", "")

"""spark_tpu.graph: Pregel loop + PageRank + connected components
(reference: graphx Pregel.scala:59, lib/PageRank.scala,
lib/ConnectedComponents.scala)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.graph import Graph, connected_components, page_rank, pregel


@pytest.fixture
def chain_graph():
    v = pd.DataFrame({"id": [10, 20, 30, 40]})
    e = pd.DataFrame({"src": [10, 20, 30], "dst": [20, 30, 40]})
    return Graph(v, e)


def test_degrees(chain_graph):
    assert chain_graph.out_degrees().tolist() == [1, 1, 1, 0]
    assert chain_graph.in_degrees().tolist() == [0, 1, 1, 1]


def test_pregel_shortest_path(chain_graph):
    """Single-source shortest hop count via min-plus Pregel."""
    import jax.numpy as jnp
    INF = np.int64(1 << 40)
    init = np.full(4, INF)
    init[0] = 0  # source = vertex 10
    dist = pregel(chain_graph, init,
                  vprog=lambda s, m: jnp.minimum(s, m),
                  send=lambda s_src, s_dst: s_src + 1,
                  combine="min", max_iter=10)
    assert dist.tolist() == [0, 1, 2, 3]


def test_pagerank_star(session):
    """A star (everyone links to hub): the hub's rank dominates, ranks
    sum to n (reference normalization)."""
    n_leaves = 9
    v = pd.DataFrame({"id": np.arange(n_leaves + 1)})
    e = pd.DataFrame({"src": np.arange(1, n_leaves + 1),
                      "dst": np.zeros(n_leaves, np.int64)})
    g = Graph(v, e)
    pr = page_rank(g, num_iter=30).sort_values(
        "pagerank", ascending=False).reset_index(drop=True)
    assert pr["id"][0] == 0
    assert np.isclose(pr["pagerank"].sum(), n_leaves + 1, rtol=1e-6)
    # all leaves tie
    leaf_ranks = pr[pr["id"] != 0]["pagerank"]
    assert np.allclose(leaf_ranks, leaf_ranks.iloc[0])


def test_pagerank_two_cycle_uniform():
    v = pd.DataFrame({"id": [0, 1]})
    e = pd.DataFrame({"src": [0, 1], "dst": [1, 0]})
    pr = page_rank(Graph(v, e), num_iter=50)
    assert np.allclose(pr["pagerank"], [1.0, 1.0])


def test_connected_components():
    v = pd.DataFrame({"id": [1, 2, 3, 7, 8, 9]})
    e = pd.DataFrame({"src": [1, 2, 7, 8], "dst": [2, 3, 8, 9]})
    cc = connected_components(Graph(v, e)).sort_values("id")
    by_id = dict(zip(cc["id"], cc["component"]))
    assert by_id[1] == by_id[2] == by_id[3]
    assert by_id[7] == by_id[8] == by_id[9]
    assert by_id[1] != by_id[7]


def test_graph_from_dataframes(session):
    vdf = session.create_dataframe(pd.DataFrame({"id": [0, 1, 2]}))
    edf = session.create_dataframe(pd.DataFrame(
        {"src": [0, 1], "dst": [1, 2]}))
    g = Graph(vdf, edf)
    assert g.num_vertices == 3 and g.num_edges == 2
    cc = connected_components(g)
    assert cc["component"].nunique() == 1


def test_unknown_vertex_raises():
    v = pd.DataFrame({"id": [0, 1]})
    e = pd.DataFrame({"src": [0], "dst": [5]})
    with pytest.raises(ValueError):
        Graph(v, e)

"""Multi-chip SPMD parity: every query must produce identical results on
an 8-shard virtual CPU mesh and on a single chip (the `local-cluster`
analog of the reference's DistributedSuite, SURVEY.md section 4).

conftest.py forces 8 virtual CPU devices, so the collectives
(all_to_all / all_gather / psum) actually execute."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit

MESH_KEY = "spark_tpu.sql.mesh.size"


@pytest.fixture
def dist(session):
    """Flip the session into 8-shard mode for one test."""
    prev = session.conf.get(MESH_KEY)
    session.conf.set(MESH_KEY, 8)
    yield session
    session.conf.set(MESH_KEY, prev)


def _parity(session, build_df, sort_cols):
    """Run the same plan single-chip and distributed; compare as pandas."""
    session.conf.set(MESH_KEY, 0)
    want = (build_df().to_pandas().sort_values(sort_cols)
            .reset_index(drop=True))
    session.conf.set(MESH_KEY, 8)
    got = (build_df().to_pandas().sort_values(sort_cols)
           .reset_index(drop=True))
    session.conf.set(MESH_KEY, 0)
    assert len(got) == len(want), (got, want)
    for c in want.columns:
        g, w = got[c], want[c]
        if g.dtype.kind == "f" or w.dtype.kind == "f":
            assert np.allclose(g.fillna(np.nan), w.fillna(np.nan),
                               equal_nan=True), (c, got, want)
        else:
            assert g.fillna(-999).tolist() == w.fillna(-999).tolist(), \
                (c, got, want)


def test_distributed_groupby_direct(session):
    _parity(session,
            lambda: session.range(10_000)
            .group_by((col("id") % 97).alias("k"))
            .agg(F.count().alias("c"), F.sum(col("id")).alias("s")),
            ["k"])


def test_distributed_groupby_sort_path(session):
    pdf = pd.DataFrame({
        "k": np.random.RandomState(0).randint(0, 1000, 5000) * 1_000_003,
        "v": np.arange(5000, dtype=np.int64)})

    def build():
        df = session.create_dataframe(pdf)
        return df.group_by(col("k")).agg(
            F.sum(col("v")).alias("s"), F.count().alias("c"),
            F.min(col("v")).alias("mn"), F.max(col("v")).alias("mx"))

    _parity(session, build, ["k"])


def test_distributed_global_aggregate(session):
    _parity(session,
            lambda: session.range(12_345).agg(
                F.sum(col("id")).alias("s"), F.count().alias("c"),
                F.min(col("id")).alias("mn"), F.max(col("id")).alias("mx"),
                F.avg(col("id")).alias("a")),
            ["s"])


def test_distributed_join_shuffle(session):
    rs = np.random.RandomState(1)
    left = pd.DataFrame({"k": rs.randint(0, 500, 2000).astype(np.int64),
                         "lv": np.arange(2000, dtype=np.int64)})
    right = pd.DataFrame({"k": np.arange(500, dtype=np.int64),
                          "rv": np.arange(500, dtype=np.int64) * 10})

    def build():
        l = session.create_dataframe(left)
        r = session.create_dataframe(right)
        return l.join(r, on="k")

    _parity(session, build, ["lv"])


def test_distributed_join_many_to_many_outer(session):
    left = pd.DataFrame({"k": np.array([1, 2, 2, 3, 9], dtype=np.int64),
                         "lv": np.array([1, 2, 3, 4, 5], dtype=np.int64)})
    right = pd.DataFrame({"k": np.array([2, 2, 3, 7], dtype=np.int64),
                          "rv": np.array([20, 21, 30, 70], dtype=np.int64)})

    for how in ("inner", "left", "right", "outer"):
        def build():
            l = session.create_dataframe(left)
            r = session.create_dataframe(right)
            return l.join(r, on="k", how=how)

        _parity(session, build, ["lv", "rv"])


def test_distributed_string_join_broadcast(session):
    # small dim side -> planner picks the broadcast (all_gather) strategy
    fact = pd.DataFrame({
        "s": [f"key{i % 7}" for i in range(1000)],
        "v": np.arange(1000, dtype=np.int64)})
    dim = pd.DataFrame({"s": [f"key{i}" for i in range(7)],
                        "dv": np.arange(7, dtype=np.int64) * 100})

    def build():
        f = session.create_dataframe(fact)
        d = session.create_dataframe(dim)
        return f.join(d, on="s")

    _parity(session, build, ["v"])


def test_broadcast_strategy_planned(dist):
    fact = dist.create_dataframe(pd.DataFrame(
        {"k": np.arange(1000, dtype=np.int64) % 7,
         "v": np.arange(1000, dtype=np.int64)}), "fact")
    dim = dist.create_dataframe(pd.DataFrame(
        {"k": np.arange(7, dtype=np.int64),
         "dv": np.arange(7, dtype=np.int64)}), "dim")
    plan = fact.join(dim, on="k")._qe().executed_plan.tree_string()
    assert "strategy=broadcast" in plan
    assert "Replicated" in plan


def test_distributed_sort_global_order(session):
    rs = np.random.RandomState(2)
    pdf = pd.DataFrame({"x": rs.permutation(4000).astype(np.int64)})

    session.conf.set(MESH_KEY, 8)
    try:
        df = session.create_dataframe(pdf)
        out = df.sort(col("x").desc()).collect().column("x").to_pylist()
    finally:
        session.conf.set(MESH_KEY, 0)
    assert out == sorted(pdf["x"].tolist(), reverse=True)


def test_distributed_sort_limit(session):
    session.conf.set(MESH_KEY, 8)
    try:
        df = session.range(1000).sort(col("id").desc()).limit(5)
        assert df.collect().column("id").to_pylist() == [999, 998, 997, 996,
                                                         995]
    finally:
        session.conf.set(MESH_KEY, 0)


def test_distributed_string_groupby(session):
    pdf = pd.DataFrame({
        "s": [f"g{i % 13}" for i in range(3000)],
        "v": np.arange(3000, dtype=np.int64)})

    def build():
        return (session.create_dataframe(pdf)
                .group_by(col("s")).agg(F.sum(col("v")).alias("sv")))

    _parity(session, build, ["s"])


def test_distributed_join_copartition_subset_keys(session):
    # left side arrives hash-partitioned on a subset of the join keys:
    # the planner must still exchange BOTH sides on the full key list
    # (checking each child in isolation silently lost matches)
    rs = np.random.RandomState(3)
    base = pd.DataFrame({"a": rs.randint(0, 40, 600).astype(np.int64),
                         "b": rs.randint(0, 5, 600).astype(np.int64)})
    rdf_pd = pd.DataFrame({"a": np.arange(40, dtype=np.int64),
                           "b": np.arange(40, dtype=np.int64) % 5,
                           "rv": np.arange(40, dtype=np.int64)})

    prev = session.conf.get("spark_tpu.sql.autoBroadcastJoinThreshold")
    session.conf.set("spark_tpu.sql.autoBroadcastJoinThreshold", 0)
    try:
        def build():
            l = (session.create_dataframe(base)
                 .group_by(col("a")).agg(F.max(col("b")).alias("b")))
            r = session.create_dataframe(rdf_pd)
            return l.join(r, on=["a", "b"])

        _parity(session, build, ["a", "b"])
    finally:
        session.conf.set("spark_tpu.sql.autoBroadcastJoinThreshold", prev)


def test_distributed_full_outer_then_groupby(session):
    # full-outer output has NULL left keys scattered across shards: the
    # join must report UnknownPartitioning so the group-by re-exchanges
    left = pd.DataFrame({"k": np.array([1, 2, 3], dtype=np.int64),
                         "lv": np.array([1, 2, 3], dtype=np.int64)})
    right = pd.DataFrame({"k": np.array([3, 4, 5, 6], dtype=np.int64),
                          "rv": np.array([30, 40, 50, 60], dtype=np.int64)})

    def build():
        l = session.create_dataframe(left)
        r = session.create_dataframe(right)
        j = l.join(r, left_on=col("k"), right_on=col("k"), how="full")
        return j.group_by(col("k")).agg(F.count().alias("c"))

    _parity(session, build, ["k"])


def test_distributed_cross_join(session):
    def build():
        a = session.create_dataframe(pd.DataFrame(
            {"x": np.arange(20, dtype=np.int64)}))
        b = session.create_dataframe(pd.DataFrame(
            {"y": np.arange(7, dtype=np.int64)}))
        return a.cross_join(b)

    _parity(session, build, ["x", "y"])


def test_distributed_filter_project(session):
    _parity(session,
            lambda: session.range(5000)
            .filter((col("id") % 7) == lit(3))
            .select((col("id") * 2).alias("x")),
            ["x"])


def test_distributed_union(session):
    """Round-2 ADVICE high: UnionExec inherited SinglePartition and lost
    rows under a mesh (striped distinct per-shard output)."""
    a = pd.DataFrame({"k": np.arange(12, dtype=np.int64)})
    b = pd.DataFrame({"k": np.arange(100, 108, dtype=np.int64)})

    def build():
        return (session.create_dataframe(a, "ua")
                .union(session.create_dataframe(b, "ub")))

    _parity(session, build, ["k"])


def test_distributed_union_then_groupby(session):
    a = pd.DataFrame({"k": np.arange(20, dtype=np.int64) % 5})
    b = pd.DataFrame({"k": np.arange(20, dtype=np.int64) % 3})

    def build():
        return (session.create_dataframe(a, "uga")
                .union(session.create_dataframe(b, "ugb"))
                .group_by(col("k")).agg(F.count().alias("c")))

    _parity(session, build, ["k"])


def test_distributed_full_join_computed_key(session):
    """Round-2 ADVICE high: full-outer on a computed key fell back to a
    replicated build, duplicating unmatched build rows per shard."""
    left = pd.DataFrame({"x": np.arange(8, dtype=np.int64)})
    right = pd.DataFrame({"y": np.arange(4, 12, dtype=np.int64)})

    def build():
        return session.create_dataframe(left, "fl").join(
            session.create_dataframe(right, "fr"),
            left_on=col("x") + 0, right_on=col("y"), how="outer")

    _parity(session, build, ["x", "y"])


def test_distributed_skewed_exchange_retry(session):
    """Size-aware exchange: all rows hash to ONE destination shard, so the
    2x-uniform seed must overflow and the executor must re-jit with a
    bigger receive block (the exch_overflow stats loop)."""
    pdf = pd.DataFrame({"k": np.zeros(4000, dtype=np.int64),
                        "v": np.arange(4000, dtype=np.int64)})

    def build():
        return (session.create_dataframe(pdf, "skewed")
                .group_by(col("k"))
                .agg(F.sum(col("v")).alias("s"), F.count().alias("c")))

    _parity(session, build, ["k"])


def test_distributed_skewed_join_exchange(session):
    rs = np.random.RandomState(7)
    left = pd.DataFrame({"k": np.where(rs.rand(3000) < 0.9, 1,
                                       rs.randint(0, 50, 3000)).astype(np.int64),
                         "lv": np.arange(3000, dtype=np.int64)})
    right = pd.DataFrame({"k": np.arange(50, dtype=np.int64),
                          "rv": np.arange(50, dtype=np.int64) * 3})

    def build():
        # force the shuffle strategy (skewed probe side) by size: the big
        # left is the probe, small right under threshold broadcasts unless
        # we disable it
        prev = session.conf.get("spark_tpu.sql.autoBroadcastJoinThreshold")
        session.conf.set("spark_tpu.sql.autoBroadcastJoinThreshold", 0)
        try:
            df = session.create_dataframe(left, "skl").join(
                session.create_dataframe(right, "skr"), on="k")
        finally:
            session.conf.set("spark_tpu.sql.autoBroadcastJoinThreshold", prev)
        return df

    _parity(session, build, ["lv"])


def test_distributed_union_mixed_partitioning(session):
    """A replicated (SinglePartition) child of a union must be striped so
    the sharded concat holds exactly one copy (code-review finding)."""
    a = pd.DataFrame({"k": np.arange(6, dtype=np.int64)})
    b = pd.DataFrame({"k": np.arange(50, 70, dtype=np.int64)})

    def build():
        sorted_a = session.create_dataframe(a, "mua").sort(col("k"))
        return sorted_a.union(session.create_dataframe(b, "mub"))

    _parity(session, build, ["k"])


def test_distributed_range_sort(session):
    """Global sort = sampled range bounds + all_to_all + local sort —
    no full-dataset all_gather (round-2 weak #5)."""
    rs = np.random.RandomState(3)
    pdf = pd.DataFrame({"k": rs.randint(-1000, 1000, 5000).astype(np.int64),
                        "v": np.arange(5000, dtype=np.int64)})

    def build():
        return session.create_dataframe(pdf, "rsort").sort(
            col("k"), col("v"))

    session.conf.set(MESH_KEY, 8)
    try:
        got = build().to_pandas()
        plan = build()._qe().executed_plan.tree_string()
    finally:
        session.conf.set(MESH_KEY, 0)
    assert "RangePartitioning" in plan, plan
    want = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    # exact ORDER matters here (not just set equality)
    assert got["k"].tolist() == want["k"].tolist()
    assert got["v"].tolist() == want["v"].tolist()


def test_distributed_sort_desc_limit(session):
    rs = np.random.RandomState(4)
    pdf = pd.DataFrame({"k": rs.randint(0, 10**9, 3000).astype(np.int64)})

    def build():
        return session.create_dataframe(pdf, "rsl").sort(
            col("k").desc()).limit(7)

    session.conf.set(MESH_KEY, 8)
    try:
        got = build().to_pandas()
    finally:
        session.conf.set(MESH_KEY, 0)
    want = pdf.sort_values("k", ascending=False).head(7)
    assert got["k"].tolist() == want["k"].tolist()


def test_distributed_sort_skewed_keys(session):
    """Heavily skewed sort keys overflow the sampled buckets and must be
    recovered by the exchange retry loop."""
    pdf = pd.DataFrame({"k": np.concatenate([
        np.zeros(2500, dtype=np.int64),
        np.arange(100, dtype=np.int64) + 1])})

    def build():
        return session.create_dataframe(pdf, "rskew").sort(col("k"))

    session.conf.set(MESH_KEY, 8)
    try:
        got = build().to_pandas()
    finally:
        session.conf.set(MESH_KEY, 0)
    assert got["k"].tolist() == sorted(pdf["k"].tolist())


def test_distributed_streaming_aggregate(session):
    """Chunked scan streaming under the mesh: per-shard accumulator
    tables carried across host-ingested chunks (round-2 weak #7 — mesh
    runs used to materialize whole scans)."""
    import spark_tpu.execution.streaming_agg as SA

    rs = np.random.RandomState(9)
    pdf = pd.DataFrame({"v": rs.randint(0, 10**6, 5000).astype(np.int64)})
    session.register_table("stream_t", pdf)
    calls = []
    orig = SA.stream_scan_aggregate_mesh

    def spy(agg, mesh, conf, cache=None, recovery=None):
        out = orig(agg, mesh, conf, cache, recovery)
        calls.append(out is not None)
        return out

    SA.stream_scan_aggregate_mesh = spy
    prev_chunk = session.conf.get("spark_tpu.sql.execution.streamingChunkRows")
    session.conf.set("spark_tpu.sql.execution.streamingChunkRows", 1024)
    # disable the device cache so the (tiny) scan doesn't go resident
    prev_cache = session.conf.get("spark_tpu.sql.io.deviceCacheBytes")
    session.conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)
    try:
        def build():
            return (session.table("stream_t")
                    .group_by((col("v") % 37).alias("k"))
                    .agg(F.count().alias("c"), F.sum(col("v")).alias("s")))

        _parity(session, build, ["k"])
    finally:
        SA.stream_scan_aggregate_mesh = orig
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows",
                         prev_chunk)
        session.conf.set("spark_tpu.sql.io.deviceCacheBytes", prev_cache)
    assert any(calls), "mesh streaming path never engaged"

"""Plan-integrity verifier + plan-change tracer + differential fuzzer.

Contract under test: every EFFECTIVE optimizer-rule application is
invariant-checked (analysis/plan_integrity.py) — a deliberately broken
rule is caught BY NAME in full mode, surfaces as PLAN_INTEGRITY
findings in lite mode, and a nondeterministic rule trips the
batch-replay determinism check. The tracer records one row per
(batch, rule) and rides explain(rules=True) + the schema-v7
`rule_trace` event record (events_tool validation + history
rule_report). The differential fuzzer's pinned seeds and the two
engine bugs the first campaign surfaced (date-literal scan pushdown,
all-null dictionary columns) stay fixed.

The whole tier-1 suite runs under planChangeValidation=full (conftest
sets the registry default), so every other test doubles as a verifier
no-false-positives check.
"""

import datetime
import gc

import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu import functions as F
from spark_tpu.analysis import (PlanChangeTracer, PlanIntegrityError,
                                PlanIntegrityValidator)
from spark_tpu.analysis.plan_integrity import check_plan, render_trace
from spark_tpu.functions import col, lit
from spark_tpu.plan import logical as L
from spark_tpu.plan.optimizer import default_optimizer
from spark_tpu.plan.rules import Batch, Rule, RuleExecutor

VALIDATION_KEY = "spark_tpu.sql.planChangeValidation"
CHANGE_LOG_KEY = "spark_tpu.sql.planChangeLog"
EXCLUDED_KEY = "spark_tpu.sql.optimizer.excludedRules"


@pytest.fixture()
def pi_session(session):
    session.register_table("pi_t", pa.table({
        "a": pa.array([1, 2, 3, 4, None], pa.int64()),
        "b": pa.array([10.0, -1.5, None, 0.25, 3.0], pa.float64()),
        "c": pa.array(["x", "y", None, "x", "z"], pa.string())}))
    return session


def _mutant_cleanup(*classes):
    """Hide test-local Rule subclasses from the rule-registry lint: the
    pass only inspects classes whose __module__ lives under spark_tpu.,
    so repointing the module is enough for any later full-tree pass in
    this pytest process. (Reassigning __bases__ away from Rule is not
    possible — CPython rejects it when deallocators differ.)"""
    for cls in classes:
        cls.__module__ = "tests.__dead_mutant__"
    gc.collect()


# ---------------------------------------------------------------------------
# the verifier catches broken rules, by name
# ---------------------------------------------------------------------------


class TestVerifierCatchesMutants:
    def _bad_prune(self):
        class BadPrune(Rule):
            name = "BadPrune"
            schema_preserving = False

            def apply(self, plan):
                def f(node):
                    if isinstance(node, L.Project) \
                            and len(node.exprs) > 1:
                        return L.Project(node.child, node.exprs[:1])
                    return node
                return plan.transform_up(f)
        return BadPrune

    def test_full_mode_names_the_rule(self, pi_session):
        """The acceptance mutant: a rule that drops columns a parent
        still references raises PlanIntegrityError carrying the rule,
        batch and offending node."""
        BadPrune = self._bad_prune()
        try:
            df = pi_session.table("pi_t") \
                .select(col("a"), col("b")).filter(col("b") > lit(0.0))
            ex = RuleExecutor([Batch("bad", [BadPrune()])],
                              validator=PlanIntegrityValidator("full"))
            with pytest.raises(PlanIntegrityError) as ei:
                ex.execute(df.plan)
            assert ei.value.rule == "BadPrune"
            assert ei.value.batch == "bad"
            assert ei.value.check == "resolution"
            assert "'b'" in str(ei.value)
        finally:
            _mutant_cleanup(BadPrune)

    def test_lite_mode_collects_findings(self, pi_session):
        BadPrune = self._bad_prune()
        try:
            df = pi_session.table("pi_t") \
                .select(col("a"), col("b")).filter(col("b") > lit(0.0))
            v = PlanIntegrityValidator("lite")
            RuleExecutor([Batch("bad", [BadPrune()])],
                         validator=v).execute(df.plan)
            assert v.findings, "lite mode swallowed the violation"
            assert all(f.code == "PLAN_INTEGRITY" for f in v.findings)
            assert v.findings[0].op == "BadPrune"
            assert v.findings[0].detail["batch"] == "bad"
        finally:
            _mutant_cleanup(BadPrune)

    def test_schema_preservation_contract(self, pi_session):
        """A rule that reshapes the root schema WITHOUT declaring
        schema_preserving=False is charged with the drift."""
        class SilentReshape(Rule):
            name = "SilentReshape"
            schema_preserving = True  # lies

            def apply(self, plan):
                if isinstance(plan, L.Project):
                    return L.Project(plan.child, plan.exprs[:1])
                return plan
        try:
            df = pi_session.table("pi_t").select(col("a"), col("b"))
            ex = RuleExecutor(
                [Batch("reshape", [SilentReshape()], strategy="once")],
                validator=PlanIntegrityValidator("full"))
            with pytest.raises(PlanIntegrityError) as ei:
                ex.execute(df.plan)
            assert ei.value.rule == "SilentReshape"
            assert ei.value.check == "schema-preservation"
        finally:
            _mutant_cleanup(SilentReshape)

    def test_nondeterministic_rule_caught(self, pi_session):
        """The batch-replay determinism check: a rule whose output
        depends on call count produces a different plan on replay."""
        class Jitter(Rule):
            name = "Jitter"
            schema_preserving = True

            def __init__(self):
                self.n = 0

            def apply(self, plan):
                self.n += 1
                return L.Limit(plan, 100 + self.n)
        try:
            df = pi_session.table("pi_t").select(col("a"))
            ex = RuleExecutor(
                [Batch("jit", [Jitter()], strategy="once")],
                validator=PlanIntegrityValidator("full"))
            with pytest.raises(PlanIntegrityError) as ei:
                ex.execute(df.plan)
            assert ei.value.check == "determinism"
            assert ei.value.batch == "jit"
        finally:
            _mutant_cleanup(Jitter)

    def test_preexisting_violations_not_attributed(self, pi_session):
        """`SELECT k, k`-style duplicate names are LEGAL user plans;
        a rule that merely touches such a plan must not be blamed."""
        df = pi_session.table("pi_t").select(col("a"), col("a")) \
            .filter(col("a") > lit(0)).filter(col("a") < lit(10))
        assert any(v["check"] == "duplicate-names"
                   for v in check_plan(df.plan))
        v = PlanIntegrityValidator("full")
        # CombineFilters is effective here (two stacked filters)
        out = default_optimizer(pi_session.conf, validator=v) \
            .execute(df.plan)
        assert out is not None  # no PlanIntegrityError raised


# ---------------------------------------------------------------------------
# end-to-end: conf wiring, trace, explain, event log
# ---------------------------------------------------------------------------


class TestTraceAndConfWiring:
    def test_full_validation_clean_query(self, pi_session):
        pi_session.conf.set(VALIDATION_KEY, "full")
        df = pi_session.table("pi_t").filter(col("a") > lit(1)) \
            .group_by(col("c")).agg(F.avg(col("b")).alias("ab"))
        qe = df._qe()
        got = qe.collect()
        assert got.num_rows >= 1
        assert qe.rule_trace, "tracer recorded nothing"
        rec = qe.rule_trace[0]
        assert set(rec) >= {"batch", "rule", "invocations",
                            "effective", "ms"}
        assert sum(r["effective"] for r in qe.rule_trace) >= 1

    def test_explain_rules_section(self, pi_session):
        qe = pi_session.table("pi_t").filter(col("a") > lit(1))._qe()
        text = qe.explain(rules=True)
        assert "== Rule Trace ==" in text
        assert "effective" in text

    def test_change_log_diff(self, pi_session):
        pi_session.conf.set(CHANGE_LOG_KEY, True)
        df = pi_session.table("pi_t").filter(col("a") > lit(0)) \
            .filter(col("a") < lit(9))
        qe = df._qe()
        qe.collect()
        diffs = [r for r in qe.rule_trace if "diff" in r]
        assert diffs, "planChangeLog recorded no diff"
        assert any(ln.startswith(("-", "+"))
                   for ln in diffs[0]["diff"].splitlines())
        # render_trace indents the diff under the summary line
        lines = render_trace(qe.rule_trace)
        assert any("effective" in ln for ln in lines)

    def test_excluded_rules_ablation(self, pi_session):
        df = pi_session.table("pi_t").filter(col("a") > lit(0)) \
            .filter(col("a") < lit(9))
        pi_session.conf.set(EXCLUDED_KEY, "*")
        qe_off = df._qe()
        base = qe_off.collect().to_pandas()
        assert not qe_off.rule_trace, "excludedRules=* still ran rules"
        pi_session.conf.set(EXCLUDED_KEY, "CombineFilters")
        qe_abl = df._qe()
        got = qe_abl.collect().to_pandas()
        assert all(r["rule"] != "CombineFilters"
                   for r in qe_abl.rule_trace)
        pd.testing.assert_frame_equal(
            got.sort_values(list(got.columns)).reset_index(drop=True),
            base.sort_values(list(base.columns)).reset_index(drop=True))

    def test_rule_trace_rides_event_log(self, pi_session, tmp_path):
        from spark_tpu import history
        pi_session.conf.set("spark_tpu.sql.eventLog.dir", str(tmp_path))
        pi_session.conf.set(VALIDATION_KEY, "full")
        df = pi_session.table("pi_t").filter(col("a") > lit(0)) \
            .filter(col("a") < lit(9))
        df._qe().collect()
        pi_session.conf.set("spark_tpu.sql.eventLog.dir", "")
        events = history.read_event_log(str(tmp_path))
        assert len(events) >= 1
        trace = events.iloc[-1]["rule_trace"]
        assert isinstance(trace, list) and trace
        assert events.iloc[-1]["schema_version"] == 7
        rr = history.rule_report(events)
        assert {"batch", "rule", "invocations", "effective", "ms",
                "integrity_findings"} <= set(rr.columns)
        assert (rr["effective"] >= 1).any()


# ---------------------------------------------------------------------------
# events_tool v7 contract
# ---------------------------------------------------------------------------


def _event(**kw):
    e = {"schema_version": 7, "ts": 1.0, "status": "ok",
         "plan": "Scan", "query_id": 1}
    e.update(kw)
    return e


class TestEventsToolV7:
    def _validate(self, e):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "events_tool", os.path.join(os.path.dirname(__file__),
                                        "..", "scripts",
                                        "events_tool.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = []
        mod.validate_event(e, "t.jsonl", 1, out)
        return out

    def test_valid_v7_trace(self):
        assert self._validate(_event(rule_trace=[
            {"batch": "Filter pushdown", "rule": "CombineFilters",
             "invocations": 3, "effective": 1, "ms": 0.2,
             "diff": "-a\n+b"}])) == []

    def test_v6_carrying_rule_trace_rejected(self):
        out = self._validate(_event(schema_version=6, rule_trace=[]))
        assert any("v7 field 'rule_trace'" in p for p in out)

    def test_malformed_fields(self):
        out = self._validate(_event(rule_trace=[
            {"batch": "b", "rule": "R", "invocations": 1,
             "effective": 2, "ms": 0.1}]))
        assert any("effective exceeds invocations" in p for p in out)
        out = self._validate(_event(rule_trace=[
            {"batch": "b", "rule": 7, "invocations": 1,
             "effective": 0, "ms": 0.1}]))
        assert any("field 'rule'" in p for p in out)
        out = self._validate(_event(rule_trace={"not": "a list"}))
        assert any("must be a list" in p for p in out)

    def test_rule_report_counts_integrity_findings(self):
        from spark_tpu import history
        events = pd.DataFrame([{
            "ts": 1.0, "app": "a", "query_id": 1,
            "rule_trace": [{"batch": "b", "rule": "R",
                            "invocations": 2, "effective": 1,
                            "ms": 0.3}],
            "analysis_findings": [{"code": "PLAN_INTEGRITY"},
                                  {"code": "PLAN_INTEGRITY"},
                                  {"code": "UDF_OPAQUE_PREDICATE"}]}])
        rr = history.rule_report(events)
        assert len(rr) == 1
        assert rr.iloc[0]["integrity_findings"] == 2
        assert rr.iloc[0]["rule"] == "R"
        # a frame without the column degrades to empty, not a crash
        assert history.rule_report(pd.DataFrame([{"ts": 1}])).empty


# ---------------------------------------------------------------------------
# rule-registry lint (RL100)
# ---------------------------------------------------------------------------


class TestRuleRegistryLint:
    def test_real_tree_clean(self):
        from spark_tpu.analysis.lints import run_passes
        violations = [v for v in run_passes(["rule-registry"])
                      if v.severity == "error"]
        assert violations == [], [v.render() for v in violations]

    def test_synthetic_violations_detected(self):
        from spark_tpu.analysis.lints import LintContext
        from spark_tpu.analysis.lints.passes import RuleRegistryPass

        class Dup(Rule):
            name = "CombineFilters"  # collides with the real rule
        Dup.__module__ = "spark_tpu.__mutant__"
        try:
            out = RuleRegistryPass().finish(LintContext())
            msgs = [m for _, _, m in out]
            assert any("duplicate rule name 'CombineFilters'" in m
                       for m in msgs)
            assert any("Dup is not reachable" in m for m in msgs)
            assert any("Dup does not declare `schema_preserving`" in m
                       for m in msgs)
        finally:
            _mutant_cleanup(Dup)


# ---------------------------------------------------------------------------
# fuzzer: pinned seeds + minimized regressions from the first campaign
# ---------------------------------------------------------------------------


class TestFuzzRegressions:
    def test_pinned_seeds(self, session):
        """A handful of seeds through the full differential harness on
        every tier-1 run (the 500-seed campaign is scripts/plan_fuzz.py
        territory; seeds here keep the harness itself honest)."""
        from spark_tpu.testing import plan_fuzz
        for seed in (0, 1, 3):
            res = plan_fuzz.run_seed(session, seed, ablate="one")
            assert res["seed"] == seed

    @pytest.mark.slow
    def test_seed_sweep(self, session):
        from spark_tpu.testing import plan_fuzz
        res = plan_fuzz.run_campaign(session, range(40), ablate="one")
        assert res["failures"] == [], res["failures"]

    def test_canonical_bytes_total_order(self):
        """-0.0 vs 0.0 distinguished; NaN payloads canonicalized;
        row order irrelevant."""
        from spark_tpu.testing.plan_fuzz import canonical_bytes
        t1 = pa.table({"x": pa.array([0.0, 1.0])})
        t2 = pa.table({"x": pa.array([-0.0, 1.0])})
        t3 = pa.table({"x": pa.array([1.0, 0.0])})
        assert canonical_bytes(t1) != canonical_bytes(t2)
        assert canonical_bytes(t1) == canonical_bytes(t3)
        nan = float("nan")
        t4 = pa.table({"x": pa.array([nan, None])})
        t5 = pa.table({"x": pa.array([None, nan])})
        assert canonical_bytes(t4) == canonical_bytes(t5)

    def test_date_literal_scan_pushdown(self, session):
        """Campaign bug #1 (seeds 24/37 of the first run): pushing
        `date_col >= lit(datetime.date)` into a scan crashed —
        io/sources.py assumed date literals carry epoch days."""
        session.register_table("pi_dates", pa.table({
            "d": pa.array([datetime.date(2024, 1, 1),
                           datetime.date(2025, 6, 15), None],
                          pa.date32()),
            "v": pa.array([1, 2, 3], pa.int64())}))
        pivot = datetime.date(2025, 1, 1)
        df = session.table("pi_dates").filter(col("d") >= lit(pivot))
        session.conf.set(EXCLUDED_KEY, "*")
        base = df._qe().collect().to_pandas()
        session.conf.set(EXCLUDED_KEY, "")
        got = df._qe().collect().to_pandas()
        pd.testing.assert_frame_equal(got, base)
        assert got["v"].tolist() == [2]

    def test_all_null_string_column(self, session):
        """Campaign bug #2 (seeds 37/76 of the first run): an all-null
        string column has an EMPTY dictionary; comparing or sorting on
        it did a jnp.take from an empty axis."""
        session.register_table("pi_nulls", pa.table({
            "s": pa.array([None, None, None], pa.string()),
            "v": pa.array([3, 1, 2], pa.int64())}))
        t = session.table("pi_nulls")
        assert t.filter(col("s") == lit("x"))._qe() \
            .collect().num_rows == 0
        got = t.sort(col("s"), col("v"))._qe().collect()
        assert got.column("v").to_pylist() == [1, 2, 3]

    def test_all_null_string_unification(self, session):
        """Campaign bug #3 (seeds 138/219/240 of the 500-seed run):
        unifying a non-empty string dictionary with an all-null side
        (union / join payload) built a ZERO-length remap table and
        jnp.take'd from it (columnar.apply_code_remap)."""
        session.register_table("pi_us_l", pa.table({
            "k": pa.array([0, 1], pa.int32()),
            "s": pa.array(["x", "y"], pa.string())}))
        session.register_table("pi_us_r", pa.table({
            "k": pa.array([0, 1], pa.int32()),
            "s": pa.array([None, None], pa.string())}))
        l, r = session.table("pi_us_l"), session.table("pi_us_r")
        got = l.union(r).sort(col("s"), col("k"))._qe().collect()
        assert got.column("s").to_pylist() == [None, None, "x", "y"]
        j = l.join(r.select(col("k"), col("s").alias("s2")),
                   on="k", how="inner")
        out = j.sort(col("k"))._qe().collect()
        assert out.column("s").to_pylist() == ["x", "y"]
        assert out.column("s2").to_pylist() == [None, None]

    def test_float_group_key_rewrite_negative_zero(self, session):
        """Campaign bug #4 (seeds 166/284/455 of the 500-seed run):
        RewriteGroupKeyAggregates substituted the group-key
        representative for sum/min/max/avg(key) — but -0.0 == 0.0
        land in ONE float group while remaining distinct values, so
        max(d) over {-0.0, 0.0} is 0.0 while the kept key may be
        -0.0 (and sum(d) != d * count(d)). The rule must skip
        fractional keys; results must match the unoptimized plan
        byte-for-byte."""
        from spark_tpu.testing.plan_fuzz import canonical_bytes
        session.register_table("pi_negz", pa.table({
            "d": pa.array([0.0, -0.0, 5.0, None], pa.float64()),
            "k": pa.array([1, 2, 3, 4], pa.int32())}))
        df = session.table("pi_negz").group_by(col("d")).agg(
            F.count("*").alias("c"), F.max(col("d")).alias("mx"),
            F.sum(col("d")).alias("sm"))
        session.conf.set(EXCLUDED_KEY, "*")
        base = canonical_bytes(df._qe().collect())
        session.conf.set(EXCLUDED_KEY, "")
        qe = df._qe()
        assert canonical_bytes(qe.collect()) == base
        fired = [r["rule"] for r in qe.rule_trace
                 if r["rule"] == "RewriteGroupKeyAggregates"
                 and r["effective"]]
        assert not fired, "rewrite must not fire on a float group key"
        # guard against over-disabling: an integral key still rewrites
        dfi = session.table("pi_negz").group_by(col("k")).agg(
            F.count("*").alias("c"), F.sum(col("k")).alias("sk"))
        qi = dfi._qe()
        qi.collect()
        assert any(r["rule"] == "RewriteGroupKeyAggregates"
                   and r["effective"] for r in qi.rule_trace)

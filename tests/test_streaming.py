"""Structured streaming: the scripted micro-batch tests of the
reference's StreamTest DSL (AddData -> process -> CheckAnswer, stop /
restart recovery, crash-replay idempotence)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.streaming import MemoryStream


def _schema_df():
    return pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                         "v": pd.Series([], dtype=np.int64)})


def test_stateful_aggregate_across_batches(session, tmp_path):
    src = MemoryStream(session, _schema_df())
    q = (src.to_df()
         .group_by(F.pmod(col("k"), 10).alias("g"))
         .agg(F.sum(col("v")).alias("s"), F.count().alias("c"))
         .write_stream(str(tmp_path / "ck")))

    src.add_data(pd.DataFrame({"k": [1, 2, 11], "v": [10, 20, 30]}))
    q.process_available()
    out = q.latest().set_index("g")
    assert out.loc[1, "s"] == 40 and out.loc[1, "c"] == 2
    assert out.loc[2, "s"] == 20

    src.add_data(pd.DataFrame({"k": [1, 2], "v": [5, 7]}))
    q.process_available()
    out = q.latest().set_index("g")
    assert out.loc[1, "s"] == 45 and out.loc[1, "c"] == 3
    assert out.loc[2, "s"] == 27 and out.loc[2, "c"] == 2


def test_stateless_append(session, tmp_path):
    src = MemoryStream(session, _schema_df())
    q = (src.to_df().filter(col("v") > 10)
         .write_stream(str(tmp_path / "ck2"), output_mode="append"))
    src.add_data(pd.DataFrame({"k": [1, 2], "v": [5, 50]}))
    q.process_available()
    assert q.latest()["v"].tolist() == [50]
    src.add_data(pd.DataFrame({"k": [3], "v": [99]}))
    q.process_available()
    assert q.latest()["v"].tolist() == [99]
    assert len(q.results()) == 2


def test_restart_resumes_committed_state(session, tmp_path):
    ck = str(tmp_path / "ck3")
    src = MemoryStream(session, _schema_df())

    def build(s):
        return (s.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s"))
                .write_stream(ck))

    q = build(src)
    src.add_data(pd.DataFrame({"k": [0, 1], "v": [100, 200]}))
    q.process_available()
    q.stop()

    # new query instance over the same checkpoint: state + offsets resume
    q2 = build(src)
    src.add_data(pd.DataFrame({"k": [0], "v": [7]}))
    q2.process_available()
    out = q2.latest().set_index("g")
    assert out.loc[0, "s"] == 107
    assert out.loc[1, "s"] == 200


def test_crash_between_logs_replays_same_range(session, tmp_path):
    """Offset logged, commit missing (crash mid-batch): the restart must
    re-run exactly the logged range, not re-plan a bigger one."""
    ck = str(tmp_path / "ck4")
    src = MemoryStream(session, _schema_df())

    def build(s):
        return (s.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s"))
                .write_stream(ck))

    q = build(src)
    src.add_data(pd.DataFrame({"k": [0], "v": [10]}))
    q.process_available()

    # simulate a crash AFTER offset-log write, BEFORE commit: plan batch 1
    # over rows [1, 2) by hand, then "crash" (never run it)
    src.add_data(pd.DataFrame({"k": [0], "v": [32]}))
    q.offset_log.add(1, {"start": 1, "end": 2})
    # more data arrives while "down"
    src.add_data(pd.DataFrame({"k": [0], "v": [1000]}))

    q2 = build(src)
    q2.process_available()
    out = q2.latest().set_index("g")
    # batch 1 replayed [1,2) only; batch 2 then covered [2,3): total exact
    assert out.loc[0, "s"] == 1042
    import os
    assert sorted(os.listdir(os.path.join(ck, "commits"))) == ["0", "1", "2"]


def test_having_above_streaming_aggregate(session, tmp_path):
    """Code-review: operators above the aggregate were dropped."""
    src = MemoryStream(session, _schema_df())
    q = (src.to_df()
         .group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .filter(col("s") > 100)
         .write_stream(str(tmp_path / "ckh")))
    src.add_data(pd.DataFrame({"k": [0, 1], "v": [10, 500]}))
    q.process_available()
    out = q.latest()
    assert out["g"].tolist() == [1]
    assert out["s"].tolist() == [500]


def test_append_mode_with_aggregate_rejected(session, tmp_path):
    src = MemoryStream(session, _schema_df())
    with pytest.raises(ValueError, match="append"):
        (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.count().alias("c"))
         .write_stream(str(tmp_path / "cka"), output_mode="append"))


def test_stream_static_join_rejected(session, tmp_path):
    from spark_tpu.expr import AnalysisError
    static = session.create_dataframe(
        pd.DataFrame({"k": [1, 2], "w": [10, 20]}), "stream_static")
    src = MemoryStream(session, _schema_df())
    q = (src.to_df().join(static, on="k")
         .group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.count().alias("c"))
         .write_stream(str(tmp_path / "ckj")))
    src.add_data(pd.DataFrame({"k": [1], "v": [1]}))
    with pytest.raises(AnalysisError, match="join|unary"):
        q.process_available()


def test_string_group_key_rejected(session, tmp_path):
    src = MemoryStream(session, pd.DataFrame(
        {"s": pd.Series([], dtype=str), "v": pd.Series([], dtype=np.int64)}))
    q = (src.to_df().group_by(col("s")).agg(F.count().alias("c"))
         .write_stream(str(tmp_path / "cks")))
    src.add_data(pd.DataFrame({"s": ["a"], "v": [1]}))
    with pytest.raises(ValueError, match="string group keys"):
        q.process_available()


def test_checkpoint_pruning(session, tmp_path):
    ck = str(tmp_path / "ckp")
    session.conf.set(
        "spark_tpu.streaming.stateStore.snapshotEveryDeltas", 2)
    src = MemoryStream(session, _schema_df())
    q = (src.to_df().group_by(F.pmod(col("k"), 3).alias("g"))
         .agg(F.count().alias("c")).write_stream(ck))
    for i in range(8):
        src.add_data(pd.DataFrame({"k": [i], "v": [i]}))
        q.process_available()
    # compaction: nothing older than the newest snapshot at/below the
    # retained floor survives, and the retained chain still restores
    store = q._store
    committed = q._committed_batch
    snaps, deltas = store.snapshot_versions(), store.delta_versions()
    keep = max(v for v in snaps if v <= committed - 2)
    assert min(snaps) == keep, (snaps, keep)
    assert all(d > keep for d in deltas), (deltas, keep)
    assert store.load_tables(committed)["cnt"].sum() == 8
    out = q.latest()
    assert out["c"].sum() == 8


# -- event time / watermarks (WatermarkTracker.scala:1) ---------------------

def _ts(s):
    return pd.Timestamp(s)


def _event_df(session):
    from spark_tpu.streaming import MemoryStream
    schema = pd.DataFrame({"ts": [pd.Timestamp("2024-01-01")],
                           "v": [0.0]})
    stream = MemoryStream(session, schema)
    df = (stream.to_df()
          .with_watermark("ts", "10 seconds")
          .group_by(F.window(col("ts"), "10 seconds").alias("w"))
          .agg(F.sum(col("v")).alias("s"), F.count().alias("c")))
    return stream, df


def test_event_time_complete_out_of_order(session, tmp_path):
    stream, df = _event_df(session)
    q = df.write_stream(str(tmp_path / "ck"), output_mode="complete")
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:01"), _ts("2024-01-01 00:00:12")],
        "v": [1.0, 2.0]}))
    q.process_available()
    # an out-of-order (but within-watermark) row lands in window 0
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:05")], "v": [4.0]}))
    q.process_available()
    out = q.latest().sort_values("w").reset_index(drop=True)
    assert out["s"].tolist() == [5.0, 2.0]
    assert out["c"].tolist() == [2, 1]


def test_event_time_late_rows_dropped(session, tmp_path):
    stream, df = _event_df(session)
    q = df.write_stream(str(tmp_path / "ck"), output_mode="complete")
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:01:00")], "v": [1.0]}))
    q.process_available()   # watermark -> 00:00:50
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:20"),   # older than wm: dropped
               _ts("2024-01-01 00:00:55")],  # within wm: counted
        "v": [100.0, 2.0]}))
    q.process_available()
    out = q.latest().sort_values("w").reset_index(drop=True)
    assert out["s"].tolist() == [2.0, 1.0]


def test_event_time_append_emits_closed_windows_once(session, tmp_path):
    stream, df = _event_df(session)
    q = df.write_stream(str(tmp_path / "ck"), output_mode="append")
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:01"), _ts("2024-01-01 00:00:03")],
        "v": [1.0, 2.0]}))
    q.process_available()   # wm = 3s-10s: nothing closed, nothing out
    assert q.latest() is None or len(q.latest()) == 0 or \
        len(q.results()) == 0
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:30")], "v": [8.0]}))
    q.process_available()   # wm = 20s: window [0,10) closes and emits
    emitted = pd.concat(q.results(), ignore_index=True)
    assert len(emitted) == 1
    assert emitted["s"].tolist() == [3.0]
    assert emitted["w"][0] == _ts("2024-01-01 00:00:00")
    # the closed window is evicted from state
    assert (q._evstate["w"] != 0).all()
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:01:00")], "v": [16.0]}))
    q.process_available()   # wm = 50s: window [30,40) closes
    emitted = pd.concat(q.results(), ignore_index=True)
    assert emitted["s"].tolist() == [3.0, 8.0]  # first window NOT re-emitted


def test_event_time_recovery_restores_watermark_and_state(session,
                                                          tmp_path):
    from spark_tpu.streaming import MemoryStream
    ck = str(tmp_path / "ck")
    stream, df = _event_df(session)
    q = df.write_stream(ck, output_mode="complete")
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:01")], "v": [1.0]}))
    q.process_available()
    wm1 = q._wm
    # fresh query over the same checkpoint + the same source content
    q2 = df.write_stream(ck, output_mode="complete")
    assert q2._wm == wm1
    stream.add_data(pd.DataFrame({
        "ts": [_ts("2024-01-01 00:00:04")], "v": [2.0]}))
    q2.process_available()
    out = q2.latest()
    assert out["s"].tolist() == [3.0]

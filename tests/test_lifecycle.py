"""Query lifecycle control suite (execution/lifecycle.py + service/).

Covers the acceptance surface: cooperative cancellation at every
engine boundary (the cancel-point chaos matrix: cancellation delivered
at the nth boundary x {single-chip chunked, mesh, streaming,
service-async}, each cell proving structured error + no thread leak +
arbiter drained + byte-identical immediate re-run), end-to-end
deadlines (armed through retry backoff, admission queue and arbiter
lease waits; deadline < stageTimeout stops the recovery ladder), the
DELETE /queries/<id> endpoint (cancel-during-queue, idempotency,
cancel-after-finish 409, structured 404), and the per-session quotas
(admission maxConcurrent starvation + arbiter hbmShare)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from spark_tpu import Conf
from spark_tpu.execution import lifecycle
from spark_tpu.execution.failures import FailureClass, classify
from spark_tpu.service.arbiter import (DeviceResourceArbiter, _Owner,
                                       install_arbiter)
from spark_tpu.service.server import SqlService
from spark_tpu.testing import faults
from spark_tpu.testing.lockwatch import LockWatch
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
BUDGET_KEY = "spark_tpu.sql.memory.deviceBudget"
MESH_KEY = "spark_tpu.sql.mesh.size"
BACKOFF_KEY = "spark_tpu.execution.backoffMs"
DEADLINE_KEY = "spark_tpu.execution.queryDeadlineMs"
STAGE_TIMEOUT_KEY = "spark_tpu.execution.stageTimeoutMs"
INJECT_KEY = "spark_tpu.faults.inject"
PORT_KEY = "spark_tpu.service.port"
MAXC_KEY = "spark_tpu.service.maxConcurrent"
QT_KEY = "spark_tpu.service.queueTimeoutMs"
SESSION_MAXC_KEY = "spark_tpu.service.session.maxConcurrent"


@pytest.fixture(scope="module")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_lifecycle") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture()
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


@pytest.fixture()
def service(tpch_path):
    def make(**conf_overrides):
        conf = Conf()
        conf.set(PORT_KEY, 0)
        for k, v in conf_overrides.items():
            conf.set(k, v)
        svc = SqlService(
            conf, init_session=lambda s: Q.register_tables(s, tpch_path))
        made.append(svc)
        return svc

    made = []
    yield make
    for svc in made:
        svc.stop()
    install_arbiter(None)


def _assert_no_prefetch_leak():
    LockWatch().assert_no_thread_leak(timeout_s=10.0)


def _cancel_when_registered(session, qid, timeout_s=30.0):
    """Poll until the execution registers its token, then cancel."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if lifecycle.cancel(session.app_id, qid):
            return True
        time.sleep(0.002)
    return False


def _run_in_thread(qe):
    out = {}

    def run():
        try:
            out["table"] = qe.collect()
        except Exception as e:  # noqa: BLE001 — asserted by callers
            out["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, out


# ---------------------------------------------------------------------------
# CancelToken / classification (unit)
# ---------------------------------------------------------------------------


def test_token_cancel_and_deadline_classify_cancelled():
    tok = lifecycle.CancelToken()
    tok.cancel()
    with pytest.raises(lifecycle.QueryCancelledError) as exc:
        tok.check("chunk")
    assert "chunk" in str(exc.value)
    assert classify(exc.value) is FailureClass.CANCELLED

    tok2 = lifecycle.CancelToken(deadline_ms=1)
    time.sleep(0.01)
    with pytest.raises(lifecycle.QueryDeadlineError) as exc2:
        tok2.check()
    assert classify(exc2.value) is FailureClass.CANCELLED


def test_token_wait_wakes_on_cross_thread_cancel():
    tok = lifecycle.CancelToken()
    threading.Timer(0.05, tok.cancel).start()
    t0 = time.perf_counter()
    with pytest.raises(lifecycle.QueryCancelledError):
        tok.wait(30.0)
    assert time.perf_counter() - t0 < 5.0  # not the 30s sleep


def test_session_cancel_unknown_query_returns_false(session):
    assert session.cancel(999999) is False


# ---------------------------------------------------------------------------
# Cancel during retry backoff: returns within ~a tick, not backoffMs
# ---------------------------------------------------------------------------


def test_cancel_during_backoff_returns_promptly(tpch_session):
    s = tpch_session
    # one transient fault, then a HUGE backoff: min first-retry sleep
    # is backoffMs * 2^0 * 0.5 = 15s — the cancel must not wait it out
    s.conf.set(BACKOFF_KEY, 30000.0)
    with faults.inject(s.conf, "stage_run:unavailable:1"):
        qe = Q.q1(s)._qe()
        t, out = _run_in_thread(qe)
        assert _cancel_when_registered(s, qe.query_id)
        t0 = time.perf_counter()
        t.join(10)
        assert not t.is_alive()
        assert time.perf_counter() - t0 < 10
    assert isinstance(out.get("error"), lifecycle.QueryCancelledError)
    # the cancel action landed in fault_summary (history FAULT_ACTIONS)
    assert qe.fault_summary.get("cancel") == 1
    # and the Chrome-trace instant span
    assert any(sp.name == "cancelled" for sp in qe.spans.spans)


# ---------------------------------------------------------------------------
# Deadline interplay: deadline < stageTimeout stops the ladder
# ---------------------------------------------------------------------------


def test_deadline_beats_stage_timeout_and_stops_ladder(tpch_session):
    s = tpch_session
    s.conf.set(STAGE_TIMEOUT_KEY, 500)
    s.conf.set(DEADLINE_KEY, 350.0)
    try:
        # a 5s slow fault at the pre-dispatch seam: the interruptible
        # sleep is capped by the 350ms budget and raises the DEADLINE
        # error — never StageTimeoutError, never a retry
        with faults.inject(s.conf, "stage_run:slow:1:5000"):
            qe = Q.q1(s)._qe()
            t0 = time.perf_counter()
            with pytest.raises(lifecycle.QueryDeadlineError):
                qe.collect()
            assert time.perf_counter() - t0 < 4.0
        assert "stage_timeout" not in qe.fault_summary
        assert "transient_retry" not in qe.fault_summary
        assert qe.fault_summary.get("cancel") == 1
        assert s.metrics.counter("query_deadline_exceeded").value >= 1
    finally:
        s.conf.set(DEADLINE_KEY, 0.0)
        s.conf.set(STAGE_TIMEOUT_KEY, 0)


def test_deadline_fires_inside_retry_backoff(tpch_session):
    s = tpch_session
    s.conf.set(BACKOFF_KEY, 60000.0)
    s.conf.set(DEADLINE_KEY, 400.0)
    try:
        with faults.inject(s.conf, "stage_run:unavailable:1"):
            qe = Q.q1(s)._qe()
            t0 = time.perf_counter()
            with pytest.raises(lifecycle.QueryDeadlineError):
                qe.collect()
            # the 30s+ backoff sleep was cut at the deadline budget
            assert time.perf_counter() - t0 < 5.0
    finally:
        s.conf.set(DEADLINE_KEY, 0.0)


# ---------------------------------------------------------------------------
# Arbiter: lease-wait deadline + per-session hbmShare quota (unit)
# ---------------------------------------------------------------------------


def test_lease_wait_respects_deadline_token():
    arb = DeviceResourceArbiter(1000)
    o1 = _Owner("s1:q1")
    assert arb.try_acquire(o1, "k1", 1000)
    ctx = lifecycle.install(lifecycle.CancelToken(deadline_ms=200))
    try:
        t0 = time.perf_counter()
        with pytest.raises(lifecycle.QueryDeadlineError):
            arb.try_acquire(_Owner("s2:q1"), "k2", 500, wait_ms=30000)
        assert time.perf_counter() - t0 < 5.0  # not the 30s wait
    finally:
        lifecycle.uninstall(ctx)
    arb.release(o1)
    assert arb.stats()["leased_bytes"] == 0


def test_lease_wait_wakes_on_cancel():
    arb = DeviceResourceArbiter(1000)
    o1 = _Owner("s1:q1")
    assert arb.try_acquire(o1, "k1", 1000)
    tok = lifecycle.CancelToken()
    ctx = lifecycle.install(tok)
    try:
        threading.Timer(0.1, tok.cancel).start()
        t0 = time.perf_counter()
        with pytest.raises(lifecycle.QueryCancelledError):
            arb.try_acquire(_Owner("s2:q1"), "k2", 500, wait_ms=30000)
        assert time.perf_counter() - t0 < 5.0
    finally:
        lifecycle.uninstall(ctx)


def test_hbm_share_caps_one_session_group():
    from spark_tpu.observability import MetricsRegistry
    m = MetricsRegistry()
    arb = DeviceResourceArbiter(1000, metrics=m)
    greedy1, greedy2 = _Owner("greedy:q1"), _Owner("greedy:q2")
    other = _Owner("other:q1")
    # share 0.25 => 250-byte cap per session group
    assert arb.try_acquire(greedy1, "k1", 200, share=0.25)
    assert not arb.try_acquire(greedy2, "k2", 100, share=0.25)
    assert m.counter("session_quota_rejections").value == 1
    # the other session still leases within ITS OWN share — greedy's
    # denial never consumed the pool
    assert arb.try_acquire(other, "k3", 200, share=0.25)
    # denial memoized per (owner, key): a later identical ask is a
    # stable verdict, not a flip-flop
    assert not arb.try_acquire(greedy2, "k2", 100, share=0.25)
    arb.release(greedy1)
    arb.release(other)
    assert arb.stats()["leased_bytes"] == 0


# ---------------------------------------------------------------------------
# Post-cancel byte parity on Q3 (engine level)
# ---------------------------------------------------------------------------


def test_post_cancel_rerun_byte_parity_q3(tpch_session):
    s = tpch_session
    s.conf.set(CHUNK_KEY, 1024)
    s.conf.set(BUDGET_KEY, 1)  # force the chunked spill path
    baseline = Q.q3(s)._qe().collect()

    qe = Q.q3(s)._qe()
    t, out = _run_in_thread(qe)
    assert _cancel_when_registered(s, qe.query_id)
    t.join(30)
    assert not t.is_alive()
    # fast queries may finish before the cancel lands — the contract
    # under test is the CANCELLED path, so only assert when it took
    if "error" in out:
        assert isinstance(out["error"], lifecycle.QueryCancelledError)
    _assert_no_prefetch_leak()
    again = Q.q3(s)._qe().collect()
    assert again.equals(baseline)  # byte-identical Arrow tables


# ---------------------------------------------------------------------------
# Cancel-point chaos matrix: cancellation delivered at the nth
# cooperative boundary x execution shape. Every cell must terminate
# with the structured error, leak no worker thread, drain the arbiter
# (when installed) and leave the engine able to reproduce the
# uninterrupted result byte-identically.
# ---------------------------------------------------------------------------


def _matrix_sweep(s, make_qe, baseline, max_n=48):
    """Sweep cancel_point:cancel:n until a run completes without the
    rule firing (n outran the query's boundary count). Returns the
    number of cancelled cells (must be >= 1)."""
    cancelled_cells = 0
    n = 1
    while n <= max_n:
        with faults.inject(s.conf, f"cancel_point:cancel:{n}") as plan:
            qe = make_qe()
            try:
                table = qe.collect()
                fired = any(site == "cancel_point"
                            for site, _, _ in plan.fired_log)
                if not fired:
                    break  # past the last boundary: sweep complete
                # the rule fired on the FINAL boundary of a run whose
                # work was already done — still a clean completion
                assert table.equals(baseline)
            except lifecycle.QueryCancelledError:
                cancelled_cells += 1
                _assert_no_prefetch_leak()
                from spark_tpu.service.arbiter import get_arbiter
                arb = get_arbiter()
                if arb is not None:
                    assert arb.stats()["leased_bytes"] == 0
                    assert arb.stats()["owners"] == 0
        # immediate identical re-run, chaos disarmed: byte parity
        again = make_qe().collect()
        assert again.equals(baseline)
        # dense early (scan/compile/attempt boundaries), sparser into
        # the chunk run to bound the sweep's wall clock
        n += 1 if n < 8 else 4
    assert cancelled_cells >= 1
    return cancelled_cells


def test_cancel_matrix_single_chip_chunked(tpch_session):
    s = tpch_session
    s.conf.set(CHUNK_KEY, 1024)
    s.conf.set(BUDGET_KEY, 1)  # chunked spill path: chunk boundaries
    baseline = Q.q1(s)._qe().collect()
    cells = _matrix_sweep(s, lambda: Q.q1(s)._qe(), baseline)
    assert cells >= 2  # at least pre-stream + chunk boundaries


def test_cancel_matrix_mesh(tpch_session):
    s = tpch_session
    s.conf.set(MESH_KEY, 8)
    s.conf.set(CHUNK_KEY, 1024)
    try:
        baseline = Q.q1(s)._qe().collect()
        cells = _matrix_sweep(s, lambda: Q.q1(s)._qe(), baseline,
                              max_n=32)
        assert cells >= 1
    finally:
        s.conf.set(MESH_KEY, 0)


def test_cancel_matrix_streaming_trigger(session, tmp_path):
    import numpy as np
    import pandas as pd
    from spark_tpu import functions as F
    from spark_tpu.functions import col
    from spark_tpu.streaming import MemoryStream
    s = session
    schema = pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                           "v": pd.Series([], dtype=np.int64)})
    stream = MemoryStream(s, schema)
    q = (stream.to_df()
         .group_by(F.pmod(col("k"), 4).alias("g"))
         .agg(F.sum(col("v")).alias("total"))
         .write_stream(str(tmp_path / "ck")))
    stream.add_data(pd.DataFrame({"k": [0, 1, 1], "v": [1, 2, 3]}))
    # cancellation at the trigger boundary: nothing of the batch
    # commits, and a later drain is exactly-once
    with faults.inject(s.conf, "cancel_point:cancel:1"):
        ctx = lifecycle.install(lifecycle.CancelToken())
        try:
            with pytest.raises(lifecycle.QueryCancelledError):
                q.process_available()
        finally:
            lifecycle.uninstall(ctx)
    assert q.latest() is None  # no batch committed
    q.process_available()  # disarmed: drains exactly-once
    out = q.latest().set_index("g")
    assert out.loc[0, "total"] == 1 and out.loc[1, "total"] == 5


# ---------------------------------------------------------------------------
# Service: DELETE /queries/<id> end to end
# ---------------------------------------------------------------------------


def _post_sql(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _http(port, method, path):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll_terminal(svc, rid, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        rec = svc.query_snapshot(rid)
        if rec and rec.get("status") not in ("submitted", "running"):
            return rec
        time.sleep(0.02)
    raise AssertionError(f"query {rid} never reached a terminal "
                         f"status: {svc.query_snapshot(rid)}")


def test_delete_running_query_bounded_latency(service):
    svc = service()
    svc.start()
    port = svc.port
    # chunked Q1 with a 10s interruptible slow fault mid-stream: the
    # uninterrupted run is >= 10s, so a < 3s cancel proves the DELETE
    # landed at a boundary (and the slow sleep woke on cancellation)
    status, body = _post_sql(port, {
        "sql": "select l_returnflag, sum(l_quantity) as s from "
               "lineitem group by l_returnflag",
        "mode": "async",
        "conf": {CHUNK_KEY: 512, BUDGET_KEY: 1,
                 INJECT_KEY: "stream_chunk:slow:2:10000"}})
    assert status == 202
    rid = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rec = svc.query_snapshot(rid)
        if rec.get("status") == "running":
            break
        time.sleep(0.01)
    time.sleep(0.2)  # let it get into the chunk loop / slow sleep
    t0 = time.perf_counter()
    code, resp = _http(port, "DELETE", f"/queries/{rid}")
    assert code == 200 and resp["status"] == "cancel_requested"
    rec = _poll_terminal(svc, rid, timeout_s=15)
    latency = time.perf_counter() - t0
    assert rec["status"] == "cancelled", rec
    assert rec["error"]["error"] == "QUERY_CANCELLED"
    assert latency < 3.0, f"cancel took {latency:.2f}s"
    _assert_no_prefetch_leak()
    assert svc.arbiter.stats()["leased_bytes"] == 0
    # cancelled status flows into the listing filter
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/queries?status=cancelled") as r:
        listing = json.loads(r.read())
    assert any(q["id"] == rid for q in listing["queries"])
    # cancel-after-finish: 409, structured
    code, resp = _http(port, "DELETE", f"/queries/{rid}")
    assert code == 409 and resp["error"] == "QUERY_FINISHED"
    # immediate clean re-run of the same query: parity with a direct
    # engine run (chaos disarmed via fresh conf override)
    status, body = _post_sql(port, {
        "sql": "select l_returnflag, sum(l_quantity) as s from "
               "lineitem group by l_returnflag",
        "conf": {INJECT_KEY: "", BUDGET_KEY: 0}})
    assert status == 200 and body["row_count"] >= 1


def test_delete_queued_async_never_executes(service):
    svc = service(**{MAXC_KEY: 1, QT_KEY: 60000})
    svc.start()
    port = svc.port
    # occupy the single slot with a slow query on session "a"
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from lineitem",
        "session": "a", "mode": "async",
        "conf": {"spark_tpu.faults.inject": "stage_run:slow:1:2500"}})
    assert status == 202
    rid_a = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if svc.query_snapshot(rid_a).get("status") == "running":
            break
        time.sleep(0.01)
    # a DIFFERENT session queues behind the slot
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from orders",
        "session": "b", "mode": "async"})
    assert status == 202
    rid_b = body["query_id"]
    time.sleep(0.2)  # parked in the admission queue
    code, resp = _http(port, "DELETE", f"/queries/{rid_b}")
    assert code == 200
    rec_b = _poll_terminal(svc, rid_b, timeout_s=10)
    assert rec_b["status"] == "cancelled"
    assert "started_ts" not in rec_b  # never executed
    assert svc.metrics.counter("query_cancelled").value >= 1
    # slot math intact: the running query finishes, and a fresh
    # submission still admits + executes
    _poll_terminal(svc, rid_a, timeout_s=30)
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from orders", "session": "b"})
    assert status == 200
    stats = svc.admission.stats()
    assert stats["running"] == 0 and stats["queued"] == 0


def test_delete_unknown_and_double_delete(service):
    svc = service()
    svc.start()
    port = svc.port
    # structured 404, same error shape as the admission bodies
    code, resp = _http(port, "DELETE", "/queries/q-999")
    assert code == 404
    assert resp["error"] == "NOT_FOUND" and "message" in resp
    assert resp["query_id"] == "q-999"
    # GET of an unknown id: structured too
    code, resp = _http(port, "GET", "/queries/q-999")
    assert code == 404 and resp["error"] == "NOT_FOUND"
    assert resp["query_id"] == "q-999"
    # double-DELETE while running is idempotent (two 200s)
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from lineitem", "mode": "async",
        "conf": {"spark_tpu.faults.inject": "stage_run:slow:1:2577"}})
    rid = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if svc.query_snapshot(rid).get("status") == "running":
            break
        time.sleep(0.01)
    code1, resp1 = _http(port, "DELETE", f"/queries/{rid}")
    code2, resp2 = _http(port, "DELETE", f"/queries/{rid}")
    assert code1 == 200
    assert code2 in (200, 409)  # 409 only if it already stopped
    rec = _poll_terminal(svc, rid, timeout_s=15)
    assert rec["status"] == "cancelled"


def test_service_deadline_in_admission_queue(service):
    svc = service(**{MAXC_KEY: 1, QT_KEY: 60000})
    svc.start()
    port = svc.port
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from lineitem",
        "session": "a", "mode": "async",
        "conf": {"spark_tpu.faults.inject": "stage_run:slow:1:2654"}})
    rid_a = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if svc.query_snapshot(rid_a).get("status") == "running":
            break
        time.sleep(0.01)
    # queued request with a 400ms end-to-end deadline: it must fail
    # with the DEADLINE error from inside the queue wait — not wait
    # out the 60s admission timeout
    t0 = time.perf_counter()
    status, resp = _post_sql(port, {
        "sql": "select count(*) as n from orders", "session": "b",
        "conf": {DEADLINE_KEY: 400.0}})
    assert status == 504, resp
    assert resp["error"] == "QUERY_DEADLINE_EXCEEDED"
    assert time.perf_counter() - t0 < 10.0
    _poll_terminal(svc, rid_a, timeout_s=30)


def test_session_quota_starvation(service):
    svc = service(**{SESSION_MAXC_KEY: 1, QT_KEY: 60000,
                     MAXC_KEY: 4})
    svc.start()
    port = svc.port
    # greedy session's first request occupies its quota slot
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from lineitem",
        "session": "greedy", "mode": "async",
        "conf": {"spark_tpu.faults.inject": "stage_run:slow:1:2731"}})
    assert status == 202
    rid_1 = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if svc.query_snapshot(rid_1).get("status") == "running":
            break
        time.sleep(0.01)
    # greedy's second request 429s with the structured quota error
    status, resp = _post_sql(port, {
        "sql": "select count(*) as n from orders",
        "session": "greedy"})
    assert status == 429, resp
    assert resp["error"] == "SESSION_QUOTA_EXCEEDED"
    assert svc.metrics.counter("session_quota_rejections").value >= 1
    # another session proceeds untouched
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from orders", "session": "other"})
    assert status == 200
    # greedy frees its slot -> admitted again
    _poll_terminal(svc, rid_1, timeout_s=30)
    status, body = _post_sql(port, {
        "sql": "select count(*) as n from orders", "session": "greedy"})
    assert status == 200
    assert svc.session_quota.stats()["sessions_in_flight"] == {}


def test_cancel_matrix_service_async(service):
    """The service-async shape of the cancel matrix: cancellation via
    the cancel_point seam inside a service-run query — structured
    record status, drained arbiter, clean re-run parity over HTTP."""
    svc = service(**{"spark_tpu.service.hbmBudget": 1 << 30})
    svc.start()
    port = svc.port
    sql = ("select l_returnflag, sum(l_quantity) as s from lineitem "
           "group by l_returnflag")
    status, base = _post_sql(port, {
        "sql": sql, "conf": {CHUNK_KEY: 512}})
    assert status == 200
    cancelled = 0
    for n in (1, 2, 4, 7, 11):
        status, body = _post_sql(port, {
            "sql": sql, "mode": "async",
            "conf": {CHUNK_KEY: 512,
                     INJECT_KEY: f"cancel_point:cancel:{n}"}})
        assert status == 202
        rec = _poll_terminal(svc, body["query_id"], timeout_s=60)
        if rec["status"] == "cancelled":
            cancelled += 1
            assert rec["error"]["error"] == "QUERY_CANCELLED"
            assert svc.arbiter.stats()["leased_bytes"] == 0
            assert svc.arbiter.stats()["owners"] == 0
            _assert_no_prefetch_leak()
        else:
            assert rec["status"] == "ok"
        # immediate clean re-run, chaos disarmed: same rows
        status, again = _post_sql(port, {
            "sql": sql, "conf": {CHUNK_KEY: 512, INJECT_KEY: ""}})
        assert status == 200
        assert again["rows"] == base["rows"]
    assert cancelled >= 1
    # lifecycle counters visible on /metrics
    from spark_tpu.observability.metrics import parse_prometheus_text
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as r:
        metrics = parse_prometheus_text(r.read().decode())
    assert metrics.get("spark_tpu_query_cancelled", 0) >= 1


# ---------------------------------------------------------------------------
# Dispatched-stage sync (executor._sync_dispatched): the dispatch gap
# ---------------------------------------------------------------------------


class _FakeDeviceArray:
    """Stand-in for a dispatched jax.Array: is_ready() flips when the
    'device' finishes; __array__ lets jax.device_get materialize it."""

    def __init__(self, ready_after_s=0.0):
        import numpy as np
        self._value = np.zeros(2, dtype=np.int64)
        self._ready_ts = time.monotonic() + ready_after_s

    def is_ready(self):
        return time.monotonic() >= self._ready_ts

    def __array__(self, dtype=None):
        return self._value


def test_dispatch_poll_cancel_lands_mid_stage():
    """Regression for the dispatch gap: with a never-ready output, a
    cancel must land within ~one poll tick instead of blocking in
    jax.device_get until the device finishes the stage."""
    from spark_tpu.execution.executor import (DISPATCH_POLL_KEY,
                                              _sync_dispatched)
    conf = Conf().set(DISPATCH_POLL_KEY, 20)
    tok = lifecycle.CancelToken()
    ctx = lifecycle.install(tok)
    try:
        timer = threading.Timer(0.15, tok.cancel)
        timer.start()
        t0 = time.monotonic()
        with pytest.raises(lifecycle.QueryCancelledError):
            _sync_dispatched(
                {"flags": _FakeDeviceArray(ready_after_s=3600)}, conf)
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0, f"cancel took {elapsed:.2f}s (gap back?)"
        timer.cancel()
    finally:
        lifecycle.uninstall(ctx)


def test_dispatch_poll_deadline_lands_mid_stage():
    from spark_tpu.execution.executor import (DISPATCH_POLL_KEY,
                                              _sync_dispatched)
    conf = Conf().set(DISPATCH_POLL_KEY, 20)
    tok = lifecycle.CancelToken(deadline_ms=150)
    ctx = lifecycle.install(tok)
    try:
        t0 = time.monotonic()
        with pytest.raises(lifecycle.QueryDeadlineError):
            _sync_dispatched(
                [_FakeDeviceArray(ready_after_s=3600)], conf)
        assert time.monotonic() - t0 < 2.0
    finally:
        lifecycle.uninstall(ctx)


def test_dispatch_poll_returns_when_ready():
    """The poll loop exits on readiness and returns device_get's
    result; arrays without is_ready (host values) never stall it."""
    from spark_tpu.execution.executor import (DISPATCH_POLL_KEY,
                                              _sync_dispatched)
    import numpy as np
    conf = Conf().set(DISPATCH_POLL_KEY, 20)
    tok = lifecycle.CancelToken()
    ctx = lifecycle.install(tok)
    try:
        out = _sync_dispatched(
            {"a": _FakeDeviceArray(ready_after_s=0.1), "b": 7}, conf)
        assert np.array_equal(out["a"], np.zeros(2, dtype=np.int64))
        assert out["b"] == 7
    finally:
        lifecycle.uninstall(ctx)


def test_dispatch_poll_disabled_blocks_straight_through():
    """dispatchPollMs=0 (and no token) short-circuits to the plain
    blocking device_get — the pre-existing fast path."""
    from spark_tpu.execution.executor import (DISPATCH_POLL_KEY,
                                              _sync_dispatched)
    import numpy as np
    conf = Conf().set(DISPATCH_POLL_KEY, 0)
    out = _sync_dispatched([_FakeDeviceArray()], conf)
    assert np.array_equal(out[0], np.zeros(2, dtype=np.int64))


def test_dispatch_gap_regression_slow_stage_cancel(service):
    """End-to-end: a slow-stage fault holds the dispatched stage on
    device; DELETE /queries/<id> during the stall must cancel the
    query promptly (structured QUERY_CANCELLED) instead of waiting
    out the stage."""
    svc = service().start()
    rec = svc.submit_async(
        "SELECT l_orderkey FROM lineitem LIMIT 4",
        conf={INJECT_KEY: "stage_run:slow:1:5000"})
    qid = rec["id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        r = svc.get_query(qid)
        if r and r["status"] == "running":
            break
        time.sleep(0.01)
    assert svc.cancel_query(qid), "cancel not delivered"
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        r = svc.get_query(qid)
        if r["status"] not in ("submitted", "running"):
            break
        time.sleep(0.02)
    assert r["status"] in ("cancelled", "ok"), r
    if r["status"] == "cancelled":
        assert r["error"]["error"] == "QUERY_CANCELLED"
    _assert_no_prefetch_leak()

"""Hash build/probe join kernel + double-buffered ingest tests.

Acceptance bar (ISSUE 7): byte-identical golden parity between
`join.kernelMode=hash` and `sort` across join types (including
many-to-many expansion, null keys, empty/skewed builds, mesh-sharded
probes, and injected `join_build` chaos), the AQE saturation fallback,
kernel-choice heuristics, the `JOIN_HASH_TABLE_PRESSURE` analyzer
finding, and the ingest prefetcher (parity on/off, one-chunk fault
replay via `rec_chunks_replayed`, stall/overlap counters).
"""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.functions import col, lit
from spark_tpu.testing import faults
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

MODE_KEY = "spark_tpu.sql.join.kernelMode"
LOAD_KEY = "spark_tpu.sql.join.hashLoadFactor"
MAX_PROBE_KEY = "spark_tpu.sql.join.hashMaxProbe"
MAX_SLOTS_KEY = "spark_tpu.sql.join.hashMaxTableSlots"
MIN_ROWS_KEY = "spark_tpu.sql.join.hashMinProbeRows"
RATIO_KEY = "spark_tpu.sql.join.hashProbeBuildRatio"
PREFETCH_KEY = "spark_tpu.sql.ingest.prefetch"
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
CACHE_KEY = "spark_tpu.sql.io.deviceCacheBytes"
BUDGET_KEY = "spark_tpu.sql.memory.deviceBudget"
MESH_KEY = "spark_tpu.sql.mesh.size"

SF = 0.002


# -- fixtures ----------------------------------------------------------------

@pytest.fixture
def tables(session):
    rs = np.random.RandomState(11)
    fact = pd.DataFrame({
        "k": rs.randint(0, 700, 20000).astype(np.int64),
        "v": np.arange(20000, dtype=np.int64)})
    # duplicate build keys: the many-to-many expansion path
    dim = pd.DataFrame({
        "k2": np.repeat(np.arange(500, dtype=np.int64), 2),
        "w": np.arange(1000, dtype=np.int64)})
    session.register_table("hj_fact", fact)
    session.register_table("hj_dim", dim)
    return session


@pytest.fixture(scope="session")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_hash_join") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


def _join_df(session, how):
    return session.table("hj_fact").join(
        session.table("hj_dim"), left_on=col("k"), right_on=col("k2"),
        how=how)


def _both_kernels(session, df_fn):
    """Run `df_fn()` under kernelMode=sort then =hash (cold stage cache
    each time) and return both frames."""
    session.conf.set(MODE_KEY, "sort")
    sort_out = df_fn().to_pandas()
    session.conf.set(MODE_KEY, "hash")
    hash_out = df_fn().to_pandas()
    return sort_out, hash_out


def _hash_ran(qe) -> bool:
    return any(k.startswith("join_table_slots_")
               for k in qe.last_metrics)


# -- kernel-choice heuristics (resolve_kernel / table_slots) -----------------

def test_table_slots_power_of_two(session):
    from spark_tpu.execution import hash_join as HJ
    conf = session.conf
    slots = HJ.table_slots(8192, conf)  # loadFactor 0.5 default
    assert slots == 16384
    assert HJ.table_slots(16, conf) >= 32
    conf.set(MAX_SLOTS_KEY, 1024)
    assert HJ.table_slots(1 << 20, conf) == 1024  # clamped


def test_resolve_kernel_modes(session):
    from spark_tpu.execution import hash_join as HJ
    conf = session.conf
    big, small = 1 << 22, 1 << 10
    assert HJ.resolve_kernel(conf, big, small, None) == "hash"  # auto
    # below hashMinProbeRows: the sort path's probe sorts are cheap
    assert HJ.resolve_kernel(conf, small, small, None) == "sort"
    # near-square join: the table build doesn't amortize
    assert HJ.resolve_kernel(conf, big, big, None) == "sort"
    conf.set(MODE_KEY, "sort")
    assert HJ.resolve_kernel(conf, big, small, None) == "sort"
    conf.set(MODE_KEY, "hash")
    assert HJ.resolve_kernel(conf, small, small, None) == "hash"
    # a saturated previous attempt pins the join to sort
    assert HJ.resolve_kernel(conf, big, small, False) == "sort"
    # maxTableSlots clamp pushing load factor past 0.7: trace-time
    # fallback even under forced hash
    conf.set(MAX_SLOTS_KEY, 1024)
    assert HJ.resolve_kernel(conf, big, 1 << 12, None) == "sort"


def test_auto_keeps_sort_on_small_joins(tables):
    """Default auto mode on test-sized joins stays on the sort kernel
    (tier-1 CPU runs never trace the hash path unasked)."""
    qe = _join_df(tables, "inner")._qe()
    qe.execute_batch()
    assert not _hash_ran(qe), qe.last_metrics


# -- kernel parity -----------------------------------------------------------

@pytest.mark.parametrize("how", ["inner", "left", "left_semi",
                                 "left_anti"])
def test_kernel_parity_join_matrix(tables, how):
    """Byte-identical output across kernels, duplicate build keys
    included (many-to-many prefix-sum expansion shared by both)."""
    sort_out, hash_out = _both_kernels(
        tables, lambda: _join_df(tables, how))
    pd.testing.assert_frame_equal(sort_out, hash_out)


def test_kernel_parity_null_keys(session):
    left = pd.DataFrame({
        "k": pd.array([1, None, 3, 4, None, 2], dtype="Int64"),
        "lv": np.arange(6, dtype=np.int64)})
    right = pd.DataFrame({
        "k2": pd.array([2, 3, None, 3], dtype="Int64"),
        "rv": np.arange(4, dtype=np.int64)})
    session.register_table("hj_nl", left)
    session.register_table("hj_nr", right)
    for how in ("inner", "left", "left_semi", "left_anti"):
        sort_out, hash_out = _both_kernels(
            session, lambda: session.table("hj_nl").join(
                session.table("hj_nr"), left_on=col("k"),
                right_on=col("k2"), how=how))
        pd.testing.assert_frame_equal(sort_out, hash_out)
    # anti keeps null-key probe rows; null build keys never match
    session.conf.set(MODE_KEY, "hash")
    anti = session.table("hj_nl").join(
        session.table("hj_nr"), left_on=col("k"), right_on=col("k2"),
        how="left_anti").to_pandas()
    assert set(anti["lv"]) == {0, 1, 3, 4}


def test_kernel_parity_float_keys(session):
    """Float keys hash by canonicalized bit pattern: +-0.0 join equal
    under both kernels."""
    left = pd.DataFrame({
        "k": np.array([0.0, -0.0, 1.5, 2.5, 3.25], dtype=np.float64),
        "lv": np.arange(5, dtype=np.int64)})
    right = pd.DataFrame({
        "k2": np.array([-0.0, 2.5, 99.0], dtype=np.float64),
        "rv": np.arange(3, dtype=np.int64)})
    session.register_table("hj_fl", left)
    session.register_table("hj_fr", right)
    sort_out, hash_out = _both_kernels(
        session, lambda: session.table("hj_fl").join(
            session.table("hj_fr"), left_on=col("k"),
            right_on=col("k2")))
    pd.testing.assert_frame_equal(sort_out, hash_out)
    assert set(hash_out["lv"]) == {0, 1, 3}  # both zeros matched


def test_kernel_parity_empty_build(tables):
    for how in ("inner", "left", "left_semi", "left_anti"):
        sort_out, hash_out = _both_kernels(
            tables, lambda: tables.table("hj_fact").join(
                tables.table("hj_dim").filter(col("w") > lit(10 ** 9)),
                left_on=col("k"), right_on=col("k2"), how=how))
        pd.testing.assert_frame_equal(sort_out, hash_out)


def test_kernel_parity_skewed_keys_near_capacity(session):
    """One hot build key (a long sorted run, not a probe cluster) plus
    a distinct-key population pushed near the table's load-factor
    ceiling."""
    rs = np.random.RandomState(3)
    hot = np.zeros(600, dtype=np.int64)
    cold = np.arange(1, 700, dtype=np.int64)
    build = pd.DataFrame({
        "k2": np.concatenate([hot, cold]),
        "w": np.arange(600 + 699, dtype=np.int64)})
    probe = pd.DataFrame({
        "k": rs.randint(0, 700, 30000).astype(np.int64),
        "v": np.arange(30000, dtype=np.int64)})
    session.register_table("hj_skp", probe)
    session.register_table("hj_skb", build)
    # 1299 build rows bucket past 2048: with maxSlots clamped to 2048
    # the 0.7 ceiling forces the trace-time sort fallback; with the
    # clamp lifted the hash kernel must agree with sort exactly
    for max_slots in (2048, 1 << 26):
        session.conf.set(MAX_SLOTS_KEY, max_slots)
        sort_out, hash_out = _both_kernels(
            session, lambda: session.table("hj_skp").join(
                session.table("hj_skb"), left_on=col("k"),
                right_on=col("k2")))
        pd.testing.assert_frame_equal(sort_out, hash_out)


def test_saturation_falls_back_via_aqe(tables):
    """hashMaxProbe=1 saturates the open table at build time (collision
    clusters outrun the bound): the join_hashsat flag re-jits the join
    on the sort kernel and results stay correct."""
    conf = tables.conf
    conf.set(MODE_KEY, "sort")
    expect = _join_df(tables, "inner").to_pandas()
    conf.set(MODE_KEY, "hash")
    conf.set(MAX_PROBE_KEY, 1)
    qe = _join_df(tables, "inner")._qe()
    got = qe.collect().to_pandas()
    pd.testing.assert_frame_equal(expect, got)
    # the AQE loop pinned this join to the sort kernel
    assert "hash_fallback" in qe.executed_plan.tree_string()


def test_hash_metrics_emitted(tables):
    tables.conf.set(MODE_KEY, "hash")
    qe = _join_df(tables, "inner")._qe()
    qe.execute_batch()
    slots = [v for k, v in qe.last_metrics.items()
             if k.startswith("join_table_slots_")]
    assert slots and all(s >= 16 and (s & (s - 1)) == 0 for s in slots)
    assert any(k.startswith("join_build_ms_")
               for k in qe.last_metrics), qe.last_metrics
    assert any(k.startswith("join_probe_ms_")
               for k in qe.last_metrics), qe.last_metrics


# -- mesh --------------------------------------------------------------------

def test_kernel_parity_mesh_sharded_probe(tables):
    tables.conf.set(MESH_KEY, 8)
    sort_out, hash_out = _both_kernels(
        tables, lambda: _join_df(tables, "inner"))
    pd.testing.assert_frame_equal(sort_out, hash_out)


# -- chaos -------------------------------------------------------------------

def test_chaos_join_build_fault_under_hash(tables):
    tables.conf.set(MODE_KEY, "sort")
    expect = _join_df(tables, "inner").to_pandas()
    tables.conf.set(MODE_KEY, "hash")
    tables.conf.set("spark_tpu.execution.backoffMs", 1)
    # cold stage cache: the join_build seam fires at TRACE time, and
    # sibling tests already compiled this exact hash stage
    tables._stage_cache.clear()
    tables._aqe_caps.clear()
    faults.reset()
    with faults.inject(tables.conf,
                       "join_build:unavailable:1") as plan:
        got = _join_df(tables, "inner").to_pandas()
    assert ("join_build", 1, "unavailable") in plan.fired_log
    pd.testing.assert_frame_equal(expect, got)


# -- TPC-H golden parity -----------------------------------------------------

@pytest.mark.parametrize("qname", ["q1", "q3", "q5"])
def test_tpch_golden_parity_hash_vs_sort(tpch_session, tpch_path,
                                         qname):
    conf = tpch_session.conf
    conf.set(MODE_KEY, "sort")
    sort_out = G.normalize_decimals(
        Q.QUERIES[qname](tpch_session).to_pandas())
    G.compare(sort_out.reset_index(drop=True),
              G.GOLDEN[qname](tpch_path))
    conf.set(MODE_KEY, "hash")
    qe = Q.QUERIES[qname](tpch_session)._qe()
    hash_out = G.normalize_decimals(qe.collect().to_pandas())
    if qname != "q1":  # q1 has no joins
        assert _hash_ran(qe), qe.last_metrics
    pd.testing.assert_frame_equal(sort_out, hash_out)


# -- analyzer finding --------------------------------------------------------

def test_hash_table_pressure_finding(tables):
    from spark_tpu.analysis.plan_analyzer import analyze_plan
    conf = tables.conf
    qe = _join_df(tables, "inner")._qe()
    conf.set(MODE_KEY, "hash")
    conf.set(MAX_SLOTS_KEY, 512)  # dim caps past 0.7 * 512
    found = [f for f in analyze_plan(qe.executed_plan, conf)
             if f.code == "JOIN_HASH_TABLE_PRESSURE"]
    assert found and found[0].detail["fallback"] == "sort"
    assert found[0].severity == "warn"
    conf.set(MAX_SLOTS_KEY, 1 << 26)
    conf.set(BUDGET_KEY, 4096)  # table bytes exceed the HBM budget
    found = [f for f in analyze_plan(qe.executed_plan, conf)
             if f.code == "JOIN_HASH_TABLE_PRESSURE"]
    assert found and found[0].detail["table_bytes"] > 4096
    # clean conf: no pressure findings on the same plan
    conf.unset(BUDGET_KEY)
    conf.set(MODE_KEY, "sort")
    assert [f for f in analyze_plan(qe.executed_plan, conf)
            if f.code == "JOIN_HASH_TABLE_PRESSURE"] == []


# -- double-buffered ingest --------------------------------------------------

@pytest.fixture
def streaming_conf(tpch_session):
    conf = tpch_session.conf
    conf.set("spark_tpu.execution.backoffMs", 1)
    conf.set(CHUNK_KEY, 1024)
    conf.set(CACHE_KEY, 0)
    faults.reset()
    yield conf
    faults.reset()


def _golden(session, qname, tpch_path):
    got = G.normalize_decimals(
        Q.QUERIES[qname](session).to_pandas()).reset_index(drop=True)
    G.compare(got, G.GOLDEN[qname](tpch_path))
    return got


def test_prefetch_parity_on_off(tpch_session, tpch_path,
                                streaming_conf):
    stall0 = tpch_session.metrics.counter("ingest_stall_ms").value
    on = _golden(tpch_session, "q1", tpch_path)
    # the consumer measured the pipeline (stall or overlap advanced)
    assert tpch_session.metrics.counter("ingest_stall_ms").value \
        + tpch_session.metrics.counter("ingest_overlap_ms").value \
        > stall0
    streaming_conf.set(PREFETCH_KEY, False)
    off = _golden(tpch_session, "q1", tpch_path)
    pd.testing.assert_frame_equal(on, off)


def test_prefetch_parity_spill_path(tpch_session, tpch_path,
                                    streaming_conf):
    streaming_conf.set(BUDGET_KEY, 1)  # force the partial-spill driver
    on = _golden(tpch_session, "q3", tpch_path)
    streaming_conf.set(PREFETCH_KEY, False)
    off = _golden(tpch_session, "q3", tpch_path)
    pd.testing.assert_frame_equal(on, off)


def test_prefetch_fault_replays_one_chunk(tpch_session, tpch_path,
                                          streaming_conf):
    """A transient fault at the prefetcher's host-decode seam replays
    exactly one chunk through the standard per-chunk retry path."""
    replayed0 = tpch_session.metrics.counter(
        "rec_chunks_replayed").value
    with faults.inject(streaming_conf,
                       "ingest_prefetch:unavailable:3") as plan:
        _golden(tpch_session, "q1", tpch_path)
    assert ("ingest_prefetch", 3, "unavailable") in plan.fired_log
    assert tpch_session.metrics.counter(
        "rec_chunks_replayed").value == replayed0 + 1


def test_prefetch_fatal_fault_propagates(tpch_session, streaming_conf):
    """A FATAL fault on the worker thread surfaces on the consumer —
    never a hang, never a truncated result."""
    with faults.inject(streaming_conf, "ingest_prefetch:fatal:2"):
        with pytest.raises(Exception, match="INTERNAL|fatal"):
            Q.QUERIES["q1"](tpch_session).to_pandas()


def test_prefetch_mesh_checkpoint_restore(tpch_session, tpch_path,
                                          streaming_conf):
    """Prefetcher + mesh checkpoint/restore compose: the restored
    stream skips checkpointed chunks through the prefetcher's
    skip_chunks cursor (PR-5 semantics unchanged)."""
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set("spark_tpu.execution.checkpoint.everyChunks", 4)
    with faults.inject(streaming_conf, "mesh:unavailable:2"):
        _golden(tpch_session, "q1", tpch_path)


def test_table_slots_non_power_of_two_clamp(session):
    """A non-power-of-two hashMaxTableSlots must floor to a power of
    two: slot indexing masks with `& (slots - 1)`, so 6e6 nominal
    slots would leave ~half the table unreachable."""
    from spark_tpu.execution import hash_join as HJ
    session.conf.set(MAX_SLOTS_KEY, 6_000_000)
    slots = HJ.table_slots(1 << 23, session.conf)
    assert slots == 1 << 22, slots  # largest power of two <= 6e6
    assert slots & (slots - 1) == 0


def test_prefetch_worker_exits_on_abandonment(tpch_session, tpch_path,
                                              streaming_conf):
    """A chunk driver unwound mid-stream (fault escalation, replan)
    abandons its PrefetchChunkIterator without close(); the worker
    thread must exit via the abandonment finalizer instead of spinning
    forever holding a decoded chunk."""
    import gc
    import threading
    import time

    import os

    from spark_tpu.io.sources import ParquetSource, PrefetchChunkIterator

    def workers():
        return [t for t in threading.enumerate()
                if t.name == "spark-tpu-ingest-prefetch" and t.is_alive()]

    src = ParquetSource(os.path.join(tpch_path, "lineitem.parquet"),
                        "lineitem")
    chunks = PrefetchChunkIterator(
        src.load_chunks(None, (), 1024), streaming_conf)
    next(chunks)  # starts the worker; stream has many chunks left
    assert len(workers()) >= 1
    del chunks  # abandoned: no close(), as on an error unwind
    gc.collect()
    deadline = time.monotonic() + 5.0
    while workers() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not workers(), "prefetch worker leaked after abandonment"


@pytest.mark.parametrize("build_keys,want_rows", [
    ([float("nan"), 2.5, 9.0], 3),        # unique-build fast path
    ([float("nan"), float("nan"), 2.5], 5),  # dup NaN: m2m expansion
])
def test_kernel_parity_nan_keys(session, build_keys, want_rows):
    """Non-null NaN float keys (Parquet NaN is a VALUE, not null) join
    equal to NaN under BOTH kernels, matching pandas merge. Regression:
    the sort kernel's +inf sentinel broke the sorted order whenever the
    build had NaN keys plus padding (NaN probes never matched), and
    duplicate NaN build keys slipped past build_has_duplicates' `==`
    so the unique fast path dropped their extra matches."""
    import pyarrow as pa
    nan = float("nan")
    session.register_table("nan_p", pa.table({
        "k": pa.array([1.5, nan, nan, 2.5], type=pa.float64()),
        "v": pa.array([0, 1, 2, 3], type=pa.int64())}))
    session.register_table("nan_b", pa.table({
        "k2": pa.array(build_keys, type=pa.float64()),
        "w": pa.array([10, 20, 30], type=pa.int64())}))

    def run(mode):
        session.conf.set(MODE_KEY, mode)
        return (session.table("nan_p").join(
                    session.table("nan_b"),
                    left_on=col("k"), right_on=col("k2"))
                .to_pandas().sort_values(["v", "w"])
                .reset_index(drop=True))

    srt, hsh = run("sort"), run("hash")
    pd.testing.assert_frame_equal(srt, hsh)
    want = (session.table("nan_p").to_pandas()
            .merge(session.table("nan_b").to_pandas(),
                   left_on="k", right_on="k2")
            .sort_values(["v", "w"]).reset_index(drop=True))
    assert len(srt) == want_rows == len(want)
    pd.testing.assert_frame_equal(srt, want)


def test_kernel_parity_signed_zero_keys(session):
    """-0.0 and +0.0 join equal under both kernels (canonicalized
    before sort/search/hash), matching pandas merge."""
    import pyarrow as pa
    session.register_table("z_p", pa.table({
        "k": pa.array([-0.0, 0.0], type=pa.float64()),
        "v": pa.array([0, 1], type=pa.int64())}))
    session.register_table("z_b", pa.table({
        "k2": pa.array([0.0], type=pa.float64()),
        "w": pa.array([7], type=pa.int64())}))

    def run(mode):
        session.conf.set(MODE_KEY, mode)
        return (session.table("z_p").join(
                    session.table("z_b"),
                    left_on=col("k"), right_on=col("k2"))
                .to_pandas().sort_values("v").reset_index(drop=True))

    srt, hsh = run("sort"), run("hash")
    pd.testing.assert_frame_equal(srt, hsh)
    assert len(srt) == 2


def test_high_load_factor_without_clamp_keeps_hash(session):
    """Regression: the 0.7 fallback bound applies only when
    hashMaxTableSlots actually reduced the table. An unclamped table
    under a user-chosen hashLoadFactor in (0.7, 0.9] must keep the
    hash kernel (and emit no misleading clamp pressure finding)."""
    from spark_tpu.execution import hash_join as HJ
    session.conf.set(LOAD_KEY, 0.9)
    # bucket ~3000: want ceil(3000/0.9)=3334 -> 4096 slots, effective
    # load 0.73 > 0.7 but NOT clamped — the conf'd load factor rules
    assert HJ.table_slots(3000, session.conf) == 4096
    session.conf.set(MODE_KEY, "hash")
    assert HJ.kernel_choice(session.conf, 1 << 22, 3000) == \
        ("hash", "forced")
    session.conf.set(MODE_KEY, "auto")
    assert HJ.kernel_choice(session.conf, 1 << 22, 3000) == \
        ("hash", "auto")
    # the clamp case still falls back with reason 'clamp'
    session.conf.set(MAX_SLOTS_KEY, 2048)
    assert HJ.kernel_choice(session.conf, 1 << 22, 3000) == \
        ("sort", "clamp")

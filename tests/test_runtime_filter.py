"""Runtime-filter subsystem tests: injection rule (plan == plan style
predicates), golden TPC-H parity with filters on/off, metric
observability, and the mesh test asserting probe-side shuffled rows
drop on a selective join."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit

RTF_KEY = "spark_tpu.sql.runtimeFilter.enabled"
THRESH_KEY = "spark_tpu.sql.runtimeFilter.creationSideThreshold"
MESH_KEY = "spark_tpu.sql.mesh.size"
BCAST_KEY = "spark_tpu.sql.autoBroadcastJoinThreshold"


@pytest.fixture
def tables(session):
    rs = np.random.RandomState(7)
    fact = pd.DataFrame({
        "k": rs.randint(0, 1000, 20000).astype(np.int64),
        "v": np.arange(20000, dtype=np.int64)})
    dim = pd.DataFrame({
        "k2": np.arange(1000, dtype=np.int64),
        "flag": (np.arange(1000) % 10).astype(np.int64),
        "name": [f"n{i % 37}" for i in range(1000)]})
    session.register_table("rtf_fact", fact)
    session.register_table("rtf_dim", dim)
    return session


def _selective_join(session):
    d = session.table("rtf_dim").filter(col("flag") == lit(0))
    return session.table("rtf_fact").join(
        d, left_on=col("k"), right_on=col("k2"))


def _count_rf(plan) -> int:
    from spark_tpu.plan import physical as P
    seen = [0]

    def walk(n):
        if isinstance(n, P.RuntimeFilterExec):
            seen[0] += 1
        for c in n.children:
            walk(c)

    walk(plan)
    return seen[0]


# -- injection rule -----------------------------------------------------------

def test_injected_when_build_selective(tables):
    plan = _selective_join(tables)._qe().executed_plan
    assert _count_rf(plan) == 1, plan.tree_string()


def test_not_injected_without_selective_build(tables):
    df = tables.table("rtf_fact").join(
        tables.table("rtf_dim"), left_on=col("k"), right_on=col("k2"))
    plan = df._qe().executed_plan
    assert _count_rf(plan) == 0, plan.tree_string()


def test_not_injected_when_disabled(tables):
    tables.conf.set(RTF_KEY, False)
    plan = _selective_join(tables)._qe().executed_plan
    assert _count_rf(plan) == 0, plan.tree_string()


def test_not_injected_over_creation_threshold(tables):
    tables.conf.set(THRESH_KEY, 64)  # bytes: everything is too big
    plan = _selective_join(tables)._qe().executed_plan
    assert _count_rf(plan) == 0, plan.tree_string()


def test_not_injected_on_left_outer(tables):
    d = tables.table("rtf_dim").filter(col("flag") == lit(0))
    df = tables.table("rtf_fact").join(
        d, left_on=col("k"), right_on=col("k2"), how="left")
    plan = df._qe().executed_plan
    assert _count_rf(plan) == 0, plan.tree_string()


def test_creation_side_descends_through_build_join(tables):
    """The build side is itself a join; the filter must extract the
    chain the key column originates from (InjectRuntimeFilter's
    extractSelectiveFilterOverScan shape, the TPC-H Q3 top join)."""
    d = tables.table("rtf_dim").filter(col("flag") == lit(0))
    mid = tables.table("rtf_fact").filter(col("v") < lit(10000)).join(
        d, left_on=col("k"), right_on=col("k2"))
    big = tables.table("rtf_fact").join(
        mid, left_on=col("v"), right_on=col("v"))
    plan = big._qe().executed_plan
    assert _count_rf(plan) >= 1, plan.tree_string()


# -- execution parity + metrics ----------------------------------------------

def _run_with_metrics(df):
    qe = df._qe()
    qe.execute_batch()
    got = df.to_pandas().sort_values("v").reset_index(drop=True)
    return got, qe.last_metrics


def test_parity_and_metrics_single_chip(tables):
    got, metrics = _run_with_metrics(_selective_join(tables))
    rtf = {k: v for k, v in metrics.items() if k.startswith("rtf_")}
    assert rtf.get("rtf_tested_rf0", 0) == 20000, rtf
    assert rtf.get("rtf_pruned_rf0", 0) > 0, rtf
    assert "rtf_build_ms_rf0" in rtf, rtf
    tables.conf.set(RTF_KEY, False)
    want, metrics_off = _run_with_metrics(_selective_join(tables))
    assert not any(k.startswith("rtf_") for k in metrics_off)
    pd.testing.assert_frame_equal(got, want)


def test_parity_string_keys(tables):
    """Dictionary-encoded string keys hash by VALUE: two independently
    encoded dictionaries must agree through the filter."""
    def build():
        d = tables.table("rtf_dim").filter(col("flag") == lit(3))
        return (tables.table("rtf_dim")
                .join(d, left_on=col("name"), right_on=col("name"))
                .group_by(col("flag")).agg(F.count().alias("c")))

    got = build().to_pandas().sort_values("flag").reset_index(drop=True)
    tables.conf.set(RTF_KEY, False)
    want = build().to_pandas().sort_values("flag").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want)


def test_mesh_shuffled_rows_drop(tables):
    """On a selective shuffle join over the mesh, the probe exchange
    must route measurably fewer rows with runtime filters on — the
    rows never crossing ICI is the whole point of the subsystem."""
    tables.conf.set(BCAST_KEY, 1)  # force the shuffle strategy
    tables.conf.set(MESH_KEY, 8)

    def probe_exchange_tag(plan):
        from spark_tpu.plan import physical as P
        hit = []

        def walk(n):
            if isinstance(n, P.JoinExec) and \
                    isinstance(n.children[0], P.ExchangeExec):
                hit.append(n.children[0].tag)
            for c in n.children:
                walk(c)

        walk(plan)
        assert hit, plan.tree_string()
        return hit[0]

    def routed(enabled):
        tables.conf.set(RTF_KEY, enabled)
        qe = _selective_join(tables)._qe()
        qe.execute_batch()
        tag = probe_exchange_tag(qe.executed_plan)
        m = qe.last_metrics
        rtf = {k: v for k, v in m.items() if k.startswith("rtf_")}
        return m[f"exch_rows_{tag}"], rtf

    on_rows, rtf_on = routed(True)
    off_rows, rtf_off = routed(False)
    assert rtf_on.get("rtf_pruned_rf0", 0) > 0, rtf_on
    assert not rtf_off
    # with the filter, the probe exchange routes only surviving rows
    assert on_rows < off_rows, (on_rows, off_rows)

    # and results stay identical
    tables.conf.set(RTF_KEY, True)
    got = (_selective_join(tables).to_pandas()
           .sort_values("v").reset_index(drop=True))
    tables.conf.set(RTF_KEY, False)
    want = (_selective_join(tables).to_pandas()
            .sort_values("v").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want)


def test_all_null_string_key_does_not_crash(session):
    """An all-None object key column becomes an all-NULL string column
    with a 0-entry dictionary; the filter kernel must not jnp.take from
    the empty hash table (it crashed the whole query)."""
    left = pd.DataFrame({"s": pd.Series([None, None], dtype=object),
                         "v": np.arange(2, dtype=np.int64)})
    right = pd.DataFrame({"s2": ["a", "b", "c", "d"],
                          "flag": np.array([0, 1, 0, 1], dtype=np.int64)})
    session.register_table("rtf_null_l", left)
    session.register_table("rtf_null_r", right)

    def build():
        r = session.table("rtf_null_r").filter(col("flag") == lit(0))
        return session.table("rtf_null_l").join(
            r, left_on=col("s"), right_on=col("s2"))

    got = build().to_pandas()
    session.conf.set(RTF_KEY, False)
    want = build().to_pandas()
    assert len(got) == 0 and len(want) == 0


def test_nan_build_key_does_not_poison_bounds(session):
    """A valid (non-NULL) NaN among the float build keys must not
    poison the min/max bounds: NaN propagating through min/max made
    every probe compare False and silently emptied the join."""
    left = pd.DataFrame({"fk": np.arange(100, dtype=np.float64),
                         "v": np.arange(100, dtype=np.int64)})
    base = np.arange(50, dtype=np.float64)
    # sqrt(-1)*sqrt(-1) -> NaN, computed (not ingested); index 8 has
    # flag == 0, so the NaN SURVIVES the build-side filter and reaches
    # the bounds computation
    base[8] = -1.0
    right = pd.DataFrame({"rk": base,
                          "flag": (np.arange(50) % 2).astype(np.int64)})
    session.register_table("rtf_nan_l", left)
    session.register_table("rtf_nan_r", right)

    def build():
        r = (session.table("rtf_nan_r").filter(col("flag") == lit(0))
             .select((F.sqrt(col("rk")) * F.sqrt(col("rk"))).alias("k2"),
                     col("flag")))
        return session.table("rtf_nan_l").join(
            r, left_on=col("fk"), right_on=col("k2"))

    got = build().to_pandas().sort_values("v").reset_index(drop=True)
    session.conf.set(RTF_KEY, False)
    want = build().to_pandas().sort_values("v").reset_index(drop=True)
    assert len(want) > 0  # the join itself must match real rows
    pd.testing.assert_frame_equal(got, want)


# -- TPC-H golden parity with filters on/off ---------------------------------

@pytest.mark.parametrize("qname", ["q3", "q5"])
def test_tpch_golden_parity_on_off(session, tmp_path_factory, qname):
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch.datagen import write_parquet

    path = str(tmp_path_factory.mktemp("tpch_rtf") / "sf")
    write_parquet(path, 0.002)
    Q.register_tables(session, path)

    def norm(df):
        out = df.copy()
        for c in out.columns:
            if len(out) and out[c].dtype == object and \
                    out[c].iloc[0].__class__.__name__ == "Decimal":
                out[c] = out[c].astype(float)
        if qname == "q5":
            out = out.sort_values("n_name")
        return out.reset_index(drop=True)

    session.conf.set(RTF_KEY, True)
    qe = Q.QUERIES[qname](session)._qe()
    assert _count_rf(qe.executed_plan) >= 1, qe.executed_plan.tree_string()
    qe.execute_batch()
    pruned = sum(v for k, v in qe.last_metrics.items()
                 if k.startswith("rtf_pruned_"))
    assert pruned > 0, qe.last_metrics
    got = norm(Q.QUERIES[qname](session).to_pandas())
    session.conf.set(RTF_KEY, False)
    off = norm(Q.QUERIES[qname](session).to_pandas())
    # byte-identical: same dtypes, same values, same order
    pd.testing.assert_frame_equal(got, off)
    want = norm(G.GOLDEN[qname](path)) if qname == "q5" else \
        G.GOLDEN[qname](path)
    G.compare(got, want)


def test_pruned_counts_shrink_static_caps(tables):
    """ROADMAP runtime-filter item (c): after a converged run, the
    pruned-row counts re-seed the guarded join's output capacity DOWN
    (survivor-sized, floored by the measured join_rows), so the next
    compile of the same plan allocates smaller buffers even on a single
    chip — pruning used to pay off only in ICI traffic."""
    from spark_tpu.plan import physical as P

    qe = _selective_join(tables)._qe()
    qe.execute_batch()
    tested = qe.last_metrics["rtf_tested_rf0"]
    pruned = qe.last_metrics["rtf_pruned_rf0"]
    assert tested == 20000 and pruned > 0

    joins = []

    def walk(n):
        for c in n.children:
            walk(c)
        if isinstance(n, P.JoinExec):
            joins.append(n)

    walk(qe.executed_plan)
    assert len(joins) == 1
    # the probe capacity would seed >= 20000; survivors bound it lower
    assert joins[0].out_cap is not None and joins[0].out_cap < tested, \
        joins[0].out_cap
    # the shrunk cap persists through the AQE store and a rerun of the
    # same plan stays correct with no overflow ramp
    qe2 = _selective_join(tables)._qe()
    _, flags, _ = qe2.execute_batch()
    assert not any(bool(v) for k, v in flags.items()
                   if k.startswith("join_overflow_")), flags
    got = _selective_join(tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    tables.conf.set(RTF_KEY, False)
    want = _selective_join(tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want)


def test_event_log_carries_rtf_metrics(tables, tmp_path):
    from spark_tpu import history
    log_dir = str(tmp_path / "events")
    tables.conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    _selective_join(tables)._qe().execute_batch()
    tables.conf.set("spark_tpu.sql.eventLog.dir", "")
    df = history.read_event_log(log_dir)
    assert any(c.startswith("rtf_pruned_") for c in df.columns), df.columns
    summary = history.runtime_filter_summary(df)
    assert len(summary) >= 1
    row = summary.iloc[-1]
    assert row["tested"] == 20000 and row["pruned"] > 0
    assert 0.0 < row["ratio"] <= 1.0


# -- semi-aware creation sides (runtimeFilter.semiAwareCreation) --------------

SEMI_KEY = "spark_tpu.sql.runtimeFilter.semiAwareCreation"


def _count_creation_semis(plan) -> int:
    from spark_tpu.plan import physical as P
    seen = [0]

    def walk(n):
        if isinstance(n, P.JoinExec) and n.how == "left_semi" \
                and n.creation_side:
            seen[0] += 1
        for c in n.children:
            walk(c)

    walk(plan)
    return seen[0]


@pytest.fixture
def semi_tables(session):
    session.conf.set(THRESH_KEY, 1 << 30)
    # t3 carries BOTH a physical k (disjoint from probe keys) and x
    # (the real join domain); t2 is the selective other side
    session.register_table("sa_t3", pd.DataFrame({
        "k": np.array([100, 101, 102, 103], dtype=np.int64),
        "x": np.array([1, 2, 3, 4], dtype=np.int64)}))
    session.register_table("sa_t4", pd.DataFrame({
        "m": np.array([1, 2, 3, 4], dtype=np.int64)}))
    session.register_table("sa_t2", pd.DataFrame({
        "j": np.array([1, 2], dtype=np.int64), "tag": ["a", "b"]}))
    session.register_table("sa_probe", pd.DataFrame({
        "k": np.arange(0, 200, dtype=np.int64),
        "v": np.arange(0, 200, dtype=np.int64)}))
    return session


def _semi_query(session):
    """Build side passes through an equi-join against selective sa_t2:
    the creation descent can inherit the tag='a' narrowing."""
    build = session.table("sa_t3").join(
        session.table("sa_t2").filter(col("tag") == lit("a")),
        left_on=col("x"), right_on=col("j"))
    return session.table("sa_probe").join(
        build, left_on=col("k"), right_on=col("x"))


def _shadowed_query(session):
    """The descent must pass THROUGH a Project that aliases x onto the
    name k while the underlying sa_t3 keeps a same-named physical k:
    name-resolution alone would bind the semi to the wrong column."""
    inner = session.table("sa_t3").join(
        session.table("sa_t4"), left_on=col("x"), right_on=col("m"))
    shadow = inner.select(col("x").alias("k"), col("k").alias("orig"))
    build = shadow.join(
        session.table("sa_t2").filter(col("tag") == lit("a")),
        left_on=col("k"), right_on=col("j"))
    return session.table("sa_probe").join(
        build, left_on=col("k"), right_on=col("k"))


def test_semi_aware_synthesizes_creation_semi(semi_tables):
    plan = _semi_query(semi_tables)._qe().executed_plan
    assert _count_creation_semis(plan) >= 1, plan.tree_string()
    semi_tables.conf.set(SEMI_KEY, False)
    plan_off = _semi_query(semi_tables)._qe().executed_plan
    assert _count_creation_semis(plan_off) == 0, plan_off.tree_string()


def test_semi_aware_parity_on_off(semi_tables):
    on = _semi_query(semi_tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    semi_tables.conf.set(SEMI_KEY, False)
    off = _semi_query(semi_tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    pd.testing.assert_frame_equal(on, off)
    assert len(on) > 0  # non-vacuous: some probe rows survive


def test_semi_aware_skips_shadowing_project(semi_tables):
    """Regression: a Project aliasing a different expr onto a join-key
    name (while the relation keeps a same-named physical column) must
    NOT synthesize a semi — binding by name would build the filter
    from a non-superset and silently drop matching probe rows."""
    on = _shadowed_query(semi_tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    plan = _shadowed_query(semi_tables)._qe().executed_plan
    # the outer probe filter must not carry an unsound creation semi:
    # the only sound semi here is the one over the benign inner join
    semi_tables.conf.set(SEMI_KEY, False)
    off = _shadowed_query(semi_tables).to_pandas() \
        .sort_values("v").reset_index(drop=True)
    pd.testing.assert_frame_equal(on, off)
    assert len(on) == 1, on  # probe k=1 matches build x=1/tag=a

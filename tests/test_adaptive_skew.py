"""Adaptive runtime re-planning: skewed shuffle joins re-plan to
broadcast (OptimizeSkewedJoin.scala:56 / DynamicJoinSelection.scala:1
analogs) and range-sort bounds sample VALID rows (weighted quantiles)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col

MESH = "spark_tpu.sql.mesh.size"
BCAST = "spark_tpu.sql.autoBroadcastJoinThreshold"
METRICS = "spark_tpu.sql.metrics.enabled"


def test_skewed_join_replans_to_broadcast(session):
    """A zipf-hot probe key overflows its exchange with one fat bucket;
    the executor must re-plan the join to broadcast (no exchange at all)
    instead of growing the bucket to the skew, and results must match
    the single-chip run."""
    rs = np.random.RandomState(3)
    n = 80_000
    k = rs.randint(0, 1000, n).astype(np.int64)
    k[: int(n * 0.9)] = 7  # 90% of rows share one key
    fact = pd.DataFrame({"k": k, "v": np.ones(n)})
    dim = pd.DataFrame({"k": np.arange(1000, dtype=np.int64),
                        "w": np.arange(1000, dtype=np.float64)})
    session.register_table("skew_fact", fact)
    session.register_table("skew_dim", dim)

    def build():
        return (session.table("skew_fact")
                .join(session.table("skew_dim"),
                      left_on=col("k"), right_on=col("k"))
                .agg(F.sum(col("v") * col("w")).alias("s"),
                     F.count().alias("c")))

    want = build().to_pandas()
    old_b = session.conf.get(BCAST)
    try:
        session.conf.set(MESH, 8)
        session.conf.set(BCAST, 0)  # force the initial plan to shuffle
        qe = build()._qe()
        got = qe.collect().to_pandas()
        assert qe._join_overrides, \
            "expected the skew re-planner to force a broadcast join"
        assert "broadcast" in qe.executed_plan.tree_string()
    finally:
        session.conf.set(MESH, 0)
        session.conf.set(BCAST, old_b)
    assert int(got["c"][0]) == int(want["c"][0]) == n
    assert np.isclose(float(got["s"][0]), float(want["s"][0]))


def test_skew_replan_respects_build_size_limit(session):
    """A skewed join whose build side exceeds the broadcast threshold
    must keep the shuffle plan (capacity growth, correct results)."""
    rs = np.random.RandomState(4)
    n = 40_000
    k = rs.randint(0, 500, n).astype(np.int64)
    k[: int(n * 0.9)] = 3
    fact = pd.DataFrame({"k": k, "v": np.ones(n)})
    dim = pd.DataFrame({"k": np.arange(500, dtype=np.int64),
                        "w": np.ones(500)})
    session.register_table("skew_fact2", fact)
    session.register_table("skew_dim2", dim)
    old_b = session.conf.get(BCAST)
    limit_key = "spark_tpu.sql.adaptive.skewJoin.broadcastThreshold"
    old_l = session.conf.get(limit_key)
    try:
        session.conf.set(MESH, 8)
        session.conf.set(BCAST, 0)
        session.conf.set(limit_key, 1)  # nothing may broadcast
        qe = (session.table("skew_fact2")
              .join(session.table("skew_dim2"),
                    left_on=col("k"), right_on=col("k"))
              .agg(F.count().alias("c")))._qe()
        got = qe.collect().to_pandas()
        assert not qe._join_overrides
    finally:
        session.conf.set(MESH, 0)
        session.conf.set(BCAST, old_b)
        session.conf.set(limit_key, old_l)
    assert int(got["c"][0]) == n


def test_range_sort_balanced_under_clustered_selection(session):
    """Round-4 VERDICT weak #5: bounds sampled at fixed slot positions
    collapse when live rows cluster in slot space. With valid-row
    sampling the range exchange stays balanced (max shard load close to
    the mean) and the global order is exact."""
    n = 40_000
    # live rows are the FIRST 5% of slots (clustered selection)
    pdf = pd.DataFrame({
        "pos": np.arange(n, dtype=np.int64),
        "key": np.random.RandomState(5).permutation(n).astype(np.int64)})
    session.register_table("clus_t", pdf)
    old_metrics = session.conf.get(METRICS)
    try:
        session.conf.set(MESH, 8)
        session.conf.set(METRICS, True)
        qe = (session.table("clus_t")
              .filter(col("pos") < n // 20)
              .sort(col("key"))._qe())
        got = qe.collect().to_pandas()
        exch_max = [v for k, v in qe.last_metrics.items()
                    if k.startswith("exch_max_e")]
    finally:
        session.conf.set(MESH, 0)
        session.conf.set(METRICS, old_metrics)
    live = n // 20
    assert got["key"].tolist() == sorted(got["key"].tolist())
    assert len(got) == live
    assert exch_max, "expected a range exchange"
    # balanced: no shard holds more than 2x the mean
    assert max(exch_max) <= 2 * (live / 8), (max(exch_max), live / 8)


def test_range_sort_tiny_live_counts(session):
    """Code-review r5: shards whose live rows number fewer than the
    sample budget must still contribute all their values (the old mask
    collapsed them onto their minimum), keeping the global order exact."""
    n = 4_000
    pdf = pd.DataFrame({
        "pos": np.arange(n, dtype=np.int64),
        "key": np.random.RandomState(6).permutation(n).astype(np.int64)})
    session.register_table("tiny_live", pdf)
    try:
        session.conf.set(MESH, 8)
        got = (session.table("tiny_live")
               .filter(col("pos") % 100 == 3)  # 5 live rows per shard
               .sort(col("key")).to_pandas())
    finally:
        session.conf.set(MESH, 0)
    assert got["key"].tolist() == sorted(got["key"].tolist())
    assert len(got) == n // 100

"""Partial-progress recovery suite (spark_tpu/execution/recovery.py):
chunk-granular retry inside the streaming drivers, stage-output reuse
across recovery loops, and mesh checkpoint/restore.

The acceptance bar (ISSUE 5): with a `stream_chunk` fault injected at
chunk k, metrics must prove the stream RESUMED (at most one chunk
replayed, not k+1 — `rec_chunks_replayed` / `chunk_retry`), and
Q1/Q3 results must match the no-fault goldens on the streaming, spill
and mesh driver paths."""

import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.testing import faults
from spark_tpu.testing.faults import FaultInjected
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
CACHE_KEY = "spark_tpu.sql.io.deviceCacheBytes"
BUDGET_KEY = "spark_tpu.sql.memory.deviceBudget"
MESH_KEY = "spark_tpu.sql.mesh.size"
DOMAIN_KEY = "spark_tpu.sql.aggregate.maxDirectDomain"
RETRY_ON_KEY = "spark_tpu.execution.chunkRetry.enabled"
RETRY_MAX_KEY = "spark_tpu.execution.chunkRetry.maxRetries"
CKPT_KEY = "spark_tpu.execution.checkpoint.everyChunks"
#: the mesh-checkpoint tests below pin the SINGLE-DEVICE fallback
#: semantics, so the elastic gang-restart rung (which would win first
#: and resume on the mesh) is disabled where noted — the mesh-side
#: recovery ladder is tests/test_elastic.py's subject
RESTART_KEY = "spark_tpu.execution.meshRestart.enabled"


@pytest.fixture(scope="session")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_recovery") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


@pytest.fixture(autouse=True)
def streaming_conf(tpch_session):
    """Chunked streaming on every query (small chunks, device-table
    cache off so _prefer_resident can't bypass the drivers),
    millisecond backoffs, disarmed plan. The conftest conf guard
    restores every key afterwards."""
    conf = tpch_session.conf
    conf.set("spark_tpu.execution.backoffMs", 1)
    conf.set(CHUNK_KEY, 1024)  # lineitem@SF0.002 ~ 12k rows -> ~12 chunks
    conf.set(CACHE_KEY, 0)
    faults.reset()
    yield
    faults.reset()


def _cold(session):
    from spark_tpu.io.device_cache import CACHE
    session._stage_cache.clear()
    session._aqe_caps.clear()
    CACHE.clear()


def _run_query(session, qname):
    df = Q.QUERIES[qname](session)
    qe = df._qe()
    table = qe.collect()
    got = G.normalize_decimals(table.to_pandas()).reset_index(drop=True)
    return got, qe


def _check_golden(got, tpch_path, qname):
    G.compare(got, G.GOLDEN[qname](tpch_path))


def _replayed(session):
    return session.metrics.counter("rec_chunks_replayed").value


# -- chunk-granular retry: all three driver paths ----------------------------

#: (id, qname, extra conf) — which streaming driver carries the query:
#: q1 takes the direct accumulator-carry path; deviceBudget=1 pushes
#: q3 (unbounded l_orderkey keys) and q1 (direct domain collapsed)
#: through the partial-spill path; mesh.size=8 puts q1 on the sharded
#: mesh streaming driver.
_PATHS = [
    ("streaming", "q1", {}),
    ("spill", "q1", {BUDGET_KEY: 1, DOMAIN_KEY: 1}),
    ("spill", "q3", {BUDGET_KEY: 1}),
    ("mesh", "q1", {MESH_KEY: 8}),
]


@pytest.mark.parametrize("path_id,qname,extra",
                         _PATHS, ids=[p[0] + "-" + p[1] for p in _PATHS])
def test_chunk_retry_replays_one_chunk(tpch_session, tpch_path, path_id,
                                       qname, extra):
    """A transient fault at chunk k replays ONLY chunk k: golden
    parity, exactly one chunk_retry action, rec_chunks_replayed grows
    by one, and the whole-query retry loop is never consulted."""
    _cold(tpch_session)
    for k, v in extra.items():
        tpch_session.conf.set(k, v)
    before = _replayed(tpch_session)
    with faults.inject(tpch_session.conf,
                       "stream_chunk:unavailable:3") as plan:
        got, qe = _run_query(tpch_session, qname)
        assert plan.fired_log == [("stream_chunk", 3, "unavailable")]
        assert plan.hits["stream_chunk"] > 3, \
            "stream produced too few chunks — scenario is near-vacuous"
    assert qe.fault_summary.get("chunk_retry") == 1, qe.fault_summary
    # the stream RESUMED: one replay, not a restart (no transient_retry,
    # no second pass over chunks 0..k-1)
    assert _replayed(tpch_session) - before == 1
    assert "transient_retry" not in qe.fault_summary, qe.fault_summary
    _check_golden(got, tpch_path, qname)


def test_chunk_retry_budget_per_chunk(tpch_session, tpch_path):
    """Two faults on DIFFERENT chunks both recover: the retry budget is
    per chunk (spark.task.maxFailures style), not per stream."""
    _cold(tpch_session)
    before = _replayed(tpch_session)
    with faults.inject(tpch_session.conf,
                       "stream_chunk:unavailable:2,"
                       "stream_chunk:unavailable:6") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert len(plan.fired_log) == 2
    assert qe.fault_summary.get("chunk_retry") == 2, qe.fault_summary
    assert _replayed(tpch_session) - before == 2
    _check_golden(got, tpch_path, "q1")


def test_chunk_retry_consecutive_hits_same_chunk(tpch_session, tpch_path):
    """A replay re-fires the seam, so back-to-back rules model a chunk
    that fails twice before succeeding — still within the per-chunk
    budget (maxRetries default 2)."""
    _cold(tpch_session)
    with faults.inject(tpch_session.conf,
                       "stream_chunk:unavailable:3,"
                       "stream_chunk:unavailable:4") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert len(plan.fired_log) == 2
    assert qe.fault_summary.get("chunk_retry") == 2, qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_chunk_retry_disabled_falls_back_to_stream_restart(tpch_session,
                                                           tpch_path):
    """chunkRetry.enabled=false restores PR-2 granularity: the fault
    surfaces to the whole-query ladder, which restarts the stream
    (transient_retry, no chunk_retry) — and still reaches parity."""
    _cold(tpch_session)
    tpch_session.conf.set(RETRY_ON_KEY, False)
    with faults.inject(tpch_session.conf,
                       "stream_chunk:unavailable:3") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log, "fault never fired — scenario is vacuous"
    assert "chunk_retry" not in qe.fault_summary, qe.fault_summary
    assert qe.fault_summary.get("transient_retry", 0) >= 1, qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_chunk_retry_zero_budget_behaves_disabled(tpch_session, tpch_path):
    _cold(tpch_session)
    tpch_session.conf.set(RETRY_MAX_KEY, 0)
    with faults.inject(tpch_session.conf, "stream_chunk:unavailable:2"):
        got, qe = _run_query(tpch_session, "q1")
    assert "chunk_retry" not in qe.fault_summary
    assert qe.fault_summary.get("transient_retry", 0) >= 1
    _check_golden(got, tpch_path, "q1")


def test_chunk_retry_fatal_not_absorbed(tpch_session):
    """Chunk retry only covers TRANSIENT/TIMEOUT: a fatal fault inside
    the chunk loop surfaces unchanged."""
    _cold(tpch_session)
    with faults.inject(tpch_session.conf, "stream_chunk:fatal:2"):
        with pytest.raises(FaultInjected, match="INTERNAL"):
            _run_query(tpch_session, "q1")


def test_chunk_retry_external_collect(tpch_session, tpch_path):
    """The out-of-core host-egress path (execution/external.py) rides
    the same per-chunk retry: ORDER BY over a scan past the device
    budget recovers a mid-stream flake chunk-wise."""
    import pandas as pd
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(BUDGET_KEY, 1)
    df = tpch_session.table("lineitem") \
        .select(col("l_orderkey"), col("l_quantity")) \
        .order_by(col("l_orderkey"))
    before = _replayed(tpch_session)
    with faults.inject(conf, "stream_chunk:unavailable:2") as plan:
        qe = df._qe()
        got = qe.collect().to_pandas()
        assert plan.fired_log, "external stream never chunked — vacuous"
    assert qe.fault_summary.get("chunk_retry") == 1, qe.fault_summary
    assert _replayed(tpch_session) - before == 1
    want = pd.read_parquet(tpch_path + "/lineitem.parquet")[
        ["l_orderkey", "l_quantity"]].sort_values(
        "l_orderkey", kind="stable").reset_index(drop=True)
    assert got["l_orderkey"].tolist() == want["l_orderkey"].tolist()
    assert float(got["l_quantity"].sum()) == pytest.approx(
        float(want["l_quantity"].sum()))


# -- stage-output reuse across recovery loops --------------------------------

def test_stage_reuse_upstream_runs_once(tpch_session, tpch_path,
                                        monkeypatch):
    """The surviving-shuffle-file analog: a transient fault in the
    DOWNSTREAM final stage re-executes the query, but the completed
    streamed-aggregate stage (and its join build sides) replay from
    the stage-output memo — the spill driver runs exactly once."""
    import spark_tpu.execution.streaming_agg as SA
    _cold(tpch_session)
    tpch_session.conf.set(BUDGET_KEY, 1)
    calls = []
    orig = SA.try_stream_aggregate_spill

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(SA, "try_stream_aggregate_spill", counting)
    reused0 = tpch_session.metrics.counter("rec_stages_reused").value
    with faults.inject(tpch_session.conf,
                       "stage_run:unavailable:1") as plan:
        got, qe = _run_query(tpch_session, "q3")
        assert plan.fired_log == [("stage_run", 1, "unavailable")]
    assert len(calls) == 1, "upstream stream re-ran despite the memo"
    assert qe.fault_summary.get("transient_retry", 0) >= 1
    assert qe.fault_summary.get("stage_reuse", 0) >= 1, qe.fault_summary
    assert tpch_session.metrics.counter(
        "rec_stages_reused").value - reused0 >= 1
    _check_golden(got, tpch_path, "q3")


def test_stage_reuse_counted_once_per_attempt():
    """A re-execution may consult the same memo entry several times
    (direct probe, then spill fallback): that is ONE reused stage, not
    several — but a LATER recovery attempt counts it again."""
    from spark_tpu.execution.recovery import RecoveryContext
    recorded = []
    rc = RecoveryContext(record=lambda a, e=None, **kw: recorded.append(a))
    rc.memo_put(("build", 1), "b")
    assert rc.memo_get(("build", 1)) == "b"
    assert recorded == []  # pre-failure dedup: not a recovery action
    rc.begin_recovery_attempt()
    assert rc.memo_get(("build", 1)) == "b"
    assert rc.memo_get(("build", 1)) == "b"  # same attempt: one record
    assert recorded == ["stage_reuse"]
    rc.begin_recovery_attempt()
    assert rc.memo_get(("build", 1)) == "b"  # next attempt counts again
    assert recorded == ["stage_reuse", "stage_reuse"]


def test_oom_evicts_memoized_stage_outputs(tpch_session, tpch_path):
    """OOM rung 1 evicts the storage pool — including memoized stage
    outputs, which pin device batches: the retry must re-run the
    stream unpinned (no stage_reuse), and still reach parity."""
    _cold(tpch_session)
    tpch_session.conf.set(BUDGET_KEY, 1)
    with faults.inject(tpch_session.conf,
                       "stage_run:resource_exhausted:1") as plan:
        got, qe = _run_query(tpch_session, "q3")
        assert plan.fired_log, "OOM never fired — scenario is vacuous"
    assert qe.fault_summary.get("oom_cache_evict", 0) >= 1
    assert "stage_reuse" not in qe.fault_summary, qe.fault_summary
    _check_golden(got, tpch_path, "q3")


def test_external_collect_exhausted_chunk_budget_restarts_stream(
        tpch_session):
    """When a chunk burns its whole per-chunk budget on the external
    path, the failure surfaces to a whole-stream transient rung (the
    documented fallback) instead of aborting collect()."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(BUDGET_KEY, 1)
    # per-chunk budget is 2: hits 1,2,3 exhaust chunk 0's retries; the
    # stream restart then passes (every rule already fired)
    spec = ",".join(f"stream_chunk:unavailable:{n}" for n in (1, 2, 3))
    df = tpch_session.table("lineitem") \
        .select(col("l_orderkey")).order_by(col("l_orderkey"))
    with faults.inject(conf, spec) as plan:
        qe = df._qe()
        got = qe.collect()
        assert len(plan.fired_log) == 3
    assert qe.fault_summary.get("chunk_retry", 0) == 2, qe.fault_summary
    assert qe.fault_summary.get("transient_retry", 0) == 1, qe.fault_summary
    keys = got.column("l_orderkey").to_pylist()
    assert keys == sorted(keys)  # complete, ordered result
    want_rows = len(tpch_session.table("lineitem").to_pandas())
    assert got.num_rows == want_rows


def test_stage_reuse_invalidated_on_spill_replan(tpch_session, tpch_path):
    """The OOM ladder's rung-2 deviceBudget re-plan changes streaming
    shapes: memoized outputs must NOT splice into the new plan. Two
    OOMs descend to the reroute; the rerouted run must still hit
    parity (a stale splice would not)."""
    _cold(tpch_session)
    spec = "stage_run:resource_exhausted:1,stage_run:resource_exhausted:2"
    with faults.inject(tpch_session.conf, spec):
        got, qe = _run_query(tpch_session, "q1")
    assert qe.fault_summary.get("oom_spill_reroute", 0) >= 1
    _check_golden(got, tpch_path, "q1")


def test_clean_run_records_no_recovery_actions(tpch_session, tpch_path):
    """Noise gate: streaming with every recovery feature armed but no
    faults records NOTHING in fault_summary (memo fills, checkpoints
    save — neither is a recovery action)."""
    _cold(tpch_session)
    tpch_session.conf.set(MESH_KEY, 8)
    tpch_session.conf.set(CKPT_KEY, 2)
    ckpt0 = tpch_session.metrics.counter("rec_ckpt_bytes").value
    got, qe = _run_query(tpch_session, "q1")
    assert qe.fault_summary == {}, qe.fault_summary
    # ...but the checkpoints were really taken
    assert tpch_session.metrics.counter("rec_ckpt_bytes").value > ckpt0
    _check_golden(got, tpch_path, "q1")


# -- mesh checkpoint/restore -------------------------------------------------

def test_checkpoint_restore_resumes_at_cursor(tpch_session, tpch_path):
    """A mesh host lost at the 2nd snapshot point: the single-device
    fallback hands the chunk-2 checkpoint to the resumed stream, which
    skips the checkpointed chunks instead of restarting at chunk 0 —
    and the merged result is golden-identical. Gang restart is
    disabled: the SINGLE-DEVICE restore rung is what this test pins
    (the mesh-side resume is tests/test_elastic.py's)."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(RESTART_KEY, False)
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 2)
    ckpt0 = tpch_session.metrics.counter("rec_ckpt_bytes").value
    with faults.inject(conf, "mesh_checkpoint:fatal:2") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log == [("mesh_checkpoint", 2, "fatal")]
    assert qe.fault_summary.get("mesh_fallback") == 1, qe.fault_summary
    assert qe.fault_summary.get("checkpoint_restore") == 1, qe.fault_summary
    restore = next(ev for ev in qe.fault_events
                   if ev["action"] == "checkpoint_restore")
    assert restore["cursor"] == 2  # resumed at the snapshot, not chunk 0
    assert restore["ckpt_rows"] > 0
    assert tpch_session.metrics.counter("rec_ckpt_bytes").value > ckpt0
    _check_golden(got, tpch_path, "q1")


def test_checkpoint_disabled_fallback_restarts(tpch_session, tpch_path):
    """checkpoint.everyChunks=0: a mid-stream mesh loss falls back
    single-device WITHOUT a restore (PR-2 behavior) — parity via full
    restart. The mesh_checkpoint seam never fires, so the fault rides
    the mesh site at compile instead."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(RESTART_KEY, False)
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 0)
    with faults.inject(conf, "mesh:fatal:1") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log == [("mesh", 1, "fatal")]
    assert qe.fault_summary.get("mesh_fallback") == 1
    assert "checkpoint_restore" not in qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_checkpoint_lost_before_first_snapshot_restarts(tpch_session,
                                                        tpch_path):
    """A mesh lost AT the first snapshot attempt has no checkpoint to
    resume from: the fallback must restart from chunk 0 (no
    checkpoint_restore) and still reach parity."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(RESTART_KEY, False)
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 3)
    with faults.inject(conf, "mesh_checkpoint:fatal:1") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log == [("mesh_checkpoint", 1, "fatal")]
    assert qe.fault_summary.get("mesh_fallback") == 1
    assert "checkpoint_restore" not in qe.fault_summary, qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_checkpoint_chunk_size_mismatch_ignored(tpch_session, tpch_path):
    """A checkpoint keyed under different chunk boundaries (e.g. the
    OOM ladder shrank streamingChunkRows between save and restore)
    must not restore — checkpoint_key pins the chunk size, so the
    fallback safely restarts from chunk 0."""
    _cold(tpch_session)
    conf = tpch_session.conf
    conf.set(RESTART_KEY, False)
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 2)

    # fail at the 3rd snapshot, then shrink the chunk size for the
    # fallback via a conf the restore path reads at resume time
    from spark_tpu.execution import executor as EX
    orig = EX.QueryExecution._handle_failure

    def shrink_then_handle(self, e):
        conf.set(CHUNK_KEY, 512)  # fallback streams different chunks
        return orig(self, e)

    EX.QueryExecution._handle_failure = shrink_then_handle
    try:
        with faults.inject(conf, "mesh_checkpoint:fatal:3") as plan:
            got, qe = _run_query(tpch_session, "q1")
            assert plan.fired_log == [("mesh_checkpoint", 3, "fatal")]
    finally:
        EX.QueryExecution._handle_failure = orig
    assert qe.fault_summary.get("mesh_fallback") == 1
    assert "checkpoint_restore" not in qe.fault_summary, qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_checkpoint_key_distinguishes_filter_values(tpch_session):
    """Two same-shaped aggregates over the same source differing only
    in predicate literals must not share a checkpoint slot (a restore
    seeded from the other stream's partials would be silently wrong)."""
    from spark_tpu.execution.streaming_agg import checkpoint_key
    from spark_tpu.plan import physical as P

    def leaf_of(df):
        qe = df._qe()
        out = []

        def walk(n):
            if isinstance(n, P.ScanExec):
                out.append(n)
            for c in n.children:
                walk(c)

        walk(qe.executed_plan)
        agg = [n for n in _iter_nodes(qe.executed_plan)
               if isinstance(n, P.HashAggregateExec)][0]
        return agg, out[0]

    def _iter_nodes(n):
        yield n
        for c in n.children:
            yield from _iter_nodes(c)

    base = tpch_session.table("lineitem")
    a1, l1 = leaf_of(base.filter(col("l_quantity") < 10).agg(
        F.sum(col("l_quantity")).alias("s")))
    a2, l2 = leaf_of(base.filter(col("l_quantity") < 20).agg(
        F.sum(col("l_quantity")).alias("s")))
    assert checkpoint_key(a1, l1, 1024) != checkpoint_key(a2, l2, 1024)
    # and the same plan produces the same key (save/restore must match)
    a3, l3 = leaf_of(base.filter(col("l_quantity") < 10).agg(
        F.sum(col("l_quantity")).alias("s")))
    assert checkpoint_key(a1, l1, 1024) == checkpoint_key(a3, l3, 1024)


def test_ingest_reader_failure_never_truncates(tpch_session):
    """A mid-stream failure of the UNDERLYING batch reader (a
    generator-backed scanner) kills the generator; retrying next()
    would read the dead reader as end-of-stream and silently aggregate
    a prefix. The iterator poisons itself instead: the per-chunk retry
    re-raises, the whole-query ladder restarts the stream fresh, and
    the result is complete."""
    import pyarrow as pa
    from spark_tpu.io.sources import ArrowTableSource, ChunkIterator

    table = pa.table({"v": list(range(10000))})
    fails = [True]  # the reader dies once, mid-stream, per process

    class FlakyOnceSource(ArrowTableSource):
        def load_chunks(self, required_columns, pushed_filters,
                        chunk_rows):
            def batches():
                for i, rb in enumerate(self.table.to_batches(
                        max_chunksize=1024)):
                    if i == 3 and fails[0]:
                        fails[0] = False
                        raise RuntimeError(
                            "UNAVAILABLE: reader connection reset")
                    yield rb
            return ChunkIterator(batches(), chunk_rows)

    tpch_session.register_table("flaky_t", FlakyOnceSource("flaky_t",
                                                           table))
    df = tpch_session.table("flaky_t").group_by(
        (col("v") % 7).alias("k")).agg(F.sum(col("v")).alias("s"))
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the transient-retry warning
        got = df.to_pandas().sort_values("k").reset_index(drop=True)
    assert not fails[0], "reader never failed — scenario is vacuous"
    # complete result — NOT the 3-batch prefix the dead reader buffered
    assert int(got["s"].sum()) == sum(range(10000))


# -- event-log / history observability ---------------------------------------

def test_recovery_actions_reach_history(tpch_session, tpch_path, tmp_path):
    from spark_tpu import history
    _cold(tpch_session)
    log_dir = str(tmp_path / "events")
    conf = tpch_session.conf
    conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    try:
        with faults.inject(conf, "stream_chunk:unavailable:2"):
            got, qe = _run_query(tpch_session, "q1")
    finally:
        conf.set("spark_tpu.sql.eventLog.dir", "")
    _check_golden(got, tpch_path, "q1")
    events = history.read_event_log(log_dir)
    summary = history.fault_summary(events)
    assert len(summary) >= 1
    row = summary.iloc[-1]
    assert row["chunk_retry"] == 1
    assert row["events_dropped"] == 0
    assert any(ev.get("action") == "chunk_retry" and "chunk" in ev
               for ev in row["events"])


# -- satellite bugfixes ------------------------------------------------------

def test_fault_events_cap_counts_drops(tpch_session):
    """executor._record_fault caps the event list at 32; overflow used
    to vanish silently — now fault_summary carries events_dropped."""
    qe = tpch_session.range(10)._qe()
    for i in range(40):
        qe._record_fault("transient_retry", RuntimeError(f"e{i}"))
    assert len(qe.fault_events) == 32
    assert qe.fault_summary["transient_retry"] == 40
    assert qe.fault_summary["events_dropped"] == 8


def test_recovery_nonconvergence_diagnostic(tpch_session):
    """_execute_recover's 32-action bound used to raise a bare
    RuntimeError; the message now carries the accumulated fault_summary
    and the last error, so a non-converging recovery is diagnosable."""
    qe = tpch_session.range(10)._qe()
    qe.fault_summary = {"transient_retry": 5}

    def boom():
        raise RuntimeError("UNAVAILABLE: flaky backend endpoint")

    qe._execute_batch_inner = boom
    qe._handle_failure = lambda e: None  # pretend every action applies
    with pytest.raises(RuntimeError, match="did not converge") as ei:
        qe._execute_recover()
    msg = str(ei.value)
    assert "transient_retry" in msg and "flaky backend endpoint" in msg

"""Concurrency analyzer suite: guarded-by lint, lock-order graph,
runtime lockwatch, and the multithreaded service stress test.

Layout mirrors tests/test_analysis.py's lint sections: per-pass
synthetic violations against injectable registries, a clean-tree
zero-findings gate over the real repository, regression tests for the
unguarded-write fixes this PR landed (listener-bus counters, faults
suppression thread-confinement, arbiter install race, prefetch-worker
join), and the stress test that proves the static lock-order claims
against OBSERVED acquisition order under real concurrent load.
"""

import ast
import json
import os
import threading
import time
import warnings

import pandas as pd
import pytest

from spark_tpu.analysis.concurrency.guarded import (GuardedAnalysis,
                                                    RegistryView)
from spark_tpu.analysis.concurrency.lockorder import (LockOrderAnalysis,
                                                      build_graph)
from spark_tpu.analysis.concurrency.registry import (CONFINED, GUARDED_BY,
                                                     LOCKS, WAIVERS,
                                                     ConfinedDecl,
                                                     GuardDecl, LockDecl,
                                                     Waiver)
from spark_tpu.testing.lockwatch import LockWatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry sanity
# ---------------------------------------------------------------------------


def test_registry_ids_and_ranks_unique():
    ids = [d.lock_id for d in LOCKS]
    assert len(ids) == len(set(ids)), "duplicate lock ids"
    ranks = [d.rank for d in LOCKS]
    assert len(ranks) == len(set(ranks)), \
        "ranks must be distinct: they are the canonical total order"
    sites = [(d.relpath, d.cls, d.attr) for d in LOCKS]
    assert len(sites) == len(set(sites)), "duplicate lock sites"


def test_registry_guards_reference_real_locks():
    lock_attrs = {(d.relpath, d.cls): set() for d in LOCKS}
    for d in LOCKS:
        lock_attrs[(d.relpath, d.cls)].add(d.attr)
    for g in GUARDED_BY:
        assert g.lock in lock_attrs.get((g.relpath, g.cls), set()), \
            f"GuardDecl {g} names a lock with no LockDecl"


def test_registry_waivers_and_confined_carry_reasons():
    for w in WAIVERS:
        assert w.reason.strip(), f"empty waiver reason: {w}"
    for c in CONFINED:
        assert c.reason.strip(), f"empty confined reason: {c}"


# ---------------------------------------------------------------------------
# guarded-by pass: synthetic violations
# ---------------------------------------------------------------------------

_MOD = "spark_tpu/fake.py"


def _view(locks=(), guards=(), waivers=(), confined=()):
    return RegistryView(locks=locks, guards=guards, waivers=waivers,
                        confined=confined, receiver_names={},
                        receiver_attrs={}, factory_returns={},
                        context_managers={}, extra_edges=(),
                        held_callees={})


def _run_guarded(src, view):
    a = GuardedAnalysis(view)
    a.add_file(_MOD, ast.parse(src))
    return a.finish()


_BOX_LOCK = LockDecl("t.box", _MOD, "Box", "_lock", "lock", 10)
_BOX_GUARD = GuardDecl(_MOD, "Box", "items", "_lock")


def test_guarded_by_clean_class():
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "    def add(self, x):\n"
        "        with self._lock:\n"
        "            self.items.append(x)\n")
    out = _run_guarded(src, _view((_BOX_LOCK,), (_BOX_GUARD,)))
    assert out == [], out


def test_guarded_by_flags_unguarded_write():
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "    def add(self, x):\n"
        "        self.items.append(x)\n"          # no lock held
        "    def reset(self):\n"
        "        self.items = []\n")              # rebind, no lock
    out = _run_guarded(src, _view((_BOX_LOCK,), (_BOX_GUARD,)))
    codes = [(code, line) for _, line, code, _ in out]
    assert ("GB101", 7) in codes and ("GB101", 9) in codes, out


def test_guarded_by_flags_undeclared_shared_state():
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "        self.extra = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.extra += 1\n")  # guarded, but NOT declared
    out = _run_guarded(src, _view((_BOX_LOCK,), (_BOX_GUARD,)))
    assert [code for _, _, code, _ in out] == ["GB102"], out
    # a waiver (with its reason) silences it
    out2 = _run_guarded(src, _view(
        (_BOX_LOCK,), (_BOX_GUARD,),
        waivers=(Waiver(_MOD, "Box", "extra", "benign test race"),)))
    assert out2 == [], out2


def test_guarded_by_flags_unregistered_and_stale_locks():
    src = (
        "import threading\n"
        "class Rogue:\n"
        "    def __init__(self):\n"
        "        self._mystery = threading.Lock()\n")
    out = _run_guarded(src, _view((_BOX_LOCK,)))
    codes = {code for _, _, code, _ in out}
    # Rogue._mystery exists but is unregistered; t.box is declared but
    # has no creation site in this synthetic tree
    assert codes == {"GB104", "GB105"}, out


def test_guarded_by_confined_class_skips_checks():
    src = (
        "class Driver:\n"
        "    def step(self):\n"
        "        self.cursor = 1\n")
    view = _view(confined=(ConfinedDecl(_MOD, "Driver", "ctxvar"),))
    assert _run_guarded(src, view) == []


def test_guarded_by_module_globals_and_contextvar():
    src = (
        "from contextvars import ContextVar\n"
        "V = ContextVar('v', default=None)\n"
        "STATE = {}\n"
        "def set_v(x):\n"
        "    global V\n"
        "    V = x\n"                 # ContextVar-backed: confined
        "def poke(k):\n"
        "    STATE[k] = 1\n")         # module dict, no guard: flagged
    # bring the module into write-check scope via a module-level guard
    # (OTHER/_L are stale and separately reported as GB103; only the
    # global-write verdicts matter here)
    view = _view(guards=(GuardDecl(_MOD, "", "OTHER", "_L"),))
    out = _run_guarded(src, view)
    gb102 = [msg for _, _, code, msg in out if code == "GB102"]
    assert any("STATE" in m for m in gb102), out
    assert not any("module global V " in m for m in gb102), \
        "ContextVar-backed global must be recognized as confined"


# ---------------------------------------------------------------------------
# lock-order pass: synthetic graphs
# ---------------------------------------------------------------------------


def _run_lockorder(src, view):
    a = LockOrderAnalysis(view)
    a.add_file(_MOD, ast.parse(src))
    return a.finish()


def test_lock_order_nested_with_edge_and_inversion():
    locks = (LockDecl("t.a", _MOD, "Two", "_a", "lock", 10),
             LockDecl("t.b", _MOD, "Two", "_b", "lock", 20))
    good = (
        "class Two:\n"
        "    def fwd(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n")
    edges, out = _run_lockorder(good, _view(locks))
    assert ("t.a", "t.b") in edges and out == [], (edges, out)
    bad = good.replace("self._a", "X").replace("self._b", "self._a") \
        .replace("X", "self._b")
    edges, out = _run_lockorder(bad, _view(locks))
    assert ("t.b", "t.a") in edges
    assert [code for _, _, code, _ in out] == ["LO202"], out


def test_lock_order_cycle_detected_via_call_graph():
    # equal ranks on purpose: the rank check alone cannot carry the
    # verdict, so the cycle detector must fire on a -> b -> a — one
    # direction extracted through a method CALL made under a held
    # lock, the other declared via EXTRA_EDGES (the escape hatch for
    # holds the lexical extractor cannot see)
    locks = (LockDecl("t.a", _MOD, "P", "_a", "lock", 10),
             LockDecl("t.b", _MOD, "P", "_b", "lock", 10))
    src = (
        "class P:\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            self.two()\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            pass\n")
    view = _view(locks)
    view.extra_edges = (("t.b", "t.a", "synthetic reverse edge"),)
    a = LockOrderAnalysis(view)
    a.add_file(_MOD, ast.parse(src))
    edges, out = a.finish()
    assert ("t.a", "t.b") in edges and ("t.b", "t.a") in edges, edges
    assert any(code == "LO201" and "cycle" in msg
               for _, _, code, msg in out), out


def test_lock_order_multi_item_with_records_inter_item_edge():
    """`with self._a, self._b:` — item a is held when item b acquires,
    so the a->b edge (and an inversion written that way) must not slip
    past the static pass."""
    locks = (LockDecl("t.a", _MOD, "M", "_a", "lock", 10),
             LockDecl("t.b", _MOD, "M", "_b", "lock", 20))
    src = (
        "class M:\n"
        "    def both(self):\n"
        "        with self._a, self._b:\n"
        "            pass\n")
    edges, out = _run_lockorder(src, _view(locks))
    assert ("t.a", "t.b") in edges and out == [], (edges, out)
    inverted = src.replace("self._a, self._b", "self._b, self._a")
    edges, out = _run_lockorder(inverted, _view(locks))
    assert ("t.b", "t.a") in edges
    assert [code for _, _, code, _ in out] == ["LO202"], out


def test_guarded_by_multi_item_with_counts_earlier_items_held():
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "    def add(self, x):\n"
        "        with self._lock, open('f'):\n"
        "            self.items.append(x)\n")
    out = _run_guarded(src, _view((_BOX_LOCK,), (_BOX_GUARD,)))
    assert out == [], out


def test_lock_order_self_deadlock_on_non_reentrant_lock():
    locks = (LockDecl("t.a", _MOD, "R", "_a", "lock", 10),)
    src = (
        "class R:\n"
        "    def outer(self):\n"
        "        with self._a:\n"
        "            self.inner()\n"
        "    def inner(self):\n"
        "        with self._a:\n"
        "            pass\n")
    _, out = _run_lockorder(src, _view(locks))
    assert any(code == "LO201" and "self-deadlock" in msg
               for _, _, code, msg in out), out
    # the same shape on an rlock is legal
    rlocks = (LockDecl("t.a", _MOD, "R", "_a", "rlock", 10),)
    _, out2 = _run_lockorder(src, _view(rlocks))
    assert out2 == [], out2


# ---------------------------------------------------------------------------
# real tree: clean gate + graph shape
# ---------------------------------------------------------------------------


def test_concurrency_passes_clean_on_real_tree():
    from spark_tpu.analysis.lints import run_passes
    notes = []
    out = run_passes(["guarded-by", "lock-order"], repo=REPO,
                     collect_notes=notes)
    assert [v.render() for v in out] == []
    # the waiver list is reviewer-visible in the lint output
    assert sum(n.startswith("waiver:") for n in notes) == len(WAIVERS)
    assert any(n.startswith("lock-order:") for n in notes)


def test_static_graph_has_known_edges_and_ascends():
    edges, violations = build_graph(REPO)
    assert violations == [], violations
    # the load-bearing nestings extracted from code, not declared:
    # arbiter holds its cv while evicting storage, and while counting
    assert ("service.arbiter", "io.device_cache") in edges
    assert ("service.arbiter", "metrics.counter") in edges
    # factory-return chains resolve (registry.counter(x).inc())
    assert ("service.admission", "metrics.registry") in edges
    from spark_tpu.analysis.concurrency.registry import rank_of
    for a, b in edges:
        if a != b:
            assert rank_of(a) < rank_of(b), (a, b)


def test_tracer_leak_scope_covers_service_and_observability(tmp_path):
    from spark_tpu.analysis.lints import run_passes
    files = {
        "spark_tpu/service/bad.py": "k = hash(col.data)\n",
        "spark_tpu/observability/bad.py": "b = bool(jnp.any(x))\n",
        "spark_tpu/streaming.py": "h = hash(batch.validity)\n",
        "spark_tpu/ml/fine.py": "h = hash(x)\n",  # out of scope
    }
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    out = run_passes(["tracer-leak"], repo=str(tmp_path))
    flagged = {v.path for v in out}
    assert flagged == {"spark_tpu/service/bad.py",
                       "spark_tpu/observability/bad.py",
                       "spark_tpu/streaming.py"}, out


def test_lint_json_output_shape(capsys):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint_cli_json", os.path.join(REPO, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--json", "guarded-by", "lock-order"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True
    assert payload["passes"] == ["guarded-by", "lock-order"]
    assert payload["violations"] == []
    assert any(n.startswith("waiver:") for n in payload["notes"])


def test_lint_severity_flows_and_warn_does_not_fail(capsys):
    """The (line, msg, code, severity) tuple protocol is live end to
    end: a warn-severity violation surfaces in text and --json output
    but exits 0 (only error severity fails the lint)."""
    import importlib.util

    from spark_tpu.analysis.lints import (LINT_PASSES, LintPass,
                                          register_lint, run_passes)

    @register_lint
    class _WarnOnly(LintPass):
        name = "test-warn-only"
        code = "TW100"
        doc = "synthetic warn emitter"

        def scope(self, relpath):
            return False

        def check(self, tree, relpath, ctx):
            return []

        def finish(self, ctx):
            return [("somewhere.py", 1, "advisory only", "TW100",
                     "warn")]

    try:
        out = run_passes(["test-warn-only"], repo=REPO)
        assert [(v.code, v.severity) for v in out] == \
            [("TW100", "warn")]
        spec = importlib.util.spec_from_file_location(
            "lint_cli_warn", os.path.join(REPO, "scripts", "lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(["test-warn-only"])
        text = capsys.readouterr().out
        assert rc == 0 and "ok with 1 warning(s)" in text, text
        rc = mod.main(["--json", "test-warn-only"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0 and payload["ok"] is True
        assert payload["violations"][0]["severity"] == "warn"
    finally:
        del LINT_PASSES["test-warn-only"]


# ---------------------------------------------------------------------------
# lockwatch units
# ---------------------------------------------------------------------------


class _Holder:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()


def test_lockwatch_records_edges_and_asserts_order():
    h = _Holder()
    watch = LockWatch()
    # real registry ids so rank lookups work: pool (14) < registry (60)
    watch.watch_attr(h, "a", "service.pool")
    watch.watch_attr(h, "b", "metrics.registry")
    with h.a:
        with h.b:
            pass
    assert watch.edges() == {("service.pool", "metrics.registry"): 1}
    watch.assert_order_consistent()
    stats = watch.report()["locks"]
    assert stats["service.pool"]["acquires"] == 1
    assert stats["service.pool"]["hold_s"] > 0
    watch.uninstall()
    assert h.a.__class__ is threading.Lock().__class__


def test_lockwatch_detects_inverted_order():
    h = _Holder()
    watch = LockWatch()
    watch.watch_attr(h, "a", "metrics.registry")   # rank 60
    watch.watch_attr(h, "b", "service.pool")       # rank 14
    with h.a:
        with h.b:  # 60 held while acquiring 14: inversion
            pass
    with pytest.raises(AssertionError, match="inverts the registry"):
        watch.assert_order_consistent()
    watch.uninstall()


def test_lockwatch_condition_wait_releases_hold():
    class _CvBox:
        def __init__(self):
            self.cv = threading.Condition()

    box = _CvBox()
    watch = LockWatch()
    watch.watch_attr(box, "cv", "service.admission")
    state = {"ready": False}

    def producer():
        with box.cv:
            state["ready"] = True
            box.cv.notify_all()

    with box.cv:
        t = threading.Thread(target=producer)
        t.start()
        # wait() releases the cv (the producer can take it) and the
        # watch pops/re-pushes the held entry around the inner wait
        assert box.cv.wait_for(lambda: state["ready"], timeout=5)
    t.join(5)
    watch.assert_order_consistent()
    assert watch.report()["locks"]["service.admission"]["acquires"] >= 2
    watch.uninstall()


def test_lockwatch_counts_contention():
    h = _Holder()
    watch = LockWatch()
    watch.watch_attr(h, "a", "service.pool")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with h.a:
            entered.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    entered.wait(5)
    got = h.a.acquire(blocking=False)
    assert got is False
    release.set()
    t.join(5)
    assert watch.report()["locks"]["service.pool"]["contended"] >= 1
    watch.uninstall()


def test_lockwatch_distinct_same_id_locks_flag_abba_shape():
    """Two DIFFERENT lock objects sharing one lock id (two sessions'
    leases) nested on one thread is an ABBA deadlock shape no rank
    ordering can catch — it must record and fail the consistency
    assert (same-OBJECT reentrancy must not)."""
    h = _Holder()
    watch = LockWatch()
    watch.watch_attr(h, "a", "service.session")
    watch.watch_attr(h, "b", "service.session")  # distinct lock, same id
    with h.a:
        with h.b:
            pass
    assert ("service.session", "service.session") in watch.edges()
    with pytest.raises(AssertionError, match="ABBA"):
        watch.assert_order_consistent()
    watch.uninstall()


def test_lockwatch_reentrant_same_object_not_flagged():
    class _R:
        def __init__(self):
            self.lk = threading.RLock()

    h = _R()
    watch = LockWatch()
    watch.watch_attr(h, "lk", "io.device_cache")
    with h.lk:
        with h.lk:  # same object: genuine reentrancy, no edge
            pass
    assert watch.edges() == {}
    watch.assert_order_consistent()
    watch.uninstall()


def test_guarded_by_nested_function_global_reported_once():
    """A violation inside a nested def must be reported exactly once
    (the module scan walks top-level functions only; _walk recursion
    covers nesting)."""
    src = (
        "STATE = {}\n"
        "def outer():\n"
        "    def inner():\n"
        "        STATE['k'] = 1\n"
        "    inner()\n")
    view = _view(guards=(GuardDecl(_MOD, "", "STATE", "_L"),))
    out = [v for v in _run_guarded(src, view) if v[2] == "GB101"]
    assert len(out) == 1, out


def test_lockwatch_thread_leak_assertion():
    watch = LockWatch()
    ok = threading.Thread(target=lambda: time.sleep(0.2), daemon=True,
                          name="spark-tpu-leaktest-short")
    ok.start()
    watch.assert_no_thread_leak(prefix="spark-tpu-leaktest-short",
                                timeout_s=5)
    bad = threading.Thread(target=lambda: time.sleep(10), daemon=True,
                           name="spark-tpu-leaktest-long")
    bad.start()
    with pytest.raises(AssertionError, match="still alive"):
        watch.assert_no_thread_leak(prefix="spark-tpu-leaktest-long",
                                    timeout_s=0.3)


# ---------------------------------------------------------------------------
# regression tests for the fixes the guarded-by pass demanded
# ---------------------------------------------------------------------------


def test_listener_bus_drop_counter_is_lossless_under_threads():
    """`dropped += 1` was an unlocked read-modify-write: concurrent
    service threads posting through a raising listener lost counts."""
    from spark_tpu.observability.listener import (ListenerBus,
                                                  QueryListener,
                                                  QueryStartEvent)

    class Raising(QueryListener):
        def on_query_start(self, event):
            raise RuntimeError("boom")

    bus = ListenerBus()
    bus.register(Raising())
    threads, posts = 8, 25
    barrier = threading.Barrier(threads)

    def worker():
        barrier.wait()
        for i in range(posts):
            bus.post("on_query_start",
                     QueryStartEvent(query_id=i, ts=0.0, plan=""))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ts = [threading.Thread(target=worker) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
    assert bus.dropped == threads * posts


def test_listener_bus_concurrent_register_during_post():
    from spark_tpu.observability.listener import (ListenerBus,
                                                  QueryListener,
                                                  QueryStartEvent)

    class Quiet(QueryListener):
        pass

    bus = ListenerBus()
    stop = threading.Event()

    def churn():
        li = Quiet()
        while not stop.is_set():
            bus.register(li)
            bus.unregister(li)

    t = threading.Thread(target=churn)
    t.start()
    try:
        for i in range(500):
            bus.post("on_query_start",
                     QueryStartEvent(query_id=i, ts=0.0, plan=""))
    finally:
        stop.set()
        t.join(10)
    assert bus.dropped == 0


def test_faults_suppression_is_thread_confined(session):
    """`suppressed()` used to swap the GLOBAL plan to None: any thread
    inside an analysis re-trace disarmed chaos sites for EVERY
    concurrent query. Suppression is now a ContextVar: another
    thread's fire() still counts (and raises) while this thread is
    suppressed."""
    from spark_tpu.testing import faults
    entered = threading.Event()
    release = threading.Event()

    def hold_suppressed():
        with faults.suppressed():
            entered.set()
            release.wait(10)

    with faults.inject(session.conf, "scan_load:fatal:1") as plan:
        t = threading.Thread(target=hold_suppressed)
        t.start()
        try:
            assert entered.wait(10)
            with pytest.raises(faults.FaultInjected):
                faults.fire("scan_load")
            assert plan.fired_log, "fire was suppressed cross-thread"
        finally:
            release.set()
            t.join(10)


def test_faults_suppression_still_masks_same_thread(session):
    from spark_tpu.testing import faults
    with faults.inject(session.conf, "scan_load:fatal:1") as plan:
        with faults.suppressed():
            faults.fire("scan_load")  # must NOT raise or count
        assert plan.fired_log == []
        with pytest.raises(faults.FaultInjected):
            faults.fire("scan_load")


def test_service_arbiter_install_race_installs_exactly_once():
    from spark_tpu import Conf
    from spark_tpu.service.arbiter import get_arbiter, install_arbiter
    from spark_tpu.service.server import SqlService
    conf = Conf()
    conf.set("spark_tpu.service.hbmBudget", 1 << 30)
    svc = SqlService(conf)
    try:
        barrier = threading.Barrier(8)

        def racer():
            barrier.wait()
            svc._ensure_arbiter()

        ts = [threading.Thread(target=racer) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert get_arbiter() is svc.arbiter
        assert svc._installed_arbiter
    finally:
        svc.stop()
    assert get_arbiter() is None  # stop() uninstalled what it owned


class _FakeChunkSource:
    """Minimal ChunkIterator stand-in for prefetch-worker tests: slow
    host decodes so close() interrupts a mid-stream pipeline."""

    def __init__(self, chunks=50, delay_s=0.01):
        self.dictionaries = {}
        self._i = 0
        self._n = chunks
        self._delay = delay_s

    def _host_next(self):
        time.sleep(self._delay)
        if self._i >= self._n:
            return None
        self._i += 1
        return ("chunk", self._i)

    def _to_device(self, payload):
        return payload

    def skip_chunks(self, n):
        return 0


def test_prefetch_close_joins_worker(session):
    from spark_tpu.io.sources import PrefetchChunkIterator
    it = PrefetchChunkIterator(_FakeChunkSource(), session.conf)
    assert next(it) == ("chunk", 1)
    assert next(it) == ("chunk", 2)
    t = it._thread
    assert t is not None and t.is_alive()
    it.close()
    assert not t.is_alive(), "close() must JOIN the worker"
    assert it._thread is None
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_close_before_start_and_exhaustion(session):
    from spark_tpu.io.sources import PrefetchChunkIterator
    it = PrefetchChunkIterator(_FakeChunkSource(chunks=2), session.conf)
    it.close()  # never started: no thread, no error
    it2 = PrefetchChunkIterator(_FakeChunkSource(chunks=2, delay_s=0.0),
                                session.conf)
    assert [x for x in it2] == [("chunk", 1), ("chunk", 2)]
    LockWatch().assert_no_thread_leak(timeout_s=5)


# ---------------------------------------------------------------------------
# the multithreaded stress test: static claims, dynamically proven
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stress_path(tmp_path_factory):
    from spark_tpu.tpch.datagen import write_parquet
    path = str(tmp_path_factory.mktemp("tpch_stress") / "sf")
    write_parquet(path, 0.002)
    return path


def test_service_stress_under_lockwatch(stress_path, tmp_path):
    """N sessions x M queries on the live service — chunked scans with
    prefetch workers, arbiter leasing, admission queueing, event-log
    writes, live /metrics scraping — under lockwatch: every query at
    golden parity, the OBSERVED lock acquisition order consistent with
    the static registry ranking, and no prefetch daemon outliving its
    query."""
    import urllib.request

    from spark_tpu import Conf
    from spark_tpu.observability.metrics import parse_prometheus_text
    from spark_tpu.service.arbiter import install_arbiter
    from spark_tpu.service.server import SqlService
    from spark_tpu.tpch import golden as G
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch import sql_queries as SQLQ

    sessions = ["s1", "s2", "s3"]
    conf = Conf()
    conf.set("spark_tpu.service.port", 0)
    conf.set("spark_tpu.service.maxConcurrent", 2)
    conf.set("spark_tpu.service.queueDepth", 8)
    conf.set("spark_tpu.service.queueTimeoutMs", 120000)
    conf.set("spark_tpu.service.hbmBudget", 1 << 30)  # arbiter live
    conf.set("spark_tpu.sql.execution.streamingChunkRows", 4096)
    conf.set("spark_tpu.sql.io.deviceCacheBytes", 0)  # re-stream scans
    conf.set("spark_tpu.sql.ingest.prefetch", True)
    conf.set("spark_tpu.sql.eventLog.dir", str(tmp_path / "events"))
    svc = SqlService(
        conf,
        init_session=lambda s: Q.register_tables(s, stress_path)).start()
    watch = LockWatch()
    try:
        # warm every session first (pool entries + compiled stages
        # exist), then install the watch over the warm topology
        for name in sessions:
            svc.submit(SQLQ.Q1, session=name)
        watch.install_service(svc)

        results, errors = [], []
        stop_scrape = threading.Event()

        def run_queries(name):
            try:
                for _ in range(2):
                    record, table = svc.submit(SQLQ.Q1, session=name)
                    results.append((record["id"], table))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((name, repr(e)))

        def scrape():
            while not stop_scrape.is_set():
                text = urllib.request.urlopen(
                    f"http://127.0.0.1:{svc.port}/metrics",
                    timeout=30).read().decode()
                parse_prometheus_text(text)
                urllib.request.urlopen(
                    f"http://127.0.0.1:{svc.port}/queries",
                    timeout=30).read()
                time.sleep(0.02)

        scraper = threading.Thread(target=scrape, daemon=True)
        scraper.start()
        threads = [threading.Thread(target=run_queries, args=(n,))
                   for n in sessions]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        stop_scrape.set()
        scraper.join(30)
        # a wedged worker must fail loudly, not pass vacuously
        assert not any(t.is_alive() for t in threads), "query wedged"
        assert errors == [], errors
        assert len(results) == 6

        # golden parity for every concurrent result
        want = G.GOLDEN["q1"](stress_path).reset_index(drop=True)
        for _, table in results:
            got = G.normalize_decimals(
                table.to_pandas())[list(want.columns)]
            G.compare(got.reset_index(drop=True), want)

        # the dynamic half of the tentpole: observed acquisition order
        # is consistent with the registry the static pass proved
        edges = watch.edges()
        assert edges, "no lock nesting observed — stress is vacuous"
        assert any(a == "service.session" for a, _ in edges), edges
        watch.assert_order_consistent()
        # prefetch must actually have run (chunked scans with the
        # double-buffered ingest on): otherwise the thread-leak claim
        # below is vacuous
        snap = svc.metrics.snapshot()["counters"]
        assert any(k.startswith("ingest_") for k in snap), snap
        # PrefetchChunkIterator.close()/exhaustion audit: no ingest
        # daemon outlives the queries that spawned it
        watch.assert_no_thread_leak()
        # contention actually happened (shared registry under 3
        # sessions + scraper) — the stats are live, not decorative
        report = watch.report()
        assert report["locks"], report
    finally:
        watch.uninstall()
        svc.stop()
        install_arbiter(None)

"""Test harness: force an 8-device virtual CPU mesh before jax loads.

The analog of the reference's `local-cluster[N,...]` multi-process test
mechanism (SURVEY.md section 4): sharding/collective code paths run on
8 virtual CPU devices so multi-chip logic is exercised in CI without TPU
hardware. Must run before any jax import.
"""

import os

# JAX_PLATFORMS alone is overridden by the axon TPU plugin in this image;
# the config update below is what actually pins the backend to CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def session():
    from spark_tpu import SparkTpuSession
    return SparkTpuSession.builder().get_or_create()

"""Test harness: force an 8-device virtual CPU mesh before jax loads.

The analog of the reference's `local-cluster[N,...]` multi-process test
mechanism (SURVEY.md section 4): sharding/collective code paths run on
8 virtual CPU devices so multi-chip logic is exercised in CI without TPU
hardware. Must run before any jax import.
"""

import os

# JAX_PLATFORMS alone is overridden by the axon TPU plugin in this image;
# the config update below is what actually pins the backend to CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

# The whole suite runs under FULL plan-change validation: every
# effective optimizer-rule application in every test is invariant- and
# determinism-checked (analysis/plan_integrity.py), so a bad rewrite
# fails loudly at its source instead of as a wrong result downstream.
# Registry DEFAULT (read by config.py at import, which is why this is
# set before spark_tpu loads), not a conf override — the per-test
# _session_conf_guard snapshot/restore leaves it alone, and a test
# that explicitly sets planChangeValidation still wins.
os.environ.setdefault("SPARK_TPU_PLAN_VALIDATION", "full")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 run "
        "(-m 'not slow')")


_FIXTURE_SESSIONS = []


@pytest.fixture(scope="session")
def session():
    from spark_tpu import SparkTpuSession
    s = SparkTpuSession.builder().get_or_create()
    _FIXTURE_SESSIONS.append(s)
    return s


@pytest.fixture(autouse=True)
def _session_conf_guard():
    """Snapshot and restore session conf overrides around EVERY test,
    so one test's mesh size / kernel mode / threshold mutation (or a
    failure before its own restore ran) can no longer cascade through
    the session-scoped fixture into 100+ downstream failures (round-5
    post-mortem). Guards BOTH the shared fixture session and whatever
    session is currently active — tests that spin up fresh sessions
    (e.g. warehouse round-trips) repoint SparkTpuSession._active, and
    guarding only _active would silently skip the one the tests use."""
    from spark_tpu.session import SparkTpuSession
    sessions = []
    if _FIXTURE_SESSIONS:
        sessions.append(_FIXTURE_SESSIONS[0])
    active = SparkTpuSession._active
    if active is not None and active not in sessions:
        sessions.append(active)
    snaps = [(s, dict(s.conf._settings)) for s in sessions]
    yield
    for s, snap in snaps:
        s.conf._settings.clear()
        s.conf._settings.update(snap)

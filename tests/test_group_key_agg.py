"""RewriteGroupKeyAggregates: sum/min/max/avg of the group key computed
post-aggregation (kernel limb-row reduction), with the alias-shadowing
regression from the round-4 review."""

import numpy as np
import pandas as pd

from spark_tpu import functions as F
from spark_tpu.functions import col


def test_group_key_agg_rewrite_parity(session):
    df = (session.range(10_000)
          .select(F.pmod(col("id"), 37).alias("k"))
          .group_by(col("k"))
          .agg(F.sum(col("k")).alias("s"), F.min(col("k")).alias("mn"),
               F.max(col("k")).alias("mx"), F.avg(col("k")).alias("a"),
               F.count().alias("c")))
    # rule engaged: the optimized plan aggregates only counts
    opt = df._qe().optimized_plan.tree_string()
    assert "__gk_cnt" in opt
    out = df.to_pandas().sort_values("k").reset_index(drop=True)
    pdf = pd.DataFrame({"k": np.arange(10_000) % 37})
    want = (pdf.groupby("k")["k"]
            .agg(["sum", "min", "max", "mean", "size"]).reset_index())
    assert out["s"].tolist() == want["sum"].tolist()
    assert out["mn"].tolist() == want["min"].tolist()
    assert out["mx"].tolist() == want["max"].tolist()
    assert np.allclose(out["a"], want["mean"])
    assert out["c"].tolist() == want["size"].tolist()


def test_group_key_agg_null_keys(session):
    t = pd.DataFrame({"k": pd.array([1, 1, None, 2], dtype="Int64")})
    o = (session.create_dataframe(t).group_by(col("k"))
         .agg(F.sum(col("k")).alias("s"), F.max(col("k")).alias("m"))
         .to_pandas().sort_values("k", na_position="first")
         .reset_index(drop=True))
    assert pd.isna(o["s"][0]) and pd.isna(o["m"][0])
    assert o["s"].tolist()[1:] == [2, 2]
    assert o["m"].tolist()[1:] == [1, 2]


def test_alias_shadowing_real_column_not_rewritten(session):
    """Round-4 review bug: group alias named like a REAL child column
    must not capture aggregates over that column."""
    pdf = pd.DataFrame({"a": np.array([1, 1, 2], dtype=np.int64),
                        "k": np.array([100, 100, 7], dtype=np.int64)})
    df = session.create_dataframe(pdf)
    out = (df.group_by(col("a").alias("k"))
           .agg(F.sum(col("k")).alias("s"), F.min(col("k")).alias("mn"))
           .to_pandas().sort_values("k").reset_index(drop=True))
    assert out["s"].tolist() == [200, 7]
    assert out["mn"].tolist() == [100, 7]


def test_group_key_agg_mesh_parity(session):
    mesh_key = "spark_tpu.sql.mesh.size"
    build = lambda: (session.range(5_000)
                     .select((col("id") % 11).alias("k"))
                     .group_by(col("k"))
                     .agg(F.sum(col("k")).alias("s"),
                          F.count().alias("c")))
    want = build().to_pandas().sort_values("k").reset_index(drop=True)
    try:
        session.conf.set(mesh_key, 8)
        got = build().to_pandas().sort_values("k").reset_index(drop=True)
    finally:
        session.conf.set(mesh_key, 0)
    assert got.equals(want)

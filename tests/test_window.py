"""Window function parity vs pandas (reference:
`execution/window/WindowExec.scala` semantics — Spark default RANGE
frame for ordered aggregates, peers included)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.window import Window

MESH_KEY = "spark_tpu.sql.mesh.size"


@pytest.fixture(scope="module")
def wdata(session):
    rs = np.random.RandomState(11)
    pdf = pd.DataFrame({
        "g": rs.randint(0, 6, 200).astype(np.int64),
        "o": rs.randint(0, 50, 200).astype(np.int64),  # has ties
        "v": rs.randint(-100, 100, 200).astype(np.int64),
    })
    session.register_table("wdata", pdf)
    return session, pdf


def test_row_number(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("rn", F.row_number().over(w))
           .to_pandas())
    want = (pdf.sort_values(["o", "v"]).groupby("g").cumcount() + 1)
    assert got["rn"].tolist() == want.sort_index().tolist()


def test_rank_dense_rank(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"))
    got = (session.table("wdata")
           .with_column("r", F.rank().over(w))
           .with_column("dr", F.dense_rank().over(w))
           .to_pandas())
    want_r = pdf.groupby("g")["o"].rank(method="min").astype(int)
    want_dr = pdf.groupby("g")["o"].rank(method="dense").astype(int)
    assert got["r"].tolist() == want_r.tolist()
    assert got["dr"].tolist() == want_dr.tolist()


def test_lag_lead(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("lg", F.lag(col("v")).over(w))
           .with_column("ld", F.lead(col("v"), 2).over(w))
           .to_pandas())
    s = pdf.sort_values(["g", "o", "v"], kind="stable")
    want_lg = s.groupby("g")["v"].shift(1).sort_index()
    want_ld = s.groupby("g")["v"].shift(-2).sort_index()
    assert np.array_equal(got["lg"].fillna(-9999).to_numpy(),
                          want_lg.fillna(-9999).to_numpy())
    assert np.array_equal(got["ld"].fillna(-9999).to_numpy(),
                          want_ld.fillna(-9999).to_numpy())


def test_sum_over_whole_partition(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g"))
    got = (session.table("wdata")
           .with_column("sv", F.sum(col("v")).over(w))
           .with_column("cv", F.count(col("v")).over(w))
           .with_column("mx", F.max(col("v")).over(w))
           .to_pandas())
    want = pdf.groupby("g")["v"]
    assert got["sv"].tolist() == want.transform("sum").tolist()
    assert got["cv"].tolist() == want.transform("count").tolist()
    assert got["mx"].tolist() == want.transform("max").tolist()


def test_running_sum_range_frame(wdata):
    """Spark default frame with ORDER BY: RANGE UNBOUNDED PRECEDING ..
    CURRENT ROW — peer rows (order-key ties) are included."""
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"))
    got = (session.table("wdata")
           .with_column("rs", F.sum(col("v")).over(w))
           .to_pandas())
    # pandas equivalent: group by (g, o) sums, cumsum within g, mapped
    # back to every row (ties share the value)
    per_o = pdf.groupby(["g", "o"])["v"].sum().groupby(level=0).cumsum()
    want = pdf.set_index(["g", "o"]).index.map(per_o)
    assert got["rs"].tolist() == list(want)


def test_global_window_no_partition(wdata):
    session, pdf = wdata
    w = Window.order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("rn", F.row_number().over(w))
           .to_pandas())
    want = (pdf.sort_values(["o", "v"], kind="stable")
            .reset_index().sort_values("index").index + 1)
    s = pdf.sort_values(["o", "v"], kind="stable")
    rn = pd.Series(np.arange(1, len(s) + 1), index=s.index).sort_index()
    assert got["rn"].tolist() == rn.tolist()


def test_window_distributed(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))

    def build():
        return (session.table("wdata")
                .with_column("rn", F.row_number().over(w))
                .with_column("sv", F.sum(col("v")).over(w)))

    session.conf.set(MESH_KEY, 0)
    want = build().to_pandas().sort_values(["g", "o", "v", "rn"]) \
        .reset_index(drop=True)
    session.conf.set(MESH_KEY, 8)
    try:
        got = build().to_pandas().sort_values(["g", "o", "v", "rn"]) \
            .reset_index(drop=True)
    finally:
        session.conf.set(MESH_KEY, 0)
    for c in want.columns:
        assert got[c].tolist() == want[c].tolist(), c


def test_sql_window_functions(wdata):
    session, pdf = wdata
    got = session.sql("""
        SELECT g, o, v,
               row_number() OVER (PARTITION BY g ORDER BY o, v) AS rn,
               sum(v) OVER (PARTITION BY g) AS sv,
               lag(v, 1) OVER (PARTITION BY g ORDER BY o, v) AS lg
        FROM wdata
    """).to_pandas()
    s = pdf.sort_values(["o", "v"]).groupby("g")
    want_rn = (s.cumcount() + 1).sort_index()
    assert got["rn"].tolist() == want_rn.tolist()
    assert got["sv"].tolist() == \
        pdf.groupby("g")["v"].transform("sum").tolist()
    s2 = pdf.sort_values(["g", "o", "v"], kind="stable")
    want_lg = s2.groupby("g")["v"].shift(1).sort_index()
    assert np.array_equal(got["lg"].fillna(-9).to_numpy(),
                          want_lg.fillna(-9).to_numpy())


def test_sql_rank_requires_over(wdata):
    session, _ = wdata
    from spark_tpu.sql.lexer import ParseError
    with pytest.raises(ParseError, match="OVER"):
        session.sql("SELECT rank() FROM wdata")

"""Window function parity vs pandas (reference:
`execution/window/WindowExec.scala` semantics — Spark default RANGE
frame for ordered aggregates, peers included)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.window import Window

MESH_KEY = "spark_tpu.sql.mesh.size"


@pytest.fixture(scope="module")
def wdata(session):
    rs = np.random.RandomState(11)
    pdf = pd.DataFrame({
        "g": rs.randint(0, 6, 200).astype(np.int64),
        "o": rs.randint(0, 50, 200).astype(np.int64),  # has ties
        "v": rs.randint(-100, 100, 200).astype(np.int64),
    })
    session.register_table("wdata", pdf)
    return session, pdf


def test_row_number(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("rn", F.row_number().over(w))
           .to_pandas())
    want = (pdf.sort_values(["o", "v"]).groupby("g").cumcount() + 1)
    assert got["rn"].tolist() == want.sort_index().tolist()


def test_rank_dense_rank(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"))
    got = (session.table("wdata")
           .with_column("r", F.rank().over(w))
           .with_column("dr", F.dense_rank().over(w))
           .to_pandas())
    want_r = pdf.groupby("g")["o"].rank(method="min").astype(int)
    want_dr = pdf.groupby("g")["o"].rank(method="dense").astype(int)
    assert got["r"].tolist() == want_r.tolist()
    assert got["dr"].tolist() == want_dr.tolist()


def test_lag_lead(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("lg", F.lag(col("v")).over(w))
           .with_column("ld", F.lead(col("v"), 2).over(w))
           .to_pandas())
    s = pdf.sort_values(["g", "o", "v"], kind="stable")
    want_lg = s.groupby("g")["v"].shift(1).sort_index()
    want_ld = s.groupby("g")["v"].shift(-2).sort_index()
    assert np.array_equal(got["lg"].fillna(-9999).to_numpy(),
                          want_lg.fillna(-9999).to_numpy())
    assert np.array_equal(got["ld"].fillna(-9999).to_numpy(),
                          want_ld.fillna(-9999).to_numpy())


def test_sum_over_whole_partition(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g"))
    got = (session.table("wdata")
           .with_column("sv", F.sum(col("v")).over(w))
           .with_column("cv", F.count(col("v")).over(w))
           .with_column("mx", F.max(col("v")).over(w))
           .to_pandas())
    want = pdf.groupby("g")["v"]
    assert got["sv"].tolist() == want.transform("sum").tolist()
    assert got["cv"].tolist() == want.transform("count").tolist()
    assert got["mx"].tolist() == want.transform("max").tolist()


def test_running_sum_range_frame(wdata):
    """Spark default frame with ORDER BY: RANGE UNBOUNDED PRECEDING ..
    CURRENT ROW — peer rows (order-key ties) are included."""
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"))
    got = (session.table("wdata")
           .with_column("rs", F.sum(col("v")).over(w))
           .to_pandas())
    # pandas equivalent: group by (g, o) sums, cumsum within g, mapped
    # back to every row (ties share the value)
    per_o = pdf.groupby(["g", "o"])["v"].sum().groupby(level=0).cumsum()
    want = pdf.set_index(["g", "o"]).index.map(per_o)
    assert got["rs"].tolist() == list(want)


def test_global_window_no_partition(wdata):
    session, pdf = wdata
    w = Window.order_by(col("o"), col("v"))
    got = (session.table("wdata")
           .with_column("rn", F.row_number().over(w))
           .to_pandas())
    want = (pdf.sort_values(["o", "v"], kind="stable")
            .reset_index().sort_values("index").index + 1)
    s = pdf.sort_values(["o", "v"], kind="stable")
    rn = pd.Series(np.arange(1, len(s) + 1), index=s.index).sort_index()
    assert got["rn"].tolist() == rn.tolist()


def test_window_distributed(wdata):
    session, pdf = wdata
    w = Window.partition_by(col("g")).order_by(col("o"), col("v"))

    def build():
        return (session.table("wdata")
                .with_column("rn", F.row_number().over(w))
                .with_column("sv", F.sum(col("v")).over(w)))

    session.conf.set(MESH_KEY, 0)
    want = build().to_pandas().sort_values(["g", "o", "v", "rn"]) \
        .reset_index(drop=True)
    session.conf.set(MESH_KEY, 8)
    try:
        got = build().to_pandas().sort_values(["g", "o", "v", "rn"]) \
            .reset_index(drop=True)
    finally:
        session.conf.set(MESH_KEY, 0)
    for c in want.columns:
        assert got[c].tolist() == want[c].tolist(), c


def test_sql_window_functions(wdata):
    session, pdf = wdata
    got = session.sql("""
        SELECT g, o, v,
               row_number() OVER (PARTITION BY g ORDER BY o, v) AS rn,
               sum(v) OVER (PARTITION BY g) AS sv,
               lag(v, 1) OVER (PARTITION BY g ORDER BY o, v) AS lg
        FROM wdata
    """).to_pandas()
    s = pdf.sort_values(["o", "v"]).groupby("g")
    want_rn = (s.cumcount() + 1).sort_index()
    assert got["rn"].tolist() == want_rn.tolist()
    assert got["sv"].tolist() == \
        pdf.groupby("g")["v"].transform("sum").tolist()
    s2 = pdf.sort_values(["g", "o", "v"], kind="stable")
    want_lg = s2.groupby("g")["v"].shift(1).sort_index()
    assert np.array_equal(got["lg"].fillna(-9).to_numpy(),
                          want_lg.fillna(-9).to_numpy())


def test_sql_rank_requires_over(wdata):
    session, _ = wdata
    from spark_tpu.sql.lexer import ParseError
    with pytest.raises(ParseError, match="OVER"):
        session.sql("SELECT rank() FROM wdata")


# -- ROWS/RANGE BETWEEN frames (reference: WindowExec.scala:36) -------------

def _frame_pdf():
    rs = np.random.RandomState(11)
    n = 500
    return pd.DataFrame({
        "g": rs.randint(0, 7, n).astype(np.int64),
        "t": rs.permutation(n).astype(np.int64),
        "v": rs.randn(n)})


def test_rows_between_sliding_parity_with_pandas(session):
    """sum/avg/min/max/count over ROWS BETWEEN 2 PRECEDING AND CURRENT
    ROW vs pandas rolling(3, min_periods=1) per partition."""
    from spark_tpu.window import Window
    pdf = _frame_pdf()
    session.register_table("wf_rows", pdf)
    w = (Window.partition_by(col("g")).order_by(col("t"))
         .rows_between(-2, 0))
    out = (session.table("wf_rows").select(
        col("g"), col("t"),
        F.sum(col("v")).over(w).alias("s"),
        F.avg(col("v")).over(w).alias("a"),
        F.min(col("v")).over(w).alias("mn"),
        F.max(col("v")).over(w).alias("mx"),
        F.count(col("v")).over(w).alias("c"),
    ).to_pandas().sort_values(["g", "t"]).reset_index(drop=True))
    want = pdf.sort_values(["g", "t"]).reset_index(drop=True)
    roll = want.groupby("g")["v"].rolling(3, min_periods=1)
    for name, series in (("s", roll.sum()), ("a", roll.mean()),
                         ("mn", roll.min()), ("mx", roll.max()),
                         ("c", roll.count())):
        got = out[name].to_numpy()
        exp = series.reset_index(level=0, drop=True).sort_index().to_numpy()
        # align: both frames sorted by (g, t)
        exp = (want.assign(x=series.reset_index(level=0, drop=True))
               .sort_values(["g", "t"])["x"].to_numpy())
        assert np.allclose(got.astype(float), exp), name


def test_rows_between_following_and_unbounded(session):
    from spark_tpu.window import Window
    pdf = pd.DataFrame({"g": [0, 0, 0, 1, 1],
                        "t": [1, 2, 3, 1, 2],
                        "v": [1.0, 2.0, 4.0, 8.0, 16.0]})
    session.register_table("wf_fol", pdf)
    w1 = Window.partition_by(col("g")).order_by(col("t")) \
        .rows_between(0, 1)       # current + next
    w2 = Window.partition_by(col("g")).order_by(col("t")) \
        .rows_between(0, Window.unboundedFollowing)  # running suffix
    out = (session.table("wf_fol").select(
        col("g"), col("t"),
        F.sum(col("v")).over(w1).alias("nxt"),
        F.sum(col("v")).over(w2).alias("suf"),
    ).to_pandas().sort_values(["g", "t"]).reset_index(drop=True))
    assert out["nxt"].tolist() == [3.0, 6.0, 4.0, 24.0, 16.0]
    assert out["suf"].tolist() == [7.0, 6.0, 4.0, 24.0, 16.0]


def test_range_between_value_offsets(session):
    """RANGE BETWEEN 10 PRECEDING AND CURRENT ROW: value-space frame
    incl. peers and gaps."""
    from spark_tpu.window import Window
    pdf = pd.DataFrame({
        "g": [0, 0, 0, 0, 0],
        "t": np.array([0, 5, 14, 15, 40], np.int64),
        "v": [1.0, 2.0, 4.0, 8.0, 16.0]})
    session.register_table("wf_range", pdf)
    w = Window.partition_by(col("g")).order_by(col("t")) \
        .range_between(-10, 0)
    out = (session.table("wf_range").select(
        col("t"), F.sum(col("v")).over(w).alias("s"))
        .to_pandas().sort_values("t").reset_index(drop=True))
    # frames: t=0 -> {0}; t=5 -> {0,5}; t=14 -> {5,14}; t=15 -> {5,14,15};
    # t=40 -> {40}
    assert out["s"].tolist() == [1.0, 3.0, 6.0, 14.0, 16.0]


def test_sql_window_frame_clause(session):
    pdf = pd.DataFrame({"g": [0, 0, 0, 1, 1],
                        "t": [1, 2, 3, 1, 2],
                        "v": [1.0, 2.0, 4.0, 8.0, 16.0]})
    session.register_table("wf_sql", pdf)
    out = session.sql(
        "SELECT g, t, sum(v) OVER (PARTITION BY g ORDER BY t "
        "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s "
        "FROM wf_sql ORDER BY g, t").to_pandas()
    assert out["s"].tolist() == [1.0, 3.0, 6.0, 8.0, 24.0]


def test_window_frames_on_mesh(session):
    """Sliding frames under the 8-shard mesh match single-chip."""
    from spark_tpu.window import Window
    pdf = _frame_pdf()
    session.register_table("wf_mesh", pdf)
    w = (Window.partition_by(col("g")).order_by(col("t"))
         .rows_between(-2, 0))
    build = lambda: (session.table("wf_mesh").select(
        col("g"), col("t"), F.sum(col("v")).over(w).alias("s"))
        .to_pandas().sort_values(["g", "t"]).reset_index(drop=True))
    want = build()
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        got = build()
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert np.allclose(got["s"], want["s"])


def test_computed_partition_key_stays_clustered(session):
    """A computed PARTITION BY key must hash-partition (projected key),
    not degrade to AllTuples (round-4 VERDICT weak #8)."""
    from spark_tpu.window import Window
    pdf = _frame_pdf()
    session.register_table("wf_ck", pdf)
    w = Window.partition_by((col("g") % 3).alias("gb")) \
        .order_by(col("t"))
    df = session.table("wf_ck").select(
        col("g"), col("t"), F.sum(col("v")).over(w).alias("s"))
    # plan-level: the WindowExec must NOT require AllTuples
    from spark_tpu.plan import physical as P
    qe = df._qe()

    def find_window(n):
        if isinstance(n, P.WindowExec):
            return n
        for c in n.children:
            f = find_window(c)
            if f is not None:
                return f
        return None

    wx = find_window(qe.executed_plan)
    assert wx is not None
    dists = wx.required_child_distributions()
    assert not isinstance(dists[0], P.AllTuples), dists
    # and parity between mesh and single-chip
    want = df.to_pandas().sort_values(["g", "t"]).reset_index(drop=True)
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        got = df.to_pandas().sort_values(["g", "t"]).reset_index(drop=True)
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert np.allclose(got["s"], want["s"])


def test_range_frame_with_filtered_rows_and_nulls(session):
    """Code-review r5: RANGE-frame binary search must survive dead
    (filtered) rows at the sorted tail and NULL order keys — both used
    to break the in-segment monotonicity the search assumes."""
    from spark_tpu.window import Window
    pdf = pd.DataFrame({
        "g": [0, 0, 0, 0, 0, 0],
        "t": np.array([1, 2, 5, 14, 15, 40], np.float64),
        "v": [100.0, 200.0, 1.0, 2.0, 4.0, 8.0]})
    pdf.loc[5, "t"] = np.nan  # NULL order key row (t=40 -> NULL)
    session.register_table("wf_dead", pdf)
    w = Window.partition_by(col("g")).order_by(col("t")) \
        .range_between(-10, 0)
    out = (session.table("wf_dead")
           .filter(col("v") < 50.0)  # drops t=1,2 -> dead sorted rows
           .select(col("t"), F.sum(col("v")).over(w).alias("s"))
           .to_pandas())
    by_t = {None if pd.isna(t) else t: s
            for t, s in zip(out["t"], out["s"])}
    # live rows: t=5 {5}; t=14 {5,14}; t=15 {5,14,15}; NULL -> its peer
    # group of NULL rows {8.0}
    assert by_t[5.0] == 1.0
    assert by_t[14.0] == 3.0
    assert by_t[15.0] == 7.0
    assert by_t[None] == 8.0


def test_frame_without_order_by_rejected(session):
    from spark_tpu.expr import AnalysisError
    from spark_tpu.window import Window
    pdf = pd.DataFrame({"g": [0, 0, 1], "v": [1.0, 2.0, 4.0]})
    session.register_table("wf_noord", pdf)
    w = Window.partition_by(col("g")).rows_between(-1, 0)
    with pytest.raises(AnalysisError):
        (session.table("wf_noord")
         .select(F.sum(col("v")).over(w).alias("s")).to_pandas())

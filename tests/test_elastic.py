"""Elastic-mesh recovery suite (spark_tpu/parallel/elastic.py): gang
restart from checkpoint, graceful decommission, and straggler chunk
rebalancing — the mitigation half of the ROADMAP elastic-mesh item.

The acceptance bar (ISSUE 11): with a mesh fault injected mid-stream,
the query completes ON THE MESH (not single-device), replays at most
`checkpoint.everyChunks` chunks (proven via `rec_chunks_replayed`),
and results are identical to the fault-free run; restart, decommission
and rebalance all observable in fault_summary and history."""

import warnings

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.observability import QueryListener
from spark_tpu.testing import faults
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
CACHE_KEY = "spark_tpu.sql.io.deviceCacheBytes"
MESH_KEY = "spark_tpu.sql.mesh.size"
CKPT_KEY = "spark_tpu.execution.checkpoint.everyChunks"
RESTART_KEY = "spark_tpu.execution.meshRestart.enabled"
RESTART_MAX_KEY = "spark_tpu.execution.meshRestart.maxRestarts"
DRAIN_KEY = "spark_tpu.execution.decommission.shards"
EXCLUDE_KEY = "spark_tpu.sql.mesh.excludeDevices"
SPANS_KEY = "spark_tpu.sql.observability.shardSpans"
REBALANCE_KEY = "spark_tpu.sql.straggler.rebalance.enabled"
MAX_SKEW_KEY = "spark_tpu.sql.straggler.rebalance.maxSkew"


@pytest.fixture(scope="session")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_elastic") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


@pytest.fixture(autouse=True)
def streaming_conf(tpch_session):
    """Chunked mesh streaming on every query; millisecond backoffs;
    disarmed plan. The conftest conf guard restores every key."""
    conf = tpch_session.conf
    conf.set("spark_tpu.execution.backoffMs", 1)
    conf.set(CHUNK_KEY, 1024)  # lineitem@SF0.002 ~ 12k rows -> ~12 chunks
    conf.set(CACHE_KEY, 0)
    faults.reset()
    yield conf
    faults.reset()


def _cold(session):
    from spark_tpu.io.device_cache import CACHE
    session._stage_cache.clear()
    session._aqe_caps.clear()
    CACHE.clear()


def _run_query(session, qname):
    qe = Q.QUERIES[qname](session)._qe()
    got = G.normalize_decimals(qe.collect().to_pandas()) \
        .reset_index(drop=True)
    return got, qe


def _check_golden(got, tpch_path, qname):
    G.compare(got, G.GOLDEN[qname](tpch_path))


def _replayed(session):
    return session.metrics.counter("rec_chunks_replayed").value


def _restarts(session):
    return session.metrics.counter("mesh_restart_attempts").value


def _mesh_stream_qe(session, n_rows=16000, name="elastic_t", mod=13):
    pdf = pd.DataFrame({"v": np.arange(n_rows, dtype=np.int64)})
    session.register_table(name, pdf)
    qe = (session.table(name)
          .group_by((col("v") % mod).alias("k"))
          .agg(F.sum(col("v")).alias("s")))._qe()
    return qe, pdf


def _groupsum_parity(got, pdf, mod=13):
    want = pdf.assign(k=pdf.v % mod).groupby("k")["v"].sum()
    res = got.set_index("k")["s"].sort_index()
    assert (res == want).all(), (res, want)


# -- gang restart ------------------------------------------------------------

def test_kill_one_host_converges_on_mesh(tpch_session, tpch_path,
                                         streaming_conf):
    """THE acceptance scenario: a host lost mid-stream (fatal at the
    2nd snapshot point) gang-restarts the mesh, resumes at the chunk-2
    checkpoint ON the mesh — never single-device — replays at most
    `checkpoint.everyChunks` chunks, and hits golden parity."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(CKPT_KEY, 2)
    before, restarts0 = _replayed(tpch_session), _restarts(tpch_session)
    with faults.inject(streaming_conf, "mesh_checkpoint:fatal:2") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log == [("mesh_checkpoint", 2, "fatal")]
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert "mesh_fallback" not in qe.fault_summary, qe.fault_summary
    assert qe.last_metrics.get("mesh_fallback") is None
    # the restart RESUMED: the replay is bounded by the checkpoint
    # cadence, never a restart-from-chunk-0
    assert 0 < _replayed(tpch_session) - before <= 2
    assert _restarts(tpch_session) - restarts0 == 1
    restore = next(ev for ev in qe.fault_events
                   if ev["action"] == "checkpoint_restore")
    assert restore["cursor"] == 2 and restore["driver"] == "mesh"
    assert restore["chunks_replayed"] <= 2
    _check_golden(got, tpch_path, "q1")


def test_gang_restart_without_checkpoint_restarts_stream(
        tpch_session, tpch_path, streaming_conf):
    """checkpoint disabled: the gang restart still keeps the query on
    the mesh — the stream just restarts from chunk 0."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(CKPT_KEY, 0)
    with faults.inject(streaming_conf, "mesh:fatal:1") as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert plan.fired_log == [("mesh", 1, "fatal")]
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert "mesh_fallback" not in qe.fault_summary
    assert "checkpoint_restore" not in qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_gang_restart_non_streamed_plan(tpch_session, tpch_path,
                                        streaming_conf):
    """Q3 (joins/exchanges — not a mesh-streamable aggregate): a
    compile-time mesh fault still restarts the gang instead of
    degrading, with parity."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    with faults.inject(streaming_conf, "mesh:fatal:1") as plan:
        got, qe = _run_query(tpch_session, "q3")
        assert plan.fired_log == [("mesh", 1, "fatal")]
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert "mesh_fallback" not in qe.fault_summary
    _check_golden(got, tpch_path, "q3")


def test_restart_budget_exhaustion_lands_single_device(
        tpch_session, tpch_path, streaming_conf):
    """`mesh_restart:fatal` kills the only restart attempt: the ladder
    must still land on the single-device rung (resuming from the
    checkpoint) and reach parity — restarts degrade gracefully, they
    never remove the final rung."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(CKPT_KEY, 2)
    streaming_conf.set(RESTART_MAX_KEY, 1)
    spec = "mesh_checkpoint:fatal:2,mesh_restart:fatal:1"
    with faults.inject(streaming_conf, spec) as plan:
        got, qe = _run_query(tpch_session, "q1")
        assert ("mesh_restart", 1, "fatal") in plan.fired_log
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert qe.fault_summary.get("mesh_fallback") == 1, qe.fault_summary
    assert qe.last_metrics.get("mesh_fallback") == 1
    # the failed attempt carries its error in the event record
    failed = next(ev for ev in qe.fault_events
                  if ev["action"] == "mesh_restart")
    assert failed.get("ok") is False and "INTERNAL" in failed["error"]
    # the single-device rung still restored from the checkpoint
    assert qe.fault_summary.get("checkpoint_restore") == 1
    _check_golden(got, tpch_path, "q1")


def test_restarts_disabled_preserves_fallback(tpch_session, tpch_path,
                                              streaming_conf):
    """meshRestart.enabled=false restores the PR-5 ladder: straight to
    single-device, no restart attempted."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(RESTART_KEY, False)
    with faults.inject(streaming_conf, "mesh:fatal:1"):
        got, qe = _run_query(tpch_session, "q1")
    assert "mesh_restart" not in qe.fault_summary, qe.fault_summary
    assert qe.fault_summary.get("mesh_fallback") == 1
    _check_golden(got, tpch_path, "q1")


def test_restart_runs_even_with_fallback_disabled(tpch_session,
                                                  tpch_path,
                                                  streaming_conf):
    """Each ladder rung has its own conf: meshFallback.enabled=false
    (mesh-or-fail — no degraded single-device mode) must NOT disable
    gang restarts; a transient mesh loss still heals on the mesh."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set("spark_tpu.execution.meshFallback.enabled", False)
    with faults.inject(streaming_conf, "mesh:fatal:1"):
        got, qe = _run_query(tpch_session, "q1")
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert "mesh_fallback" not in qe.fault_summary
    _check_golden(got, tpch_path, "q1")


def test_restart_skipped_when_pool_collapsed(tpch_session, tpch_path,
                                             streaming_conf,
                                             monkeypatch):
    """A healthy pool of <= 1 devices cannot host a gang: the restart
    rung is skipped (no budget burned, no doomed re-mesh) and the
    ladder goes straight to the single-device rung."""
    from spark_tpu.parallel import elastic as EL
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    monkeypatch.setattr(EL, "healthy_device_count", lambda conf: 1)
    with faults.inject(streaming_conf, "mesh:fatal:1"):
        got, qe = _run_query(tpch_session, "q1")
    assert "mesh_restart" not in qe.fault_summary, qe.fault_summary
    assert qe.fault_summary.get("mesh_fallback") == 1
    _check_golden(got, tpch_path, "q1")


def test_stale_decommission_request_discarded(tpch_session,
                                              streaming_conf):
    """A drain request with no position valid for the gang must be
    discarded at the next mesh query (with a warning), never left
    armed to fire on a future larger mesh."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    tpch_session.decommission_shards([9])  # 8-gang: position invalid
    with pytest.warns(UserWarning, match="stale decommission"):
        qe, pdf = _mesh_stream_qe(tpch_session, name="stale_t")
        b, _, _ = qe.execute_batch()
    assert "decommission" not in qe.fault_summary, qe.fault_summary
    assert streaming_conf.get(DRAIN_KEY) == ""  # consumed, not armed
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)


def test_decommission_requests_merge(tpch_session, streaming_conf):
    """Back-to-back drain requests merge — the second must not
    silently drop a still-pending first."""
    tpch_session.decommission_shards([1])
    tpch_session.decommission_shards([2])
    assert streaming_conf.get(DRAIN_KEY) == "1,2"
    streaming_conf.set(DRAIN_KEY, "")


def test_unparseable_decommission_request_discarded(tpch_session,
                                                    streaming_conf):
    """A spec with no parseable entry is discarded at the next mesh
    query (it could never fire, and left armed it would warn at every
    chunk boundary forever)."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(DRAIN_KEY, "x3")
    with pytest.warns(UserWarning, match="unparseable decommission"):
        qe, pdf = _mesh_stream_qe(tpch_session, name="unparse_t")
        b, _, _ = qe.execute_batch()
    assert streaming_conf.get(DRAIN_KEY) == ""
    assert "decommission" not in qe.fault_summary
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)


def test_exclusions_do_not_mask_misconfiguration(tpch_session,
                                                 streaming_conf):
    """An exclusion must not swallow the mesh.size-vs-devices setup
    diagnostic: a pool short even BEFORE exclusions still raises."""
    from spark_tpu.parallel.mesh import get_mesh
    streaming_conf.set(MESH_KEY, 64)  # more than the 8 virtual devices
    streaming_conf.set(EXCLUDE_KEY, "3")
    with pytest.raises(RuntimeError, match="devices visible"):
        get_mesh(streaming_conf)
    streaming_conf.set(EXCLUDE_KEY, "")


def test_mesh_fallback_not_sticky_across_executions(tpch_session,
                                                    streaming_conf):
    """Satellite regression: a fallback used to pin the QueryExecution
    single-device FOREVER (the _exec_conf overlay and _mesh_fallback
    flag survived execute_batch re-entry). A later execution of the
    same qe with a healed mesh must run on the mesh again."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(RESTART_KEY, False)
    qe, pdf = _mesh_stream_qe(tpch_session, name="sticky_t")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject(streaming_conf, "mesh:fatal:1") as plan:
            b, _, _ = qe.execute_batch()
            assert plan.fired_log, "mesh fault never fired — vacuous"
    assert qe.fault_summary.get("mesh_fallback") == 1
    assert qe.last_metrics.get("mesh_fallback") == 1
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)
    # the mesh healed (no faults): the SAME qe re-executes on the mesh
    b2, _, _ = qe.execute_batch()
    assert qe.fault_summary == {}, qe.fault_summary
    assert qe.last_metrics.get("mesh_fallback") is None, qe.last_metrics
    _groupsum_parity(b2.to_arrow().to_pandas(), pdf)


# -- graceful decommission ---------------------------------------------------

def test_decommission_drains_at_chunk_boundary(tpch_session,
                                               streaming_conf):
    """A drain requested mid-stream applies at the next chunk boundary:
    checkpoint forced at the cursor, `decommission` recorded, the
    shard's device excluded at session level, and the query continues
    on the 7-gang from the checkpoint — with parity."""
    _cold(tpch_session)
    conf = streaming_conf
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 2)
    conf.set(SPANS_KEY, "on")

    class Drainer(QueryListener):
        done = False

        def on_shard_records(self, e):
            if not self.done and e.chunk >= 1:
                self.done = True
                tpch_session.decommission_shards([3])

    drainer = Drainer()
    tpch_session.add_listener(drainer)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            qe, pdf = _mesh_stream_qe(tpch_session, name="drain_t")
            b, _, _ = qe.execute_batch()
    finally:
        tpch_session.remove_listener(drainer)
    assert drainer.done, "drain request never posted — vacuous"
    assert qe.fault_summary.get("decommission") == 1, qe.fault_summary
    # session-level exclusion persisted, one-shot request consumed,
    # and the plan-facing gang size follows the surviving pool
    assert conf.get(EXCLUDE_KEY) != ""
    assert conf.get(DRAIN_KEY) == ""
    assert int(conf.get(MESH_KEY)) == 7
    # the drain forced a checkpoint: the reduced gang RESUMED, and the
    # post-drain chunks ran on 7 shards
    assert qe.fault_summary.get("checkpoint_restore") == 1
    comp = [r for r in qe.spans.shard_records if r["phase"] == "compute"]
    shards_by_chunk = {}
    for r in comp:
        shards_by_chunk.setdefault(r["chunk"], set()).add(r["shard"])
    assert max(len(s) for s in shards_by_chunk.values()) == 8
    assert len(shards_by_chunk[max(shards_by_chunk)]) == 7
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)


def test_decommission_before_first_chunk(tpch_session, tpch_path,
                                         streaming_conf):
    """A drain requested before the stream starts applies at the FIRST
    boundary: no checkpoint to force (cursor 0), the whole stream runs
    on the reduced gang, golden parity holds."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    streaming_conf.set(SPANS_KEY, "on")
    tpch_session.decommission_shards([7])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got, qe = _run_query(tpch_session, "q1")
    assert qe.fault_summary.get("decommission") == 1, qe.fault_summary
    assert "checkpoint_restore" not in qe.fault_summary
    comp = [r for r in qe.spans.shard_records if r["phase"] == "compute"]
    assert comp and {r["shard"] for r in comp} == set(range(7))
    _check_golden(got, tpch_path, "q1")


def test_decommission_seam_fault_rides_mesh_ladder(tpch_session,
                                                   streaming_conf):
    """A fatal at the `decommission` seam (the drain machinery dying at
    its boundary) is a mesh failure: gang restart keeps the query on
    the mesh, and the drain applies at the restarted stream's first
    boundary."""
    _cold(tpch_session)
    streaming_conf.set(MESH_KEY, 8)
    tpch_session.decommission_shards([2])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject(streaming_conf, "decommission:fatal:1") as plan:
            qe, pdf = _mesh_stream_qe(tpch_session, name="drainfault_t")
            b, _, _ = qe.execute_batch()
            assert plan.fired_log == [("decommission", 1, "fatal")]
    assert qe.fault_summary.get("mesh_restart") == 1, qe.fault_summary
    assert qe.fault_summary.get("decommission") == 1, qe.fault_summary
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)


def test_pending_decommission_parsing(tpch_session, streaming_conf):
    from spark_tpu.parallel.elastic import pending_decommission
    from spark_tpu.parallel.mesh import get_mesh
    streaming_conf.set(MESH_KEY, 8)
    mesh = get_mesh(streaming_conf)
    streaming_conf.set(DRAIN_KEY, "")
    assert pending_decommission(streaming_conf, mesh) == ((), ())
    streaming_conf.set(DRAIN_KEY, "3,5")
    pos, ids = pending_decommission(streaming_conf, mesh)
    assert pos == (3, 5) and len(ids) == 2
    # positions outside the current gang are ignored (an
    # already-drained position must not re-fire forever)
    streaming_conf.set(DRAIN_KEY, "64")
    assert pending_decommission(streaming_conf, mesh) == ((), ())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        streaming_conf.set(DRAIN_KEY, "junk,2")
        pos, _ = pending_decommission(streaming_conf, mesh)
    assert pos == (2,)
    streaming_conf.set(DRAIN_KEY, "")


def test_get_mesh_exclusions_shrink(tpch_session, streaming_conf):
    """Exclusions shrink the gang to the surviving pool instead of
    raising; <= 1 survivor degrades to single-chip (None)."""
    import jax
    from spark_tpu.parallel.mesh import get_mesh
    streaming_conf.set(MESH_KEY, 8)
    ids = [int(d.id) for d in jax.devices()]
    streaming_conf.set(EXCLUDE_KEY, str(ids[0]))
    mesh = get_mesh(streaming_conf)
    assert int(mesh.devices.size) == 7
    assert ids[0] not in [int(d.id) for d in mesh.devices.flat]
    streaming_conf.set(EXCLUDE_KEY, ",".join(str(i) for i in ids[:7]))
    assert get_mesh(streaming_conf) is None
    streaming_conf.set(EXCLUDE_KEY, "")


# -- straggler rebalancing ---------------------------------------------------

def _slow_shard_rules(shard, chunks, n=8, ms=60):
    return ",".join(f"shard_chunk:slow:{c * n + shard + 1}:{ms}"
                    for c in range(chunks))


def test_rebalance_shifts_rows_off_flagged_shard(tpch_session,
                                                 streaming_conf,
                                                 tmp_path):
    """The detect->act loop: a chaos-slowed shard 5 gets flagged by the
    StragglerMonitor mid-stream and subsequent chunks assign it HALF
    its fair share (maxSkew 0.5) — proven via shard_summary() row
    deltas from the event log, with parity and the `shard_rebalance`
    action + `rebalance_rows` counter observable."""
    from spark_tpu import history
    _cold(tpch_session)
    conf = streaming_conf
    log_dir = str(tmp_path / "ev")
    conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    conf.set(MESH_KEY, 8)
    conf.set(SPANS_KEY, "on")
    conf.set("spark_tpu.sql.straggler.minChunks", 3)
    conf.set("spark_tpu.sql.straggler.factor", 4.0)
    rb0 = tpch_session.metrics.counter("rebalance_rows").value
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with faults.inject(conf, _slow_shard_rules(5, 6)) as plan:
                qe, pdf = _mesh_stream_qe(tpch_session, name="rebal_t")
                b, _, _ = qe.execute_batch()
        assert plan.fired_log, "shard_chunk seam never fired — vacuous"
    finally:
        conf.set("spark_tpu.sql.eventLog.dir", "")
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)
    assert qe.fault_summary.get("shard_rebalance") == 1, qe.fault_summary
    moved = tpch_session.metrics.counter("rebalance_rows").value - rb0
    assert moved > 0
    # shard_summary row deltas: 16000 rows / 1024-chunks / 8 shards =
    # 128 fair rows per full chunk; post-flag shard 5 holds <= 64
    shards = history.shard_summary(history.read_event_log(log_dir))
    mine = shards[(shards["query_id"] == qe.query_id)
                  & (shards["phase"] == "compute")]
    s5 = mine[mine["shard"] == 5].set_index("chunk")["rows"]
    assert s5.iloc[0] == 128  # even split before detection
    assert s5.min() <= 64, s5  # skewed away after the flag
    # the deficit moved ONTO healthy shards, not out of the query
    last_chunk = mine[mine["chunk"] == int(s5.index.max())]
    assert int(last_chunk["rows"].sum()) > 0
    assert int(mine["rows"].sum()) == len(pdf)


def test_rebalance_disabled_keeps_even_assignment(tpch_session,
                                                  streaming_conf):
    """rebalance.enabled=false: the straggler still flags (detection
    untouched) but assignment stays even and nothing is recorded."""
    _cold(tpch_session)
    conf = streaming_conf
    conf.set(MESH_KEY, 8)
    conf.set(SPANS_KEY, "on")
    conf.set(REBALANCE_KEY, False)
    conf.set("spark_tpu.sql.straggler.minChunks", 3)
    conf.set("spark_tpu.sql.straggler.factor", 4.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject(conf, _slow_shard_rules(5, 6)):
            qe, pdf = _mesh_stream_qe(tpch_session, name="rebal_off_t")
            b, _, _ = qe.execute_batch()
    assert "shard_rebalance" not in qe.fault_summary, qe.fault_summary
    comp = [r for r in qe.spans.shard_records
            if r["phase"] == "compute" and r["shard"] == 5]
    full = [r["rows"] for r in comp if r["chunk"] < 15]
    assert full and all(r == 128 for r in full), full
    _groupsum_parity(b.to_arrow().to_pandas(), pdf)


def test_rebalance_state_math():
    """Assignment invariants: targets sum to the live count, the slow
    shard's share drops by maxSkew, slot capacity bounds every target,
    and flagging is bounded/idempotent."""
    from spark_tpu.config import Conf
    from spark_tpu.parallel.elastic import RebalanceState
    conf = Conf()
    st = RebalanceState(8, conf)
    assert not st.active
    even = st.targets(1024)
    assert even.sum() == 1024 and set(even) == {128}
    st.flag(5)
    assert st.active and st.slow == {5}
    st.flag(5)  # idempotent
    assert st.slow == {5}
    t = st.targets(1024)
    assert t.sum() == 1024
    assert t[5] == 64  # (1 - 0.5) x fair
    s_cap = st.slot_capacity(1024)
    assert all(int(x) <= s_cap for x in t)
    # odd live counts still sum exactly (largest-remainder rounding)
    t2 = st.targets(1000)
    assert t2.sum() == 1000
    # can never flag the whole gang: someone must absorb the rows
    for s in range(8):
        st.flag(s)
    assert len(st.slow) == 7


def test_rebalance_weight_decay_returns_to_uniform():
    """decayChunks > 0: a flagged shard earns its share back linearly
    over that many rebalanced chunks — shares return to uniform, the
    state goes inert (zero-cost padding path again), slot capacity
    stays constant across the whole decay (stable jit shapes), and a
    re-flag mid-decay resets the penalty to full."""
    from spark_tpu.config import Conf
    from spark_tpu.parallel.elastic import RebalanceState
    conf = Conf()
    conf.set("spark_tpu.sql.straggler.rebalance.decayChunks", 4)
    st = RebalanceState(4, conf)
    st.flag(1)
    cap = st.slot_capacity(1024)
    t0 = st.targets(1024)
    assert t0[1] == 128  # (1 - 0.5) x fair at full penalty
    shares = [t0[1]]
    for _ in range(4):
        st.tick()
        if st.active:
            assert st.slot_capacity(1024) == cap  # shape-stable decay
            shares.append(int(st.targets(1024)[1]))
    # monotonically recovering, and fully recovered at the end
    assert shares == sorted(shares)
    assert not st.active
    even = st.targets(1024)
    assert set(even) == {256}  # uniform again
    # re-flag mid-decay resets to the full penalty
    st.flag(2)
    st.tick()
    st.flag(2)
    assert st.penalty[2] == 1.0
    # decayChunks = 0 keeps the legacy stay-flagged-forever behavior
    st0 = RebalanceState(4, Conf())
    st0.flag(1)
    for _ in range(10):
        st0.tick()
    assert st0.active and st0.slow == {1}


def test_rebalance_batch_preserves_rows():
    """pad_chunk_for_shards with an active state moves rows between
    shard segments but never loses or duplicates a live row."""
    import jax
    from spark_tpu.columnar import Batch
    from spark_tpu.config import Conf
    from spark_tpu.parallel.elastic import (RebalanceState,
                                            pad_chunk_for_shards)
    st = RebalanceState(4, Conf())
    st.flag(1)
    vals = np.arange(100, dtype=np.int64)
    b = Batch.from_numpy({"v": vals})
    out = pad_chunk_for_shards(b, 4, st)
    assert out.capacity % 4 == 0
    mask = np.asarray(jax.device_get(out.selection_mask()))
    data = np.asarray(jax.device_get(out.columns["v"].data))
    live = sorted(data[mask].tolist())
    assert live == vals.tolist()
    s_cap = out.capacity // 4
    seg1 = mask[1 * s_cap:2 * s_cap].sum()
    seg_others = [mask[i * s_cap:(i + 1) * s_cap].sum()
                  for i in (0, 2, 3)]
    assert seg1 < min(seg_others)


# -- observability -----------------------------------------------------------

def test_elastic_actions_reach_history(tpch_session, streaming_conf,
                                       tmp_path):
    """mesh_restart flows through fault_summary into the event log and
    history.fault_summary's action columns."""
    from spark_tpu import history
    _cold(tpch_session)
    conf = streaming_conf
    log_dir = str(tmp_path / "ev")
    conf.set("spark_tpu.sql.eventLog.dir", log_dir)
    conf.set(MESH_KEY, 8)
    conf.set(CKPT_KEY, 2)
    try:
        with faults.inject(conf, "mesh_checkpoint:fatal:2"):
            _run_query(tpch_session, "q1")
    finally:
        conf.set("spark_tpu.sql.eventLog.dir", "")
    summary = history.fault_summary(history.read_event_log(log_dir))
    assert len(summary) >= 1
    row = summary.iloc[-1]
    assert row["mesh_restart"] == 1
    assert row["mesh_fallback"] == 0
    assert row["checkpoint_restore"] == 1
    assert any(ev.get("action") == "mesh_restart" for ev in row["events"])

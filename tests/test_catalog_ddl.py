"""Catalog + DDL/DML: CREATE TABLE [AS SELECT], INSERT INTO, DROP,
SHOW TABLES, DESCRIBE — and the round-trip across fresh sessions over
the same warehouse dir (reference: SessionCatalog.scala:1,
command/tables.scala:1)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.expr import AnalysisError
from spark_tpu.functions import col

WH_KEY = "spark_tpu.sql.warehouse.dir"


@pytest.fixture
def wh_session(session, tmp_path):
    old = session.conf.get(WH_KEY)
    session.conf.set(WH_KEY, str(tmp_path / "wh"))
    yield session
    session.conf.set(WH_KEY, old)


def test_create_insert_select_roundtrip(wh_session):
    s = wh_session
    s.sql("CREATE TABLE ddl_t (k BIGINT, name STRING, price DECIMAL(10,2))")
    s.sql("INSERT INTO ddl_t VALUES (1, 'widget', 9.50), (2, 'gadget', 3.25)")
    s.sql("INSERT INTO ddl_t VALUES (3, NULL, -1.00)")
    out = s.sql("SELECT k, name, price FROM ddl_t ORDER BY k").to_pandas()
    assert out["k"].tolist() == [1, 2, 3]
    assert out["name"].tolist()[:2] == ["widget", "gadget"]
    assert pd.isna(out["name"][2])
    assert [float(x) for x in out["price"]] == [9.5, 3.25, -1.0]


def test_ctas_and_insert_select(wh_session):
    s = wh_session
    pdf = pd.DataFrame({"a": np.arange(10, dtype=np.int64),
                        "b": np.arange(10, dtype=np.float64) * 1.5})
    s.register_table("src_view", pdf)
    s.sql("CREATE TABLE ctas_t AS SELECT a, b * 2 AS b2 FROM src_view "
          "WHERE a >= 5")
    out = s.sql("SELECT * FROM ctas_t ORDER BY a").to_pandas()
    assert out["a"].tolist() == [5, 6, 7, 8, 9]
    assert np.allclose(out["b2"], [15.0, 18.0, 21.0, 24.0, 27.0])
    s.sql("INSERT INTO ctas_t SELECT a, b FROM src_view WHERE a < 2")
    n = s.sql("SELECT count(*) AS c FROM ctas_t").to_pandas()
    assert int(n["c"][0]) == 7


def test_show_describe_drop(wh_session):
    s = wh_session
    s.sql("CREATE TABLE show_t (x INT, y STRING)")
    s.register_table("tmp_v", pd.DataFrame({"z": [1]}))
    rows = s.sql("SHOW TABLES").to_pandas()
    by_name = dict(zip(rows["tableName"], rows["isTemporary"]))
    assert by_name["show_t"] == False  # noqa: E712
    assert by_name["tmp_v"] == True  # noqa: E712
    d = s.sql("DESCRIBE show_t").to_pandas()
    assert d["col_name"].tolist() == ["x", "y"]
    s.sql("DROP TABLE show_t")
    rows = s.sql("SHOW TABLES").to_pandas()
    assert "show_t" not in rows["tableName"].tolist()
    with pytest.raises(AnalysisError):
        s.sql("DROP TABLE show_t")
    s.sql("DROP TABLE IF EXISTS show_t")  # no raise


def test_create_errors_and_replace(wh_session):
    s = wh_session
    s.sql("CREATE TABLE err_t (x INT)")
    with pytest.raises(AnalysisError):
        s.sql("CREATE TABLE err_t (x INT)")
    s.sql("CREATE TABLE IF NOT EXISTS err_t (x INT)")  # no raise
    s.register_table("seed", pd.DataFrame({"x": np.array([7], np.int32)}))
    s.sql("CREATE OR REPLACE TABLE err_t AS SELECT x FROM seed")
    out = s.sql("SELECT * FROM err_t").to_pandas()
    assert out["x"].tolist() == [7]


def test_warehouse_survives_fresh_session(tmp_path):
    """The DDL round-trip bar: a brand-new session over the same
    warehouse dir sees tables a previous session created."""
    from spark_tpu.session import SparkTpuSession
    wh = str(tmp_path / "wh2")
    s1 = SparkTpuSession()
    s1.conf.set(WH_KEY, wh)
    s1.sql("CREATE TABLE persist_t (k BIGINT, v DOUBLE)")
    s1.sql("INSERT INTO persist_t VALUES (1, 1.5), (2, 2.5)")

    s2 = SparkTpuSession()
    s2.conf.set(WH_KEY, wh)
    out = s2.sql("SELECT k, v FROM persist_t ORDER BY k").to_pandas()
    assert out["k"].tolist() == [1, 2]
    assert out["v"].tolist() == [1.5, 2.5]
    assert "persist_t" in [r["name"] for r in s2.catalog.list_tables()]
    # restore the default active session for later tests
    SparkTpuSession._active = None


def test_insert_position_cast_and_arity_check(wh_session):
    s = wh_session
    s.sql("CREATE TABLE cast_t (k BIGINT, v DOUBLE)")
    s.sql("INSERT INTO cast_t VALUES (1, 2)")  # int -> double cast
    out = s.sql("SELECT * FROM cast_t").to_pandas()
    assert out["v"].tolist() == [2.0]
    with pytest.raises(AnalysisError):
        s.sql("INSERT INTO cast_t VALUES (1, 2, 3)")


def test_dataframe_api_over_persistent_table(wh_session):
    s = wh_session
    s.sql("CREATE TABLE api_t (k BIGINT, v DOUBLE)")
    s.sql("INSERT INTO api_t VALUES (1, 10.0), (2, 20.0), (3, 30.0)")
    out = (s.table("api_t").filter(col("k") > 1)
           .agg_sum("v") if hasattr(s.table("api_t"), "agg_sum") else
           s.table("api_t").filter(col("k") > 1).to_pandas())
    assert sorted(out["v"].tolist()) == [20.0, 30.0]

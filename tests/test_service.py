"""Concurrent multi-session SQL service suite (spark_tpu/service/).

Covers the acceptance surface: two sessions with conflicting conf
overlays running TPC-H Q1/Q3 concurrently with golden parity over ONE
shared arbiter pool and ONE compiled-stage cache; admission-queue
rejection at queueDepth with structured errors + listener-bus events;
arbiter lease exhaustion degrading through the spill/OOM machinery
instead of crashing; and the HTTP endpoints end to end."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from spark_tpu import Conf
from spark_tpu.observability.metrics import parse_prometheus_text
from spark_tpu.service.admission import (AdmissionController,
                                         AdmissionRejected,
                                         AdmissionTimeout)
from spark_tpu.service.arbiter import (DeviceResourceArbiter, ResultCache,
                                       get_arbiter, install_arbiter)
from spark_tpu.service.server import SqlService
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
HBM_KEY = "spark_tpu.service.hbmBudget"
PORT_KEY = "spark_tpu.service.port"
MAXC_KEY = "spark_tpu.service.maxConcurrent"
DEPTH_KEY = "spark_tpu.service.queueDepth"
QT_KEY = "spark_tpu.service.queueTimeoutMs"
CACHE_BYTES_KEY = "spark_tpu.sql.io.deviceCacheBytes"


@pytest.fixture(scope="module")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_service") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture()
def service(tpch_path):
    """A fresh service per test (ephemeral port, TPC-H tables on every
    pooled session), torn down with the arbiter uninstalled."""
    def make(**conf_overrides):
        conf = Conf()
        conf.set(PORT_KEY, 0)
        for k, v in conf_overrides.items():
            conf.set(k, v)
        svc = SqlService(
            conf, init_session=lambda s: Q.register_tables(s, tpch_path))
        made.append(svc)
        return svc

    made = []
    yield make
    for svc in made:
        svc.stop()
    install_arbiter(None)


def _golden(name, path):
    want = G.GOLDEN[name](path)
    return want.reset_index(drop=True)


def _check(name, got_df, path):
    want = _golden(name, path)
    got = G.normalize_decimals(got_df)[list(want.columns)]
    G.compare(got.reset_index(drop=True), want)


# ---------------------------------------------------------------------------
# Admission controller (unit)
# ---------------------------------------------------------------------------


def test_admission_rejects_at_queue_depth():
    ctl = AdmissionController(max_concurrent=1, queue_depth=1,
                              queue_timeout_ms=0)
    ctl.acquire("a")  # takes the only slot
    release_b = threading.Event()
    queued = threading.Event()
    got_slot = []

    def queued_query():
        queued.set()
        with ctl.slot("b"):
            got_slot.append("b")
            release_b.wait(5)

    t = threading.Thread(target=queued_query, daemon=True)
    t.start()
    queued.wait(5)
    for _ in range(100):  # wait until b is actually parked in the queue
        if ctl.stats()["queued"] == 1:
            break
        time.sleep(0.01)
    assert ctl.stats()["queued"] == 1
    # queue full: the third submission is rejected with the structured
    # error, not queued
    with pytest.raises(AdmissionRejected) as exc:
        ctl.acquire("c")
    err = exc.value.to_dict()
    assert err["error"] == "ADMISSION_REJECTED"
    assert err["queue_depth"] == 1 and err["max_concurrent"] == 1
    ctl.release()  # a frees -> b runs
    release_b.set()
    t.join(5)
    assert got_slot == ["b"]
    assert ctl.stats() == {"running": 0, "queued": 0,
                           "max_concurrent": 1, "queue_depth": 1}


def test_admission_queue_timeout():
    ctl = AdmissionController(max_concurrent=1, queue_depth=4,
                              queue_timeout_ms=30)
    ctl.acquire("a")
    with pytest.raises(AdmissionTimeout) as exc:
        ctl.acquire("b")
    assert exc.value.to_dict()["error"] == "ADMISSION_TIMEOUT"
    ctl.release()
    # slot free again: acquire succeeds immediately
    ctl.acquire("c")
    ctl.release()


# ---------------------------------------------------------------------------
# Arbiter (unit)
# ---------------------------------------------------------------------------


def test_arbiter_lease_grant_deny_release():
    arb = DeviceResourceArbiter(1000)
    from spark_tpu.service.arbiter import _Owner
    a, b = _Owner("a"), _Owner("b")
    assert arb.try_acquire(a, "scan1", 600)
    assert arb.try_acquire(a, "scan1", 600)  # idempotent per key
    assert arb.leased_bytes == 600
    assert not arb.try_acquire(b, "scan2", 600)  # pool exhausted
    # denial memoized: even after a releases, b's verdict is stable
    arb.release(a)
    assert not arb.try_acquire(b, "scan2", 600)
    arb.release(b)  # clears the denial memo
    assert arb.try_acquire(b, "scan2", 600)
    arb.release(b)
    assert arb.leased_bytes == 0


def test_arbiter_evicts_storage_under_lease_pressure(session):
    """UnifiedMemoryManager discipline: lease pressure evicts the
    device table cache (storage pool) before denying execution."""
    from spark_tpu.io.device_cache import CACHE
    from spark_tpu.service.arbiter import _Owner
    import numpy as np
    # park a real device batch in the cache so it has evictable bytes
    df = session.create_dataframe(
        {"x": np.arange(4096, dtype=np.int64)}, name="arb_evict_t")
    session.conf.set(CACHE_BYTES_KEY, 1 << 30)
    df.collect()
    # the create_dataframe scan is uncacheable (no load_chunks isn't
    # required; ArrowTableSource has a token) — ensure something cached
    if CACHE.nbytes == 0:
        pytest.skip("scan did not cache; nothing to evict")
    cached = CACHE.nbytes
    arb = DeviceResourceArbiter(cached + 100)
    owner = _Owner("q")
    # pool nearly full of storage: the lease only fits after eviction
    assert arb.try_acquire(owner, "s", cached + 50)
    assert CACHE.nbytes < cached  # storage was evicted
    arb.release(owner)


def test_result_cache_lru_bound():
    import pyarrow as pa
    rc = ResultCache(max_bytes=1)  # tiny: every insert evicts
    t = pa.table({"a": list(range(1000))})
    rc["fp1"] = t
    assert "fp1" not in rc and len(rc) == 0  # over-bound: rejected
    rc2 = ResultCache(max_bytes=t.nbytes * 2 + 100)
    rc2["fp1"] = t
    rc2["fp2"] = t
    assert "fp1" in rc2 and "fp2" in rc2
    rc2["fp3"] = t  # past the bound: LRU (fp1) evicted
    assert "fp1" not in rc2
    assert rc2.get("fp3") is t and rc2.pop("fp3") is t
    assert "fp3" not in rc2


# ---------------------------------------------------------------------------
# Concurrency: two sessions, conflicting overlays, golden parity
# ---------------------------------------------------------------------------


def test_concurrent_sessions_conflicting_conf_parity(service, tpch_path):
    """Two pooled sessions with conflicting overlays run Q1 and Q3
    concurrently, repeatedly, sharing ONE arbiter HBM pool, ONE stage
    cache and ONE metrics registry — both must hold golden parity and
    keep their own conf."""
    svc = service(**{HBM_KEY: 8 << 30})
    svc.start()  # installs the shared arbiter pool
    # conflicting overlays: a streams Q1 in small chunks, b stays
    # whole-input with a different estimatedGroups seed
    a_conf = {CHUNK_KEY: 2048,
              "spark_tpu.sql.caseSensitive": "false"}
    b_conf = {"spark_tpu.sql.aggregate.estimatedGroups": 1 << 10,
              "spark_tpu.sql.caseSensitive": "true"}
    errors = []
    results = {}

    def run(name, sql, sess, conf, rounds=3):
        try:
            for _ in range(rounds):
                record, table = svc.submit(sql, session=sess, conf=conf)
                assert record["status"] == "ok"
            results[name] = table.to_pandas()
        except Exception as e:  # noqa: BLE001 — surfaced via errors
            errors.append((name, e))

    t1 = threading.Thread(target=run,
                          args=("q1", SQLQ.Q1, "sess_a", a_conf))
    t3 = threading.Thread(target=run,
                          args=("q3", SQLQ.Q3, "sess_b", b_conf))
    t1.start(); t3.start()
    t1.join(300); t3.join(300)
    assert not errors, errors
    _check("q1", results["q1"], tpch_path)
    _check("q3", results["q3"], tpch_path)
    # overlays stayed per-session (no cross-stomp)
    sessions = svc.pool.sessions()
    assert int(sessions["sess_a"].conf.get(CHUNK_KEY)) == 2048
    assert int(sessions["sess_b"].conf.get(
        "spark_tpu.sql.aggregate.estimatedGroups")) == 1 << 10
    assert bool(sessions["sess_b"].conf.get(
        "spark_tpu.sql.caseSensitive")) is True
    assert bool(sessions["sess_a"].conf.get(
        "spark_tpu.sql.caseSensitive")) is False
    # both sessions share ONE compiled-stage cache object and drained
    # their leases from the ONE arbiter pool
    assert sessions["sess_a"]._stage_cache is sessions["sess_b"]._stage_cache
    assert get_arbiter() is svc.arbiter
    assert svc.arbiter.leased_bytes == 0


def test_shared_compile_cache_hit_across_sessions(service, tpch_path):
    """The second session's identical query hits the sessions-shared
    compiled-stage cache (the bucket-aligned stage keys from PR 4 make
    the keys identical across sessions over the same Parquet)."""
    svc = service()
    _, t_a = svc.submit(SQLQ.Q1, session="alpha")
    hits_before = svc.metrics.counter("compile_cache_hits").value
    _, t_b = svc.submit(SQLQ.Q1, session="beta")
    hits_after = svc.metrics.counter("compile_cache_hits").value
    assert hits_after > hits_before, (hits_before, hits_after)
    _check("q1", t_b.to_pandas(), tpch_path)
    # parity across sessions too
    _check("q1", t_a.to_pandas(), tpch_path)


def test_arbiter_lease_exhaustion_degrades_not_crashes(service,
                                                      tpch_path):
    """A starved shared pool routes queries down the spill/streaming
    paths (the UnifiedMemoryManager + OOM-ladder integration): parity
    holds, `arbiter_lease_denied` counts, nothing crashes, and the
    pool drains back to zero leases afterwards."""
    from spark_tpu.io.device_cache import CACHE
    CACHE.clear()  # cold: a warm cached scan is admitted as storage
    svc = service(**{HBM_KEY: 4096})  # 4KB: nothing fits resident
    svc.start()  # installs the arbiter
    assert get_arbiter() is svc.arbiter
    record, table = svc.submit(SQLQ.Q1, session="starved")
    assert record["status"] == "ok"
    _check("q1", table.to_pandas(), tpch_path)
    assert svc.metrics.counter("arbiter_lease_denied").value > 0
    assert svc.arbiter.leased_bytes == 0  # all leases released


def test_arbiter_large_pool_grants_and_releases(service, tpch_path):
    """With a roomy pool the same query stays resident: leases are
    granted and fully released at query end."""
    from spark_tpu.io.device_cache import CACHE
    CACHE.clear()  # cold: a warm cached scan is admitted without a lease
    svc = service(**{HBM_KEY: 8 << 30})
    svc.start()
    record, table = svc.submit(SQLQ.Q1, session="roomy")
    assert record["status"] == "ok"
    _check("q1", table.to_pandas(), tpch_path)
    assert svc.metrics.counter("arbiter_lease_granted").value > 0
    assert svc.arbiter.leased_bytes == 0


def test_arbiter_credits_warm_cached_scan(service, tpch_path):
    """A scan already resident in the device table cache is admitted
    as STORAGE (headroom already subtracts its bytes): re-leasing it
    would double-count and evict the very table the query reuses."""
    from spark_tpu.io.device_cache import CACHE
    CACHE.clear()
    svc = service(**{HBM_KEY: 64 << 20})
    svc.start()
    svc.submit(SQLQ.Q1, session="warm")  # cold: leases + fills cache
    assert CACHE.nbytes > 0
    denied0 = svc.metrics.counter("arbiter_lease_denied").value
    hits0 = CACHE.hits
    record, table = svc.submit(SQLQ.Q1, session="warm")
    assert record["status"] == "ok"
    assert CACHE.hits > hits0  # served from the warm cache...
    # ...with no lease denial (and so no self-eviction re-ingest)
    assert svc.metrics.counter("arbiter_lease_denied").value == denied0
    _check("q1", table.to_pandas(), tpch_path)


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


def _post_sql(port, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def _get_json(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.status, json.load(resp)


def test_http_sql_roundtrip_parity_and_status(service, tpch_path):
    import pandas as pd
    svc = service().start()
    port = svc.port
    status, resp = _post_sql(port, {"sql": SQLQ.Q1})
    assert status == 200 and resp["status"] == "ok"
    got = pd.DataFrame(resp["rows"], columns=resp["columns"])
    _check("q1", got, tpch_path)
    # status record from the listener bus
    status, rec = _get_json(port, f"/queries/{resp['query_id']}")
    assert status == 200 and rec["status"] == "ok"
    assert rec["engine_query_id"] >= 1
    assert rec["phase_times_s"]  # on_query_end fed the record
    assert any(e["action"] == "admitted" for e in rec["events"])
    # metrics exposition parses and shows the service counters
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as m:
        text = m.read().decode()
    parsed = parse_prometheus_text(text)
    assert parsed["spark_tpu_service_queries_submitted"] >= 1
    assert parsed["spark_tpu_queries_total"] >= 1
    # health
    status, h = _get_json(port, "/healthz")
    assert status == 200 and h["status"] == "ok" and h["sessions"] >= 1


def test_http_arrow_format(service, tpch_path):
    import pyarrow as pa
    svc = service().start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/sql",
        data=json.dumps({"sql": SQLQ.Q1, "format": "arrow"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"] == \
            "application/vnd.apache.arrow.stream"
        qid = resp.headers["X-Query-Id"]
        table = pa.ipc.open_stream(resp.read()).read_all()
    assert qid.startswith("q-")
    _check("q1", table.to_pandas(), tpch_path)


def test_http_bad_request_and_sql_error(service):
    svc = service().start()
    port = svc.port
    # malformed body
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql", data=b"not json",
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=30)
    assert exc.value.code == 400
    # user errors (parse/analysis) surface structured as 400, not 500
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post_sql(port, {"sql": "select nope from missing_table"})
    assert exc.value.code == 400
    body = json.load(exc.value)
    assert body["error"] == "INVALID_SQL"
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post_sql(port, {"sql": "SELEKT 1"})
    assert exc.value.code == 400
    assert json.load(exc.value)["error"] == "INVALID_SQL"
    # 404s
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get_json(port, "/queries/q-99999")
    assert exc.value.code == 404


def test_http_admission_rejection_structured(service, tpch_path):
    """maxConcurrent=1, queueDepth=0: while one slow query holds the
    slot, a second HTTP submission gets a structured 429 + a rejected
    ServiceEvent on the bus + the counter at /metrics."""
    svc = service(**{MAXC_KEY: 1, DEPTH_KEY: 0, QT_KEY: 100}).start()
    port = svc.port
    events = []

    from spark_tpu.observability import QueryListener

    class Sub(QueryListener):
        def on_service(self, event):
            events.append((event.action, event.query_id))

    svc.bus.register(Sub())
    # hold the only slot directly via the admission controller (a
    # deterministic stand-in for a long-running query)
    svc.admission.acquire("holder")
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_sql(port, {"sql": SQLQ.Q1})
        assert exc.value.code == 429
        body = json.load(exc.value)
        assert body["error"] == "ADMISSION_REJECTED"
        assert body["queue_depth"] == 0
        assert body["query_id"].startswith("q-")
    finally:
        svc.admission.release()
    assert ("rejected", body["query_id"]) in events
    _, parsed = None, parse_prometheus_text(svc.metrics_text())
    assert parsed["spark_tpu_service_rejected"] >= 1
    # the rejected record is poll-visible with the structured error
    _, rec = _get_json(port, f"/queries/{body['query_id']}")
    assert rec["status"] == "rejected"
    assert rec["error"]["error"] == "ADMISSION_REJECTED"
    # and the service still works once the slot frees
    status, resp = _post_sql(port, {"sql": SQLQ.Q1})
    assert status == 200 and resp["status"] == "ok"


def test_http_query_listing_timeline_and_plan(service, tpch_path):
    """The live history API: GET /queries lists a completed Q1,
    /queries/<id>/timeline serves phase spans + stage peak-HBM +
    per-shard rows as JSON, /queries/<id>/plan serves the runtime
    tree — no JSONL scraping."""
    svc = service(**{"spark_tpu.sql.observability.xlaCost": "on"})
    svc.start()
    port = svc.port
    _, resp = _post_sql(port, {"sql": SQLQ.Q1})
    qid = resp["query_id"]
    _post_sql(port, {"sql": "select count(*) as n from lineitem"})
    status, listing = _get_json(port, "/queries")
    assert status == 200 and listing["total"] >= 2
    assert listing["queries"][0]["submitted_ts"] >= \
        listing["queries"][-1]["submitted_ts"]  # newest first
    assert any(q["id"] == qid and q["status"] == "ok"
               for q in listing["queries"])
    # pagination: limit=1 pages with next_offset
    _, page = _get_json(port, "/queries?limit=1")
    assert len(page["queries"]) == 1 and page["next_offset"] == 1
    _, page2 = _get_json(port, "/queries?limit=1&offset=1")
    assert page2["queries"][0]["id"] != page["queries"][0]["id"]
    # filters
    _, only_ok = _get_json(port, "/queries?status=ok&session=default")
    assert only_ok["total"] >= 2
    # timeline: spans + stage HBM + shards list (empty on single chip)
    _, tl = _get_json(port, f"/queries/{qid}/timeline")
    assert tl["engine_query_id"] >= 1
    assert any(s["name"] == "dispatch" for s in tl["spans"]), tl["spans"]
    assert any(s.get("peak_hbm_bytes") for s in tl["stages"]), tl
    assert isinstance(tl["shards"], list)
    assert tl["phase_times_s"].get("execution") is not None
    # plan: runtime-annotated physical tree + the submitted SQL
    _, pl = _get_json(port, f"/queries/{qid}/plan")
    assert "HashAggregateExec" in pl["physical"], pl
    assert "rows out" in pl["physical"]  # runtime annotations present
    assert pl["sql"].lstrip().lower().startswith("select")
    # unknown ids 404 on both detail endpoints
    for suffix in ("timeline", "plan"):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get_json(port, f"/queries/q-99999/{suffix}")
        assert exc.value.code == 404


def test_history_store_bounded(service):
    from spark_tpu.service.query_history import QueryHistoryStore
    store = QueryHistoryStore(max_entries=2)
    for i in range(4):
        store.put(f"q-{i}", {"engine_query_id": i})
    assert len(store) == 2
    assert store.get("q-0") is None and store.get("q-3") is not None


def test_concurrent_queries_scrape_and_rotation(service, tpch_path,
                                                tmp_path):
    """Satellite: pooled sessions running parallel queries while
    /metrics is scraped and the event log rotates (tiny maxBytes) —
    the Prometheus text must stay parseable on every scrape and the
    rotated event log must replay with zero corrupt lines."""
    from spark_tpu.service.query_history import QueryHistoryStore  # noqa: F401
    ev_dir = str(tmp_path / "ev")
    svc = service(**{
        "spark_tpu.sql.eventLog.dir": ev_dir,
        "spark_tpu.sql.eventLog.maxBytes": 512,
        "spark_tpu.sql.metrics.sink": "prometheus",
        "spark_tpu.sql.metrics.dir": str(tmp_path / "m"),
    }).start()
    port = svc.port
    n_sessions, n_rounds = 3, 3
    errors = []
    done = threading.Event()

    def run(sess):
        try:
            for _ in range(n_rounds):
                record, _ = svc.submit(
                    "select count(*) as n from lineitem", session=sess)
                assert record["status"] == "ok"
        except Exception as e:  # noqa: BLE001 — surfaced via errors
            errors.append((sess, e))

    def scrape():
        try:
            while not done.is_set():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=30) as m:
                    parsed = parse_prometheus_text(m.read().decode())
                assert isinstance(parsed, dict)
                time.sleep(0.01)
        except Exception as e:  # noqa: BLE001
            errors.append(("scrape", e))

    threads = [threading.Thread(target=run, args=(f"s{i}",))
               for i in range(n_sessions)]
    scraper = threading.Thread(target=scrape)
    scraper.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    done.set()
    scraper.join(60)
    assert not errors, errors
    # rotated log replays completely: one parseable line per query,
    # schema-valid throughout (read_event_log raises on corrupt JSON)
    import os as _os
    from spark_tpu import history as H
    files = _os.listdir(ev_dir)
    assert len(files) > n_sessions, files  # rotation actually rolled
    events = H.read_event_log(ev_dir)
    assert len(events) == n_sessions * n_rounds
    assert (events["status"] == "ok").all()
    assert (events["schema_version"] == 7).all()
    # the versioned-schema validator agrees line by line
    import importlib.util
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "events_tool", _os.path.join(root, "scripts", "events_tool.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    assert tool.validate([ev_dir]) == []


def test_http_async_submission(service):
    svc = service().start()
    status, resp = _post_sql(svc.port, {"sql": SQLQ.Q1, "mode": "async"})
    assert status == 202
    qid = resp["query_id"]
    for _ in range(600):
        _, rec = _get_json(svc.port, f"/queries/{qid}")
        if rec["status"] in ("ok", "error"):
            break
        time.sleep(0.1)
    assert rec["status"] == "ok" and rec["row_count"] >= 1


def test_session_pool_bound(service):
    from spark_tpu.service.pool import PoolExhausted
    svc = service(**{"spark_tpu.service.maxSessions": 1})
    svc.submit("select l_orderkey from lineitem limit 1", session="only")
    with pytest.raises(PoolExhausted):
        svc.submit("select l_orderkey from lineitem limit 1",
                   session="another")


def test_active_session_contextvar_isolated(service):
    """Pooled sessions never clobber the process-global active session
    (the builder singleton other code in the process relies on)."""
    from spark_tpu import SparkTpuSession
    before = SparkTpuSession._active
    svc = service()
    svc.submit("select count(*) as n from lineitem")
    assert SparkTpuSession._active is before


def test_session_busy_sheds_with_structured_timeout(service):
    """A second request for a session already running a query must not
    burn an execution slot waiting — it sheds with a structured 503
    after queueTimeoutMs while OTHER sessions keep executing."""
    svc = service(**{QT_KEY: 150})
    entry = svc.pool.get_or_create("busy")
    entry.lock.acquire()  # stand-in for a long-running query
    try:
        with pytest.raises(AdmissionTimeout) as exc:
            svc.submit("select count(*) as n from lineitem",
                       session="busy")
        assert exc.value.to_dict()["session"] == "busy"
        # an idle session is unaffected (no slot was consumed)
        record, _ = svc.submit("select count(*) as n from lineitem",
                               session="idle")
        assert record["status"] == "ok"
    finally:
        entry.lock.release()


def test_async_submissions_bounded(service):
    """An async burst past maxConcurrent + queueDepth rejects at the
    front door (429-shaped) instead of spawning unbounded threads."""
    svc = service(**{MAXC_KEY: 1, DEPTH_KEY: 0, QT_KEY: 100})
    svc.admission.acquire("holder")  # pin the only slot
    # park the first worker at the session lease too: with warm caches
    # session init is fast enough that the worker could reach the
    # queue_depth=0 admission rejection (freeing its in-flight slot)
    # before the second submission's bound check runs
    entry = svc.pool.get_or_create("default")
    entry.lock.acquire()
    try:
        first = svc.submit_async(
            "select count(*) as n from lineitem")  # occupies the bound
        with pytest.raises(AdmissionRejected) as exc:
            svc.submit_async("select count(*) as n from lineitem")
        body = exc.value.to_dict()
        assert body["error"] == "ADMISSION_REJECTED"
        assert body["bound"] == 1
    finally:
        # slot first, lease second: the worker waking from the lease
        # must find the slot free (queue_depth=0 would otherwise
        # reject it in the gap between the two releases)
        svc.admission.release()
        entry.lock.release()
    for _ in range(200):
        if first["status"] in ("ok", "error", "queue_timeout"):
            break
        time.sleep(0.05)
    assert first["status"] in ("ok", "queue_timeout")


def test_pinned_cache_entries_survive_lease_pressure():
    """evict_bytes skips entries pinned by running queries: their HBM
    would not actually be freed (the query's reference keeps it live),
    so crediting their bytes would overcommit the pool."""
    from spark_tpu.io.device_cache import DeviceTableCache

    class _B:  # minimal Batch stand-in for batch_nbytes
        def __init__(self, n):
            import numpy as np

            class _C:
                def __init__(self):
                    self.data = np.zeros(n, dtype="u1")
                    self.validity = None
            self.columns = {"c": _C()}
            self.selection = None

    cache = DeviceTableCache()
    cache.put(("pinned",), _B(1000), budget=1 << 20)
    cache.put(("loose",), _B(500), budget=1 << 20)
    assert cache.pin(("pinned",))
    freed = cache.evict_bytes(10_000)
    assert freed == 500  # only the unpinned entry went
    assert cache.contains(("pinned",))
    cache.unpin(("pinned",))
    assert cache.evict_bytes(10_000) == 1000  # now reclaimable
    assert not cache.pin(("missing",))  # absent key: caller leases


def _stand_in_batch(n):
    """Minimal Batch stand-in for batch_nbytes."""
    import numpy as np

    class _C:
        def __init__(self):
            self.data = np.zeros(n, dtype="u1")
            self.validity = None

    class _B:
        def __init__(self):
            self.columns = {"c": _C()}
            self.selection = None
    return _B()


def test_put_eviction_skips_pinned_entries():
    """put's budget eviction must honor pins like evict_bytes does:
    evicting an entry a running query was admitted against frees no
    HBM (its reference stays live) while zeroing the storage bytes it
    is accounted under — phantom headroom for the next admission."""
    from spark_tpu.io.device_cache import DeviceTableCache
    cache = DeviceTableCache()
    cache.put(("pinned",), _stand_in_batch(1000), budget=2000)
    assert cache.pin(("pinned",))
    cache.put(("loose",), _stand_in_batch(800), budget=2000)
    # over budget: the pinned entry is older (LRU victim) but must
    # survive; the loose one goes instead
    cache.put(("new",), _stand_in_batch(900), budget=2000)
    assert cache.contains(("pinned",))
    assert not cache.contains(("loose",))
    assert cache.contains(("new",))
    # everything else pinned: the just-inserted entry itself survives
    assert cache.pin(("new",))
    cache.put(("last",), _stand_in_batch(1000), budget=2000)
    assert cache.contains(("last",)) and cache.contains(("new",))
    cache.unpin(("pinned",))
    cache.unpin(("new",))
    cache.evict_bytes(1 << 30)


def test_lease_kept_when_cache_put_rejected():
    """convert_lease_to_pin must NOT drop the lease when the entry
    never landed in the device cache (put rejected it): the batch is
    live on device but absent from CACHE.nbytes, so dropping the lease
    would credit phantom headroom."""
    from spark_tpu.io.device_cache import CACHE
    from spark_tpu.service.arbiter import _Owner
    arb = DeviceResourceArbiter(10_000)
    owner = _Owner("q")
    key = ("svc-test-lease-kept",)
    assert arb.try_acquire(owner, key, 4000)
    # key is NOT in the cache: pin fails, lease must be retained
    arb.convert_lease_to_pin(owner, key)
    assert arb.leased_bytes == 4000
    # once the entry genuinely lands in storage, conversion proceeds
    CACHE.put(key, _stand_in_batch(100), budget=1 << 20)
    try:
        arb.convert_lease_to_pin(owner, key)
        assert arb.leased_bytes == 0
    finally:
        arb.release(owner)  # unpins
        CACHE.evict_bytes(200)


def test_prefer_resident_takes_no_lease_for_streaming_scan():
    """_prefer_resident runs its cheap disqualifiers BEFORE consulting
    the arbiter: a scan that will stream anyway (uncacheable source)
    must not hold an est-sized lease from the shared pool to query
    end."""
    from spark_tpu import types as T
    from spark_tpu.execution.streaming_agg import _prefer_resident
    from spark_tpu.service import arbiter as A

    class _Src:
        def cache_token(self):
            return None  # uncacheable: the scan streams

        def estimated_rows(self):
            return 1_000_000

    class _Field:
        dtype = T.IntegerType()
        nullable = False

    class _Schema:
        fields = [_Field()]

    class _Leaf:
        source = _Src()
        required_columns = None
        pushed_filters = ()

        def schema(self):
            return _Schema()

    arb = DeviceResourceArbiter(1 << 30)
    install_arbiter(arb)
    try:
        conf = Conf()
        conf.set(CACHE_BYTES_KEY, 1 << 30)
        token = A.enter_query("stream-test")
        try:
            assert _prefer_resident(_Leaf(), conf) is False
            assert arb.leased_bytes == 0  # no est-sized lease parked
        finally:
            A.exit_query(token)
    finally:
        install_arbiter(None)


def test_standalone_session_result_cache_unbounded(session):
    """Standalone sessions keep the pre-service unbounded result cache
    unless resultCacheBytes is explicitly set — a cache()-marked table
    larger than a default bound must not silently recompute."""
    from spark_tpu.service.arbiter import RESULT_CACHE_BYTES_KEY
    from spark_tpu.session import SparkTpuSession
    assert session._data_cache.max_bytes == 0
    conf = Conf()
    conf.set(RESULT_CACHE_BYTES_KEY, 1234)
    bounded = SparkTpuSession(conf=conf, register_active=False)
    assert bounded._data_cache.max_bytes == 1234

"""Observability layer: listener bus ordering (incl. under faults),
span/Chrome-trace validity, XLA cost accounting, metrics sinks,
event-log hardening + rotation, history replay views, and golden
parity with every observability conf enabled."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu import history
from spark_tpu.functions import col
from spark_tpu.observability import QueryListener
from spark_tpu.observability.metrics import parse_prometheus
from spark_tpu.observability.sinks import json_default
from spark_tpu.testing import faults
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

EVENT_KEY = "spark_tpu.sql.eventLog.dir"
TRACE_KEY = "spark_tpu.sql.trace.dir"
SINK_KEY = "spark_tpu.sql.metrics.sink"
MDIR_KEY = "spark_tpu.sql.metrics.dir"
MAXB_KEY = "spark_tpu.sql.eventLog.maxBytes"
COST_KEY = "spark_tpu.sql.observability.xlaCost"


class Recorder(QueryListener):
    """Collects (callback, event) tuples for ordering assertions."""

    def __init__(self):
        self.calls = []

    def on_query_start(self, e):
        self.calls.append(("start", e))

    def on_stage_compiled(self, e):
        self.calls.append(("compiled", e))

    def on_stage_completed(self, e):
        self.calls.append(("completed", e))

    def on_fault(self, e):
        self.calls.append(("fault", e))

    def on_query_end(self, e):
        self.calls.append(("end", e))

    def names(self):
        return [c[0] for c in self.calls]


def _fresh_agg(session, n=777):
    """A plan unlikely to be stage-cached already (n varies per test)."""
    return (session.range(n)
            .group_by((col("id") % 5).alias("k"))
            .agg(F.sum(col("id")).alias("s")))


# -- listener bus ------------------------------------------------------------

def test_listener_callback_ordering(session):
    rec = Recorder()
    session.add_listener(rec)
    try:
        _fresh_agg(session, 771).to_pandas()
    finally:
        session.remove_listener(rec)
    names = rec.names()
    assert names[0] == "start" and names[-1] == "end"
    assert "completed" in names
    if "compiled" in names:  # cold stage cache: compile precedes run
        assert names.index("compiled") < names.index("completed")
    end = rec.calls[-1][1]
    assert end.status == "ok"
    assert end.query_id == rec.calls[0][1].query_id
    assert end.event["metrics"], end.event


def test_listener_ordering_under_faults(session):
    session.conf.set("spark_tpu.execution.backoffMs", 1)
    rec = Recorder()
    session.add_listener(rec)
    try:
        with faults.inject(session.conf, "stage_run:unavailable:1"):
            got = _fresh_agg(session, 772).to_pandas()
    finally:
        session.remove_listener(rec)
    assert got["s"].sum() == sum(range(772))
    names = rec.names()
    # retry: fault posted between start and end, completion still last
    assert "fault" in names
    assert rec.calls[names.index("fault")][1].action == "transient_retry"
    assert names.index("fault") < names.index("end")
    assert names[-1] == "end" and rec.calls[-1][1].status == "ok"
    # the transient retry dropped the compiled entry: a second compile
    # event lands AFTER the fault
    compiles = [i for i, n in enumerate(names) if n == "compiled"]
    assert compiles and compiles[-1] > names.index("fault")


def test_listener_failure_isolated(session):
    class Bad(QueryListener):
        def on_query_end(self, e):
            raise RuntimeError("listener bug")

    bad = Bad()
    session.add_listener(bad)
    try:
        with pytest.warns(UserWarning, match="listener bug"):
            out = session.range(50).to_pandas()
    finally:
        session.remove_listener(bad)
    assert len(out) == 50
    assert session.listeners.dropped >= 1


def test_failed_query_posts_error_end(session):
    rec = Recorder()
    session.add_listener(rec)
    try:
        with faults.inject(session.conf, "stage_run:fatal:1"):
            with pytest.raises(Exception, match="INTERNAL"):
                _fresh_agg(session, 773).to_pandas()
    finally:
        session.remove_listener(rec)
    assert rec.names()[-1] == "end"
    end = rec.calls[-1][1]
    assert end.status == "error"
    assert "INTERNAL" in end.event["error"]


# -- spans / chrome trace ----------------------------------------------------

def test_chrome_trace_valid(session, tmp_path):
    trace_dir = str(tmp_path / "traces")
    session.conf.set(TRACE_KEY, trace_dir)
    try:
        _fresh_agg(session, 774).to_pandas()
    finally:
        session.conf.set(TRACE_KEY, "")
    files = [f for f in os.listdir(trace_dir)
             if f.endswith(".trace.json")]
    assert files, os.listdir(trace_dir)
    with open(os.path.join(trace_dir, files[-1])) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events
    names = {e["name"] for e in events}
    # the lifecycle phases are all present as spans
    assert {"analysis", "optimize", "plan", "ingest",
            "dispatch"} <= names, names
    for e in events:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float))
        assert e["tid"] >= 1  # query id
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_spans_in_event_log(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        _fresh_agg(session, 775).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    events = history.read_event_log(log_dir)
    spans = history.stage_summary(events)
    assert {"analysis", "dispatch"} <= set(spans["span"])
    assert (spans["dur_ms"] >= 0).all()


# -- XLA cost accounting -----------------------------------------------------

def test_stage_cost_captured_in_event_log(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        qe = _fresh_agg(session, 776)._qe()
        qe.execute_batch()
    finally:
        session.conf.set(EVENT_KEY, "")
    assert qe.stage_costs, "cost capture should be on with eventLog set"
    info = next(iter(qe.stage_costs.values()))
    assert info.get("flops", 0) > 0
    assert info.get("peak_hbm_bytes", 0) > 0
    events = history.read_event_log(log_dir)
    comp = history.compile_summary(events)
    assert len(comp) >= 1 and comp["flops"].notna().any()
    hbm = history.hbm_summary(events)
    assert len(hbm) >= 1
    assert hbm.iloc[-1]["peak_hbm_bytes"] > 0
    # runtime explain surfaces the same accounting
    text = qe.explain(runtime=True)
    assert "Stage cost (XLA)" in text and "peak HBM" in text


def test_cost_capture_off_by_default(session):
    qe = _fresh_agg(session, 778)._qe()
    qe.execute_batch()
    assert not qe.stage_costs  # no observability output configured


def test_oom_diagnostic_cites_measured_hbm(session, tmp_path):
    from spark_tpu.execution.failures import StageOOMError
    session.conf.set("spark_tpu.execution.backoffMs", 1)
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    try:
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with faults.inject(session.conf,
                               "stage_run:resource_exhausted:1,"
                               "stage_run:resource_exhausted:2,"
                               "stage_run:resource_exhausted:3"):
                with pytest.raises(StageOOMError) as exc:
                    _fresh_agg(session, 779).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    msg = str(exc.value)
    assert "measured peak HBM demand" in msg, msg
    assert "temps=" in msg


# -- metrics registry + sinks ------------------------------------------------

def test_prometheus_sink_scrape_parses(session, tmp_path):
    mdir = str(tmp_path / "metrics")
    session.conf.set(SINK_KEY, "prometheus")
    session.conf.set(MDIR_KEY, mdir)
    try:
        _fresh_agg(session, 780).to_pandas()
    finally:
        session.conf.set(SINK_KEY, "")
    prom = parse_prometheus(os.path.join(mdir, "metrics.prom"))
    assert prom["spark_tpu_queries_total"] >= 1
    assert "spark_tpu_query_execution_count" in prom
    assert any(k.startswith("spark_tpu_compile_cache_") for k in prom)
    assert any(k.startswith("spark_tpu_device_cache_") for k in prom)


def test_jsonl_sink_appends_snapshots(session, tmp_path):
    mdir = str(tmp_path / "metrics")
    session.conf.set(SINK_KEY, "jsonl")
    session.conf.set(MDIR_KEY, mdir)
    try:
        _fresh_agg(session, 781).to_pandas()
        _fresh_agg(session, 782).to_pandas()
    finally:
        session.conf.set(SINK_KEY, "")
    lines = [json.loads(ln) for ln in
             open(os.path.join(mdir, "metrics.jsonl"))]
    assert len(lines) >= 2
    assert lines[-1]["counters"]["queries_total"] \
        > lines[0]["counters"]["queries_total"] - 1
    assert "ts" in lines[-1]


def test_sink_validator_rejects_unknown(session):
    with pytest.raises(ValueError):
        session.conf.set(SINK_KEY, "statsd")


def test_metrics_lint_clean():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "metrics_lint", os.path.join(root, "scripts", "metrics_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.run() == []


def test_unregistered_metric_name_rejected(session):
    from spark_tpu.config import Conf
    from spark_tpu.plan.physical import ExecContext
    ctx = ExecContext(Conf())
    with pytest.raises(ValueError, match="unregistered metric"):
        ctx.add_metric("made_up_metric", 1)
    ctx.add_metric("rows_op1", 1)  # registered prefix passes


# -- event-log hardening + rotation ------------------------------------------

def test_json_default_encoder():
    import jax.numpy as jnp
    assert json_default(np.int64(7)) == 7
    assert json_default(np.float32(0.5)) == 0.5
    assert json_default(np.array([1, 2])) == [1, 2]
    assert json_default(jnp.asarray(3)) == 3
    assert json_default({"b", "a"}) == ["a", "b"]
    # end-to-end: numpy scalars inside an event dict serialize
    s = json.dumps({"v": np.int64(5), "w": np.float64(1.5)},
                   default=json_default)
    assert json.loads(s) == {"v": 5, "w": 1.5}


def test_event_log_schema_and_unique_filename(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        _fresh_agg(session, 783).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    files = os.listdir(log_dir)
    assert len(files) == 1
    # session-unique name: app-<pid>-<token>.jsonl, not bare pid
    assert files[0] == f"app-{session.app_id}.jsonl"
    assert files[0] != f"app-{os.getpid()}.jsonl"
    line = json.loads(open(os.path.join(log_dir, files[0])).read()
                      .splitlines()[-1])
    assert line["schema_version"] == 7
    assert line["status"] == "ok"
    assert line["query_id"] >= 1


def test_event_log_rotation_and_replay_order(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    session.conf.set(MAXB_KEY, 1)  # every write rolls the previous file
    session.conf.set(COST_KEY, "off")  # keep lines small + fast
    try:
        for i in range(4):
            session.range(100 + i).agg(
                F.sum(col("id")).alias("s")).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
        session.conf.set(MAXB_KEY, 0)
        session.conf.set(COST_KEY, "auto")
    names = sorted(os.listdir(log_dir))
    rolled = [n for n in names if n.count(".") == 2]
    assert len(rolled) == 3, names  # 4 writes -> 3 rolls + live file
    events = history.read_event_log(log_dir)
    assert len(events) == 4
    # replay order == write order (rolled files first, in N order)
    assert events["ts"].is_monotonic_increasing
    # per-app filter sees rolled files too
    assert len(history.read_event_log(log_dir, app=session.app_id)) == 4


def test_event_log_write_failure_warns_not_raises(session, tmp_path):
    bad = tmp_path / "afile"
    bad.write_text("x")
    session.conf.set(EVENT_KEY, str(bad))
    try:
        with pytest.warns(UserWarning, match="event log write failed"):
            out = session.range(5).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    assert len(out) == 5


# -- runtime tree annotations ------------------------------------------------

def test_runtime_tree_join_annotations(session):
    left = pd.DataFrame({"k": np.arange(50, dtype=np.int64),
                         "v": np.arange(50, dtype=np.int64)})
    right = pd.DataFrame({"k": np.arange(0, 50, 5, dtype=np.int64),
                          "w": np.arange(10, dtype=np.int64)})
    session.register_table("obs_l", left)
    session.register_table("obs_r", right)
    df = session.table("obs_l").join(session.table("obs_r"), on="k")
    qe = df._qe()
    qe.execute_batch()
    text = qe.explain(runtime=True)
    assert "join rows: 10" in text, text
    assert "cap" in text  # capacity rides along with the actual


# -- history: compare_runs ---------------------------------------------------

def _synthetic_events(tmp_path, name, execution_s):
    log_dir = tmp_path / name
    log_dir.mkdir()
    lines = [{"schema_version": 2, "query_id": i + 1, "ts": 100.0 + i,
              "status": "ok", "plan": "(AggExec (ScanExec t))",
              "phase_times_s": {"execution": execution_s},
              "metrics": {"rows_op1": 1000 * (i + 1)},
              "stages": [{"key_hash": "abc", "flops": 5000,
                          "peak_hbm_bytes": 4096,
                          "argument_bytes": 2048, "temp_bytes": 1024,
                          "output_bytes": 1024}]}
             for i in range(2)]
    with open(log_dir / "app-1-synthetic.jsonl", "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    return str(log_dir)


def test_hbm_summary_on_synthetic_log(tmp_path):
    events = history.read_event_log(
        _synthetic_events(tmp_path, "a", 0.5))
    hbm = history.hbm_summary(events)
    assert len(hbm) == 2
    row = hbm.iloc[0]
    assert row["peak_hbm_bytes"] == 4096
    assert row["peak_stage"] == "abc"
    assert row["capacity_bytes"] is None  # CPU logs no capacity


def test_compare_runs_on_synthetic_logs(tmp_path):
    base = history.read_event_log(_synthetic_events(tmp_path, "a", 2.0))
    other = history.read_event_log(_synthetic_events(tmp_path, "b", 1.0))
    cmp = history.compare_runs(base, other)
    assert len(cmp) >= 1
    row = cmp[cmp["column"] == "phase_execution_s"].iloc[0]
    assert row["base"] == 2.0 and row["other"] == 1.0
    assert row["delta"] == -1.0 and row["ratio"] == 0.5


# -- per-shard telemetry + straggler detection -------------------------------

MESH_KEY = "spark_tpu.sql.mesh.size"
CHUNK_KEY = "spark_tpu.sql.execution.streamingChunkRows"
CACHE_KEY = "spark_tpu.sql.io.deviceCacheBytes"
SHARD_SPANS_KEY = "spark_tpu.sql.observability.shardSpans"


def _mesh_stream_qe(session, n_rows=5000, chunk=1024, name="shard_obs_t"):
    """A mesh streamed-aggregate execution with per-shard spans on."""
    pdf = pd.DataFrame({"v": np.arange(n_rows, dtype=np.int64)})
    session.register_table(name, pdf)
    session.conf.set(CHUNK_KEY, chunk)
    session.conf.set(CACHE_KEY, 0)
    session.conf.set(SHARD_SPANS_KEY, "on")
    session.conf.set(MESH_KEY, 8)
    qe = (session.table(name)
          .group_by((col("v") % 13).alias("k"))
          .agg(F.sum(col("v")).alias("s")))._qe()
    return qe, pdf


def test_shard_telemetry_mesh_stream(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        qe, pdf = _mesh_stream_qe(session)
        qe.execute_batch()
    finally:
        session.conf.set(EVENT_KEY, "")
        session.conf.set(MESH_KEY, 0)
    comp = [r for r in qe.spans.shard_records if r["phase"] == "compute"]
    assert {r["shard"] for r in comp} == set(range(8))
    assert max(r["chunk"] for r in comp) >= 2  # genuinely chunked
    # per-shard row counts tile the scan exactly (psum-free coverage)
    assert sum(r["rows"] for r in comp) == len(pdf)
    assert all(r["bytes"] == r["rows"] * 8 for r in comp)
    ingest = [r for r in qe.spans.shard_records
              if r["phase"] == "ingest"]
    assert ingest and all(r["shard"] is None for r in ingest)
    # exchange transfer vectors rode the metrics channel into records
    transfer = [r for r in qe.spans.shard_records
                if r["phase"] == "transfer"]
    assert transfer and all(
        r["source"].startswith("exchange:") for r in transfer)
    # ...and the [n]-vector metrics never leak into scalar last_metrics
    assert not any(k.startswith("shard_") for k in qe.last_metrics)
    # event log: schema v3 `shards` replayed by the history views
    events = history.read_event_log(log_dir)
    assert events.iloc[-1]["schema_version"] == 7
    ss = history.shard_summary(events)
    assert len(ss) == len(qe.spans.shard_records)
    rep = history.straggler_report(events)
    assert not rep.empty and not rep["flagged"].any()


def test_straggler_monitor_flags_slow_shard(session):
    """Chaos: a `slow` fault on exactly one shard's telemetry window
    (shard 5, every chunk) must flag exactly that shard — on_straggler
    event + straggler_flagged counter — with result parity."""
    from spark_tpu.observability import QueryListener, StragglerMonitor

    straggler_events = []

    class Sub(QueryListener):
        def on_straggler(self, e):
            straggler_events.append(e)

    sub = Sub()
    session.add_listener(sub)
    session.conf.set("spark_tpu.sql.straggler.minChunks", 3)
    session.conf.set("spark_tpu.sql.straggler.factor", 4.0)
    flagged_before = session.metrics.counter("straggler_flagged").value
    # 5 chunks x 8 shards; shard 5's window is hit c*8 + 5 + 1
    rules = ",".join(f"shard_chunk:slow:{c * 8 + 6}:60" for c in range(5))
    try:
        with faults.inject(session.conf, rules) as fp:
            qe, pdf = _mesh_stream_qe(session, name="straggler_t")
            batch, _, _ = qe.execute_batch()
            got = batch.to_arrow().to_pandas()
    finally:
        session.remove_listener(sub)
        session.conf.set(MESH_KEY, 0)
    assert fp.fired_log, "shard_chunk seam never fired — test is vacuous"
    # parity: the slow shard perturbed nothing but its wait
    want = pdf.assign(k=pdf.v % 13).groupby("k")["v"].sum()
    res = got.set_index("k")["s"].sort_index()
    assert (res == want).all()
    mon = StragglerMonitor.of(session)
    assert mon is not None
    assert mon.report().get(qe.query_id) == {5}, mon.report()
    assert session.metrics.counter("straggler_flagged").value \
        == flagged_before + 1
    assert len(straggler_events) == 1
    ev = straggler_events[0]
    assert ev.shard == 5 and ev.query_id == qe.query_id
    assert ev.median_ms > ev.baseline_ms


def test_straggler_monitor_state_self_bounded(session):
    """With shardSpans=on and NO observability output, on_query_end
    never fires — the monitor's live maps must self-bound instead of
    leaking one entry per mesh query (code-review finding)."""
    from spark_tpu.observability import StragglerMonitor
    from spark_tpu.observability.listener import ShardChunkEvent
    from spark_tpu.observability import straggler as S
    mon = StragglerMonitor.of(session)
    assert mon is not None
    for qid in range(1000, 1000 + S._LIVE_BOUND + 5):
        mon.on_shard_records(ShardChunkEvent(
            query_id=qid, ts=0.0, chunk=0,
            records=[{"shard": 0, "host": 0, "phase": "compute",
                      "wait_ms": 0.1},
                     {"shard": 1, "host": 0, "phase": "compute",
                      "wait_ms": 0.1}]))
    assert len(mon._waits) <= S._LIVE_BOUND
    assert 1000 not in mon._waits  # oldest evicted
    assert 1000 + S._LIVE_BOUND + 4 in mon._waits  # newest retained


def test_shard_telemetry_retry_discards_failed_attempt(session):
    """A ChunkRetrier replay re-dispatches the SAME chunk index: the
    failed attempt's buffered array must be discarded, not flushed —
    duplicate (shard, chunk) records would double-count row totals
    and skew straggler medians (code-review finding)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from spark_tpu.observability.spans import (ShardStreamTelemetry,
                                               SpanRecorder)
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    rec = SpanRecorder(1)
    telem = ShardStreamTelemetry(rec, mesh, query_id=1)
    # sharded like the driver's real output: one piece per mesh device
    arr = jax.device_put(jnp.ones((8,), jnp.int64),
                         NamedSharding(mesh, PartitionSpec("data")))
    telem.chunk_dispatched(0, arr, 8, _t.perf_counter())
    telem.chunk_dispatched(0, arr, 8, _t.perf_counter())  # retry, same ci
    telem.chunk_dispatched(1, arr, 8, _t.perf_counter())
    telem.finish()
    comp = [r for r in rec.shard_records if r["phase"] == "compute"]
    assert len(comp) == 16  # 2 chunks x 8 shards: retry deduped
    per_chunk = {(r["chunk"], r["shard"]) for r in comp}
    assert len(per_chunk) == len(comp)  # no duplicate (chunk, shard)


def test_straggler_min_chunks_above_window_still_detects(session):
    """minChunks above the default rolling WINDOW must widen the
    window, not silently disable detection (code-review finding)."""
    from spark_tpu.observability import StragglerMonitor
    from spark_tpu.observability import straggler as S
    from spark_tpu.observability.listener import ShardChunkEvent
    mon = StragglerMonitor.of(session)
    min_chunks = S.WINDOW + 8
    session.conf.set("spark_tpu.sql.straggler.minChunks", min_chunks)
    session.conf.set("spark_tpu.sql.straggler.factor", 3.0)
    qid = 7777
    for c in range(min_chunks + 2):
        mon.on_shard_records(ShardChunkEvent(
            query_id=qid, ts=0.0, chunk=c,
            records=[{"shard": s, "host": 0, "phase": "compute",
                      "wait_ms": 50.0 if s == 2 else 0.1}
                     for s in range(4)]))
    assert mon.flagged(qid) == {2}, mon.flagged(qid)


def test_shard_telemetry_off_by_default(session):
    """No observability output + shardSpans=auto: the mesh stream must
    record nothing (zero flight-recorder tax on bare runs)."""
    pdf = pd.DataFrame({"v": np.arange(4000, dtype=np.int64)})
    session.register_table("shard_off_t", pdf)
    session.conf.set(CHUNK_KEY, 1024)
    session.conf.set(CACHE_KEY, 0)
    session.conf.set(MESH_KEY, 8)
    try:
        qe = (session.table("shard_off_t")
              .group_by((col("v") % 7).alias("k"))
              .agg(F.sum(col("v")).alias("s")))._qe()
        qe.execute_batch()
    finally:
        session.conf.set(MESH_KEY, 0)
    assert qe.spans.shard_records == []


def test_shard_records_bounded(session):
    session.conf.set(
        "spark_tpu.sql.observability.maxShardRecords", 10)
    try:
        qe, _ = _mesh_stream_qe(session, name="shard_bound_t")
        qe.execute_batch()
    finally:
        session.conf.set(MESH_KEY, 0)
    assert len(qe.spans.shard_records) == 10
    assert qe.spans.shard_dropped > 0  # truncation counted, not silent


# -- analyzer self-grading (predictions) -------------------------------------

def test_prediction_report_and_grading(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        left = pd.DataFrame({"k": np.arange(200, dtype=np.int64) % 50,
                             "v": np.arange(200, dtype=np.int64)})
        right = pd.DataFrame({"k": np.arange(50, dtype=np.int64),
                              "w": np.arange(50, dtype=np.int64)})
        session.register_table("pred_l", left)
        session.register_table("pred_r", right)
        qe = (session.table("pred_l")
              .join(session.table("pred_r"), on="k")
              .group_by(col("k")).agg(F.sum(col("v")).alias("s")))._qe()
        qe.execute_batch()
    finally:
        session.conf.set(EVENT_KEY, "")
    assert qe.plan_predictions, "no predictions harvested from the plan"
    kinds = {p["kind"] for p in qe.plan_predictions}
    assert "join_rows" in kinds and "agg_groups" in kinds
    graded = history.grade_predictions(qe.plan_predictions,
                                       qe.last_metrics)
    assert graded, (qe.plan_predictions, qe.last_metrics)
    assert all(g["grade"] in ("hit", "over", "under") for g in graded)
    jr = [g for g in graded if g["kind"] == "join_rows"]
    assert jr and jr[0]["observed"] == 200  # fk join: one match per row
    # replayed from the event log, the report grades the same rows
    events = history.read_event_log(log_dir)
    rep = history.prediction_report(events)
    assert len(rep) >= len(graded)
    assert set(rep["grade"]) <= {"hit", "over", "under"}


# -- events_tool (schema validation + tail) ----------------------------------

def _events_tool():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "events_tool", os.path.join(root, "scripts", "events_tool.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_events_tool_validate_and_tail(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        _fresh_agg(session, 784).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    tool = _events_tool()
    assert tool.validate([log_dir]) == []
    assert tool.main(["validate", log_dir]) == 0
    lines = tool.tail([log_dir], n=5)
    assert lines and "ok" in lines[-1]
    # a corrupt line and a schema violation both fail loudly
    path = os.path.join(log_dir, os.listdir(log_dir)[0])
    with open(path, "a") as f:
        f.write("not json\n")
        f.write(json.dumps({"schema_version": 2, "query_id": 1,
                            "ts": 1.0, "status": "ok", "plan": "p",
                            "shards": []}) + "\n")  # v3 field in v2
    problems = tool.validate([log_dir])
    assert len(problems) == 2, problems
    assert tool.main(["validate", log_dir]) == 1
    # old-version lines (v2, no shards) still validate
    ok2 = {"schema_version": 2, "query_id": 1, "ts": 1.0,
           "status": "ok", "plan": "p",
           "phase_times_s": {"execution": 0.1}}
    p2 = tmp_path / "old" / "app-1-old.jsonl"
    p2.parent.mkdir()
    p2.write_text(json.dumps(ok2) + "\n")
    assert tool.validate([str(tmp_path / "old")]) == []


# -- golden parity with everything on ----------------------------------------

@pytest.fixture(scope="module")
def obs_tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_obs") / "sf")
    write_parquet(path, 0.002)
    return path


@pytest.mark.parametrize("qname", ["q1", "q3"])
def test_golden_parity_all_observability_on(session, obs_tpch_path,
                                            tmp_path, qname):
    """Tracing/metrics/cost capture must not perturb results."""
    Q.register_tables(session, obs_tpch_path)
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    session.conf.set(TRACE_KEY, str(tmp_path / "tr"))
    session.conf.set(SINK_KEY, "jsonl,prometheus")
    session.conf.set(MDIR_KEY, str(tmp_path / "m"))
    session.conf.set(COST_KEY, "on")
    try:
        got = G.normalize_decimals(
            Q.QUERIES[qname](session)._qe().collect().to_pandas())
    finally:
        session.conf.set(EVENT_KEY, "")
        session.conf.set(TRACE_KEY, "")
        session.conf.set(SINK_KEY, "")
        session.conf.set(COST_KEY, "auto")
    G.compare(got.reset_index(drop=True),
              G.GOLDEN[qname](obs_tpch_path))
    # and all three artifact families exist
    assert os.listdir(str(tmp_path / "ev"))
    assert os.listdir(str(tmp_path / "tr"))
    assert os.path.exists(str(tmp_path / "m" / "metrics.prom"))

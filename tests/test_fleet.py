"""Chaos matrix for the crash-only serving fleet (service/fleet.py).

kill -9 mid-query x {sync, async, streaming-trigger} x {affinity
re-home, flap-breaker quarantine, SIGTERM drain}, plus the worker
lifecycle satellites: /healthz liveness/readiness split, signal-safe
idempotent stop, drain shedding. Every fleet cell asserts structured
errors (WORKER_LOST / FLEET_UNAVAILABLE / FLEET_DRAINING) or byte
parity, zero orphaned worker processes, zero leaked fleet threads,
and the fleet back at full strength after recovery.

Workers are REAL subprocesses (python -m spark_tpu.service.fleet
--worker); the supervisor runs in-process so tests can reach its ring
and worker table directly. Session init ships as a tmp-dir module on
PYTHONPATH (subprocesses can't inherit lambdas)."""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest

from spark_tpu import Conf
from spark_tpu.execution import lifecycle
from spark_tpu.observability.metrics import parse_prometheus_text
from spark_tpu.service.admission import ServiceDraining
from spark_tpu.service.fleet import (FleetSupervisor, _is_read,
                                     _merge_prometheus)
from spark_tpu.service.server import SqlService
from spark_tpu.testing.lockwatch import LockWatch
from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch import sql_queries as SQLQ
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002
WORKERS_KEY = "spark_tpu.service.fleet.workers"
RESTART_MAX_KEY = "spark_tpu.service.fleet.restartMaxPerWindow"
RESTART_WINDOW_KEY = "spark_tpu.service.fleet.restartWindowMs"
RESTART_BACKOFF_KEY = "spark_tpu.service.fleet.restartBackoffMs"
DRAIN_TIMEOUT_KEY = "spark_tpu.service.fleet.drainTimeoutMs"
HEALTH_INTERVAL_KEY = "spark_tpu.service.fleet.healthIntervalMs"
FLEET_DIR_KEY = "spark_tpu.service.fleet.dir"
INIT_KEY = "spark_tpu.service.fleet.init"
PORT_KEY = "spark_tpu.service.port"
WAREHOUSE_KEY = "spark_tpu.sql.warehouse.dir"
CC_ENABLED_KEY = "spark_tpu.sql.compileCache.enabled"
CC_DIR_KEY = "spark_tpu.sql.compileCache.dir"
CC_WARM_KEY = "spark_tpu.sql.compileCache.warmStart"
INJECT_KEY = "spark_tpu.faults.inject"

TPCH_INIT_SRC = """\
import spark_tpu.tpch.queries as Q
PATH = {path!r}
def init(session):
    Q.register_tables(session, PATH)
"""

STREAM_INIT_SRC = """\
import tempfile
import numpy as np
import pandas as pd
from spark_tpu.streaming import MemoryStream
def init(session):
    src = MemoryStream(session, pd.DataFrame(
        {"k": pd.Series([], dtype=np.int64),
         "v": pd.Series([], dtype=np.int64)}))
    ck = tempfile.mkdtemp(prefix="fleet-stream-ck-")
    q = src.to_df().write_stream(ck, output_mode="append")
    q.start(trigger_ms=200)
"""


# -- HTTP helpers -----------------------------------------------------------


def _req(port, method, path, body=None, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


def _post_sql(port, sql, session="default", conf=None, mode=None,
              timeout=120):
    body = {"sql": sql, "session": session}
    if conf:
        body["conf"] = conf
    if mode:
        body["mode"] = mode
    return _req(port, "POST", "/sql", body, timeout=timeout)


def _assert_pid_dead(pid, timeout_s=15.0):
    """The crash-only invariant: killed/stopped workers are REAPED —
    no zombie, no orphan."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.05)
    raise AssertionError(f"worker pid {pid} still alive (orphan)")


def _wait(cond, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _running_on_fleet(port, qid):
    st, _, listing = _req(port, "GET", "/queries")
    return st == 200 and any(
        q.get("id") == qid and q.get("status") == "running"
        for q in listing.get("queries", []))


# -- fixtures ---------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch_fleet") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="module")
def init_dir(tmp_path_factory, tpch_path):
    """Tmp dir on the workers' PYTHONPATH holding the init modules."""
    d = tmp_path_factory.mktemp("fleet_init")
    (d / "fleet_tpch_init.py").write_text(
        TPCH_INIT_SRC.format(path=tpch_path))
    (d / "fleet_stream_init.py").write_text(STREAM_INIT_SRC)
    prev = os.environ.get("PYTHONPATH")
    os.environ["PYTHONPATH"] = str(d) + (
        os.pathsep + prev if prev else "")
    yield str(d)
    if prev is None:
        os.environ.pop("PYTHONPATH", None)
    else:
        os.environ["PYTHONPATH"] = prev


def _fleet_conf(tmp_path_factory, workers, init_spec, **overrides):
    cache = tmp_path_factory.mktemp("fleet_cc")
    conf = (Conf()
            .set(PORT_KEY, 0)
            .set(WORKERS_KEY, workers)
            .set(HEALTH_INTERVAL_KEY, 100)
            .set(RESTART_BACKOFF_KEY, 100)
            .set(RESTART_MAX_KEY, 5)
            .set(RESTART_WINDOW_KEY, 60000)
            .set(DRAIN_TIMEOUT_KEY, 30000)
            .set(FLEET_DIR_KEY, str(tmp_path_factory.mktemp("fleet")))
            .set(WAREHOUSE_KEY,
                 str(tmp_path_factory.mktemp("fleet_wh")))
            .set(CC_ENABLED_KEY, True)
            .set(CC_DIR_KEY, str(cache))
            .set(CC_WARM_KEY, True))
    if init_spec:
        conf.set(INIT_KEY, init_spec)
    for k, v in overrides.items():
        conf.set(k, v)
    return conf


@pytest.fixture(scope="module")
def fleet(tmp_path_factory, init_dir):
    """One 2-worker fleet shared by the routing/failover cells; each
    kill cell restores full strength before finishing, and teardown
    asserts zero orphans + zero leaked fleet threads."""
    conf = _fleet_conf(tmp_path_factory, 2,
                       "fleet_tpch_init:init")
    sup = FleetSupervisor(conf).start()
    assert sup.wait_ready(180), sup.fleet_health()
    yield sup
    pids = sup.worker_pids()
    prefix = sup.thread_prefix
    sup.stop()
    for pid in pids:
        _assert_pid_dead(pid)
    LockWatch().assert_no_thread_leak(prefix, timeout_s=15)


# -- routing + parity -------------------------------------------------------


def test_router_parity_and_introspection(fleet, tpch_path):
    st, hdrs, resp = _post_sql(fleet.port, SQLQ.Q1, session="alpha")
    assert st == 200 and resp["status"] == "ok", resp
    # session affinity: the router picked the session's ring-home
    assert int(hdrs["X-Fleet-Worker"]) == fleet._route("alpha")[0]
    got = pd.DataFrame(resp["rows"], columns=resp["columns"])
    want = G.GOLDEN["q1"](tpch_path).reset_index(drop=True)
    G.compare(G.normalize_decimals(got)[list(want.columns)]
              .reset_index(drop=True), want)
    # same session routes to the same worker; the generation-prefixed
    # id routes GET /queries/<id> back to the owner without a table
    qid = resp["query_id"]
    assert qid.startswith(f"q-w{hdrs['X-Fleet-Worker']}g")
    st, hdrs2, rec = _req(fleet.port, "GET", f"/queries/{qid}")
    assert st == 200 and rec["status"] == "ok"
    assert hdrs2["X-Fleet-Worker"] == hdrs["X-Fleet-Worker"]
    # merged listing sees it; fleet health + metrics agree
    st, _, listing = _req(fleet.port, "GET", "/queries")
    assert st == 200 and any(q["id"] == qid
                             for q in listing["queries"])
    st, _, health = _req(fleet.port, "GET", "/healthz")
    assert st == 200 and health["workers_ready"] == 2
    prom = parse_prometheus_text(urllib.request.urlopen(
        f"http://127.0.0.1:{fleet.port}/metrics",
        timeout=30).read().decode())
    assert prom.get("spark_tpu_fleet_requests_proxied", 0) >= 1
    # a stale generation 503s structurally instead of 404-ing
    st, _, err = _req(fleet.port, "GET", "/queries/q-w0g999-1")
    assert st == 503 and err["error"] == "WORKER_LOST"


def test_metrics_fanout_merges_worker_series(fleet):
    """GET /metrics on the router merges the supervisor's fleet_*
    counters with every live worker's metrics, each worker's series
    tagged worker="<idx>" — one scrape covers the whole fleet and
    stays valid exposition (parseable, one # TYPE line per family)."""
    st, hdrs, resp = _post_sql(fleet.port, "SHOW TABLES",
                               session="metrics-fanout")
    assert st == 200, resp
    widx = hdrs["X-Fleet-Worker"]
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{fleet.port}/metrics",
        timeout=30).read().decode()
    prom = parse_prometheus_text(text)  # merged doc parses cleanly
    # supervisor's own series stay unlabeled...
    assert prom.get("spark_tpu_fleet_requests_proxied", 0) >= 1
    # ...and the worker that served the query shows up labeled
    assert prom.get(
        f'spark_tpu_service_admitted{{worker="{widx}"}}', 0) >= 1
    # TYPE lines dedup across sources: one per family name
    families = [ln.split()[2] for ln in text.splitlines()
                if ln.startswith("# TYPE ")]
    assert len(families) == len(set(families)), families


def test_sync_kill9_failover_parity_and_rehome(fleet, tpch_path):
    """Sync cell: kill -9 the session's home worker mid-query. The
    idempotent read retries ONCE on the re-homed worker with golden
    parity, and the session's DURABLE catalog state (a CTAS table in
    the shared warehouse dir) survives the crash — the re-homed
    worker reads the same bytes the dead worker wrote."""
    home = fleet._route("alpha")[0]
    pid = fleet._workers[home].snapshot()["pid"]
    # durable session state, written through the home worker
    st, _, resp = _post_sql(
        fleet.port,
        "CREATE TABLE fleet_scratch AS "
        "SELECT l_orderkey FROM lineitem LIMIT 1", session="alpha")
    assert st == 200, resp
    st, _, before = _post_sql(
        fleet.port, "SELECT l_orderkey FROM fleet_scratch",
        session="alpha")
    assert st == 200, before

    results = []

    def run():
        results.append(_post_sql(
            fleet.port, SQLQ.Q1, session="alpha",
            conf={INJECT_KEY: "stage_run:slow:1:2500"}))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # kill once the query is observably in flight on the worker
    _wait(lambda: any(
        q.get("status") == "running" and q.get("session") == "alpha"
        for q in _req(fleet.port, "GET", "/queries")[2].get(
            "queries", [])), 30, "query in flight")
    os.kill(pid, signal.SIGKILL)
    t.join(120)
    assert results, "query thread wedged"
    st, hdrs, resp = results[0]
    assert st == 200 and resp["status"] == "ok", resp
    assert hdrs.get("X-Fleet-Failover") == "1"
    assert int(hdrs["X-Fleet-Worker"]) != home
    got = pd.DataFrame(resp["rows"], columns=resp["columns"])
    want = G.GOLDEN["q1"](tpch_path).reset_index(drop=True)
    G.compare(G.normalize_decimals(got)[list(want.columns)]
              .reset_index(drop=True), want)
    # durable state re-homed with the session: the new worker serves
    # the table the dead worker created, byte-for-byte
    st, _, after = _post_sql(
        fleet.port, "SELECT l_orderkey FROM fleet_scratch",
        session="alpha")
    assert st == 200 and after["rows"] == before["rows"], after
    # fleet back at full strength; the killed pid was reaped
    assert fleet.wait_ready(180), fleet.fleet_health()
    _assert_pid_dead(pid)
    assert fleet._workers[home].snapshot()["generation"] >= 2


def test_async_kill9_worker_lost_structured(fleet):
    """Async cell: the submitted query's record dies with its worker.
    GET/DELETE on its id answer 503 WORKER_LOST (broken worker first,
    stale generation after the respawn) — never a 404, never a hang."""
    session = "beta"
    st, hdrs, resp = _post_sql(
        fleet.port, SQLQ.Q1, session=session, mode="async",
        conf={INJECT_KEY: "stage_run:slow:1:3000"})
    assert st == 202, resp
    qid = resp["query_id"]
    owner = int(hdrs["X-Fleet-Worker"])
    pid = fleet._workers[owner].snapshot()["pid"]
    _wait(lambda: _running_on_fleet(fleet.port, qid), 30,
          "async query running")
    os.kill(pid, signal.SIGKILL)
    st, _, err = _req(fleet.port, "GET", f"/queries/{qid}")
    assert st == 503 and err["error"] == "WORKER_LOST", err
    assert err["query_id"] == qid and err["worker"] == owner
    st, _, err = _req(fleet.port, "DELETE", f"/queries/{qid}")
    assert st == 503 and err["error"] == "WORKER_LOST", err
    # after the respawn the generation moved on: still WORKER_LOST
    assert fleet.wait_ready(180), fleet.fleet_health()
    st, _, err = _req(fleet.port, "GET", f"/queries/{qid}")
    assert st == 503 and err["error"] == "WORKER_LOST", err
    _assert_pid_dead(pid)


# -- streaming-trigger cell -------------------------------------------------


def test_streaming_trigger_kill9_rehome(tmp_path_factory, init_dir):
    """Streaming cell: a worker with a live supervised trigger loop is
    kill -9'd. The loop is in-memory worker state — it vanishes from
    the merged listing, the fleet sheds structurally while down, and
    the respawned worker's session init starts a FRESH loop."""
    conf = _fleet_conf(tmp_path_factory, 1, "fleet_stream_init:init")
    sup = FleetSupervisor(conf).start()
    try:
        assert sup.wait_ready(180), sup.fleet_health()
        st, _, resp = _post_sql(sup.port, "SHOW TABLES",
                                session="gamma")
        assert st == 200, resp
        _wait(lambda: _req(sup.port, "GET", "/queries")[2].get(
            "streams"), 30, "live trigger loop in merged listing")
        pid = sup._workers[0].snapshot()["pid"]
        os.kill(pid, signal.SIGKILL)
        # single worker down: structured shed, streams gone
        st, _, err = _post_sql(sup.port, "SHOW TABLES",
                               session="gamma")
        assert st == 503, err
        assert err["error"] in ("WORKER_LOST", "FLEET_UNAVAILABLE")
        _assert_pid_dead(pid)
        # crash-only recovery: respawn, re-init, fresh loop
        assert sup.wait_ready(180), sup.fleet_health()
        st, _, resp = _post_sql(sup.port, "SHOW TABLES",
                                session="gamma")
        assert st == 200, resp
        _wait(lambda: _req(sup.port, "GET", "/queries")[2].get(
            "streams"), 30, "respawned trigger loop")
        assert sup._workers[0].snapshot()["generation"] >= 2
    finally:
        pids = sup.worker_pids()
        prefix = sup.thread_prefix
        sup.stop()
        for p in pids:
            _assert_pid_dead(p)
        LockWatch().assert_no_thread_leak(prefix, timeout_s=15)


# -- flap breaker -----------------------------------------------------------


def test_flap_breaker_quarantine_and_shed(tmp_path_factory, init_dir):
    """A deterministic boot failure (unimportable init module) crashes
    the worker every spawn: after restartMaxPerWindow crashes inside
    the window the breaker QUARANTINES the slot instead of respawn-
    storming, traffic sheds with structured 503s, and every death
    left a flight bundle."""
    conf = _fleet_conf(tmp_path_factory, 1,
                       "fleet_no_such_module_xyz:init",
                       **{RESTART_MAX_KEY: 2,
                          RESTART_BACKOFF_KEY: 50})
    sup = FleetSupervisor(conf).start()
    try:
        _wait(lambda: sup._workers[0].snapshot()["state"]
              == "quarantined", 120, "flap-breaker quarantine")
        st, _, err = _post_sql(sup.port, "SHOW TABLES")
        assert st == 503 and err["error"] == "FLEET_UNAVAILABLE", err
        st, _, health = _req(sup.port, "GET", "/healthz")
        assert st == 503 and health["status"] == "degraded"
        prom = parse_prometheus_text(urllib.request.urlopen(
            f"http://127.0.0.1:{sup.port}/metrics",
            timeout=30).read().decode())
        assert prom.get("spark_tpu_fleet_worker_lost", 0) >= 2
        assert prom.get("spark_tpu_fleet_quarantined", 0) >= 1
        bundles_dir = os.path.join(
            str(conf.get(FLEET_DIR_KEY)), "bundles")
        bundles = sorted(os.listdir(bundles_dir))
        assert len(bundles) >= 2, bundles
        manifest = json.load(open(os.path.join(
            bundles_dir, bundles[0], "MANIFEST.json")))
        assert manifest["worker"] == 0 and manifest["reason"]
        stderr_txt = open(os.path.join(
            bundles_dir, bundles[0], "stderr.txt")).read()
        assert "fleet_no_such_module_xyz" in stderr_txt
    finally:
        prefix = sup.thread_prefix
        sup.stop()
        LockWatch().assert_no_thread_leak(prefix, timeout_s=15)


# -- drain ------------------------------------------------------------------


def test_drain_finishes_inflight_sheds_new(tmp_path_factory,
                                           init_dir):
    """Drain cell: shutdown() mid-query stops admitting (structured
    FLEET_DRAINING), lets the in-flight query finish with its result
    intact, SIGTERMs the worker through its own drain path (exit 0),
    and leaves zero orphans and zero fleet threads."""
    conf = _fleet_conf(tmp_path_factory, 1, "fleet_tpch_init:init")
    sup = FleetSupervisor(conf).start()
    stopped = False
    try:
        assert sup.wait_ready(180), sup.fleet_health()
        pid = sup._workers[0].snapshot()["pid"]
        results, shut = [], []

        def run():
            results.append(_post_sql(
                sup.port, SQLQ.Q1, session="delta",
                conf={INJECT_KEY: "stage_run:slow:1:2000"}))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        _wait(lambda: any(
            q.get("status") == "running"
            for q in _req(sup.port, "GET", "/queries")[2].get(
                "queries", [])), 60, "query in flight")
        ts = threading.Thread(target=lambda: shut.append(
            sup.shutdown()), daemon=True)
        ts.start()
        # draining: the front door sheds IMMEDIATELY and structurally
        _wait(lambda: _post_sql(sup.port, "SHOW TABLES",
                                timeout=10)[2].get("error")
              == "FLEET_DRAINING", 10, "drain shed")
        t.join(120)
        ts.join(120)
        assert not ts.is_alive() and shut == [True], shut
        st, _, resp = results[0]
        # zero dropped in-flight: the query that was running when the
        # drain began completed normally
        assert st == 200 and resp["status"] == "ok", resp
        _assert_pid_dead(pid)
        assert sup.wait_for_shutdown(1)
        stopped = True
        LockWatch().assert_no_thread_leak(sup.thread_prefix,
                                          timeout_s=15)
    finally:
        if not stopped:
            sup.stop()


# -- worker lifecycle satellites (in-process SqlService) --------------------


@pytest.fixture()
def svc_conf(tmp_path):
    def make(**overrides):
        conf = Conf().set(PORT_KEY, 0)
        for k, v in overrides.items():
            conf.set(k, v)
        return conf
    return make


def _warm_gate(monkeypatch):
    """Replace the warm-start replay with an Event-gated stub so tests
    can hold a service in live-but-not-ready deterministically."""
    from spark_tpu.execution import compile_cache as CC
    gate = threading.Event()

    def slow_warm(stage_cache, conf, metrics):
        gate.wait(10)
        return 0

    monkeypatch.setattr(CC, "warm_start", slow_warm)
    return gate


def test_healthz_liveness_readiness_split(svc_conf, tmp_path,
                                          monkeypatch):
    gate = _warm_gate(monkeypatch)
    conf = svc_conf(**{CC_ENABLED_KEY: True,
                       CC_DIR_KEY: str(tmp_path / "cc"),
                       CC_WARM_KEY: True})
    svc = SqlService(conf).start()
    try:
        # live-but-NOT-ready while the manifest replays
        st, _, live = _req(svc.port, "GET", "/healthz/live")
        assert st == 200 and live["live"] and not live["ready"]
        st, _, ready = _req(svc.port, "GET", "/healthz/ready")
        assert st == 503 and ready["error"] == "NOT_READY", ready
        st, _, health = _req(svc.port, "GET", "/healthz")
        assert st == 200 and health["ready"] is False
        gate.set()
        _wait(lambda: _req(svc.port, "GET",
                           "/healthz/ready")[0] == 200, 15,
              "readiness flip after warm start")
    finally:
        gate.set()
        svc.stop()


def test_stop_idempotent_and_concurrent(svc_conf):
    """Double-stop / stop-racing-shutdown never deadlocks: every
    caller returns inside the bounded joins."""
    svc = SqlService(svc_conf()).start()
    threads = [threading.Thread(target=svc.stop, daemon=True)
               for _ in range(2)]
    threads.append(threading.Thread(target=svc.shutdown, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not any(t.is_alive() for t in threads), "stop deadlocked"
    svc.stop()  # and once more, after the fact
    assert svc.wait_for_shutdown(1)


def test_stop_during_warm_start_no_deadlock(svc_conf, tmp_path,
                                            monkeypatch):
    gate = _warm_gate(monkeypatch)
    conf = svc_conf(**{CC_ENABLED_KEY: True,
                       CC_DIR_KEY: str(tmp_path / "cc"),
                       CC_WARM_KEY: True})
    svc = SqlService(conf).start()
    t0 = time.monotonic()
    stopper = threading.Thread(target=svc.stop, daemon=True)
    stopper.start()
    time.sleep(0.2)
    gate.set()  # replay finishes under a concurrent stop
    stopper.join(45)
    assert not stopper.is_alive(), "stop wedged on the warm thread"
    assert time.monotonic() - t0 < 40
    assert svc.ready  # the finally-set readiness flag still flipped


def test_sigterm_runs_drain_path(svc_conf):
    """SIGTERM lands in the installed handler, drains and stops the
    service from a normal thread, and unblocks wait_for_shutdown —
    what a fleet worker does when its supervisor terminates it."""
    saved = {s: signal.getsignal(s)
             for s in (signal.SIGTERM, signal.SIGINT)}
    svc = SqlService(svc_conf()).start()
    try:
        svc.install_signal_handlers()
        os.kill(os.getpid(), signal.SIGTERM)
        assert svc.wait_for_shutdown(30), "handler never fired"
        _wait(lambda: svc._stopped, 30, "signal-driven stop")
        with pytest.raises(ServiceDraining):
            svc.submit("SHOW TABLES")
    finally:
        for s, h in saved.items():
            signal.signal(s, h)
        svc.stop()


def test_sigterm_drains_inflight_async_query(svc_conf, tpch_path):
    """Regression: the SIGTERM handler must NOT set the shutdown
    event itself — a worker main parked on wait_for_shutdown() would
    wake, call stop() and exit while an in-flight ASYNC query (which
    the router's in-flight count never sees) was still running,
    silently skipping the bounded-drain guarantee. The event may only
    fire once drain+stop completed, with the async query finished
    inside drainTimeoutMs."""
    saved = {s: signal.getsignal(s)
             for s in (signal.SIGTERM, signal.SIGINT)}
    conf = svc_conf(**{DRAIN_TIMEOUT_KEY: 30000})
    svc = SqlService(
        conf, init_session=lambda s: Q.register_tables(s, tpch_path))
    svc.start()
    try:
        svc.install_signal_handlers()
        st, _, resp = _post_sql(
            svc.port, "select count(*) as n from lineitem",
            mode="async",
            conf={INJECT_KEY: "stage_run:slow:1:2500"})
        assert st == 202, resp
        rid = resp["query_id"]
        _wait(lambda: svc.query_snapshot(rid).get("status")
              == "running", 60, "async query in flight")
        t0 = time.monotonic()
        os.kill(os.getpid(), signal.SIGTERM)
        # parked exactly like _worker_main: waking implies the drain
        # already ran and stop() tore the service down
        assert svc.wait_for_shutdown(60), "drain+stop never finished"
        assert (time.monotonic() - t0) * 1e3 <= 30000
        assert svc._stopped, "event fired before stop() completed"
        rec = svc.query_snapshot(rid)
        assert rec["status"] == "ok", (
            f"in-flight async query dropped by early exit: {rec}")
        with svc._async_lock:
            assert svc._async_inflight == 0
    finally:
        for s, h in saved.items():
            signal.signal(s, h)
        svc.stop()


def test_drain_sheds_structured_and_is_idempotent(svc_conf):
    svc = SqlService(svc_conf()).start()
    try:
        assert svc.drain(timeout_ms=2000) is True
        assert svc.drain(timeout_ms=2000) is True  # idempotent
        with pytest.raises(ServiceDraining) as exc:
            svc.submit("SHOW TABLES")
        err = exc.value.to_dict()
        assert err["error"] == "SERVICE_DRAINING"
        assert exc.value.http_status == 503
    finally:
        svc.stop()


# -- exposition merge (unit) ------------------------------------------------


def test_merge_prometheus_labels_and_dedups():
    sup = "# TYPE spark_tpu_fleet_x counter\nspark_tpu_fleet_x 2\n"
    w0 = ("# TYPE spark_tpu_service_admitted counter\n"
          "spark_tpu_service_admitted 3\n"
          "# TYPE spark_tpu_h histogram\n"
          'spark_tpu_h_bucket{le="1"} 1\n')
    w1 = ("# TYPE spark_tpu_service_admitted counter\n"
          "spark_tpu_service_admitted 5\n")
    text = _merge_prometheus([(None, sup), ("0", w0), ("1", w1)])
    prom = parse_prometheus_text(text)
    assert prom["spark_tpu_fleet_x"] == 2
    # same family from two workers: one TYPE line, two labeled series
    assert prom['spark_tpu_service_admitted{worker="0"}'] == 3
    assert prom['spark_tpu_service_admitted{worker="1"}'] == 5
    # worker label lands FIRST inside an existing label set
    assert prom['spark_tpu_h_bucket{worker="0",le="1"}'] == 1
    families = [ln.split()[2] for ln in text.splitlines()
                if ln.startswith("# TYPE ")]
    assert len(families) == len(set(families)), families


# -- read classifier --------------------------------------------------------


def test_is_read_classifier():
    assert _is_read("SELECT 1 FROM t")
    assert _is_read("  -- comment\n  select x from t")
    assert _is_read("WITH c AS (SELECT 1 FROM t) SELECT * FROM c")
    assert _is_read("SHOW TABLES")
    assert _is_read("DESCRIBE t")
    assert not _is_read("CREATE TABLE t AS SELECT 1 FROM s")
    assert not _is_read("INSERT INTO t VALUES (1)")
    assert not _is_read("DROP TABLE t")
    assert not _is_read("")
    assert not _is_read("-- only a comment")

"""Unattended streaming: network source + supervised trigger loop +
host-spillable keyed state.

The robustness tier over test_streaming_durability.py: the crash
matrix here kills the stream at every NEW seam (`stream_net_connect`,
`stream_net_recv`, `trigger_tick`, `state_spill`) for stateless /
stateful / spilled-event-time queries against a live socket producer,
and proves a fresh query over the same checkpoint recovers
byte-identical output. The non-matrix tests pin the individual
guarantees: mid-batch socket kills reconnect with zero loss and zero
duplication, poison frames quarantine without wedging the stream, the
wall-clock trigger loop skips (never queues) missed ticks under an
injected clock, the restart supervisor's backoff ladder is
deterministic under injected sleep+rng, FATAL errors park the query in
structured FAILED status with zero orphan threads, spilled state is
output-identical to resident state, and the SQL service lists/stops
live loops."""

import json
import os
import socket
import time

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.config import Conf
from spark_tpu.execution import lifecycle
from spark_tpu.functions import col
from spark_tpu.io.network_source import (MAX_RECONNECTS_KEY,
                                         FrameProducer)
from spark_tpu.streaming import (SPILL_BYTES_KEY, SPILL_PARTS_KEY,
                                 TRIGGER_BACKOFF_KEY,
                                 TRIGGER_MAX_RESTARTS_KEY, MemoryStream,
                                 get_live, live_queries, read_sink)
from spark_tpu.testing import faults
from spark_tpu.testing.lockwatch import LockWatch

SEAMS = ("stream_net_connect", "stream_net_recv", "trigger_tick",
         "state_spill")

#: "spilled" = the event-time/watermark shape with a 1-byte HBM budget
#: for resident keyed state, so EVERY batch runs through the
#: host-spill backend (execution/external.py SpillableKeyedState)
SHAPES = ("stateless", "stateful", "spilled")

TRIGGER_PREFIX = "spark-tpu-stream-trigger"


# -- harness ----------------------------------------------------------------


def _schema_df(shape):
    if shape == "spilled":
        return pd.DataFrame({"ts": [pd.Timestamp("2024-01-01")],
                             "v": [0.0]})
    return pd.DataFrame({"k": pd.Series([], dtype=np.int64),
                         "v": pd.Series([], dtype=np.int64)})


def _round_df(shape, i):
    if shape == "spilled":
        base = pd.Timestamp("2024-01-01") + pd.Timedelta(seconds=30 * i)
        return pd.DataFrame(
            {"ts": [base, base + pd.Timedelta(seconds=4)],
             "v": [float(i + 1), float(2 * i + 1)]})
    return pd.DataFrame(
        {"k": np.arange(6, dtype=np.int64) + i,
         "v": np.arange(6, dtype=np.int64) * (i + 1)})


def _plan(shape, src):
    df = src.to_df()
    if shape == "stateless":
        return df.filter(col("v") >= 0), "append"
    if shape == "stateful":
        return (df.group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s"),
                     F.count().alias("c")), "complete")
    return (df.with_watermark("ts", "10 seconds")
            .group_by(F.window(col("ts"), "10 seconds").alias("w"))
            .agg(F.sum(col("v")).alias("s"),
                 F.count().alias("c")), "complete")


def _norm(shape, pdf):
    if pdf is None or not len(pdf):
        return pdf
    key = {"stateful": "g", "spilled": "w"}.get(shape)
    if key is not None and key in pdf.columns:
        return pdf.sort_values(key).reset_index(drop=True)
    return pdf.reset_index(drop=True)


def _join_loop(q, want_status, timeout_s=15.0):
    """Wait for the supervised loop to reach a terminal status."""
    deadline = time.monotonic() + timeout_s
    while q.status == "RUNNING" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert q.status == want_status, (q.status, q.exception())


class _NetFeeder:
    """One (shape, sink) network-stream fixture: a live FrameProducer
    plus fresh queries over ONE persistent checkpoint. `hard_crash`
    closes the consumer socket the way a dead process would — the
    producer notices the FIN and frees its serve loop for the next
    (recovered) consumer's connection."""

    def __init__(self, session, shape, sink, base, tag):
        self.session = session
        self.shape = shape
        self.producer = FrameProducer()
        self.port = self.producer.start()
        self.ck = os.path.join(base, f"ck_{tag}")
        self.sink = (os.path.join(base, f"sink_{tag}")
                     if sink == "file" else None)
        self._n = 0

    def feed(self):
        self.producer.send(_round_df(self.shape, self._n))
        self._n += 1

    def query(self):
        src = self.session.network_stream(
            "127.0.0.1", self.port, _schema_df(self.shape))
        plan_df, mode = _plan(self.shape, src)
        return plan_df.write_stream(self.ck, output_mode=mode,
                                    sink_path=self.sink)

    @staticmethod
    def hard_crash(q):
        q.stream.close()

    def close(self):
        self.producer.close()


# -- the crash matrix -------------------------------------------------------


@pytest.mark.parametrize("sink", ["memory", "file"])
@pytest.mark.parametrize("shape", SHAPES)
def test_unattended_crash_matrix(session, tmp_path, shape, sink):
    if shape == "spilled":
        session.conf.set(SPILL_BYTES_KEY, 1)
        session.conf.set(SPILL_PARTS_KEY, 4)
    base = str(tmp_path)
    # uninterrupted baseline: 3 feed rounds, one query start to finish
    fb = _NetFeeder(session, shape, sink, base, "base")
    try:
        qb = fb.query()
        for _ in range(3):
            fb.feed()
            qb.process_available()
        want_concat = (pd.concat(qb.results(), ignore_index=True)
                       if shape == "stateless" else None)
        want_final = _norm(shape, qb.latest())
        want_sink = (_norm(shape, read_sink(fb.sink))
                     if sink == "file" else None)
        fb.hard_crash(qb)
    finally:
        fb.close()

    for seam in SEAMS:
        f = _NetFeeder(session, shape, sink, base, seam)
        try:
            q = f.query()
            f.feed()
            q.process_available()  # batch 0 commits clean
            f.feed()
            fired = False
            if seam == "trigger_tick":
                # the seam lives at the top of the supervised loop's
                # tick: a fatal there parks the query in FAILED — the
                # in-loop flavor of a hard crash
                with faults.inject(session.conf,
                                   "trigger_tick:fatal:1"):
                    q.start(trigger_ms=5)
                    _join_loop(q, "FAILED")
                fired = "FaultInjected" in (q.exception() or "")
                q.stop()
            else:
                if seam == "stream_net_connect":
                    # the seam only fires when a connect happens: kill
                    # the warm connection so batch 1 must reconnect
                    f.producer.kill_connection()
                with faults.inject(session.conf,
                                   f"{seam}:fatal:1") as fp:
                    try:
                        q.process_available()  # crash mid-batch-1
                    except faults.FaultInjected:
                        fired = True
            # state_spill only exists on the spilled shape; every
            # other (seam, shape) must actually crash or the cell is
            # vacuous
            expect_fire = not (seam == "state_spill"
                               and shape != "spilled")
            assert fired == expect_fire, (shape, sink, seam)
            survivors = dict(q._sink_results)
            f.hard_crash(q)
            del q  # the hard crash: the query object is GONE
            f.feed()
            q2 = f.query()  # fresh query over the same checkpoint
            q2.process_available()
            combined = dict(survivors)
            combined.update(q2._sink_results)
            cell = f"{shape}/{sink}/{seam}"
            try:
                if shape == "stateless":
                    got = pd.concat(
                        [combined[k] for k in sorted(combined)],
                        ignore_index=True)
                    pd.testing.assert_frame_equal(got, want_concat)
                else:
                    got_final = _norm(shape, combined[max(combined)])
                    pd.testing.assert_frame_equal(got_final, want_final)
                if sink == "file":
                    got_sink = _norm(shape, read_sink(f.sink))
                    pd.testing.assert_frame_equal(
                        got_sink.sort_values(list(got_sink.columns))
                        .reset_index(drop=True),
                        want_sink.sort_values(list(want_sink.columns))
                        .reset_index(drop=True))
            except AssertionError as e:
                raise AssertionError(
                    f"crash-matrix cell {cell}: {e}") from e
            f.hard_crash(q2)
        finally:
            f.close()
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)


# -- network source: reconnect ladder ---------------------------------------


def test_socket_kill_mid_stream_zero_loss_zero_dup(session, tmp_path):
    """The headline acceptance: a connection killed mid-stream (both
    flavors — clean EOF at a frame boundary and a torn frame mid-
    payload) resumes at the durable offset via the handshake: every
    row arrives exactly once, one `streaming_reconnects` tick per
    re-established connection."""
    prod = FrameProducer()
    port = prod.start()
    try:
        src = session.network_stream("127.0.0.1", port,
                                     _schema_df("stateless"))
        q = (src.to_df().filter(col("v") >= 0)
             .write_stream(str(tmp_path / "ck"), output_mode="append",
                           sink_path=str(tmp_path / "sink")))
        prod.send(_round_df("stateless", 0))
        q.process_available()
        rc0 = session.metrics.counter("streaming_reconnects").value
        # clean kill: EOF at a frame boundary, frames pending
        prod.kill_connection()
        prod.send(_round_df("stateless", 1))
        q.process_available()
        assert session.metrics.counter(
            "streaming_reconnects").value == rc0 + 1
        # torn kill: half a frame on the wire -> stall -> reconnect ->
        # the SAME frame arrives whole (nothing durable was skipped,
        # nothing durable was resent)
        prod.kill_connection_midframe()
        prod.send(_round_df("stateless", 2))
        q.process_available()
        assert session.metrics.counter(
            "streaming_reconnects").value == rc0 + 2
        got = pd.concat(q.results(), ignore_index=True)
        want = pd.concat([_round_df("stateless", i) for i in range(3)],
                         ignore_index=True)
        pd.testing.assert_frame_equal(got, want)
        assert src.quarantined() == []
        got_sink = read_sink(str(tmp_path / "sink"))
        pd.testing.assert_frame_equal(
            got_sink.sort_values(["k", "v"]).reset_index(drop=True),
            want.sort_values(["k", "v"]).reset_index(drop=True))
        src.close()
    finally:
        prod.close()


def test_poison_frame_quarantined_stream_flows(session, tmp_path):
    """One undecodable frame cannot wedge the stream: it quarantines
    durably (seen-log entry + counter), later frames flow, and a fresh
    query over the checkpoint skips it without re-decoding or
    re-counting."""
    prod = FrameProducer()
    port = prod.start()
    ck = str(tmp_path / "ck")
    q0 = session.metrics.counter("streaming_frames_quarantined").value
    try:
        def build():
            src = session.network_stream("127.0.0.1", port,
                                         _schema_df("stateless"))
            return src, (src.to_df().filter(col("v") >= 0)
                         .write_stream(ck, output_mode="append"))

        src, q = build()
        prod.send(_round_df("stateless", 0))
        prod.send_poison()
        prod.send(_round_df("stateless", 1))
        with pytest.warns(UserWarning, match="poison network frame"):
            q.process_available()
        assert session.metrics.counter(
            "streaming_frames_quarantined").value == q0 + 1
        got = pd.concat(q.results(), ignore_index=True)
        want = pd.concat([_round_df("stateless", 0),
                          _round_df("stateless", 1)],
                         ignore_index=True)
        pd.testing.assert_frame_equal(got, want)
        quar = src.quarantined()
        assert len(quar) == 1 and quar[0]["index"] == 1
        src.close()
        del q
        src2, q2 = build()
        q2.process_available()  # drained: nothing new
        assert len(src2.quarantined()) == 1
        assert session.metrics.counter(
            "streaming_frames_quarantined").value == q0 + 1
        src2.close()
    finally:
        prod.close()


def test_reconnect_ladder_exhaustion_is_transient_shaped(session,
                                                         tmp_path):
    """A producer that never comes back exhausts the per-poll ladder
    with a TRANSIENT-classified error (the trigger supervisor's retry
    contract), not a raw socket error."""
    from spark_tpu.execution import failures
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()  # nothing listens here now
    session.conf.set(MAX_RECONNECTS_KEY, 1)
    session.conf.set(
        "spark_tpu.streaming.source.network.backoffMs", 1)
    src = session.network_stream("127.0.0.1", port,
                                 _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    with pytest.raises(ConnectionError,
                       match="connection attempt budget exhausted"):
        q.process_available()
    try:
        q.process_available()
    except ConnectionError as e:
        assert failures.classify(e) == failures.FailureClass.TRANSIENT


# -- supervised trigger loop ------------------------------------------------


def test_trigger_overrun_skips_never_queues(session, tmp_path):
    """Injected-clock pacing: a batch 2.5x slower than the interval
    SKIPS the missed ticks and re-anchors on the wall-clock grid —
    sleeps stay positive (no backlog of queued ticks is ever run
    back-to-back)."""
    src = MemoryStream(session, _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))

    class _Clk:
        t = 0.0

    clk = _Clk()
    waits = []

    def sleep_fn(s):
        waits.append(s)
        clk.t += s
        if len(waits) >= 5:
            raise lifecycle.QueryCancelledError("test: stop the loop")

    orig = q.process_available

    def slow():
        clk.t += 0.25  # the batch costs 2.5 trigger intervals
        return orig()

    q.process_available = slow
    q.start(trigger_ms=100.0, clock=lambda: clk.t, sleep=sleep_fn)
    q._loop_thread.join(timeout=10)
    assert not q._loop_thread.is_alive()
    assert q.status == "STOPPED" and q.exception() is None
    s = q.state()
    # each iteration: tick, overrun by 150ms -> skip 2, wait 50ms
    assert s["ticks"] == 5
    assert s["skipped_ticks"] == 10
    assert all(w == pytest.approx(0.05) for w in waits), waits
    assert all(w > 0 for w in waits)
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)


def test_supervisor_backoff_deterministic_then_parks(session, tmp_path):
    """Transient tick failures climb ONE deterministic ladder under
    injected sleep+rng — delays double from trigger.backoffMs — and an
    exhausted ladder parks the query in FAILED with the error
    preserved and zero orphan threads."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    session.conf.set(MAX_RECONNECTS_KEY, 0)
    session.conf.set(
        "spark_tpu.streaming.source.network.backoffMs", 1)
    session.conf.set(TRIGGER_MAX_RESTARTS_KEY, 3)
    session.conf.set(TRIGGER_BACKOFF_KEY, 8)
    src = session.network_stream("127.0.0.1", port,
                                 _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    sleeps = []

    class _Rng:
        @staticmethod
        def random():
            return 1.0  # jitter factor pinned to 1.0

    q.start(trigger_ms=5, clock=lambda: 0.0,
            sleep=lambda s: sleeps.append(round(s * 1e3, 6)),
            rng=_Rng())
    _join_loop(q, "FAILED")
    q._loop_thread.join(timeout=10)
    assert sleeps == [8.0, 16.0, 32.0]  # backoffMs * 2^n, jitter = 1
    assert q.state()["restarts"] == 3
    assert "connection attempt budget exhausted" in q.exception()
    assert get_live(q._live_id) is None  # parked loops unregister
    q.stop()  # idempotent on a parked loop
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)


def test_fatal_batch_parks_failed_zero_orphans(session, tmp_path):
    """A FATAL batch error (unbounded group domain) must NOT retry:
    the query parks immediately in structured FAILED status, restarts
    stay 0, and no trigger thread outlives the park."""
    src = MemoryStream(session, _schema_df("stateful"))
    q = (src.to_df().group_by(col("k").alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .write_stream(str(tmp_path / "ck")))
    src.add_data(_round_df("stateful", 0))
    q.start(trigger_ms=5)
    _join_loop(q, "FAILED")
    assert "ValueError" in q.exception()
    assert q.state()["restarts"] == 0
    assert get_live(q._live_id) is None
    q.stop()
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)


def test_trigger_loop_runs_commits_and_stops_bounded(session, tmp_path):
    """Happy path end to end on the real clock: start() drives batches
    unattended, stop() joins bounded, is idempotent, and a stopped
    query's durable state serves a fresh manual-trigger query."""
    src = MemoryStream(session, _schema_df("stateful"))
    ck = str(tmp_path / "ck")

    def build():
        return (src.to_df()
                .group_by(F.pmod(col("k"), 5).alias("g"))
                .agg(F.sum(col("v")).alias("s")).write_stream(ck))

    q = build()
    src.add_data(_round_df("stateful", 0))
    q.start(trigger_ms=10)
    deadline = time.monotonic() + 15
    while q._committed_batch < 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    src.add_data(_round_df("stateful", 1))
    while q._committed_batch < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert q._committed_batch >= 1
    live_id = q._live_id
    assert any(r["id"] == live_id and r["status"] == "RUNNING"
               for r in live_queries())
    q.stop()
    assert q.status == "STOPPED"
    q.stop()  # idempotent
    assert all(r["id"] != live_id for r in live_queries())
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)
    # durable state is live after the loop stopped: a fresh query
    # folds the next round onto it, landing on an uninterrupted
    # twin's totals
    src.add_data(_round_df("stateful", 2))
    q2 = build()
    q2.process_available()
    src3 = MemoryStream(session, _schema_df("stateful"))
    q3 = (src3.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
          .agg(F.sum(col("v")).alias("s"))
          .write_stream(str(tmp_path / "ck3")))
    for i in range(3):
        src3.add_data(_round_df("stateful", i))
    q3.process_available()
    pd.testing.assert_frame_equal(
        q2.latest().sort_values("g").reset_index(drop=True),
        q3.latest().sort_values("g").reset_index(drop=True))


def test_deadline_caps_unattended_loop(session, tmp_path):
    """execution.queryDeadlineMs bounds an unattended stream end to
    end: the loop's lifecycle token expires mid-pacing-sleep and the
    query parks FAILED with the structured deadline error."""
    session.conf.set(lifecycle.DEADLINE_KEY, 150)
    src = MemoryStream(session, _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    q.start(trigger_ms=20)
    _join_loop(q, "FAILED")
    assert "QueryDeadlineError" in q.exception()
    LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)


# -- host-spillable keyed state ---------------------------------------------


def test_spilled_state_output_parity_with_resident(session, tmp_path):
    """A 1-byte state budget reroutes the event-time path through the
    host-spill backend: the stream COMPLETES, output is byte-identical
    to a resident run, the spill counter ticks, and crash recovery is
    unchanged (fresh query over the spilled checkpoint lands on the
    same totals)."""
    # resident twin first (conf untouched)
    src_r = MemoryStream(session, _schema_df("spilled"))
    plan_r, mode = _plan("spilled", src_r)
    q_r = plan_r.write_stream(str(tmp_path / "ck_r"), output_mode=mode)
    for i in range(3):
        src_r.add_data(_round_df("spilled", i))
        q_r.process_available()
    want = _norm("spilled", q_r.latest())
    assert q_r._spill is None  # resident run never engaged

    session.conf.set(SPILL_BYTES_KEY, 1)
    session.conf.set(SPILL_PARTS_KEY, 4)
    sp0 = session.metrics.counter("streaming_spill_bytes").value
    src_s = MemoryStream(session, _schema_df("spilled"))
    ck = str(tmp_path / "ck_s")
    plan_s, _ = _plan("spilled", src_s)
    q_s = plan_s.write_stream(ck, output_mode=mode)
    for i in range(3):
        src_s.add_data(_round_df("spilled", i))
        q_s.process_available()
    assert q_s._spill is not None  # the budget engaged the backend
    assert session.metrics.counter(
        "streaming_spill_bytes").value > sp0
    spill_dir = os.path.join(ck, "state", "spill")
    assert [f for f in os.listdir(spill_dir)
            if f.endswith(".parquet")]
    pd.testing.assert_frame_equal(_norm("spilled", q_s.latest()), want)
    # crash recovery rides the SAME delta/snapshot store: a fresh
    # query over the spilled checkpoint folds the next round onto
    # identical state
    del q_s
    src_r.add_data(_round_df("spilled", 3))
    q_r.process_available()
    src_s.add_data(_round_df("spilled", 3))
    plan_s2, _ = _plan("spilled", src_s)
    q_s2 = plan_s2.write_stream(ck, output_mode=mode)
    q_s2.process_available()
    pd.testing.assert_frame_equal(
        _norm("spilled", q_s2.latest()),
        _norm("spilled", q_r.latest()))


# -- observability: v6 trigger record ---------------------------------------


def test_trigger_event_log_v6_summary_and_validator(session, tmp_path):
    """Supervised ticks that ran batches land a schema-v6 `trigger`
    record in the event log; streaming_summary folds them in beside
    the batch rows; events_tool validates v6 and rejects a pre-v6 line
    smuggling a trigger record."""
    from spark_tpu import history
    ev_dir = str(tmp_path / "events")
    session.conf.set("spark_tpu.sql.eventLog.dir", ev_dir)
    src = MemoryStream(session, _schema_df("stateful"))
    q = (src.to_df().group_by(F.pmod(col("k"), 5).alias("g"))
         .agg(F.sum(col("v")).alias("s"))
         .write_stream(str(tmp_path / "ck")))
    src.add_data(_round_df("stateful", 0))
    q.start(trigger_ms=10)
    deadline = time.monotonic() + 15
    while q._committed_batch < 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    src.add_data(_round_df("stateful", 1))
    while q._committed_batch < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    q.stop()
    session.conf.set("spark_tpu.sql.eventLog.dir", "")
    events = history.read_event_log(ev_dir)
    assert (events["schema_version"].dropna() == 7).all()
    ss = history.streaming_summary(events)
    trig = ss[ss["record"] == "trigger"]
    assert len(trig) >= 2, ss
    assert (trig["batches_run"] >= 1).all()
    assert (trig["restarts"] == 0).all()
    assert (trig["reconnects"] == 0).all()
    assert (trig["source"] == "memory").all()
    assert (trig["skew_ms"] >= 0).all()
    assert trig["tick"].is_monotonic_increasing
    assert len(ss[ss["record"] == "batch"]) >= 2
    # the versioned-schema validator accepts the v6 lines
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "events_tool", os.path.join(root, "scripts", "events_tool.py"))
    et = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(et)
    assert et.validate([ev_dir]) == []
    # a pre-v6 line smuggling a trigger record is rejected
    bad = {"schema_version": 5, "query_id": 1, "ts": 1.0,
           "status": "ok", "plan": "x", "trigger": {"tick": 1}}
    bad_path = os.path.join(ev_dir, "app-bad.jsonl")
    with open(bad_path, "w") as f:
        f.write(json.dumps(bad) + "\n")
    problems = et.validate([bad_path])
    assert any("v6 field 'trigger'" in p for p in problems), problems
    # and a malformed v6 trigger record is rejected
    bad2 = dict(bad, schema_version=6,
                trigger={"tick": "one", "skew_ms": 0.0,
                         "batches_run": 1, "restarts": 0,
                         "source": "memory", "reconnects": 0})
    with open(bad_path, "w") as f:
        f.write(json.dumps(bad2) + "\n")
    problems = et.validate([bad_path])
    assert any("malformed trigger record" in p for p in problems), \
        problems


# -- service visibility -----------------------------------------------------


def test_service_lists_and_stops_live_streams(session, tmp_path):
    """GET /queries folds live trigger loops in under `streams`;
    DELETE /queries/stream-<n> stops the loop bounded (zero orphan
    threads) and a second DELETE is a structured 404."""
    from spark_tpu.service.server import SqlService
    svc = SqlService(Conf())
    src = MemoryStream(session, _schema_df("stateless"))
    q = (src.to_df().filter(col("v") >= 0)
         .write_stream(str(tmp_path / "ck"), output_mode="append"))
    q.start(trigger_ms=20)
    try:
        live_id = q._live_id
        rows = [s for s in svc.query_listing()["streams"]
                if s["id"] == live_id]
        assert rows and rows[0]["status"] == "RUNNING"
        assert rows[0]["source"] == "memory"
        assert rows[0]["trigger_ms"] == 20.0
        status, body = svc.cancel_query(live_id)
        assert status == 200
        assert body["status"] == "stopped"
        assert body["query_status"] == "STOPPED"
        assert get_live(live_id) is None
        assert all(s["id"] != live_id
                   for s in svc.query_listing()["streams"])
        status2, body2 = svc.cancel_query(live_id)
        assert status2 == 404 and body2["error"] == "NOT_FOUND"
        LockWatch().assert_no_thread_leak(TRIGGER_PREFIX)
    finally:
        q.stop()
        svc.stop()

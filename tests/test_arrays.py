"""Arrays + explode: offsets-encoded list columns (reference:
UnsafeArrayData.java:1 layout -> Arrow List layout on device;
GenerateExec.scala:1 -> static-capacity GenerateExec)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col


@pytest.fixture
def adf(session):
    pdf = pd.DataFrame({
        "k": np.array([1, 2, 3, 4], np.int64),
        "a": [[1, 2, 3], [], [4, 5], None],
        "v": np.array([10.0, 20.0, 30.0, 40.0])})
    session.register_table("arr_t", pdf)
    return session.table("arr_t"), pdf


def test_list_roundtrip_ingest_egress(adf):
    df, pdf = adf
    out = df.to_pandas()
    assert [list(x) if x is not None else None
            for x in out["a"].tolist()] == [[1, 2, 3], [], [4, 5], None]


def test_size_and_contains_and_element_at(adf):
    df, _ = adf
    out = df.select(
        col("k"),
        F.size(col("a")).alias("n"),
        F.array_contains(col("a"), 4).alias("c"),
        F.element_at(col("a"), 2).alias("e2"),
        F.element_at(col("a"), -1).alias("last"),
    ).to_pandas().sort_values("k").reset_index(drop=True)
    assert out["n"].tolist() == [3, 0, 2, -1]  # NULL -> -1 (legacy)
    assert out["c"].tolist()[:3] == [False, False, True]
    assert out["e2"][0] == 2 and pd.isna(out["e2"][1]) \
        and out["e2"][2] == 5 and pd.isna(out["e2"][3])
    assert out["last"][0] == 3 and out["last"][2] == 5


def test_make_array_and_explode_roundtrip(session):
    pdf = pd.DataFrame({"x": np.array([1, 2], np.int64),
                        "y": np.array([10, 20], np.int64)})
    session.register_table("mk_t", pdf)
    out = (session.table("mk_t")
           .select(col("x"), F.array(col("x"), col("y")).alias("a"))
           .select(col("x"), F.explode(col("a")).alias("e"))
           .to_pandas().sort_values(["x", "e"]).reset_index(drop=True))
    assert out["x"].tolist() == [1, 1, 2, 2]
    assert out["e"].tolist() == [1, 10, 2, 20]


def test_explode_replicates_and_drops_empty(adf):
    df, _ = adf
    out = (df.select(col("k"), col("v"),
                     F.explode(col("a")).alias("e"))
           .to_pandas().sort_values(["k", "e"]).reset_index(drop=True))
    # rows 2 (empty) and 4 (NULL) vanish; 1 and 3 replicate
    assert out["k"].tolist() == [1, 1, 1, 3, 3]
    assert out["e"].tolist() == [1, 2, 3, 4, 5]
    assert out["v"].tolist() == [10.0, 10.0, 10.0, 30.0, 30.0]


def test_explode_outer_keeps_empty_rows(adf):
    df, _ = adf
    out = (df.select(col("k"), F.explode_outer(col("a")).alias("e"))
           .to_pandas().sort_values(["k", "e"]).reset_index(drop=True))
    assert out["k"].tolist() == [1, 1, 1, 2, 3, 3, 4]
    got = out["e"].tolist()
    assert got[:3] == [1, 2, 3] and got[4:6] == [4, 5]
    assert pd.isna(got[3]) and pd.isna(got[6])


def test_explode_after_filter(adf):
    df, _ = adf
    out = (df.filter(col("k") != 1)
           .select(col("k"), F.explode(col("a")).alias("e"))
           .to_pandas().sort_values(["k", "e"]).reset_index(drop=True))
    assert out["k"].tolist() == [3, 3]
    assert out["e"].tolist() == [4, 5]


def test_explode_then_aggregate(adf):
    df, _ = adf
    out = (df.select(F.explode(col("a")).alias("e"))
           .agg(F.sum(col("e")).alias("s"), F.count().alias("c"))
           .to_pandas())
    assert int(out["s"][0]) == 15 and int(out["c"][0]) == 5


def test_sql_explode_and_array_fns(session, adf):
    out = session.sql(
        "SELECT k, explode(a) AS e FROM arr_t WHERE k <> 4 "
        "ORDER BY k, e").to_pandas()
    assert out["k"].tolist() == [1, 1, 1, 3, 3]
    assert out["e"].tolist() == [1, 2, 3, 4, 5]
    out2 = session.sql(
        "SELECT k, size(a) AS n, array_contains(a, 1) AS c FROM arr_t "
        "ORDER BY k").to_pandas()
    assert out2["n"].tolist() == [3, 0, 2, -1]
    assert bool(out2["c"][0]) is True and bool(out2["c"][1]) is False


def test_string_array_explode(session):
    pdf = pd.DataFrame({"k": np.array([1, 2], np.int64),
                        "s": [["aa", "bb"], ["cc"]]})
    session.register_table("sarr_t", pdf)
    out = (session.table("sarr_t")
           .select(col("k"), F.explode(col("s")).alias("w"))
           .to_pandas().sort_values(["k", "w"]).reset_index(drop=True))
    assert out["w"].tolist() == ["aa", "bb", "cc"]


def test_explode_on_mesh(session, adf):
    df, _ = adf
    build = lambda: (df.select(col("k"), F.explode(col("a")).alias("e"))
                     .agg(F.sum(col("e")).alias("s")).to_pandas())
    want = build()
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        got = build()
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert int(got["s"][0]) == int(want["s"][0]) == 15

"""Arrow-batched Python UDF worker pool (spark_tpu/udf_worker/).

The out-of-process lane (`spark_tpu.sql.udf.mode=worker`): pooled
CPython subprocess workers fed length-framed Arrow IPC batches over
stdin/stdout (the `PythonRunner.scala:84` / `pyspark/worker.py:504`
seam). The acceptance surface proven here:

- byte parity with the in-process lane across the UDF matrix (scalar,
  pandas, grouped-map, NULLs, strings, dates, decimals, nesting);
- batch-granular retry: a worker SIGKILLed mid-batch replays EXACTLY
  the in-flight batch (`rec_chunks_replayed`), results stay identical;
- a wedged worker past `udf.batchTimeoutMs` is killed and the batch
  replays on a fresh worker;
- DELETE mid-UDF: structured cancel error, zero surviving children,
  arbiter drained, byte-identical re-run;
- the pool bound (`udf.pool.maxWorkers`), reuse across queries, lazy
  reap of workers that died between queries (stale-pipe regression);
- worker tracebacks surface through QueryExecution and the service as
  structured UDF_ERROR records (HTTP 400);
- concurrent service sessions under lockwatch stay rank-consistent.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from spark_tpu import Conf
from spark_tpu import functions as F
from spark_tpu.execution import lifecycle
from spark_tpu.functions import col, pandas_udf, udf
from spark_tpu.service.arbiter import install_arbiter
from spark_tpu.service.server import SqlService
from spark_tpu.testing import faults
from spark_tpu.testing.lockwatch import LockWatch
from spark_tpu.udf_worker import UdfError

MODE_KEY = "spark_tpu.sql.udf.mode"
BATCH_KEY = "spark_tpu.sql.udf.arrow.maxRecordsPerBatch"
TIMEOUT_KEY = "spark_tpu.sql.udf.batchTimeoutMs"
MAXW_KEY = "spark_tpu.sql.udf.pool.maxWorkers"
PORT_KEY = "spark_tpu.service.port"


@pytest.fixture
def tdf(session):
    pdf = pd.DataFrame({
        "x": np.array([1.0, 2.0, np.nan, 4.0, 5.5, np.nan, 7.0]),
        "i": np.array([10, 20, 30, 40, 50, 60, 70], dtype=np.int64),
        "s": ["aa", "bb", None, "dd", None, "ff", "gg"]})
    session.register_table("udfw_t", pdf)
    return session.table("udfw_t"), pdf


def _both_modes(session, build):
    """Evaluate `build()` (a DataFrame factory) under each lane and
    return (inprocess_frame, worker_frame)."""
    session.conf.set(MODE_KEY, "inprocess")
    a = build().to_pandas()
    session.conf.set(MODE_KEY, "worker")
    b = build().to_pandas()
    return a, b


# ---------------------------------------------------------------------------
# Parity matrix: worker lane must be byte-identical to in-process
# ---------------------------------------------------------------------------


def test_worker_parity_scalar_with_nulls(session, tdf):
    df, _ = tdf
    session.conf.set(BATCH_KEY, 3)  # 7 rows -> 3 Arrow batches
    plus = udf(lambda v: None if v is None else v + 1.0, "double")
    a, b = _both_modes(session, lambda: df.select(
        col("i"), plus(col("x")).alias("y")))
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_strings_and_null_returns(session, tdf):
    df, _ = tdf
    session.conf.set(BATCH_KEY, 2)
    shout = udf(lambda s: None if s in (None, "bb") else s.upper(),
                "string")
    a, b = _both_modes(session, lambda: df.select(
        shout(col("s")).alias("u")))
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_pandas_udf(session, tdf):
    df, _ = tdf
    session.conf.set(BATCH_KEY, 4)

    @pandas_udf(returnType="double")
    def scaled(v: pd.Series) -> pd.Series:
        return v * 10.0

    a, b = _both_modes(session, lambda: df.select(
        scaled(col("x")).alias("y")))
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_filter_and_nested(session, tdf):
    df, _ = tdf
    session.conf.set(BATCH_KEY, 2)
    is_big = udf(lambda v: v is not None and v > 25, "boolean")
    double = udf(lambda v: None if v is None else v * 2, "long")
    inc = udf(lambda v: None if v is None else v + 1, "long")
    a, b = _both_modes(session, lambda: df.filter(
        is_big(col("i") + 1)).select(inc(double(col("i"))).alias("y")))
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_dates_and_decimals(session):
    import decimal
    pdf = pd.DataFrame({
        "d": pd.to_datetime(["2023-01-15", "2024-06-30", "2025-12-01"]),
        "m": [decimal.Decimal("12.50"), decimal.Decimal("0.75"),
              decimal.Decimal("99.99")]})
    session.register_table("udfw_dt", pdf)
    session.conf.set(BATCH_KEY, 2)
    year_of = udf(lambda d: d.year, "int")
    dollars = udf(lambda m: float(m) * 2, "double")
    a, b = _both_modes(session, lambda: session.table("udfw_dt").select(
        year_of(col("d")).alias("y"), dollars(col("m")).alias("v")))
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_grouped_map(session):
    pdf = pd.DataFrame({
        "k": np.array([0, 0, 1, 1, 2], dtype=np.int64),
        "v": np.array([1.0, 3.0, 5.0, 7.0, 9.0])})
    session.register_table("udfw_gm", pdf)

    def center(g: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"k": g["k"], "c": g["v"] - g["v"].mean()})

    a, b = _both_modes(session, lambda: (
        session.table("udfw_gm").group_by(col("k"))
        .apply_in_pandas(center, "k long, c double")))
    a = a.sort_values(["k", "c"]).reset_index(drop=True)
    b = b.sort_values(["k", "c"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


def test_worker_parity_udf_under_agg(session, tdf):
    df, _ = tdf
    session.conf.set(BATCH_KEY, 3)
    half = udf(lambda v: v / 2.0, "double")
    a, b = _both_modes(session, lambda: (
        df.filter(col("i") > 10).select(half(col("i")).alias("h"))
        .agg(F.sum(col("h")).alias("s"))))
    pd.testing.assert_frame_equal(a, b)


def test_worker_mode_metrics_and_event_record(session, tdf):
    df, _ = tdf
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 3)
    m = session.metrics
    b0, r0 = (m.counter("udf_batches").value,
              m.counter("udf_rows").value)
    twice = udf(lambda v: v * 2, "long")
    qe = df.select(twice(col("i")).alias("t"))._qe()
    qe.collect()
    assert m.counter("udf_batches").value - b0 == 3  # ceil(7/3)
    assert m.counter("udf_rows").value - r0 == 7
    assert qe.udf_summary["mode"] == "worker"
    assert qe.udf_summary["batches"] == 3
    assert qe.udf_summary["rows"] == 7
    # per-batch spans rode the recorder
    assert sum(1 for sp in qe.spans.spans
               if sp.name == "udf_batch") == 3


# ---------------------------------------------------------------------------
# Batch-granular retry: kill mid-batch, wedge recovery
# ---------------------------------------------------------------------------


def test_killed_worker_replays_exactly_one_batch(session, tdf):
    df, pdf = tdf
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 3)
    twice = udf(lambda v: v * 2, "long")
    session.conf.set(MODE_KEY, "inprocess")
    want = df.select(twice(col("i")).alias("t")).to_pandas()
    session.conf.set(MODE_KEY, "worker")

    replayed0 = session.metrics.counter("rec_chunks_replayed").value
    restarts0 = session.metrics.counter("udf_worker_restarts").value
    procs_before = set(id(p) for p in session._udf_pool.child_procs())
    with faults.inject(session.conf, "udf_batch:fatal:2") as plan:
        out = df.select(twice(col("i")).alias("t")).to_pandas()
        assert plan.fired_log == [("udf_batch", 2, "fatal")]
    pd.testing.assert_frame_equal(out, want)
    # EXACTLY the in-flight batch replayed — not the whole input
    assert session.metrics.counter(
        "rec_chunks_replayed").value - replayed0 == 1
    assert session.metrics.counter(
        "udf_worker_restarts").value - restarts0 == 1
    # the killed child is really dead; a replacement was spawned
    new = [p for p in session._udf_pool.child_procs()
           if id(p) not in procs_before]
    assert any(p.poll() is not None for p in
               session._udf_pool.child_procs())
    assert new, "no replacement worker was spawned"


def test_wedged_worker_batch_timeout_recovers(session, tdf, tmp_path):
    """First attempt wedges (sleeps far past the batch timeout); the
    handle times out, the worker is killed, the batch replays on a
    fresh worker where the flag file makes the UDF return promptly."""
    df, _ = tdf
    flag = str(tmp_path / "udfw_wedge_once")
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 100)
    session.conf.set(TIMEOUT_KEY, 800)

    def wedge_once(v):
        import os as _os
        import time as _time
        if not _os.path.exists(flag):
            open(flag, "w").close()
            _time.sleep(60)
        return v if v is None else v + 1.0

    f = udf(wedge_once, "double")
    t0 = time.perf_counter()
    out = df.select(f(col("x")).alias("y")).to_pandas()
    took = time.perf_counter() - t0
    assert took < 30, f"wedge recovery took {took:.1f}s"
    assert out["y"][0] == 2.0 and pd.isna(out["y"][2])
    session.conf.set(TIMEOUT_KEY, 0)


# ---------------------------------------------------------------------------
# Pool: bound, reuse across queries, lazy reap of dead idle workers
# ---------------------------------------------------------------------------


def test_pool_bound_and_reuse_across_queries(session, tdf):
    df, _ = tdf
    session._udf_pool.shutdown()  # clean slate from earlier tests
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(MAXW_KEY, 1)
    session.conf.set(BATCH_KEY, 2)
    twice = udf(lambda v: v * 2, "long")
    df.select(twice(col("i")).alias("t")).to_pandas()
    pool = session._udf_pool
    assert pool.live_count() == 1 and pool.idle_count() == 1
    pid0 = pool._idle[0].pid
    df.select(twice(col("i")).alias("t")).to_pandas()
    assert pool.live_count() == 1, \
        "second query must reuse the pooled worker, not spawn"
    assert pool._idle[0].pid == pid0, \
        "worker was not reused across queries"


def test_worker_died_between_queries_reaped_lazily(session, tdf):
    """Stale-pipe regression: a worker killed while idle (machine
    hygiene, OOM killer) must be reaped at the next checkout — not
    handed out as a poisoned handle that BrokenPipeErrors the query."""
    df, _ = tdf
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 4)
    twice = udf(lambda v: v * 2, "long")
    want = df.select(twice(col("i")).alias("t")).to_pandas()
    pool = session._udf_pool
    assert pool.idle_count() >= 1
    # murder every idle worker behind the pool's back
    for h in list(pool._idle):
        h.proc.kill()
        h.proc.wait(10)
    out = df.select(twice(col("i")).alias("t")).to_pandas()
    pd.testing.assert_frame_equal(out, want)


def test_user_error_surfaces_worker_traceback(session, tdf):
    df, _ = tdf
    session.conf.set(MODE_KEY, "worker")

    def boom(v):
        raise ValueError("user bug here")

    f = udf(boom, "double")
    with pytest.raises(UdfError) as ei:
        df.select(f(col("x")).alias("y")).to_pandas()
    assert "user bug here" in str(ei.value)
    assert ei.value.code == "UDF_ERROR"
    assert "in boom" in ei.value.worker_traceback
    # the pool survives a user error: next query reuses the lane
    ok = udf(lambda v: v, "double")
    df.select(ok(col("x")).alias("y")).to_pandas()


def test_cancel_mid_udf_engine_level_no_orphans(session, tdf):
    df, _ = tdf
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 1)

    def slow(v):
        import time as _time
        _time.sleep(0.4)
        return v

    f = udf(slow, "double")
    qe = df.select(f(col("x")).alias("y"))._qe()
    out = {}

    def run():
        try:
            out["table"] = qe.collect()
        except Exception as e:  # noqa: BLE001 — asserted below
            out["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if lifecycle.cancel(session.app_id, qe.query_id):
            break
        time.sleep(0.002)
    t.join(30)
    assert not t.is_alive()
    if "error" in out:  # fast runs may finish before the cancel lands
        assert isinstance(out["error"], lifecycle.QueryCancelledError)
        # zero children survive a cancelled query
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(p.poll() is not None
                   for p in session._udf_pool.child_procs()):
                break
            time.sleep(0.05)
        assert all(p.poll() is not None
                   for p in session._udf_pool.child_procs())
    # immediate re-run parity
    session.conf.set(MODE_KEY, "inprocess")
    want = df.select(f(col("x")).alias("y")).to_pandas()
    session.conf.set(MODE_KEY, "worker")
    got = df.select(f(col("x")).alias("y")).to_pandas()
    pd.testing.assert_frame_equal(got, want)


# ---------------------------------------------------------------------------
# Analyzer / predictions / history plumbing
# ---------------------------------------------------------------------------


def test_analyzer_udf_findings_and_prediction_grading(session):
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 30000)
    pdf = pd.DataFrame({"x": np.arange(100000, dtype="float64")})
    session.register_table("udfw_big", pdf)
    f = udf(lambda v: v * 2.0, "double")
    qe = session.table("udfw_big").select(f(col("x")).alias("y"))._qe()
    qe.collect()
    by_code = {fi.code: fi for fi in qe.analysis_findings}
    rt = by_code["UDF_HOST_ROUNDTRIP"]
    assert rt.detail["rows_bound"] == 100000
    assert rt.detail["batches_bound"] == 4
    assert rt.detail["bytes_bound"] > 0
    # a scalar UDF over a large scan earns the @pandas_udf nudge
    sc = by_code["UDF_SCALAR_LARGE_INPUT"]
    assert sc.severity == "info" and "pandas_udf" in sc.message
    kinds = {p["kind"]: p for p in qe.plan_predictions}
    assert kinds["udf_batches"]["predicted"] == 4
    assert kinds["udf_rows"]["predicted"] == 100000
    from spark_tpu.history import grade_predictions
    grades = grade_predictions(
        qe.plan_predictions,
        {"udf_batches": qe.udf_summary["batches"],
         "udf_rows": qe.udf_summary["rows"]})
    by_kind = {g["kind"]: g for g in grades}
    assert by_kind["udf_batches"]["grade"] == "hit"
    assert by_kind["udf_rows"]["grade"] == "hit"


def test_pandas_udf_not_flagged_scalar_large(session):
    session.conf.set(MODE_KEY, "worker")
    pdf = pd.DataFrame({"x": np.arange(100000, dtype="float64")})
    session.register_table("udfw_big2", pdf)

    @pandas_udf(returnType="double")
    def scaled(v: pd.Series) -> pd.Series:
        return v * 2.0

    qe = session.table("udfw_big2").select(
        scaled(col("x")).alias("y"))._qe()
    qe.collect()
    assert not any(fi.code == "UDF_SCALAR_LARGE_INPUT"
                   for fi in qe.analysis_findings)


def test_event_log_udf_record_and_prediction_report(session, tmp_path):
    from spark_tpu.history import prediction_report, read_event_log
    d = str(tmp_path / "events")
    session.conf.set("spark_tpu.sql.eventLog.dir", d)
    session.conf.set(MODE_KEY, "worker")
    session.conf.set(BATCH_KEY, 3)
    pdf = pd.DataFrame({"x": np.arange(10, dtype="float64")})
    session.register_table("udfw_ev", pdf)
    f = udf(lambda v: v + 1.0, "double")
    session.table("udfw_ev").select(f(col("x")).alias("y")).to_pandas()
    session.conf.set("spark_tpu.sql.eventLog.dir", "")
    events = read_event_log(d)
    u = events.iloc[-1]["udf"]
    assert u["mode"] == "worker" and u["batches"] == 4 and u["rows"] == 10
    assert events.iloc[-1]["schema_version"] == 7
    rep = prediction_report(events)
    udf_rows = rep[rep["kind"].isin(["udf_batches", "udf_rows"])] \
        if not rep.empty else rep
    assert len(udf_rows) == 2
    assert set(udf_rows["grade"]) == {"hit"}
    # the v5 record also passes the CI schema validator
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), os.pardir,
                      "scripts", "events_tool.py"), "validate", d],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# Service: UDF_ERROR surfaces, DELETE mid-UDF, concurrency/lockwatch
# ---------------------------------------------------------------------------


def _register_service_udfs(s):
    pdf = pd.DataFrame({
        "x": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
        "i": np.array([1, 2, 3, 4, 5, 6], dtype=np.int64)})
    s.register_table("svc_t", pdf)
    s.udf.register("twice", lambda v: v * 2.0, "double")

    def svc_boom(v):
        raise RuntimeError("svc udf exploded")

    s.udf.register("svc_boom", svc_boom, "double")

    def svc_slow(v):
        import time as _time
        _time.sleep(0.5)
        return v

    s.udf.register("svc_slow", svc_slow, "double")


@pytest.fixture()
def udf_service():
    def make(**conf_overrides):
        conf = Conf()
        conf.set(PORT_KEY, 0)
        for k, v in conf_overrides.items():
            conf.set(k, v)
        svc = SqlService(conf, init_session=_register_service_udfs)
        made.append(svc)
        return svc

    made = []
    yield make
    for svc in made:
        svc.stop()
    install_arbiter(None)


def _post_sql(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sql",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _http(port, method, path):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll_terminal(svc, rid, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        rec = svc.query_snapshot(rid)
        if rec and rec.get("status") not in ("submitted", "running"):
            return rec
        time.sleep(0.02)
    raise AssertionError(f"query {rid} never reached a terminal "
                         f"status: {svc.query_snapshot(rid)}")


def test_service_udf_error_structured_400(udf_service):
    svc = udf_service()
    svc.start()
    status, body = _post_sql(svc.port, {
        "sql": "select svc_boom(x) as y from svc_t",
        "conf": {MODE_KEY: "worker"}})
    assert status == 400
    assert body["error"] == "UDF_ERROR"
    assert "svc udf exploded" in body["message"]
    assert "svc_boom" in body.get("traceback", "")
    # the async record carries the same structured error
    status, body = _post_sql(svc.port, {
        "sql": "select svc_boom(x) as y from svc_t",
        "mode": "async", "conf": {MODE_KEY: "worker"}})
    assert status == 202
    rec = _poll_terminal(svc, body["query_id"])
    assert rec["status"] == "error"
    assert rec["error"]["error"] == "UDF_ERROR"
    assert "svc udf exploded" in rec["error"]["message"]
    assert "svc_boom" in rec["error"].get("traceback", "")


def test_service_delete_mid_udf_no_surviving_children(udf_service):
    svc = udf_service()
    svc.start()
    port = svc.port
    status, body = _post_sql(port, {
        "sql": "select svc_slow(x) as y from svc_t",
        "mode": "async",
        "conf": {MODE_KEY: "worker", BATCH_KEY: 1}})
    assert status == 202
    rid = body["query_id"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if svc.query_snapshot(rid).get("status") == "running":
            break
        time.sleep(0.01)
    time.sleep(0.3)  # let it get into the per-batch worker loop
    code, resp = _http(port, "DELETE", f"/queries/{rid}")
    assert code == 200 and resp["status"] == "cancel_requested"
    rec = _poll_terminal(svc, rid, timeout_s=20)
    assert rec["status"] == "cancelled", rec
    assert rec["error"]["error"] == "QUERY_CANCELLED"
    # zero surviving children across every pooled session
    deadline = time.monotonic() + 10
    sessions = [e.session for e in svc.pool._entries.values()]
    while time.monotonic() < deadline:
        if all(p.poll() is not None
               for s in sessions for p in s._udf_pool.child_procs()):
            break
        time.sleep(0.05)
    leaked = [p.pid for s in sessions
              for p in s._udf_pool.child_procs() if p.poll() is None]
    assert not leaked, f"workers survived the cancel: {leaked}"
    assert svc.arbiter.stats()["leased_bytes"] == 0
    # clean re-run of the same query succeeds with correct rows
    status, body = _post_sql(port, {
        "sql": "select svc_slow(x) as y from svc_t",
        "conf": {MODE_KEY: "worker", BATCH_KEY: 1}})
    assert status == 200
    assert [r["y"] for r in body["rows"]] == [1.0, 2.0, 3.0, 4.0,
                                              5.0, 6.0]


def test_service_concurrent_udf_queries_lockwatch(udf_service):
    svc = udf_service(**{"spark_tpu.service.maxConcurrent": 4})
    svc.start()
    port = svc.port
    watch = LockWatch()
    watch.install_service(svc)
    try:
        results = [None] * 6

        def run(ix):
            results[ix] = _post_sql(port, {
                "sql": "select twice(x) as y, i from svc_t",
                "session": f"s{ix % 2}",
                "conf": {MODE_KEY: "worker", BATCH_KEY: 2}})

        # two named sessions appear on first use: warm them, then
        # re-install so their pool cvs are wrapped too
        run(0), run(1)
        watch.install_service(svc)
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(2, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        for st, body in results:
            assert st == 200, body
            assert [r["y"] for r in body["rows"]] == \
                [2.0, 4.0, 6.0, 8.0, 10.0, 12.0]
        watch.assert_order_consistent()
        watch.assert_no_thread_leak()
    finally:
        watch.uninstall()
    # the udf pool cv showed up in the observed lock traffic
    assert any("udf.pool" in k for k in watch.lock_stats), \
        watch.report()

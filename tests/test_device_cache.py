"""Device-table cache: loaded scans reused across queries, staleness by
source identity stamps, LRU byte budget (round-4 perf work; reference:
CacheManager.scala + the UnifiedMemoryManager storage pool)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.io.device_cache import CACHE


@pytest.fixture(autouse=True)
def clear_cache():
    CACHE.clear()
    yield
    CACHE.clear()


def test_parquet_scan_cached_across_queries(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": np.arange(1000, dtype=np.int64) % 7,
                             "v": np.arange(1000, dtype=np.int64)}), p)
    q = lambda: (session.read_parquet(p).group_by(col("k"))
                 .agg(F.sum(col("v")).alias("s")).to_pandas()
                 .sort_values("k").reset_index(drop=True))
    first = q()
    h0, m0 = CACHE.hits, CACHE.misses
    second = q()
    assert CACHE.hits > h0  # warm run hit the device cache
    assert first.equals(second)


def test_parquet_rewrite_invalidates(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"v": np.arange(10, dtype=np.int64)}), p)
    s1 = session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert s1 == 45
    pq.write_table(pa.table({"v": np.arange(100, dtype=np.int64)}), p)
    s2 = session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert s2 == 4950  # (size, mtime) stamp changed -> cache miss


def test_reregister_table_not_stale(session):
    session.register_table("dc_t", pd.DataFrame(
        {"v": np.array([1, 2, 3], dtype=np.int64)}))
    a = session.table("dc_t").agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    session.register_table("dc_t", pd.DataFrame(
        {"v": np.array([10, 20], dtype=np.int64)}))
    b = session.table("dc_t").agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert (a, b) == (6, 30)  # fresh source token -> no stale hit


def test_budget_eviction(session, tmp_path):
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    try:
        session.conf.set(key_budget, 64 << 10)  # 64 KB
        paths = []
        for i in range(3):
            p = str(tmp_path / f"t{i}.parquet")
            pq.write_table(pa.table(
                {"v": np.arange(4000, dtype=np.int64) + i}), p)
            paths.append(p)
        for p in paths:  # each table ~32KB: the third load evicts the first
            session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
                .to_pandas()
        assert CACHE.nbytes <= 64 << 10
        assert len(CACHE._entries) < 3
    finally:
        session.conf.set(key_budget, prev)


def test_eviction_squeeze_recompute_parity(session, tmp_path):
    """Byte-budget squeeze: a budget holding ~1 of 3 tables churns the
    LRU across a query loop — every reload recomputes the evicted batch
    from source and results stay correct (evict-then-recompute parity),
    and the eviction counter proves the squeeze actually evicted."""
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    try:
        session.conf.set(key_budget, 48 << 10)  # each table is ~32KB
        paths, want = [], []
        for i in range(3):
            p = str(tmp_path / f"sq{i}.parquet")
            v = np.arange(4000, dtype=np.int64) + i
            pq.write_table(pa.table({"v": v}), p)
            paths.append(p)
            want.append(int(v.sum()))
        ev0 = CACHE.evictions
        for _round in range(3):
            for i, p in enumerate(paths):
                got = session.read_parquet(p).agg(
                    F.sum(col("v")).alias("s")).to_pandas()["s"][0]
                assert int(got) == want[i], (i, _round)
        assert CACHE.evictions > ev0  # budget pressure did evict
        assert CACHE.nbytes <= 48 << 10
    finally:
        session.conf.set(key_budget, prev)


def test_rewrite_detected_through_eviction_churn(session, tmp_path):
    """A parquet rewrite (same row count/byte size, fresh mtime) must
    miss the cache even while budget pressure is churning entries — the
    (size, mtime_ns) stamp is re-checked on every load, so an
    evict-reload cycle can never resurrect stale data."""
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    p = str(tmp_path / "target.parquet")
    other = str(tmp_path / "churn.parquet")

    def total(path):
        return int(session.read_parquet(path).agg(
            F.sum(col("v")).alias("s")).to_pandas()["s"][0])

    try:
        session.conf.set(key_budget, 48 << 10)
        pq.write_table(pa.table({"v": np.arange(1000, dtype=np.int64)}), p)
        pq.write_table(pa.table(
            {"v": np.arange(4000, dtype=np.int64)}), other)
        assert total(p) == sum(range(1000))
        total(other)  # churn: the big table evicts the target entry
        # rewrite with the SAME shape/size but shifted values: only the
        # mtime stamp distinguishes old from new
        pq.write_table(pa.table(
            {"v": np.arange(1000, dtype=np.int64) + 7}), p)
        assert total(p) == sum(range(1000)) + 7 * 1000
        # and a rewrite while the entry is STILL cached also misses
        pq.write_table(pa.table(
            {"v": np.arange(1000, dtype=np.int64) + 11}), p)
        assert total(p) == sum(range(1000)) + 11 * 1000
    finally:
        session.conf.set(key_budget, prev)


def test_cache_disabled_matches(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": np.arange(100, dtype=np.int64) % 3,
                             "v": np.arange(100, dtype=np.int64)}), p)
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    q = lambda: (session.read_parquet(p).group_by(col("k"))
                 .agg(F.count().alias("c")).to_pandas()
                 .sort_values("k").reset_index(drop=True))
    warm = q()
    try:
        session.conf.set(key_budget, 0)
        cold = q()
    finally:
        session.conf.set(key_budget, prev)
    assert warm.equals(cold)

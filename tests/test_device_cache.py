"""Device-table cache: loaded scans reused across queries, staleness by
source identity stamps, LRU byte budget (round-4 perf work; reference:
CacheManager.scala + the UnifiedMemoryManager storage pool)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.io.device_cache import CACHE


@pytest.fixture(autouse=True)
def clear_cache():
    CACHE.clear()
    yield
    CACHE.clear()


def test_parquet_scan_cached_across_queries(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": np.arange(1000, dtype=np.int64) % 7,
                             "v": np.arange(1000, dtype=np.int64)}), p)
    q = lambda: (session.read_parquet(p).group_by(col("k"))
                 .agg(F.sum(col("v")).alias("s")).to_pandas()
                 .sort_values("k").reset_index(drop=True))
    first = q()
    h0, m0 = CACHE.hits, CACHE.misses
    second = q()
    assert CACHE.hits > h0  # warm run hit the device cache
    assert first.equals(second)


def test_parquet_rewrite_invalidates(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"v": np.arange(10, dtype=np.int64)}), p)
    s1 = session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert s1 == 45
    pq.write_table(pa.table({"v": np.arange(100, dtype=np.int64)}), p)
    s2 = session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert s2 == 4950  # (size, mtime) stamp changed -> cache miss


def test_reregister_table_not_stale(session):
    session.register_table("dc_t", pd.DataFrame(
        {"v": np.array([1, 2, 3], dtype=np.int64)}))
    a = session.table("dc_t").agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    session.register_table("dc_t", pd.DataFrame(
        {"v": np.array([10, 20], dtype=np.int64)}))
    b = session.table("dc_t").agg(F.sum(col("v")).alias("s")) \
        .to_pandas()["s"][0]
    assert (a, b) == (6, 30)  # fresh source token -> no stale hit


def test_budget_eviction(session, tmp_path):
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    try:
        session.conf.set(key_budget, 64 << 10)  # 64 KB
        paths = []
        for i in range(3):
            p = str(tmp_path / f"t{i}.parquet")
            pq.write_table(pa.table(
                {"v": np.arange(4000, dtype=np.int64) + i}), p)
            paths.append(p)
        for p in paths:  # each table ~32KB: the third load evicts the first
            session.read_parquet(p).agg(F.sum(col("v")).alias("s")) \
                .to_pandas()
        assert CACHE.nbytes <= 64 << 10
        assert len(CACHE._entries) < 3
    finally:
        session.conf.set(key_budget, prev)


def test_cache_disabled_matches(session, tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": np.arange(100, dtype=np.int64) % 3,
                             "v": np.arange(100, dtype=np.int64)}), p)
    key_budget = "spark_tpu.sql.io.deviceCacheBytes"
    prev = session.conf.get(key_budget)
    q = lambda: (session.read_parquet(p).group_by(col("k"))
                 .agg(F.count().alias("c")).to_pandas()
                 .sort_values("k").reset_index(drop=True))
    warm = q()
    try:
        session.conf.set(key_budget, 0)
        cold = q()
    finally:
        session.conf.set(key_budget, prev)
    assert warm.equals(cold)

"""Write path (df.write.parquet) round-trips and the plan-fingerprint
data cache (reference: FileFormatWriter.scala, CacheManager.scala)."""

import decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit, to_date


@pytest.fixture()
def typed_table(session):
    tbl = pa.table({
        "i": pa.array([1, 2, 3, 4], type=pa.int64()),
        "d": pa.array([decimal.Decimal("1.25"), decimal.Decimal("-2.50"),
                       decimal.Decimal("3.75"), decimal.Decimal("0.00")],
                      type=pa.decimal128(10, 2)),
        "dt": pa.array([18000, 18001, None, 18003], type=pa.date32()),
        "s": pa.array(["aa", "bb", None, "aa"]),
        "f": pa.array([1.5, None, 3.5, 4.5], type=pa.float64()),
    })
    session.register_table("wt", tbl)
    return session, tbl


def test_write_read_round_trip(typed_table, tmp_path):
    session, tbl = typed_table
    path = str(tmp_path / "out")
    session.table("wt").write.parquet(path)
    got = session.read_parquet(path).to_pandas()
    want = tbl.to_pandas()
    assert got["i"].tolist() == want["i"].tolist()
    assert [str(x) for x in got["d"]] == [str(x) for x in want["d"]]
    assert got["s"].tolist() == want["s"].tolist()
    assert np.array_equal(got["f"].fillna(-1), want["f"].fillna(-1))
    assert got["dt"].astype(str).tolist() == want["dt"].astype(str).tolist()


def test_write_modes(typed_table, tmp_path):
    session, tbl = typed_table
    path = str(tmp_path / "modes")
    df = session.table("wt")
    df.write.parquet(path)
    with pytest.raises(FileExistsError):
        df.write.parquet(path)
    df.write.mode("ignore").parquet(path)
    assert len(session.read_parquet(path).to_pandas()) == 4
    df.write.mode("append").parquet(path)
    assert len(session.read_parquet(path).to_pandas()) == 8
    df.write.mode("overwrite").parquet(path)
    assert len(session.read_parquet(path).to_pandas()) == 4


def test_write_computed_frame(session, tmp_path):
    path = str(tmp_path / "computed")
    (session.range(100)
     .select((col("id") * 2).alias("x"))
     .filter(col("x") >= 100)
     .write.parquet(path))
    got = session.read_parquet(path).to_pandas()
    assert got["x"].tolist() == list(range(100, 200, 2))


def test_cache_hit_replaces_subtree(session):
    pdf = pd.DataFrame({"k": np.arange(20, dtype=np.int64) % 4,
                        "v": np.arange(20, dtype=np.int64)})
    session.register_table("ct", pdf)
    df = (session.table("ct").group_by(col("k"))
          .agg(F.sum(col("v")).alias("s")))
    df.cache()
    first = df.to_pandas().sort_values("k").reset_index(drop=True)
    # second run must plan against the cached scan, not the aggregate
    qe2 = df._qe()
    plan = qe2.optimized_plan.tree_string()
    assert "__cached__" in plan, plan
    second = df.to_pandas().sort_values("k").reset_index(drop=True)
    assert first.equals(second)
    # a LARGER query containing the cached subtree also uses it
    top = df.filter(col("s") > 10)
    assert "__cached__" in top._qe().optimized_plan.tree_string()
    df.unpersist()
    assert "__cached__" not in df._qe().optimized_plan.tree_string()

"""Set operations (INTERSECT/EXCEPT), grouping analytics (ROLLUP/CUBE/
GROUPING SETS), user accumulators, and transient-failure retry."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, udf


@pytest.fixture
def two_frames(session):
    l = pd.DataFrame({"k": [1, 2, None, 3], "s": ["a", "b", None, "c"]})
    r = pd.DataFrame({"k": [2, None, 4], "s": ["b", None, "d"]})
    session.register_table("so_l", l)
    session.register_table("so_r", r)
    return session.table("so_l"), session.table("so_r"), l, r


def test_intersect_with_nulls(two_frames):
    a, b, _, _ = two_frames
    out = a.intersect(b).to_pandas().sort_values(
        "k", na_position="last").reset_index(drop=True)
    assert out["k"].tolist()[0] == 2.0
    assert pd.isna(out["k"][1]) and pd.isna(out["s"][1])
    assert len(out) == 2  # NULL row matches NULL row


def test_except_and_subtract(two_frames):
    a, b, _, _ = two_frames
    out = a.except_(b).to_pandas().sort_values("k").reset_index(drop=True)
    assert out["k"].tolist() == [1.0, 3.0]
    assert a.subtract(b).to_pandas().shape == out.shape


def test_sql_intersect_except(session, two_frames):
    out = session.sql(
        "SELECT k FROM so_l INTERSECT SELECT k FROM so_r").to_pandas()
    got = sorted([x for x in out["k"] if not pd.isna(x)])
    assert got == [2.0] and out["k"].isna().sum() == 1
    out2 = session.sql(
        "SELECT k FROM so_l EXCEPT SELECT k FROM so_r").to_pandas()
    assert sorted(out2["k"].dropna()) == [1.0, 3.0]


def test_rollup_cube_grouping_sets(session):
    pdf = pd.DataFrame({"a": ["x", "x", "y", "y"], "b": [1, 2, 1, 2],
                        "v": [10.0, 20.0, 30.0, 40.0]})
    session.register_table("ga_t", pdf)
    roll = session.sql(
        "SELECT a, b, sum(v) AS s FROM ga_t GROUP BY ROLLUP(a, b) "
        "ORDER BY a, b").to_pandas()
    assert len(roll) == 7  # 4 leaves + 2 subtotals + 1 grand total
    grand = roll[roll["a"].isna() & roll["b"].isna()]
    assert grand["s"].tolist() == [100.0]
    sub_x = roll[(roll["a"] == "x") & roll["b"].isna()]
    assert sub_x["s"].tolist() == [30.0]

    cube = session.sql(
        "SELECT a, b, sum(v) AS s FROM ga_t GROUP BY CUBE(a, b) "
        "ORDER BY a, b, s").to_pandas()
    assert len(cube) == 9  # 4 + 2 + 2 + 1
    b_only = cube[cube["a"].isna() & (cube["b"] == 1)]
    assert b_only["s"].tolist() == [40.0]

    gs = session.sql(
        "SELECT a, sum(v) AS s FROM ga_t "
        "GROUP BY GROUPING SETS((a), ()) ORDER BY a").to_pandas()
    assert gs["s"].tolist() == [30.0, 70.0, 100.0][0:len(gs)] or \
        sorted(gs["s"]) == [30.0, 70.0, 100.0]


def test_null_group_keys_merge_after_union(session):
    """The set-op machinery exposed this engine bug: two NULL group keys
    with DIFFERENT dead payloads (e.g. post-union dictionary remap) must
    land in ONE group."""
    l = pd.DataFrame({"s": ["a", None], "v": [1.0, 2.0]})
    r = pd.DataFrame({"s": ["b", None], "v": [4.0, 8.0]})
    u = (session.create_dataframe(l, "ng_l")
         .union(session.create_dataframe(r, "ng_r")))
    out = (u.group_by(col("s")).agg(F.sum(col("v")).alias("sv"))
           .to_pandas())
    null_rows = out[out["s"].isna()]
    assert len(null_rows) == 1
    assert null_rows["sv"].tolist() == [10.0]


def test_user_accumulator_in_udf(session):
    acc = session.long_accumulator("nulls_seen")
    pdf = pd.DataFrame({"x": [1.0, None, 3.0, None]})
    session.register_table("acc_t", pdf)

    @udf(returnType="double")
    def watch(v):
        if v is None:
            acc.add(1)
            return None
        return v

    out = session.table("acc_t").select(watch(col("x")).alias("y")) \
        .to_pandas()
    assert acc.value == 2
    assert out["y"].isna().sum() == 2


def test_transient_failure_retries(session, monkeypatch):
    """A transient (remote-compile-style) stage failure retries with a
    fresh compile instead of surfacing (maxTaskFailures seat)."""
    from spark_tpu.execution.executor import QueryExecution

    calls = {"n": 0}
    orig = QueryExecution._compile_stage

    def flaky(self, root, mesh=None, args=None):
        fn = orig(self, root, mesh, args)
        def wrapper(*a, **k):
            if calls["n"] == 0:
                calls["n"] += 1
                raise RuntimeError(
                    "INTERNAL: remote_compile: HTTP 500 (simulated)")
            return fn(*a, **k)
        return wrapper

    monkeypatch.setattr(QueryExecution, "_compile_stage", flaky)
    with pytest.warns(UserWarning, match="transient stage failure"):
        out = session.range(100).agg(F.sum(col("id")).alias("s")) \
            .to_pandas()
    assert int(out["s"][0]) == 4950
    assert calls["n"] == 1


def test_intersect_binds_tighter_than_union(session):
    """Code-review r5: standard SQL precedence — INTERSECT before
    UNION. A UNION ALL B INTERSECT C == A UNION ALL (B INTERSECT C)."""
    session.register_table("p1", pd.DataFrame({"a": [1, 2, 2]}))
    session.register_table("p2", pd.DataFrame({"a": [2, 5]}))
    session.register_table("p3", pd.DataFrame({"a": [1, 5]}))
    out = session.sql(
        "SELECT a FROM p1 UNION ALL SELECT a FROM p2 "
        "INTERSECT SELECT a FROM p3").to_pandas()
    assert sorted(out["a"].tolist()) == [1, 2, 2, 5]


def test_rollup_with_qualified_ref_and_bare_grouping_set(session):
    session.register_table("q1t", pd.DataFrame({"a": [1, 2, 2]}))
    out = session.sql(
        "SELECT q1t.a, count(*) AS c FROM q1t GROUP BY ROLLUP(a) "
        "ORDER BY a").to_pandas()
    assert out["c"].tolist() == [3, 1, 2]
    out2 = session.sql(
        "SELECT a, sum(a) AS s FROM q1t "
        "GROUP BY GROUPING SETS (a, ()) ORDER BY a").to_pandas()
    assert out2["s"].tolist() == [5, 1, 4]


def test_except_all_clear_error(session, two_frames):
    a, b, _, _ = two_frames
    from spark_tpu.expr import AnalysisError
    with pytest.raises(AnalysisError, match="EXCEPT ALL"):
        a.exceptAll(b)

"""Status store, latency histograms/SLO, and the flight recorder:
Histogram metric semantics + Prometheus round-trip, the listener-fed
status store (fold-in, ring bounds, heartbeat lifecycle), latency/SLO
burn accounting at query end, crash-time flight-recorder bundles
(injected fatal + on-demand), live `/status` + `/status/timeseries`
under a concurrent service with lockwatch, and the offline replay
views (history.status_summary, events_tool stats)."""

import glob
import json
import os
import threading

import pandas as pd
import pytest

from spark_tpu import Conf, history
from spark_tpu import functions as F
from spark_tpu.functions import col
from spark_tpu.observability.flight_recorder import FlightRecorder
from spark_tpu.observability.metrics import (Histogram, MetricsRegistry,
                                             parse_prometheus_text,
                                             prometheus_text)
from spark_tpu.observability.status_store import StatusStore
from spark_tpu.testing import faults
from spark_tpu.testing.lockwatch import LockWatch

EVENT_KEY = "spark_tpu.sql.eventLog.dir"
SLO_KEY = "spark_tpu.service.slo.latencyMs"
STATUS_RING_KEY = "spark_tpu.sql.status.ringSize"
HEARTBEAT_KEY = "spark_tpu.sql.status.heartbeatMs"
STATUS_ON_KEY = "spark_tpu.sql.status.enabled"
FR_ON_KEY = "spark_tpu.sql.flightRecorder.enabled"
FR_DIR_KEY = "spark_tpu.sql.flightRecorder.dir"
FR_RING_KEY = "spark_tpu.sql.flightRecorder.ringSize"


def _fresh_agg(session, n):
    """A plan unlikely to be stage-cached already (n varies per test)."""
    return (session.range(n)
            .group_by((col("id") % 7).alias("k"))
            .agg(F.sum(col("id")).alias("s")))


# -- Histogram metric type ---------------------------------------------------

def test_histogram_counts_sum_and_percentiles():
    h = Histogram()
    for v in (1.0, 2.0, 4.0, 8.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(115.0)
    assert snap["min"] == 1.0 and snap["max"] == 100.0
    # one slot per bound + overflow, totals preserved
    assert len(snap["counts"]) == len(snap["bounds"]) + 1
    assert sum(snap["counts"]) == 5
    p = h.percentiles()
    assert p["p50"] <= p["p95"] <= p["p99"]
    # quantile estimates are clamped to the observed range
    assert snap["min"] <= p["p50"] and p["p99"] <= snap["max"]


def test_histogram_empty_and_overflow():
    h = Histogram()
    assert h.quantile(0.99) == 0.0
    assert h.snapshot()["min"] == 0.0
    big = Histogram.DEFAULT_BOUNDS[-1] * 4  # beyond the last bound
    h.observe(big)
    snap = h.snapshot()
    assert snap["counts"][-1] == 1  # overflow bucket
    assert h.quantile(0.99) == big  # clamped to max_v, not a bound


def test_histogram_concurrent_observe():
    h = Histogram()

    def hammer():
        for i in range(500):
            h.observe(float(i % 32) + 0.5)

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    [t.start() for t in ts]
    [t.join(30) for t in ts]
    snap = h.snapshot()
    assert snap["count"] == 2000
    assert sum(snap["counts"]) == 2000


# -- Prometheus exposition round-trip ----------------------------------------

def test_prometheus_histogram_round_trip():
    reg = MetricsRegistry()
    reg.counter("status_heartbeats").inc(3)
    reg.gauge("status_queries_inflight").set(2)
    h = reg.histogram("status_latency_ms")
    for v in (0.5, 3.0, 3.0, 900.0):
        h.observe(v)
    text = prometheus_text(reg.snapshot())
    parsed = parse_prometheus_text(text)  # every line must round-trip
    assert parsed["spark_tpu_status_heartbeats"] == 3
    assert parsed["spark_tpu_status_queries_inflight"] == 2
    assert parsed["spark_tpu_status_latency_ms_count"] == 4
    assert parsed["spark_tpu_status_latency_ms_sum"] == \
        pytest.approx(906.5)
    buckets = {k: v for k, v in parsed.items()
               if k.startswith("spark_tpu_status_latency_ms_bucket")}
    assert buckets['spark_tpu_status_latency_ms_bucket{le="+Inf"}'] == 4
    # cumulative and monotone in bound order
    assert buckets['spark_tpu_status_latency_ms_bucket{le="0.5"}'] == 1
    assert buckets['spark_tpu_status_latency_ms_bucket{le="4"}'] == 3
    ordered = [buckets[f'spark_tpu_status_latency_ms_bucket{{le="{b:g}"}}']
               for b in Histogram.DEFAULT_BOUNDS]
    assert ordered == sorted(ordered)


def test_prometheus_timer_summary_round_trip():
    reg = MetricsRegistry()
    t = reg.timer("udf_exec_ms")
    t.observe(0.25)
    t.observe(0.75)
    parsed = parse_prometheus_text(prometheus_text(reg.snapshot()))
    assert parsed["spark_tpu_udf_exec_ms_seconds_count"] == 2
    assert parsed["spark_tpu_udf_exec_ms_seconds_sum"] == \
        pytest.approx(1.0)
    # legacy pair still present for existing scrapers
    assert parsed["spark_tpu_udf_exec_ms_count"] == 2
    assert parsed["spark_tpu_udf_exec_ms_seconds_total"] == \
        pytest.approx(1.0)


# -- StatusStore: fold-in, rings, heartbeat lifecycle ------------------------

def _fresh_store(providers=None, ring=4, enabled=True):
    conf = Conf()
    conf.set(STATUS_RING_KEY, ring)
    if not enabled:
        conf.set(STATUS_ON_KEY, False)
    return StatusStore(conf, MetricsRegistry(), providers), conf


def test_status_store_listener_fold_in(session):
    store = StatusStore(session.conf, session.metrics)
    feed = store.bind(session, "t0")
    try:
        _fresh_agg(session, 771771).to_pandas()
    finally:
        session.remove_listener(feed)
    snap = store.snapshot()
    assert snap["enabled"] is True
    assert snap["queries_total"] >= 1
    assert snap["statuses"].get("ok", 0) >= 1
    assert snap["queries_inflight"]["t0"] == 0
    assert snap["sessions"]["t0"]["ok"] >= 1
    # per-phase cumulative seconds folded from the end event
    assert snap["phase_seconds"], snap
    assert session.metrics.gauge("status_queries_inflight").value == 0


def test_status_store_ring_capacity_bound():
    ticks = {"n": 0}

    def prov():
        ticks["n"] += 1
        return {"depth": ticks["n"], "skipped": "text"}

    store, _ = _fresh_store({"q": prov}, ring=4)
    for _ in range(11):
        store.sample()
    ts = store.timeseries()
    assert ts["heartbeats"] == 11
    assert ts["ring_capacity"] == 4
    pts = ts["series"]["q_depth"]
    assert len(pts) == 4  # bounded: 11 samples, ring keeps the last 4
    assert [v for _, v in pts] == [8.0, 9.0, 10.0, 11.0]
    assert "q_skipped" not in ts["series"]  # non-numeric leaves dropped
    # names/limit filters
    ts2 = store.timeseries(names=["q_depth"], limit=2)
    assert list(ts2["series"]) == ["q_depth"]
    assert len(ts2["series"]["q_depth"]) == 2


def test_status_store_provider_failure_isolated():
    def bad():
        raise RuntimeError("provider down")

    store, _ = _fresh_store({"bad": bad, "ok": lambda: {"x": 1}})
    vals = store.sample()
    assert vals["ok_x"] == 1.0  # the healthy provider still sampled
    snap = store.snapshot()
    assert "error" in snap["providers"]["bad"]
    assert snap["providers"]["ok"] == {"x": 1}


def test_status_store_heartbeat_joins_on_stop():
    store, conf = _fresh_store({"p": lambda: {"v": 1}})
    conf.set(HEARTBEAT_KEY, 20)
    store.start()
    try:
        assert any(t.name == "spark-tpu-status-heartbeat"
                   for t in threading.enumerate())
        deadline = 200
        while store.snapshot()["heartbeats"] < 2 and deadline:
            threading.Event().wait(0.02)
            deadline -= 1
        assert store.snapshot()["heartbeats"] >= 2
    finally:
        store.stop()
    LockWatch().assert_no_thread_leak(
        prefix="spark-tpu-status-heartbeat", timeout_s=5.0)
    store.stop()  # idempotent


def test_status_store_disabled_is_inert():
    store, _ = _fresh_store(enabled=False)
    store.start()
    assert store._thread is None  # no heartbeat thread spawned
    assert store.snapshot()["enabled"] is False


# -- latency histograms + SLO burn at query end ------------------------------

def test_latency_histograms_and_slo_burn(session, tmp_path):
    m = session.metrics
    lat0 = m.histogram("status_latency_ms").snapshot()["count"]
    slo0 = m.counter("slo_queries_total").value
    burn0 = m.counter("slo_burned_total").value
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    session.conf.set(SLO_KEY, 1)  # 1 ms target: a fresh agg burns it
    try:
        _fresh_agg(session, 772772).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
        session.conf.set(SLO_KEY, 0)
    assert m.histogram("status_latency_ms").snapshot()["count"] > lat0
    # per-phase and per-class histograms fed alongside
    names = m.histogram_names()
    assert any(n.startswith("status_phase_ms_") for n in names), names
    assert any(n.startswith("status_class_ms_") for n in names), names
    assert m.counter("slo_queries_total").value > slo0
    assert m.counter("slo_burned_total").value > burn0
    assert m.counter("slo_burn_ms_total").value >= 1


def test_slo_disabled_by_default(session, tmp_path):
    slo0 = session.metrics.counter("slo_queries_total").value
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    try:
        _fresh_agg(session, 773773).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    # no target set -> latency histograms still fill, burn counters idle
    assert session.metrics.counter("slo_queries_total").value == slo0


# -- flight recorder ---------------------------------------------------------

def test_flightrec_installed_and_rings_fill(session, tmp_path):
    rec = FlightRecorder.of(session)
    assert rec is not None  # installed by default on every session
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))  # events on
    try:
        _fresh_agg(session, 774774).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    with rec._lock:
        kinds = {r["kind"] for r in rec._rings["query"]}
    assert {"start", "end"} <= kinds


def test_flightrec_bundle_on_injected_fatal(session, tmp_path):
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    session.conf.set(FR_DIR_KEY, str(tmp_path / "fr"))
    try:
        _fresh_agg(session, 775001).to_pandas()  # a healthy query first
        with faults.inject(session.conf, "stage_run:fatal:1"):
            with pytest.raises(faults.FaultInjected):
                _fresh_agg(session, 775775).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
        session.conf.set(FR_DIR_KEY, "")
    bundles = glob.glob(str(tmp_path / "fr" / "bundle-*"))
    assert len(bundles) == 1, bundles
    b = bundles[0]
    manifest = json.load(open(os.path.join(b, "MANIFEST.json")))
    assert manifest["bundle_version"] == 1
    assert manifest["reason"] == "fatal"
    assert "FaultInjected" in manifest["error"]
    assert manifest["extra"]["plan"]
    for fname in manifest["files"]:
        assert os.path.exists(os.path.join(b, fname)), fname
    rings = [json.loads(line)
             for line in open(os.path.join(b, "rings.jsonl"))]
    assert {"query", "stage"} <= {r["subsystem"] for r in rings}
    spans = json.load(open(os.path.join(b, "spans.json")))
    assert any(spans["spans"].values())  # the healthy query's spans
    conf_snap = json.load(open(os.path.join(b, "conf.json")))
    assert FR_DIR_KEY in conf_snap["explicitly_set"]
    assert conf_snap["effective"][FR_ON_KEY] is True
    threads_txt = open(os.path.join(b, "threads.txt")).read()
    assert "MainThread" in threads_txt
    tail = [json.loads(line) for line in
            open(os.path.join(b, "eventlog_tail.jsonl"))]
    assert tail and all("schema_version" in e for e in tail)
    metrics_snap = json.load(open(os.path.join(b, "metrics.json")))
    assert "counters" in metrics_snap


def test_flightrec_results_identical_on_vs_off(session, tmp_path):
    session.conf.set(FR_DIR_KEY, str(tmp_path / "fr"))
    try:
        on = _fresh_agg(session, 776776).to_pandas()
        session.conf.set(FR_ON_KEY, False)
        off = _fresh_agg(session, 776776).to_pandas()
    finally:
        session.conf.set(FR_ON_KEY, True)
        session.conf.set(FR_DIR_KEY, "")
    pd.testing.assert_frame_equal(on, off)  # byte-identical
    # a healthy run never dumps a bundle on its own
    assert glob.glob(str(tmp_path / "fr" / "bundle-*")) == []


def test_flightrec_disabled_dump_returns_none(session):
    rec = FlightRecorder.of(session)
    session.conf.set(FR_ON_KEY, False)
    try:
        assert rec.dump("test") is None
    finally:
        session.conf.set(FR_ON_KEY, True)


def test_flightrec_on_demand_dump(session, tmp_path):
    rec = FlightRecorder.of(session)
    session.conf.set(FR_DIR_KEY, str(tmp_path / "fr"))
    try:
        path = rec.dump("on_demand", extra={"who": "test"})
    finally:
        session.conf.set(FR_DIR_KEY, "")
    assert path and os.path.isdir(path)
    manifest = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert manifest["reason"] == "on_demand"
    assert manifest["extra"] == {"who": "test"}
    assert manifest["error"] is None
    assert session.metrics.counter("flightrec_bundles").value >= 1


def test_flightrec_ring_bounded(session, tmp_path):
    session.conf.set(FR_RING_KEY, 8)
    session.conf.set(EVENT_KEY, str(tmp_path / "ev"))
    rec = FlightRecorder(session)  # fresh: rings created at cap 8
    session.add_listener(rec)
    try:
        for i in range(6):  # 6 starts + 6 ends = 12 records > 8
            _fresh_agg(session, 777100 + i).to_pandas()
    finally:
        session.remove_listener(rec)
        session.conf.set(EVENT_KEY, "")
        session.conf.set(FR_RING_KEY, 256)
    with rec._lock:
        assert len(rec._rings["query"]) == 8  # bounded, newest kept
        assert all(len(d) <= 8 for d in rec._rings.values())


# -- live service: /status, /status/timeseries, /debug/bundle ----------------

@pytest.fixture(scope="module")
def status_tpch_path(tmp_path_factory):
    from spark_tpu.tpch.datagen import write_parquet
    path = str(tmp_path_factory.mktemp("tpch_status") / "sf")
    write_parquet(path, 0.001)
    return path


def test_status_under_concurrent_service(status_tpch_path, tmp_path):
    import urllib.request

    from spark_tpu.service.arbiter import install_arbiter
    from spark_tpu.service.server import SqlService
    from spark_tpu.tpch import queries as Q
    from spark_tpu.tpch import sql_queries as SQLQ

    sessions = ["s1", "s2", "s3"]
    conf = Conf()
    conf.set("spark_tpu.service.port", 0)
    conf.set("spark_tpu.service.hbmBudget", 1 << 30)
    conf.set(HEARTBEAT_KEY, 25)
    conf.set(FR_DIR_KEY, str(tmp_path / "fr"))
    svc = SqlService(
        conf, init_session=lambda s: Q.register_tables(
            s, status_tpch_path)).start()
    watch = LockWatch()
    scrapes = []
    try:
        for name in sessions:  # warm the pool, then watch it
            svc.submit(SQLQ.Q1, session=name)
        watch.install_service(svc)

        results, errors = [], []
        stop_scrape = threading.Event()

        def run_queries(name):
            try:
                for _ in range(2):
                    results.append(svc.submit(SQLQ.Q1, session=name)[1])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((name, repr(e)))

        def scrape():
            while not stop_scrape.is_set():
                st = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{svc.port}/status", timeout=30))
                ts = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{svc.port}/status/timeseries"
                    f"?limit=5", timeout=30))
                scrapes.append((st, ts))
                threading.Event().wait(0.02)

        scraper = threading.Thread(target=scrape, daemon=True)
        scraper.start()
        threads = [threading.Thread(target=run_queries, args=(n,))
                   for n in sessions]
        [t.start() for t in threads]
        [t.join(300) for t in threads]
        stop_scrape.set()
        scraper.join(30)
        assert not any(t.is_alive() for t in threads), "query wedged"
        assert errors == [], errors
        assert len(results) == 6

        st = svc.status_store.snapshot()
        assert st["queries_total"] >= 9  # 3 warm + 6 concurrent
        assert st["statuses"].get("ok", 0) >= 9
        assert st["queries_inflight_total"] == 0
        assert set(sessions) <= set(st["sessions"])
        lat = st["latency"]["e2e_ms"]
        assert lat["count"] >= 9 and lat["p50"] <= lat["p95"]
        for prov in ("admission", "quota", "arbiter", "pool", "udf"):
            assert prov in st["providers"], st["providers"]
        # every live scrape parsed; rings bounded on every series
        assert scrapes, "scraper never ran"
        for st_s, ts_s in scrapes:
            assert st_s["enabled"] is True
            for pts in ts_s["series"].values():
                assert len(pts) <= 5  # limit honored
        # the heartbeat actually sampled while queries ran
        ts_all = svc.status_store.timeseries()
        assert ts_all["heartbeats"] >= 1
        for pts in ts_all["series"].values():
            assert len(pts) <= ts_all["ring_capacity"]

        # on-demand bundle over HTTP, one per pooled session
        db = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/debug/bundle", timeout=60))
        assert len(db["bundles"]) >= len(sessions)
        for entry in db["bundles"]:
            manifest = json.load(open(os.path.join(
                entry["path"], "MANIFEST.json")))
            assert manifest["reason"] == "on_demand"

        watch.assert_order_consistent()
    finally:
        watch.uninstall()
        svc.stop()
        install_arbiter(None)
    # stop() joined the heartbeat: no status thread may survive
    LockWatch().assert_no_thread_leak(
        prefix="spark-tpu-status-heartbeat", timeout_s=5.0)


def test_status_timeseries_bad_limit_is_400(status_tpch_path):
    import urllib.error
    import urllib.request

    from spark_tpu.service.arbiter import install_arbiter
    from spark_tpu.service.server import SqlService
    from spark_tpu.tpch import queries as Q

    conf = Conf()
    conf.set("spark_tpu.service.port", 0)
    svc = SqlService(
        conf, init_session=lambda s: Q.register_tables(
            s, status_tpch_path)).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{svc.port}/status/timeseries"
                f"?limit=bogus", timeout=30)
        assert ei.value.code == 400
    finally:
        svc.stop()
        install_arbiter(None)


# -- offline replay: history.status_summary + events_tool stats --------------

def _events_tool():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "events_tool", os.path.join(root, "scripts", "events_tool.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_history_status_summary_and_stats(session, tmp_path):
    log_dir = str(tmp_path / "ev")
    session.conf.set(EVENT_KEY, log_dir)
    try:
        _fresh_agg(session, 778778).to_pandas()
        _fresh_agg(session, 779779).to_pandas()
    finally:
        session.conf.set(EVENT_KEY, "")
    events = history.read_event_log(log_dir)
    summ = history.status_summary(events)
    assert len(summ) == 1  # one app
    row = summ.iloc[0]
    assert row["queries"] == 2 and row["n_ok"] == 2
    assert row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]
    assert row["p99_ms"] > 0
    assert any(c.startswith("total_") for c in summ.columns)

    tool = _events_tool()
    lines = tool.stats([log_dir])
    text = "\n".join(lines)
    assert "records: 2" in text
    assert "ok=2" in text
    assert "schema versions: v7=2" in text
    assert "time span:" in text
    assert tool.main(["stats", log_dir]) == 0
    # empty target still prints a sane summary
    empty = tmp_path / "none"
    empty.mkdir()
    assert "records: 0" in "\n".join(tool.stats([str(empty)]))

"""Golden-file SQL harness (reference: SQLQueryTestSuite.scala:124):
every ``tests/sql/*.sql`` statement runs against fixed tables and its
formatted output is compared to the committed ``*.sql.out`` golden —
under a CONF MATRIX (mesh 0/8 x aggregate kernelMode auto/scatter), the
reference's codegen-on/off x AQE-on/off trait pattern.

Regenerate goldens with ``SPARK_TPU_GENERATE_GOLDEN=1 pytest
tests/test_sql_golden.py`` after an intended semantic change.
"""

import glob
import os

import numpy as np
import pandas as pd
import pytest

SQL_DIR = os.path.join(os.path.dirname(__file__), "sql")
MESH = "spark_tpu.sql.mesh.size"
KERN = "spark_tpu.sql.aggregate.kernelMode"

CONF_MATRIX = [
    {MESH: 0, KERN: "auto"},
    {MESH: 8, KERN: "auto"},
    {MESH: 0, KERN: "scatter"},
    {MESH: 8, KERN: "scatter"},
]


@pytest.fixture(scope="module")
def golden_session(session):
    rs = np.random.RandomState(21)
    n = 64
    session.register_table("golden_t", pd.DataFrame({
        "k": (np.arange(n) % 4).astype(np.int64),
        "v": rs.randint(0, 40, n).astype(np.int64),
        "s": rs.choice(["ab", "cd", "ef"], n)}))
    session.register_table("golden_dim", pd.DataFrame({
        "k": np.arange(4, dtype=np.int64),
        "name": ["zero", "one", "two", "three"]}))
    return session


def _fmt(df: pd.DataFrame) -> str:
    """Stable text rendering (schema line + rows)."""
    lines = ["\t".join(df.columns)]
    for _, row in df.iterrows():
        cells = []
        for x in row:
            if pd.isna(x):
                cells.append("NULL")
            elif isinstance(x, float):
                cells.append(f"{x:.6g}")
            else:
                cells.append(str(x))
        lines.append("\t".join(cells))
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("sql_path", sorted(
    glob.glob(os.path.join(SQL_DIR, "*.sql"))),
    ids=lambda p: os.path.basename(p)[:-4])
def test_sql_golden(golden_session, sql_path):
    session = golden_session
    query = open(sql_path).read()
    golden_path = sql_path + ".out"
    outputs = {}
    old = {k: session.conf.get(k) for k in (MESH, KERN)}
    try:
        for conf in CONF_MATRIX:
            for k, v in conf.items():
                session.conf.set(k, v)
            got = _fmt(session.sql(query).to_pandas())
            outputs[tuple(conf.values())] = got
    finally:
        for k, v in old.items():
            session.conf.set(k, v)
    # every conf combination must agree with each other first
    distinct = set(outputs.values())
    assert len(distinct) == 1, \
        f"conf matrix disagreement for {sql_path}: {outputs}"
    got = distinct.pop()
    if os.environ.get("SPARK_TPU_GENERATE_GOLDEN"):
        with open(golden_path, "w") as f:
            f.write(got)
    assert os.path.exists(golden_path), \
        f"missing golden {golden_path}; run with " \
        f"SPARK_TPU_GENERATE_GOLDEN=1"
    want = open(golden_path).read()
    assert got == want, f"golden mismatch for {sql_path}"

"""Round-3 ADVICE fixes: NOT IN null-aware anti-join semantics and
statement-scoped CTE caching with re-register invalidation."""

import numpy as np
import pandas as pd
import pytest


def _t(session, name, **cols):
    session.register_table(name, pd.DataFrame(cols))


def test_not_in_null_in_subquery_empties_result(session):
    _t(session, "na_t", x=np.array([1, 2, 3], dtype=np.int64))
    _t(session, "na_s", y=pd.array([1, None], dtype="Int64"))
    out = session.sql(
        "SELECT x FROM na_t WHERE x NOT IN (SELECT y FROM na_s)"
    ).to_pandas()
    assert len(out) == 0  # NULL in the subquery -> three-valued UNKNOWN


def test_not_in_empty_subquery_keeps_all(session):
    _t(session, "na_t2", x=pd.array([1, None, 3], dtype="Int64"))
    _t(session, "na_s2", y=np.array([99], dtype=np.int64))
    out = session.sql(
        "SELECT x FROM na_t2 WHERE x NOT IN "
        "(SELECT y FROM na_s2 WHERE y < 0)").to_pandas()
    # empty subquery: NOT IN is TRUE for every row, even NULL x
    assert len(out) == 3


def test_not_in_null_probe_dropped(session):
    _t(session, "na_t3", x=pd.array([1, None, 5], dtype="Int64"))
    _t(session, "na_s3", y=np.array([1, 2], dtype=np.int64))
    out = session.sql(
        "SELECT x FROM na_t3 WHERE x NOT IN (SELECT y FROM na_s3)"
    ).to_pandas()
    assert out["x"].tolist() == [5]  # NULL probe is UNKNOWN, dropped


def test_not_in_plain_still_works(session):
    _t(session, "na_t4", x=np.array([1, 2, 3], dtype=np.int64))
    _t(session, "na_s4", y=np.array([2], dtype=np.int64))
    out = session.sql(
        "SELECT x FROM na_t4 WHERE x NOT IN (SELECT y FROM na_s4)"
    ).to_pandas()
    assert sorted(out["x"].tolist()) == [1, 3]


def test_not_in_null_aware_mesh_parity(session):
    mesh_key = "spark_tpu.sql.mesh.size"
    _t(session, "na_t5",
       x=pd.array([1, None, 5, 7, 8], dtype="Int64"))
    _t(session, "na_s5", y=np.array([1, 7], dtype=np.int64))
    q = ("SELECT x FROM na_t5 WHERE x NOT IN (SELECT y FROM na_s5)")
    want = sorted(session.sql(q).to_pandas()["x"].tolist())
    try:
        session.conf.set(mesh_key, 8)
        got = sorted(session.sql(q).to_pandas()["x"].tolist())
    finally:
        session.conf.set(mesh_key, 0)
    assert got == want == [5, 8]


def test_not_in_null_aware_survives_scalar_subquery(session):
    """Round-4 review: map_expressions (run when a scalar subquery is
    present) rebuilt Joins without the null_aware flag, silently
    reverting NOT IN to plain anti-join."""
    _t(session, "na_t6", x=np.array([1, 2, 3], dtype=np.int64))
    _t(session, "na_s6", y=pd.array([1, None], dtype="Int64"))
    out = session.sql(
        "SELECT x FROM na_t6 WHERE x > (SELECT min(y) FROM na_s6) "
        "AND x NOT IN (SELECT y FROM na_s6)").to_pandas()
    assert len(out) == 0  # NULL in the NOT IN subquery: zero rows


def test_reregister_invalidates_cte_cache(session):
    """Round-3 ADVICE medium: the session plan-fingerprint cache kept
    CTE materializations keyed only by table NAME; re-registering and
    re-running a WITH query returned stale results."""
    _t(session, "cc_t", v=np.array([1, 2, 3], dtype=np.int64))
    q = ("WITH s AS (SELECT sum(v) AS sv FROM cc_t) "
         "SELECT sv FROM s")
    assert session.sql(q).to_pandas()["sv"][0] == 6
    _t(session, "cc_t", v=np.array([10, 20], dtype=np.int64))
    assert session.sql(q).to_pandas()["sv"][0] == 30


def test_implicit_cte_data_evicted(session):
    """WITH-clause materializations are statement-scoped: materialized
    DATA does not accumulate in the session after execution (the
    requests/marks stay so re-execution still dedupes)."""
    _t(session, "ev_t", v=np.array([1, 2], dtype=np.int64))
    q = ("WITH s AS (SELECT v + 1 AS w FROM ev_t) "
         "SELECT sum(w) AS sw FROM s")
    before_data = len(session._data_cache)
    assert session.sql(q).to_pandas()["sw"][0] == 5
    assert len(session._data_cache) == before_data

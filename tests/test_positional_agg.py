"""Positional aggregates: percentile/median (exact, interpolated) and
collect_list/collect_set (array outputs) — reference
ApproximatePercentile.scala:1, Percentile.scala, collect.scala."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col


@pytest.fixture
def pdf(session):
    rs = np.random.RandomState(9)
    d = pd.DataFrame({
        "g": rs.randint(0, 5, 200).astype(np.int64),
        "v": rs.randn(200),
        "i": rs.randint(0, 10, 200).astype(np.int64),
        "s": rs.choice(["aa", "bb", "cc"], 200)})
    d.loc[::17, "v"] = np.nan  # NULLs must be ignored
    session.register_table("pos_t", d)
    return d


def test_percentile_median_parity_with_pandas(session, pdf):
    out = (session.table("pos_t").group_by(col("g")).agg(
        F.percentile(col("v"), 0.25).alias("p25"),
        F.median(col("v")).alias("med"),
        F.count().alias("c"),
    ).to_pandas().sort_values("g").reset_index(drop=True))
    want = pdf.groupby("g").agg(
        p25=("v", lambda s: s.quantile(0.25)),
        med=("v", "median"), c=("v", "size")).reset_index()
    assert out["g"].tolist() == want["g"].tolist()
    assert np.allclose(out["p25"], want["p25"])
    assert np.allclose(out["med"], want["med"])
    assert out["c"].tolist() == want["c"].tolist()


def test_global_median_and_sql(session, pdf):
    out = session.sql(
        "SELECT median(v) AS m, percentile(v, 0.9) AS p "
        "FROM pos_t").to_pandas()
    assert np.isclose(out["m"][0], pdf["v"].median())
    assert np.isclose(out["p"][0], pdf["v"].quantile(0.9))


def test_collect_list_and_set(session, pdf):
    out = (session.table("pos_t").group_by(col("g")).agg(
        F.collect_list(col("i")).alias("li"),
        F.collect_set(col("i")).alias("se"),
    ).to_pandas().sort_values("g").reset_index(drop=True))
    for _, row in out.iterrows():
        grp = pdf[pdf["g"] == row["g"]]["i"]
        assert sorted(row["li"]) == sorted(grp.tolist())
        assert sorted(row["se"]) == sorted(set(grp.tolist()))


def test_collect_list_strings(session, pdf):
    out = (session.table("pos_t").group_by(col("g")).agg(
        F.collect_set(col("s")).alias("ss"),
    ).to_pandas().sort_values("g").reset_index(drop=True))
    for _, row in out.iterrows():
        grp = set(pdf[pdf["g"] == row["g"]]["s"])
        assert sorted(row["ss"]) == sorted(grp)


def test_collect_then_explode_roundtrip(session, pdf):
    n = (session.table("pos_t").group_by(col("g"))
         .agg(F.collect_list(col("i")).alias("li"))
         .select(F.explode(col("li")).alias("e"))
         .agg(F.count().alias("c")).to_pandas())
    assert int(n["c"][0]) == len(pdf)


def test_positional_on_mesh(session, pdf):
    build = lambda: (session.table("pos_t").group_by(col("g")).agg(
        F.median(col("v")).alias("m")).to_pandas()
        .sort_values("g").reset_index(drop=True))
    want = build()
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        got = build()
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert np.allclose(got["m"], want["m"])


def test_mixed_with_regular_aggs_and_sql_collect(session, pdf):
    out = session.sql(
        "SELECT g, sum(i) AS si, median(v) AS m, collect_set(i) AS cs "
        "FROM pos_t GROUP BY g ORDER BY g").to_pandas()
    want = pdf.groupby("g").agg(si=("i", "sum"),
                                m=("v", "median")).reset_index()
    assert out["si"].tolist() == want["si"].tolist()
    assert np.allclose(out["m"], want["m"])
    for _, row in out.iterrows():
        grp = set(pdf[pdf["g"] == row["g"]]["i"])
        assert sorted(row["cs"]) == sorted(grp)


def test_positional_over_streamable_range(session):
    """Code-review r5: a global median over a chunkable Range used to
    crash in the streaming driver's prepare_direct (positional aggs have
    no accumulators); it must fall back to whole-input execution."""
    old = session.conf.get("spark_tpu.sql.execution.streamingChunkRows")
    try:
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows",
                         1000)
        out = (session.range(10_000)
               .agg(F.median(col("id")).alias("m")).to_pandas())
    finally:
        session.conf.set("spark_tpu.sql.execution.streamingChunkRows",
                         old)
    assert np.isclose(out["m"][0], (10_000 - 1) / 2)


def test_positional_computed_group_key_on_mesh(session, pdf):
    """Code-review r5: a computed group key under a mesh positional
    aggregate must gather (AllTuples) instead of hashing a key column
    that does not exist in the child schema."""
    build = lambda: (session.table("pos_t")
                     .group_by((col("g") % 2).alias("gb"))
                     .agg(F.median(col("v")).alias("m"))
                     .to_pandas().sort_values("gb")
                     .reset_index(drop=True))
    want = build()
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        got = build()
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert np.allclose(got["m"], want["m"])

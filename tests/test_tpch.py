"""TPC-H result parity at small scale: engine output vs the independent
pandas golden implementations, single-chip and on the 8-shard mesh."""

import os

import numpy as np
import pandas as pd
import pytest

from spark_tpu.tpch import golden as G
from spark_tpu.tpch import queries as Q
from spark_tpu.tpch.datagen import write_parquet

SF = 0.002  # ~12k lineitem rows: fast CI, still exercises every path
MESH_KEY = "spark_tpu.sql.mesh.size"


@pytest.fixture(scope="session")
def tpch_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tpch") / "sf_small")
    write_parquet(path, SF)
    return path


@pytest.fixture(scope="session")
def tpch_session(session, tpch_path):
    Q.register_tables(session, tpch_path)
    return session


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for c in out.columns:
        if len(out) and out[c].dtype == object and \
                out[c].iloc[0].__class__.__name__ == "Decimal":
            out[c] = out[c].astype(float)
    return out


@pytest.mark.parametrize("qname", ["q1", "q3", "q5", "q6"])
def test_tpch_parity_single_chip(tpch_session, tpch_path, qname):
    got = _norm(Q.QUERIES[qname](tpch_session).to_pandas())
    want = G.GOLDEN[qname](tpch_path)
    if qname in ("q1",):  # deterministic sort keys
        got = got.reset_index(drop=True)
    elif qname == "q5":
        # ties in revenue are sort-order ambiguous; re-sort both by name
        got = got.sort_values("n_name").reset_index(drop=True)
        want = want.sort_values("n_name").reset_index(drop=True)
    G.compare(got, want)


@pytest.mark.parametrize("qname", ["q1", "q3", "q6"])
def test_tpch_parity_mesh(tpch_session, tpch_path, qname):
    tpch_session.conf.set(MESH_KEY, 8)
    try:
        got = _norm(Q.QUERIES[qname](tpch_session).to_pandas())
    finally:
        tpch_session.conf.set(MESH_KEY, 0)
    want = G.GOLDEN[qname](tpch_path)
    G.compare(got.reset_index(drop=True), want)


def test_q6_pushdown_reaches_scan(tpch_session):
    plan = Q.q6(tpch_session)._qe().executed_plan.tree_string()
    assert "pushed=" in plan and "l_shipdate" in plan

"""Python UDFs: scalar, pandas (vectorized), SQL-registered, and
grouped-map — the `ArrowEvalPythonExec.scala:1` / `worker.py:504`
capability, evaluated as host stages between jitted plan segments."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import functions as F
from spark_tpu.functions import col, lit, udf, pandas_udf


@pytest.fixture
def tdf(session):
    pdf = pd.DataFrame({
        "x": np.array([1.0, 2.0, np.nan, 4.0]),
        "i": np.array([10, 20, 30, 40], dtype=np.int64),
        "s": ["aa", "bb", None, "dd"]})
    session.register_table("udf_t", pdf)
    return session.table("udf_t"), pdf


def test_scalar_udf_select(tdf):
    df, pdf = tdf
    plus_one = udf(lambda v: None if v is None else v + 1.0, "double")
    out = df.select(col("i"), plus_one(col("x")).alias("y")).to_pandas()
    assert out["y"][0] == 2.0 and out["y"][1] == 3.0
    assert pd.isna(out["y"][2])  # NULL in -> None -> NULL out
    assert out["y"][3] == 5.0


def test_scalar_udf_strings_and_null_return(tdf):
    df, _ = tdf
    shout = udf(lambda s: None if s in (None, "bb") else s.upper(),
                "string")
    out = df.select(shout(col("s")).alias("u")).to_pandas()
    assert out["u"][0] == "AA"
    assert pd.isna(out["u"][1])  # fn returned None
    assert pd.isna(out["u"][2])  # NULL input stayed NULL
    assert out["u"][3] == "DD"


def test_udf_in_filter_and_expression_args(tdf):
    df, pdf = tdf
    is_big = udf(lambda v: v is not None and v > 25, "boolean")
    out = df.filter(is_big(col("i") + 1)).to_pandas()
    assert out["i"].tolist() == [30, 40]


def test_nested_udfs(tdf):
    df, _ = tdf
    double = udf(lambda v: None if v is None else v * 2, "long")
    inc = udf(lambda v: None if v is None else v + 1, "long")
    out = df.select(inc(double(col("i"))).alias("y")).to_pandas()
    assert out["y"].tolist() == [21, 41, 61, 81]


def test_pandas_udf_vectorized(tdf):
    df, pdf = tdf

    @pandas_udf(returnType="double")
    def scaled(v: pd.Series) -> pd.Series:
        return v * 10.0

    out = df.select(scaled(col("x")).alias("y")).to_pandas()
    assert out["y"][0] == 10.0 and out["y"][1] == 20.0
    assert pd.isna(out["y"][2])
    assert out["y"][3] == 40.0


def test_sql_registered_udf(tdf):
    df, _ = tdf
    session = df.session
    session.udf.register("cube_it", lambda v: None if v is None
                         else v ** 3, "long")
    out = session.sql("SELECT i, cube_it(i) AS c FROM udf_t").to_pandas()
    assert out["c"].tolist() == [1000, 8000, 27000, 64000]


def test_udf_downstream_of_jitted_ops_and_upstream_agg(tdf):
    """The UDF stage cuts the plan: jitted filter below, jitted
    aggregate above."""
    df, pdf = tdf
    half = udf(lambda v: v / 2.0, "double")
    out = (df.filter(col("i") > 10)
           .select(half(col("i")).alias("h"))
           .agg(F.sum(col("h")).alias("s"))
           .to_pandas())
    assert out["s"][0] == (20 + 30 + 40) / 2.0


def test_grouped_map_apply_in_pandas(session):
    pdf = pd.DataFrame({
        "k": np.array([0, 0, 1, 1, 2], dtype=np.int64),
        "v": np.array([1.0, 3.0, 5.0, 7.0, 9.0])})
    session.register_table("gm_t", pdf)

    def center(g: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"k": g["k"],
                             "c": g["v"] - g["v"].mean()})

    out = (session.table("gm_t").group_by(col("k"))
           .apply_in_pandas(center, "k long, c double")
           .to_pandas().sort_values(["k", "c"]).reset_index(drop=True))
    want = pdf.assign(
        c=pdf.groupby("k")["v"].transform(lambda s: s - s.mean()))[
        ["k", "c"]].sort_values(["k", "c"]).reset_index(drop=True)
    assert out["k"].tolist() == want["k"].tolist()
    assert np.allclose(out["c"], want["c"])


def test_udf_on_mesh(tdf):
    """UDF host stage below a mesh-sharded aggregate."""
    df, pdf = tdf
    session = df.session
    twice = udf(lambda v: v * 2, "long")
    try:
        session.conf.set("spark_tpu.sql.mesh.size", 8)
        out = (df.select(twice(col("i")).alias("t"))
               .agg(F.sum(col("t")).alias("s")).to_pandas())
    finally:
        session.conf.set("spark_tpu.sql.mesh.size", 0)
    assert out["s"][0] == 2 * pdf["i"].sum()


def test_udf_date_and_decimal_args(session):
    import datetime
    import decimal
    pdf = pd.DataFrame({
        "d": pd.to_datetime(["2023-01-15", "2024-06-30"]),
        "m": [decimal.Decimal("12.50"), decimal.Decimal("0.75")]})
    session.register_table("udf_dt", pdf)
    year_of = udf(lambda d: d.year, "int")
    dollars = udf(lambda m: float(m) * 2, "double")
    out = (session.table("udf_dt")
           .select(year_of(col("d")).alias("y"),
                   dollars(col("m")).alias("v")).to_pandas())
    assert out["y"].tolist() == [2023, 2024]
    assert out["v"].tolist() == [25.0, 1.5]

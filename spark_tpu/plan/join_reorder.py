"""Cost-based join reorder (reference: CostBasedJoinReorder.scala:1).

A logical optimizer rule that re-sequences maximal regions of inner
equi-joins (chains and bushes of `Join(how='inner', condition=None)`)
by estimated cost. TPC-DS's deep snowflakes are where join ORDER, not
kernel choice, dominates: joining the most selective dimensions first
shrinks every intermediate the later joins (and the runtime filters
built on them) ever see.

Cost model — the planner-statistics sliver, star-schema shaped:

- each base relation contributes `base` rows (source statistics via
  `planner.estimate_rows`, ignoring filters) and a filter selectivity
  `frac` estimated from its Filter chain (equality ~0.1 per conjunct,
  ranges interpolated against Parquet-footer column min/max when
  `spark_tpu.sql.stats.parquetFooter` provides them, OR/NOT combined
  probabilistically);
- an inner FK join of an accumulated side A with relation R produces
  `max(rows) x frac(smaller side)` rows — joining a filtered dimension
  scales the fact side by the dimension's selectivity;
- the chosen order minimizes the SUM of intermediate result sizes
  (left-deep dynamic programming over connected subsets, Selinger
  -style, bounded by `spark_tpu.sql.cbo.maxReorderRelations`).

The rebuilt tree keeps the engine's orientation convention (larger
side on the probe/left, dimensions on the build/right — the same
convention the SQL frontend's size flip establishes) and is wrapped in
a Project restoring the original output schema, so everything above is
oblivious. The rule runs BEFORE physical planning, hence before
runtime-filter injection: creation sides are chosen on the REORDERED
tree, composing with (not bypassing) the PR-1/7 filter machinery.

Soundness gates — a region is only reordered when:
- every join key is a plain column reference and resolves to exactly
  one region relation (no `_r` rename collisions anywhere in the
  region);
- every relation has a row estimate (no estimate -> no cost -> keep
  the frontend order);
- the region joins are all plain inner equi-joins (a residual
  condition or null-aware join is a region BOUNDARY, reordering may
  still happen below it).

Decisions are appended to the executor's reorder log (event-log
`reorder` records + the explain()/history surface), and each planned
join carries its estimated output rows (`_cbo_est_rows`) which
`analysis/predictions.py` emits as a `join_rows` prediction with basis
`cbo-reorder` — graded against observed `join_rows_<tag>` by
`history.prediction_report`, so a systematically-wrong reorder cost
model is visible in the same self-grading loop as the other
estimators."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..expr import (And, BinaryComparison, ColumnRef, EQ, Expression,
                    GE, GT, In, IsNull, LE, LT, NE, Not, Or)
from . import logical as L
from .rules import Rule

ENABLED_KEY = "spark_tpu.sql.cbo.joinReorder"
MAX_RELATIONS_KEY = "spark_tpu.sql.cbo.maxReorderRelations"
STATS_FOOTER_KEY = "spark_tpu.sql.stats.parquetFooter"

#: fallback selectivities when no tighter bound is derivable (the
#: FilterEstimation.scala defaults, same spirit)
SEL_EQ = 0.1
SEL_RANGE = 0.33
SEL_ISNULL = 0.05
SEL_DEFAULT = 0.5


def _plain_name(e: Expression) -> Optional[str]:
    from ..expr import Alias
    while isinstance(e, Alias):
        e = e.child
    if isinstance(e, ColumnRef):
        return e.name()
    return None


# ---------------------------------------------------------------------------
# Selectivity estimation
# ---------------------------------------------------------------------------


def _numeric(value) -> Optional[float]:
    """Best-effort numeric view of a stats/literal value (dates ->
    epoch days, Decimal -> float)."""
    import datetime
    import decimal
    if value is None or isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, decimal.Decimal):
        return float(value)
    if isinstance(value, datetime.date):
        return float((value - datetime.date(1970, 1, 1)).days)
    return None


def _scan_stats(leaf: L.LogicalPlan, conf) -> Dict[str, dict]:
    """Column stats of the scan at the bottom of a Filter/Scan chain
    (empty when disabled, unavailable, or the chain projects/aliases —
    a renamed column must not bind another column's bounds)."""
    if conf is None or not bool(conf.get(STATS_FOOTER_KEY)):
        return {}
    node = leaf
    while isinstance(node, L.Filter):
        node = node.child
    if not isinstance(node, L.Scan):
        return {}
    try:
        stats = node.source.column_stats()
    except Exception:  # noqa: BLE001 — stats are advisory
        return {}
    return stats or {}


def _range_fraction(stats: Optional[dict], op, lit_value) -> float:
    """Fraction of [min, max] selected by `col <op> literal`, linearly
    interpolated from footer stats; SEL_RANGE when unavailable."""
    if not stats:
        return SEL_RANGE
    lo = _numeric(stats.get("min"))
    hi = _numeric(stats.get("max"))
    v = _numeric(lit_value)
    if lo is None or hi is None or v is None or hi <= lo:
        return SEL_RANGE
    frac = (v - lo) / (hi - lo)
    frac = min(1.0, max(0.0, frac))
    if op in (LT, LE):
        out = frac
    else:  # GT, GE
        out = 1.0 - frac
    # clamp away from 0: footer min/max are bounds, not histograms
    return min(1.0, max(0.01, out))


def estimate_selectivity(cond: Expression, stats: Dict[str, dict]) -> float:
    """Heuristic selectivity of one predicate over its relation."""
    if isinstance(cond, And):
        a, b = cond.children
        return estimate_selectivity(a, stats) * \
            estimate_selectivity(b, stats)
    if isinstance(cond, Or):
        a = estimate_selectivity(cond.children[0], stats)
        b = estimate_selectivity(cond.children[1], stats)
        return min(1.0, a + b - a * b)
    if isinstance(cond, Not):
        return max(0.0, 1.0 - estimate_selectivity(cond.children[0],
                                                   stats))
    if isinstance(cond, EQ):
        return SEL_EQ
    if isinstance(cond, NE):
        return 1.0 - SEL_EQ
    if isinstance(cond, In):
        return min(1.0, SEL_EQ * max(1, len(cond.values)))
    if isinstance(cond, IsNull):
        return SEL_ISNULL
    if isinstance(cond, BinaryComparison) and \
            type(cond) in (LT, LE, GT, GE):
        from ..expr import Literal
        le, re = cond.children
        if isinstance(le, ColumnRef) and isinstance(re, Literal):
            return _range_fraction(stats.get(le.name()), type(cond),
                                   re.value)
        if isinstance(re, ColumnRef) and isinstance(le, Literal):
            flipped = {LT: GT, LE: GE, GT: LT, GE: LE}[type(cond)]
            return _range_fraction(stats.get(re.name()), flipped,
                                   le.value)
        return SEL_RANGE
    return SEL_DEFAULT


def _leaf_estimate(leaf: L.LogicalPlan, conf) -> Optional[Tuple[int, float]]:
    """(base_rows, selectivity_fraction) for one region relation: base
    from source statistics ignoring filters, fraction from the Filter
    chain's predicates. None when the source has no estimate."""
    from .planner import estimate_rows
    base = estimate_rows(leaf)
    if base is None or base <= 0:
        return None
    stats = _scan_stats(leaf, conf)
    frac = 1.0
    node = leaf
    while isinstance(node, (L.Filter, L.Project)):
        if isinstance(node, L.Filter):
            frac *= estimate_selectivity(node.condition, stats)
        node = node.children[0]
    return base, max(frac, 1.0 / max(base, 1))


# ---------------------------------------------------------------------------
# Region flattening
# ---------------------------------------------------------------------------


def _is_region_join(node: L.LogicalPlan) -> bool:
    return (isinstance(node, L.Join) and node.how == "inner"
            and node.condition is None and not node.null_aware
            and all(_plain_name(k) is not None
                    for k in node.left_keys + node.right_keys))


class _Region:
    """A maximal flattened inner-equi-join region: `rels` in frontend
    (in-order) sequence, `edges` as (rel_a, name_a, rel_b, name_b)."""

    def __init__(self):
        self.rels: List[L.LogicalPlan] = []
        self.edges: List[Tuple[int, str, int, str]] = []
        self.ok = True

    def owner_of(self, name: str) -> Optional[int]:
        hits = [i for i, r in enumerate(self.rels)
                if name in r.schema().names]
        return hits[0] if len(hits) == 1 else None


def _flatten(node: L.LogicalPlan, region: _Region) -> None:
    if not region.ok:
        return
    if _is_region_join(node):
        # a rename inside the region means two relations collide on a
        # column name — key origins would be ambiguous; keep the tree
        nm = node.right_name_map()
        if any(k != v for k, v in nm.items()):
            region.ok = False
            return
        _flatten(node.left, region)
        _flatten(node.right, region)
        if not region.ok:
            return
        for lk, rk in zip(node.left_keys, node.right_keys):
            ln, rn = _plain_name(lk), _plain_name(rk)
            lo, ro = region.owner_of(ln), region.owner_of(rn)
            if lo is None or ro is None or lo == ro:
                region.ok = False
                return
            region.edges.append((lo, ln, ro, rn))
    else:
        region.rels.append(node)


# ---------------------------------------------------------------------------
# Order search (left-deep DP over connected subsets)
# ---------------------------------------------------------------------------


def _join_estimate(rows_a: float, frac_a: float, rows_b: float,
                   frac_b: float) -> float:
    """FK-heuristic output estimate: the larger side scaled by the
    smaller (dimension) side's accumulated filter selectivity."""
    if rows_a >= rows_b:
        return max(1.0, rows_a * min(1.0, frac_b))
    return max(1.0, rows_b * min(1.0, frac_a))


def _best_order(est: List[Tuple[int, float]],
                adj: List[int]) -> Optional[Tuple[Tuple[int, ...],
                                                  List[int]]]:
    """Minimal-cost left-deep order over connected subsets.
    `est[i] = (rows_i, frac_i)`, `adj[i]` = bitmask of neighbors.
    Returns (order, per-join estimated output rows) or None when the
    region graph is disconnected."""
    n = len(est)
    full = (1 << n) - 1
    # state per subset: (cost, order, rows, frac, per_join_rows)
    best: Dict[int, Tuple[float, Tuple[int, ...], float, float,
                          List[int]]] = {}
    for i in range(n):
        rows = max(1.0, est[i][0] * est[i][1])
        best[1 << i] = (0.0, (i,), rows, est[i][1], [])
    for mask in range(1, full + 1):
        state = best.get(mask)
        if state is None:
            continue
        cost, order, rows, frac, per = state
        for i in range(n):
            bit = 1 << i
            if mask & bit or not (adj[i] & mask):
                continue
            ri = max(1.0, est[i][0] * est[i][1])
            out = _join_estimate(rows, frac, ri, est[i][1])
            nxt = (cost + out, order + (i,), out,
                   min(1.0, frac * est[i][1]), per + [int(out)])
            cur = best.get(mask | bit)
            # deterministic: strictly-better cost wins; ties keep the
            # lexicographically-earlier order (frontend bias)
            if cur is None or (nxt[0], nxt[1]) < (cur[0], cur[1]):
                best[mask | bit] = nxt
    final = best.get(full)
    if final is None:
        return None
    return final[1], final[4]


# ---------------------------------------------------------------------------
# The rule
# ---------------------------------------------------------------------------


class CostBasedJoinReorder(Rule):
    name = "CostBasedJoinReorder"
    # the restoring Project keeps names/dtypes but re-derives
    # nullability from the reordered join tree
    schema_preserving = False

    def __init__(self, conf=None, log: Optional[list] = None):
        self.conf = conf
        self.log = log

    def apply(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        if self.conf is None or not bool(self.conf.get(ENABLED_KEY)):
            return plan
        return self._rewrite(plan)

    def _rewrite(self, node: L.LogicalPlan) -> L.LogicalPlan:
        if _is_region_join(node):
            out = self._try_region(node)
            if out is not None:
                return out
        return node.map_children(self._rewrite)

    def _rel_label(self, rel: L.LogicalPlan) -> str:
        n = rel
        while isinstance(n, (L.Filter, L.Project)):
            n = n.children[0]
        if isinstance(n, L.Scan):
            return n.source.name
        return type(n).__name__.lower()

    def _record(self, region: _Region, order, per_join, changed: bool
                ) -> None:
        """`kind` disambiguates the two change classes: "order" = the
        relation sequence itself moved; "orientation" = same sequence
        but a probe/build side flip (the capacity convention) altered
        the tree — without it a changed=true record whose order equals
        its relations list reads as a contradiction."""
        from .rules import in_replay
        if self.log is None or in_replay():
            # the integrity validator's determinism replay re-applies
            # this rule; its decisions must not double-append
            return
        labels = [self._rel_label(r) for r in region.rels]
        seq_changed = tuple(order) != tuple(range(len(labels)))
        self.log.append({
            "relations": labels,
            "order": [labels[i] for i in order],
            "est_rows": list(per_join),
            "changed": bool(changed),
            "kind": ("order" if changed and seq_changed
                     else "orientation" if changed else "kept")})

    @staticmethod
    def _signature(node: L.LogicalPlan, leaf_index: Dict[int, int]):
        """Shape signature of a region tree: leaves by region index,
        joins by (children signatures, key-name pairs) — the change
        test (attribute-based same_result would see the advisory
        `_cbo_est_rows` annotation as a difference)."""
        if _is_region_join(node):
            pairs = tuple(sorted(
                (_plain_name(lk), _plain_name(rk))
                for lk, rk in zip(node.left_keys, node.right_keys)))
            return ("J",
                    CostBasedJoinReorder._signature(node.left, leaf_index),
                    CostBasedJoinReorder._signature(node.right, leaf_index),
                    pairs)
        return ("R", leaf_index[id(node)])

    def _try_region(self, node: L.LogicalPlan) -> Optional[L.LogicalPlan]:
        """Reorder one maximal region; None = not eligible (caller
        recurses into children instead)."""
        max_rels = int(self.conf.get(MAX_RELATIONS_KEY))
        region = _Region()
        _flatten(node, region)
        if not region.ok or not (3 <= len(region.rels) <= max_rels):
            return None
        # estimates; any missing -> keep the frontend order
        est: List[Tuple[int, float]] = []
        for rel in region.rels:
            e = _leaf_estimate(rel, self.conf)
            if e is None:
                return None
            est.append(e)
        n = len(region.rels)
        adj = [0] * n
        for a, _na, b, _nb in region.edges:
            adj[a] |= 1 << b
            adj[b] |= 1 << a
        found = _best_order(est, adj)
        if found is None:
            return None  # disconnected region (cross joins): keep
        order, per_join = found
        # rewrite the region relations themselves first (nested regions
        # under aggregates/subqueries)
        rels = [self._rewrite(r) for r in region.rels]
        rebuilt, new_leaf_index = self._build(rels, est, region.edges,
                                              order)
        if rebuilt is None:
            return None
        orig_leaf_index = {id(r): i for i, r in enumerate(region.rels)}
        changed = (self._signature(node, orig_leaf_index)
                   != self._signature(rebuilt, new_leaf_index))
        self._record(region, order, per_join, changed)
        if not changed:
            # keep the frontend tree (modulo rewritten leaves below it)
            return self._rebuild_shape(node, {
                id(r): new for r, new in zip(region.rels, rels)})
        # restore the original output schema (names AND order) so
        # everything above the region is oblivious to the reorder
        from ..expr import ColumnRef as Ref
        orig_names = node.schema().names
        return L.Project(rebuilt, [Ref(nm) for nm in orig_names])

    def _rebuild_shape(self, node: L.LogicalPlan,
                       leaf_map: Dict[int, L.LogicalPlan]
                       ) -> L.LogicalPlan:
        """The original region tree with its leaves swapped for their
        rewritten versions (identity-preserving when nothing below
        changed)."""
        if _is_region_join(node):
            left = self._rebuild_shape(node.left, leaf_map)
            right = self._rebuild_shape(node.right, leaf_map)
            if left is node.left and right is node.right:
                return node
            return L.Join(left, right, node.left_keys, node.right_keys,
                          "inner")
        return leaf_map[id(node)]

    def _build(self, rels: List[L.LogicalPlan],
               est: List[Tuple[int, float]],
               edges: List[Tuple[int, str, int, str]],
               order: Tuple[int, ...]
               ) -> Tuple[Optional[L.LogicalPlan], Dict[int, int]]:
        """Left-deep tree over `order`, orientation following the
        engine convention: bigger estimated side on the probe (left).
        Also returns the id(new leaf) -> region index map for the
        shape-signature change test.

        Orientation follows BASE capacities, not post-filter estimates:
        the engine masks filtered rows rather than compacting them, so
        the side with more physical rows (the fact) must stay on the
        probe/left regardless of how selective its filters are — a
        build side is sorted at its full static capacity."""
        leaf_index = {id(rels[i]): i for i in range(len(rels))}
        bound = {order[0]}
        acc = rels[order[0]]
        acc_rows = max(1.0, est[order[0]][0] * est[order[0]][1])
        acc_frac = est[order[0]][1]
        acc_cap = float(est[order[0]][0])
        for i in order[1:]:
            acc_keys: List[Expression] = []
            rel_keys: List[Expression] = []
            for a, na, b, nb in edges:
                if a in bound and b == i:
                    acc_keys.append(ColumnRef(na))
                    rel_keys.append(ColumnRef(nb))
                elif b in bound and a == i:
                    acc_keys.append(ColumnRef(nb))
                    rel_keys.append(ColumnRef(na))
            if not acc_keys:
                return None, leaf_index  # disconnected step
            ri = max(1.0, est[i][0] * est[i][1])
            if float(est[i][0]) > acc_cap:
                join = L.Join(rels[i], acc, rel_keys, acc_keys, "inner")
            else:
                join = L.Join(acc, rels[i], acc_keys, rel_keys, "inner")
            out = _join_estimate(acc_rows, acc_frac, ri, est[i][1])
            join._cbo_est_rows = int(out)
            acc = join
            acc_rows = out
            acc_frac = min(1.0, acc_frac * est[i][1])
            acc_cap = max(acc_cap, float(est[i][0]))
            bound.add(i)
        return acc, leaf_index

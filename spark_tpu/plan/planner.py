"""Logical -> physical planning.

The analog of the reference's `SparkPlanner.scala:28` strategies +
`EnsureRequirements.scala:44`: translate each logical node into an
executable operator, then walk the tree inserting Exchange nodes wherever
a child's output partitioning does not satisfy the operator's required
distribution.

Distributed mode (mesh.size > 1) additionally:
- shards leaves over the mesh data axis (their partitioning becomes
  Unknown, which forces exchanges);
- splits aggregates into partial -> exchange -> final, the
  `AggUtils.scala` two-phase plan, so only accumulator tables cross ICI;
- picks broadcast vs shuffle joins from source row estimates against
  `autoBroadcastJoinThreshold` (`SparkStrategies.scala JoinSelection:142`).
"""

from __future__ import annotations

from typing import Optional

from ..config import Conf
from ..expr import AnalysisError, ColumnRef
from . import logical as L
from . import physical as P


def plan_physical(plan: L.LogicalPlan, conf: Conf,
                  join_strategy_overrides: Optional[dict] = None
                  ) -> P.PhysicalPlan:
    """`join_strategy_overrides` ({join_tag: strategy}) is the adaptive
    re-planner's seam (DynamicJoinSelection.scala:1): join tags depend
    only on join order in the converted tree, so they are stable across
    re-plans of the same optimized plan — overrides apply BEFORE
    exchange insertion so requirements re-derive for the new strategy."""
    n = max(1, int(conf.get("spark_tpu.sql.mesh.size")))
    phys = _convert(plan, conf, n)
    if join_strategy_overrides:
        _assign_join_tags(phys)
        _apply_strategy_overrides(phys, join_strategy_overrides)
    phys = ensure_requirements(phys, conf, n)
    from .runtime_filter import ENABLED_KEY, inject_runtime_filters
    if bool(conf.get(ENABLED_KEY)):
        phys = inject_runtime_filters(phys, conf)
    _assign_join_tags(phys)
    return phys


def _apply_strategy_overrides(plan: P.PhysicalPlan,
                              overrides: dict) -> None:
    for c in plan.children:
        _apply_strategy_overrides(c, overrides)
    if isinstance(plan, P.JoinExec) and plan.tag in overrides:
        plan.strategy = overrides[plan.tag]


def _assign_join_tags(plan: P.PhysicalPlan) -> None:
    """Stable per-node tags for join/exchange overflow flags+metrics (the
    executor's capacity-retry loop keys on them)."""
    counter = [0]
    ex_counter = [0]

    agg_counter = [0]
    op_counter = [0]
    rf_counter = [0]
    cj_counter = [0]
    seen = set()  # creation chains are DAG-shared under rf nodes:
    # tag each node once, or op numbers get burned and overwritten

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            walk(c)
        if isinstance(node, P.JoinExec):
            if node.creation_side:
                # runtime-filter creation semis: a separate namespace,
                # so injecting one never renumbers the real joins the
                # strategy-override / AQE-cap channels key on
                node.tag = f"cj{cj_counter[0]}"
                cj_counter[0] += 1
            else:
                node.tag = f"j{counter[0]}"
                counter[0] += 1
        elif isinstance(node, P.ExchangeExec):
            node.tag = f"e{ex_counter[0]}"
            ex_counter[0] += 1
        elif isinstance(node, P.HashAggregateExec):
            node.tag = f"a{agg_counter[0]}"
            agg_counter[0] += 1
        elif isinstance(node, P.RuntimeFilterExec):
            node.tag = f"rf{rf_counter[0]}"
            rf_counter[0] += 1
        node.op_tag = f"op{op_counter[0]}"
        op_counter[0] += 1

    walk(plan)


def estimate_rows(plan: L.LogicalPlan) -> Optional[int]:
    """Upper-bound row estimate from source statistics (the planner-side
    sliver of the reference's statsEstimation/ package). None = unknown."""
    if isinstance(plan, L.Range):
        return plan.num_rows()
    if isinstance(plan, L.Scan):
        return plan.source.estimated_rows()
    if isinstance(plan, (L.Project, L.Filter, L.Sort)):
        return estimate_rows(plan.children[0])
    if isinstance(plan, L.Limit):
        child = estimate_rows(plan.children[0])
        return plan.n if child is None else min(plan.n, child)
    if isinstance(plan, L.Aggregate):
        return estimate_rows(plan.children[0])
    if isinstance(plan, L.Join) and plan.how == "inner":
        # FK-join heuristic: output cardinality ~ the fact side's (drives
        # probe/build-side ordering in the SQL frontend's join search)
        l = estimate_rows(plan.children[0])
        r = estimate_rows(plan.children[1])
        if l is not None and r is not None:
            return max(l, r)
    return None


def _estimated_bytes(plan: L.LogicalPlan) -> Optional[int]:
    rows = estimate_rows(plan)
    if rows is None:
        return None
    return rows * 8 * max(1, len(plan.schema().fields))


def _pick_join_strategy(plan: L.Join, conf: Conf, n: int) -> str:
    if n <= 1:
        return "shuffle"  # strategies coincide on one chip
    if plan.how in ("right", "full"):
        # replicated build would emit its unmatched rows on every shard
        return "shuffle"
    threshold = int(conf.get("spark_tpu.sql.autoBroadcastJoinThreshold"))
    est = _estimated_bytes(plan.right)
    if est is not None and est <= threshold:
        return "broadcast"
    return "shuffle"


def _convert(plan: L.LogicalPlan, conf: Conf, n: int) -> P.PhysicalPlan:
    if isinstance(plan, L.Range):
        node = P.RangeExec(plan.start, plan.end, plan.step)
        node.dist_n = n
        return node
    if isinstance(plan, L.Scan):
        node = P.ScanExec(plan.source, plan.required_columns,
                          plan.pushed_filters)
        node.dist_n = n
        return node
    if isinstance(plan, L.Project):
        return P.ProjectExec(_convert(plan.child, conf, n), plan.exprs)
    if isinstance(plan, L.Filter):
        return P.FilterExec(_convert(plan.child, conf, n), plan.condition)
    if isinstance(plan, L.Aggregate):
        child = _convert(plan.child, conf, n)
        # size the sort-path output table from the estimate registry
        # instead of the full input capacity (round-2 dead conf, now
        # load-bearing; overflow re-jits via the agg_overflow flag)
        est = int(conf.get("spark_tpu.sql.aggregate.estimatedGroups"))
        rows = estimate_rows(plan.child)
        if rows is not None:
            # bucket the estimate: it lands verbatim in the stage-cache
            # key (simple_string), so a raw row count would recompile
            # per exact input size (analysis UNBUCKETED_CAPACITY);
            # compute buckets it before use anyway, so output shapes
            # are unchanged
            from ..columnar import bucket_capacity
            est = min(est, bucket_capacity(max(1, rows)))
        positional = any(getattr(a.func, "positional", False)
                         for a in plan.agg_exprs)
        if n <= 1 or positional:
            # positional aggregates (percentile/collect_*) have no
            # partial/final decomposition: one complete pass per shard
            # behind the hash-clustered (or AllTuples) exchange the
            # complete mode's requirements already demand
            return P.HashAggregateExec(child, plan.group_exprs,
                                       plan.agg_exprs, mode="complete",
                                       est_groups=est)
        # two-phase: per-shard partial tables, exchange by group key (or
        # collapse to every shard for global aggregates), final re-reduce
        partial = P.HashAggregateExec(child, plan.group_exprs,
                                      plan.agg_exprs, mode="partial",
                                      est_groups=est)
        final_groups = [ColumnRef(g.name()) for g in plan.group_exprs]
        return P.HashAggregateExec(partial, final_groups, plan.agg_exprs,
                                   mode="final", est_groups=est)
    if isinstance(plan, L.Join):
        strategy = _pick_join_strategy(plan, conf, n)
        exec_ = P.JoinExec(_convert(plan.left, conf, n),
                           _convert(plan.right, conf, n),
                           plan.left_keys, plan.right_keys, plan.how,
                           plan.condition, plan.schema(), strategy=strategy)
        exec_.null_aware = plan.null_aware
        # reorder cost-model estimate (plan/join_reorder.py): advisory
        # only — graded as a `join_rows` prediction, shown by
        # explain(runtime=True); never part of the stage key
        exec_.cbo_est_rows = getattr(plan, "_cbo_est_rows", None)
        return exec_
    if isinstance(plan, L.WindowPlan):
        return P.WindowExec(_convert(plan.child, conf, n), plan.wexprs,
                            plan.schema())
    if isinstance(plan, L.Watermark):
        return _convert(plan.child, conf, n)  # batch: passthrough
    if isinstance(plan, L.Generate):
        return P.GenerateExec(_convert(plan.child, conf, n),
                              plan.gen_expr, plan.out_name,
                              plan.schema(), outer=plan.outer)
    if isinstance(plan, L.Sort):
        return P.SortExec(_convert(plan.child, conf, n), plan.orders)
    if isinstance(plan, L.Limit):
        return P.LimitExec(_convert(plan.child, conf, n), plan.n)
    if isinstance(plan, L.Union):
        return P.UnionExec(_convert(plan.children[0], conf, n),
                           _convert(plan.children[1], conf, n), plan.schema())
    raise AnalysisError(f"no physical strategy for {type(plan).__name__}")


def _join_co_partitioned(left: P.PhysicalPlan, right: P.PhysicalPlan,
                         lk, rk) -> bool:
    """True when both join children are ALREADY laid out so equal keys
    share a shard. Checked jointly — each side satisfying its clustered
    requirement in isolation is NOT enough: hash layouts on different key
    subsets (or subset positions) send equal rows to different shards
    (reference: EnsureRequirements' key-ordering co-partition check)."""
    lp = left.output_partitioning()
    rp = right.output_partitioning()
    if isinstance(lp, P.SinglePartition) and isinstance(rp, P.SinglePartition):
        return True
    if not (isinstance(lp, P.HashPartitioning)
            and isinstance(rp, P.HashPartitioning)):
        return False
    if lp.num_partitions != rp.num_partitions or not lp.keys:
        return False
    try:
        lpos = [lk.index(k) for k in lp.keys]
        rpos = [rk.index(k) for k in rp.keys]
    except ValueError:
        return False
    return lpos == rpos


def ensure_requirements(plan: P.PhysicalPlan, conf: Conf,
                        n: int = 1) -> P.PhysicalPlan:
    """Insert exchanges where child partitioning fails the requirement
    (reference: EnsureRequirements.ensureDistributionAndOrdering:49)."""
    import copy
    new_children = tuple(ensure_requirements(c, conf, n)
                         for c in plan.children)
    if new_children != plan.children:
        plan = copy.copy(plan)
        plan.children = new_children

    dists = plan.required_child_distributions()
    parts = n if n > 1 else int(conf.get("spark_tpu.sql.shuffle.partitions"))

    if isinstance(plan, P.JoinExec) and dists and \
            isinstance(dists[0], P.ClusteredDistribution):
        lk, rk = dists[0].keys, dists[1].keys
        if not _join_co_partitioned(plan.left, plan.right, list(lk), list(rk)):
            plan = copy.copy(plan)
            plan.children = (
                P.ExchangeExec(plan.children[0],
                               P.HashPartitioning(lk, parts)),
                P.ExchangeExec(plan.children[1],
                               P.HashPartitioning(rk, parts)))
        return plan

    fixed = []
    changed = False
    for child, dist in zip(plan.children, dists):
        if child.output_partitioning().satisfies(dist):
            fixed.append(child)
            continue
        changed = True
        if isinstance(dist, P.ClusteredDistribution):
            fixed.append(P.ExchangeExec(
                child, P.HashPartitioning(dist.keys, parts)))
        elif isinstance(dist, P.OrderedDistribution):
            fixed.append(P.ExchangeExec(
                child, P.RangePartitioning(dist.order_key, parts,
                                           orders=plan.orders)))
        elif isinstance(dist, P.AllTuples):
            fixed.append(P.ExchangeExec(child, P.SinglePartition()))
        elif isinstance(dist, P.BroadcastDistribution):
            fixed.append(P.ExchangeExec(child, P.Replicated()))
        else:
            fixed.append(child)
    if changed:
        plan = copy.copy(plan)
        plan.children = tuple(fixed)
    return plan

"""Logical -> physical planning.

The analog of the reference's `SparkPlanner.scala:28` strategies +
`EnsureRequirements.scala:44`: translate each logical node into an
executable operator, then walk the tree inserting Exchange nodes wherever
a child's output partitioning does not satisfy the operator's required
distribution. On one chip everything is SinglePartition and no exchange
materializes; the distributed planner (parallel/) re-plans aggregates as
partial/final across a hash exchange the way `AggUtils.scala` does.
"""

from __future__ import annotations

from typing import Optional

from ..config import Conf
from ..expr import AnalysisError
from . import logical as L
from . import physical as P


def plan_physical(plan: L.LogicalPlan, conf: Conf) -> P.PhysicalPlan:
    phys = _convert(plan, conf)
    phys = ensure_requirements(phys, conf)
    _assign_join_tags(phys)
    return phys


def _assign_join_tags(plan: P.PhysicalPlan) -> None:
    """Stable per-node tags for join overflow flags/metrics (the executor's
    capacity-retry loop keys on them)."""
    counter = [0]

    def walk(node):
        for c in node.children:
            walk(c)
        if isinstance(node, P.JoinExec):
            node.tag = f"j{counter[0]}"
            counter[0] += 1

    walk(plan)


def _convert(plan: L.LogicalPlan, conf: Conf) -> P.PhysicalPlan:
    if isinstance(plan, L.Range):
        return P.RangeExec(plan.start, plan.end, plan.step)
    if isinstance(plan, L.Scan):
        return P.ScanExec(plan.source, plan.required_columns, plan.pushed_filters)
    if isinstance(plan, L.Project):
        return P.ProjectExec(_convert(plan.child, conf), plan.exprs)
    if isinstance(plan, L.Filter):
        return P.FilterExec(_convert(plan.child, conf), plan.condition)
    if isinstance(plan, L.Aggregate):
        return P.HashAggregateExec(_convert(plan.child, conf),
                                   plan.group_exprs, plan.agg_exprs,
                                   mode="complete")
    if isinstance(plan, L.Join):
        return P.JoinExec(_convert(plan.left, conf), _convert(plan.right, conf),
                          plan.left_keys, plan.right_keys, plan.how,
                          plan.condition, plan.schema())
    if isinstance(plan, L.Sort):
        return P.SortExec(_convert(plan.child, conf), plan.orders)
    if isinstance(plan, L.Limit):
        return P.LimitExec(_convert(plan.child, conf), plan.n)
    if isinstance(plan, L.Union):
        return P.UnionExec(_convert(plan.children[0], conf),
                           _convert(plan.children[1], conf), plan.schema())
    raise AnalysisError(f"no physical strategy for {type(plan).__name__}")


def ensure_requirements(plan: P.PhysicalPlan, conf: Conf) -> P.PhysicalPlan:
    """Insert exchanges where child partitioning fails the requirement
    (reference: EnsureRequirements.ensureDistributionAndOrdering:49)."""
    new_children = tuple(ensure_requirements(c, conf) for c in plan.children)
    if new_children != plan.children:
        import copy
        plan = copy.copy(plan)
        plan.children = new_children
    fixed = []
    changed = False
    for child, dist in zip(plan.children, plan.required_child_distributions()):
        if child.output_partitioning().satisfies(dist):
            fixed.append(child)
            continue
        changed = True
        if isinstance(dist, P.ClusteredDistribution):
            n = int(conf.get("spark_tpu.sql.shuffle.partitions"))
            fixed.append(P.ExchangeExec(
                child, P.HashPartitioning(dist.keys, n)))
        elif isinstance(dist, P.AllTuples):
            fixed.append(P.ExchangeExec(child, P.SinglePartition()))
        elif isinstance(dist, P.BroadcastDistribution):
            fixed.append(P.ExchangeExec(child, P.Replicated()))
        else:
            fixed.append(child)
    if changed:
        import copy
        plan = copy.copy(plan)
        plan.children = tuple(fixed)
    return plan

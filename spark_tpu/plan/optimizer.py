"""Logical optimizer rules.

The round-1 subset of the reference's `optimizer/Optimizer.scala:42`
default batches: filter combination, filter pushdown through projections
and into scans, column pruning into scans, and constant folding.
Every rule is plan->plan and covered by plan==plan tests (the pattern of
the reference's `PlanTest.comparePlans`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import types as T
from ..columnar import Batch as ColBatch
from ..expr import (Alias, And, ColumnRef, Expression, Literal, Mul)
from .logical import (Aggregate, Filter, Join, Limit, LogicalPlan, Project,
                      Range, Scan, Sort, Union)
from .rules import Batch, Rule, RuleExecutor


class CombineFilters(Rule):
    name = "CombineFilters"
    schema_preserving = True

    def apply(self, plan):
        def f(node):
            if isinstance(node, Filter) and isinstance(node.child, Filter):
                inner = node.child
                return Filter(inner.child, And(inner.condition, node.condition))
            return node
        return plan.transform_up(f)


def _substitute(expr: Expression, mapping: dict) -> Expression:
    def f(node):
        if isinstance(node, ColumnRef) and node._name in mapping:
            return mapping[node._name]
        return node
    return expr.transform_up(f)


class PushFilterThroughProject(Rule):
    name = "PushFilterThroughProject"
    schema_preserving = True

    def apply(self, plan):
        def f(node):
            if isinstance(node, Filter) and isinstance(node.child, Project):
                proj = node.child
                mapping = {}
                for e in proj.exprs:
                    if isinstance(e, Alias):
                        mapping[e.name()] = e.child
                    elif isinstance(e, ColumnRef):
                        mapping[e.name()] = e
                cond = _substitute(node.condition, mapping)
                try:
                    cond.dtype(proj.child.schema())
                except Exception:
                    return node  # references a computed column we can't inline
                return Project(Filter(proj.child, cond), proj.exprs)
            return node
        return plan.transform_up(f)


class PushFilterIntoScan(Rule):
    """Hand conjuncts to the source (reference: DataSource V2
    `SupportsPushDownFilters` / `V2ScanRelationPushDown`). The source keeps
    what it can use for IO skipping; everything is still re-applied as a
    residual filter for correctness (same contract as Spark's parquet
    row-group pushdown)."""

    name = "PushFilterIntoScan"
    schema_preserving = True

    def apply(self, plan):
        def f(node):
            if isinstance(node, Filter) and isinstance(node.child, Scan):
                scan = node.child
                conjuncts = _split_conjuncts(node.condition)
                new_pushed = [c for c in conjuncts
                              if scan.source.can_push(c)
                              and not any(c is p for p in scan.pushed_filters)
                              and not any(_expr_eq(c, p) for p in scan.pushed_filters)]
                if not new_pushed:
                    return node
                new_scan = Scan(scan.source, scan.required_columns,
                                tuple(scan.pushed_filters) + tuple(new_pushed))
                return Filter(new_scan, node.condition)
            return node
        return plan.transform_up(f)


def _expr_eq(a, b):
    from ..expr import structurally_equal
    return structurally_equal(a, b)


def _split_conjuncts(e: Expression) -> List[Expression]:
    if isinstance(e, And):
        return _split_conjuncts(e.children[0]) + _split_conjuncts(e.children[1])
    return [e]


class PruneColumns(Rule):
    """Top-down required-column propagation narrowing Scan nodes
    (reference: `ColumnPruning` + `V2ScanRelationPushDown` column pruning)."""

    name = "PruneColumns"
    # narrows INTERIOR Scan/Join schemas; the root stays stable only
    # when a Project/Aggregate caps the tree, so no blanket guarantee
    schema_preserving = False

    def apply(self, plan):
        return self._prune(plan, None)

    def _prune(self, node: LogicalPlan, needed: Optional[Set[str]]):
        if isinstance(node, Scan):
            if needed is None:
                return node
            avail = node.source.schema().names
            for f in node.pushed_filters:
                needed = needed | f.references()
            from ..expr import case_sensitive
            if case_sensitive():
                cols = tuple(n for n in avail if n in needed)
            else:
                # match the engine's case-insensitive resolution — a
                # reference spelled 'mixed' must keep column 'Mixed'
                lowered = {n.lower() for n in needed}
                cols = tuple(n for n in avail if n.lower() in lowered)
            if node.required_columns is not None and \
                    set(node.required_columns) == set(cols):
                return node
            return Scan(node.source, cols, node.pushed_filters)
        if isinstance(node, Project):
            child_needed = set()
            for e in node.exprs:
                child_needed |= e.references()
            return Project(self._prune(node.child, child_needed), node.exprs)
        if isinstance(node, Filter):
            child_needed = None if needed is None else \
                needed | node.condition.references()
            return Filter(self._prune(node.child, child_needed), node.condition)
        if isinstance(node, Aggregate):
            child_needed = set()
            for g in node.group_exprs:
                child_needed |= g.references()
            for a in node.agg_exprs:
                child_needed |= a.func.references()
            return Aggregate(self._prune(node.child, child_needed),
                             node.group_exprs, node.agg_exprs)
        if isinstance(node, Join):
            left_names = set(node.left.schema().names)
            right_names = set(node.right.schema().names)
            refs = set()
            for k in node.left_keys + node.right_keys:
                refs |= k.references()
            if node.condition is not None:
                refs |= node.condition.references()
            if needed is None:
                ln = rn = None
            else:
                want = needed | refs
                # references use POST-join names: collisions were `_r`
                # -suffixed (Join.right_name_map), so a wanted `x_r` must
                # keep the right child's `x`
                rename = {out: orig for orig, out
                          in node.right_name_map().items()}
                ln = {n for n in want if n in left_names}
                rn = set()
                for w in want:
                    orig = rename.get(w, w)
                    if orig not in right_names:
                        continue
                    rn.add(orig)
                    # a rename exists only while its colliding columns
                    # do: pruning them would silently change the join's
                    # output names. Keep the WHOLE `_r` chain alive —
                    # for a wanted `x_r_r`, both left `x` and `x_r`
                    # forced the suffixes.
                    step = orig
                    while step != w:
                        if step in left_names:
                            ln.add(step)
                        step = step + "_r"
            new = copy_join(node, self._prune(node.left, ln),
                            self._prune(node.right, rn))
            return new
        if isinstance(node, Sort):
            child_needed = None
            if needed is not None:
                child_needed = set(needed)
                for o in node.orders:
                    child_needed |= o.child.references()
            return Sort(self._prune(node.child, child_needed), node.orders)
        if isinstance(node, Limit):
            return Limit(self._prune(node.child, needed), node.n)
        if isinstance(node, Union):
            return Union(self._prune(node.children[0], None),
                         self._prune(node.children[1], None))
        return node.map_children(lambda c: self._prune(c, None))


def copy_join(j: Join, left, right) -> Join:
    new = Join(left, right, j.left_keys, j.right_keys, j.how, j.condition,
               j.null_aware)
    # carry the reorder cost-model annotation through rebuilds (PruneColumns
    # runs after the JoinReorder batch and must not strip it)
    if hasattr(j, "_cbo_est_rows"):
        new._cbo_est_rows = j._cbo_est_rows
    return new


_EMPTY_BATCH = None


def _empty_batch():
    global _EMPTY_BATCH
    if _EMPTY_BATCH is None:
        _EMPTY_BATCH = ColBatch({}, None)
    return _EMPTY_BATCH


class ConstantFolding(Rule):
    name = "ConstantFolding"
    # a folded Literal is non-null, so a nullable-typed constant
    # expression legitimately tightens nullability at the root
    schema_preserving = False

    def apply(self, plan):
        def fold_expr(e: Expression) -> Expression:
            def f(node):
                if (node.foldable() and not isinstance(node, Literal)
                        and not isinstance(node, Alias)):
                    try:
                        dt = node.dtype(T.Schema([]))
                    except Exception:
                        return node
                    if isinstance(dt, (T.StringType, T.DecimalType)):
                        return node
                    try:
                        v = node.eval(_empty_batch())
                    except Exception:
                        return node
                    if v.validity is not None:
                        return node
                    val = np.asarray(v.data).item()
                    return Literal(val, dt)
                return node
            return e.transform_up(f)

        def f(node):
            if isinstance(node, Project):
                return Project(node.child, [fold_expr(e) for e in node.exprs])
            if isinstance(node, Filter):
                return Filter(node.child, fold_expr(node.condition))
            return node
        return plan.transform_up(f)


class CollapseProjectIntoAggregate(Rule):
    """Aggregate over Project -> Aggregate with the projected expressions
    inlined (reference: CollapseProject). Besides removing a pass, this
    lets `key_domain` see through `(id % N) AS k` aliases, keeping the
    dense-domain MXU aggregate path that a bare ColumnRef group key
    would miss (the sort path is ~30x slower at bench shapes)."""

    name = "CollapseProjectIntoAggregate"
    # inlining projected expressions into the aggregate can tighten
    # nullability (e.g. an aliased non-null arithmetic replacing a ref)
    schema_preserving = False

    def apply(self, plan):
        def f(node):
            if not (isinstance(node, Aggregate)
                    and isinstance(node.child, Project)):
                return node
            proj = node.child
            mapping = {}
            for e in proj.exprs:
                if isinstance(e, Alias):
                    mapping[e.name()] = e.child
                elif isinstance(e, ColumnRef):
                    mapping[e.name()] = e

            def subst(e: Expression) -> Expression:
                out = _substitute(e, mapping)
                # every reference must resolve below the projection
                try:
                    out.dtype(proj.child.schema())
                except Exception:
                    return None
                return out

            new_groups = []
            for g in node.group_exprs:
                s = subst(g.child if isinstance(g, Alias) else g)
                if s is None:
                    return node
                new_groups.append(Alias(s, g.name()))
            new_aggs = []
            for a in node.agg_exprs:
                func = a.func
                if func.children:
                    args = [subst(c) for c in func.children]
                    if any(s is None for s in args):
                        return node
                    func = func.with_args(args)
                new_aggs.append(type(a)(func, a.out_name))
            return Aggregate(proj.child, new_groups, new_aggs)

        return plan.transform_up(f)


class RewriteDistinctAggregates(Rule):
    """count/sum/avg(DISTINCT x) -> the plain aggregate over a
    (groups, x) dedupe aggregate — the single-distinct case of the
    reference's `AggUtils.planAggregateWithOneDistinct` (Expand-based
    mixed plans are not supported; mixing distinct and plain aggregates
    raises)."""

    name = "RewriteDistinctAggregates"
    # count(distinct) -> count over a dedupe changes result nullability
    schema_preserving = False

    def apply(self, plan):
        from ..expr_agg import (AggExpr, Avg, AvgDistinct, Count,
                                CountDistinct, Sum, SumDistinct)
        markers = {CountDistinct: Count, SumDistinct: Sum,
                   AvgDistinct: Avg}

        def f(node):
            if not isinstance(node, Aggregate):
                return node
            distinct = [a for a in node.agg_exprs
                        if type(a.func) in markers]
            if not distinct:
                return node
            if len(distinct) != len(node.agg_exprs):
                from ..expr import AnalysisError
                raise AnalysisError(
                    "mixing DISTINCT aggregates with plain aggregates is "
                    "not supported yet")
            firsts = [a.func.child for a in distinct]
            from ..expr import structurally_equal
            if not all(structurally_equal(firsts[0], e) for e in firsts[1:]):
                from ..expr import AnalysisError
                raise AnalysisError(
                    "multiple DISTINCT aggregates on different expressions "
                    "are not supported yet")
            dedup_key = Alias(firsts[0], "__distinct_key")
            inner = Aggregate(node.child,
                              list(node.group_exprs) + [dedup_key], [])
            outer_groups = [ColumnRef(g.name()) for g in node.group_exprs]
            outer_aggs = [AggExpr(markers[type(a.func)](
                ColumnRef("__distinct_key")), a.out_name)
                for a in distinct]
            return Aggregate(inner, outer_groups, outer_aggs)

        return plan.transform_up(f)


class RewriteGroupKeyAggregates(Rule):
    """sum/min/max/avg OF A GROUP KEY rewrite to post-aggregation
    arithmetic: within a group every value of the key is identical, so
    sum(k) = k * count(k), min(k) = max(k) = k, avg(k) = k. This drops
    whole accumulator rows from the aggregate kernel (the MXU one-hot
    kernel's cost is linear in limb rows — the headline
    AggregateBenchmark shape `sum(k) group by k` goes from 4 limb rows
    to 1). No reference analog: WholeStageCodegen pays per-row cost for
    these regardless; the columnar formulation makes the rewrite free.

    NULL-key groups stay correct without conditionals: the projected
    key value is itself NULL exactly for that group, and sum's count
    factor only multiplies a non-null key."""

    name = "RewriteGroupKeyAggregates"
    # sum/min/max/avg of a group key become post-aggregation arithmetic
    # whose nullability follows the key, not the aggregate
    schema_preserving = False

    def apply(self, plan):
        from ..expr import Cast, structurally_equal
        from ..expr_agg import AggExpr, Avg, Count, Max, Min, Sum

        def match_group(node, child, child_schema):
            for g in node.group_exprs:
                base = g.child if isinstance(g, Alias) else g
                if structurally_equal(child, g) or \
                        structurally_equal(child, base):
                    return g
                if isinstance(child, ColumnRef) and \
                        child.name() == g.name():
                    # a bare name equal to the group ALIAS only means
                    # the group key when no real child column shadows
                    # it — group_by(col('a').alias('k')).agg(sum('k'))
                    # with an actual column k must aggregate column k
                    try:
                        child.dtype(child_schema)
                        resolves_in_child = True
                    except Exception:
                        resolves_in_child = False
                    if not resolves_in_child:
                        return g
            return None

        def f(node):
            if not isinstance(node, Aggregate) or not node.group_exprs:
                return node
            child_schema = node.child.schema()
            hits = {}
            for a in node.agg_exprs:
                if not isinstance(a.func, (Sum, Min, Max, Avg)) or \
                        a.func.child is None:
                    continue
                if isinstance(a.func, Avg) and isinstance(
                        a.func.child.dtype(child_schema), T.DecimalType):
                    continue  # avg(decimal) shifts scale; keep in agg
                try:
                    child_dt = a.func.child.dtype(child_schema)
                except Exception:
                    child_dt = None
                if isinstance(child_dt, T.FractionalType):
                    # -0.0 == 0.0 land in ONE group yet remain distinct
                    # values, so the group's key representative is not
                    # value-faithful: max(k) over {-0.0, 0.0} is 0.0
                    # but the kept key may be -0.0 (and sum(k) != k*n).
                    # Found by the differential plan fuzzer (seed class
                    # 166/284/455); float keys keep the real aggregate.
                    continue
                g = match_group(node, a.func.child, child_schema)
                if g is not None:
                    hits[a.out_name] = (a, g)
            if not hits:
                return node

            remaining = [a for a in node.agg_exprs
                         if a.out_name not in hits]
            # one count per distinct summed key expression
            cnt_names = {}
            counts = []
            for out_name, (a, g) in hits.items():
                if not isinstance(a.func, Sum):
                    continue
                key = repr(g)
                if key not in cnt_names:
                    cnt_names[key] = f"__gk_cnt{len(cnt_names)}"
                    counts.append(AggExpr(Count(a.func.child),
                                          cnt_names[key]))
            inner = Aggregate(node.child, node.group_exprs,
                              remaining + counts)
            out_exprs = [ColumnRef(g.name()) for g in node.group_exprs]
            for a in node.agg_exprs:
                hit = hits.get(a.out_name)
                if hit is None:
                    out_exprs.append(ColumnRef(a.out_name))
                    continue
                _, g = hit
                keyref = ColumnRef(g.name())
                want = a.func.result_type(child_schema)
                if isinstance(a.func, Sum):
                    e = Mul(keyref, ColumnRef(cnt_names[repr(g)]))
                    if type(e.dtype(inner.schema())) is not type(want) or \
                            isinstance(want, T.DecimalType):
                        e = Cast(e, want)
                elif isinstance(a.func, Avg):
                    e = Cast(keyref, want)
                else:  # min/max of the key is the key
                    e = keyref
                out_exprs.append(Alias(e, a.out_name))
            return Project(inner, out_exprs)

        return plan.transform_up(f)


EXCLUDED_RULES_KEY = "spark_tpu.sql.optimizer.excludedRules"


def excluded_rule_names(conf) -> Set[str]:
    """Parse `spark_tpu.sql.optimizer.excludedRules` (comma-separated
    rule names; `*` = every rule, i.e. optimizer off — the differential
    fuzzer's baseline/ablation lever)."""
    if conf is None:
        return set()
    raw = str(conf.get(EXCLUDED_RULES_KEY) or "")
    return {s.strip() for s in raw.split(",") if s.strip()}


def default_optimizer(conf=None, reorder_log=None, validator=None,
                      tracer=None) -> RuleExecutor:
    """`conf` enables the conf-gated batches (cost-based join reorder)
    and the excludedRules ablation lever; without it the pipeline is the
    conf-independent rule set (rule unit tests). `reorder_log` is a list
    the reorder rule appends decision records to (the executor threads
    it into the event log). `validator`/`tracer` are the plan-integrity
    hooks (analysis/plan_integrity.py) installed by the executor from
    `planChangeValidation` / `planChangeLog`."""
    from .join_reorder import CostBasedJoinReorder
    batches = [
        Batch("Rewrite", [RewriteDistinctAggregates()], strategy="once"),
        Batch("Filter pushdown", [
            CombineFilters(),
            PushFilterThroughProject(),
            PushFilterIntoScan(),
        ]),
        # after pushdown (selectivities read the settled Filter chains),
        # before pruning/collapse (which see the reordered tree)
        Batch("JoinReorder", [CostBasedJoinReorder(conf, reorder_log)],
              strategy="once"),
        Batch("Collapse", [CollapseProjectIntoAggregate()]),
        Batch("KeyAggs", [RewriteGroupKeyAggregates()], strategy="once"),
        Batch("Fold", [ConstantFolding()], strategy="once"),
        Batch("Prune", [PruneColumns()], strategy="once"),
    ]
    excluded = excluded_rule_names(conf)
    if excluded:
        kept = []
        for b in batches:
            rules = [r for r in b.rules
                     if "*" not in excluded and r.name not in excluded]
            if rules:
                kept.append(Batch(b.name, rules, b.strategy,
                                  b.max_iterations))
        batches = kept
    return RuleExecutor(batches, validator=validator, tracer=tracer)

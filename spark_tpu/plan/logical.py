"""Logical plan nodes.

The analogs of the reference's `plans/logical/basicLogicalOperators.scala`
(Project/Filter/Aggregate/Join/Sort/Limit/Range/Union). Plans are
immutable trees; `schema()` performs type resolution (the Analyzer's
job in `analysis/Analyzer.scala:172` — here resolution is eager and
name-based because the DataFrame API builds plans bottom-up, with
`AnalysisError` raised on unresolvable names/types).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from .. import types as T
from ..expr import (AnalysisError, Expression, SortOrder, structurally_equal)
from ..expr_agg import AggExpr


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    def schema(self) -> T.Schema:
        raise NotImplementedError

    def map_children(self, f: Callable[["LogicalPlan"], "LogicalPlan"]):
        if not self.children:
            return self
        new = copy.copy(self)
        new.children = tuple(f(c) for c in self.children)
        return new

    def transform_up(self, f) -> "LogicalPlan":
        node = self.map_children(lambda c: c.transform_up(f))
        out = f(node)
        return node if out is None else out

    def transform_down(self, f) -> "LogicalPlan":
        out = f(self)
        node = self if out is None else out
        return node.map_children(lambda c: c.transform_down(f))

    def output_names(self) -> List[str]:
        return self.schema().names

    def tree_string(self, depth: int = 0) -> str:
        line = "  " * depth + self.simple_string()
        return "\n".join([line] + [c.tree_string(depth + 1) for c in self.children])

    def simple_string(self) -> str:
        return type(self).__name__

    def same_result(self, other: "LogicalPlan") -> bool:
        """Structural plan equality for rule tests (reference: PlanTest.comparePlans)."""
        if type(self) is not type(other) or len(self.children) != len(other.children):
            return False
        sa = {k: v for k, v in self.__dict__.items() if k != "children"}
        sb = {k: v for k, v in other.__dict__.items() if k != "children"}
        for k in sa:
            if not _attr_eq(sa.get(k), sb.get(k)):
                return False
        return all(a.same_result(b) for a, b in zip(self.children, other.children))

    def __repr__(self):
        return self.tree_string()


def _attr_eq(a, b) -> bool:
    if isinstance(a, Expression) and isinstance(b, Expression):
        return structurally_equal(a, b)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_attr_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, AggExpr) and isinstance(b, AggExpr):
        return (a.out_name == b.out_name
                and type(a.func) is type(b.func)
                and (a.func.child is None) == (b.func.child is None)
                and (a.func.child is None
                     or structurally_equal(a.func.child, b.func.child)))
    try:
        return bool(a == b)
    except Exception:
        return a is b


class LeafPlan(LogicalPlan):
    pass


class ScalarSubqueryExpr(Expression):
    """An uncorrelated scalar subquery embedded in an expression
    (reference: ScalarSubquery in subquery.scala). The executor runs the
    subplan before tracing the outer query and substitutes its single
    value as a Literal — the host-driven analog of Spark's subquery
    stage execution."""

    def __init__(self, plan: LogicalPlan):
        self.plan = plan
        self.children = ()

    def dtype(self, schema):
        return self.plan.schema().fields[0].dtype

    def nullable(self, schema):
        return True  # empty result -> NULL

    def references(self):
        return set()

    def foldable(self):
        return False

    def __repr__(self):
        return "scalar-subquery(...)"


def iter_expressions(plan: LogicalPlan):
    """Yield every expression embedded anywhere in the plan — the single
    enumeration of expression-bearing slots (keep map_expressions' node
    cases in sync with this)."""
    stack = [plan]
    while stack:
        n = stack.pop()
        stack.extend(n.children)
        if isinstance(n, Project):
            yield from n.exprs
        elif isinstance(n, Filter):
            yield n.condition
        elif isinstance(n, Join):
            yield from n.left_keys
            yield from n.right_keys
            if n.condition is not None:
                yield n.condition
        elif isinstance(n, Aggregate):
            yield from n.group_exprs
            for a in n.agg_exprs:
                yield from a.func.children
        elif isinstance(n, Sort):
            for o in n.orders:
                yield o.child
        elif isinstance(n, WindowPlan):
            for w, _name in n.wexprs:
                yield from w.children
        elif isinstance(n, Generate):
            yield n.gen_expr


def iter_scans(plan: LogicalPlan):
    """Yield every Scan node (shared by the data-cache fingerprint, the
    AQE-caps key, and register_table invalidation — ONE walk to keep in
    sync, per round-4 review)."""
    if isinstance(plan, Scan):
        yield plan
    for c in plan.children:
        yield from iter_scans(c)


def map_expressions(plan: LogicalPlan, f) -> LogicalPlan:
    """Rebuild a plan with every embedded expression passed through
    `f: Expression -> Expression` (used for scalar-subquery substitution;
    the reference's QueryPlan.transformExpressions). Node cases must
    mirror iter_expressions."""
    import copy as _copy

    def walk(node: LogicalPlan) -> LogicalPlan:
        node = node.map_children(walk)
        if isinstance(node, Project):
            return Project(node.child, [f(e) for e in node.exprs])
        if isinstance(node, Filter):
            return Filter(node.child, f(node.condition))
        if isinstance(node, Join):
            return Join(node.left, node.right,
                        [f(k) for k in node.left_keys],
                        [f(k) for k in node.right_keys], node.how,
                        None if node.condition is None
                        else f(node.condition),
                        node.null_aware)
        if isinstance(node, Aggregate):
            aggs = []
            for a in node.agg_exprs:
                func = a.func
                if func.children:
                    func = func.with_args([f(c) for c in func.children])
                aggs.append(type(a)(func, a.out_name))
            return Aggregate(node.child, [f(g) for g in node.group_exprs],
                             aggs)
        if isinstance(node, Sort):
            return Sort(node.child, [SortOrder(f(o.child), o.ascending,
                                               o.nulls_first)
                                     for o in node.orders])
        if isinstance(node, WindowPlan):
            return WindowPlan(node.child,
                              [(w.map_children(f), name)
                               for w, name in node.wexprs])
        if isinstance(node, Generate):
            return Generate(node.child, f(node.gen_expr), node.out_name,
                            node.outer)
        return node

    return walk(plan)


class Range(LeafPlan):
    """spark.range analog (reference: org.apache.spark.sql.execution.basicPhysicalOperators RangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1):
        self.start = start
        self.end = end
        self.step = step
        self.children = ()

    def num_rows(self) -> int:
        return max(0, -(-(self.end - self.start) // self.step))

    def schema(self) -> T.Schema:
        return T.Schema([T.Field("id", T.LONG, nullable=False)])

    def simple_string(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class Scan(LeafPlan):
    """Scan of a cataloged table (V1 FileSourceScanExec / InMemoryScan analog).

    `source` is a TableSource (io.catalog) that knows its schema and can
    produce device batches, optionally with column pruning + predicate
    pushdown (the `SupportsPushDownFilters/RequiredColumns` mixins of the
    reference's DataSource V2 `connector/read/` API).
    """

    def __init__(self, source, required_columns: Optional[Sequence[str]] = None,
                 pushed_filters: Sequence[Expression] = ()):
        self.source = source
        self.required_columns = (tuple(required_columns)
                                 if required_columns is not None else None)
        self.pushed_filters = tuple(pushed_filters)
        self.children = ()

    def schema(self) -> T.Schema:
        full = self.source.schema()
        if self.required_columns is None:
            return full
        return T.Schema([full.field(n) for n in self.required_columns])

    def simple_string(self):
        cols = "*" if self.required_columns is None else ",".join(self.required_columns)
        f = f" pushed={list(self.pushed_filters)!r}" if self.pushed_filters else ""
        return f"Scan({self.source.name}, [{cols}]{f})"


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: Sequence[Expression]):
        self.children = (child,)
        self.exprs = tuple(exprs)

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        cs = self.child.schema()
        return T.Schema([T.Field(e.name(), e.dtype(cs), e.nullable(cs))
                         for e in self.exprs])

    def simple_string(self):
        return f"Project({list(self.exprs)!r})"


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: Expression):
        self.children = (child,)
        self.condition = condition

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        cond_t = self.condition.dtype(self.child.schema())
        if not isinstance(cond_t, T.BooleanType):
            raise AnalysisError(f"filter condition must be boolean, got {cond_t!r}")
        return self.child.schema()

    def simple_string(self):
        return f"Filter({self.condition!r})"


class Aggregate(LogicalPlan):
    def __init__(self, child: LogicalPlan, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[AggExpr]):
        self.children = (child,)
        self.group_exprs = tuple(group_exprs)
        self.agg_exprs = tuple(agg_exprs)

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        cs = self.child.schema()
        fields = [T.Field(g.name(), g.dtype(cs), g.nullable(cs))
                  for g in self.group_exprs]
        for a in self.agg_exprs:
            fields.append(T.Field(a.out_name, a.func.result_type(cs),
                                  a.func.result_nullable(cs)))
        return T.Schema(fields)

    def simple_string(self):
        return (f"Aggregate(groups={list(self.group_exprs)!r}, "
                f"aggs={list(self.agg_exprs)!r})")


JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti")


class Join(LogicalPlan):
    """Equi-join on key expression pairs (reference: logical Join +
    ExtractEquiJoinKeys). `condition` is an optional residual non-equi
    predicate applied post-match."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 how: str = "inner", condition: Optional[Expression] = None,
                 null_aware: bool = False):
        if how not in JOIN_TYPES:
            raise AnalysisError(f"unsupported join type {how!r}")
        if len(left_keys) != len(right_keys) or not left_keys:
            raise AnalysisError("join requires matching, non-empty key lists")
        if null_aware and how != "left_anti":
            raise AnalysisError("null_aware applies to left_anti only")
        self.children = (left, right)
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.how = how
        self.condition = condition
        # SQL NOT IN semantics (null-aware anti-join, reference: the
        # NAAJ path in SparkStrategies JoinSelection): any NULL in the
        # build keys empties the result; a NULL probe key only survives
        # when the build side is empty
        self.null_aware = null_aware

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def right_name_map(self) -> dict:
        """right-field name -> output name (collisions suffixed `_r`)."""
        taken = {f.name for f in self.left.schema().fields}
        m = {}
        for f in self.right.schema().fields:
            name = f.name
            while name in taken:
                name = name + "_r"
            m[f.name] = name
            taken.add(name)
        return m

    def schema(self) -> T.Schema:
        ls = self.left.schema()
        if self.how in ("left_semi", "left_anti"):
            return ls
        rs = self.right.schema()
        name_map = self.right_name_map()
        left_nullable = self.how in ("right", "full")
        right_nullable = self.how in ("left", "full")
        fields = [T.Field(f.name, f.dtype, f.nullable or left_nullable)
                  for f in ls.fields]
        for f in rs.fields:
            fields.append(T.Field(name_map[f.name], f.dtype,
                                  f.nullable or right_nullable))
        return T.Schema(fields)

    def simple_string(self):
        return (f"Join({self.how}, {list(self.left_keys)!r} = "
                f"{list(self.right_keys)!r}"
                + (f", cond={self.condition!r}" if self.condition is not None else "")
                + (", null_aware" if self.null_aware else "")
                + ")")


class WindowPlan(LogicalPlan):
    """Append window-function columns over ONE shared (partition, order)
    spec (reference: logical Window in basicLogicalOperators.scala;
    different specs become separate nodes)."""

    def __init__(self, child: LogicalPlan, wexprs: Sequence[Tuple]):
        # wexprs: (WindowExpr, out_name) pairs sharing one spec
        from ..window import WindowExpr
        if not wexprs:
            raise AnalysisError("Window requires at least one function")
        spec0 = wexprs[0][0].spec
        for w, _ in wexprs:
            if not isinstance(w, WindowExpr):
                raise AnalysisError(f"not a window expression: {w!r}")
            if (tuple(repr(p) for p in w.spec._partition)
                    != tuple(repr(p) for p in spec0._partition)
                    or tuple(repr(o) for o in w.spec._order)
                    != tuple(repr(o) for o in spec0._order)):
                raise AnalysisError(
                    "one Window node requires a shared window spec")
        self.children = (child,)
        self.wexprs = tuple(wexprs)

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        cs = self.child.schema()
        fields = list(cs.fields)
        for w, name in self.wexprs:
            fields.append(T.Field(name, w.dtype(cs), w.nullable(cs)))
        return T.Schema(fields)

    def simple_string(self):
        return f"Window({[(repr(w), n) for w, n in self.wexprs]!r})"


class Watermark(LogicalPlan):
    """Event-time watermark marker (reference: EventTimeWatermark in
    basicLogicalOperators.scala + WatermarkTracker.scala:1): schema
    passthrough; the streaming runtime reads (column, delay) to drop
    late rows and evict closed windows. Batch planning strips it."""

    def __init__(self, child: LogicalPlan, col_name: str, delay_us: int):
        self.children = (child,)
        self.col_name = col_name
        self.delay_us = int(delay_us)

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        return self.child.schema()

    def simple_string(self):
        return f"Watermark({self.col_name}, {self.delay_us}us)"


class Generate(LogicalPlan):
    """One output row per array element of `gen_expr` (explode) — the
    reference's logical Generate (`basicLogicalOperators.scala`) over
    `GenerateExec.scala:1`. Child columns replicate per element; the
    element column appends as `out_name`. `outer=True` keeps empty/NULL
    arrays as one NULL-element row (explode_outer)."""

    def __init__(self, child: LogicalPlan, gen_expr, out_name: str,
                 outer: bool = False):
        self.children = (child,)
        self.gen_expr = gen_expr
        self.out_name = out_name
        self.outer = outer

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        cs = self.child.schema()
        dt = self.gen_expr.dtype(cs)
        if not isinstance(dt, T.ArrayType):
            raise AnalysisError(
                f"explode() needs an array, got {dt!r}")
        # array columns do not replicate through a Generate (their
        # per-row slices have no cheap element-space gather); scalar
        # columns + the generated element column come out
        fields = [f for f in cs.fields
                  if not isinstance(f.dtype, T.ArrayType)]
        fields.append(T.Field(self.out_name, dt.element, True))
        return T.Schema(fields)

    def simple_string(self):
        return (f"Generate(explode{'_outer' if self.outer else ''}"
                f"({self.gen_expr!r}) AS {self.out_name})")


class Sort(LogicalPlan):
    def __init__(self, child: LogicalPlan, orders: Sequence[SortOrder]):
        self.children = (child,)
        self.orders = tuple(orders)

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        return self.child.schema()

    def simple_string(self):
        return f"Sort({list(self.orders)!r})"


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, n: int):
        self.children = (child,)
        self.n = n

    @property
    def child(self):
        return self.children[0]

    def schema(self) -> T.Schema:
        return self.child.schema()

    def simple_string(self):
        return f"Limit({self.n})"


class Union(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan):
        ls, rs = left.schema(), right.schema()
        if len(ls) != len(rs):
            raise AnalysisError("UNION requires same column count")
        self.children = (left, right)

    def schema(self) -> T.Schema:
        ls = self.children[0].schema()
        rs = self.children[1].schema()
        fields = []
        for a, b in zip(ls.fields, rs.fields):
            fields.append(T.Field(a.name, T.common_type(a.dtype, b.dtype),
                                  a.nullable or b.nullable))
        return T.Schema(fields)

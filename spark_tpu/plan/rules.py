"""Rule executor: fixed-point batches of plan-rewrite rules.

Direct analog of the reference's `catalyst/rules/RuleExecutor.scala`
(fixed-point vs once batches, per-rule effectiveness tracking a la
`QueryPlanningTracker.scala:93`).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Sequence

from .logical import LogicalPlan


class Rule:
    name: str = "rule"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        raise NotImplementedError


@dataclass
class Batch:
    name: str
    rules: Sequence[Rule]
    strategy: str = "fixed_point"  # or "once"
    max_iterations: int = 100


@dataclass
class RuleTiming:
    total_ns: int = 0
    invocations: int = 0
    effective: int = 0


class RuleExecutor:
    def __init__(self, batches: Sequence[Batch]):
        self.batches = list(batches)
        self.timings: Dict[str, RuleTiming] = {}

    def execute(self, plan: LogicalPlan) -> LogicalPlan:
        for batch in self.batches:
            iters = 1 if batch.strategy == "once" else batch.max_iterations
            for _ in range(iters):
                changed = False
                for rule in batch.rules:
                    t0 = time.perf_counter_ns()
                    new_plan = rule.apply(plan)
                    t = self.timings.setdefault(rule.name, RuleTiming())
                    t.total_ns += time.perf_counter_ns() - t0
                    t.invocations += 1
                    if new_plan is not plan and not new_plan.same_result(plan):
                        t.effective += 1
                        changed = True
                        plan = new_plan
                    else:
                        plan = new_plan
                if not changed:
                    break
            else:
                if batch.strategy == "fixed_point":
                    raise RuntimeError(
                        f"batch {batch.name!r} did not converge in "
                        f"{batch.max_iterations} iterations")
        return plan

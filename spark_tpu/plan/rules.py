"""Rule executor: fixed-point batches of plan-rewrite rules.

Direct analog of the reference's `catalyst/rules/RuleExecutor.scala`
(fixed-point vs once batches, per-rule effectiveness tracking a la
`QueryPlanningTracker.scala:93`), plus the plan-integrity seam: an
optional validator (per-effective-rule invariant checks + per-batch
determinism replay, `analysis/plan_integrity.py`) and an optional
tracer (the `PlanChangeLogger` analog feeding the event log's
`rule_trace` record).
"""

from __future__ import annotations

import time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

from .logical import LogicalPlan

#: True while the integrity validator replays a batch for the
#: determinism check. Rules with observable side channels (the join
#: reorder decision log) must stay silent during a replay — otherwise
#: the check itself would double-append their records. ContextVar, not
#: a module global: service sessions optimize on concurrent threads.
_IN_REPLAY: ContextVar[bool] = ContextVar(
    "spark_tpu_rule_replay", default=False)


def in_replay() -> bool:
    return _IN_REPLAY.get()


class Rule:
    name: str = "rule"

    #: Plan-integrity contract: True = this rule keeps the ROOT output
    #: schema (names/dtypes/nullability) byte-identical; False = the
    #: rule legitimately reshapes output schemas and the verifier skips
    #: the preservation check for it. None = undeclared — the verifier
    #: holds undeclared rules to the preservation contract and lint
    #: RL100 fails any concrete Rule subclass that doesn't declare
    #: explicitly in its own class body.
    schema_preserving: Optional[bool] = None

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        raise NotImplementedError


@dataclass
class Batch:
    name: str
    rules: Sequence[Rule]
    strategy: str = "fixed_point"  # or "once"
    max_iterations: int = 100


@dataclass
class RuleTiming:
    total_ns: int = 0
    invocations: int = 0
    effective: int = 0


class RuleExecutor:
    def __init__(self, batches: Sequence[Batch], validator=None,
                 tracer=None):
        self.batches = list(batches)
        self.timings: Dict[str, RuleTiming] = {}
        #: analysis.plan_integrity.PlanIntegrityValidator (or None):
        #: after_rule on every effective application, after_batch with a
        #: replay closure for the determinism check
        self.validator = validator
        #: analysis.plan_integrity.PlanChangeTracer (or None)
        self.tracer = tracer
        #: did the batch currently being observed rewrite the plan?
        self._batch_effective = False

    def execute(self, plan: LogicalPlan) -> LogicalPlan:
        for batch in self.batches:
            batch_input = plan
            plan = self._run_batch(batch, plan, observe=True)
            # a no-op batch replays trivially — only batches with at
            # least one effective application pay the determinism
            # replay (an extra full batch run)
            if self.validator is not None and self._batch_effective:
                self.validator.after_batch(
                    batch, batch_input, plan,
                    lambda p, b=batch: self._replay_batch(b, p))
        return plan

    def _replay_batch(self, batch: Batch, plan: LogicalPlan
                      ) -> LogicalPlan:
        """Side-effect-free re-run for the determinism check: no
        timings, no tracer/validator hooks, side channels silenced."""
        token = _IN_REPLAY.set(True)
        try:
            return self._run_batch(batch, plan, observe=False)
        finally:
            _IN_REPLAY.reset(token)

    def _run_batch(self, batch: Batch, plan: LogicalPlan,
                   observe: bool) -> LogicalPlan:
        iters = 1 if batch.strategy == "once" else batch.max_iterations
        if observe:
            self._batch_effective = False
        for _ in range(iters):
            changed = False
            for rule in batch.rules:
                t0 = time.perf_counter_ns()
                new_plan = rule.apply(plan)
                elapsed_ns = time.perf_counter_ns() - t0
                effective = (new_plan is not plan
                             and not new_plan.same_result(plan))
                if observe:
                    t = self.timings.setdefault(rule.name, RuleTiming())
                    t.total_ns += elapsed_ns
                    t.invocations += 1
                    if effective:
                        t.effective += 1
                    if self.tracer is not None:
                        self.tracer.after_rule(
                            batch.name, rule, plan, new_plan, effective,
                            elapsed_ns / 1e6)
                    if effective and self.validator is not None:
                        self.validator.after_rule(batch.name, rule,
                                                  plan, new_plan)
                if effective:
                    changed = True
                    if observe:
                        self._batch_effective = True
                plan = new_plan
            if not changed:
                break
        else:
            if batch.strategy == "fixed_point":
                raise RuntimeError(
                    f"batch {batch.name!r} did not converge in "
                    f"{batch.max_iterations} iterations")
        return plan

"""Runtime join-filter injection (reference: InjectRuntimeFilter.scala:1).

The planner-side rule of the runtime-filter subsystem: after exchange
insertion, walk the physical plan and, for each shuffle/broadcast join
whose build side is selective and small, wrap the probe-side subtree
(BELOW its exchange) in a `RuntimeFilterExec` that prunes probe rows
against a device Bloom filter + min/max key bounds built from the
build-side keys in-stage (execution/join.py kernels over sketch.py).

Creation-side extraction follows the reference's
`extractSelectiveFilterOverScan`: descend from the join's build child
through exchanges, joins (into the child the key column originates
from), aggregates (through group keys), sorts and limits, until a cheap
Project/Filter-over-leaf chain evaluates the key. Every descent step
only ever WIDENS the key set (join outputs, aggregate group keys and
limits are subsets of their origin columns), so the filter built from
the chain is a superset of the true build keys — pruning stays sound,
it just prunes less than a perfect filter would.

Injection preconditions:
- join type is probe-prunable (inner / left_semi: dropping a probe row
  with no build match cannot change the result);
- the creation chain is selective (a FilterExec or pushed scan filters
  — an unfiltered table filters nothing worth the build);
- estimated creation bytes <= runtimeFilter.creationSideThreshold
  (the chain is recomputed for the filter, reference-style).

The whole rule is a no-op when spark_tpu.sql.runtimeFilter.enabled is
false, and plans differ structurally on/off (the compiled-stage cache
keys on describe(), so toggling recompiles rather than reuses).
"""

from __future__ import annotations

import copy
from typing import Optional, Tuple

from ..expr import Alias, ColumnRef, Expression
from . import physical as P

ENABLED_KEY = "spark_tpu.sql.runtimeFilter.enabled"
THRESHOLD_KEY = "spark_tpu.sql.runtimeFilter.creationSideThreshold"
FPP_KEY = "spark_tpu.sql.runtimeFilter.expectedFpp"

#: join types where dropping a non-matching probe row preserves results
_PRUNABLE_JOINS = ("inner", "left_semi")


def estimate_rows_physical(node: P.PhysicalPlan) -> Optional[int]:
    """Upper-bound-ish row estimate over the PHYSICAL tree (the
    planner.estimate_rows analog after conversion; exchanges and
    filters pass through, inner joins take the FK max heuristic)."""
    if isinstance(node, P.ScanExec):
        return node.source.estimated_rows()
    if isinstance(node, P.RangeExec):
        return node.num_rows()
    if isinstance(node, P.InputExec):
        return node.load().capacity
    if isinstance(node, (P.ProjectExec, P.FilterExec, P.SortExec,
                         P.ExchangeExec, P.WindowExec,
                         P.HashAggregateExec, P.RuntimeFilterExec)):
        return estimate_rows_physical(node.children[0])
    if isinstance(node, P.LimitExec):
        child = estimate_rows_physical(node.children[0])
        return node.n if child is None else min(node.n, child)
    if isinstance(node, P.JoinExec):
        if node.how in ("left_semi", "left_anti"):
            return estimate_rows_physical(node.children[0])
        l = estimate_rows_physical(node.children[0])
        r = estimate_rows_physical(node.children[1])
        if node.how == "inner" and l is not None and r is not None:
            return max(l, r)
        return None
    if isinstance(node, P.UnionExec):
        l = estimate_rows_physical(node.children[0])
        r = estimate_rows_physical(node.children[1])
        if l is not None and r is not None:
            return l + r
    return None


def _plain_name(e: Expression) -> Optional[str]:
    while isinstance(e, Alias):
        e = e.child
    if isinstance(e, ColumnRef):
        return e.name()
    return None


def _resolves(e: Expression, schema) -> bool:
    try:
        e.dtype(schema)
        return True
    except Exception:
        return False


def _cheap_chain(node: P.PhysicalPlan) -> bool:
    """True when the subtree is only Project/Filter over one leaf —
    cheap enough to recompute for the filter build (the reference
    bounds its creation side the same way)."""
    while isinstance(node, (P.ProjectExec, P.FilterExec)):
        node = node.children[0]
    return isinstance(node, P.LeafExec)


def _chain_selective(node: P.PhysicalPlan) -> bool:
    """A creation chain is worth a filter only if something narrows it:
    a residual FilterExec or filters pushed into the scan."""
    while isinstance(node, (P.ProjectExec, P.FilterExec)):
        if isinstance(node, P.FilterExec):
            return True
        node = node.children[0]
    return isinstance(node, P.ScanExec) and bool(node.pushed_filters)


def _substitute(expr: Expression, mapping: dict) -> Expression:
    def f(node):
        if isinstance(node, ColumnRef) and node._name in mapping:
            return mapping[node._name]
        return node
    return expr.transform_up(f)


def extract_creation_side(node: P.PhysicalPlan, key: Expression
                          ) -> Optional[Tuple[P.PhysicalPlan, Expression]]:
    """Descend from a join's build child to the cheap chain the key
    column originates from. Returns (creation_plan, key_expr) with the
    key rewritten to evaluate against creation_plan's output, or None.
    Every hop preserves the superset property (see module docstring)."""
    if _cheap_chain(node) and _resolves(key, node.schema()):
        return node, key
    if isinstance(node, (P.ExchangeExec, P.SortExec, P.LimitExec,
                         P.RuntimeFilterExec)):
        return extract_creation_side(node.children[0], key)
    if isinstance(node, P.FilterExec):
        # descending past the filter widens the key set: still sound
        return extract_creation_side(node.children[0], key)
    if isinstance(node, P.ProjectExec):
        mapping = {}
        for e in node.exprs:
            if isinstance(e, Alias):
                mapping[e.name()] = e.child
            elif isinstance(e, ColumnRef):
                mapping[e.name()] = e
        new = _substitute(key, mapping)
        if _resolves(new, node.children[0].schema()):
            return extract_creation_side(node.children[0], new)
        return None
    if isinstance(node, P.JoinExec):
        name = _plain_name(key)
        if name is None:
            return None
        left_names = list(node.left.schema().names)
        if node.how in ("left_semi", "left_anti"):
            if name in left_names:
                return extract_creation_side(node.left, ColumnRef(name))
            return None
        out_names = list(node.schema().names)
        if name not in out_names:
            return None
        idx = out_names.index(name)
        n_left = len(left_names)
        if idx < n_left:
            return extract_creation_side(node.left,
                                         ColumnRef(left_names[idx]))
        right_names = list(node.right.schema().names)
        if idx - n_left >= len(right_names):
            return None
        return extract_creation_side(node.right,
                                     ColumnRef(right_names[idx - n_left]))
    if isinstance(node, P.HashAggregateExec):
        name = _plain_name(key)
        for g in node.group_exprs:
            if g.name() != name:
                continue
            base = g
            while isinstance(base, Alias):
                base = base.child
            if isinstance(base, ColumnRef):
                return extract_creation_side(node.children[0],
                                             ColumnRef(base.name()))
        return None
    return None


def inject_runtime_filters(plan: P.PhysicalPlan, conf
                           ) -> P.PhysicalPlan:
    """Bottom-up walk wrapping eligible joins' probe subtrees (below
    their exchange) in RuntimeFilterExec nodes. Tags are assigned by
    the planner's _assign_join_tags pass afterwards."""
    threshold = int(conf.get(THRESHOLD_KEY))
    fpp = float(conf.get(FPP_KEY))

    def walk(node):
        new_children = tuple(walk(c) for c in node.children)
        if new_children != node.children:
            node = copy.copy(node)
            node.children = new_children
        if isinstance(node, P.JoinExec) and node.how in _PRUNABLE_JOINS:
            injected = _try_inject(node, threshold, fpp)
            if injected is not None:
                node = injected
        return node

    return walk(plan)


def _try_inject(join: P.JoinExec, threshold: int, fpp: float
                ) -> Optional[P.JoinExec]:
    probe, build = join.children
    target = probe.children[0] if isinstance(probe, P.ExchangeExec) \
        else probe
    if isinstance(target, P.RuntimeFilterExec):
        return None  # one filter per probe side
    for pk, bk in zip(join.left_keys, join.right_keys):
        found = extract_creation_side(build, bk)
        if found is None:
            continue
        creation, build_key = found
        if creation is target:
            continue  # self-filter: the probe IS the creation chain
        if not _chain_selective(creation):
            continue
        rows = estimate_rows_physical(creation)
        if rows is None:
            continue
        width = 8 * max(1, len(creation.schema().fields))
        if rows * width > threshold:
            continue
        if not _resolves(pk, target.schema()):
            continue
        # bucketed: est_items sits verbatim in simple_string and hence
        # the stage-cache key; a raw scan row count would recompile the
        # stage per exact input size (analysis UNBUCKETED_CAPACITY).
        # Bloom sizing only rounds UP — false-positive rate can only
        # improve, results are unchanged by construction.
        from ..columnar import bucket_capacity
        rf = P.RuntimeFilterExec(target, creation, pk, build_key,
                                 est_items=bucket_capacity(max(int(rows), 8)),
                                 fpp=fpp)
        new_join = copy.copy(join)
        if isinstance(probe, P.ExchangeExec):
            new_ex = copy.copy(probe)
            new_ex.children = (rf,)
            new_join.children = (new_ex, build)
        else:
            new_join.children = (rf, build)
        return new_join
    return None

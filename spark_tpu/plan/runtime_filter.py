"""Runtime join-filter injection (reference: InjectRuntimeFilter.scala:1).

The planner-side rule of the runtime-filter subsystem: after exchange
insertion, walk the physical plan and, for each shuffle/broadcast join
whose build side is selective and small, wrap the probe-side subtree
(BELOW its exchange) in a `RuntimeFilterExec` that prunes probe rows
against a device Bloom filter + min/max key bounds built from the
build-side keys in-stage (execution/join.py kernels over sketch.py).

Creation-side extraction follows the reference's
`extractSelectiveFilterOverScan`: descend from the join's build child
through exchanges, joins (into the child the key column originates
from), aggregates (through group keys), sorts and limits, until a cheap
Project/Filter-over-leaf chain evaluates the key. Every descent step
only ever WIDENS the key set (join outputs, aggregate group keys and
limits are subsets of their origin columns), so the filter built from
the chain is a superset of the true build keys — pruning stays sound,
it just prunes less than a perfect filter would.

Injection preconditions:
- join type is probe-prunable (inner / left_semi: dropping a probe row
  with no build match cannot change the result);
- the creation chain is selective (a FilterExec or pushed scan filters
  — an unfiltered table filters nothing worth the build);
- estimated creation bytes <= runtimeFilter.creationSideThreshold
  (the chain is recomputed for the filter, reference-style).

The whole rule is a no-op when spark_tpu.sql.runtimeFilter.enabled is
false, and plans differ structurally on/off (the compiled-stage cache
keys on describe(), so toggling recompiles rather than reuses).
"""

from __future__ import annotations

import copy
from typing import Optional, Tuple

from ..expr import Alias, ColumnRef, Expression
from . import physical as P

ENABLED_KEY = "spark_tpu.sql.runtimeFilter.enabled"
THRESHOLD_KEY = "spark_tpu.sql.runtimeFilter.creationSideThreshold"
FPP_KEY = "spark_tpu.sql.runtimeFilter.expectedFpp"
SEMI_KEY = "spark_tpu.sql.runtimeFilter.semiAwareCreation"

#: join types where dropping a non-matching probe row preserves results
_PRUNABLE_JOINS = ("inner", "left_semi")


def estimate_rows_physical(node: P.PhysicalPlan) -> Optional[int]:
    """Upper-bound-ish row estimate over the PHYSICAL tree (the
    planner.estimate_rows analog after conversion; exchanges and
    filters pass through, inner joins take the FK max heuristic)."""
    if isinstance(node, P.ScanExec):
        return node.source.estimated_rows()
    if isinstance(node, P.RangeExec):
        return node.num_rows()
    if isinstance(node, P.InputExec):
        return node.load().capacity
    if isinstance(node, (P.ProjectExec, P.FilterExec, P.SortExec,
                         P.ExchangeExec, P.WindowExec,
                         P.HashAggregateExec, P.RuntimeFilterExec)):
        return estimate_rows_physical(node.children[0])
    if isinstance(node, P.LimitExec):
        child = estimate_rows_physical(node.children[0])
        return node.n if child is None else min(node.n, child)
    if isinstance(node, P.JoinExec):
        if node.how in ("left_semi", "left_anti"):
            return estimate_rows_physical(node.children[0])
        l = estimate_rows_physical(node.children[0])
        r = estimate_rows_physical(node.children[1])
        if node.how == "inner" and l is not None and r is not None:
            return max(l, r)
        return None
    if isinstance(node, P.UnionExec):
        l = estimate_rows_physical(node.children[0])
        r = estimate_rows_physical(node.children[1])
        if l is not None and r is not None:
            return l + r
    return None


def _plain_name(e: Expression) -> Optional[str]:
    while isinstance(e, Alias):
        e = e.child
    if isinstance(e, ColumnRef):
        return e.name()
    return None


def _resolves(e: Expression, schema) -> bool:
    try:
        e.dtype(schema)
        return True
    except Exception:
        return False


def _cheap_chain(node: P.PhysicalPlan) -> bool:
    """True when the subtree is only Project/Filter over one leaf —
    cheap enough to recompute for the filter build (the reference
    bounds its creation side the same way)."""
    while isinstance(node, (P.ProjectExec, P.FilterExec)):
        node = node.children[0]
    return isinstance(node, P.LeafExec)


def _chain_selective(node: P.PhysicalPlan) -> bool:
    """A creation chain is worth a filter only if something narrows it:
    a residual FilterExec, filters pushed into the scan, or (for a
    synthesized semi-narrowed creation) a selective side of the semi."""
    while isinstance(node, (P.ProjectExec, P.FilterExec)):
        if isinstance(node, P.FilterExec):
            return True
        node = node.children[0]
    if isinstance(node, P.JoinExec) and node.creation_side:
        return (_chain_selective(node.children[0])
                or _chain_selective(node.children[1]))
    return isinstance(node, P.ScanExec) and bool(node.pushed_filters)


def _substitute(expr: Expression, mapping: dict) -> Expression:
    def f(node):
        if isinstance(node, ColumnRef) and node._name in mapping:
            return mapping[node._name]
        return node
    return expr.transform_up(f)


def _semi_other(node: P.PhysicalPlan) -> Optional[P.PhysicalPlan]:
    """The build side of a SYNTHESIZED creation semi: a cheap,
    recomputable copy of `node` with pass-throughs stripped. Cheap
    Project/Filter-over-leaf chains are shared verbatim (the documented
    creation DAG); equi-joins of cheap sides are shallow-copied with
    `creation_side` set so the planner tags them in the cj namespace.
    Dropping a Sort/Limit/RuntimeFilter hop only WIDENS the semi's keep
    set — still a superset of the true build keys, so still sound."""
    while isinstance(node, (P.ExchangeExec, P.SortExec, P.LimitExec,
                            P.RuntimeFilterExec)):
        node = node.children[0]
    if _cheap_chain(node):
        return node
    if isinstance(node, P.JoinExec) and node.how in ("inner",
                                                     "left_semi"):
        l = _semi_other(node.left)
        r = _semi_other(node.right)
        if l is None or r is None:
            return None
        new = copy.copy(node)
        new.creation_side = True
        new.children = (l, r)
        return new
    return None


def _creation_anchor(node: P.PhysicalPlan) -> P.PhysicalPlan:
    """The original-tree node a (possibly nested-synthesized) creation
    chain bottoms out at: synthesized left-semis preserve their left
    child's schema, so the anchor's schema IS the creation's schema."""
    while isinstance(node, P.JoinExec) and node.creation_side:
        node = node.children[0]
    return node


def _tree_contains(node: P.PhysicalPlan, target: P.PhysicalPlan) -> bool:
    if node is target:
        return True
    return any(_tree_contains(c, target) for c in node.children)


def _keys_transparent(node: P.PhysicalPlan, target: P.PhysicalPlan,
                      names) -> bool:
    """True when every descent hop from `node` down to `target` passes
    the columns in `names` through UNCHANGED (same name, same value),
    so an ancestor join's key exprs resolve against target's schema to
    the values they had at `node`. Name-resolution alone is NOT enough:
    a Project that aliases a different expr onto a key's name while the
    underlying relation keeps a same-named physical column would bind
    the wrong column and build the filter from a non-superset — so a
    shadowing Project, a join whose children both carry a key name
    (ambiguous binding), or an aggregate that computes one fails the
    check and the synthesis is skipped."""
    if node is target:
        return True
    if isinstance(node, P.JoinExec) and node.creation_side:
        return _keys_transparent(node.children[0], target, names)
    if isinstance(node, (P.ExchangeExec, P.SortExec, P.LimitExec,
                         P.RuntimeFilterExec, P.FilterExec)):
        return _keys_transparent(node.children[0], target, names)
    if isinstance(node, P.ProjectExec):
        for e in node.exprs:
            if isinstance(e, Alias) and e.name() in names:
                base = e.child
                if not (isinstance(base, ColumnRef)
                        and base.name() == e.name()):
                    return False
        return _keys_transparent(node.children[0], target, names)
    if isinstance(node, P.JoinExec):
        l_names = set(node.left.schema().names)
        r_names = set(node.right.schema().names)
        if any(n in l_names and n in r_names for n in names):
            return False
        for child in node.children:
            if _tree_contains(child, target):
                side = l_names if child is node.children[0] else r_names
                if not all(n in side for n in names):
                    return False
                return _keys_transparent(child, target, names)
        return False
    if isinstance(node, P.HashAggregateExec):
        for n in names:
            ok = False
            for g in node.group_exprs:
                if g.name() != n:
                    continue
                base = g
                while isinstance(base, Alias):
                    base = base.child
                ok = isinstance(base, ColumnRef) and base.name() == n
            if not ok:
                return False
        return _keys_transparent(node.children[0], target, names)
    return False


def _synthesize_semi(join: P.JoinExec, side: str,
                     sub: Tuple[P.PhysicalPlan, Expression]
                     ) -> Optional[Tuple[P.PhysicalPlan, Expression]]:
    """Wrap a creation chain extracted from `join`'s `side` child in a
    left-semi against the OTHER child, so the creation keys inherit the
    other side's narrowing (Q5: customer inherits the nation<-region
    semi-effect) instead of widening past it. Ignoring the join's
    residual condition (and any Sort/Limit dropped by `_semi_other`)
    only widens the keep set, so the synthesized chain still yields a
    superset of the true build keys."""
    creation, ckey = sub
    if side == "left":
        other_child, keys_self, keys_other = \
            join.right, join.left_keys, join.right_keys
    else:
        other_child, keys_self, keys_other = \
            join.left, join.right_keys, join.left_keys
    if not keys_self:
        return None
    other = _semi_other(other_child)
    if other is None or not _chain_selective(other):
        return None  # nothing to inherit: plain descent is equivalent
    # the join keys must survive the descent: a Project hop may have
    # renamed them away from creation's output
    if not all(_resolves(k, creation.schema()) for k in keys_self):
        return None
    if not all(_resolves(k, other.schema()) for k in keys_other):
        return None
    # ... and resolve to the SAME VALUES they had at the join's child:
    # name resolution alone would let a shadowing Project (a different
    # expr aliased onto a key name over a relation that keeps a
    # same-named physical column) bind the wrong column and build the
    # filter from a non-superset — silently wrong results
    names = [_plain_name(k) for k in keys_self]
    if any(n is None for n in names):
        return None
    self_child = join.left if side == "left" else join.right
    if not _keys_transparent(self_child, _creation_anchor(creation),
                             names):
        return None
    semi = P.JoinExec(creation, other, keys_self, keys_other,
                      how="left_semi", condition=None,
                      out_schema=creation.schema())
    semi.creation_side = True
    return semi, ckey


def extract_creation_side(node: P.PhysicalPlan, key: Expression,
                          semi_aware: bool = False
                          ) -> Optional[Tuple[P.PhysicalPlan, Expression]]:
    """Descend from a join's build child to the cheap chain the key
    column originates from. Returns (creation_plan, key_expr) with the
    key rewritten to evaluate against creation_plan's output, or None.
    Every hop preserves the superset property (see module docstring).
    With `semi_aware`, a descent through an equi-join whose other side
    is selective keeps that side's effect as a synthesized left-semi
    (`runtimeFilter.semiAwareCreation`; single-chip only — the caller
    gates on mesh size, see the conf doc)."""
    if _cheap_chain(node) and _resolves(key, node.schema()):
        return node, key
    if isinstance(node, (P.ExchangeExec, P.SortExec, P.LimitExec,
                         P.RuntimeFilterExec)):
        return extract_creation_side(node.children[0], key, semi_aware)
    if isinstance(node, P.FilterExec):
        # descending past the filter widens the key set: still sound
        return extract_creation_side(node.children[0], key, semi_aware)
    if isinstance(node, P.ProjectExec):
        mapping = {}
        for e in node.exprs:
            if isinstance(e, Alias):
                mapping[e.name()] = e.child
            elif isinstance(e, ColumnRef):
                mapping[e.name()] = e
        new = _substitute(key, mapping)
        if _resolves(new, node.children[0].schema()):
            return extract_creation_side(node.children[0], new,
                                         semi_aware)
        return None
    if isinstance(node, P.JoinExec):
        name = _plain_name(key)
        if name is None:
            return None
        left_names = list(node.left.schema().names)
        if node.how in ("left_semi", "left_anti"):
            if name not in left_names:
                return None
            sub = extract_creation_side(node.left, ColumnRef(name),
                                        semi_aware)
            if semi_aware and sub is not None \
                    and node.how == "left_semi":
                semi = _synthesize_semi(node, "left", sub)
                if semi is not None:
                    return semi
            return sub
        out_names = list(node.schema().names)
        if name not in out_names:
            return None
        idx = out_names.index(name)
        n_left = len(left_names)
        if idx < n_left:
            sub = extract_creation_side(node.left,
                                        ColumnRef(left_names[idx]),
                                        semi_aware)
            side = "left"
        else:
            right_names = list(node.right.schema().names)
            if idx - n_left >= len(right_names):
                return None
            sub = extract_creation_side(
                node.right, ColumnRef(right_names[idx - n_left]),
                semi_aware)
            side = "right"
        if semi_aware and sub is not None and node.how == "inner":
            semi = _synthesize_semi(node, side, sub)
            if semi is not None:
                return semi
        return sub
    if isinstance(node, P.HashAggregateExec):
        name = _plain_name(key)
        for g in node.group_exprs:
            if g.name() != name:
                continue
            base = g
            while isinstance(base, Alias):
                base = base.child
            if isinstance(base, ColumnRef):
                return extract_creation_side(node.children[0],
                                             ColumnRef(base.name()),
                                             semi_aware)
        return None
    return None


def inject_runtime_filters(plan: P.PhysicalPlan, conf
                           ) -> P.PhysicalPlan:
    """Bottom-up walk wrapping eligible joins' probe subtrees (below
    their exchange) in RuntimeFilterExec nodes. Tags are assigned by
    the planner's _assign_join_tags pass afterwards."""
    threshold = int(conf.get(THRESHOLD_KEY))
    fpp = float(conf.get(FPP_KEY))
    # synthesized creation semis are sound only when every shard sees
    # the full other side — i.e. single chip (see the conf doc)
    semi_aware = bool(conf.get(SEMI_KEY)) \
        and int(conf.get("spark_tpu.sql.mesh.size")) <= 1

    def walk(node):
        new_children = tuple(walk(c) for c in node.children)
        if new_children != node.children:
            node = copy.copy(node)
            node.children = new_children
        if isinstance(node, P.JoinExec) and node.how in _PRUNABLE_JOINS:
            injected = _try_inject(node, threshold, fpp, semi_aware)
            if injected is not None:
                node = injected
        return node

    return walk(plan)


def _try_inject(join: P.JoinExec, threshold: int, fpp: float,
                semi_aware: bool = False) -> Optional[P.JoinExec]:
    probe, build = join.children
    target = probe.children[0] if isinstance(probe, P.ExchangeExec) \
        else probe
    if isinstance(target, P.RuntimeFilterExec):
        return None  # one filter per probe side
    for pk, bk in zip(join.left_keys, join.right_keys):
        found = extract_creation_side(build, bk, semi_aware)
        if found is None:
            continue
        creation, build_key = found
        if creation is target:
            continue  # self-filter: the probe IS the creation chain
        if not _chain_selective(creation):
            continue
        rows = estimate_rows_physical(creation)
        if rows is None:
            continue
        width = 8 * max(1, len(creation.schema().fields))
        if rows * width > threshold:
            continue
        if not _resolves(pk, target.schema()):
            continue
        # bucketed: est_items sits verbatim in simple_string and hence
        # the stage-cache key; a raw scan row count would recompile the
        # stage per exact input size (analysis UNBUCKETED_CAPACITY).
        # Bloom sizing only rounds UP — false-positive rate can only
        # improve, results are unchanged by construction.
        from ..columnar import bucket_capacity
        rf = P.RuntimeFilterExec(target, creation, pk, build_key,
                                 est_items=bucket_capacity(max(int(rows), 8)),
                                 fpp=fpp)
        new_join = copy.copy(join)
        if isinstance(probe, P.ExchangeExec):
            new_ex = copy.copy(probe)
            new_ex.children = (rf,)
            new_join.children = (new_ex, build)
        else:
            new_join.children = (rf, build)
        return new_join
    return None

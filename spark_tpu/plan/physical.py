"""Physical plan: partitioning algebra + executable operators.

The partitioning algebra mirrors the reference's
`plans/physical/partitioning.scala` (`Distribution:31`,
`HashPartitioning:212`); operators mirror `execution/SparkPlan.scala`
(`requiredChildDistribution`, `outputPartitioning`) but `compute` builds a
*traced* jnp program over whole Batches instead of an RDD of row
iterators — the executor jits the composed tree, so XLA fusion plays the
role of `WholeStageCodegenExec.scala:626`.
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column, bucket_capacity
from ..config import Conf
from ..expr import (Alias, AnalysisError, Expression, SortOrder, Vec)
from ..expr_agg import AggExpr
from ..execution import aggregate as agg_kernels
from ..execution import join as join_kernels
from ..execution import sort as sort_kernels


# ---------------------------------------------------------------------------
# Partitioning algebra (reference: partitioning.scala)
# ---------------------------------------------------------------------------

class Distribution:
    pass


@dataclass(frozen=True)
class UnspecifiedDistribution(Distribution):
    pass


@dataclass(frozen=True)
class AllTuples(Distribution):
    """All rows co-located in one logical partition."""


@dataclass(frozen=True)
class ClusteredDistribution(Distribution):
    keys: Tuple[str, ...]


@dataclass(frozen=True)
class BroadcastDistribution(Distribution):
    """Full copy on every shard."""


@dataclass(frozen=True)
class OrderedDistribution(Distribution):
    """Rows range-partitioned by sort key: shard i's keys all <= shard
    i+1's (reference: OrderedDistribution in partitioning.scala:79)."""

    order_key: Tuple[str, ...]  # repr of the SortOrders (equality basis)


class Partitioning:
    num_partitions: int = 1

    def satisfies(self, dist: Distribution) -> bool:
        if isinstance(dist, UnspecifiedDistribution):
            return True
        return False


@dataclass(frozen=True)
class SinglePartition(Partitioning):
    num_partitions: int = 1

    def satisfies(self, dist):
        return not isinstance(dist, BroadcastDistribution)


@dataclass(frozen=True)
class HashPartitioning(Partitioning):
    keys: Tuple[str, ...] = ()
    num_partitions: int = 1

    def satisfies(self, dist):
        if isinstance(dist, UnspecifiedDistribution):
            return True
        if isinstance(dist, ClusteredDistribution):
            return set(self.keys).issubset(set(dist.keys)) and len(self.keys) > 0
        return False


@dataclass(frozen=True)
class Replicated(Partitioning):
    num_partitions: int = 1

    def satisfies(self, dist):
        return isinstance(dist, (UnspecifiedDistribution, BroadcastDistribution))


@dataclass(frozen=True)
class RangePartitioning(Partitioning):
    """Contiguous key ranges over the mesh axis in shard order
    (reference: RangePartitioning, partitioning.scala:255). `orders`
    carries the actual SortOrder objects for the exchange lowering;
    equality/hashing uses their repr (SortOrder overloads no __eq__)."""

    order_key: Tuple[str, ...] = ()
    num_partitions: int = 1
    orders: Tuple = dataclasses.field(default=(), compare=False, hash=False)

    def satisfies(self, dist):
        if isinstance(dist, UnspecifiedDistribution):
            return True
        if isinstance(dist, OrderedDistribution):
            return self.order_key == dist.order_key
        return False


@dataclass(frozen=True)
class UnknownPartitioning(Partitioning):
    num_partitions: int = 1


# ---------------------------------------------------------------------------
# Execution context
# ---------------------------------------------------------------------------

class ExecContext:
    """Per-execution state threaded through `compute` calls: conf, runtime
    flags (traced scalars surfaced to the host, e.g. join-capacity
    overflow), and per-operator metrics (the SQLMetrics analog).

    When running inside `shard_map` over a mesh, `axis_name`/`n_shards`
    identify the data axis: leaves synthesize only their stripe and
    ExchangeExec lowers to collectives (parallel/shuffle.py)."""

    def __init__(self, conf: Conf, axis_name: Optional[str] = None,
                 n_shards: int = 1):
        self.conf = conf
        self.axis_name = axis_name
        self.n_shards = n_shards
        self.flags: Dict[str, object] = {}
        self.metrics: Dict[str, object] = {}

    def add_flag(self, name: str, value) -> None:
        if name in self.flags:
            self.flags[name] = self.flags[name] | value
        else:
            self.flags[name] = value

    def add_metric(self, name: str, value) -> None:
        # registered prefixes only (observability/metrics.py): an
        # unregistered name would flow into the event log but silently
        # miss every history summary — fail at trace time instead.
        # scripts/metrics_lint.py enforces the same statically.
        from ..observability.metrics import is_registered_metric
        if not is_registered_metric(name):
            raise ValueError(
                f"unregistered metric name {name!r}: add its prefix to "
                f"observability.metrics.METRIC_PREFIXES and a history "
                f"summary consumer")
        self.metrics[name] = value


# ---------------------------------------------------------------------------
# Operators
# ---------------------------------------------------------------------------

class PhysicalPlan:
    children: Tuple["PhysicalPlan", ...] = ()

    def schema(self) -> T.Schema:
        raise NotImplementedError

    def output_partitioning(self) -> Partitioning:
        return SinglePartition()

    def required_child_distributions(self) -> List[Distribution]:
        return [UnspecifiedDistribution() for _ in self.children]

    def compute(self, ctx: ExecContext, inputs: List[Batch]) -> Batch:
        raise NotImplementedError

    def describe(self) -> str:
        """Stable structural fingerprint for the compiled-stage cache
        (plays the role of the Janino cache key in CodeGenerator.scala:1435)."""
        parts = [self.simple_string()]
        for c in self.children:
            parts.append(c.describe())
        return "(" + " ".join(parts) + ")"

    def simple_string(self) -> str:
        return type(self).__name__

    def tree_string(self, depth: int = 0) -> str:
        line = "  " * depth + self.simple_string()
        return "\n".join([line] + [c.tree_string(depth + 1)
                                   for c in self.children])

    def __repr__(self):
        return self.tree_string()


class LeafExec(PhysicalPlan):
    """Leaves either synthesize data in-trace (Range) or consume a host
    -loaded Batch passed as a jit argument (Scan)."""

    #: True when the executor must load and pass a Batch argument
    needs_input = False

    #: mesh data-axis size the planner targeted (1 = single chip). When
    #: >1, the leaf's rows are sharded over the axis, so its output
    #: partitioning is unknown and exchanges get inserted above it.
    dist_n: int = 1

    def output_partitioning(self):
        if self.dist_n > 1:
            return UnknownPartitioning(self.dist_n)
        return SinglePartition()

    def load(self):  # host side
        raise NotImplementedError


class RangeExec(LeafExec):
    def __init__(self, start: int, end: int, step: int = 1):
        self.start, self.end, self.step = start, end, step
        self.children = ()

    def schema(self):
        return T.Schema([T.Field("id", T.LONG, nullable=False)])

    def num_rows(self) -> int:
        return max(0, -(-(self.end - self.start) // self.step))

    def compute(self, ctx, inputs):
        n = self.num_rows()
        cap = bucket_capacity(n)
        bits = self._id_bits()
        if ctx.axis_name is not None:
            # synthesize only this shard's contiguous stripe
            shards = ctx.n_shards
            cap += (-cap) % shards
            local = cap // shards
            i = jax.lax.axis_index(ctx.axis_name)
            base = i.astype(jnp.int64) * local
            offs = base + jnp.arange(local, dtype=jnp.int64)
            ids = self.start + self.step * offs
            return Batch({"id": Column(ids, T.LONG, bits=bits)}, offs < n)
        ids = self.start + self.step * jnp.arange(cap, dtype=jnp.int64)
        sel = jnp.arange(cap) < n
        return Batch({"id": Column(ids, T.LONG, bits=bits)}, sel)

    def _id_bits(self) -> Optional[int]:
        """Static id bound: values in [0, 2^bits) when the range is
        non-negative. Capacity padding (bucket rounding, chunk tails,
        and shard-multiple rounding) can synthesize ids past `end`;
        2x num_rows plus a generous shard-rounding slack bounds every
        padding scheme used."""
        if self.start < 0 or self.step < 0:
            return None
        hi = self.start + self.step * (2 * max(self.num_rows(), 8) + 8192)
        return max(1, int(np.ceil(np.log2(max(hi, 2)))))

    def simple_string(self):
        return f"RangeExec({self.start},{self.end},{self.step})"


class ScanExec(LeafExec):
    needs_input = True

    def __init__(self, source, required_columns, pushed_filters):
        self.source = source
        self.required_columns = required_columns
        self.pushed_filters = tuple(pushed_filters)
        self.children = ()

    def schema(self):
        full = self.source.schema()
        if self.required_columns is None:
            return full
        return T.Schema([full.field(n) for n in self.required_columns])

    def load(self) -> Batch:
        return self.source.load(self.required_columns, self.pushed_filters)

    def compute(self, ctx, inputs):
        # the executor substitutes the loaded batch
        raise RuntimeError("ScanExec.compute is handled by the executor")

    def simple_string(self):
        cols = "*" if self.required_columns is None else \
            ",".join(self.required_columns)
        return (f"ScanExec({self.source.name},[{cols}],"
                f"pushed={[repr(f) for f in self.pushed_filters]})")


class InputExec(LeafExec):
    """A leaf holding an already-computed device Batch (e.g. the result of
    a streamed aggregation) — the analog of a materialized QueryStageExec
    in the reference's AQE loop (`AdaptiveSparkPlanExec.scala:64`)."""

    needs_input = True

    def __init__(self, batch: Batch, schema: T.Schema, label: str = "input"):
        self._batch = batch
        self._schema = schema
        self.label = label
        self.children = ()

    def schema(self):
        return self._schema

    def load(self) -> Batch:
        return self._batch

    def compute(self, ctx, inputs):
        raise RuntimeError("InputExec.compute is handled by the executor")

    def simple_string(self):
        return f"InputExec({self.label},{self._schema!r})"


class UnaryExec(PhysicalPlan):
    @property
    def child(self) -> PhysicalPlan:
        return self.children[0]

    def output_partitioning(self):
        return self.child.output_partitioning()


class ProjectExec(UnaryExec):
    def __init__(self, child: PhysicalPlan, exprs: Sequence[Expression]):
        self.children = (child,)
        self.exprs = tuple(exprs)

    def schema(self):
        cs = self.child.schema()
        return T.Schema([T.Field(e.name(), e.dtype(cs), e.nullable(cs))
                         for e in self.exprs])

    def compute(self, ctx, inputs):
        batch = inputs[0]
        cap = batch.capacity
        cols = {}
        for e in self.exprs:
            v = e.eval(batch)
            data = v.data
            if data is None:
                raise AnalysisError(f"cannot project host-only value {e!r}")
            if np.ndim(data) == 0:
                data = jnp.broadcast_to(data, (cap,))
            validity = v.validity
            if validity is not None and np.ndim(validity) == 0:
                validity = jnp.broadcast_to(validity, (cap,))
            cols[e.name()] = Column(data, v.dtype, validity, v.dictionary,
                                    offsets=v.offsets,
                                    elem_validity=v.elem_validity)
        return Batch(cols, batch.selection)

    def simple_string(self):
        return f"ProjectExec({[repr(e) for e in self.exprs]})"


class FilterExec(UnaryExec):
    def __init__(self, child: PhysicalPlan, condition: Expression):
        self.children = (child,)
        self.condition = condition

    def schema(self):
        return self.child.schema()

    def compute(self, ctx, inputs):
        batch = inputs[0]
        v = self.condition.eval(batch)
        keep = v.data
        if v.validity is not None:
            keep = keep & v.validity  # NULL predicate -> drop row
        if np.ndim(keep) == 0:
            keep = jnp.broadcast_to(keep, (batch.capacity,))
        sel = keep if batch.selection is None else (batch.selection & keep)
        return batch.with_selection(sel)

    def simple_string(self):
        return f"FilterExec({self.condition!r})"


class HashAggregateExec(UnaryExec):
    """Trace-time choice between dense-domain scatter aggregation and the
    sort-based general path (see execution/aggregate.py). `mode` follows
    the reference's partial/final split (`AggUtils.scala`):

    - complete: update + reduce + finalize in one node;
    - partial:  update + reduce, outputs raw accumulator columns;
    - final:    re-reduces accumulator columns by key, then finalizes.
    """

    def __init__(self, child: PhysicalPlan, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[AggExpr], mode: str = "complete",
                 est_groups: Optional[int] = None):
        assert mode in ("complete", "partial", "final")
        self.children = (child,)
        self.group_exprs = tuple(group_exprs)
        self.agg_exprs = tuple(agg_exprs)
        self.mode = mode
        self.est_groups = est_groups
        self.tag = "a0"

    def _child_schema_for_types(self) -> T.Schema:
        cs = self.child.schema()
        if self.mode == "final":
            # accumulator dtypes are schema-independent; group types come
            # from the partial output schema
            return cs
        return cs

    def _acc_col_name(self, i: int, j: int, spec) -> str:
        return f"__acc_{i}_{j}_{spec.suffix}"

    def schema(self):
        cs = self.child.schema()
        fields = [T.Field(g.name(), g.dtype(cs), g.nullable(cs))
                  for g in self.group_exprs]
        if self.mode == "partial":
            base = self._base_schema()
            for i, a in enumerate(self.agg_exprs):
                for j, spec in enumerate(a.func.accumulators(base)):
                    fields.append(T.Field(
                        self._acc_col_name(i, j, spec),
                        _np_to_logical(spec.np_dtype), False))
        else:
            base = self._base_schema()
            for a in self.agg_exprs:
                fields.append(T.Field(a.out_name, a.func.result_type(base),
                                      a.func.result_nullable(base)))
        return T.Schema(fields)

    def _base_schema(self) -> T.Schema:
        """Schema the aggregate functions' children resolve against: the
        pre-aggregation input schema. A FINAL stage looks through its
        exchange to its own partial stage (or a spliced InputExec's
        stashed schema); complete/partial stages resolve against their
        direct child — which may itself be an INDEPENDENT aggregate
        (nested aggregation, e.g. max over a grouped subquery) whose
        OUTPUT schema is exactly the right base."""
        node: PhysicalPlan = self.children[0]
        while isinstance(node, ExchangeExec):
            node = node.children[0]
        if self.mode == "final":
            if isinstance(node, HashAggregateExec):
                return node._base_schema()
            stashed = getattr(node, "_agg_base_schema", None)
            if stashed is not None:
                return stashed
        return node.schema()

    def compute(self, ctx, inputs):
        batch = inputs[0]
        base = self._base_schema()
        sel = batch.selection

        if any(getattr(a.func, "positional", False)
               for a in self.agg_exprs):
            return self._compute_positional(ctx, batch, base)

        key_vecs = [g.eval(batch) for g in self.group_exprs]
        if self.mode == "final":
            specs = [a.func.accumulators(base) for a in self.agg_exprs]
            contribs = []
            for i, a in enumerate(self.agg_exprs):
                row = []
                for j, spec in enumerate(specs[i]):
                    col = batch.columns[self._acc_col_name(i, j, spec)]
                    data = col.data
                    if sel is not None:
                        data = jnp.where(sel, data, jnp.asarray(spec.neutral))
                    row.append(data)
                contribs.append(row)
        else:
            specs = [a.func.accumulators(base) for a in self.agg_exprs]
            contribs = self._updates(batch, sel, ctx)

        domains = [agg_kernels.key_domain(g, v)
                   for g, v in zip(self.group_exprs, key_vecs)]
        max_domain = int(ctx.conf.get("spark_tpu.sql.aggregate.maxDirectDomain"))
        cs = self.child.schema()
        nullables = [g.nullable(cs) for g in self.group_exprs]
        spans = agg_kernels.key_spans(
            nullables, [d for d in domains if d is not None])
        use_direct = (all(d is not None for d in domains)
                      and int(np.prod(list(spans) or [1])) <= max_domain)

        if use_direct:
            key_arrays, key_valids, accs, occupied = \
                agg_kernels.direct_aggregate(
                    key_vecs, domains, spans, contribs, specs, sel,
                    kernel_mode=str(ctx.conf.get(
                        "spark_tpu.sql.aggregate.kernelMode")),
                    merge=(self.mode == "final"),
                    reuse_count=None if self.mode == "final"
                    else self._occupancy_reuse(batch))
        else:
            num_segments = batch.capacity
            if self.est_groups and self.group_exprs:
                num_segments = min(batch.capacity,
                                   bucket_capacity(self.est_groups))
            (key_arrays, key_valids, accs, occupied,
             total_groups) = agg_kernels.sort_aggregate(
                key_vecs, contribs, specs, sel, batch.capacity,
                num_segments=num_segments)
            if num_segments < batch.capacity:
                # sized-down output: surface the real group count so the
                # executor can re-jit bigger on overflow (AQE loop)
                ctx.add_metric(f"agg_groups_{self.tag}", total_groups)
                ctx.add_flag(f"agg_overflow_{self.tag}",
                             total_groups > num_segments)

        if not self.group_exprs:
            # global aggregate: exactly one output row, always present
            occupied = jnp.ones((1,), jnp.bool_)
            key_arrays = []
            key_valids = []
            accs = [[acc[:1] for acc in row] for row in accs]

        cols: Dict[str, Column] = {}
        for g, vec, arr, kv in zip(self.group_exprs, key_vecs, key_arrays,
                                   key_valids):
            cols[g.name()] = Column(arr, vec.dtype, kv, vec.dictionary)

        if self.mode == "partial":
            for i, a in enumerate(self.agg_exprs):
                for j, spec in enumerate(specs[i]):
                    cols[self._acc_col_name(i, j, spec)] = Column(
                        accs[i][j], _np_to_logical(spec.np_dtype))
        else:
            for i, a in enumerate(self.agg_exprs):
                data, validity = a.func.device_finalize(accs[i], base)
                cols[a.out_name] = Column(
                    data, a.func.result_type(base), validity,
                    getattr(a.func, "output_dictionary", None))
        ctx.add_metric(f"agg_groups", jnp.sum(occupied.astype(jnp.int32)))
        return Batch(cols, occupied)

    def _compute_positional(self, ctx, batch: Batch, base) -> Batch:
        """Aggregates with positional functions (percentile/median/
        collect_list/collect_set — ApproximatePercentile.scala:1,
        collect.scala): one complete pass over a (group keys, value)
        sort per distinct value child. Regular functions in the same
        SELECT ride a sort_aggregate over the SAME key order, so all
        output columns align group-for-group."""
        from ..expr import cast_vec
        if self.mode != "complete":
            raise AnalysisError(
                "positional aggregates (percentile/median/collect_*) "
                "have no partial/final decomposition")
        sel = batch.selection
        cap = batch.capacity
        key_vecs = [g.eval(batch) for g in self.group_exprs]
        num_segments = cap

        regular = [(i, a) for i, a in enumerate(self.agg_exprs)
                   if not getattr(a.func, "positional", False)]
        specs = [a.func.accumulators(base) for _, a in regular]
        contribs = [a.func.update(batch, sel) for _, a in regular]
        (key_arrays, key_valids, accs, occupied,
         _total) = agg_kernels.sort_aggregate(
            key_vecs, contribs, specs, sel, cap,
            num_segments=num_segments)
        if not self.group_exprs:
            occupied = jnp.ones((1,), jnp.bool_) \
                if num_segments == 1 else \
                jnp.arange(num_segments) < 1
            key_arrays, key_valids = [], []

        out_cols: Dict[str, Column] = {}
        for g, vec, arr, kv in zip(self.group_exprs, key_vecs,
                                   key_arrays, key_valids):
            out_cols[g.name()] = Column(arr, vec.dtype, kv,
                                        vec.dictionary)

        results: Dict[int, Column] = {}
        for j, (_, a) in enumerate(regular):
            data, validity = a.func.device_finalize(accs[j], base)
            results[regular[j][0]] = Column(
                data, a.func.result_type(base), validity,
                getattr(a.func, "output_dictionary", None))

        from ..expr_agg import CollectList, Percentile
        sorts = {}  # child repr -> positional_sort outputs
        for i, a in enumerate(self.agg_exprs):
            if not getattr(a.func, "positional", False):
                continue
            f = a.func
            vec = f.child.eval(batch)
            if isinstance(f, Percentile):
                vec = cast_vec(vec, T.DOUBLE)
            skey = (repr(f.child), isinstance(f, Percentile))
            if skey not in sorts:
                sorts[skey] = agg_kernels.positional_sort(
                    key_vecs, vec, sel, cap)
            (vals_s, vvalid_s, _starts, gid, gstart, row_start, _tg,
             _ops) = sorts[skey]
            if isinstance(f, Percentile):
                out, ok = agg_kernels.positional_percentile(
                    vals_s, vvalid_s, gid, gstart, num_segments,
                    f.q, cap)
                results[i] = Column(out, T.DOUBLE, ok & occupied)
            else:
                data, offs = agg_kernels.positional_collect(
                    vals_s, vvalid_s, gid, row_start, num_segments,
                    f.distinct, cap)
                results[i] = Column(
                    data, T.ArrayType(vec.dtype), occupied,
                    vec.dictionary, offsets=offs)

        for i, a in enumerate(self.agg_exprs):
            out_cols[a.out_name] = results[i]
        ctx.add_metric("agg_groups",
                       jnp.sum(occupied.astype(jnp.int32)))
        return Batch(out_cols, occupied)

    def _occupancy_reuse(self, batch) -> Optional[Tuple[int, int]]:
        """(i, j) of an accumulator whose contribution equals the
        selection indicator (a count over a trace-time-never-null
        child): the MXU kernel rides it for occupancy instead of adding
        its own ones row. Trace-time validity (`v.validity is None`) is
        the exact gate — static nullability over-approximates (e.g.
        `pmod(x, const)` is schema-nullable but runtime-valid)."""
        from ..expr_agg import Avg, Count, Sum
        for i, a in enumerate(self.agg_exprs):
            f = a.func
            if isinstance(f, Count) and f.child is None:
                return (i, 0)
            if isinstance(f, (Count, Sum, Avg)) and f.child is not None:
                if f.child.eval(batch).validity is None:
                    return (i, 0 if isinstance(f, Count) else 1)
        return None

    # -- reusable direct-path steps (shared with the streaming driver) ------

    def prepare_direct(self, probe_batch: Batch, conf,
                       pad_dict: bool = True) -> Optional["DirectAggPlan"]:
        """Trace-time check + static metadata for the dense-domain path.
        Returns None when any key lacks a static domain (sort path)."""
        base = self._base_schema()
        cs = self.child.schema()
        key_vecs = [g.eval(probe_batch) for g in self.group_exprs]
        domains = []
        for g, v in zip(self.group_exprs, key_vecs):
            dom = agg_kernels.key_domain(g, v)
            if dom is None:
                return None
            d, lo = dom
            if pad_dict and v.dictionary is not None:
                # headroom for dictionaries that grow across chunks
                d = bucket_capacity(max(16, 2 * d))
            domains.append((d, lo))
        spans = agg_kernels.key_spans(
            [g.nullable(cs) for g in self.group_exprs], domains)
        total = int(np.prod(list(spans) or [1]))
        if total > int(conf.get("spark_tpu.sql.aggregate.maxDirectDomain")):
            return None
        strides = []
        t = 1
        for span in spans:
            strides.append(t)
            t *= span
        specs = [a.func.accumulators(base) for a in self.agg_exprs]
        return DirectAggPlan(
            domains=domains, spans=spans, strides=strides, total=total,
            key_dtypes=[v.dtype for v in key_vecs],
            key_dicts=[v.dictionary for v in key_vecs], specs=specs)

    def direct_init_tables(self, prep: "DirectAggPlan"):
        return agg_kernels.direct_init(prep.spans, prep.specs)

    def _updates(self, batch: Batch, sel, ctx=None, row_base=None):
        """Per-row accumulator contributions. Position-packed aggregates
        (First/Last/AnyValue, `uses_row_base`) receive a globally unique
        row base: `row_base` spaces host-driven chunks and the shard
        index spaces mesh shards, so accumulator merges never tie on
        in-chunk position (a tie would let the two word accumulators of
        one 64-bit value each pick a different row)."""
        if any(a.func.uses_row_base for a in self.agg_exprs):
            base = jnp.asarray(0 if row_base is None else row_base,
                               jnp.int64)
            if ctx is not None and ctx.axis_name is not None \
                    and ctx.n_shards > 1:
                if ctx.n_shards * batch.capacity >= (1 << 30):
                    raise RuntimeError(
                        "first/last aggregation input exceeds the 2^30 "
                        "packed-position bound "
                        f"({ctx.n_shards} shards x {batch.capacity} rows)")
                base = base + jax.lax.axis_index(ctx.axis_name) \
                    .astype(jnp.int64) * batch.capacity
            return [a.func.update(batch, sel, row_base=base)
                    if a.func.uses_row_base else a.func.update(batch, sel)
                    for a in self.agg_exprs]
        return [a.func.update(batch, sel) for a in self.agg_exprs]

    def direct_update_tables(self, tables, batch: Batch,
                             prep: "DirectAggPlan", conf=None,
                             row_base=None):
        sel = batch.selection
        key_vecs = [g.eval(batch) for g in self.group_exprs]
        idx, _, _ = agg_kernels.direct_index(key_vecs, prep.domains,
                                             prep.spans, sel)
        contribs = self._updates(batch, sel, row_base=row_base)
        mode = str(conf.get("spark_tpu.sql.aggregate.kernelMode")) \
            if conf is not None else "auto"
        return agg_kernels.direct_update(tables, idx, prep.total, contribs,
                                         prep.specs, kernel_mode=mode,
                                         reuse_count=self._occupancy_reuse(
                                             batch))

    def direct_finalize_tables(self, tables, prep: "DirectAggPlan",
                               dict_overrides: Optional[Dict] = None) -> Batch:
        cnt, accs = tables
        base = self._base_schema()
        occupied = cnt > 0
        key_arrays, key_valids = agg_kernels.direct_keys(
            prep.domains, prep.spans, prep.strides, prep.key_dtypes)
        if not self.group_exprs:
            occupied = jnp.ones((1,), jnp.bool_)
        cols: Dict[str, Column] = {}
        for g, arr, kv, dt, dic in zip(self.group_exprs, key_arrays,
                                       key_valids, prep.key_dtypes,
                                       prep.key_dicts):
            if dict_overrides and g.name() in dict_overrides:
                dic = dict_overrides[g.name()]
            cols[g.name()] = Column(arr, dt, kv, dic)
        for i, a in enumerate(self.agg_exprs):
            data, validity = a.func.device_finalize(accs[i], base)
            cols[a.out_name] = Column(
                data, a.func.result_type(base), validity,
                getattr(a.func, "output_dictionary", None))
        return Batch(cols, occupied)

    def direct_partial_batch(self, tables, prep: "DirectAggPlan",
                             dict_overrides: Optional[Dict] = None) -> Batch:
        """Partial-mode output batch from carried accumulator tables:
        group keys + RAW accumulator columns + occupancy selection (the
        shape the exchange+final stages consume)."""
        cnt, accs = tables
        base = self._base_schema()
        key_arrays, key_valids = agg_kernels.direct_keys(
            prep.domains, prep.spans, prep.strides, prep.key_dtypes)
        cols: Dict[str, Column] = {}
        for g, arr, kv, dt, dic in zip(self.group_exprs, key_arrays,
                                       key_valids, prep.key_dtypes,
                                       prep.key_dicts):
            if dict_overrides and g.name() in dict_overrides:
                dic = dict_overrides[g.name()]
            cols[g.name()] = Column(arr, dt, kv, dic)
        for i, a in enumerate(self.agg_exprs):
            for j, spec in enumerate(prep.specs[i]):
                cols[self._acc_col_name(i, j, spec)] = Column(
                    accs[i][j], _np_to_logical(spec.np_dtype))
        return Batch(cols, cnt > 0)

    def output_partitioning(self):
        if self.mode == "partial":
            # per-shard accumulator tables: rows for one key exist on
            # every shard, so nothing stronger than the child's layout
            # (claiming SinglePartition here would suppress the exchange
            # the final aggregate depends on)
            return self.child.output_partitioning()
        if not self.group_exprs:
            return SinglePartition()
        return self.child.output_partitioning()

    def required_child_distributions(self):
        if self.mode in ("complete", "final"):
            if not self.group_exprs:
                return [AllTuples()]
            names = []
            for g in self.group_exprs:
                e = g
                while isinstance(e, Alias):
                    e = e.child
                from ..expr import ColumnRef
                if not isinstance(e, ColumnRef):
                    # a computed group key has no child column to hash
                    # (mesh positional aggregates reach complete mode
                    # directly): gather instead of a broken exchange
                    return [AllTuples()]
                names.append(e.name())
            return [ClusteredDistribution(tuple(names))]
        return [UnspecifiedDistribution()]

    def simple_string(self):
        return (f"HashAggregateExec(mode={self.mode}, "
                f"groups={[repr(g) for g in self.group_exprs]}, "
                f"aggs={[repr(a) for a in self.agg_exprs]}, "
                f"est={self.est_groups})")


@dataclass
class DirectAggPlan:
    """Static (trace-time) metadata for the dense-domain aggregate path.
    `domains` entries are (domain, lo) pairs — see `aggregate.key_domain`."""

    domains: List[Tuple[int, int]]
    spans: List[int]  # domain + null slot for schema-nullable keys
    strides: List[int]
    total: int
    key_dtypes: List[T.DataType]
    key_dicts: List
    specs: List


def _np_to_logical(np_dtype) -> T.DataType:
    m = {np.dtype(np.int64): T.LONG, np.dtype(np.float64): T.DOUBLE,
         np.dtype(np.int32): T.INT, np.dtype(np.float32): T.FLOAT,
         np.dtype(np.bool_): T.BOOLEAN, np.dtype(np.int16): T.SHORT,
         np.dtype(np.int8): T.BYTE}
    return m[np.dtype(np_dtype)]


class SortExec(UnaryExec):
    """Global sort: range-partition over the mesh (sampled bounds +
    all_to_all), then sort locally — shard i's keys <= shard i+1's, so
    the ordered shard concat IS the global order (reference:
    SortExec.scala:40 + RangePartitioning). Single-chip, the requirement
    is trivially satisfied and this is just the local sort."""

    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder]):
        self.children = (child,)
        self.orders = tuple(orders)

    def schema(self):
        return self.child.schema()

    def order_key(self) -> Tuple[str, ...]:
        return tuple(repr(o) for o in self.orders)

    def required_child_distributions(self):
        return [OrderedDistribution(self.order_key())]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def compute(self, ctx, inputs):
        batch = inputs[0]
        perm, n_valid = sort_kernels.sort_permutation(batch, self.orders)
        return sort_kernels.apply_permutation(batch, perm, n_valid)

    def simple_string(self):
        return f"SortExec({[repr(o) for o in self.orders]})"


class WindowExec(UnaryExec):
    """All window functions of one spec over one sorted permutation
    (reference: execution/window/WindowExec.scala — frame processors
    become segmented scans, execution/window.py). Partitions co-locate
    via a hash exchange; an empty PARTITION BY needs all rows together."""

    def __init__(self, child: PhysicalPlan, wexprs: Sequence[Tuple],
                 out_schema: T.Schema):
        self.children = (child,)
        self.wexprs = tuple(wexprs)
        self._schema = out_schema

    def schema(self):
        return self._schema

    def _spec(self):
        return self.wexprs[0][0].spec

    def required_child_distributions(self):
        from ..expr import ColumnRef
        spec = self._spec()
        if not spec._partition:
            return [AllTuples()]
        names = []
        for p in spec._partition:
            e = p
            while isinstance(e, Alias):
                e = e.child
            if not isinstance(e, ColumnRef):
                return [AllTuples()]
            names.append(e.name())
        return [ClusteredDistribution(tuple(names))]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def compute(self, ctx, inputs):
        from ..execution import window as win
        from ..execution.sort import sort_operands
        batch = inputs[0]
        cap = batch.capacity
        sel = batch.selection_mask()
        spec = self._spec()

        p_orders = [SortOrder(p, ascending=True) for p in spec._partition]
        p_ops = sort_operands(batch, p_orders)
        o_ops = sort_operands(batch, list(spec._order))

        operands = [(~sel).astype(jnp.int8)] + p_ops + o_ops
        num_keys = len(operands)
        operands.append(jnp.arange(cap, dtype=jnp.int32))
        sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
        perm = sorted_ops[-1]
        valid_sorted = sorted_ops[0] == 0
        sp_ops = list(sorted_ops[1:1 + len(p_ops)])
        so_ops = list(sorted_ops[1 + len(p_ops):num_keys])

        starts = win._segment_starts(sp_ops, cap, valid_sorted)
        gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
        gid = jnp.where(valid_sorted, gid, cap)
        change = win._peer_change(starts, so_ops, cap)
        base = self.child.schema()

        new_cols: Dict[str, Column] = dict(batch.columns)
        for w, name in self.wexprs:
            out_dtype = w.dtype(base)
            validity_sorted = None
            if w.kind == "row_number":
                vals = win.row_number(starts, cap)
            elif w.kind == "rank":
                vals = win.rank(starts, change, cap)
            elif w.kind == "dense_rank":
                vals = win.dense_rank(starts, change, cap)
            elif w.kind in ("lag", "lead"):
                v = w.arg.eval(batch)
                if v.dictionary is not None and w.default is not None:
                    raise AnalysisError(
                        "lag/lead with a default on a string column is "
                        "not supported (the default would be written in "
                        "dictionary-code space)")
                data_s = jnp.take(v.data, perm)
                val_s = None if v.validity is None else \
                    jnp.take(v.validity, perm)
                vals, validity_sorted = win.shift_in_segment(
                    data_s, val_s, gid, w.offset, w.default, cap)
            else:
                if w.arg is None:  # count(*) over (...)
                    data_s = jnp.ones((cap,), jnp.int64)
                    val_s = None
                else:
                    v = w.arg.eval(batch)
                    if v.dictionary is not None and w.kind != "count":
                        # codes are insertion-ordered, not lexicographic:
                        # min/max/sum over codes would silently corrupt
                        raise AnalysisError(
                            f"window {w.kind} over a string column is "
                            f"not supported")
                    from ..expr import cast_vec
                    acc_t = out_dtype if w.kind in ("sum",) else v.dtype
                    if w.kind == "avg":
                        from ..expr_agg import Sum
                        acc_t = Sum(w.arg).result_type(base)
                    vv = cast_vec(v, acc_t)
                    data_s = jnp.take(vv.data, perm)
                    val_s = None if vv.validity is None else \
                        jnp.take(vv.validity, perm)
                if val_s is not None:
                    val_s = val_s & valid_sorted
                else:
                    val_s = valid_sorted
                frame = w.spec._frame
                if frame is not None and frame[0] == "range":
                    from ..window import UNBOUNDED_PRECEDING as _UP
                    if frame[1] <= _UP and frame[2] == 0:
                        # RANGE UNBOUNDED PRECEDING .. CURRENT ROW is
                        # exactly the default peer frame: no value
                        # arithmetic, so any order keys are fine
                        frame = None
                if frame is None:
                    out, cnt = win.windowed_agg(
                        "sum" if w.kind == "avg" else w.kind, data_s,
                        val_s, gid, cap, starts, change,
                        bool(spec._order), cap)
                else:
                    # ROWS/RANGE BETWEEN (WindowExec.scala:36 frames)
                    if not spec._order:
                        raise AnalysisError(
                            "a window frame requires an ORDER BY in "
                            "its window specification")
                    range_key = range_key_valid = None
                    if frame[0] == "range":
                        range_key, range_key_valid = \
                            self._range_frame_key(batch, spec, frame,
                                                  base, perm,
                                                  valid_sorted)
                    lo, hi = win.frame_bounds(
                        frame, starts, change, cap, bool(spec._order),
                        n_valid=jnp.sum(valid_sorted.astype(jnp.int32)),
                        range_key=range_key,
                        range_key_valid=range_key_valid)
                    max_len = None
                    if frame[0] == "rows":
                        from ..window import (UNBOUNDED_FOLLOWING as _UF,
                                              UNBOUNDED_PRECEDING as _UP2)
                        if frame[1] > _UP2 and frame[2] < _UF:
                            max_len = min(cap, frame[2] - frame[1] + 1)
                    out, cnt = win.framed_agg(
                        "sum" if w.kind == "avg" else w.kind, data_s,
                        val_s, lo, hi, cap, max_len=max_len)
                if w.kind == "avg":
                    safe = jnp.maximum(cnt, 1)
                    if isinstance(out_dtype, T.DecimalType):
                        from ..expr_agg import decimal_avg_halfup
                        arg_t = w.arg.dtype(base)
                        vals = decimal_avg_halfup(
                            out.astype(jnp.int64), safe,
                            10 ** (out_dtype.scale - arg_t.scale))
                    else:
                        vals = out.astype(jnp.float64) / safe
                elif w.kind == "count":
                    vals = cnt
                else:
                    vals = out
                if w.kind != "count":
                    validity_sorted = cnt > 0
            # scatter back to input row order
            unsorted = jnp.zeros((cap,), vals.dtype).at[perm].set(vals)
            validity = None
            if validity_sorted is not None:
                validity = jnp.zeros((cap,), jnp.bool_).at[perm].set(
                    validity_sorted)
            src_dict = None
            if w.kind in ("lag", "lead"):
                src = w.arg.eval(batch)
                src_dict = src.dictionary
            new_cols[name] = Column(unsorted.astype(out_dtype.np_dtype),
                                    out_dtype, validity, src_dict)
        return Batch(new_cols, batch.selection)

    def _range_frame_key(self, batch, spec, frame, base, perm,
                         valid_sorted):
        """Sorted order-key values for a RANGE frame with value-space
        offsets: exactly one ascending numeric/date order key (the
        reference's RangeFrame constraint). Keys are sanitized so NULL
        and filtered rows carry monotone sentinels (see
        win.sanitize_range_key)."""
        from ..execution import window as win
        from ..window import UNBOUNDED_FOLLOWING, UNBOUNDED_PRECEDING
        _, a, b = frame
        if a <= UNBOUNDED_PRECEDING and b >= UNBOUNDED_FOLLOWING:
            return None, None
        if len(spec._order) != 1:
            raise AnalysisError(
                "RANGE BETWEEN with offsets requires exactly one ORDER "
                "BY key")
        o = spec._order[0]
        if not o.ascending:
            raise AnalysisError(
                "RANGE BETWEEN with offsets supports ascending order "
                "keys only")
        v = o.child.eval(batch)
        if v.dictionary is not None or isinstance(
                v.dtype, (T.StringType, T.BooleanType)):
            raise AnalysisError(
                "RANGE BETWEEN needs a numeric or date order key")
        key = jnp.take(v.data, perm)
        kv = None if v.validity is None else jnp.take(v.validity, perm)
        key = win.sanitize_range_key(key, kv, valid_sorted,
                                     o.nulls_first)
        return key, kv

    def simple_string(self):
        # the FULL spec must be in the fingerprint: the compiled-stage
        # cache keys on describe(), and two windows differing only in
        # partition/order would otherwise collide
        return f"WindowExec({[(repr(w), n) for w, n in self.wexprs]})"


class GenerateExec(UnaryExec):
    """explode: one output row per flattened array element. Output
    capacity is the VALUES capacity — a static shape (the flattened
    element array's padded length), so unlike the reference's
    `GenerateExec.scala:1` row iterator no AQE sizing is needed: element
    slots map back to their rows via one searchsorted over offsets and
    every child column gathers by that segment id. `outer` appends one
    slot per input row for empty/NULL arrays (explode_outer)."""

    def __init__(self, child: PhysicalPlan, gen_expr, out_name: str,
                 out_schema: T.Schema, outer: bool = False):
        self.children = (child,)
        self.gen_expr = gen_expr
        self.out_name = out_name
        self._schema = out_schema
        self.outer = outer

    def schema(self):
        return self._schema

    def compute(self, ctx, inputs):
        batch = inputs[0]
        cap = batch.capacity
        v = self.gen_expr.eval(batch)
        if v.offsets is None:
            raise AnalysisError(
                f"explode() needs an array, got {v.dtype!r}")
        vcap = max(int(v.data.shape[0]), 1)
        iota = jnp.arange(vcap, dtype=jnp.int32)
        seg = jnp.searchsorted(v.offsets, iota, side="right") - 1
        seg_c = jnp.clip(seg, 0, cap - 1)
        total = v.offsets[-1]
        row_live = batch.selection_mask()
        live = (iota < total) & jnp.take(row_live, seg_c)
        if v.validity is not None:
            live = live & jnp.take(v.validity, seg_c)

        def replicate(col: Column, idx):
            data = jnp.take(col.data, idx)
            valid = None if col.validity is None else \
                jnp.take(col.validity, idx)
            return data, valid

        elem_t = v.dtype.element
        parts = {}
        for name, col in batch.columns.items():
            if col.offsets is not None:
                continue  # array columns do not replicate (see schema)
            parts[name] = replicate(col, seg_c)
        elem_data = v.data
        elem_valid = v.elem_validity
        sel = live
        if self.outer:
            # one extra slot per input row, live only for empty/NULL
            # arrays; its element is NULL (explode_outer semantics)
            lens = v.offsets[1:] - v.offsets[:-1]
            empty = lens == 0
            if v.validity is not None:
                empty = empty | ~v.validity
            extra_live = row_live & empty
            for name, col in batch.columns.items():
                if name not in parts:
                    continue
                d, va = parts[name]
                d2 = jnp.concatenate([d, col.data])
                va2 = None
                if va is not None:
                    va2 = jnp.concatenate([va, col.validity])
                parts[name] = (d2, va2)
            elem_data = jnp.concatenate(
                [elem_data, jnp.zeros((cap,), elem_data.dtype)])
            ev_main = elem_valid if elem_valid is not None else \
                jnp.ones((vcap,), jnp.bool_)
            elem_valid = jnp.concatenate(
                [ev_main, jnp.zeros((cap,), jnp.bool_)])
            sel = jnp.concatenate([live, extra_live])

        cols = {n: Column(d, batch.columns[n].dtype, va,
                          batch.columns[n].dictionary)
                for n, (d, va) in parts.items()}
        cols[self.out_name] = Column(elem_data, elem_t, elem_valid,
                                     v.dictionary)
        ctx.add_metric(f"gen_rows_{self.out_name}",
                       jnp.sum(sel.astype(jnp.int64)))
        return Batch(cols, sel)

    def simple_string(self):
        return (f"GenerateExec(explode{'_outer' if self.outer else ''}"
                f"({self.gen_expr!r}) AS {self.out_name})")


class LimitExec(UnaryExec):
    """First-n. Over a range-partitioned (sorted) child it stays
    distributed: shard i keeps rows whose global rank — its local rank
    plus the psum'd count on lower shards — is under n, with no gather of
    the dataset (reference: the GlobalLimit/LocalLimit split in
    limit.scala). Otherwise it collapses to one logical partition."""

    def __init__(self, child: PhysicalPlan, n: int):
        self.children = (child,)
        self.n = n

    def schema(self):
        return self.child.schema()

    def required_child_distributions(self):
        if isinstance(self.child.output_partitioning(), RangePartitioning):
            return [UnspecifiedDistribution()]
        return [AllTuples()]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def compute(self, ctx, inputs):
        batch = inputs[0]
        sel = batch.selection_mask()
        local_rank = jnp.cumsum(sel.astype(jnp.int32)) - sel.astype(jnp.int32)
        if ctx.axis_name is not None and ctx.n_shards > 1 and \
                isinstance(self.child.output_partitioning(),
                           RangePartitioning):
            n_shards = ctx.n_shards
            local_count = jnp.sum(sel.astype(jnp.int32))
            counts = jax.lax.all_gather(local_count, ctx.axis_name)
            i = jax.lax.axis_index(ctx.axis_name)
            offset = jnp.sum(jnp.where(
                jnp.arange(n_shards) < i, counts, 0))
            keep = local_rank < jnp.maximum(self.n - offset, 0)
            return batch.with_selection(sel & keep)
        keep = local_rank < self.n
        return batch.with_selection(sel & keep)

    def simple_string(self):
        return f"LimitExec({self.n})"


class JoinExec(PhysicalPlan):
    """General equi-join: sorted-build binary-search with prefix-sum
    expansion (execution/join.py). Build side = right child. Supports
    many-to-many matches, inner/left/right/full outer, semi/anti, and
    residual (non-equi) conditions for every join type.

    `out_cap` is the static capacity of the expanded-pair block; None
    defaults to the probe capacity (exact for FK joins). When the traced
    row total overflows it, the executor reads the real total from the
    `join_rows_<tag>` metric and re-jits with a larger capacity — the
    stats->re-plan loop of the reference's AQE
    (`AdaptiveSparkPlanExec.scala:64`)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 how: str, condition: Optional[Expression],
                 out_schema: T.Schema, out_cap: Optional[int] = None,
                 tag: str = "j0", strategy: str = "shuffle"):
        self.children = (left, right)
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.how = how
        self.condition = condition
        self._schema = out_schema
        self.out_cap = out_cap
        # unique-build fast path (HashedRelation.scala keyIsUnique
        # analog): assume each probe row matches <=1 build row — the
        # FK->PK shape — and emit probe-layout output with zero
        # expansion. A duplicate build key raises the
        # join_nonunique_<tag> flag and the AQE loop re-jits with the
        # general expansion path (False). None/True = try it.
        self.unique_build: Optional[bool] = None
        # hash-kernel AQE state (execution/hash_join.py): None = the
        # conf/cardinality heuristic decides; False = a previous
        # attempt saturated the open-addressing table within
        # join.hashMaxProbe steps (join_hashsat_<tag> flag) — stay on
        # the sort kernel for this join.
        self.hash_fallback: Optional[bool] = None
        # True for left_semi joins SYNTHESIZED by the runtime-filter
        # rule to narrow a creation chain (plan/runtime_filter.py):
        # tagged from a separate counter (cj<n>) so real joins keep
        # their tag numbering across the strategy-override path
        self.creation_side = False
        # SQL NOT IN null-aware anti-join (left_anti only)
        self.null_aware = False
        # reorder cost-model output estimate (plan/join_reorder.py),
        # advisory: graded as a join_rows prediction, shown in the
        # runtime tree — never in simple_string (stage keys must not
        # vary with estimates)
        self.cbo_est_rows: Optional[int] = None
        self.tag = tag
        # "shuffle": co-partition both sides (ShuffledHashJoinExec.scala:37
        # analog); "broadcast": replicate the small build side via
        # all_gather and leave the probe side in place
        # (BroadcastHashJoinExec.scala:40 analog). Picked by the planner
        # from source row estimates vs autoBroadcastJoinThreshold.
        self.strategy = strategy

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def schema(self):
        return self._schema

    def _clusterable_key_names(self):
        """Key positions usable for hash partitioning: both sides must be
        plain column references (the exchange hashes child columns by
        name; a computed key has no column to hash)."""
        from ..expr import ColumnRef
        lk, rk = [], []
        for l, r in zip(self.left_keys, self.right_keys):
            le, re = l, r
            while isinstance(le, Alias):
                le = le.child
            while isinstance(re, Alias):
                re = re.child
            if isinstance(le, ColumnRef) and isinstance(re, ColumnRef):
                lk.append(le.name())
                rk.append(re.name())
        return tuple(lk), tuple(rk)

    def required_child_distributions(self):
        if self.strategy == "broadcast":
            return [UnspecifiedDistribution(), BroadcastDistribution()]
        lk, rk = self._clusterable_key_names()
        if not lk:
            if self.how in ("right", "full"):
                # a replicated build would append its locally-unmatched
                # rows on EVERY shard (n-fold duplication); with no key
                # columns to hash-partition on, co-locate everything and
                # let the striped SinglePartition output dedupe
                return [AllTuples(), AllTuples()]
            # no hashable key columns (e.g. cross join's literal keys):
            # every probe row must see every build row -> replicate build
            return [UnspecifiedDistribution(), BroadcastDistribution()]
        return [ClusteredDistribution(lk), ClusteredDistribution(rk)]

    def output_partitioning(self):
        lp = self.left.output_partitioning()
        rp = self.right.output_partitioning()
        if isinstance(lp, SinglePartition) and \
                isinstance(rp, (SinglePartition, Replicated)):
            # both sides fully co-located: every shard computed the same
            # complete result (valid for every join type incl. outer)
            return SinglePartition()
        if self.how in ("right", "full"):
            # appended null-extended rows carry NULL left keys on whatever
            # shard held the unmatched build row — no layout guarantee
            # (the reference returns UnknownPartitioning here too)
            return UnknownPartitioning()
        return lp

    def _eval_keys(self, probe_batch, build_batch):
        def bcast(v: Vec, cap: int) -> Vec:
            # literal keys (cross join lowers to a constant-key equi-join)
            if v.data is not None and np.ndim(v.data) == 0:
                return Vec(jnp.broadcast_to(v.data, (cap,)), v.dtype,
                           v.validity, v.dictionary)
            return v

        lvecs = [bcast(k.eval(probe_batch), probe_batch.capacity)
                 for k in self.left_keys]
        rvecs = [bcast(k.eval(build_batch), build_batch.capacity)
                 for k in self.right_keys]
        lvecs, rvecs = _unify_key_dictionaries(lvecs, rvecs)
        if len(lvecs) != 1:
            lk, rk, exact = _pack_key_pair(lvecs, rvecs)
        else:
            lk, rk = lvecs[0], rvecs[0]
            exact = True
        return lvecs, rvecs, lk, rk, exact

    def _build_name_map(self, probe_batch, build_batch):
        """(left_names, out_names) with the `_r` collision suffix shared
        by every join type so one condition expression works for all."""
        left_names = list(probe_batch.columns.keys())
        if self.how in ("left_semi", "left_anti"):
            taken = set(left_names)
            out_names = list(left_names)
            for n in build_batch.columns.keys():
                name = n
                while name in taken:
                    name = name + "_r"
                out_names.append(name)
                taken.add(name)
        else:
            out_names = self._schema.names
        return left_names, out_names

    def _compute_unique(self, ctx, probe_batch, build_batch,
                        lvecs, rvecs, lk, keys_s, perm, n_valid, valid_s,
                        hash_lc=None):
        """Unique-build fast path: probe-layout output, zero expansion
        (HashedRelation keyIsUnique analog). Raises join_nonunique_<tag>
        when the build side has duplicate keys; the AQE loop then
        re-jits with unique_build=False. `hash_lc` is the hash kernel's
        (lo, cnt) probe result when that kernel ran (the sort kernel's
        single-searchsorted match_unique otherwise)."""
        ctx.add_flag(f"join_nonunique_{self.tag}",
                     join_kernels.build_has_duplicates(keys_s, valid_s))
        if hash_lc is not None:
            lo, cnt = hash_lc
            build_idx = jnp.take(perm, jnp.minimum(lo,
                                                   keys_s.shape[0] - 1))
            found = cnt > 0
        else:
            build_idx, found = join_kernels.match_unique(
                keys_s, n_valid, perm, lk, probe_batch.selection)
        psel = probe_batch.selection_mask()
        exact = len(lvecs) == 1
        if not exact:
            # packed keys: verify true equality (a pack collision pair
            # in the build would have raised the nonunique flag, so the
            # single candidate is the only possible match)
            for lvec, rvec in zip(lvecs, rvecs):
                eq = lvec.data == jnp.take(rvec.data, build_idx)
                if lvec.validity is not None:
                    eq = eq & lvec.validity
                if rvec.validity is not None:
                    eq = eq & jnp.take(rvec.validity, build_idx)
                found = found & eq

        left_names, out_names = self._build_name_map(probe_batch,
                                                     build_batch)
        n_left = len(left_names)
        cols: Dict[str, Column] = {}
        for name, out_name in zip(left_names, out_names[:n_left]):
            cols[out_name] = probe_batch.columns[name]  # no gather
        build_name_map = list(zip(build_batch.columns.keys(),
                                  out_names[n_left:]))
        for (out_name, col) in join_kernels.gather_columns(
                build_batch, build_idx, found, build_name_map):
            cols[out_name] = col

        if self.condition is not None:
            out_probe = Batch(cols, psel & found)
            v = self.condition.eval(out_probe)
            keep = v.data if v.validity is None else (v.data & v.validity)
            found = found & keep
            for out_name, col in join_kernels.gather_columns(
                    build_batch, build_idx, found, build_name_map):
                cols[out_name] = col

        ctx.add_metric(f"join_rows_{self.tag}",
                       jnp.sum((psel & found).astype(jnp.int64)))
        if self.how == "left_semi":
            return probe_batch.with_selection(psel & found)
        if self.how == "left_anti":
            sel = psel & ~found
            if self.null_aware:
                sel = sel & self._null_aware_mask(ctx, lvecs[0],
                                                  build_batch, rvecs[0])
            return probe_batch.with_selection(sel)
        if self.how == "left":
            return Batch(cols, psel)
        return Batch(cols, psel & found)

    def _null_aware_mask(self, ctx, probe_key_vec, build_batch,
                         build_key_vec):
        """Per-probe-row NOT IN adjustment (SQL three-valued logic):
        a NULL anywhere in the build keys empties the result; a NULL
        probe key survives only when the build side is empty. Scalars
        reduce over the mesh axis — NULL build rows hash to ONE shard
        but empty every shard's output."""
        bsel = build_batch.selection_mask()
        if build_key_vec.validity is not None:
            has_null = jnp.sum((bsel & ~build_key_vec.validity)
                               .astype(jnp.int32))
        else:
            has_null = jnp.zeros((), jnp.int32)
        nonempty = jnp.sum(bsel.astype(jnp.int32))
        if ctx.axis_name is not None:
            has_null = jax.lax.psum(has_null, ctx.axis_name)
            nonempty = jax.lax.psum(nonempty, ctx.axis_name)
        mask = jnp.broadcast_to(has_null == 0,
                                (probe_key_vec.data.shape[0],))
        if probe_key_vec.validity is not None:
            mask = mask & (probe_key_vec.validity | (nonempty == 0))
        return mask

    def compute(self, ctx, inputs):
        import time as _time
        from ..execution import hash_join as hash_kernels
        probe_batch, build_batch = inputs
        lvecs, rvecs, lk, rk, exact = self._eval_keys(probe_batch, build_batch)
        t_build = _time.perf_counter()
        keys_s, perm, n_valid, _valid_s = join_kernels.build_sorted(
            rk, build_batch.selection)
        # kernel choice (join.kernelMode): hash builds an open-
        # addressing table over the sorted build's distinct keys and
        # probes it with a bounded vectorized loop; both kernels return
        # the same (lo, cnt) sorted-order contract, so everything
        # downstream (expansion, gathers, output order) is shared and
        # results are byte-identical across modes.
        kernel = hash_kernels.resolve_kernel(
            ctx.conf, probe_batch.capacity, build_batch.capacity,
            self.hash_fallback)
        hash_lc = None
        if kernel == "hash":
            slots = hash_kernels.table_slots(build_batch.capacity,
                                             ctx.conf)
            max_probe = int(ctx.conf.get(hash_kernels.MAX_PROBE_KEY))
            # both sides hash under the promoted common dtype: mixed-
            # precision keys (float32 probe vs float64 build) must hash
            # the same bit pattern wherever `==` calls them equal
            hash_dt = jnp.promote_types(lk.data.dtype, keys_s.dtype)
            t_pos, cnt_all, saturated = hash_kernels.build_table(
                keys_s, _valid_s, slots, max_probe, hash_dtype=hash_dt)
            # a cluster longer than the probe bound: re-jit on sort
            ctx.add_flag(f"join_hashsat_{self.tag}", saturated)
            ctx.add_metric(f"join_table_slots_{self.tag}",
                           jnp.asarray(slots, jnp.int64))
            # trace-time program-construction cost, the rtf_build_ms
            # convention: the kernels fuse into the stage, so this is
            # the honest per-join observable (pmax'd across shards)
            ctx.add_metric(f"join_build_ms_{self.tag}", jnp.float32(
                (_time.perf_counter() - t_build) * 1e3))
            t_probe = _time.perf_counter()
            hash_lc = hash_kernels.probe_table(
                t_pos, cnt_all, keys_s, lk, probe_batch.selection,
                slots, max_probe, hash_dtype=hash_dt)
            ctx.add_metric(f"join_probe_ms_{self.tag}", jnp.float32(
                (_time.perf_counter() - t_probe) * 1e3))
        if (self.unique_build is not False
                and self.how in ("inner", "left", "left_semi",
                                 "left_anti")):
            return self._compute_unique(ctx, probe_batch, build_batch,
                                        lvecs, rvecs, lk, keys_s, perm,
                                        n_valid, _valid_s,
                                        hash_lc=hash_lc)
        if hash_lc is not None:
            lo, cnt = hash_lc
        else:
            lo, cnt = join_kernels.match_ranges(keys_s, n_valid, lk,
                                                probe_batch.selection)
        psel = probe_batch.selection_mask()
        semi_anti = self.how in ("left_semi", "left_anti")

        if semi_anti and exact and self.condition is None:
            found = cnt > 0
            if self.how == "left_semi":
                return probe_batch.with_selection(psel & found)
            sel = psel & ~found
            if self.null_aware:
                sel = sel & self._null_aware_mask(ctx, lvecs[0],
                                                  build_batch, rvecs[0])
            return probe_batch.with_selection(sel)

        probe_cap = probe_batch.capacity
        build_cap = build_batch.capacity
        out_cap = self.out_cap if self.out_cap is not None else probe_cap
        outer_probe = self.how in ("left", "full")
        if outer_probe:
            cnt_eff = jnp.where(psel, jnp.maximum(cnt, 1), 0)
        else:
            cnt_eff = jnp.where(psel, cnt, 0)
        p, build_idx, is_pair, valid, total = join_kernels.expand(
            lo, cnt, cnt_eff, perm, out_cap)
        ctx.add_metric(f"join_rows_{self.tag}", total)
        ctx.add_flag(f"join_overflow_{self.tag}", total > out_cap)

        pair_pass = is_pair
        if not exact:
            # hashed key pack: verify true per-key equality on each pair
            for lvec, rvec in zip(lvecs, rvecs):
                eq = jnp.take(lvec.data, p) == jnp.take(rvec.data, build_idx)
                if lvec.validity is not None:
                    eq = eq & jnp.take(lvec.validity, p)
                if rvec.validity is not None:
                    eq = eq & jnp.take(rvec.validity, build_idx)
                pair_pass = pair_pass & eq

        # assemble the expanded block: probe columns at p, build at build_idx
        left_names, out_names = self._build_name_map(probe_batch,
                                                     build_batch)
        n_left = len(left_names)
        cols: Dict[str, Column] = {}
        for (out_name, col) in join_kernels.gather_columns(
                probe_batch, p, valid,
                list(zip(left_names, out_names[:n_left]))):
            cols[out_name] = col
        build_name_map = list(zip(build_batch.columns.keys(),
                                  out_names[n_left:]))
        for (out_name, col) in join_kernels.gather_columns(
                build_batch, build_idx, pair_pass, build_name_map):
            cols[out_name] = col

        if self.condition is not None:
            out_probe = Batch(cols, valid & pair_pass)
            v = self.condition.eval(out_probe)
            keep = v.data if v.validity is None else (v.data & v.validity)
            pair_pass = pair_pass & keep
            # pairs dropped by the residual must also null the build side
            for out_name, col in join_kernels.gather_columns(
                    build_batch, build_idx, pair_pass, build_name_map):
                cols[out_name] = col

        # per-probe-row "any pair survived" (drives null-extension +
        # semi/anti). p is non-decreasing (output rows are emitted in
        # probe order), so count survivors per p-run with a prefix-sum
        # difference at run bounds — a colliding scatter-max serializes
        # on TPU (~90ms/4M rows, Q3 profile)
        m = (valid & pair_pass).astype(jnp.int32)
        csum_m = jnp.cumsum(m)
        ex_m = csum_m - m
        rpos = jnp.arange(out_cap, dtype=jnp.int32)
        run_start = (rpos == 0) | (p != jnp.roll(p, 1))
        nxt_p = jnp.concatenate([p[1:], jnp.full((1,), probe_cap, p.dtype)])
        run_end = nxt_p != p
        # no `valid` mask: tail rows (r >= total) share the last emitting
        # row's p (clipped), so they extend its run with m=0 — harmless —
        # while masking would lose that run's end marker entirely
        sidx_p = jnp.where(run_start, p, probe_cap)
        eidx_p = jnp.where(run_end, p, probe_cap)
        pstart = jnp.zeros((probe_cap,), jnp.int32).at[sidx_p].set(
            rpos, mode="drop")
        pend = jnp.zeros((probe_cap,), jnp.int32).at[eidx_p].set(
            rpos, mode="drop")
        ppresent = jnp.zeros((probe_cap,), jnp.bool_).at[sidx_p].set(
            jnp.ones((out_cap,), jnp.bool_), mode="drop")
        any_pass = ppresent & (
            (jnp.take(csum_m, pend) - jnp.take(ex_m, pstart)) > 0)

        if semi_anti:
            if self.how == "left_semi":
                return probe_batch.with_selection(psel & any_pass)
            sel = psel & ~any_pass
            if self.null_aware:
                sel = sel & self._null_aware_mask(ctx, lvecs[0],
                                                  build_batch, rvecs[0])
            return probe_batch.with_selection(sel)

        if outer_probe:
            # keep surviving pairs; for probe rows with none, keep exactly
            # the first emitted row as a null-extended row
            off_p = jnp.take(
                jnp.cumsum(cnt_eff) - cnt_eff, p)
            is_first = jnp.arange(out_cap, dtype=jnp.int32) == off_p
            null_ext = is_first & ~jnp.take(any_pass, p)
            sel = valid & (pair_pass | null_ext)
            # null-extended rows must show NULL build columns even when
            # they reused a failed pair slot
            for out_name, col in join_kernels.gather_columns(
                    build_batch, build_idx, pair_pass & ~null_ext,
                    build_name_map):
                cols[out_name] = col
        else:
            sel = valid & pair_pass

        if self.how in ("right", "full"):
            # append build rows no surviving pair touched, null-extended left
            scatter_b = jnp.where(valid & pair_pass, build_idx, build_cap)
            matched_b = jnp.zeros((build_cap,), jnp.bool_).at[scatter_b].max(
                jnp.ones_like(pair_pass), mode="drop")
            bsel = build_batch.selection_mask()
            app_sel = bsel & ~matched_b
            app_cols: Dict[str, Column] = {}
            for name, out_name in zip(left_names, out_names[:n_left]):
                src = probe_batch.columns[name]
                app_cols[out_name] = Column(
                    jnp.zeros((build_cap,), src.data.dtype), src.dtype,
                    jnp.zeros((build_cap,), jnp.bool_), src.dictionary)
            for name, out_name in build_name_map:
                src = build_batch.columns[name]
                app_cols[out_name] = Column(src.data, src.dtype,
                                            src.validity, src.dictionary)
            merged: Dict[str, Column] = {}
            for out_name in cols:
                a, b = cols[out_name], app_cols[out_name]
                av = a.validity if a.validity is not None else \
                    jnp.ones((out_cap,), jnp.bool_)
                bv = b.validity if b.validity is not None else \
                    jnp.ones((build_cap,), jnp.bool_)
                merged[out_name] = Column(
                    jnp.concatenate([a.data, b.data.astype(a.data.dtype)]),
                    a.dtype, jnp.concatenate([av, bv]), a.dictionary)
            return Batch(merged, jnp.concatenate([sel, app_sel]))

        return Batch(cols, sel)

    def simple_string(self):
        return (f"JoinExec({self.how}, {[repr(k) for k in self.left_keys]} = "
                f"{[repr(k) for k in self.right_keys]}, "
                f"cond={self.condition!r}, cap={self.out_cap}, "
                f"uniq={self.unique_build}, "
                + ("null_aware, " if self.null_aware else "")
                # only when the AQE loop forced the sort fallback, so
                # pre-existing plan strings (and cached stage keys) are
                # untouched on the common path
                + ("hash_fallback, " if self.hash_fallback is False
                   else "")
                + f"strategy={self.strategy})")


class RuntimeFilterExec(PhysicalPlan):
    """Probe-side runtime join filter (reference: the exec side of
    `InjectRuntimeFilter.scala:1`, with `common/sketch/BloomFilter.java`
    replaced by the device kernels in sketch.py).

    children = (probe_child, creation_plan). The creation plan is the
    join build side's cheap Project/Filter-over-leaf chain (the same
    node objects — the tree becomes a DAG; the duplicate computation is
    bounded by runtimeFilter.creationSideThreshold, mirroring the
    reference's duplicated creation-side subquery). compute() builds a
    Bloom filter + min/max bounds from the creation keys in-stage,
    pmax/pmin-combines them across the mesh axis, and narrows the probe
    batch's selection mask — placed BELOW the probe-side exchange, so
    pruned rows never radix-partition or cross ICI.

    Dropping this node never changes results (the join re-checks every
    key): streamed/out-of-core chain matchers skip it."""

    def __init__(self, child: PhysicalPlan, creation: PhysicalPlan,
                 probe_key: Expression, build_key: Expression,
                 est_items: Optional[int] = None, fpp: float = 0.03):
        self.children = (child, creation)
        self.probe_key = probe_key
        self.build_key = build_key
        self.est_items = est_items
        self.fpp = fpp
        self.tag = "rf0"

    @property
    def creation(self) -> PhysicalPlan:
        return self.children[1]

    def schema(self):
        return self.children[0].schema()

    def output_partitioning(self):
        return self.children[0].output_partitioning()

    def compute(self, ctx, inputs):
        import time as _time
        probe, build = inputs
        n_items = self.est_items
        global_cap = build.capacity * max(1, ctx.n_shards)
        if n_items is None:
            n_items = global_cap
        # the planner estimate is pre-filter; the batch capacity is a
        # tighter static bound on insertable rows — don't size the
        # (replicated) bit array past it
        n_items = min(n_items, global_cap)
        t0 = _time.perf_counter()
        filt = join_kernels.build_runtime_filter(
            build, self.build_key, ctx, expected_items=max(int(n_items), 8),
            fpp=self.fpp)
        build_ms = (_time.perf_counter() - t0) * 1e3
        keep = join_kernels.apply_runtime_filter(filt, probe,
                                                 self.probe_key)
        psel = probe.selection_mask()
        ctx.add_metric(f"rtf_tested_{self.tag}",
                       jnp.sum(psel.astype(jnp.int64)))
        ctx.add_metric(f"rtf_pruned_{self.tag}",
                       jnp.sum((psel & ~keep).astype(jnp.int64)))
        # host time spent CONSTRUCTING the filter program (trace time):
        # the build itself fuses into the stage, so this is the honest
        # per-filter build-cost observable — a static metric, pmax'd
        # across shards
        ctx.add_metric(f"rtf_build_ms_{self.tag}",
                       jnp.float32(build_ms))
        return probe.with_selection(psel & keep)

    def simple_string(self):
        return (f"RuntimeFilterExec({self.probe_key!r} IN "
                f"bloom({self.build_key!r}), est={self.est_items}, "
                f"fpp={self.fpp})")


def _unify_key_dictionaries(lvecs: List[Vec], rvecs: List[Vec]
                            ) -> Tuple[List[Vec], List[Vec]]:
    """Re-encode string join keys onto one shared dictionary per key pair.

    Two independently-encoded string columns assign codes independently, so
    comparing raw codes is meaningless (round-1 high-severity bug). The
    merge happens on host at trace time; codes are remapped with a device
    gather. Non-string keys pass through."""
    from ..columnar import unify_string_columns
    out_l, out_r = [], []
    for lv, rv in zip(lvecs, rvecs):
        if not isinstance(lv.dtype, T.StringType) and \
                not isinstance(rv.dtype, T.StringType):
            out_l.append(lv)
            out_r.append(rv)
            continue
        if lv.dictionary is None or rv.dictionary is None:
            raise AnalysisError(
                "string join keys require dictionary-encoded columns")
        l_data, r_data, merged = unify_string_columns(
            lv.data, lv.dictionary, rv.data, rv.dictionary)
        out_l.append(Vec(l_data, T.STRING, lv.validity, merged))
        out_r.append(Vec(r_data, T.STRING, rv.validity, merged))
    return out_l, out_r


def _key_width(v: Vec) -> Optional[int]:
    """Bits needed to represent the key's domain, or None when unbounded."""
    if v.dictionary is not None:
        n = len(v.dictionary)
        return max(1, (n - 1).bit_length()) if n > 1 else 1
    if isinstance(v.dtype, T.BooleanType):
        return 1
    if isinstance(v.dtype, T.ByteType):
        return 8
    if isinstance(v.dtype, T.ShortType):
        return 16
    if isinstance(v.dtype, (T.IntegerType, T.DateType)):
        return 32
    return None  # int64/timestamp: full range, cannot pack with others


def _unsigned_key(v: Vec, width: int):
    """Map key values to [0, 2^width) preserving distinctness (bias the
    sign bit for signed dtypes; dictionary codes are already unsigned)."""
    data = v.data.astype(jnp.int64)
    if v.dictionary is None and not isinstance(v.dtype, T.BooleanType):
        data = data + jnp.int64(1 << (width - 1))
    return data


_MIX_MUL = np.uint64(0xBF58476D1CE4E5B9)
_MIX_MUL2 = np.uint64(0x94D049BB133111EB)


def _mix64(x):
    """splitmix64 finalizer (wrapping uint64 arithmetic)."""
    u = x.astype(jnp.uint64)
    u = (u ^ (u >> 30)) * _MIX_MUL
    u = (u ^ (u >> 27)) * _MIX_MUL2
    u = u ^ (u >> 31)
    return u.astype(jnp.int64)


def _pack_key_pair(lvecs: List[Vec], rvecs: List[Vec]
                   ) -> Tuple[Vec, Vec, bool]:
    """Combine multi-key join keys into one int64 key per side.

    Widths are derived JOINTLY per key position (max of the two sides) so
    both sides share one bit layout. Returns (lk, rk, exact): when the
    combined widths fit in 63 bits the packing is collision-free
    (exact=True); otherwise both sides are hash-mixed and the caller MUST
    re-verify per-key equality on matches (round-1 packed lossily and
    joined silently wrong)."""
    validity_l = None
    validity_r = None
    for lv, rv in zip(lvecs, rvecs):
        for v in (lv, rv):
            if not isinstance(v.dtype, (T.IntegralType, T.StringType,
                                        T.DateType, T.BooleanType,
                                        T.TimestampType)):
                raise AnalysisError(
                    f"multi-key join on {v.dtype!r} unsupported")
        if lv.validity is not None:
            validity_l = lv.validity if validity_l is None else \
                (validity_l & lv.validity)
        if rv.validity is not None:
            validity_r = rv.validity if validity_r is None else \
                (validity_r & rv.validity)
    def kind(v):
        if v.dictionary is not None:
            return "dict"
        return "bool" if isinstance(v.dtype, T.BooleanType) else "int"

    widths = []
    for lv, rv in zip(lvecs, rvecs):
        wl, wr = _key_width(lv), _key_width(rv)
        if wl is None or wr is None or kind(lv) != kind(rv):
            widths.append(None)  # hash path (+ per-key re-verify)
        else:
            widths.append(max(wl, wr))
    if all(w is not None for w in widths) and sum(widths) <= 63:
        acc_l = jnp.zeros((), jnp.int64)
        acc_r = jnp.zeros((), jnp.int64)
        for lv, rv, w in zip(lvecs, rvecs, widths):
            acc_l = (acc_l << w) | _unsigned_key(lv, w)
            acc_r = (acc_r << w) | _unsigned_key(rv, w)
        return (Vec(acc_l, T.LONG, validity_l),
                Vec(acc_r, T.LONG, validity_r), True)
    hl = jnp.zeros((), jnp.int64)
    hr = jnp.zeros((), jnp.int64)
    for lv, rv in zip(lvecs, rvecs):
        hl = _mix64(hl ^ _mix64(lv.data.astype(jnp.int64)))
        hr = _mix64(hr ^ _mix64(rv.data.astype(jnp.int64)))
    return Vec(hl, T.LONG, validity_l), Vec(hr, T.LONG, validity_r), False


class ExchangeExec(UnaryExec):
    """Repartitioning boundary (reference: ShuffleExchangeExec.scala:115
    for the hash case, BroadcastExchangeExec.scala:78 for Replicated).

    On a single chip this is the identity; inside a `shard_map` over the
    mesh it lowers to collectives (parallel/shuffle.py):
      HashPartitioning           -> radix-partition + all_to_all
      SinglePartition/Replicated -> all_gather"""

    def __init__(self, child: PhysicalPlan, partitioning: Partitioning):
        self.children = (child,)
        self.partitioning = partitioning
        #: per-(src,dst) receive block size; None = seeded from the input
        #: capacity (2x uniform spread), grown by the executor on overflow
        self.block_cap: Optional[int] = None
        self.tag = "e0"

    def schema(self):
        return self.child.schema()

    def output_partitioning(self):
        return self.partitioning

    def compute(self, ctx, inputs):
        if ctx.axis_name is None or ctx.n_shards <= 1:
            return inputs[0]
        from ..parallel import shuffle
        if isinstance(self.partitioning, HashPartitioning):
            return shuffle.exchange_hash(inputs[0], self.partitioning.keys,
                                         ctx, block_cap=self.block_cap,
                                         tag=self.tag)
        if isinstance(self.partitioning, RangePartitioning):
            return shuffle.exchange_range(inputs[0],
                                          self.partitioning.orders, ctx,
                                          block_cap=self.block_cap,
                                          tag=self.tag)
        if isinstance(self.partitioning, (SinglePartition, Replicated)):
            return shuffle.all_gather_batch(inputs[0], ctx)
        raise AnalysisError(
            f"no collective lowering for {self.partitioning!r}")

    def simple_string(self):
        return f"ExchangeExec({self.partitioning!r}, block={self.block_cap})"


class UnionExec(PhysicalPlan):
    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 out_schema: T.Schema):
        self.children = (left, right)
        self._schema = out_schema

    def schema(self):
        return self._schema

    def output_partitioning(self):
        # per-shard concatenation of sharded children is NOT a single
        # partition: inheriting the base SinglePartition would both skip
        # needed exchanges above and make the executor stripe the
        # (distinct) per-shard output (round-2 high-severity bug)
        lp = self.children[0].output_partitioning()
        rp = self.children[1].output_partitioning()
        if isinstance(lp, SinglePartition) and isinstance(rp, SinglePartition):
            return SinglePartition()
        return UnknownPartitioning(
            max(lp.num_partitions, rp.num_partitions))

    def compute(self, ctx, inputs):
        from ..columnar import unify_string_columns
        lb, rb = inputs
        if ctx.axis_name is not None and ctx.n_shards > 1:
            # a SinglePartition child is physically replicated on every
            # shard; concatenated as-is it would appear n times in the
            # gathered output — take this shard's stripe so the union
            # totals exactly one copy per side
            from ..parallel.shuffle import stripe_batch
            parts = [c.output_partitioning() for c in self.children]
            if not all(isinstance(p, SinglePartition) for p in parts):
                if isinstance(parts[0], (SinglePartition, Replicated)):
                    lb = stripe_batch(lb, ctx)
                if isinstance(parts[1], (SinglePartition, Replicated)):
                    rb = stripe_batch(rb, ctx)
        cols = {}
        for out_f, ln, rn in zip(self._schema.fields, lb.names, rb.names):
            lc, rc = lb.columns[ln], rb.columns[rn]
            l_data, r_data = lc.data, rc.data
            dictionary = None
            if isinstance(out_f.dtype, T.StringType):
                # merge the two dictionaries and remap right codes — raw
                # right codes under the left dictionary decode to wrong
                # strings (round-1 high-severity bug)
                if lc.dictionary is None or rc.dictionary is None:
                    raise AnalysisError(
                        "UNION of string columns requires dictionaries")
                l_data, r_data, dictionary = unify_string_columns(
                    l_data, lc.dictionary, r_data, rc.dictionary)
            data = jnp.concatenate([
                l_data.astype(out_f.dtype.np_dtype),
                r_data.astype(out_f.dtype.np_dtype)])
            if lc.validity is None and rc.validity is None:
                validity = None
            else:
                lv = lc.validity if lc.validity is not None else \
                    jnp.ones((lb.capacity,), jnp.bool_)
                rv = rc.validity if rc.validity is not None else \
                    jnp.ones((rb.capacity,), jnp.bool_)
                validity = jnp.concatenate([lv, rv])
            cols[out_f.name] = Column(data, out_f.dtype, validity, dictionary)
        sel = jnp.concatenate([lb.selection_mask(), rb.selection_mask()])
        return Batch(cols, sel)
